"""BASELINE row 6: p99 pull latency at 10k agents (simulated swarm).

Drives the production policy code (RequestManager, ConnState,
AnnounceQueue, default_priority handout) through the discrete-event
simulator in ``kraken_tpu/p2p/sim.py`` -- no sockets, no GIL ceiling, so
the row's named scale is measured directly rather than extrapolated.
Deterministic per (seed, config): same invocation replays exactly.

    python bench_sim.py                    # 10k agents, 64 x 4 MiB pieces
    python bench_sim.py --agents 2000      # smaller, faster
"""

import argparse
import json
import time

from kraken_tpu.p2p.sim import run_sim


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=10_000)
    ap.add_argument("--pieces", type=int, default=64)
    ap.add_argument("--piece-mb", type=int, default=4)
    ap.add_argument("--origins", type=int, default=1)
    ap.add_argument("--seed", type=int, default=1)
    # Round-5 production shapes (VERDICT r4 #8):
    ap.add_argument("--downlink-mbytes", type=float, default=0.0,
                    help="per-host downlink cap in MEGABYTES/s (matches "
                         "SimConfig's bytes/s fields); 0 = uplink-only")
    ap.add_argument("--layers", type=str, default="",
                    help="comma-separated pieces per layer: image-shaped "
                         "pull (overrides --pieces)")
    ap.add_argument("--restart-at", type=float, default=0.0)
    ap.add_argument("--restart-frac", type=float, default=0.0)
    # Tracker HA fleet (round 12): shard announces over N trackers and
    # optionally kill the blob-0 owners mid-run, with a like-for-like
    # no-kill control (same seed/config) in the output.
    ap.add_argument("--trackers", type=int, default=1)
    ap.add_argument("--tracker-kill-at", type=float, default=0.0)
    ap.add_argument("--tracker-kill", type=int, default=0)
    ap.add_argument("--tracker-restart-after", type=float, default=0.0)
    ap.add_argument("--tracker-down-mode", default="refuse",
                    choices=["refuse", "blackhole"])
    # Total-outage drill (PEX plane): kill EVERY tracker mid-run with
    # gossip peer exchange on, against a same-seed no-kill control --
    # the row is what fraction of in-flight pulls still complete.
    ap.add_argument("--tracker-kill-all", action="store_true")
    ap.add_argument("--pex", action="store_true",
                    help="gossip peer exchange (implied by "
                         "--tracker-kill-all)")
    ap.add_argument("--pex-interval", type=float, default=5.0)
    args = ap.parse_args()

    t0 = time.time()
    kw = dict(
        n_agents=args.agents,
        num_pieces=args.pieces,
        piece_bytes=args.piece_mb << 20,
        n_origins=args.origins,
        seed=args.seed,
        downlink_bps=args.downlink_mbytes * 1e6,
        blob_pieces=(
            tuple(int(x) for x in args.layers.split(",")) if args.layers
            else None
        ),
        n_trackers=args.trackers,
        tracker_down_mode=args.tracker_down_mode,
        tracker_restart_after_s=args.tracker_restart_after,
        pex=args.pex or args.tracker_kill_all,
        pex_interval_s=args.pex_interval,
    )
    r = run_sim(**kw, restart_at_s=args.restart_at,
                restart_frac=args.restart_frac,
                tracker_kill_at_s=args.tracker_kill_at,
                tracker_kill=args.tracker_kill,
                tracker_kill_all=args.tracker_kill_all)
    if args.tracker_kill_all and args.tracker_kill_at > 0:
        # Same-seed no-kill control: "the fleet survived TOTAL tracker
        # loss at ratio X of its healthy completion, costing Y of pull
        # p99" is a measured delta, not a cross-shape comparison.
        control = run_sim(**kw, restart_at_s=args.restart_at,
                          restart_frac=args.restart_frac)
        r["control_no_tracker_kill"] = control
        if control["completed"]:
            r["tracker_blackout_completion_ratio"] = round(
                r["completed"] / control["completed"], 4
            )
        if r["p99_s"] is not None and control["p99_s"]:
            r["tracker_blackout_p99_delta_s"] = round(
                r["p99_s"] - control["p99_s"], 3
            )
    if args.tracker_kill > 0 and args.tracker_kill_at > 0:
        # Like-for-like healthy-fleet control (same seed/config, no
        # kill): "the tracker death cost X of announce p99" is a
        # measured delta, not a cross-shape comparison.
        control = run_sim(**kw, restart_at_s=args.restart_at,
                          restart_frac=args.restart_frac)
        r["control_no_tracker_kill"] = control
        if r["announce_p99_s"] is not None and control["announce_p99_s"]:
            r["tracker_kill_announce_p99_ratio"] = round(
                r["announce_p99_s"] / control["announce_p99_s"], 3
            )
    if args.restart_frac > 0 and args.restart_at > 0:
        # Like-for-like control: the SAME seed and config with the wave
        # switched off, so "the restart wave cost X seconds of p99" is a
        # measured delta against an identical swarm, not a comparison
        # across differently-shaped runs (VERDICT r5 #9).
        control = run_sim(**kw, restart_at_s=0.0, restart_frac=0.0)
        r["control_no_wave"] = control
        if r["p99_s"] is not None and control["p99_s"] is not None:
            r["restart_wave_p99_delta_s"] = round(
                r["p99_s"] - control["p99_s"], 3
            )
            r["restart_wave_p99_ratio"] = round(
                r["p99_s"] / control["p99_s"], 3
            ) if control["p99_s"] else None
    r["bench_wall_s"] = round(time.time() - t0, 2)
    print(json.dumps({
        "metric": f"sim_swarm_pull_p99_s_at_{args.agents}",
        "value": round(r["p99_s"], 3) if r["p99_s"] is not None else None,
        "unit": "s",
        "vs_baseline": None,
        "detail": r,
    }))


if __name__ == "__main__":
    main()

"""Dedup-plane benchmark: cross-layer dedup ratio on a synthetic corpus.

BASELINE.json config #4: FastCDC over a Docker-layer-like corpus, 64 KiB
average chunks; north-star target >= 30% cross-layer dedup. Prints ONE
JSON line:

    {"metric": "cdc_cross_layer_dedup_ratio", "value": ..., "unit":
     "fraction", "vs_baseline": value/0.30, "chunk_gbps": ...,
     "identity_dedup_ratio": ...}

The synthetic corpus models what defeats fixed-size dedup in registries:
layers share file *content* but at different byte offsets (tar headers,
file ordering, prepended metadata differ per image build). Each layer is
a tar-like stream of (512 B unique header + shared-or-unique file body);
consecutive "image builds" reuse most files, reorder some, and patch a
few. ``identity_dedup_ratio`` is what whole-blob dedup (the reference's
only mechanism: content-addressed identical blobs) achieves on the same
corpus -- the delta is the capability this plane adds.

Round 9 adds the cash-in row: ``delta_bytes_moved_ratio`` -- bytes a
REAL agent pull actually fetches (swarm piece ingress + origin range
GETs, registry-counted) divided by blob size, on consecutive
build-over-build pulls through a live tracker+origin+agent herd with
the chunk-level delta-transfer plane ON, against the delta-off control
(median +/- IQR over ``DEDUP_DELTA_LAYERS-1`` pulls). The sub-corpus is
the same generator at image-shaped file sizes (``DEDUP_DELTA_FILE_KB``,
default 1 MiB -- see the DELTA_* knob comments for why the headline
corpus's 192 KB files are below the production CDC's resolution). The
detected dedup ratio is the *ceiling*; this row is what the wire now
*moves*. tests/test_delta.py::test_delta_pull_band pins the same
measurement as a tier-1 CI band (delta-on <= 0.6x of control).

Run on TPU (default platform) or CPU (JAX_PLATFORMS=cpu). The chunking
rate reported is the end-to-end two-phase chunker (device gear-hash pass +
host cut selection).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_FILES = int(os.environ.get("DEDUP_FILES", 96))
FILE_KB = int(os.environ.get("DEDUP_FILE_KB", 192))
N_LAYERS = int(os.environ.get("DEDUP_LAYERS", 24))
FILES_PER_LAYER = int(os.environ.get("DEDUP_FILES_PER_LAYER", 24))
REUSE = float(os.environ.get("DEDUP_REUSE", 0.8))  # share of reused files
# Delta e2e sub-corpus (same generator, image-shaped file sizes): the
# planner's win tracks chunks-per-file, and the headline corpus's 192 KB
# files sit at the production 64 KiB-avg CDC resolution floor (~3
# chunks/file -> ~0.2 duplicate fraction vs the previous build even
# though file REUSE is 0.8). Real build-over-build layers carry multi-MB
# files (shared libs, venvs); 1 MiB files give ~16 chunks/file and a
# 0.6-0.8 vs-prev duplicate fraction -- the regime delta transfer is for.
DELTA_LAYERS = int(os.environ.get("DEDUP_DELTA_LAYERS", 8))  # e2e pulls
DELTA_FILE_KB = int(os.environ.get("DEDUP_DELTA_FILE_KB", 1024))
DELTA_FILES_PER_LAYER = int(os.environ.get("DEDUP_DELTA_FILES_PER_LAYER", 8))


def make_corpus(
    rng: np.random.Generator,
    n_files: int | None = None,
    file_kb: int | None = None,
    n_layers: int | None = None,
    files_per_layer: int | None = None,
) -> list[bytes]:
    n_files = N_FILES if n_files is None else n_files
    file_kb = FILE_KB if file_kb is None else file_kb
    n_layers = N_LAYERS if n_layers is None else n_layers
    files_per_layer = (
        FILES_PER_LAYER if files_per_layer is None else files_per_layer
    )
    files = [
        rng.integers(0, 256, size=file_kb * 1024, dtype=np.uint8).tobytes()
        for _ in range(n_files)
    ]
    layers = []
    prev: list[int] = []
    for li in range(n_layers):
        n_reuse = int(files_per_layer * REUSE) if prev else 0
        reused = list(rng.choice(prev, size=min(n_reuse, len(prev)),
                                 replace=False)) if prev else []
        fresh = list(rng.choice(
            [i for i in range(n_files) if i not in reused],
            size=files_per_layer - len(reused), replace=False))
        members = reused + fresh
        rng.shuffle(members)
        parts = []
        for fi in members:
            header = rng.integers(0, 256, size=512, dtype=np.uint8).tobytes()
            parts.append(header)
            parts.append(files[fi])
        layers.append(b"".join(parts))
        prev = members
    return layers


async def _delta_herd(layers: list[bytes], root: str, on: bool) -> dict:
    """Pull ``layers`` in build order through a live tracker+origin+agent
    herd; returns ``{"ratios": [...], "stored_bytes": n}`` where ratios
    are bytes-moved/blob-size for every build-over-build pull (the first
    pull -- cold cache, necessarily ~1.0 -- is excluded) and
    stored_bytes is the agent store's end-of-run disk usage. "Moved" is
    what the agent actually fetched: swarm piece ingress
    (``p2p_piece_bytes_down_total``) plus delta range GETs
    (``delta_bytes_fetched_total``), read as registry deltas around each
    pull. With ``on`` True the agent ALSO runs the chunk store tier
    (store/chunkstore.py), so stored_bytes measures the at-rest cash-in
    next to the wire one; ``on`` False runs the shipped defaults (both
    off): the control both ratio rows are quoted against."""
    from urllib.parse import quote

    from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.client import BlobClient, ClusterClient
    from kraken_tpu.origin.metainfogen import PieceLengthConfig
    from kraken_tpu.placement import HostList, Ring
    from kraken_tpu.utils.httputil import HTTPClient
    from kraken_tpu.utils.metrics import REGISTRY

    ns = "library/bench-delta"
    tracker = TrackerNode(announce_interval_seconds=0.1)
    await tracker.start()
    origin = OriginNode(
        store_root=os.path.join(root, "origin"),
        tracker_addr=tracker.addr,
        # 256 KiB pieces: a ~5 MB layer carries ~19 pieces, so planning
        # exercises both fully-covered pieces and range-filled holes.
        piece_lengths=PieceLengthConfig(table=((0, 262144),)),
        delta={"enabled": True} if on else None,
    )
    await origin.start()
    ring = Ring(HostList(static=[origin.addr]), max_replica=2)
    cluster = ClusterClient(ring)
    tracker.server.origin_cluster = cluster
    agent = AgentNode(
        store_root=os.path.join(root, "agent"),
        tracker_addr=tracker.addr,
        delta={"enabled": True, "min_blob_bytes": 1} if on else None,
        chunkstore=(
            {"enabled": True, "min_blob_bytes": 1} if on else None
        ),
    )
    await agent.start()
    http = HTTPClient()
    oc = BlobClient(origin.addr)
    down = REGISTRY.counter("p2p_piece_bytes_down_total")
    fetched = REGISTRY.counter("delta_bytes_fetched_total")
    ratios: list[float] = []
    try:
        for i, blob in enumerate(layers):
            d = Digest.from_bytes(blob)
            await oc.upload(ns, d, blob)
            d0, f0 = down.value(), fetched.value()
            got = await http.get(
                f"http://{agent.addr}/namespace/"
                f"{quote(ns, safe='')}/blobs/{d.hex}"
            )
            assert got == blob, "pulled blob must be bit-identical"
            moved = (down.value() - d0) + (fetched.value() - f0)
            if i > 0:
                ratios.append(moved / len(blob))
            if on:
                # Conversion runs as a background task after each pull;
                # wait it out so the NEXT pull's delta plan copies from
                # a chunk-backed base and the end-of-run disk usage
                # reflects the tier, not an in-flight flat file.
                deadline = asyncio.get_running_loop().time() + 30.0
                while (
                    not agent.store.is_chunked(d)
                    and asyncio.get_running_loop().time() < deadline
                ):
                    await asyncio.sleep(0.05)
        stored = agent.store.disk_usage_bytes()
    finally:
        await http.close()
        await oc.close()
        await agent.stop()
        await origin.stop()
        await cluster.close()
        await tracker.stop()
    return {"ratios": ratios, "stored_bytes": stored}


def delta_moved_rows(rng: np.random.Generator) -> dict:
    """The delta-transfer cash-in rows: median +/- IQR of the per-pull
    bytes-moved ratio, delta-on vs the delta-off control, over
    ``DELTA_LAYERS - 1`` build-over-build pulls of an image-shaped
    sub-corpus (``DELTA_FILE_KB`` files; see the module docstring)."""
    import asyncio
    import tempfile

    sub = make_corpus(
        rng, n_files=4 * DELTA_FILES_PER_LAYER, file_kb=DELTA_FILE_KB,
        n_layers=DELTA_LAYERS, files_per_layer=DELTA_FILES_PER_LAYER,
    )
    with tempfile.TemporaryDirectory() as tmp:
        res_on = asyncio.run(_delta_herd(sub, os.path.join(tmp, "on"), True))
        res_off = asyncio.run(
            _delta_herd(sub, os.path.join(tmp, "off"), False)
        )
    on, off = res_on["ratios"], res_off["ratios"]

    def q(vals, p):
        return round(float(np.percentile(vals, p)), 4)

    return {
        "delta_bytes_moved_ratio": q(on, 50),
        "delta_bytes_moved_ratio_iqr": [q(on, 25), q(on, 75)],
        "delta_off_bytes_moved_ratio": q(off, 50),
        "delta_off_bytes_moved_ratio_iqr": [q(off, 25), q(off, 75)],
        "delta_vs_off": round(q(on, 50) / max(q(off, 50), 1e-9), 4),
        "delta_pulls": len(on),
        # The at-rest cash-in (store/chunkstore.py): end-of-run agent
        # disk usage, chunk tier vs the flat-blob control, over the
        # same build-over-build pulls. tests/test_chunkstore.py pins
        # the same measurement as a tier-1 band (<= 0.7x of control).
        "delta_bytes_stored_ratio": round(
            res_on["stored_bytes"] / max(res_off["stored_bytes"], 1), 4
        ),
        "delta_stored_bytes": res_on["stored_bytes"],
        "delta_off_stored_bytes": res_off["stored_bytes"],
    }


def main() -> None:
    import hashlib

    from kraken_tpu.ops.cdc import CDCParams, chunk_spans

    rng = np.random.default_rng(7)
    layers = make_corpus(rng)
    total = sum(len(b) for b in layers)

    # Whole-blob (reference-style) dedup baseline.
    seen_blobs: set[bytes] = set()
    identity_dup = 0
    for b in layers:
        h = hashlib.sha256(b).digest()
        if h in seen_blobs:
            identity_dup += len(b)
        else:
            seen_blobs.add(h)

    params = CDCParams()  # 16/64/256 KiB -- BASELINE config #4
    seen: set[bytes] = set()
    dup_bytes = 0
    t0 = time.perf_counter()
    for blob in layers:
        for s, e in chunk_spans(blob, params):
            fp = hashlib.sha256(blob[s:e]).digest()
            if fp in seen:
                dup_bytes += e - s
            else:
                seen.add(fp)
    dt = time.perf_counter() - t0

    ratio = dup_bytes / total

    # Delta-transfer cash-in: what a real pull MOVES, on vs off.
    delta_rows = delta_moved_rows(rng)

    # Device gear-pass rate, relay excluded (marginal method, as bench.py):
    # the end-to-end chunk wall clock above is dominated by this rig's
    # ~25 MB/s host->device relay, which a production PCIe host doesn't have.
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.cdc_pallas import _BUF, _ROWS, _T_DISPATCH, _gear_pallas

    # The production large-blob path: the Pallas VMEM-doubling kernel,
    # fed the [T, rows, 128] segment layout with data resident.
    n = _T_DISPATCH * (_BUF - 1024)
    dev = jax.random.bits(
        jax.random.PRNGKey(0), (_T_DISPATCH, _ROWS, 128), dtype=jnp.uint8
    )
    dev.block_until_ready()
    ms, ml = params.mask_strict, params.mask_loose

    def dispatch():
        return _gear_pallas(dev, ms, ml)[0]

    np.asarray(dispatch()[0, 0])
    def timed(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch()
        np.asarray(out[0, 0])
        return time.perf_counter() - t0
    # The relay's latency jitter (~100s of ms) swamps small marginal
    # windows; queue 40 extra 64 MiB dispatches (2.5 GB) per trial.
    rates = []
    for _ in range(5):
        t_s, t_l = timed(2), timed(42)
        rates.append(40 * n / max(t_l - t_s, 1e-9) / 1e9)
    gear_gbps = sorted(rates)[len(rates) // 2]

    print(
        json.dumps(
            {
                "metric": "cdc_cross_layer_dedup_ratio",
                "value": round(ratio, 4),
                "unit": "fraction",
                "vs_baseline": round(ratio / 0.30, 3),
                "gear_pass_gbps": round(gear_gbps, 2),
                "chunk_wallclock_gbps_relay_bound": round(total / dt / 1e9, 3),
                "identity_dedup_ratio": round(identity_dup / total, 4),
                **delta_rows,
                "corpus_bytes": total,
                "layers": N_LAYERS,
            }
        )
    )


if __name__ == "__main__":
    main()

"""Dedup-plane benchmark: cross-layer dedup ratio on a synthetic corpus.

BASELINE.json config #4: FastCDC over a Docker-layer-like corpus, 64 KiB
average chunks; north-star target >= 30% cross-layer dedup. Prints ONE
JSON line:

    {"metric": "cdc_cross_layer_dedup_ratio", "value": ..., "unit":
     "fraction", "vs_baseline": value/0.30, "chunk_gbps": ...,
     "identity_dedup_ratio": ...}

The synthetic corpus models what defeats fixed-size dedup in registries:
layers share file *content* but at different byte offsets (tar headers,
file ordering, prepended metadata differ per image build). Each layer is
a tar-like stream of (512 B unique header + shared-or-unique file body);
consecutive "image builds" reuse most files, reorder some, and patch a
few. ``identity_dedup_ratio`` is what whole-blob dedup (the reference's
only mechanism: content-addressed identical blobs) achieves on the same
corpus -- the delta is the capability this plane adds.

Run on TPU (default platform) or CPU (JAX_PLATFORMS=cpu). The chunking
rate reported is the end-to-end two-phase chunker (device gear-hash pass +
host cut selection).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_FILES = int(os.environ.get("DEDUP_FILES", 96))
FILE_KB = int(os.environ.get("DEDUP_FILE_KB", 192))
N_LAYERS = int(os.environ.get("DEDUP_LAYERS", 24))
FILES_PER_LAYER = int(os.environ.get("DEDUP_FILES_PER_LAYER", 24))
REUSE = float(os.environ.get("DEDUP_REUSE", 0.8))  # share of reused files


def make_corpus(rng: np.random.Generator) -> list[bytes]:
    files = [
        rng.integers(0, 256, size=FILE_KB * 1024, dtype=np.uint8).tobytes()
        for _ in range(N_FILES)
    ]
    layers = []
    prev: list[int] = []
    for li in range(N_LAYERS):
        n_reuse = int(FILES_PER_LAYER * REUSE) if prev else 0
        reused = list(rng.choice(prev, size=min(n_reuse, len(prev)),
                                 replace=False)) if prev else []
        fresh = list(rng.choice(
            [i for i in range(N_FILES) if i not in reused],
            size=FILES_PER_LAYER - len(reused), replace=False))
        members = reused + fresh
        rng.shuffle(members)
        parts = []
        for fi in members:
            header = rng.integers(0, 256, size=512, dtype=np.uint8).tobytes()
            parts.append(header)
            parts.append(files[fi])
        layers.append(b"".join(parts))
        prev = members
    return layers


def main() -> None:
    import hashlib

    from kraken_tpu.ops.cdc import CDCParams, chunk_spans

    rng = np.random.default_rng(7)
    layers = make_corpus(rng)
    total = sum(len(b) for b in layers)

    # Whole-blob (reference-style) dedup baseline.
    seen_blobs: set[bytes] = set()
    identity_dup = 0
    for b in layers:
        h = hashlib.sha256(b).digest()
        if h in seen_blobs:
            identity_dup += len(b)
        else:
            seen_blobs.add(h)

    params = CDCParams()  # 16/64/256 KiB -- BASELINE config #4
    seen: set[bytes] = set()
    dup_bytes = 0
    t0 = time.perf_counter()
    for blob in layers:
        for s, e in chunk_spans(blob, params):
            fp = hashlib.sha256(blob[s:e]).digest()
            if fp in seen:
                dup_bytes += e - s
            else:
                seen.add(fp)
    dt = time.perf_counter() - t0

    ratio = dup_bytes / total

    # Device gear-pass rate, relay excluded (marginal method, as bench.py):
    # the end-to-end chunk wall clock above is dominated by this rig's
    # ~25 MB/s host->device relay, which a production PCIe host doesn't have.
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.cdc_pallas import _BUF, _ROWS, _T_DISPATCH, _gear_pallas

    # The production large-blob path: the Pallas VMEM-doubling kernel,
    # fed the [T, rows, 128] segment layout with data resident.
    n = _T_DISPATCH * (_BUF - 1024)
    dev = jax.random.bits(
        jax.random.PRNGKey(0), (_T_DISPATCH, _ROWS, 128), dtype=jnp.uint8
    )
    dev.block_until_ready()
    ms, ml = params.mask_strict, params.mask_loose

    def dispatch():
        return _gear_pallas(dev, ms, ml)[0]

    np.asarray(dispatch()[0, 0])
    def timed(k):
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch()
        np.asarray(out[0, 0])
        return time.perf_counter() - t0
    # The relay's latency jitter (~100s of ms) swamps small marginal
    # windows; queue 40 extra 64 MiB dispatches (2.5 GB) per trial.
    rates = []
    for _ in range(5):
        t_s, t_l = timed(2), timed(42)
        rates.append(40 * n / max(t_l - t_s, 1e-9) / 1e9)
    gear_gbps = sorted(rates)[len(rates) // 2]

    print(
        json.dumps(
            {
                "metric": "cdc_cross_layer_dedup_ratio",
                "value": round(ratio, 4),
                "unit": "fraction",
                "vs_baseline": round(ratio / 0.30, 3),
                "gear_pass_gbps": round(gear_gbps, 2),
                "chunk_wallclock_gbps_relay_bound": round(total / dt / 1e9, 3),
                "identity_dedup_ratio": round(identity_dup / total, 4),
                "corpus_bytes": total,
                "layers": N_LAYERS,
            }
        )
    )


if __name__ == "__main__":
    main()

"""CDC gear kernel host->device overlap efficiency (VERDICT r4 #4).

The SHA plane proved its staging-pipeline shape with bench_overlap.py
(0.978 at round 4); this is the SAME instrument pointed at the dedup
plane's Pallas gear kernel (ops/cdc_pallas.py):

    ratio = wall(pipelined feed+compute) / max(wall(feed), wall(compute))

~1.0 = JAX async dispatch hides the smaller cost behind the larger while
segments of blob i+1 stream in during the gear pass over blob i; ~2.0 =
transfers serialize against compute. Per-batch compute is calibrated to
the per-batch feed time with r CHAINED kernel steps -- chained from
PYTHON (each step's input folds the previous strict mask), NOT via
lax.fori_loop: this platform's replay coalescing executes a fori_loop of
pallas dispatches in ~0.1 ms regardless of trip count (the measurement
pathology PERF.md documents), so a loop-chained "compute" measures
nothing. The rig's relay makes absolute feed rate secondary; the SHAPE
is what transfers to production PCIe.

Prints ONE JSON line. TPU by default; OVERLAP_BATCHES tunes the load.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

K = int(os.environ.get("OVERLAP_BATCHES", 6))


def main() -> None:
    import jax

    from kraken_tpu.ops.cdc import CDCParams
    from kraken_tpu.ops.cdc_pallas import _ROWS, _gear_pallas

    p = CDCParams()
    # ~4 MiB per feed batch, matching bench_overlap.py's shape: the
    # relay throttles hard under sustained multi-GB transfer load
    # (measured: 1.5 GB/s burst -> ~13 MB/s sustained), so the overlap
    # shape is only measurable inside the burst window.
    T = 16
    batch_bytes = T * _ROWS * 128
    rng = np.random.default_rng(0)
    batches = [
        rng.integers(0, 256, size=(T, _ROWS, 128), dtype=np.uint8)
        for _ in range(K)
    ]

    @jax.jit
    def step(x):
        strict, _loose = _gear_pallas(x, p.mask_strict, p.mask_loose)
        # Fold one strict row back into the input: every chained step is
        # data-dependent and distinct (no replay coalescing).
        x = jax.lax.dynamic_update_slice(x, strict[:, :1, :], (0, 0, 0))
        return x, strict

    dev0 = jax.device_put(batches[0])
    dev0.block_until_ready()
    x, s = step(dev0)  # compile
    jax.block_until_ready((x, s))

    # Calibrate chained steps per batch toward one batch's feed time.
    t0 = time.perf_counter()
    for _ in range(8):
        x, s = step(x)
    np.asarray(s[0, 0, 0])
    kernel_s = (time.perf_counter() - t0) / 8
    t0 = time.perf_counter()
    jax.device_put(batches[1]).block_until_ready()
    feed_s = time.perf_counter() - t0
    r = max(1, min(10_000, round(feed_s / max(kernel_s, 1e-6))))

    def feed_only() -> float:
        t0 = time.perf_counter()
        devs = [jax.device_put(b) for b in batches]
        for d in devs:
            d.block_until_ready()
        return time.perf_counter() - t0

    def compute_only() -> float:
        t0 = time.perf_counter()
        x, s = dev0, None
        for _ in range(K * r):
            x, s = step(x)
        np.asarray(s[0, 0, 0])
        return time.perf_counter() - t0

    wall_feed = feed_only()
    wall_comp = compute_only()
    if not 0.67 <= wall_comp / wall_feed <= 1.5:
        r = max(1, min(10_000, round(r * wall_feed / max(wall_comp, 1e-9))))
        wall_comp = compute_only()

    def pipelined() -> float:
        # Feed batch i+1 while batch i's chained gear passes run: issue
        # everything async, block at the end.
        t0 = time.perf_counter()
        lasts = []
        for b in batches:
            x = jax.device_put(b)
            s = None
            for _ in range(r):
                x, s = step(x)
            lasts.append(s)
        for s in lasts:
            s.block_until_ready()
        return time.perf_counter() - t0

    trials = []
    for _ in range(5):
        f, c, pw = feed_only(), compute_only(), pipelined()
        trials.append({
            "feed_s": round(f, 3), "compute_s": round(c, 3),
            "pipelined_s": round(pw, 3),
            "ratio": round(pw / max(f, c), 3),
        })
    ratios = sorted(t["ratio"] for t in trials)
    ratio = ratios[len(ratios) // 2]
    med_feed = sorted(t["feed_s"] for t in trials)[len(trials) // 2]
    print(json.dumps({
        "metric": "cdc_feed_compute_overlap_ratio",
        "value": ratio,
        "unit": "wall(pipelined) / max(wall(feed), wall(compute)), median of 5",
        "vs_baseline": round(ratio / 1.15, 3),  # target <= 1.15
        "batches": K,
        "batch_mb": round(batch_bytes / 1e6, 2),
        "kernel_passes_per_batch": r,
        "trials": trials,
        "feed_mbps": round(K * batch_bytes / med_feed / 1e6, 1),
    }))


if __name__ == "__main__":
    main()

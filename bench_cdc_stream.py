"""BASELINE row 4 at scale: CDC dedup over a 100+ GB generated corpus.

The round-3 bench measured the cross-layer dedup ratio to 0.81 GB; this
one streams a deterministic synthetic Docker-layer corpus of STREAM_GB
(default 100) through the HOST chunking plane (native C FastCDC,
`kraken_tpu/native/hostpack.c:kt_cdc_chunk`) with nothing ever written
to disk, and reports the sustained pipeline rate plus the dedup-ratio
curve vs corpus size.

Corpus model (extends bench_dedup.py's): a pool of content files; each
"image build" layer packs FILES_PER_LAYER files as (unique 512 B header +
body), reusing REUSE of the previous build's members, pulling the rest
from the pool, and introducing NEW_PER_LAYER freshly-generated files
(replacing pool slots) -- so the steady-state ratio reflects genuine
content churn, not pool exhaustion. Identity (whole-blob) dedup on this
corpus is 0: every layer differs.

Chunk identity = SHA-256 of chunk bytes (truncated to 128 bits for the
seen-set; collision probability at ~2M chunks is ~1e-26). This bench is
host-plane by design: the device gear-pass rate is measured separately
in bench_dedup.py (marginal method; this rig's ~25 MB/s relay forbids
streaming 100 GB through the chip).

    STREAM_GB=100 python bench_cdc_stream.py     # the row-4 run (~6 min)
    STREAM_GB=2 python bench_cdc_stream.py       # quick

Prints ONE JSON line.
"""

import hashlib
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

STREAM_GB = float(os.environ.get("STREAM_GB", 100))
POOL_FILES = int(os.environ.get("CDC_POOL_FILES", 512))
FILE_KB = int(os.environ.get("CDC_FILE_KB", 1024))
FILES_PER_LAYER = int(os.environ.get("CDC_FILES_PER_LAYER", 16))
NEW_PER_LAYER = int(os.environ.get("CDC_NEW_PER_LAYER", 4))
REUSE = float(os.environ.get("CDC_REUSE", 0.8))
CHECKPOINTS_GB = (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000)


def layer_stream(rng: np.random.Generator):
    """Yield (layer_bytes) forever; deterministic for a given seed."""
    pool = [
        rng.integers(0, 256, size=FILE_KB * 1024, dtype=np.uint8).tobytes()
        for _ in range(POOL_FILES)
    ]
    prev: list[int] = []
    while True:
        # Fresh content enters the pool (replacing random slots): the
        # model's genuine-new-bytes rate.
        for _ in range(NEW_PER_LAYER):
            slot = int(rng.integers(0, POOL_FILES))
            pool[slot] = rng.integers(
                0, 256, size=FILE_KB * 1024, dtype=np.uint8
            ).tobytes()
        n_reuse = min(int(FILES_PER_LAYER * REUSE), len(prev))
        reused = (
            list(rng.choice(prev, size=n_reuse, replace=False))
            if prev else []
        )
        fresh = [
            int(i) for i in rng.choice(POOL_FILES, size=FILES_PER_LAYER
                                       - len(reused), replace=False)
        ]
        members = reused + fresh
        rng.shuffle(members)
        parts = []
        for fi in members:
            parts.append(
                rng.integers(0, 256, size=512, dtype=np.uint8).tobytes()
            )
            parts.append(pool[fi])
        yield b"".join(parts)
        prev = members


def main() -> None:
    from kraken_tpu.native import have_native_packer
    from kraken_tpu.ops.cdc import CDCParams, chunk_host

    params = CDCParams()  # 16/64/256 KiB -- BASELINE config #4
    target = int(STREAM_GB * 1e9)
    rng = np.random.default_rng(7)
    seen: set[bytes] = set()
    total = 0
    dup_bytes = 0
    chunks = 0
    curve: list[dict] = []
    next_cp = iter([int(g * 1e9) for g in CHECKPOINTS_GB])
    cp = next(next_cp)
    t0 = time.perf_counter()
    for layer in layer_stream(rng):
        cuts = chunk_host(layer, params)
        start = 0
        view = memoryview(layer)
        for end in cuts.tolist():
            fp = hashlib.sha256(view[start:end]).digest()[:16]
            if fp in seen:
                dup_bytes += end - start
            else:
                seen.add(fp)
            start = end
        chunks += len(cuts)
        total += len(layer)
        while total >= cp:
            curve.append({
                "gb": round(cp / 1e9),
                "ratio": round(dup_bytes / total, 4),
            })
            try:
                cp = next(next_cp)
            except StopIteration:
                cp = 1 << 62
        if total >= target:
            break
    wall = time.perf_counter() - t0

    print(json.dumps({
        "metric": "cdc_stream_dedup_ratio",
        "value": round(dup_bytes / total, 4),
        "unit": f"fraction at {round(total / 1e9, 1)} GB",
        "vs_baseline": round(dup_bytes / total / 0.30, 3),
        "corpus_gb": round(total / 1e9, 2),
        "pipeline_gbps": round(total / wall / 1e9, 3),
        "chunks": chunks,
        "avg_chunk_kb": round(total / max(1, chunks) / 1024, 1),
        "ratio_curve": curve,
        "native_chunker": have_native_packer(),
        "unique_chunk_index_mb": round(len(seen) * 85 / 1e6),
        "peak_rss_mb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        // 1024,
    }))


if __name__ == "__main__":
    main()

"""Resource sentinel tests (kraken_tpu/utils/resources.py).

The sentinel is the fleet-survival plane's eyes: these pin the sampling
primitives (fd/RSS/task census), the orphan-scan classification against
LIVE store state (an active upload or a resumable ``.part`` must never
read as debris), budget-breach firing + the sustained-breach latch that
enters lameduck, live reload, and the ``/debug/resources`` surface on
real assembled nodes.
"""

import asyncio
import os
import time

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.store import CAStore, PieceStatusMetadata
from kraken_tpu.store.metadata import NamespaceMetadata
from kraken_tpu.utils.metrics import REGISTRY
from kraken_tpu.utils.resources import (
    ResourceSentinel,
    ResourcesConfig,
    open_fd_count,
    rss_bytes,
    scan_store_orphans,
    task_census,
)


def _breaches(kind: str) -> float:
    return REGISTRY.counter("resource_budget_breaches_total").value(kind=kind)


# -- config ----------------------------------------------------------------

def test_resources_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="max_open_fdz"):
        ResourcesConfig.from_dict({"max_open_fdz": 10})
    cfg = ResourcesConfig.from_dict(None)
    assert cfg.interval_seconds > 0 and cfg.breach_streak >= 1


# -- process probes --------------------------------------------------------

def test_process_probes_report_positive_numbers():
    fds = open_fd_count()
    rss = rss_bytes()
    assert fds is not None and fds > 0
    assert rss is not None and rss > (1 << 20)


def test_fd_probe_tracks_an_actual_open():
    before = open_fd_count()
    with open(os.devnull):
        during = open_fd_count()
    after = open_fd_count()
    assert during == before + 1
    assert after == before


def test_task_census_tags_by_creation_site():
    async def main():
        async def leaky_worker():
            await asyncio.sleep(30)

        tasks = [asyncio.create_task(leaky_worker()) for _ in range(3)]
        await asyncio.sleep(0)
        total, top = task_census()
        for t in tasks:
            t.cancel()
        return total, top

    total, top = asyncio.run(main())
    assert total >= 3
    site = next((s for s in top if "leaky_worker" in s), None)
    assert site is not None, f"no leaky_worker site in {top}"
    assert top[site] == 3
    # The tag is greppable: file, line, qualname.
    assert "test_resources.py" in site and ":" in site


# -- orphan scan -----------------------------------------------------------

def _backdate(path: str, seconds: float) -> None:
    t = time.time() - seconds
    os.utime(path, (t, t))


def test_orphan_scan_counts_only_real_debris(tmp_path):
    store = CAStore(str(tmp_path / "s"))

    # Committed healthy blob + its namespace sidecar: never debris.
    blob = os.urandom(1000)
    d = Digest.from_bytes(blob)
    store.create_cache_file(d, iter([blob]))
    store.set_metadata(d, NamespaceMetadata("ns"))
    _backdate(store.cache_path(d), 7200)

    # LIVE upload spool (fresh mtime) vs abandoned one (idle past TTL).
    store.create_upload()
    stale_uid = store.create_upload()
    _backdate(store.upload_path(stale_uid), 7200)

    # Resumable in-progress download: ``.part`` + piece-bitfield
    # sidecar. NEVER debris while the .part is fresh -- and the sidecar
    # stays protected even when backdated, as long as its .part exists.
    d2 = Digest.from_bytes(b"partial")
    store.allocate_partial_file(d2, 4096)
    store.set_metadata(d2, PieceStatusMetadata(4))
    md_path = store._md_path(store.cache_path(d2), PieceStatusMetadata.name)
    _backdate(md_path, 7200)

    # True orphan sidecar: no data file, no .part beside it.
    d3 = Digest.from_bytes(b"ghost")
    orphan = store._md_path(store.cache_path(d3), "namespace")
    os.makedirs(os.path.dirname(orphan), exist_ok=True)
    with open(orphan, "wb"):
        pass
    _backdate(orphan, 7200)

    # tmp-sidecar survivor (crashed set_metadata).
    tmp_md = store.cache_path(d) + "._md_namespace.tmp999.1"
    with open(tmp_md, "wb"):
        pass
    _backdate(tmp_md, 7200)

    counts = scan_store_orphans(
        store, upload_ttl_seconds=3600, min_age_seconds=60
    )
    assert counts["stale_spool"] == 1  # the live spool is NOT counted
    assert counts["stale_partial"] == 0  # fresh .part = active download
    assert counts["orphan_sidecar"] == 1  # d3 only; d2's bitfield spared
    assert counts["tmp_sidecar"] == 1
    assert counts["quarantine"] == 0

    # The .part past the TTL becomes debris (fsck's sweep rule); its
    # bitfield sidecar still is not counted while the .part exists.
    _backdate(store.partial_path(d2), 7200)
    counts = scan_store_orphans(
        store, upload_ttl_seconds=3600, min_age_seconds=60
    )
    assert counts["stale_partial"] == 1
    assert counts["orphan_sidecar"] == 1

    # Quarantined blobs count (operator-visible damage evidence).
    store.quarantine_cache_file(d)
    counts = scan_store_orphans(
        store, upload_ttl_seconds=3600, min_age_seconds=60
    )
    assert counts["quarantine"] == 1

    # Fresh debris under min_age is invisible: the live-race guard (a
    # sidecar between write and rename must not read as an orphan).
    fresh = store._md_path(store.cache_path(Digest.from_bytes(b"x")), "namespace")
    os.makedirs(os.path.dirname(fresh), exist_ok=True)
    with open(fresh, "wb"):
        pass
    c2 = scan_store_orphans(store, upload_ttl_seconds=3600, min_age_seconds=60)
    assert c2["orphan_sidecar"] == counts["orphan_sidecar"]


# -- budgets, streaks, latch, reload ---------------------------------------

def test_budget_breach_counts_and_sustained_hook_latches():
    fired: list[list[str]] = []

    async def main():
        sentinel = ResourceSentinel(
            "test-node",
            {"max_tasks": 1, "breach_streak": 2, "drain_on_breach": True,
             "interval_seconds": 999},
            on_sustained_breach=fired.append,
        )
        try:
            async def sleeper():
                await asyncio.sleep(30)

            tasks = [asyncio.create_task(sleeper()) for _ in range(3)]
            await asyncio.sleep(0)
            before = _breaches("tasks")

            s1 = await sentinel.sample()
            assert "tasks" in s1["breached"]
            assert fired == []  # streak 1 < breach_streak 2
            s2 = await sentinel.sample()
            assert "tasks" in s2["breached"]
            assert len(fired) == 1 and fired[0] == ["tasks"]
            await sentinel.sample()
            assert len(fired) == 1  # latched: no re-fire while breached
            assert _breaches("tasks") == before + 3  # every breach counts

            # Live reload: raising the budget clears the breach (and the
            # latch); dropping it again re-arms the hook.
            sentinel.apply({"max_tasks": 10_000, "breach_streak": 2,
                            "drain_on_breach": True, "interval_seconds": 999})
            s4 = await sentinel.sample()
            assert s4["breached"] == []
            sentinel.apply({"max_tasks": 1, "breach_streak": 2,
                            "drain_on_breach": True, "interval_seconds": 999})
            await sentinel.sample()
            await sentinel.sample()
            assert len(fired) == 2

            for t in tasks:
                t.cancel()
        finally:
            sentinel.stop()

    asyncio.run(main())


def test_drain_on_breach_false_never_fires_hook():
    fired = []

    async def main():
        sentinel = ResourceSentinel(
            "observe-only",
            {"max_tasks": 0, "max_open_fds": 1, "breach_streak": 1,
             "drain_on_breach": False, "interval_seconds": 999},
            on_sustained_breach=fired.append,
        )
        try:
            before = _breaches("fds")
            s = await sentinel.sample()
            assert "fds" in s["breached"]  # any real process has > 1 fd
            assert _breaches("fds") == before + 1
            assert fired == []  # counted + warned, never drained
        finally:
            sentinel.stop()

    asyncio.run(main())


# -- live nodes: /debug/resources + breach -> lameduck ---------------------

def test_debug_resources_and_breach_drain_on_live_nodes(tmp_path):
    from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
    from kraken_tpu.utils.httputil import HTTPClient

    async def main():
        import json

        tracker = TrackerNode()
        await tracker.start()
        # Origin: observe-only budgets -- a forced fd breach counts but
        # must NOT drain.
        origin = OriginNode(
            store_root=str(tmp_path / "o"),
            tracker_addr=tracker.addr,
            dedup=False,
            resources={"interval_seconds": 999, "max_open_fds": 1,
                       "breach_streak": 1, "drain_on_breach": False},
        )
        await origin.start()
        # Agent: task budget with teeth -- a sustained breach enters
        # lameduck (the leaking-node-sheds-itself contract).
        agent = AgentNode(
            store_root=str(tmp_path / "a"),
            tracker_addr=tracker.addr,
            resources={"interval_seconds": 999, "max_tasks": 1,
                       "breach_streak": 1, "drain_on_breach": True},
        )
        await agent.start()
        http = HTTPClient()
        try:
            # The debug surface is live on BOTH muxes and carries the
            # process probes plus each node's sentinel.
            for node in (origin, agent):
                doc = json.loads(
                    await http.get(f"http://{node.addr}/debug/resources")
                )
                assert doc["process"]["open_fds"] > 0
                assert doc["process"]["rss_bytes"] > 0
                comps = {
                    s["last_sample"]["component"] if s["last_sample"] else None
                    for s in doc["sentinels"].values()
                }
                names = {k.split("/")[0] for k in doc["sentinels"]}
                assert {"origin", "agent"} <= names, (comps, names)

            # Forced origin fd breach: counter moves, no drain.
            before = _breaches("fds")
            s = await origin.sentinel.sample()
            assert "fds" in s["breached"]
            assert _breaches("fds") == before + 1
            assert origin.server.lameduck is False
            ok = await http.get(f"http://{origin.addr}/health")
            assert ok == b"ok"

            # Forced agent task breach: sustained (streak 1) -> the node
            # sheds itself. /health flips to 503 and new pulls refuse.
            s = await agent.sentinel.sample()
            assert "tasks" in s["breached"]
            assert agent.server.lameduck is True
            from kraken_tpu.utils.httputil import HTTPError

            with pytest.raises(HTTPError) as ei:
                await http.get(f"http://{agent.addr}/health", retry_5xx=False)
            assert ei.value.status == 503
            assert REGISTRY.counter("resource_breach_drains_total").value(
                component="agent"
            ) >= 1
            # The drain shows on the debug surface too.
            doc = json.loads(
                await http.get(f"http://{agent.addr}/debug/resources")
            )
            assert any(
                v["breach_latched"] for v in doc["sentinels"].values()
            )
        finally:
            await http.close()
            await agent.stop()
            await origin.stop()
            await tracker.stop()

    asyncio.run(main())


def test_sentinel_samples_node_planes(tmp_path):
    """The sentinel's sample carries the node's OWN planes: bufpool
    lease counts from its scheduler and debris from its store."""
    from kraken_tpu.assembly import AgentNode, TrackerNode

    async def main():
        tracker = TrackerNode()
        await tracker.start()
        agent = AgentNode(
            store_root=str(tmp_path / "a"),
            tracker_addr=tracker.addr,
            resources={"interval_seconds": 999,
                       "orphan_min_age_seconds": 0.0},
        )
        await agent.start()
        try:
            # Plant one provable orphan sidecar in the agent's store.
            ghost = agent.store._md_path(
                agent.store.cache_path(Digest.from_bytes(b"ghost")),
                "namespace",
            )
            os.makedirs(os.path.dirname(ghost), exist_ok=True)
            with await asyncio.to_thread(open, ghost, "wb"):
                pass
            _backdate(ghost, 10)
            s = await agent.sentinel.sample()
            assert s["orphans"]["orphan_sidecar"] == 1
            assert s["orphans_total"] == 1
            assert s["bufpool_leased"] == 0
            assert s["conns"] == 0
            assert s["open_fds"] > 0 and s["tasks"] > 0
            assert REGISTRY.gauge("resource_orphan_files").value(
                component="agent", kind="orphan_sidecar"
            ) == 1
        finally:
            await agent.stop()
            await tracker.stop()

    asyncio.run(main())

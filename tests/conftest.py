"""Test session setup.

Tests run on a virtual 8-device CPU mesh so sharding/collective paths are
exercised without multi-chip hardware (the driver separately dry-runs the
multi-chip path; benchmarks run on the real TPU). Must run before jax is
imported anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-registers the TPU platform and overrides
# JAX_PLATFORMS via jax.config; pin it back to cpu for the test session.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    # Registered here (no pytest.ini exists): tier-1 is `-m 'not slow'`,
    # so the fast chaos subset runs in tier-1 and the soak subset does
    # not (docs/TESTING.md).
    config.addinivalue_line(
        "markers", "slow: soak-length tests excluded from tier-1"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failpoint-driven failure injection (tests/test_chaos.py)",
    )

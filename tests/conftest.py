"""Test session setup.

Tests run on a virtual 8-device CPU mesh so sharding/collective paths are
exercised without multi-chip hardware (the driver separately dry-runs the
multi-chip path; benchmarks run on the real TPU). Must run before jax is
imported anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-registers the TPU platform and overrides
# JAX_PLATFORMS via jax.config; pin it back to cpu for the test session.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

"""Test session setup.

Tests run on a virtual 8-device CPU mesh so sharding/collective paths are
exercised without multi-chip hardware (the driver separately dry-runs the
multi-chip path; benchmarks run on the real TPU). Must run before jax is
imported anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-registers the TPU platform and overrides
# JAX_PLATFORMS via jax.config; pin it back to cpu for the test session.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest  # noqa: E402

# Modules under the task-leak tripwire. Hedging and drain made
# cancellation the hot regression surface: a losing hedge or a drained
# conn that is cancelled but never reaped keeps pulling bytes (and
# holding buffers) forever, and asyncio.run's shutdown would silently
# cancel it -- hiding exactly the bug. These modules' asyncio.run calls
# get wrapped so the test FAILS if any task is still pending once the
# test body returns (short grace for in-flight done-callbacks).
# test_soak is the long-lived-fleet tier: a task leaked per soak cycle
# is exactly the weekly-OOM class the sentinel exists to catch, so the
# soak runs under the same tripwire.
_TASK_LEAK_MODULES = {"test_chaos", "test_degradation", "test_soak"}


# Suites running under the KT_SANITIZE asyncio sanitizer in tier-1:
# asyncio debug mode + the slow-sync-callback watchdog
# (kraken_tpu/utils/sanitize.py) that FAILS a test on any on-loop stall
# past the threshold, blaming the stack via the profiler's fold. The
# chaos + degradation suites are the loop's torture tier -- exactly
# where a blocking call regression would hide behind injected faults.
# KT_SANITIZE=1 extends it to every suite; KT_SANITIZE=0 force-disables
# (rig escape hatch); KT_SANITIZE_THRESHOLD tunes the stall bar.
_SANITIZE_MODULES = {"test_chaos", "test_degradation"}


@pytest.fixture(autouse=True)
def kt_sanitize(request, monkeypatch):
    import asyncio

    mode = os.environ.get("KT_SANITIZE", "")
    mod = request.module.__name__.rsplit(".", 1)[-1]
    enabled = mode == "1" or (mode != "0" and mod in _SANITIZE_MODULES)
    if not enabled:
        yield
        return

    from kraken_tpu.utils.sanitize import sanitized_run

    threshold = float(os.environ.get("KT_SANITIZE_THRESHOLD", "1.0"))
    violations: list = []
    orig_run = asyncio.run

    def sanitizing_run(coro, **kw):
        return sanitized_run(
            coro, threshold_seconds=threshold, violations=violations,
            _run=orig_run, **kw,
        )

    monkeypatch.setattr(asyncio, "run", sanitizing_run)
    yield
    assert not violations, (
        "KT_SANITIZE caught on-loop stalls (sync work on the event"
        " loop):\n" + "\n".join(v.render() for v in violations)
    )


@pytest.fixture(autouse=True)
def no_leaked_asyncio_tasks(request, monkeypatch):
    import asyncio

    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod not in _TASK_LEAK_MODULES:
        yield
        return
    leaks: list[str] = []
    orig_run = asyncio.run

    def checked_run(coro, **kw):
        async def wrapper():
            try:
                return await coro
            finally:
                cur = asyncio.current_task()
                pending: list = []
                for _ in range(40):  # ~2 s grace: reaping, not sleeping
                    pending = [
                        t for t in asyncio.all_tasks()
                        if t is not cur and not t.done()
                    ]
                    if not pending:
                        break
                    await asyncio.sleep(0.05)
                leaks.extend(
                    f"{t.get_name()}: {t.get_coro()!r}" for t in pending
                )
        return orig_run(wrapper(), **kw)

    monkeypatch.setattr(asyncio, "run", checked_run)
    yield
    assert not leaks, (
        "leaked pending asyncio tasks after test body:\n" + "\n".join(leaks)
    )


def pytest_configure(config):
    # Registered here (no pytest.ini exists): tier-1 is `-m 'not slow'`,
    # so the fast chaos subset runs in tier-1 and the soak subset does
    # not (docs/TESTING.md).
    config.addinivalue_line(
        "markers", "slow: soak-length tests excluded from tier-1"
    )
    config.addinivalue_line(
        "markers",
        "chaos: failpoint-driven failure injection (tests/test_chaos.py)",
    )
    config.addinivalue_line(
        "markers",
        "soak: gated multi-minute origin soak (tests/test_soak.py) --"
        " also requires KT_SOAK=1 (docs/TESTING.md)",
    )


def pytest_collection_modifyitems(config, items):
    # The gated soak tier: `soak`-marked tests need BOTH `-m slow` (they
    # are slow-marked too, so tier-1 never sees them) and the explicit
    # KT_SOAK=1 ack -- a bare `-m slow` run must not silently commit to
    # 5-10 minutes of wall.
    if os.environ.get("KT_SOAK") == "1":
        return
    skip = pytest.mark.skip(
        reason="gated soak: set KT_SOAK=1 (and run with -m slow)"
    )
    for item in items:
        if "soak" in item.keywords:
            item.add_marker(skip)

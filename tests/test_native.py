"""Golden tests for the native host packer (C, AVX-512 w/ scalar fallback).

The packed layout feeds the production Pallas path; a silent layout bug
would produce wrong digests at 80+ GB/s, so the C output is checked
element-exactly against an independent NumPy construction.
"""

import numpy as np
import pytest

from kraken_tpu import native


def _reference(data: np.ndarray, nb_out: int) -> np.ndarray:
    m, piece_len = data.shape
    t, nbd = m // 1024, piece_len // 64
    w = data.reshape(t, 1024, nbd, 16, 4)
    be = (
        (w[..., 0].astype(np.uint32) << 24)
        | (w[..., 1].astype(np.uint32) << 16)
        | (w[..., 2].astype(np.uint32) << 8)
        | w[..., 3].astype(np.uint32)
    )
    out = np.zeros((t, nb_out, 16, 1024), dtype=np.uint32)
    out[:, :nbd] = be.transpose(0, 2, 3, 1)
    return out


@pytest.mark.parametrize("piece_len,tiles", [(64, 1), (576, 1), (4096, 2)])
def test_pack_tiles_matches_reference(piece_len, tiles):
    rng = np.random.default_rng(piece_len)
    data = rng.integers(0, 256, size=(1024 * tiles, piece_len), dtype=np.uint8)
    nb_out = ((piece_len // 64 + 7) // 8) * 8  # packed_nb for _KB=8
    got = native.pack_tiles(data, nb_out)
    assert np.array_equal(got, _reference(data, nb_out))


def test_pack_tiles_validates_shape():
    with pytest.raises(ValueError):
        native.pack_tiles(np.zeros((100, 64), dtype=np.uint8), 1)
    with pytest.raises(ValueError):
        native.pack_tiles(np.zeros((1024, 63), dtype=np.uint8), 1)


@pytest.mark.parametrize("threads", [1, 3, 8, 64])
def test_pack_tiles_threaded_matches_single(threads):
    """The pthread fan-out over 16-piece groups must be bit-identical to
    the single-threaded pack for every thread count (including more
    threads than groups, which clamps)."""
    if not native.have_native_packer():
        pytest.skip("no C toolchain")
    rng = np.random.default_rng(threads)
    data = rng.integers(0, 256, size=(2048, 448), dtype=np.uint8)
    nb_out = 8
    base = native.pack_tiles(data, nb_out, threads=1)
    got = native.pack_tiles(data, nb_out, threads=threads)
    assert np.array_equal(got, base)
    assert np.array_equal(got, _reference(data, nb_out))


def test_scalar_and_simd_paths_agree():
    """The runtime-dispatched C path must agree with the NumPy fallback
    (covers both when the build has AVX-512 and when it doesn't)."""
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, size=(1024, 128), dtype=np.uint8)
    c_out = native.pack_tiles(data, 2)
    lib = native._LIB
    try:
        native._LIB = None
        py_out = native.pack_tiles(data, 2)
    finally:
        native._LIB = lib
    assert np.array_equal(c_out, py_out)


def test_native_cdc_chunker_matches_reference():
    """The C chunker and the NumPy fallback both produce chunk_reference's
    exact cuts -- boundaries are a persistent on-disk contract."""

    import kraken_tpu.native as nat
    from kraken_tpu.ops.cdc import CDCParams, chunk_host, chunk_reference

    p = CDCParams(min_size=64, avg_size=256, max_size=1024)
    rng = np.random.default_rng(3)
    for n in (0, 1, 63, 64, 65, 255, 4096, 20000):
        data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        ref = chunk_reference(data, p) if n else []
        assert chunk_host(data, p).tolist() == ref, n
        lib, nat._LIB = nat._LIB, None  # force the NumPy fallback
        try:
            assert chunk_host(data, p).tolist() == ref, ("numpy", n)
        finally:
            nat._LIB = lib
    # Low-entropy data (max_size forcing) and default params.
    data = b"\x00" * 300_000
    pd = CDCParams()
    ref = chunk_reference(data, pd)
    assert chunk_host(data, pd).tolist() == ref
    assert ref[0] == pd.max_size  # constant data never hits a mask

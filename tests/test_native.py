"""Golden tests for the native host packer (C, AVX-512 w/ scalar fallback).

The packed layout feeds the production Pallas path; a silent layout bug
would produce wrong digests at 80+ GB/s, so the C output is checked
element-exactly against an independent NumPy construction.
"""

import numpy as np
import pytest

from kraken_tpu import native


def _reference(data: np.ndarray, nb_out: int) -> np.ndarray:
    m, piece_len = data.shape
    t, nbd = m // 1024, piece_len // 64
    w = data.reshape(t, 1024, nbd, 16, 4)
    be = (
        (w[..., 0].astype(np.uint32) << 24)
        | (w[..., 1].astype(np.uint32) << 16)
        | (w[..., 2].astype(np.uint32) << 8)
        | w[..., 3].astype(np.uint32)
    )
    out = np.zeros((t, nb_out, 16, 1024), dtype=np.uint32)
    out[:, :nbd] = be.transpose(0, 2, 3, 1)
    return out


@pytest.mark.parametrize("piece_len,tiles", [(64, 1), (576, 1), (4096, 2)])
def test_pack_tiles_matches_reference(piece_len, tiles):
    rng = np.random.default_rng(piece_len)
    data = rng.integers(0, 256, size=(1024 * tiles, piece_len), dtype=np.uint8)
    nb_out = ((piece_len // 64 + 7) // 8) * 8  # packed_nb for _KB=8
    got = native.pack_tiles(data, nb_out)
    assert np.array_equal(got, _reference(data, nb_out))


def test_pack_tiles_validates_shape():
    with pytest.raises(ValueError):
        native.pack_tiles(np.zeros((100, 64), dtype=np.uint8), 1)
    with pytest.raises(ValueError):
        native.pack_tiles(np.zeros((1024, 63), dtype=np.uint8), 1)


@pytest.mark.parametrize("threads", [1, 3, 8, 64])
def test_pack_tiles_threaded_matches_single(threads):
    """The pthread fan-out over 16-piece groups must be bit-identical to
    the single-threaded pack for every thread count (including more
    threads than groups, which clamps)."""
    if not native.have_native_packer():
        pytest.skip("no C toolchain")
    rng = np.random.default_rng(threads)
    data = rng.integers(0, 256, size=(2048, 448), dtype=np.uint8)
    nb_out = 8
    base = native.pack_tiles(data, nb_out, threads=1)
    got = native.pack_tiles(data, nb_out, threads=threads)
    assert np.array_equal(got, base)
    assert np.array_equal(got, _reference(data, nb_out))


def test_scalar_and_simd_paths_agree():
    """The runtime-dispatched C path must agree with the NumPy fallback
    (covers both when the build has AVX-512 and when it doesn't)."""
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, size=(1024, 128), dtype=np.uint8)
    c_out = native.pack_tiles(data, 2)
    lib = native._LIB
    try:
        native._LIB = None
        py_out = native.pack_tiles(data, 2)
    finally:
        native._LIB = lib
    assert np.array_equal(c_out, py_out)


def test_native_cdc_chunker_matches_reference():
    """The C chunker and the NumPy fallback both produce chunk_reference's
    exact cuts -- boundaries are a persistent on-disk contract."""

    import kraken_tpu.native as nat
    from kraken_tpu.ops.cdc import CDCParams, chunk_host, chunk_reference

    p = CDCParams(min_size=64, avg_size=256, max_size=1024)
    rng = np.random.default_rng(3)
    for n in (0, 1, 63, 64, 65, 255, 4096, 20000):
        data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        ref = chunk_reference(data, p) if n else []
        assert chunk_host(data, p).tolist() == ref, n
        lib, nat._LIB = nat._LIB, None  # force the NumPy fallback
        try:
            assert chunk_host(data, p).tolist() == ref, ("numpy", n)
        finally:
            nat._LIB = lib
    # Low-entropy data (max_size forcing) and default params.
    data = b"\x00" * 300_000
    pd = CDCParams()
    ref = chunk_reference(data, pd)
    assert chunk_host(data, pd).tolist() == ref
    assert ref[0] == pd.max_size  # constant data never hits a mask


def test_pack_tiles_range_matches_reference():
    """Cooperative range packing (the GIL-free HashPool entry): disjoint
    group stripes written by separate calls must reassemble to exactly
    the single-call layout, including out-of-range clamping."""
    if not native.have_native_packer():
        pytest.skip("no native packer on this rig")
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(2048, 576), dtype=np.uint8)
    nb_out = 16
    out = np.zeros((2, nb_out, 16, 1024), dtype=np.uint32)
    n_groups = 2048 // 16
    # Three unequal stripes + a deliberately overshooting upper bound.
    native.pack_tiles_range(data, nb_out, out, 0, 17)
    native.pack_tiles_range(data, nb_out, out, 17, 100)
    native.pack_tiles_range(data, nb_out, out, 100, n_groups + 50)
    assert np.array_equal(out, _reference(data, nb_out))


def test_pack_tiles_pooled_matches_reference():
    """pack_tiles_pooled through a real HashPool must be bit-exact (and
    fall back cleanly when the pool can't help)."""
    from kraken_tpu.core.hasher import HashPool

    rng = np.random.default_rng(12)
    data = rng.integers(0, 256, size=(2048, 576), dtype=np.uint8)
    want = _reference(data, 16)
    pool = HashPool(2, name="test-pack")
    assert np.array_equal(native.pack_tiles_pooled(data, 16, pool), want)
    # pool=None falls back to the single-call path.
    assert np.array_equal(native.pack_tiles_pooled(data, 16, None), want)


def test_pack_out_buffer_validation():
    """Caller-supplied `out` (a bufpool staging lease in production) is
    validated for dtype, shape, contiguity, and writability before any
    raw pointer reaches the C packer."""
    data = np.zeros((1024, 64), dtype=np.uint8)
    with pytest.raises(ValueError):  # wrong dtype
        native.pack_tiles(data, 8, out=np.zeros((1, 8, 16, 1024), np.uint64))
    with pytest.raises(ValueError):  # wrong shape
        native.pack_tiles(data, 8, out=np.zeros((1, 8, 16, 512), np.uint32))
    big = np.zeros((1, 8, 16, 2048), dtype=np.uint32)
    with pytest.raises(ValueError):  # non-contiguous view
        native.pack_tiles(data, 8, out=big[:, :, :, ::2])
    ro = np.zeros((1, 8, 16, 1024), dtype=np.uint32)
    ro.setflags(write=False)
    with pytest.raises(ValueError):  # read-only
        native.pack_tiles(data, 8, out=ro)


def test_pooled_pack_scales_with_workers():
    """On a multi-core rig, 2 pack workers must beat 1 by a real margin
    (the pack loop is GIL-free and group-parallel). Interleaved pairwise
    timing so machine noise hits both configs alike."""
    import os
    import time

    if (os.cpu_count() or 1) < 2:
        pytest.skip("scaling pin needs >= 2 cores")
    if not native.have_native_packer():
        pytest.skip("no native packer on this rig")
    from kraken_tpu.core.hasher import HashPool

    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, size=(8192, 4096), dtype=np.uint8)
    out = np.zeros((8, 64, 16, 1024), dtype=np.uint32)
    pool1 = HashPool(1, name="scale1")
    pool2 = HashPool(2, name="scale2")

    def run(pool) -> float:
        t0 = time.perf_counter()
        native.pack_tiles_pooled(data, 64, pool, out=out)
        return time.perf_counter() - t0

    for pool in (pool2, pool1):  # warm caches + pool threads
        run(pool)
    ratios = []
    for _ in range(5):
        t1, t2 = run(pool1), run(pool2)
        ratios.append(t1 / t2)
    ratios.sort()
    assert ratios[len(ratios) // 2] >= 1.3, ratios

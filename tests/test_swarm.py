"""In-process multi-peer swarm tests: real schedulers, real TCP conns, real
piece exchange on localhost; fake announce/metainfo layer.

This is the reference's key testing trick (SURVEY.md SS4 tier 3): full swarm
behavior -- seeder->leecher, N-way fan-out, piece verification, blacklist --
with no containers.
"""

import asyncio
import os

import numpy as np
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.scheduler import Scheduler, SchedulerConfig
from kraken_tpu.p2p.storage import AgentTorrentArchive, BatchedVerifier, OriginTorrentArchive
from kraken_tpu.store import CAStore

NS = "test-ns"


def make_metainfo(blob: bytes, piece_length: int = 4096) -> MetaInfo:
    hashes = get_hasher("cpu").hash_pieces(blob, piece_length)
    return MetaInfo(Digest.from_bytes(blob), len(blob), piece_length, hashes.tobytes())


class FakeTracker:
    """In-memory announce + metainfo service shared by all peers in test."""

    def __init__(self, interval: float = 0.2):
        self.metainfos: dict[str, MetaInfo] = {}
        self.peers: dict[str, dict[str, PeerInfo]] = {}  # info_hash -> peers
        self.interval = interval
        self.down = False  # outage injection: every RPC raises

    def client_for(self, scheduler_ref: dict):
        tracker = self

        class _Client:
            async def get(self, namespace: str, d: Digest) -> MetaInfo:
                if tracker.down:
                    raise ConnectionError("tracker down")
                return tracker.metainfos[d.hex]

            async def announce(self, d, h, namespace, complete):
                if tracker.down:
                    raise ConnectionError("tracker down")
                sched = scheduler_ref["s"]
                me = PeerInfo(
                    peer_id=sched.peer_id, ip=sched.ip, port=sched.port,
                    complete=complete,
                )
                swarm = tracker.peers.setdefault(h.hex, {})
                swarm[me.peer_id.hex] = me
                others = [p for pid, p in swarm.items() if pid != me.peer_id.hex]
                return others, tracker.interval

        return _Client()


def make_peer(tmp_path, name: str, tracker: FakeTracker, seed_blob: bytes | None = None,
              events=None):
    """Build a scheduler with its own store. If ``seed_blob``, preload and
    seed it (origin-style). ``events`` is an optional networkevent
    Producer (swarm tracing assertions)."""
    store = CAStore(str(tmp_path / name))
    verifier = BatchedVerifier()
    ref: dict = {}
    if seed_blob is not None:
        d = Digest.from_bytes(seed_blob)
        store.create_cache_file(d, iter([seed_blob]))
        archive = OriginTorrentArchive(store, verifier)
    else:
        archive = AgentTorrentArchive(store, verifier)
    client = tracker.client_for(ref)
    sched = Scheduler(
        peer_id=PeerID(os.urandom(20).hex()),
        ip="127.0.0.1",
        port=0,
        archive=archive,
        metainfo_client=client,
        announce_client=client,
        config=SchedulerConfig(
            announce_interval_seconds=0.1,
            retry_tick_seconds=0.2,
            dial_timeout_seconds=2.0,
        ),
        events=events,
    )
    ref["s"] = sched
    return sched, store


async def start_all(*scheds):
    for s in scheds:
        await s.start()


async def stop_all(*scheds):
    for s in scheds:
        await s.stop()


def test_seeder_to_leecher(tmp_path):
    async def main():
        blob = os.urandom(100_000)
        mi = make_metainfo(blob)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 15)
            assert lstore.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


def test_multi_leecher_fanout(tmp_path):
    """One seeder, several leechers downloading concurrently; all must
    converge byte-identically (pieces flow leecher<->leecher too)."""

    async def main():
        blob = os.urandom(300_000)
        mi = make_metainfo(blob, piece_length=8192)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leechers = [make_peer(tmp_path, f"l{i}", tracker) for i in range(4)]
        await start_all(seeder, *(s for s, _ in leechers))
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(
                asyncio.gather(*(s.download(NS, mi.digest) for s, _ in leechers)),
                30,
            )
            for _s, store in leechers:
                assert store.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(seeder, *(s for s, _ in leechers))

    asyncio.run(main())


def test_download_coalesces(tmp_path):
    async def main():
        blob = os.urandom(50_000)
        mi = make_metainfo(blob)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(
                asyncio.gather(*(leecher.download(NS, mi.digest) for _ in range(5))),
                15,
            )
            assert lstore.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


def test_resume_from_partial(tmp_path):
    """A leecher with a persisted partial bitfield only fetches missing
    pieces and completes (crash-resume, SURVEY.md SS5)."""

    async def main():
        blob = os.urandom(64 * 1024)
        mi = make_metainfo(blob, piece_length=4096)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)

        # Pre-populate half the pieces as a crashed download would leave.
        from kraken_tpu.store import PieceStatusMetadata

        lstore.allocate_partial_file(mi.digest, mi.length)
        status = PieceStatusMetadata(mi.num_pieces)
        path = lstore.partial_path(mi.digest)
        with await asyncio.to_thread(open, path, "r+b") as f:
            for i in range(0, mi.num_pieces, 2):
                f.seek(i * mi.piece_length)
                f.write(blob[i * mi.piece_length : (i + 1) * mi.piece_length])
                status.set(i)
        lstore.set_metadata(mi.digest, status)

        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 15)
            assert lstore.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


def test_corrupt_seeder_blacklisted(tmp_path):
    """A peer serving corrupt pieces gets dropped + blacklisted; the
    download then succeeds from an honest seeder."""

    async def main():
        blob = os.urandom(60_000)
        mi = make_metainfo(blob, piece_length=4096)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        # Evil seeder: same metainfo, different (wrong) content.
        evil_blob = os.urandom(len(blob))
        evil, estore = make_peer(tmp_path, "evil", tracker, seed_blob=evil_blob)
        # Register evil's torrent under the REAL metainfo: build a lying
        # archive view by committing evil blob under the real digest.
        estore.wipe()
        estore.create_cache_file(mi.digest, iter([evil_blob]), verify=False)

        honest, _ = make_peer(tmp_path, "honest", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)

        await start_all(evil, honest, leecher)
        try:
            evil.seed(mi, NS)
            await asyncio.sleep(0.15)  # let evil announce first
            honest.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 20)
            assert lstore.read_cache_file(mi.digest) == blob
            # evil must be blacklisted for this torrent
            assert any(
                leecher.conn_state.blacklist.blocked(evil.peer_id, mi.info_hash)
                for _ in [0]
            )
        finally:
            await stop_all(evil, honest, leecher)

    asyncio.run(main())


def test_announce_rate_bounded_at_scale(tmp_path):
    """1k seeding torrents on one scheduler: announce calls/sec stays at
    the configured cap, not O(torrents) (announcequeue pacing)."""

    async def main():
        calls = []

        class CountingClient:
            async def get(self, namespace, d):
                raise AssertionError("not used")

            async def announce(self, d, h, namespace, complete):
                calls.append(asyncio.get_running_loop().time())
                return [], 0.05  # tracker asks for very eager re-announce

        store = CAStore(str(tmp_path / "s"))
        client = CountingClient()
        sched = Scheduler(
            peer_id=PeerID(os.urandom(20).hex()),
            ip="127.0.0.1",
            port=0,
            archive=OriginTorrentArchive(store, BatchedVerifier()),
            metainfo_client=client,
            announce_client=client,
            config=SchedulerConfig(
                announce_interval_seconds=0.05,
                max_announce_rate=50.0,
                announce_tick_seconds=0.05,
            ),
        )
        await sched.start()
        try:
            rng = np.random.default_rng(3)
            for i in range(1000):
                blob = rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
                d = Digest.from_bytes(blob + i.to_bytes(4, "big"))
                mi = MetaInfo(d, 64, 4096, b"\x00" * 32)
                store.create_cache_file(d, iter([blob]), verify=False)
                sched.seed(mi, NS)
            assert len(sched._controls) == 1000
            t0 = asyncio.get_running_loop().time()
            await asyncio.sleep(2.0)
            window = [t for t in calls if t >= t0]
            rate = len(window) / 2.0
            # Unpaced this would be ~1000 first announces immediately and
            # ~20k/s steady-state at the 0.05 s tracker interval.
            assert rate <= 50.0 * 1.5, f"announce rate {rate}/s exceeds cap"
            assert rate >= 50.0 * 0.5, f"announce rate {rate}/s: pump stalled?"
        finally:
            await sched.stop()

    asyncio.run(main())


def test_announce_inflight_capped_when_tracker_hangs(tmp_path):
    """Total-outage announce storm control: with every walk hanging to
    its timeout, at most max_announce_inflight walks may be in flight
    per agent -- the rate cap only bounds STARTS, so without this cap N
    failing torrents stack N hung walks. When the walks finally resolve
    the pump must resume, and the per-torrent decorrelated-jitter
    backoffs must desync (no synchronized retry storm at revival)."""

    async def main():
        inflight = {"now": 0, "peak": 0, "total": 0}
        gate = asyncio.Event()

        class HangingClient:
            async def get(self, namespace, d):
                raise AssertionError("not used")

            async def announce(self, d, h, namespace, complete):
                inflight["now"] += 1
                inflight["total"] += 1
                inflight["peak"] = max(inflight["peak"], inflight["now"])
                try:
                    await gate.wait()
                finally:
                    inflight["now"] -= 1
                raise ConnectionError("tracker dark")

        store = CAStore(str(tmp_path / "s"))
        client = HangingClient()
        sched = Scheduler(
            peer_id=PeerID(os.urandom(20).hex()),
            ip="127.0.0.1",
            port=0,
            archive=OriginTorrentArchive(store, BatchedVerifier()),
            metainfo_client=client,
            announce_client=client,
            config=SchedulerConfig(
                announce_interval_seconds=0.05,
                # Long enough that the backoff cap (= interval) leaves
                # the jitter draw room to spread; the FIRST failure's
                # backoff is deterministically base=1.0 s, divergence
                # shows from the second failure on.
                seed_announce_interval_seconds=10.0,
                max_announce_rate=1000.0,
                announce_tick_seconds=0.02,
                max_announce_inflight=8,
            ),
        )
        await sched.start()
        try:
            rng = np.random.default_rng(4)
            for i in range(100):
                blob = rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
                d = Digest.from_bytes(blob + i.to_bytes(4, "big"))
                mi = MetaInfo(d, 64, 4096, b"\x00" * 32)
                store.create_cache_file(d, iter([blob]), verify=False)
                sched.seed(mi, NS)
            await asyncio.sleep(1.0)
            # The cap held AND saturated: bounded, not stalled.
            assert inflight["peak"] <= 8, inflight
            assert inflight["now"] == 8, inflight
            # Walks resolve (all failing): the pump works through the
            # backlog instead of staying wedged at the first 8...
            gate.set()
            await asyncio.sleep(0.6)
            assert inflight["total"] >= 30, inflight
            # ...and after a SECOND failure round (first backoff is the
            # deterministic 1.0 s base; retries land ~1 s later) the
            # per-torrent backoffs are jittered apart, not synchronized
            # into one storm.
            await asyncio.sleep(1.6)
            backoffs = {
                round(ctl.announce_backoff, 6)
                for ctl in sched._controls.values()
                if ctl.announce_backoff > 1.0001
            }
            assert len(backoffs) >= 10, sorted(backoffs)[:20]
        finally:
            gate.set()
            await sched.stop()

    asyncio.run(main())


def test_seeder_dies_mid_pull_then_returns(tmp_path):
    """The only seeder dies mid-transfer; the leecher's request timeouts +
    retry ticks keep the torrent alive, and when a seeder returns on the
    SAME address the download completes -- no manual intervention, no
    restart of the leecher (the failure-recovery story of SURVEY.md SS5
    at the swarm layer)."""

    async def main():
        from kraken_tpu.store import PieceStatusMetadata

        blob = os.urandom(2 * 1024 * 1024)
        mi = make_metainfo(blob, piece_length=4096)  # 512 pieces
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        seeder, _sstore = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        port = seeder.port  # rebind here after the "crash"
        stopped = asyncio.Event()

        async def kill_when_partial():
            # Deterministically mid-pull: wait for SOME but well under all
            # pieces (a near-complete trigger could let the download finish
            # before stop() lands). Bail if the download somehow completes
            # first -- completion DELETES the piece-status sidecar, so the
            # poll would otherwise spin forever.
            while True:
                await asyncio.sleep(0.005)
                if lstore.in_cache(mi.digest):
                    raise AssertionError("download finished before the kill")
                # Live progress, not the sidecar: persistence is debounced
                # (round 5), so the on-disk bitfield lags real progress.
                n = next(
                    (
                        ctl.torrent.num_pieces_complete()
                        for ctl in leecher._controls.values()
                        if ctl.torrent.metainfo.digest == mi.digest
                    ),
                    0,
                )
                if 0 < n < mi.num_pieces // 2:
                    break
            await seeder.stop()
            stopped.set()
            await asyncio.sleep(1.0)  # swarm starves: the only seeder is gone
            reborn, _ = make_peer(
                tmp_path, "seeder", tracker, seed_blob=blob
            )
            reborn.port = port
            await reborn.start()
            reborn.seed(mi, NS)
            return reborn

        seeder.seed(mi, NS)
        kill_task = asyncio.create_task(kill_when_partial())
        try:
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            assert lstore.read_cache_file(mi.digest) == blob
            assert stopped.is_set(), "seeder never actually died mid-test"
        finally:
            # Bounded, and never mask the try-body's failure: the leecher
            # must stop even if the kill task itself blew up.
            reborn = None
            try:
                reborn = await asyncio.wait_for(
                    asyncio.shield(kill_task), 10
                )
            except Exception:
                kill_task.cancel()
            scheds = [leecher] + ([reborn] if reborn is not None else [])
            await stop_all(*scheds)

    asyncio.run(main())


def test_tracker_outage_mid_pull_data_plane_survives(tmp_path):
    """The tracker dies mid-transfer: established conns keep exchanging
    pieces (the data plane owes the tracker nothing after discovery), the
    swallowed-announce meter counts the outage, a NEW leecher can't join
    (typed failure, not a hang), and on revival it completes normally."""

    async def main():
        from kraken_tpu.p2p.scheduler import _announce_failures
        from kraken_tpu.store import PieceStatusMetadata

        blob = os.urandom(1024 * 1024)
        mi = make_metainfo(blob, piece_length=4096)  # 256 pieces
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        seeder, _sstore = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        seeder.seed(mi, NS)

        outage = asyncio.Event()

        async def kill_tracker_when_partial():
            # Poll LIVE torrent progress (bitfield sidecar persistence is
            # debounced since round 5, so the on-disk copy lags by up to
            # BITS_FLUSH_SECONDS -- a small blob completes before the
            # first flush).
            while True:
                await asyncio.sleep(0.002)
                if lstore.in_cache(mi.digest):
                    raise AssertionError("download finished before outage")
                n = next(
                    (
                        ctl.torrent.num_pieces_complete()
                        for ctl in leecher._controls.values()
                        if ctl.torrent.metainfo.digest == mi.digest
                    ),
                    0,
                )
                if 0 < n < mi.num_pieces // 2:
                    break
            tracker.down = True
            outage.set()

        kill_task = asyncio.create_task(kill_tracker_when_partial())
        late, latestore = make_peer(tmp_path, "late", tracker)
        try:
            failures_before = _announce_failures.counter.value()
            # Metainfo was fetched while the tracker was up; the conns are
            # established: the pull must complete through the outage.
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            await kill_task
            assert outage.is_set() and tracker.down
            assert lstore.read_cache_file(mi.digest) == blob

            # A late joiner fails TYPED at the metainfo fetch -- no hang.
            await late.start()
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(late.download(NS, mi.digest), 10)

            # The periodic announce pump keeps hitting the dead tracker and
            # must METER it (VERDICT r3 missing #4: no silent swallows).
            deadline = asyncio.get_running_loop().time() + 10
            while _announce_failures.counter.value() <= failures_before:
                assert asyncio.get_running_loop().time() < deadline, (
                    "announce failures were swallowed unmetered"
                )
                await asyncio.sleep(0.05)

            # Revival: the next announce round re-forms the swarm and the
            # late joiner completes (seeder + completed leecher both serve).
            tracker.down = False
            await asyncio.wait_for(late.download(NS, mi.digest), 30)
            assert latestore.read_cache_file(mi.digest) == blob
        finally:
            if not kill_task.done():
                kill_task.cancel()
            await stop_all(seeder, leecher, late)

    asyncio.run(main())


def test_torrent_summary_emitted_on_completion(tmp_path):
    """Every completed download leaves ONE torrent_summary line in the
    networkevents JSONL stream -- the per-torrent lifecycle rollup
    (pieces, peers used, bytes up/down, duration, blacklist events;
    upstream torrentlog parity). Seeders (complete at creation) emit
    none: there is no download story to tell."""
    import io
    import json

    from kraken_tpu.p2p.networkevent import Producer

    async def main():
        blob = os.urandom(100_000)
        mi = make_metainfo(blob)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        sink = io.StringIO()
        seeder_events = Producer("seeder-pid")
        leecher_events = Producer("leecher-pid", sink=sink)
        seeder, _ = make_peer(
            tmp_path, "seeder", tracker, seed_blob=blob,
            events=seeder_events,
        )
        leecher, lstore = make_peer(
            tmp_path, "leecher", tracker, events=leecher_events,
        )
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 15)
            assert lstore.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(seeder, leecher)

        lines = [json.loads(ln) for ln in sink.getvalue().splitlines()]
        summaries = [e for e in lines if e["name"] == "torrent_summary"]
        assert len(summaries) == 1, [e["name"] for e in lines]
        s = summaries[0]
        assert s["info_hash"] == mi.info_hash.hex
        assert s["blob"] == mi.digest.hex
        assert s["pieces"] == mi.num_pieces
        assert s["length"] == len(blob)
        assert s["peers"] >= 1
        # Endgame can duplicate a piece; bytes_down covers at least the
        # blob, and this leecher never served.
        assert s["bytes_down"] >= len(blob)
        assert s["bytes_up"] == 0
        assert s["duration_s"] >= 0
        assert s["blacklist_events"] == 0
        # The summary rides the SAME stream as the piece events, after
        # its own torrent_complete.
        names = [e["name"] for e in lines]
        assert names.index("torrent_complete") < names.index("torrent_summary")
        assert "receive_piece" in names
        # A pure seeder never completes a download: no summary.
        assert not [
            e for e in seeder_events.events if e["name"] == "torrent_summary"
        ]

    asyncio.run(main())


def test_torrent_summary_counts_blacklist_events(tmp_path):
    """A pull that survives a corrupt peer reports the ban in its
    summary (the operator's at-a-glance 'this pull fought misbehavior'
    signal)."""
    from kraken_tpu.p2p.networkevent import Producer
    from kraken_tpu.p2p.storage import Torrent

    async def main():
        blob = os.urandom(60_000)
        mi = make_metainfo(blob)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi

        events = Producer("leecher-pid")
        evil, _ = make_peer(tmp_path, "evil", tracker, seed_blob=blob)
        # The corrupt seeder serves flipped bytes (same shape the chaos
        # tier uses: the read path lies, the wire stays honest).
        orig_read = Torrent.read_piece

        def corrupt_read(self, i):
            data = orig_read(self, i)
            return bytes([data[0] ^ 0xFF]) + data[1:]

        evil_torrents = []
        orig_create = evil.archive.create_torrent

        def tracked_create(metainfo):
            t = orig_create(metainfo)
            evil_torrents.append(t)
            t.read_piece = corrupt_read.__get__(t, Torrent)
            return t

        evil.archive.create_torrent = tracked_create
        honest, _ = make_peer(tmp_path, "honest", tracker, seed_blob=blob)
        leecher, lstore = make_peer(
            tmp_path, "leecher", tracker, events=events
        )
        await start_all(evil, honest, leecher)
        try:
            evil.seed(mi, NS)
            honest.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 20)
            assert lstore.read_cache_file(mi.digest) == blob
        finally:
            await stop_all(evil, honest, leecher)

        summaries = [
            e for e in events.events if e["name"] == "torrent_summary"
        ]
        assert len(summaries) == 1
        # The leecher may or may not have dialed the corrupt seeder
        # first, but when it did, the ban must be in the rollup.
        banned = [
            e for e in events.events if e["name"] == "blacklist_conn"
        ]
        assert summaries[0]["blacklist_events"] == len(banned)

    asyncio.run(main())

"""Observability plane: per-endpoint metrics, /metrics, hasher gauges,
structured JSON logs.

VERDICT r2 missing #1: the repo had zero metrics. Now every component app
carries latency/status middleware and a Prometheus-text /metrics
endpoint; the hash plane exports the north-star GB/s and batch-occupancy
gauges; the CLI emits one JSON line per log record.

NOTE: the herd here runs in ONE process, so all five components share the
process-global registry -- each scrape returns the union, and per-
component assertions go through the ``component`` label (in production
each process exposes only its own).
"""

import asyncio
import os
import json
import logging

from kraken_tpu.utils.metrics import Registry, REGISTRY
from kraken_tpu.utils.structlog import JSONFormatter


def test_counter_gauge_histogram_render():
    reg = Registry()
    c = reg.counter("reqs_total", "requests")
    c.inc(component="origin", status="200")
    c.inc(2, component="origin", status="200")
    c.inc(component="agent", status="404")
    g = reg.gauge("gbps", "throughput")
    g.set(74.8, hasher="tpu")
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05, endpoint="/health")
    h.observe(0.5, endpoint="/health")
    h.observe(5.0, endpoint="/health")

    text = reg.render()
    assert '# TYPE reqs_total counter' in text
    assert 'reqs_total{component="origin",status="200"} 3.0' in text
    assert 'reqs_total{component="agent",status="404"} 1.0' in text
    assert 'gbps{hasher="tpu"} 74.8' in text
    assert 'lat_seconds_bucket{endpoint="/health",le="0.1"} 1.0' in text
    assert 'lat_seconds_bucket{endpoint="/health",le="1.0"} 2.0' in text
    assert 'lat_seconds_bucket{endpoint="/health",le="+Inf"} 3.0' in text
    assert 'lat_seconds_count{endpoint="/health"} 3.0' in text
    assert 'lat_seconds_sum{endpoint="/health"} 5.55' in text
    assert c.value(component="origin", status="200") == 3.0
    assert h.count(endpoint="/health") == 3.0


def test_json_log_line_roundtrips():
    fmt = JSONFormatter(component="origin")
    rec = logging.LogRecord(
        "kraken.assembly", logging.INFO, __file__, 1,
        "evicted blobs", (), None,
    )
    rec.count = 7
    doc = json.loads(fmt.format(rec))
    assert doc["msg"] == "evicted blobs"
    assert doc["level"] == "info"
    assert doc["component"] == "origin"
    assert doc["count"] == 7
    assert isinstance(doc["ts"], float)


def test_metrics_move_across_all_five_components(tmp_path):
    asyncio.run(_drive_metrics_herd(tmp_path))


async def _drive_metrics_herd(tmp_path):
    from kraken_tpu.utils.httputil import HTTPClient
    from tests.test_registry import (
        build_cluster, make_image, pull_image, push_image, stop_cluster,
    )

    c = await build_cluster(tmp_path, "obs")
    http = HTTPClient()
    try:
        config, layers, manifest = make_image()
        await push_image(
            http, c["proxy"].addr, "library/obs", "v1", config, layers,
            manifest,
        )
        await pull_image(
            http, f"{c['agent'].host}:{c['agent'].registry_port}",
            "library/obs", "v1",
        )

        # Every node type serves /metrics with ITS requests counted.
        addrs = {
            "tracker": c["tracker"].addr,
            "origin": c["origin"].addr,
            "build-index": c["bindex"].addr,
            "proxy": c["proxy"].addr,
            "agent": c["agent"].addr,
            "agent-registry": f"{c['agent'].host}:{c['agent'].registry_port}",
        }
        for component, addr in addrs.items():
            text = (await http.get(f"http://{addr}/metrics")).decode()
            assert f'component="{component}"' in text, (
                f"no {component} requests counted; scrape:\n"
                + text[:2000]
            )
            assert "http_request_duration_seconds_bucket" in text

        # The endpoint label is the route template, never a raw digest.
        origin_text = (
            await http.get(f"http://{c['origin'].addr}/metrics")
        ).decode()
        assert 'endpoint="/namespace/{ns}/blobs/{d}/uploads/{uid}"' in origin_text
        assert "sha256:" not in origin_text

        # North-star hasher gauges moved (metainfo-gen hashed the layers).
        assert REGISTRY.counter("hasher_bytes_total").value(hasher="cpu") > 0
        assert "hasher_last_gbps" in origin_text
        # Agent verify plane counted the swarm pieces.
        assert REGISTRY.counter("verify_pieces_total").value() > 0
    finally:
        await http.close()
        await stop_cluster(c)


def test_network_events_cover_piece_flow(tmp_path):
    """The swarm tracing plane records the full reference event set during
    a real transfer: torrent add, conn lifecycle, per-piece request and
    receive, completion (SURVEY SS5 offline swarm reconstruction)."""

    from kraken_tpu.p2p.networkevent import Producer
    from test_swarm import FakeTracker, make_metainfo, make_peer, NS

    async def main():
        blob = os.urandom(64 * 1024)
        mi = make_metainfo(blob, piece_length=4096)  # 16 pieces
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        leecher.events = Producer("leecher")  # in-memory ring
        await seeder.start()
        await leecher.start()
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 15)
        finally:
            await seeder.stop()
            await leecher.stop()

        names = {e["name"] for e in leecher.events.events}
        assert {"add_torrent", "announce", "add_active_conn",
                "request_piece", "receive_piece",
                "torrent_complete"} <= names
        received = [e for e in leecher.events.events
                    if e["name"] == "receive_piece"]
        assert len(received) == mi.num_pieces
        assert all(e["info_hash"] == mi.info_hash.hex for e in received)

    asyncio.run(main())


def test_failure_meter_counts_and_throttles(caplog):
    """Every failure increments the counter; the WARN is throttled to one
    per window with a suppressed-count on the next emit."""

    from kraken_tpu.utils.metrics import FailureMeter

    log = logging.getLogger("kraken.test.meter")
    m = FailureMeter("test_meter_failures_total", "t", log,
                     throttle_seconds=3600)
    with caplog.at_level(logging.WARNING, logger="kraken.test.meter"):
        for i in range(10):
            m.record("probe", RuntimeError(f"e{i}"))
    assert m.counter.value() == 10
    warns = [r for r in caplog.records if "probe failed" in r.getMessage()]
    assert len(warns) == 1  # 9 suppressed inside the window
    m._last_warn = -float("inf")  # window elapses
    with caplog.at_level(logging.WARNING, logger="kraken.test.meter"):
        m.record("probe", RuntimeError("e10"))
    assert any(
        "9 similar suppressed" in r.getMessage() for r in caplog.records
    )


def test_announce_failures_metered_when_tracker_dies(tmp_path):
    """A dead tracker is visible: announce_failures_total moves while the
    seeding agent's announce loop retries into the void."""

    async def main():
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from test_herd import build_herd, teardown

        from kraken_tpu.core.digest import Digest
        from kraken_tpu.origin.client import BlobClient

        counter = REGISTRY.counter("announce_failures_total")
        tracker, origins, agents, cluster = await build_herd(
            tmp_path, n_agents=0
        )
        try:
            blob = os.urandom(50_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)  # origin seeds + announces
            await oc.close()
            before = counter.value()
            await tracker.stop()  # the void
            for _ in range(100):
                if counter.value() > before:
                    break
                await asyncio.sleep(0.05)
            assert counter.value() > before, "announce failures not metered"
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_debug_stacks_endpoint(tmp_path):
    """/debug/stacks (the pprof-goroutine-dump equivalent) lists thread
    stacks and live asyncio tasks on every instrumented component."""
    import aiohttp

    from kraken_tpu.assembly import TrackerNode

    async def main():
        tracker = TrackerNode()
        await tracker.start()
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                    f"http://{tracker.addr}/debug/stacks"
                ) as r:
                    assert r.status == 200
                    text = await r.text()
            assert "=== thread" in text
            assert "=== asyncio tasks:" in text
            # The serving task itself shows up with a file:line frame.
            assert ".py:" in text
        finally:
            await tracker.stop()

    asyncio.run(main())


def test_dedup_add_blob_failures_metered():
    """VERDICT r4 weak #2: a dedup plane that dies per-blob must move
    origin_dedup_failures_total, not vanish in a bare except."""
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.server import OriginServer

    class ExplodingDedup:
        async def add_blob(self, d):
            raise RuntimeError("sidecar corrupt")

    async def main():
        srv = OriginServer(store=None, generator=None, dedup=ExplodingDedup())
        before = srv._dedup_failures.counter.value()
        srv._schedule_dedup(Digest.from_bytes(b"x"))
        for _ in range(50):
            if srv._dedup_failures.counter.value() > before:
                break
            await asyncio.sleep(0.01)
        assert srv._dedup_failures.counter.value() > before

    asyncio.run(main())


def test_evict_callback_failures_metered(tmp_path):
    """Both evict callbacks (on_evict dedup removal, after_evict unseed)
    meter their failures; eviction itself still completes."""
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.store import CAStore
    from kraken_tpu.store.cleanup import CleanupManager

    store = CAStore(str(tmp_path / "s"))
    blob = b"evict me"
    d = Digest.from_bytes(blob)
    store.create_cache_file(d, iter([blob]))

    def boom(_d):
        raise RuntimeError("callback dead")

    mgr = CleanupManager(store, on_evict=boom, after_evict=boom)
    before = mgr._evict_failures.counter.value()
    mgr._evict(d)
    assert mgr._evict_failures.counter.value() == before + 2
    assert not store.in_cache(d)  # eviction completed despite callbacks


def test_jax_profile_lock_survives_client_disconnect(monkeypatch):
    """ADVICE r5: a client disconnect mid-capture cancels the handler;
    the shielded stop_trace keeps running in its thread, and the
    process-global profile lock must stay held until stop COMPLETES --
    releasing it earlier would let a second capture start_trace while
    the profiler is still serializing the first. The lock is handed to
    stop's done-callback on cancellation (utils/metrics.py)."""
    import threading

    import aiohttp
    import jax

    from kraken_tpu.assembly import TrackerNode

    started = threading.Event()
    release = threading.Event()
    stopped = threading.Event()
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda out_dir: started.set()
    )

    def slow_stop():
        release.wait(10)
        stopped.set()

    monkeypatch.setattr(jax.profiler, "stop_trace", slow_stop)

    async def main():
        tracker = TrackerNode()
        await tracker.start()
        try:
            url = f"http://{tracker.addr}/debug/jax-profile"
            # Raw socket so we can hard-close mid-capture (an impatient
            # curl): _serve runs with handler_cancellation, so the
            # disconnect cancels the handler between start and stop.
            reader, writer = await asyncio.open_connection(
                tracker.host, tracker.port
            )
            writer.write(
                b"GET /debug/jax-profile?seconds=30 HTTP/1.1\r\n"
                b"Host: x\r\n\r\n"
            )
            await writer.drain()
            assert await asyncio.to_thread(started.wait, 5), "capture never started"
            writer.close()

            async with aiohttp.ClientSession() as http:
                # stop_trace is still running (blocked on `release`): a
                # second capture must see the lock held -> 409. Poll a
                # little to let the cancellation propagate first.
                for _ in range(50):
                    async with http.get(url, params={"seconds": "0.01"}) as r:
                        status = r.status
                    assert status in (200, 409)
                    if status == 409:
                        break
                    await asyncio.sleep(0.02)
                assert status == 409, "lock was released before stop_trace finished"

                # stop completes -> lock releases -> captures work again.
                release.set()
                assert await asyncio.to_thread(stopped.wait, 5)
                for _ in range(100):
                    async with http.get(url, params={"seconds": "0.01"}) as r:
                        status = r.status
                    if status == 200:
                        break
                    await asyncio.sleep(0.02)
                assert status == 200, "lock never released after stop_trace"
        finally:
            await tracker.stop()

    asyncio.run(main())


def test_debug_jax_profile_endpoint(tmp_path):
    """/debug/jax-profile captures a jax.profiler trace (the SURVEY SS5
    tracing story for the TPU half) and answers 409 while one runs."""
    import aiohttp

    from kraken_tpu.assembly import TrackerNode

    async def main():
        tracker = TrackerNode()
        await tracker.start()
        try:
            out = str(tmp_path / "trace")
            async with aiohttp.ClientSession() as http:
                async with http.get(
                    f"http://{tracker.addr}/debug/jax-profile",
                    params={"seconds": "0.3", "dir": out},
                ) as r:
                    assert r.status == 200, await r.text()
                    body = await r.json()
            assert body["trace_dir"] == out
            # A plugins/profile/<ts>/*.xplane.pb tree appears.
            found = [
                p for p in __import__("pathlib").Path(out).rglob("*")
                if p.is_file()
            ]
            assert found, "no trace files written"
        finally:
            await tracker.stop()

    asyncio.run(main())


def test_log_storm_filter_suppresses_and_summarizes():
    """utils/structlog.StormFilter: a repeated WARN template passes
    `burst` lines per window, drops the rest (counted on /metrics),
    and the first line of the next window carries `suppressed_similar`
    -- so a flapping peer cannot drown the postmortem-relevant lines
    the SLO dumps point at."""
    from kraken_tpu.utils.structlog import StormFilter

    t = [0.0]
    filt = StormFilter(burst=3, window_seconds=60.0, clock=lambda: t[0])

    def rec(msg, *args, level=logging.WARNING, name="kraken.p2p"):
        return logging.LogRecord(name, level, __file__, 1, msg, args, None)

    # Template-keyed: 100 instances of one storm, 3 pass.
    passed = [r for r in (
        rec("announce %s failed", i) for i in range(100)
    ) if filt.filter(r)]
    assert len(passed) == 3
    # A DIFFERENT template is its own key and passes fresh.
    assert filt.filter(rec("conn %s reset", 1))
    # INFO and below are never storm-limited.
    assert all(
        filt.filter(rec("announce %s failed", i, level=logging.INFO))
        for i in range(10)
    )
    # Next window: the first record passes AND carries the summary.
    t[0] += 61
    summary = rec("announce %s failed", 101)
    assert filt.filter(summary)
    assert summary.suppressed_similar == 97
    # The summary serializes into the JSON line (the formatter emits
    # every non-reserved attribute).
    line = json.loads(JSONFormatter("agent").format(summary))
    assert line["suppressed_similar"] == 97
    # A second record in the new window has no summary to carry.
    follow = rec("announce %s failed", 102)
    assert filt.filter(follow)
    assert not hasattr(follow, "suppressed_similar")
    # Suppressions are visible on /metrics even while muted.
    assert REGISTRY.counter("log_suppressed_total").value() >= 97


def test_log_storm_filter_is_wired_into_setup(monkeypatch):
    """setup_json_logging installs the storm filter on its handler --
    the production path, not just the class."""
    from kraken_tpu.utils.structlog import StormFilter, setup_json_logging

    root = logging.getLogger()
    handlers0, level0 = root.handlers[:], root.level
    try:
        setup_json_logging("agent")
        assert any(
            isinstance(f, StormFilter)
            for h in root.handlers for f in h.filters
        )
    finally:
        root.handlers, root.level = handlers0, level0

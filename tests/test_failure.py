"""Failure plane end-to-end: origin death -> ring shrink -> re-replicate
-> pulls still succeed; revival -> ring re-grow -> refill.

VERDICT r2 missing #2: health monitors and Ring.on_change existed but
nothing subscribed. Now each origin probes its ring peers, refreshes its
ring, and repairs (re-replicates affected blobs) on every membership
change; the tracker's cluster client drops failing origins via its
passive filter.
"""

import asyncio
import os

from kraken_tpu.assembly import OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.placement.healthcheck import PassiveFilter


async def _wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        out = cond()
        if asyncio.iscoroutine(out):
            out = await out
        if out:
            return
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(interval)


def _origin(tmp_path, name, addrs, http_port=0, p2p_port=0):
    """An origin with its OWN ring view over the fixed cluster addrs (as in
    production: every origin monitors the cluster independently)."""
    return OriginNode(
        store_root=str(tmp_path / name),
        http_port=http_port,
        p2p_port=p2p_port,
        ring=Ring(HostList(static=addrs), max_replica=2),
        self_addr=addrs_by_name(addrs, name),
        dedup=False,
        health_interval_seconds=0.2,
        health_fail_threshold=2,
    )


def addrs_by_name(addrs, name):
    return addrs[int(name[-1])]


def test_origin_death_rereplicates_and_revival_refills(tmp_path):
    asyncio.run(_drive_failure(tmp_path))


async def _drive_failure(tmp_path):
    # Fixed ports so a revived origin comes back at the same address.
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in ports]

    tracker = TrackerNode(
        announce_interval_seconds=0.1,
        peer_ttl_seconds=5.0,
        ring_refresh_seconds=0.2,
    )
    await tracker.start()
    nodes = {}
    for i in range(3):
        n = _origin(tmp_path, f"origin{i}", addrs, http_port=ports[i])
        n.tracker_addr = tracker.addr
        await n.start()
        nodes[i] = n
    health = PassiveFilter(fail_threshold=1, cooldown_seconds=1.0)
    from kraken_tpu.utils.httputil import HTTPClient

    cluster = ClusterClient(
        Ring(HostList(static=addrs), max_replica=2, health_filter=health.filter),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
        health=health,
    )
    tracker.server.origin_cluster = cluster

    # Every origin's independent ring must converge on full membership
    # before the upload, or placement below races the health monitors.
    await _wait_for(
        lambda: all(len(nodes[i].ring.members) == 3 for i in range(3)),
        msg="origin rings to converge on full membership",
    )

    blob = os.urandom(400_000)
    d = Digest.from_bytes(blob)
    owners = cluster.ring.locations(d)
    assert len(owners) == 2
    owner_idx = [addrs.index(a) for a in owners]
    spare_idx = next(i for i in range(3) if i not in owner_idx)

    try:
        # Upload to one owner; replication fans to the other.
        oc = BlobClient(owners[0])
        await oc.upload("ns", d, blob)
        await oc.close()
        await _wait_for(
            lambda: all(nodes[i].store.in_cache(d) for i in owner_idx),
            msg="initial replication to both owners",
        )
        assert not nodes[spare_idx].store.in_cache(d)

        # Kill one owner. Survivors' monitors must drop it, rings shrink,
        # and repair must re-replicate the blob onto the spare origin.
        dead = owner_idx[0]
        await nodes[dead].stop()
        await _wait_for(
            lambda: addrs[dead] not in nodes[spare_idx].ring.members,
            msg="survivor ring to drop the dead origin",
        )
        await _wait_for(
            lambda: nodes[spare_idx].store.in_cache(d),
            msg="re-replication onto the spare origin",
        )

        # Reads through the (passively health-filtered) cluster still work.
        got = await cluster.download("ns", d)
        assert got == blob

        # Revive the dead origin at the same address: rings re-grow and
        # repair refills it with the blobs it owns.
        revived = _origin(
            tmp_path / "revived", f"origin{dead}", addrs, http_port=ports[dead]
        )
        revived.tracker_addr = tracker.addr
        await revived.start()
        nodes[dead] = revived
        await _wait_for(
            lambda: addrs[dead] in nodes[spare_idx].ring.members,
            msg="survivor ring to re-admit the revived origin",
        )
        await _wait_for(
            lambda: revived.store.in_cache(d),
            msg="repair to refill the revived origin",
        )
    finally:
        for n in nodes.values():
            await n.stop()
        await cluster.close()
        await tracker.stop()

"""Chunk-level delta transfer: recipes, planning, and the pull path.

Tiers here:

- property tests: ChunkRecipe serialize/deserialize roundtrip and
  recipe-diff correctness (have/need spans exactly tile the blob -- no
  overlap, no gap) under a randomized corpus;
- surface tests: the origin /recipe endpoint (gated, hit-vs-recompute
  accounting) and the tracker proxy (X-Kraken-Origin stamp);
- the tier-1 byte-moved BAND: a build-over-build pull with delta on must
  move <= ``BAND_MAX`` of the blob's bytes while the delta-off control
  moves ~all of them -- a planner regression that silently re-fetches
  everything fails here, not in production dashboards;
- chaos tier: corrupt local base -> fp re-verify rejects the span ->
  clean fallback, bit-identical; recipe-miss and evicted-base paths via
  failpoints.

Every e2e herd uses 16 KiB pieces and 256/1024/4096 CDC params so a
~400 KB blob exercises multi-piece, multi-chunk planning in milliseconds.
"""

import asyncio
import os

import numpy as np
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, MetaInfoError, chunk_fp
from kraken_tpu.ops.cdc import CDCParams
from kraken_tpu.p2p.delta import DeltaConfig, HaveSpan, diff_recipes
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.metrics import REGISTRY

PARAMS = CDCParams(min_size=256, avg_size=1024, max_size=4096)
NS = "library/delta"
BAND_MAX = 0.6  # acceptance bar: delta-on moves <= 0.6x of delta-off

_D = Digest.from_bytes(b"recipe-test")


@pytest.fixture(autouse=True)
def chaos_plane():
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    yield failpoints.FAILPOINTS
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow(False)


# -- properties: recipe format + diff ------------------------------------


def _random_recipe(rng, digest=_D, n=None) -> ChunkRecipe:
    n = int(rng.integers(0, 64)) if n is None else n
    fps = rng.integers(0, 1 << 63, size=n, dtype=np.uint64).tolist()
    sizes = rng.integers(1, 1 << 20, size=n, dtype=np.uint32).tolist()
    return ChunkRecipe(digest, fps, sizes)


def test_chunk_recipe_roundtrip_property():
    rng = np.random.default_rng(11)
    for _ in range(50):
        r = _random_recipe(rng)
        back = ChunkRecipe.deserialize(r.serialize())
        assert back == r
        assert back.length == r.length
        assert list(back.chunks()) == list(r.chunks())
    # Offsets are cumulative and tile [0, length).
    r = _random_recipe(rng, n=17)
    pos = 0
    for _fp, off, size in r.chunks():
        assert off == pos
        pos += size
    assert pos == r.length


def test_chunk_recipe_malformed():
    good = _random_recipe(np.random.default_rng(1), n=3).serialize()
    with pytest.raises(MetaInfoError):
        ChunkRecipe.deserialize(b"not json")
    with pytest.raises(MetaInfoError):
        ChunkRecipe.deserialize(b'{"version":2}')
    with pytest.raises(MetaInfoError):
        ChunkRecipe.deserialize(b'[1,2,3]')
    import json

    doc = json.loads(good)
    doc["length"] += 1  # sizes no longer sum to the declared length
    with pytest.raises(MetaInfoError):
        ChunkRecipe.deserialize(json.dumps(doc).encode())
    doc = json.loads(good)
    doc["fps"] = doc["fps"][:-2]  # misaligned table
    with pytest.raises(MetaInfoError):
        ChunkRecipe.deserialize(json.dumps(doc).encode())
    with pytest.raises(MetaInfoError):
        ChunkRecipe(_D, [1, 2], [10])  # length mismatch
    with pytest.raises(MetaInfoError):
        ChunkRecipe(_D, [1], [0])  # zero-size chunk


def test_diff_recipes_tiling_property():
    """have + need spans must tile the target exactly, for any pair of
    recipes drawn from a shared chunk pool (the randomized corpus)."""
    rng = np.random.default_rng(5)
    pool_fps = rng.integers(0, 1 << 63, size=40, dtype=np.uint64)
    pool_sizes = rng.integers(1, 8192, size=40, dtype=np.uint32)
    for _trial in range(30):
        def draw(k):
            idx = rng.integers(0, 40, size=k)
            return (
                [int(pool_fps[i]) for i in idx],
                [int(pool_sizes[i]) for i in idx],
            )
        t_fps, t_sizes = draw(int(rng.integers(1, 30)))
        b_fps, b_sizes = draw(int(rng.integers(0, 30)))
        target = ChunkRecipe(_D, t_fps, t_sizes)
        base = ChunkRecipe(_D, b_fps, b_sizes)
        haves, needs = diff_recipes(target, base)
        spans = sorted(
            [(h.target_off, h.size) for h in haves] + list(needs)
        )
        pos = 0
        for off, size in spans:
            assert off == pos, "overlap or gap in the partition"
            pos += size
        assert pos == target.length
        base_keys = {
            (fp, size) for fp, _off, size in base.chunks()
        }
        for h in haves:
            assert (h.fp, h.size) in base_keys
            # The base offset really points at a chunk of that (fp, size).
            assert 0 <= h.base_off <= base.length - h.size


def test_diff_recipes_merges_adjacent_needs():
    target = ChunkRecipe(_D, [1, 2, 3, 4], [10, 20, 30, 40])
    base = ChunkRecipe(_D, [1, 4], [10, 40])
    haves, needs = diff_recipes(target, base)
    assert [(h.target_off, h.size, h.base_off) for h in haves] == [
        (0, 10, 0), (60, 40, 10),
    ]
    assert needs == [(10, 50)]  # the two middle chunks merged


def test_delta_config_from_dict():
    cfg = DeltaConfig.from_dict({"enabled": True, "max_bases": 5})
    assert cfg.enabled and cfg.max_bases == 5
    assert DeltaConfig.from_dict(None).enabled is False  # shipped default
    with pytest.raises(ValueError):
        DeltaConfig.from_dict({"enabld": True})


# -- e2e herd harness -----------------------------------------------------


def _make_build_pair(rng, n_files=24, file_kb=16, reuse=0.8):
    """Two consecutive 'image builds': tar-like streams of (64 B unique
    header + file body) where build 2 reuses ``reuse`` of build 1's files
    in shuffled order -- shared content at SHIFTED offsets, the case that
    defeats identity dedup and that CDC recipes are for."""
    files = [
        rng.integers(0, 256, size=file_kb * 1024, dtype=np.uint8).tobytes()
        for _ in range(2 * n_files)
    ]

    def layer(members):
        parts = []
        for fi in members:
            parts.append(rng.integers(0, 256, size=64, dtype=np.uint8).tobytes())
            parts.append(files[fi])
        return b"".join(parts)

    m1 = list(range(n_files))
    n_keep = int(n_files * reuse)
    m2 = m1[:n_keep] + list(range(n_files, 2 * n_files - n_keep))
    rng.shuffle(m2)
    return layer(m1), layer(m2)


class _Herd:
    """tracker + origin (+ cluster wiring) + agent, delta-capable."""

    def __init__(self, tmp_path, agent_delta=None, origin_delta=None):
        self.tmp = tmp_path
        self.agent_delta = agent_delta
        self.origin_delta = origin_delta

    async def __aenter__(self):
        from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
        from kraken_tpu.origin.client import ClusterClient
        from kraken_tpu.origin.dedup import DedupIndex
        from kraken_tpu.origin.metainfogen import PieceLengthConfig
        from kraken_tpu.placement import HostList, Ring

        self.tracker = TrackerNode(announce_interval_seconds=0.1)
        await self.tracker.start()
        self.origin = OriginNode(
            store_root=str(self.tmp / "origin"),
            tracker_addr=self.tracker.addr,
            piece_lengths=PieceLengthConfig(table=((0, 16384),)),
            delta=self.origin_delta,
        )
        # Small CDC params so ~400 KB blobs carry hundreds of chunks.
        self.origin.dedup = DedupIndex(self.origin.store, params=PARAMS)
        await self.origin.start()
        ring = Ring(HostList(static=[self.origin.addr]), max_replica=2)
        self.cluster = ClusterClient(ring)
        self.tracker.server.origin_cluster = self.cluster
        self.agent = AgentNode(
            store_root=str(self.tmp / "agent"),
            tracker_addr=self.tracker.addr,
            delta=self.agent_delta,
        )
        await self.agent.start()
        from kraken_tpu.utils.httputil import HTTPClient
        from kraken_tpu.origin.client import BlobClient

        self.http = HTTPClient()
        self.oc = BlobClient(self.origin.addr)
        return self

    async def __aexit__(self, *exc):
        await self.http.close()
        await self.oc.close()
        await self.agent.stop()
        await self.origin.stop()
        await self.cluster.close()
        await self.tracker.stop()

    async def upload(self, blob: bytes) -> Digest:
        d = Digest.from_bytes(blob)
        await self.oc.upload(NS, d, blob)
        return d

    async def pull(self, d: Digest) -> tuple[bytes, int]:
        """Pull through the agent; returns (bytes, bytes_moved) where
        moved = swarm piece ingress + delta range fetches during the
        pull (REGISTRY deltas -- the registry is process-global)."""
        down = REGISTRY.counter("p2p_piece_bytes_down_total")
        fetched = REGISTRY.counter("delta_bytes_fetched_total")
        d0, f0 = down.value(), fetched.value()
        from urllib.parse import quote

        body = await self.http.get(
            f"http://{self.agent.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}"
        )
        moved = (down.value() - d0) + (fetched.value() - f0)
        return body, int(moved)


DELTA_ON = {"enabled": True, "min_blob_bytes": 1}


def test_delta_pull_band(tmp_path):
    """THE acceptance band (tier-1): on the build-over-build corpus a
    delta-on pull moves <= 0.6x the bytes of the delta-off control, the
    result is bit-identical, and local copies actually happened. A
    planner regression that silently re-fetches everything fails here."""
    asyncio.run(_delta_pull_band(tmp_path))


async def _delta_pull_band(tmp_path):
    rng = np.random.default_rng(7)
    v1, v2 = _make_build_pair(rng)
    copied = REGISTRY.counter("delta_bytes_copied_local_total")
    async with _Herd(
        tmp_path / "on", agent_delta=DELTA_ON, origin_delta={"enabled": True}
    ) as herd:
        d1 = await herd.upload(v1)
        got1, moved1 = await herd.pull(d1)
        assert got1 == v1
        # First pull: nothing cached locally -> full fetch.
        assert moved1 >= len(v1)
        d2 = await herd.upload(v2)
        c0 = copied.value()
        got2, moved2 = await herd.pull(d2)
        assert got2 == v2, "delta-assembled blob must be bit-identical"
        on_ratio = moved2 / len(v2)
        assert copied.value() > c0, "no local copies happened"
    async with _Herd(tmp_path / "off") as herd:  # shipped defaults: off
        d1 = await herd.upload(v1)
        await herd.pull(d1)
        d2 = await herd.upload(v2)
        got2, moved_off = await herd.pull(d2)
        assert got2 == v2
        off_ratio = moved_off / len(v2)
    assert off_ratio >= 0.95, f"control pull should move ~all bytes: {off_ratio}"
    assert on_ratio <= BAND_MAX * off_ratio, (
        f"delta-on moved {on_ratio:.3f}x vs control {off_ratio:.3f}x -- "
        f"planner regression (band: <= {BAND_MAX}x of control)"
    )


def test_delta_live_reload_enables(tmp_path):
    """Shipped-off nodes enable delta via reload() (the SIGHUP path) --
    rollout is a config refresh, not a restart: origin first (recipe
    endpoint goes 404 -> 200), then the agent planner."""
    asyncio.run(_delta_live_reload(tmp_path))


async def _delta_live_reload(tmp_path):
    rng = np.random.default_rng(8)
    v1, v2 = _make_build_pair(rng)
    async with _Herd(tmp_path) as herd:  # both sides shipped-off
        d1 = await herd.upload(v1)
        # Recipe endpoint is dark while disabled.
        from kraken_tpu.utils.httputil import HTTPError
        from urllib.parse import quote

        url = (
            f"http://{herd.origin.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d1.hex}/recipe"
        )
        with pytest.raises(HTTPError) as ei:
            await herd.http.get(url, retry_5xx=False)
        assert ei.value.status == 404
        herd.origin.reload({"delta": {"enabled": True}})
        raw = await herd.http.get(url, retry_5xx=False)
        recipe = ChunkRecipe.deserialize(raw)
        assert recipe.length == len(v1)
        assert recipe.digest.hex == d1.hex
        # Agent side: planner live-enables too.
        herd.agent.reload({"delta": DELTA_ON})
        assert herd.agent.delta.config.enabled
        await herd.pull(d1)
        d2 = await herd.upload(v2)
        got2, moved2 = await herd.pull(d2)
        assert got2 == v2
        assert moved2 < len(v2), "post-reload pull should have delta'd"


def test_origin_recipe_endpoint_accounting(tmp_path):
    """Recipe requests are counted hit vs recompute; the recipe's chunks
    tile the blob and fingerprint-match its bytes; the tracker proxy
    stamps the serving origin."""
    asyncio.run(_origin_recipe_endpoint(tmp_path))


async def _origin_recipe_endpoint(tmp_path):
    rng = np.random.default_rng(9)
    v1, _ = _make_build_pair(rng, n_files=6)
    served = REGISTRY.counter("origin_recipe_requests_total")
    async with _Herd(
        tmp_path, origin_delta={"enabled": True}
    ) as herd:
        d = await herd.upload(v1)
        # Commit-time dedup indexing is async; the sidecar may not exist
        # yet -- the first recipe request derives it (recompute), the
        # second hits the sidecar.
        from urllib.parse import quote

        url = (
            f"http://{herd.origin.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}/recipe"
        )
        h0, r0 = served.value(result="hit"), served.value(result="recompute")
        raw = await herd.http.get(url, retry_5xx=False)
        recipe = ChunkRecipe.deserialize(raw)
        assert recipe.length == len(v1)
        # Every chunk's fp matches the actual bytes (the agent-side
        # re-verify contract).
        for fp, off, size in recipe.chunks():
            assert chunk_fp(v1[off : off + size]) == fp
        raw2 = await herd.http.get(url, retry_5xx=False)
        assert raw2 == raw
        assert served.value(result="hit") + served.value(
            result="recompute"
        ) == h0 + r0 + 2
        assert served.value(result="hit") >= h0 + 1  # second hit the sidecar
        # Tracker proxy: same body, origin addr stamped.
        _status, headers, body = await herd.http.request_full(
            "GET",
            f"http://{herd.tracker.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}/recipe",
            retry_5xx=False,
        )
        assert body == raw
        assert headers.get("X-Kraken-Origin") == herd.origin.addr
        # Tracker /similar proxy answers too (self never listed).
        import json

        sim = json.loads(await herd.http.get(
            f"http://{herd.tracker.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}/similar",
            retry_5xx=False,
        ))
        assert "similar" in sim


# -- chaos tier -----------------------------------------------------------


def test_delta_corrupt_base_falls_back_bit_identical(tmp_path):
    """A corrupt local delta base: the fp re-verify rejects the damaged
    chunk's span, those pieces ride the swarm, and the result is STILL
    bit-identical -- delta is an optimization, never a trust change."""
    asyncio.run(_delta_corrupt_base(tmp_path))


async def _delta_corrupt_base(tmp_path):
    rng = np.random.default_rng(10)
    v1, v2 = _make_build_pair(rng)
    rejects = REGISTRY.counter("delta_chunk_verify_failures_total")
    async with _Herd(
        tmp_path, agent_delta=DELTA_ON, origin_delta={"enabled": True}
    ) as herd:
        d1 = await herd.upload(v1)
        got1, _ = await herd.pull(d1)
        assert got1 == v1
        # Flip bytes INSIDE the agent's cached copy of the base -- at-rest
        # corruption the recipe knows nothing about. Scattered every
        # 24 KiB so shared (have) chunks are guaranteed to be hit, not
        # just the per-build unique headers.
        path = herd.agent.store.cache_path(d1)
        with await asyncio.to_thread(open, path, "r+b") as f:
            for off in range(8192, len(v1), 24576):
                f.seek(off)
                f.write(b"\xde\xad\xbe\xef")
        r0 = rejects.value()
        d2 = await herd.upload(v2)
        got2, _moved = await herd.pull(d2)
        assert got2 == v2, "corrupt base must never reach the blob"
        assert rejects.value() > r0, "fp re-verify never fired"


def test_delta_recipe_miss_full_pull(tmp_path):
    """Failpoint origin.recipe.miss: the recipe plane goes dark -- the
    pull degrades to a full fetch, counted on delta_recipe_misses_total,
    and still completes bit-identically."""
    asyncio.run(_delta_recipe_miss(tmp_path))


async def _delta_recipe_miss(tmp_path):
    rng = np.random.default_rng(12)
    v1, v2 = _make_build_pair(rng, n_files=8)
    misses = REGISTRY.counter("delta_recipe_misses_total")
    pulls = REGISTRY.counter("delta_pulls_total")
    async with _Herd(
        tmp_path, agent_delta=DELTA_ON, origin_delta={"enabled": True}
    ) as herd:
        d1 = await herd.upload(v1)
        await herd.pull(d1)
        failpoints.FAILPOINTS.arm("origin.recipe.miss", "always")
        m0 = misses.value(side="target")
        p0 = pulls.value(outcome="recipe_miss")
        d2 = await herd.upload(v2)
        got2, moved2 = await herd.pull(d2)
        assert got2 == v2
        assert moved2 >= len(v2)  # nothing was delta'd
        assert misses.value(side="target") == m0 + 1
        assert pulls.value(outcome="recipe_miss") == p0 + 1


def test_delta_base_evicted_mid_plan_falls_back(tmp_path):
    """Failpoint p2p.delta.base.evict: /similar handed a base the cache
    evicted between plan and copy -- the planner degrades to the full
    swarm pull cleanly (no crash, no partial trust), bit-identical."""
    asyncio.run(_delta_base_evicted(tmp_path))


async def _delta_base_evicted(tmp_path):
    rng = np.random.default_rng(13)
    v1, v2 = _make_build_pair(rng, n_files=8)
    pulls = REGISTRY.counter("delta_pulls_total")
    copied = REGISTRY.counter("delta_bytes_copied_local_total")
    async with _Herd(
        tmp_path, agent_delta=DELTA_ON, origin_delta={"enabled": True}
    ) as herd:
        d1 = await herd.upload(v1)
        await herd.pull(d1)
        failpoints.FAILPOINTS.arm("p2p.delta.base.evict", "once")
        n0 = pulls.value(outcome="no_cover")
        c0 = copied.value()
        d2 = await herd.upload(v2)
        got2, moved2 = await herd.pull(d2)
        assert got2 == v2
        assert moved2 >= len(v2)  # the whole blob came over the wire
        assert copied.value() == c0, "copied from an evicted base?"
        assert pulls.value(outcome="no_cover") == n0 + 1
        assert not herd.agent.store.in_cache(d1)  # base really evicted


def test_copy_piece_holes_and_fp_reject(tmp_path):
    """Unit: _copy_piece fills exactly the covered intervals, reports the
    complement as holes, and rejects a chunk whose bytes don't hash to
    the recipe fp."""
    from kraken_tpu.p2p.delta import DeltaPlanner

    from kraken_tpu.store.chunkstore import FlatReader

    base = bytes(np.random.default_rng(3).integers(0, 256, 8192, np.uint8))
    path = tmp_path / "base"
    path.write_bytes(base)
    raw_fd = os.open(str(path), os.O_RDONLY)
    fd = [FlatReader(raw_fd, len(base))]  # the per-base reader list
    try:
        planner = DeltaPlanner.__new__(DeltaPlanner)  # only _copy_piece
        planner._chunk_rejects = REGISTRY.counter(
            "delta_chunk_verify_failures_total"
        )
        # Piece [0, 4096); two verified chunks cover [100,1100)+[2000,2500).
        spans = [
            HaveSpan(100, 1000, 0, chunk_fp(base[0:1000])),
            HaveSpan(2000, 500, 4000, chunk_fp(base[4000:4500])),
        ]
        out = planner._copy_piece(fd, 0, 4096, spans, {})
        assert out is not None
        buf, holes, copied_n = out
        assert copied_n == 1500
        assert bytes(buf[100:1100]) == base[0:1000]
        assert bytes(buf[2000:2500]) == base[4000:4500]
        assert holes == [(0, 100), (1100, 900), (2500, 1596)]
        # A chunk that straddles the piece end: only the overlap copies,
        # but the WHOLE chunk is fp-verified -- and the verdict is
        # cached, so the NEXT piece reads just its overlap and the
        # copied bytes still match.
        straddle = HaveSpan(3900, 1000, 500, chunk_fp(base[500:1500]))
        verified = {}
        buf, holes, copied_n = planner._copy_piece(
            fd, 0, 4096, [straddle], verified
        )
        assert copied_n == 196
        assert bytes(buf[3900:4096]) == base[500:696]
        assert verified == {straddle: True}
        buf2, _holes2, copied2 = planner._copy_piece(
            fd, 4096, 4096, [straddle], verified
        )
        assert copied2 == 1000 - 196
        assert bytes(buf2[0 : 1000 - 196]) == base[696:1500]
        # Wrong fp -> None (reject), nothing trusted -- and counted ONCE
        # across every piece the corrupt chunk covers.
        rejects = planner._chunk_rejects
        r0 = rejects.value()
        bad = HaveSpan(3900, 1000, 0, 12345)
        verified = {}
        assert planner._copy_piece(fd, 0, 4096, [bad], verified) is None
        assert planner._copy_piece(fd, 4096, 4096, [bad], verified) is None
        assert verified == {bad: False}
        assert rejects.value() == r0 + 1
    finally:
        os.close(raw_fd)

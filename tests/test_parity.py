"""Parity nibbles: Redis-protocol peerstore, DNS hostlist, TLS listener,
bounded dedup index (VERDICT r2 next #10 + weak #6/#7).
"""

import asyncio
import os
import ssl
import subprocess
import time

import numpy as np
import pytest

from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.placement.hostlist import HostList
from kraken_tpu.tracker.peerstore import RedisPeerStore


# -- fake Redis (RESP server; HSET/EXPIRE/HGETALL/HDEL surface) --------------


class FakeRedis:
    """In-memory RESP server covering what RedisPeerStore uses (HSET /
    EXPIRE / HGETALL / HDEL). Verifies the client's protocol encoding
    byte-for-byte by parsing it for real."""

    __test__ = False

    def __init__(self):
        self.hashes: dict[bytes, dict[bytes, bytes]] = {}
        self.expiry: dict[bytes, float] = {}  # key -> absolute deadline
        self.addr = ""
        self._server = None

    async def _handle(self, reader, writer):
        try:
            while True:
                line = (await reader.readline()).rstrip(b"\r\n")
                if not line:
                    return
                assert line[:1] == b"*", f"expected array, got {line!r}"
                args = []
                for _ in range(int(line[1:])):
                    lenline = (await reader.readline()).rstrip(b"\r\n")
                    assert lenline[:1] == b"$"
                    n = int(lenline[1:])
                    args.append((await reader.readexactly(n + 2))[:-2])
                reply = self._dispatch(args)
                writer.write(reply)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        finally:
            # 3.12's Server.wait_closed() waits for every handler's
            # transport to close; an unclosed writer hangs teardown.
            writer.close()

    def _live(self, key: bytes, now: float) -> dict[bytes, bytes] | None:
        if self.expiry.get(key, float("inf")) <= now:
            self.hashes.pop(key, None)
            self.expiry.pop(key, None)
            return None
        return self.hashes.get(key)

    def _dispatch(self, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        now = time.monotonic()
        if cmd == b"HSET":
            key, field, val = args[1], args[2], args[3]
            h = self._live(key, now)
            if h is None:
                h = self.hashes.setdefault(key, {})
                self.expiry.pop(key, None)
            created = 0 if field in h else 1
            h[field] = val
            return b":%d\r\n" % created
        if cmd == b"EXPIRE":
            key, ttl = args[1], int(args[2])
            if self._live(key, now) is None:
                return b":0\r\n"
            self.expiry[key] = now + ttl
            return b":1\r\n"
        if cmd == b"HGETALL":
            h = self._live(args[1], now) or {}
            out = b"*%d\r\n" % (2 * len(h))
            for f, v in h.items():
                out += b"$%d\r\n%s\r\n" % (len(f), f)
                out += b"$%d\r\n%s\r\n" % (len(v), v)
            return out
        if cmd == b"HDEL":
            h = self._live(args[1], now) or {}
            removed = 0
            for f in args[2:]:
                if h.pop(f, None) is not None:
                    removed += 1
            return b":%d\r\n" % removed
        return b"-ERR unknown command\r\n"

    async def __aenter__(self):
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", 0)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"127.0.0.1:{port}"
        return self

    async def __aexit__(self, *exc):
        self._server.close()
        await self._server.wait_closed()


def _peer(i: int, complete=False) -> PeerInfo:
    return PeerInfo(
        peer_id=PeerID(bytes([i]).hex() * 20), ip="10.0.0.%d" % i,
        port=7000 + i, complete=complete,
    )


def test_redis_peerstore_roundtrip_and_ttl():
    async def main():
        async with FakeRedis() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=1)
            await store.update("hash1", _peer(1))
            await store.update("hash1", _peer(2, complete=True))
            await store.update("hash2", _peer(3))

            got = await store.get_peers("hash1")
            assert {p.ip for p in got} == {"10.0.0.1", "10.0.0.2"}
            assert any(p.complete for p in got)
            assert len(await store.get_peers("hash2")) == 1
            assert await store.get_peers("nope") == []

            # TTL: rewrite each record's embedded expiry into the past --
            # the read path must treat those peers as gone (and reap them).
            import json as _json

            for key, h in srv.hashes.items():
                for f, v in list(h.items()):
                    doc = _json.loads(v)
                    doc["_expiry"] = 0
                    h[f] = _json.dumps(doc).encode()
            assert await store.get_peers("hash1") == []
            assert srv.hashes[b"swarm:hash1"] == {}  # lazily reaped
            await store.close()

    asyncio.run(main())


def test_redis_peerstore_glob_metachars_stay_literal():
    """Info hashes are opaque strings: ones containing glob/driver
    metacharacters address exactly their own swarm hash key."""
    async def main():
        async with FakeRedis() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30)
            await store.update("a*", _peer(1))
            await store.update("aZ", _peer(2))
            got = await store.get_peers("a*")
            assert [p.ip for p in got] == ["10.0.0.1"]
            assert len(await store.get_peers("aZ")) == 1
            await store.close()

    asyncio.run(main())


def test_redis_peerstore_survives_server_restart():
    async def main():
        async with FakeRedis() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30)
            await store.update("h", _peer(1))
            # Kill the conn under the client; next call must reconnect.
            store._conn.close()
            got = await store.get_peers("h")
            assert len(got) == 1
            await store.close()

    asyncio.run(main())


def test_tracker_uses_redis_store(tmp_path):
    """End to end: a TrackerNode backed by the Redis-protocol store hands
    out peers recorded by other announcers."""
    from aiohttp import ClientSession

    from kraken_tpu.assembly import TrackerNode

    async def main():
        async with FakeRedis() as srv:
            tracker = TrackerNode(redis_addr=srv.addr)
            await tracker.start()
            try:
                async with ClientSession() as http:
                    async def announce(peer):
                        async with http.post(
                            f"http://{tracker.addr}/announce",
                            json={"info_hash": "abc",
                                  "peer": peer.to_dict()},
                        ) as r:
                            assert r.status == 200
                            return (await r.json())["peers"]

                    assert await announce(_peer(1)) == []
                    got = await announce(_peer(2))
                    assert [p["ip"] for p in got] == ["10.0.0.1"]
            finally:
                await tracker.stop()

    asyncio.run(main())


# -- DNS hostlist ------------------------------------------------------------


def test_hostlist_from_dns(monkeypatch):
    import socket as socket_mod

    answers = [[("10.0.0.1",), ("10.0.0.2",)]]

    def fake_getaddrinfo(name, port, family=0, proto=0):
        assert name == "origins.internal" and port == 8080
        assert family == socket_mod.AF_INET
        if answers[0] is None:
            raise OSError("dns down")
        return [
            (socket_mod.AF_INET, socket_mod.SOCK_STREAM, 6, "", (a[0], port))
            for a in answers[0]
        ]

    monkeypatch.setattr(
        "kraken_tpu.placement.hostlist.socket.getaddrinfo", fake_getaddrinfo
    )
    hl = HostList.from_dns("origins.internal:8080")
    assert hl.resolve() == ["10.0.0.1:8080", "10.0.0.2:8080"]

    answers[0] = [("10.0.0.2",), ("10.0.0.3",)]
    assert hl.resolve() == ["10.0.0.2:8080", "10.0.0.3:8080"]

    # DNS blip: last good answer survives (no mass re-replication).
    answers[0] = None
    assert hl.resolve() == ["10.0.0.2:8080", "10.0.0.3:8080"]

    # TLS-fronted clusters resolve with an https scheme prefix.
    answers[0] = [("10.0.0.9",)]
    hl_tls = HostList.from_dns("origins.internal:8080", scheme="https")
    assert hl_tls.resolve() == ["https://10.0.0.9:8080"]

    with pytest.raises(ValueError):
        HostList.from_dns("no-port")


# -- TLS listener ------------------------------------------------------------


def test_origin_tls_listener(tmp_path):
    from kraken_tpu.assembly import OriginNode

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        check=True, capture_output=True,
    )
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(str(cert), str(key))

    async def main():
        from aiohttp import ClientSession, TCPConnector

        node = OriginNode(
            store_root=str(tmp_path / "o"), dedup=False,
            ssl_context=server_ctx,
        )
        await node.start()
        try:
            client_ctx = ssl.create_default_context(cafile=str(cert))
            client_ctx.check_hostname = False
            async with ClientSession(
                connector=TCPConnector(ssl=client_ctx)
            ) as http:
                async with http.get(f"https://{node.addr}/health") as r:
                    assert r.status == 200
                    assert await r.text() == "ok"
        finally:
            await node.stop()

    asyncio.run(main())


def test_intra_cluster_tls_via_https_addr(tmp_path):
    """Internal clients reach TLS-fronted components when the configured
    address carries an https:// prefix (base_url) and the HTTPClient is
    given the cluster CA."""
    from kraken_tpu.assembly import TrackerNode
    from kraken_tpu.tracker.client import TrackerClient
    from kraken_tpu.utils.httputil import HTTPClient

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(str(cert), str(key))

    async def main():
        tracker = TrackerNode(ssl_context=server_ctx)
        await tracker.start()
        try:
            client_ctx = ssl.create_default_context(cafile=str(cert))
            client = TrackerClient(
                f"https://{tracker.addr}",
                peer_id=_peer(1).peer_id,
                ip="127.0.0.1", port=7001,
                http=HTTPClient(ssl=client_ctx),
            )
            from kraken_tpu.core.metainfo import InfoHash

            peers, interval = await client.announce(
                None, InfoHash("ab" * 32), "ns", complete=False
            )
            assert peers == [] and interval > 0
            await client.close()
        finally:
            await tracker.stop()

    asyncio.run(main())


# -- bounded dedup index -----------------------------------------------------


def test_dedup_index_bounded(tmp_path):
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.ops.cdc import CDCParams
    from kraken_tpu.origin.dedup import DedupIndex
    from kraken_tpu.store import CAStore

    rng = np.random.default_rng(0)
    store = CAStore(str(tmp_path))
    index = DedupIndex(
        store, params=CDCParams(min_size=256, avg_size=1024, max_size=4096),
        max_blobs=5,
    )
    digests = []
    for i in range(12):
        blob = rng.integers(0, 256, size=8192, dtype=np.uint8).tobytes()
        d = Digest.from_bytes(blob)
        uid = store.create_upload()
        store.write_upload_chunk(uid, 0, blob)
        store.commit_upload(uid, d)
        index.add_blob_sync(d)
        digests.append(d)

    assert index.stats()["blobs"] == 5  # bounded, oldest evicted
    assert digests[0].hex not in index._indexed
    assert digests[-1].hex in index._indexed
    # Evicted blobs re-admit from their persisted sidecar on next touch.
    index.add_blob_sync(digests[0])
    assert digests[0].hex in index._indexed
    assert index.stats()["blobs"] == 5


def test_redis_peerstore_survives_protocol_garbage():
    """A reply the client cannot parse must invalidate the connection
    (stream position unknowable) and recover on the next command -- never
    leave a desynced stream that shifts every later reply."""
    async def main():
        class GarbageOnce(FakeRedis):
            def __init__(self):
                super().__init__()
                self.garbage_next = False

            def _dispatch(self, args):
                if self.garbage_next:
                    self.garbage_next = False
                    return b"\xff\xfe not resp at all\r\n"
                return super()._dispatch(args)

        async with GarbageOnce() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30, timeout_seconds=2)
            try:
                await store.update("h", _peer(1))
                srv.garbage_next = True
                # First attempt hits the garbage reply -> conn invalidated
                # -> retry reconnects onto a clean stream and succeeds.
                got = await store.get_peers("h")
                assert [p.ip for p in got] == ["10.0.0.1"]
                # And the store keeps working on a clean stream.
                await store.update("h", _peer(2))
                assert len(await store.get_peers("h")) == 2
            finally:
                await store.close()

    asyncio.run(main())


def test_redis_peerstore_reconnects_when_socket_dies_mid_get_peers():
    """Kill the fake-Redis socket MID-REPLY (half an HGETALL answer,
    then EOF): the client must invalidate the half-read stream, count
    the reconnect, retry on a fresh conn, and answer -- a dropped store
    conn must never poison subsequent announces."""

    async def main():
        class DiesMidReply(FakeRedis):
            def __init__(self):
                super().__init__()
                self.die_mid_hgetall = False

            async def _handle(self, reader, writer):
                try:
                    while True:
                        line = (await reader.readline()).rstrip(b"\r\n")
                        if not line:
                            return
                        assert line[:1] == b"*"
                        args = []
                        for _ in range(int(line[1:])):
                            lenline = (await reader.readline()).rstrip(b"\r\n")
                            n = int(lenline[1:])
                            args.append(
                                (await reader.readexactly(n + 2))[:-2]
                            )
                        reply = self._dispatch(args)
                        if (self.die_mid_hgetall
                                and args[0].upper() == b"HGETALL"):
                            self.die_mid_hgetall = False
                            # Half the reply, then the process "dies".
                            writer.write(reply[: max(1, len(reply) // 2)])
                            await writer.drain()
                            writer.close()
                            return
                        writer.write(reply)
                        await writer.drain()
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                finally:
                    writer.close()

        from kraken_tpu.utils.metrics import REGISTRY

        reconnects = REGISTRY.counter("redis_peerstore_reconnects_total")
        async with DiesMidReply() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30,
                                   timeout_seconds=2)
            try:
                await store.update("h", _peer(1))
                await store.update("h", _peer(2))
                before = reconnects.value()
                srv.die_mid_hgetall = True
                got = await store.get_peers("h")  # reconnect + retry
                assert len(got) == 2
                assert reconnects.value() > before
                # And the stream stays clean afterwards.
                await store.update("h", _peer(3))
                assert len(await store.get_peers("h")) == 3
            finally:
                await store.close()

    asyncio.run(main())


def test_redis_peerstore_lazy_hdel_failure_does_not_poison_reads():
    """The read path's housekeeping HDEL is best-effort: a server error
    there must not turn a successful handout into a 500 (the announce
    already has its peers)."""

    async def main():
        class HdelErrs(FakeRedis):
            def _dispatch(self, args):
                if args[0].upper() == b"HDEL":
                    return b"-ERR hdel refused\r\n"
                return super()._dispatch(args)

        import json as _json

        async with HdelErrs() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=1,
                                   timeout_seconds=2)
            try:
                await store.update("h", _peer(1))
                await store.update("h", _peer(2))
                # Expire peer 1 far enough back that the lazy reap (one
                # extra TTL of grace) wants to HDEL it.
                h = srv.hashes[b"swarm:h"]
                f = _peer(1).peer_id.hex.encode()
                doc = _json.loads(h[f])
                doc["_expiry"] = 0
                h[f] = _json.dumps(doc).encode()
                got = await store.get_peers("h")  # HDEL fails inside
                assert [p.ip for p in got] == ["10.0.0.2"]
                # Store keeps working (conn not invalidated: the -ERR
                # reply left the stream in sync).
                assert len(await store.get_peers("h")) == 1
            finally:
                await store.close()

    asyncio.run(main())


def test_redis_peerstore_pipeline_error_keeps_stream_synced():
    """A server error mid-pipeline (e.g. WRONGTYPE on HSET) must consume
    the remaining replies: the NEXT command must read its own reply, not
    the pipelined EXPIRE's leftover ':1'."""
    async def main():
        class WrongTypeOnce(FakeRedis):
            def __init__(self):
                super().__init__()
                self.fail_hset_once = False

            def _dispatch(self, args):
                if self.fail_hset_once and args[0].upper() == b"HSET":
                    self.fail_hset_once = False
                    return b"-WRONGTYPE key holds another kind of value\r\n"
                return super()._dispatch(args)

        from kraken_tpu.tracker.peerstore import RespError

        async with WrongTypeOnce() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30, timeout_seconds=2)
            try:
                await store.update("h", _peer(1))
                srv.fail_hset_once = True
                with pytest.raises(RespError):
                    await store.update("h", _peer(2))
                # Stream stayed synced: reads and writes keep working.
                got = await store.get_peers("h")
                assert [p.ip for p in got] == ["10.0.0.1"]
                await store.update("h", _peer(3))
                assert len(await store.get_peers("h")) == 2
            finally:
                await store.close()

    asyncio.run(main())


def test_announce_shape_garbage_is_400():
    """Wrong-shaped announce bodies (right keys, wrong types) must be 400s,
    not 500s."""
    from aiohttp import ClientSession

    from kraken_tpu.assembly import TrackerNode

    async def main():
        tracker = TrackerNode()
        await tracker.start()
        try:
            async with ClientSession() as http:
                for body in (
                    b"[]", b"null", b'{"info_hash": "x"}',
                    b'{"info_hash": "x", "peer": []}',
                    b'{"info_hash": "x", "peer": "y"}',
                    b'{"info_hash": ["x"], "peer": {"peer_id": 5}}',
                    # unhashable info_hash with a perfectly VALID peer:
                    # must 400 at parse, not 500 at store time
                    b'{"info_hash": ["x"], "peer": {"peer_id": "'
                    + b"ab" * 20 + b'", "ip": "1.2.3.4", "port": 1}}',
                    b'{"info_hash": 5, "peer": {"peer_id": "'
                    + b"ab" * 20 + b'", "ip": "1.2.3.4", "port": 1}}',
                    b'{"info_hash": "x", "peer": {"peer_id": 5, "ip": 1, "port": []}}',
                ):
                    async with http.post(
                        f"http://{tracker.addr}/announce", data=body,
                        headers={"Content-Type": "application/json"},
                    ) as r:
                        assert r.status == 400, (body, r.status)
        finally:
            await tracker.stop()

    asyncio.run(main())


def test_inmemory_peerstore_samples_prunes_and_sweeps():
    """Pins the large-swarm handout behavior PERF.md calls load-bearing:
    over-limit swarms are randomly SAMPLED (a stable slice hands every
    announcer the same N peers and starves the rest), emptied swarms are
    dropped on read, and an amortized sweep reaps one-shot swarms nobody
    queries again."""

    async def main():
        from kraken_tpu.tracker.peerstore import InMemoryPeerStore

        def peer(i: int) -> PeerInfo:
            return PeerInfo(peer_id=PeerID(f"{i:040x}"), ip="10.0.0.1", port=i)

        store = InMemoryPeerStore(ttl_seconds=30.0)
        for i in range(400):
            await store.update("big", peer(i), now=0.0)
        # Small swarm: everyone, no sampling.
        await store.update("small", peer(1), now=0.0)
        assert len(await store.get_peers("small", limit=10, now=1.0)) == 1
        # Over-limit swarm: repeated reads must not keep returning the
        # same window. 5 draws of 10 from 400 cover >10 distinct peers
        # with probability 1 - ~1e-60.
        seen = set()
        for _ in range(5):
            got = await store.get_peers("big", limit=10, now=1.0)
            assert len(got) == 10
            seen |= {p.peer_id for p in got}
        assert len(seen) > 10
        # Emptied swarm entries are dropped on read...
        store2 = InMemoryPeerStore(ttl_seconds=1.0)
        await store2.update("oneshot", peer(1), now=0.0)
        assert await store2.get_peers("oneshot", now=10.0) == []
        assert "oneshot" not in store2._swarms
        # ...and swarms nobody re-reads are reaped by the update sweep.
        store3 = InMemoryPeerStore(ttl_seconds=1.0)
        for i in range(200):
            await store3.update(f"h{i}", peer(i), now=0.0)
        for j in range(InMemoryPeerStore._SWEEP_EVERY):
            await store3.update("live", peer(j % 64), now=100.0)
        assert set(store3._swarms) == {"live"}

    asyncio.run(main())


def test_redis_peerstore_samples_large_swarms():
    """Same starvation fix on the Redis store: HGETALL field order is
    stable, so over-limit swarms must sample, not slice."""

    async def main():
        async with FakeRedis() as srv:
            store = RedisPeerStore(srv.addr, ttl_seconds=30)
            for i in range(1, 120):
                await store.update("big", _peer(i % 250 + 1))
            seen = set()
            for _ in range(5):
                got = await store.get_peers("big", limit=10)
                assert len(got) == 10
                seen |= {p.port for p in got}
            assert len(seen) > 10
            await store.close()

    asyncio.run(main())


def test_mutual_tls_requires_client_cert(tmp_path):
    """tls.client_ca turns the listener into mutual TLS: a cert-less
    client is refused at the handshake; a client presenting a cert signed
    by the CA gets through -- including via the process-wide outbound
    identity (tls_client YAML -> set_default_client_ssl) that every
    internal HTTPClient inherits."""
    from kraken_tpu.assembly import TrackerNode
    from kraken_tpu.tracker.client import TrackerClient
    from kraken_tpu.utils.httputil import HTTPClient, set_default_client_ssl

    def gen_selfsigned(name):
        cert, key = tmp_path / f"{name}.pem", tmp_path / f"{name}.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", f"/CN={name}",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True,
        )
        return cert, key

    server_cert, server_key = gen_selfsigned("server")
    client_cert, client_key = gen_selfsigned("client")

    # Server: terminate TLS + REQUIRE a client cert chained to client_ca
    # (the self-signed client cert is its own CA).
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(str(server_cert), str(server_key))
    server_ctx.load_verify_locations(cafile=str(client_cert))
    server_ctx.verify_mode = ssl.CERT_REQUIRED

    async def main():
        from kraken_tpu.core.metainfo import InfoHash

        tracker = TrackerNode(ssl_context=server_ctx)
        await tracker.start()
        try:
            # 1. No client cert: refused during the handshake.
            bare_ctx = ssl.create_default_context(cafile=str(server_cert))
            bare = TrackerClient(
                f"https://{tracker.addr}",
                peer_id=_peer(1).peer_id, ip="127.0.0.1", port=7001,
                http=HTTPClient(ssl=bare_ctx, retries=1),
            )
            with pytest.raises(Exception) as exc_info:
                await bare.announce(
                    None, InfoHash("ab" * 32), "ns", complete=False
                )
            assert not isinstance(exc_info.value, AssertionError)
            await bare.close()

            # 2. Process-wide identity: HTTPClient() with NO explicit ssl
            # picks up the default context (what tls_client YAML sets).
            ident_ctx = ssl.create_default_context(cafile=str(server_cert))
            ident_ctx.load_cert_chain(str(client_cert), str(client_key))
            set_default_client_ssl(ident_ctx)
            try:
                ok = TrackerClient(
                    f"https://{tracker.addr}",
                    peer_id=_peer(2).peer_id, ip="127.0.0.1", port=7002,
                    http=HTTPClient(),
                )
                peers, interval = await ok.announce(
                    None, InfoHash("ab" * 32), "ns", complete=False
                )
                assert peers == [] and interval > 0
                await ok.close()
            finally:
                set_default_client_ssl(None)
        finally:
            await tracker.stop()

    asyncio.run(main())

"""Unit tests for kraken_tpu.core (digest, metainfo, peer, hasher)."""

import hashlib
import io

import numpy as np
import pytest

from kraken_tpu.core import (
    BlobInfo,
    CPUPieceHasher,
    Digest,
    DigestError,
    Digester,
    MetaInfo,
    MetaInfoError,
    PeerID,
    PeerIDFactory,
    PeerInfo,
    get_hasher,
)
from kraken_tpu.core.fixtures import (
    blob_and_metainfo_fixture,
    blob_fixture,
    metainfo_fixture,
)
from kraken_tpu.core.metainfo import num_pieces


class TestDigest:
    def test_from_bytes_matches_hashlib(self):
        data = b"hello kraken"
        d = Digest.from_bytes(data)
        assert d.hex == hashlib.sha256(data).hexdigest()
        assert str(d) == f"sha256:{d.hex}"
        assert d.raw == hashlib.sha256(data).digest()

    def test_parse_roundtrip(self):
        d = Digest.from_bytes(b"x")
        assert Digest.parse(str(d)) == d

    @pytest.mark.parametrize(
        "bad",
        [
            "sha256",  # no separator
            "md5:" + "a" * 32,  # wrong algo
            "sha256:" + "a" * 63,  # short hex
            "sha256:" + "A" * 64,  # uppercase rejected (canonical form only)
            "sha256:" + "g" * 64,  # non-hex
        ],
    )
    def test_parse_rejects_malformed(self, bad):
        with pytest.raises(DigestError):
            Digest.parse(bad)

    def test_from_reader_streams(self):
        data = blob_fixture(10 * 1024 * 1024 + 13, seed=1)
        assert Digest.from_reader(io.BytesIO(data)) == Digest.from_bytes(data)

    def test_digester_incremental(self):
        d = Digester()
        d.update(b"hello ")
        d.update(b"world")
        assert d.digest() == Digest.from_bytes(b"hello world")

    def test_digester_tee(self):
        d = Digester()
        chunks = [b"ab", b"cd", b"ef"]
        out = list(d.tee(iter(chunks)))
        assert out == chunks
        assert d.digest() == Digest.from_bytes(b"abcdef")

    def test_hashable_and_ordered(self):
        a, b = Digest.from_bytes(b"a"), Digest.from_bytes(b"b")
        assert len({a, b, Digest.from_bytes(b"a")}) == 2
        assert (a < b) != (b < a)


class TestMetaInfo:
    def test_num_pieces(self):
        assert num_pieces(0, 4) == 0
        assert num_pieces(1, 4) == 1
        assert num_pieces(4, 4) == 1
        assert num_pieces(5, 4) == 2

    def test_piece_layout_with_ragged_tail(self):
        blob = blob_fixture(10_000, seed=2)
        mi = metainfo_fixture(blob, piece_length=4096)
        assert mi.num_pieces == 3
        assert mi.piece_length_of(0) == 4096
        assert mi.piece_length_of(2) == 10_000 - 2 * 4096
        with pytest.raises(IndexError):
            mi.piece_length_of(3)

    def test_verify_piece(self):
        blob, mi = blob_and_metainfo_fixture(size=10_000, piece_length=4096, seed=3)
        for i in range(mi.num_pieces):
            piece = blob[i * 4096 : (i + 1) * 4096]
            assert mi.verify_piece(i, piece)
            assert not mi.verify_piece(i, piece[:-1])  # wrong length
            if piece:
                corrupted = bytes([piece[0] ^ 1]) + piece[1:]
                assert not mi.verify_piece(i, corrupted)

    def test_serialize_roundtrip(self):
        _, mi = blob_and_metainfo_fixture(seed=4)
        mi2 = MetaInfo.deserialize(mi.serialize())
        assert mi2 == mi
        assert mi2.info_hash == mi.info_hash

    def test_info_hash_depends_on_content(self):
        blob = blob_fixture(8192, seed=5)
        a = metainfo_fixture(blob, piece_length=4096)
        b = metainfo_fixture(blob, piece_length=8192)
        assert a.info_hash != b.info_hash

    def test_deserialize_rejects_garbage(self):
        with pytest.raises(MetaInfoError):
            MetaInfo.deserialize(b"not json")
        with pytest.raises(MetaInfoError):
            MetaInfo.deserialize(b'{"version": 99}')

    def test_hash_count_validated(self):
        blob = blob_fixture(8192, seed=6)
        with pytest.raises(MetaInfoError):
            MetaInfo(Digest.from_bytes(blob), len(blob), 4096, b"\x00" * 32)

    def test_zero_length_blob(self):
        mi = metainfo_fixture(b"", piece_length=4096)
        assert mi.num_pieces == 0
        assert MetaInfo.deserialize(mi.serialize()) == mi


class TestPeer:
    def test_addr_hash_deterministic(self):
        f = PeerIDFactory(PeerIDFactory.ADDR_HASH)
        assert f.create("10.0.0.1", 5000) == f.create("10.0.0.1", 5000)
        assert f.create("10.0.0.1", 5000) != f.create("10.0.0.1", 5001)

    def test_random_unique(self):
        f = PeerIDFactory(PeerIDFactory.RANDOM)
        assert f.create("10.0.0.1", 5000) != f.create("10.0.0.1", 5000)

    def test_peer_info_roundtrip(self):
        p = PeerInfo(PeerID("ab" * 20), "10.0.0.2", 1234, origin=True, complete=True)
        assert PeerInfo.from_dict(p.to_dict()) == p
        assert p.addr == "10.0.0.2:1234"

    def test_blob_info_roundtrip(self):
        assert BlobInfo.from_dict(BlobInfo(123).to_dict()) == BlobInfo(123)


class TestCPUPieceHasher:
    def test_matches_hashlib_ragged(self):
        h = CPUPieceHasher()
        blob = blob_fixture(10_000, seed=7)
        out = h.hash_pieces(blob, 4096)
        assert out.shape == (3, 32)
        for i in range(3):
            piece = blob[i * 4096 : (i + 1) * 4096]
            assert out[i].tobytes() == hashlib.sha256(piece).digest()

    def test_empty_blob(self):
        assert CPUPieceHasher().hash_pieces(b"", 4096).shape == (0, 32)

    def test_hash_batch(self):
        h = CPUPieceHasher()
        pieces = [b"a", b"bb", b"", blob_fixture(5000, seed=8)]
        out = h.hash_batch(pieces)
        assert out.shape == (4, 32)
        for i, p in enumerate(pieces):
            assert out[i].tobytes() == hashlib.sha256(p).digest()

    def test_registry(self):
        assert isinstance(get_hasher("cpu"), CPUPieceHasher)
        assert get_hasher("cpu") is get_hasher("cpu")
        with pytest.raises(KeyError):
            get_hasher("nope")


class TestPooledCPUPieceHasher:
    """hash_workers pool: bit-identical to the serial oracle -- sharding
    only reorders WHICH thread hashes a piece, never piece boundaries --
    and visible on the pool gauges."""

    def test_hash_pieces_parity_with_serial(self):
        blob = blob_fixture(1_000_000, seed=11)
        serial = CPUPieceHasher().hash_pieces(blob, 4096)
        for workers in (1, 2, 3):
            pooled = CPUPieceHasher(workers=workers).hash_pieces(blob, 4096)
            assert (pooled == serial).all(), workers

    def test_hash_pieces_parity_ragged_and_tiny(self):
        h = CPUPieceHasher(workers=2)
        for size in (0, 1, 4095, 4096, 4097, 40_961):
            blob = blob_fixture(size, seed=size) if size else b""
            assert (
                h.hash_pieces(blob, 4096)
                == CPUPieceHasher().hash_pieces(blob, 4096)
            ).all(), size

    def test_hash_batch_parity(self):
        pieces = [b"", b"x", blob_fixture(5000, seed=1),
                  blob_fixture(100_000, seed=2)]
        serial = CPUPieceHasher().hash_batch(pieces)
        pooled = CPUPieceHasher(workers=2).hash_batch(pieces)
        assert (pooled == serial).all()

    def test_registry_caches_per_worker_count(self):
        assert get_hasher("cpu", workers=2) is get_hasher("cpu", workers=2)
        assert get_hasher("cpu", workers=2) is not get_hasher("cpu")
        assert get_hasher("cpu").pool is None
        assert get_hasher("cpu", workers=2).pool.workers == 2

    def test_pool_gauges_visible(self):
        from kraken_tpu.utils.metrics import REGISTRY

        CPUPieceHasher(workers=2).hash_pieces(blob_fixture(100_000, seed=3),
                                              4096)
        text = REGISTRY.render()
        # Label carries the worker count: two pools in one process must
        # publish to distinct series.
        assert 'hash_pool_workers{pool="cpu/2"} 2' in text
        assert "hash_pool_occupancy" in text
        assert "hash_pool_queue_depth" in text


def test_metainfo_deserialize_fuzz_only_metainfoerror():
    """Metainfo comes off the wire (tracker proxy): any corruption --
    structural or bit-level -- must surface as MetaInfoError, never a raw
    KeyError/AttributeError escaping to the scheduler."""
    import json



    rng = np.random.default_rng(5)
    blob = b"x" * 1000
    mi = MetaInfo(
        Digest.from_bytes(blob), len(blob), 1024,
        __import__("hashlib").sha256(blob).digest(),
    )
    raw = mi.serialize()
    doc = json.loads(raw)
    cases = [
        b"", b"null", b"[]", b'"x"', b"{}", b'{"version":1}',
        b'{"version":1,"info":[]}', b'{"version":1,"info":{}}',
        json.dumps({**doc, "info": {
            k: v for k, v in doc["info"].items() if k != "name"
        }}).encode(),
        json.dumps({**doc, "digest": 5}).encode(),
        json.dumps({**doc, "info": {**doc["info"], "piece_hashes": "zz"}}).encode(),
        json.dumps({**doc, "info": {**doc["info"], "length": "big"}}).encode(),
    ]
    for _ in range(300):
        b = bytearray(raw)
        i = int(rng.integers(0, len(b)))
        b[i] ^= int(rng.integers(1, 256))
        cases.append(bytes(b))
    for c in cases:
        try:
            got = MetaInfo.deserialize(c)
            assert got.digest == mi.digest  # survived mutation unchanged
        except MetaInfoError:
            pass  # the only acceptable failure type

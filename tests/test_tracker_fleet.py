"""Tracker high-availability plane (round 12).

The contract under test: a swarm hangs off a FLEET of trackers, not one
address. Clients shard each request by info hash over the rendezvous
ring and fail over along it through the degradation machinery
(breakers, deadline budgets, hedged reads); trackers serve any swarm,
forward non-owner announces toward the live owner, and drain via the
standard lameduck contract -- so killing 1-of-N trackers mid-pull is a
blip in announce latency, never a failed pull.
"""

import asyncio
import json
import os

import pytest

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import InfoHash
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.placement.hrw import rendezvous_hash
from kraken_tpu.tracker.client import (
    TrackerClient,
    TrackerFleetClient,
    make_tracker_client,
    parse_tracker_addrs,
)
from kraken_tpu.tracker.server import TrackerServer
from kraken_tpu.utils.httputil import HTTPClient
from kraken_tpu.utils.metrics import REGISTRY

NS = "library/fleet"


def _pid(i: int) -> PeerID:
    return PeerID(f"{i:040x}")


def _peer(i: int) -> PeerInfo:
    return PeerInfo(peer_id=_pid(i), ip="10.0.0.%d" % (i % 250 + 1),
                    port=7000 + i)


async def _start_trackers(n: int, **kw):
    trackers = [TrackerNode(announce_interval_seconds=0.1,
                            peer_ttl_seconds=5.0, **kw) for _ in range(n)]
    for t in trackers:
        await t.start()
    # The fleet list exists only after every port is bound.
    addrs = [t.addr for t in trackers]
    for t in trackers:
        t.server.set_fleet(addrs, t.addr)
        t.fleet_addrs, t.self_addr = list(addrs), t.addr
    return trackers, addrs


def _fleet_client(addrs, i=1, **kw) -> TrackerFleetClient:
    return TrackerFleetClient(
        addrs, _pid(i), "127.0.0.1", 7000 + i,
        announce_timeout_seconds=kw.pop("announce_timeout_seconds", 3.0),
        **kw,
    )


# -- client-side sharding + failover ----------------------------------------


def test_make_tracker_client_picks_shape():
    """<= 1 addr keeps the pre-fleet single-host client (including the
    legacy empty-addr construction); >= 2 builds the fleet."""
    single = make_tracker_client("1.2.3.4:7602", _pid(1), "h", 1)
    assert isinstance(single, TrackerClient)
    empty = make_tracker_client("", _pid(1), "h", 1)
    assert isinstance(empty, TrackerClient) and empty.addr == ""
    fleet = make_tracker_client("a:1, b:2,,c:3", _pid(1), "h", 1)
    assert isinstance(fleet, TrackerFleetClient)
    assert fleet.addrs == ["a:1", "b:2", "c:3"]
    assert parse_tracker_addrs(["x:1", "", "y:2"]) == ["x:1", "y:2"]


def test_fleet_shards_announces_by_info_hash(tmp_path):
    """In a healthy fleet every announce lands on its rendezvous owner:
    each tracker's peer store holds exactly the swarms it owns."""

    async def main():
        trackers, addrs = await _start_trackers(3)
        client = _fleet_client(addrs)
        try:
            hashes = [InfoHash(f"{i:02x}" + "cd" * 31) for i in range(12)]
            for h in hashes:
                await client.announce(None, h, NS, complete=False)
            for h in hashes:
                owner = rendezvous_hash(h.hex, addrs, k=1)[0]
                for t in trackers:
                    stored = t.server.peers._swarms.get(h.hex)
                    if t.addr == owner:
                        assert stored, f"owner {owner} missing swarm"
                    else:
                        assert not stored, (
                            f"non-owner {t.addr} got swarm {h.hex[:8]}"
                        )
            assert client.owner_of(hashes[0].hex) == rendezvous_hash(
                hashes[0].hex, addrs, k=1
            )[0]
        finally:
            await client.close()
            for t in trackers:
                await t.stop()

    asyncio.run(main())


def test_fleet_fails_over_when_owner_dies(tmp_path):
    """Kill a swarm's shard owner: announces fail over to the next ring
    tracker (counted), the breaker records the dead host, and the
    handout still works -- no announce ever errors because the owner
    died."""

    async def main():
        trackers, addrs = await _start_trackers(3)
        h = InfoHash("ee" * 32)
        owner = rendezvous_hash(h.hex, addrs, k=1)[0]
        client = _fleet_client(addrs, i=1)
        client2 = _fleet_client(addrs, i=2)
        failovers = REGISTRY.counter("tracker_fleet_failovers_total")
        before = failovers.value(op="announce")
        try:
            await client.announce(None, h, NS, complete=True)
            # The owner dies (process gone: connections refused).
            victim = next(t for t in trackers if t.addr == owner)
            await victim.stop()
            peers, interval = await client2.announce(
                None, h, NS, complete=False
            )
            assert interval > 0  # served, not errored
            assert failovers.value(op="announce") > before
            # The swarm re-forms on the survivor within one announce:
            # client1 re-announces (failing over too), then client2 sees
            # it in the handout.
            await client.announce(None, h, NS, complete=True)
            peers, _ = await client2.announce(None, h, NS, complete=False)
            assert any(p.peer_id == _pid(1) for p in peers)
            # Breaker evidence: the dead owner is held unhealthy in this
            # client's breaker after enough failures (the walk marks one
            # failure per announce that had to route around it).
            for _ in range(3):
                await client2.announce(None, h, NS, complete=False)
            snap = client2.health.snapshot()
            assert owner in snap["hosts"]
        finally:
            await client.close()
            await client2.close()
            for t in trackers:
                if t is not victim:
                    await t.stop()

    asyncio.run(main())


def test_fleet_set_addrs_reshards_and_prunes(tmp_path):
    """SIGHUP membership swap: dropped trackers lose their sub-clients
    and breaker verdicts; ownership re-shards on the next call."""

    async def main():
        client = _fleet_client(["a:1", "b:2", "c:3"])
        try:
            client.health.failed("c:3")
            client.set_addrs(["a:1", "b:2"])
            assert client.addrs == ["a:1", "b:2"]
            assert "c:3" not in client.health.snapshot()["hosts"]
            assert client.owner_of("ab" * 32) in ("a:1", "b:2")
            with pytest.raises(ValueError):
                client.set_addrs([])
        finally:
            await client.close()

    asyncio.run(main())


def test_fleet_port_setter_fans_out():
    """Assembly learns the p2p port post-bind; the setter must reach
    every lazily-built sub-client."""

    async def main():
        client = _fleet_client(["a:1", "b:2"])
        try:
            sub = client._client("a:1")
            client.port = 4242
            assert sub.port == 4242
            assert client._client("b:2").port == 4242
        finally:
            await client.close()

    asyncio.run(main())


def test_recipe_cache_survives_failover(monkeypatch):
    """The agent-side TTL cache: a recipe fetched once is never
    re-fetched across a tracker failover (recipes are CAS-immutable),
    with hit/miss counters."""

    calls = {"recipe": 0, "similar": 0}

    async def fake_recipe(self, namespace, d, deadline=None):
        calls["recipe"] += 1
        return ("RECIPE", "origin:1")

    async def fake_similar(self, namespace, d, deadline=None):
        calls["similar"] += 1
        return [{"digest": "ab" * 32, "score": 0.9}]

    async def main():
        monkeypatch.setattr(TrackerClient, "get_recipe", fake_recipe)
        monkeypatch.setattr(TrackerClient, "similar", fake_similar)
        client = _fleet_client(
            ["a:1", "b:2", "c:3"], recipe_cache_ttl_seconds=60.0
        )
        hits = REGISTRY.counter("tracker_recipe_cache_total")
        h0 = hits.value(op="recipe", result="hit")
        d = Digest.from_bytes(b"target")
        try:
            assert await client.get_recipe(NS, d) == ("RECIPE", "origin:1")
            assert calls["recipe"] == 1
            # Failover (membership swap = the owner changed): the cache
            # answers; no sub-client call happens.
            client.set_addrs(["b:2", "c:3"])
            assert await client.get_recipe(NS, d) == ("RECIPE", "origin:1")
            assert calls["recipe"] == 1
            assert hits.value(op="recipe", result="hit") == h0 + 1
            # /similar caches the same way.
            assert len(await client.similar(NS, d)) == 1
            assert len(await client.similar(NS, d)) == 1
            assert calls["similar"] == 1
        finally:
            await client.close()

    asyncio.run(main())


def test_blackholed_owner_pays_one_slice_not_the_whole_budget(monkeypatch):
    """A PARTITIONED owner (SYN blackhole: the socket hangs, no RST)
    must cost one per-attempt slice of the walk budget, be counted as
    host evidence, and the announce must still succeed via a survivor
    inside the budget -- a whole-budget hang would make failover
    unreachable for every swarm the corpse owns."""

    import time as _time

    h = InfoHash("dd" * 32)

    async def main():
        client = _fleet_client(
            ["a:1", "b:2", "c:3"], announce_timeout_seconds=1.5
        )
        owner = client.owner_of(h.hex)

        async def fake_announce(self, d, ih, namespace, complete,
                                deadline=None):
            if self.addr == owner:
                await asyncio.sleep(3600)  # the blackhole
            return [], 0.5

        monkeypatch.setattr(TrackerClient, "announce", fake_announce)
        try:
            t0 = _time.monotonic()
            peers, interval = await client.announce(None, h, NS, False)
            wall = _time.monotonic() - t0
            assert interval == 0.5  # a survivor answered
            # Paid ~one slice (budget/fleet = 0.5 s), not the whole 1.5.
            assert wall < 1.2, wall
            # The hang IS host evidence: the breaker recorded it, so
            # fail_threshold announces later the owner is skipped cold.
            snap = client.health.snapshot()
            assert snap["hosts"][owner]["consecutive_fails"] >= 1
        finally:
            await client.close()

    asyncio.run(main())


# -- hashring rebalance properties -------------------------------------------


def test_rebalance_moves_about_one_nth_of_ownership():
    """The property the whole plane leans on: adding (or removing) one
    tracker moves only ~1/N of info-hash ownership. Pinned with slack
    for hash variance; a change to the rendezvous scoring that breaks
    minimal reshuffling must fail here."""
    keys = [Digest.from_bytes(os.urandom(32)).hex for _ in range(2000)]
    three = ["t1:7602", "t2:7602", "t3:7602"]
    four = three + ["t4:7602"]

    def owners(addrs):
        return {k: rendezvous_hash(k, addrs, k=1)[0] for k in keys}

    o3, o4 = owners(three), owners(four)
    moved_add = sum(1 for k in keys if o3[k] != o4[k]) / len(keys)
    # Expected exactly 1/4 on add; allow hash variance around it.
    assert 0.15 <= moved_add <= 0.35, moved_add
    # Every moved key moved TO the new tracker -- rendezvous never
    # shuffles ownership between survivors.
    assert all(
        o4[k] == "t4:7602" for k in keys if o3[k] != o4[k]
    )
    # Removal: only the dead tracker's keys move (to survivors).
    o2 = owners(three[:2])
    moved_rm = [k for k in keys if o3[k] != o2[k]]
    assert all(o3[k] == "t3:7602" for k in moved_rm)
    assert 0.23 <= len(moved_rm) / len(keys) <= 0.43


def test_membership_change_announce_never_loses_a_peer(tmp_path):
    """A client with a STALE fleet view announces to a tracker that is
    no longer the owner: the non-owner accepts (its local handout
    works) AND forwards to the live owner, so clients with the fresh
    view find the peer there -- a registered peer is never lost to a
    membership change."""

    async def main():
        trackers, addrs = await _start_trackers(3)
        try:
            h = InfoHash("aa" * 32)
            owner = rendezvous_hash(h.hex, addrs, k=1)[0]
            non_owner = next(t for t in trackers if t.addr != owner)
            owner_node = next(t for t in trackers if t.addr == owner)
            http = HTTPClient()
            try:
                # The stale-view announce lands on the non-owner.
                body = await http.post(
                    f"http://{non_owner.addr}/announce",
                    data=json.dumps({
                        "info_hash": h.hex, "peer": _peer(7).to_dict(),
                    }),
                )
                assert json.loads(body)["interval"] > 0
                # Accepted locally (never an error; handout from the
                # local store works immediately)...
                assert h.hex in non_owner.server.peers._swarms
                # ...and forwarded: the owner's store learns the peer.
                for _ in range(100):
                    if h.hex in owner_node.server.peers._swarms:
                        break
                    await asyncio.sleep(0.02)
                swarm = owner_node.server.peers._swarms.get(h.hex, {})
                assert _pid(7).hex in swarm
                # Fresh-view clients asking the owner get the peer.
                body = await http.post(
                    f"http://{owner}/announce",
                    data=json.dumps({
                        "info_hash": h.hex, "peer": _peer(8).to_dict(),
                    }),
                )
                handed = json.loads(body)["peers"]
                assert any(p["peer_id"] == _pid(7).hex for p in handed)
            finally:
                await http.close()
        finally:
            for t in trackers:
                await t.stop()

    asyncio.run(main())


def test_forwarded_announces_are_not_reforwarded(tmp_path):
    """The X-Kraken-Forwarded marker stops forwarding loops: a tracker
    whose fleet view disagrees must not bounce one announce around the
    fleet forever."""

    async def main():
        server = TrackerServer(
            fleet_addrs=["other:1", "me:2"], self_addr="me:2",
        )
        forwarded = []
        server._maybe_forward = (
            lambda ih, doc: forwarded.append(ih)
        )

        class Req:
            headers = {"X-Kraken-Forwarded": "1"}

            async def json(self):
                return {"info_hash": "ab" * 32,
                        "peer": _peer(1).to_dict()}

        resp = await server._announce_inner(Req())
        assert resp.status == 200
        assert forwarded == []  # marker honored
        await server.close()

    asyncio.run(main())


def test_tracker_lameduck_drains_and_fleet_routes_around(tmp_path):
    """The PR-5 drain contract on trackers: POST /debug/lameduck flips
    /health AND /announce to 503+Retry-After, and a fleet client simply
    fails over -- the rolling-restart runbook's step 1."""

    async def main():
        trackers, addrs = await _start_trackers(2)
        client = _fleet_client(addrs)
        h = InfoHash("bb" * 32)
        owner = rendezvous_hash(h.hex, addrs, k=1)[0]
        victim = next(t for t in trackers if t.addr == owner)
        http = HTTPClient(retries=0)
        try:
            await client.announce(None, h, NS, complete=True)
            body = await http.post(f"http://{victim.addr}/debug/lameduck")
            assert json.loads(body)["lameduck"] is True
            # /health and /announce refuse with the drain 503.
            for path, method in (("/health", "GET"), ("/announce", "POST")):
                status, headers, _ = await http.request_full(
                    method, f"http://{victim.addr}{path}",
                    data=json.dumps({"info_hash": h.hex,
                                     "peer": _peer(3).to_dict()})
                    if method == "POST" else None,
                    ok_statuses=(503,), retry_5xx=False,
                )
                assert status == 503 and "Retry-After" in headers
            # The fleet shrugs: the owner's drain 503 walks to the peer.
            peers, interval = await client.announce(
                None, h, NS, complete=False
            )
            assert interval > 0
        finally:
            await http.close()
            await client.close()
            for t in trackers:
                await t.stop()

    asyncio.run(main())


# -- total-outage latch (PEX plane) ------------------------------------------


def _dead_fleet(monkeypatch, addrs, calls, **kw):
    """Fleet client whose every sub-client RPC is a refused socket."""
    from kraken_tpu.placement.healthcheck import PassiveFilter

    async def dead_announce(self, d, ih, namespace, complete, deadline=None):
        calls.append(self.addr)
        raise ConnectionError("connection refused")

    monkeypatch.setattr(TrackerClient, "announce", dead_announce)
    kw.setdefault(
        "health",
        PassiveFilter(fail_threshold=1,
                      cooldown_seconds=kw.pop("cooldown", 30.0)),
    )
    return _fleet_client(addrs, **kw)


def test_outage_latch_engages_and_fail_fasts(monkeypatch):
    """Every tracker breaker-open: the latch engages (gauge + counter +
    typed error) and subsequent announces fail FAST -- zero sub-client
    calls, not another full-budget walk over sockets already known
    dark."""

    async def main():
        calls = []
        client = _dead_fleet(monkeypatch, ["a:1", "b:2", "c:3"], calls)
        outages = REGISTRY.counter("tracker_outages_total")
        before = outages.value()
        h = InfoHash("ab" * 32)
        try:
            assert client.outage is False
            # Walk 1 burns the fleet: every addr fails once, every
            # breaker opens (threshold 1).
            with pytest.raises(ConnectionError):
                await client.announce(None, h, NS, complete=False)
            assert len(calls) == 3
            # Walk 2 hits the gate: latch engages, typed error, NO calls.
            with pytest.raises(ConnectionError, match="fleet outage"):
                await client.announce(None, h, NS, complete=False)
            assert len(calls) == 3
            assert client.outage is True
            assert outages.value() == before + 1
            assert REGISTRY.gauge("tracker_outage").value() == 1
            # Steady-state outage: N more announces cost ZERO walks.
            for _ in range(10):
                with pytest.raises(ConnectionError, match="fleet outage"):
                    await client.announce(None, h, NS, complete=False)
            assert len(calls) == 3
        finally:
            await client.close()

    asyncio.run(main())


def test_outage_latch_clears_only_on_walk_success(monkeypatch):
    """Hysteresis: a cooldown expiring re-admits the walk (the walk IS
    the probe) but the latch clears only when a walk SUCCEEDS end to
    end -- and the latched time lands on tracker_outage_seconds_total."""

    async def main():
        calls = []
        alive = {"up": False}

        async def flaky_announce(self, d, ih, namespace, complete,
                                 deadline=None):
            calls.append(self.addr)
            if not alive["up"]:
                raise ConnectionError("connection refused")
            return [], 0.5

        from kraken_tpu.placement.healthcheck import PassiveFilter

        monkeypatch.setattr(TrackerClient, "announce", flaky_announce)
        client = _fleet_client(
            ["a:1", "b:2"],
            health=PassiveFilter(fail_threshold=1, cooldown_seconds=0.15),
        )
        seconds = REGISTRY.counter("tracker_outage_seconds_total")
        s0 = seconds.value()
        h = InfoHash("cd" * 32)
        try:
            with pytest.raises(ConnectionError):
                await client.announce(None, h, NS, complete=False)
            with pytest.raises(ConnectionError, match="fleet outage"):
                await client.announce(None, h, NS, complete=False)
            assert client.outage is True
            # Cooldown expires -> the gate passes -> the probe walk runs
            # but still FAILS: latched it stays (no half-open flicker).
            await asyncio.sleep(0.2)
            n = len(calls)
            with pytest.raises(ConnectionError):
                await client.announce(None, h, NS, complete=False)
            assert len(calls) > n  # a real walk ran (the probe)
            assert client.outage is True
            # Trackers come back; next post-cooldown walk succeeds and
            # the latch clears with the outage time accrued. The failed
            # probe re-opened the breakers with a LONGER jittered
            # cooldown (<= 3x the base), so out-wait that.
            alive["up"] = True
            await asyncio.sleep(0.5)
            peers, interval = await client.announce(
                None, h, NS, complete=False
            )
            assert interval == 0.5
            assert client.outage is False
            assert REGISTRY.gauge("tracker_outage").value() == 0
            assert seconds.value() - s0 >= 0.3
        finally:
            await client.close()

    asyncio.run(main())


def test_set_addrs_to_all_dead_membership_short_circuits(monkeypatch):
    """The SIGHUP footgun: membership swapped to a fleet that is ENTIRELY
    dark. One discovery walk per new addr set is fair; after the
    breakers trip, repeated announces must ride the outage latch --
    not spin full-budget failover walks against corpses."""

    async def main():
        calls = []
        client = _dead_fleet(monkeypatch, ["a:1", "b:2"], calls)
        h = InfoHash("ef" * 32)
        try:
            with pytest.raises(ConnectionError):
                await client.announce(None, h, NS, complete=False)
            with pytest.raises(ConnectionError, match="fleet outage"):
                await client.announce(None, h, NS, complete=False)
            assert client.outage is True
            # Swap to a different -- equally dead -- membership. Fresh
            # addrs mean fresh breakers: exactly ONE discovery walk may
            # run, then the latch must re-engage.
            client.set_addrs(["d:4", "e:5"])
            n = len(calls)
            for _ in range(10):
                with pytest.raises(ConnectionError):
                    await client.announce(None, h, NS, complete=False)
            assert len(calls) - n == 2, calls[n:]  # one walk over d,e
            assert client.outage is True  # latched straight through
        finally:
            await client.close()

    asyncio.run(main())


# -- the acceptance herd: 3 trackers + origin + agent, kill one mid-pull -----


def test_kill_one_of_three_trackers_mid_pull_completes_bit_identical(tmp_path):
    """THE acceptance scenario: a real 3-tracker fleet fronting an
    origin and an agent; the blob's announce shard owner dies MID-PULL;
    the pull completes bit-identically with zero intervention, and the
    dead tracker's breaker state is visible on the agent's
    /debug/healthcheck."""

    async def main():
        from kraken_tpu.origin.metainfogen import PieceLengthConfig

        trackers, addrs = await _start_trackers(3)
        fleet_spec = ",".join(addrs)
        origin = OriginNode(
            store_root=str(tmp_path / "origin"), tracker_addr=fleet_spec,
            # Small pieces: the agent's ingress token bucket can only
            # pace requests <= its capacity (oversize frames pass whole).
            piece_lengths=PieceLengthConfig(table=((0, 65536),)),
        )
        await origin.start()
        ring = Ring(HostList(static=[origin.addr]), max_replica=2)
        cluster = ClusterClient(ring)
        for t in trackers:
            t.server.origin_cluster = cluster
        agent = AgentNode(
            store_root=str(tmp_path / "agent"), tracker_addr=fleet_spec,
            # Throttle the pull so the tracker death lands mid-transfer
            # (the token bucket's initial burst = 1 s of rate, so a
            # 1.2 MB blob takes ~5 s at this cap).
            p2p_bandwidth={"ingress_bps": 200_000, "egress_bps": 0},
        )
        await agent.start()
        assert isinstance(agent._tracker_client, TrackerFleetClient)
        assert isinstance(origin._tracker_client, TrackerFleetClient)
        http = HTTPClient(timeout_seconds=120.0)
        victim = None
        try:
            blob = os.urandom(1_200_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob, chunk_size=400_000)
            mi = await oc.get_metainfo(NS, d)
            await oc.close()
            owner = rendezvous_hash(mi.info_hash.hex, addrs, k=1)[0]
            victim = next(t for t in trackers if t.addr == owner)

            pull = asyncio.create_task(http.get(
                f"http://{agent.addr}/namespace/"
                f"{NS.replace('/', '%2F')}/blobs/{d.hex}"
            ))
            # Let the pull engage (metainfo + announce + first pieces),
            # then kill the swarm's announce shard owner.
            await asyncio.sleep(0.6)
            assert not pull.done()
            await victim.stop()

            got = await asyncio.wait_for(pull, timeout=90)
            assert got == blob  # bit-identical through the tracker death

            # Failover is observable: subsequent announces route around
            # the dead owner, and the breaker surface the operators read
            # (GET /debug/healthcheck on the agent) names it.
            for _ in range(200):
                snap = json.loads(await http.get(
                    f"http://{agent.addr}/debug/healthcheck"
                ))
                fleet = {
                    name: doc for name, doc in snap.items()
                    if owner in doc.get("hosts", {})
                }
                if fleet:
                    break
                await asyncio.sleep(0.05)
            assert fleet, f"dead tracker absent from breaker surface: {snap}"
        finally:
            await http.close()
            await agent.stop()
            await origin.stop()
            await cluster.close()
            for t in trackers:
                if t is not victim:
                    await t.stop()

    asyncio.run(main())

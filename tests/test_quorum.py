"""Quorum write plane kill-tests: replication-acked commits, hinted
handoff under partition, read-repair on owner miss, and hint durability
across origin restart.

The contract under test (Dynamo sloppy quorum, ISSUE 20): with
``write_quorum: N`` an upload commit acks only once N ring replicas
durably hold the blob (local commit is copy #1); replicas unreachable at
commit time get a durable hint that replays when they return; a GET
landing on an owner that misses locally repairs from a sibling before
serving. Every scenario asserts ZERO lost blobs and bit-identical pulls
-- and none of these herds has a backend at all, so every recovery here
is peer-to-peer by construction (zero backend reads).
"""

import asyncio
import logging
import os
import socket

import pytest

from kraken_tpu.assembly import OriginNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient
from kraken_tpu.origin.server import HINT_KIND, QuorumConfig
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.metrics import REGISTRY

pytestmark = pytest.mark.chaos

NS = "quorum"


@pytest.fixture(autouse=True)
def chaos_plane():
    """Every test starts disarmed and ACKNOWLEDGED (nodes may assemble
    with failpoints armed), and leaves the process-global plane clean --
    a leaked armed failpoint would inject into unrelated tests."""
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    yield failpoints.FAILPOINTS
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow(False)


async def _wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        out = cond()
        if asyncio.iscoroutine(out):
            out = await out
        if out:
            return
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(interval)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _counter(name: str, **labels) -> float:
    return REGISTRY.counter(name).value(**labels)


def _node(tmp_path, i, addrs, ports, quorum) -> OriginNode:
    """One origin over a STATIC full-mesh ring (max_replica=3: every
    origin owns every digest, so quorum placement is deterministic and
    read-repair applies on any node). Slow health keeps ring membership
    static through the short partition windows these tests arm."""
    return OriginNode(
        store_root=str(tmp_path / f"origin{i}"),
        http_port=ports[i],
        ring=Ring(HostList(static=addrs), max_replica=3),
        self_addr=addrs[i],
        dedup=False,
        quorum=quorum,
        health_interval_seconds=30.0,
    )


async def _herd(tmp_path, quorum, n=3):
    """n origins on fixed ports, retry POLL stopped on each: the tests
    below drive ``retry.run_once()`` by hand so async replication and
    hint replay happen exactly when the scenario says, never racing the
    assertions in between.

    Only node 0 -- the origin every scenario uploads through -- carries
    the quorum config; the replicas keep the shipped ``write_quorum: 1``.
    A replica receiving a quorum push commits through the same path and
    would otherwise cascade its OWN quorum write (harmless in production,
    its push resolves on a stat hit, but it doubles every counter delta
    these tests pin)."""
    ports = [_free_port() for _ in range(n)]
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = []
    for i in range(n):
        node = _node(tmp_path, i, addrs, ports, quorum if i == 0 else None)
        await node.start()
        node.retry.stop()
        nodes.append(node)
    return nodes, addrs, ports


async def _stop_all(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            # Scenario already stopped this node mid-test; teardown
            # must still reach the remaining live ones.
            logging.getLogger("test_quorum").debug(
                "duplicate stop in teardown", exc_info=True
            )


def test_owner_kill_after_quorum_ack_no_lost_blobs(tmp_path):
    asyncio.run(_drive_owner_kill(tmp_path))


async def _drive_owner_kill(tmp_path):
    """THE kill-test from the issue: quorum=2 push, kill the owner right
    after the ack, and the blob must survive -- one replica holds it
    synchronously (the ack waited for it), the other read-repairs from
    that sibling at first GET. Bit-identical both ways, no backend."""
    q = QuorumConfig(write_quorum=2, push_timeout_seconds=10.0)
    nodes, addrs, _ports = await _herd(tmp_path, q)
    try:
        blob = os.urandom(300_000)
        d = Digest.from_bytes(blob)
        # Deterministically partition replica 2 at the push layer, so
        # exactly one replica (node 1) is the synchronous quorum copy.
        failpoints.FAILPOINTS.arm(
            f"origin.quorum.replica.partition@{addrs[2]}", "always"
        )
        before_q = _counter("origin_quorum_writes_total", outcome="quorum")
        before_rr = _counter("origin_read_repairs_total")

        oc = BlobClient(addrs[0])
        await oc.upload(NS, d, blob)
        await oc.close()

        # The ack was replication-gated: the quorum copy is already
        # durable on node 1 at this instant, no background wait.
        assert nodes[1].store.in_cache(d)
        assert not nodes[2].store.in_cache(d)
        assert (
            _counter("origin_quorum_writes_total", outcome="quorum")
            == before_q + 1
        )

        # Kill the owner right after the ack (its pending async
        # replication tasks die with it -- the poll was never running).
        await nodes[0].stop()
        failpoints.FAILPOINTS.disarm_all()

        # Survivor that HAS it serves bit-identical.
        c1 = BlobClient(addrs[1])
        assert await c1.download(NS, d) == blob
        await c1.close()

        # Survivor that MISSES read-repairs from its sibling, then
        # serves bit-identical. No backend exists to fall back to.
        c2 = BlobClient(addrs[2])
        assert await c2.download(NS, d) == blob
        await c2.close()
        assert nodes[2].store.in_cache(d)
        assert _counter("origin_read_repairs_total") == before_rr + 1
    finally:
        await _stop_all(nodes)


def test_total_partition_acks_via_hints_then_replays(tmp_path):
    asyncio.run(_drive_total_partition(tmp_path))


async def _drive_total_partition(tmp_path):
    """Partition wider than the quorum: EVERY replica unreachable at
    commit. The write must still ack (sloppy-quorum availability), the
    unreachable replicas must be durably hinted, and healing the
    partition must converge all copies through hint replay."""
    q = QuorumConfig(write_quorum=2, push_timeout_seconds=10.0)
    nodes, addrs, _ports = await _herd(tmp_path, q)
    try:
        blob = os.urandom(200_000)
        d = Digest.from_bytes(blob)
        failpoints.FAILPOINTS.arm("origin.quorum.replica.partition", "always")
        before_h = _counter("origin_quorum_writes_total", outcome="hinted")
        before_j = _counter("origin_hints_total", state="journaled")
        before_r = _counter("origin_hints_total", state="replayed")

        oc = BlobClient(addrs[0])
        await oc.upload(NS, d, blob)  # must NOT raise: partition != outage
        await oc.close()

        assert (
            _counter("origin_quorum_writes_total", outcome="hinted")
            == before_h + 1
        )
        assert (
            _counter("origin_hints_total", state="journaled") == before_j + 2
        )
        # Both hints are durably journaled, keyed by digest.
        assert nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 2
        assert not nodes[1].store.in_cache(d)
        assert not nodes[2].store.in_cache(d)

        # Heal the partition; replay the hints by hand.
        failpoints.FAILPOINTS.disarm_all()
        await nodes[0].retry.run_once()
        assert nodes[1].store.in_cache(d)
        assert nodes[2].store.in_cache(d)
        assert (
            _counter("origin_hints_total", state="replayed") == before_r + 2
        )
        assert nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 0

        for a in addrs[1:]:
            c = BlobClient(a)
            assert await c.download(NS, d) == blob
            await c.close()
    finally:
        await _stop_all(nodes)


def test_symmetric_link_partition_at_http_layer(tmp_path):
    asyncio.run(_drive_symmetric_partition(tmp_path))


async def _drive_symmetric_partition(tmp_path):
    """Same contract, but the partition is injected where real ones
    live: the HTTP transport (rpc.link.drop@dst blocks every connection
    INTO a host, including the quorum pushes). The fan-out burns its
    deadline budget against dead links, acks hinted, and convergence
    comes from replay once the links return."""
    q = QuorumConfig(write_quorum=2, push_timeout_seconds=1.5)
    nodes, addrs, _ports = await _herd(tmp_path, q)
    try:
        blob = os.urandom(150_000)
        d = Digest.from_bytes(blob)
        failpoints.FAILPOINTS.arm(f"rpc.link.drop@{addrs[1]}", "always")
        failpoints.FAILPOINTS.arm(f"rpc.link.drop@{addrs[2]}", "always")
        before_h = _counter("origin_quorum_writes_total", outcome="hinted")

        oc = BlobClient(addrs[0])
        await oc.upload(NS, d, blob)
        await oc.close()

        assert (
            _counter("origin_quorum_writes_total", outcome="hinted")
            == before_h + 1
        )
        # Both isolated replicas hinted -- whether the fan-out saw them
        # FAIL (connection refused by the fault matrix) or ABANDONED
        # them at the budget, an unmet quorum hints the whole set.
        assert nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 2

        failpoints.FAILPOINTS.disarm_all()
        await nodes[0].retry.run_once()
        assert nodes[1].store.in_cache(d)
        assert nodes[2].store.in_cache(d)
        for a in addrs[1:]:
            c = BlobClient(a)
            assert await c.download(NS, d) == blob
            await c.close()
    finally:
        await _stop_all(nodes)


def test_asymmetric_partition_still_meets_quorum(tmp_path):
    asyncio.run(_drive_asymmetric_partition(tmp_path))


async def _drive_asymmetric_partition(tmp_path):
    """One-way fault: only the link INTO replica 1 is down. Replica 2
    confirms, so the quorum is met and the commit acks as a full quorum
    write -- the degraded replica converges afterwards (via its hint or
    the async replication task; which one wins the race is deliberately
    unasserted, both are correct)."""
    q = QuorumConfig(write_quorum=2, push_timeout_seconds=1.5)
    nodes, addrs, _ports = await _herd(tmp_path, q)
    try:
        blob = os.urandom(150_000)
        d = Digest.from_bytes(blob)
        failpoints.FAILPOINTS.arm(f"rpc.link.drop@{addrs[1]}", "always")
        before_q = _counter("origin_quorum_writes_total", outcome="quorum")

        oc = BlobClient(addrs[0])
        await oc.upload(NS, d, blob)
        await oc.close()

        assert (
            _counter("origin_quorum_writes_total", outcome="quorum")
            == before_q + 1
        )
        assert nodes[2].store.in_cache(d)

        failpoints.FAILPOINTS.disarm_all()

        async def _converged():
            await nodes[0].retry.run_once()
            return nodes[1].store.in_cache(d)

        await _wait_for(_converged, msg="degraded replica to converge")
        for a in addrs[1:]:
            c = BlobClient(a)
            assert await c.download(NS, d) == blob
            await c.close()
    finally:
        await _stop_all(nodes)


def test_hints_replay_across_origin_restart(tmp_path):
    asyncio.run(_drive_hint_restart(tmp_path))


async def _drive_hint_restart(tmp_path):
    """Hints are DURABLE: journal them under a partition, hard-stop the
    owner, bring a fresh process image up over the same store -- the
    hints must still be there and must replay to convergence. This is
    the window a crash-between-ack-and-replay falls into."""
    q = QuorumConfig(write_quorum=2, push_timeout_seconds=10.0)
    nodes, addrs, ports = await _herd(tmp_path, q)
    try:
        blob = os.urandom(200_000)
        d = Digest.from_bytes(blob)
        failpoints.FAILPOINTS.arm("origin.quorum.replica.partition", "always")
        oc = BlobClient(addrs[0])
        await oc.upload(NS, d, blob)
        await oc.close()
        assert nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 2

        # Owner dies with the hints unplayed; partition heals while
        # it is down; a replacement comes up over the same volume.
        await nodes[0].stop()
        failpoints.FAILPOINTS.disarm_all()
        before_r = _counter("origin_hints_total", state="replayed")
        reborn = _node(tmp_path, 0, addrs, ports, q)
        await reborn.start()
        reborn.retry.stop()
        nodes[0] = reborn

        assert reborn.retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 2
        await reborn.retry.run_once()
        assert nodes[1].store.in_cache(d)
        assert nodes[2].store.in_cache(d)
        assert (
            _counter("origin_hints_total", state="replayed") == before_r + 2
        )
        for a in addrs:
            c = BlobClient(a)
            assert await c.download(NS, d) == blob
            await c.close()
    finally:
        await _stop_all(nodes)

"""Metric-catalog lint, runtime half: dynamic names cannot drift either.

The static two-way rule lives in the analyzer now (`metric-catalog`,
kraken_tpu/lint/project.py -- every literal register site must be
cataloged AND every catalog row must name a register site; the tree
gate in tests/test_lint.py runs it). What statics cannot see is a
metric whose name is computed at runtime, so this test keeps the live
half: boot a real agent+origin pair, drive one upload + one pull, and
check every name the REGISTRY actually minted against the SAME
containment contract the static rule uses (`is_cataloged` -- one
shared predicate, so the two halves can never disagree about what
"cataloged" means).

Runs the pair in a SUBPROCESS: the test session's process-global
REGISTRY accumulates names from every suite that ran before this one,
so an in-process walk would lint whatever the test ordering happened to
register. A fresh interpreter registers exactly what a production boot
+ one upload + one pull register.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from kraken_tpu.lint.project import is_cataloged

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PAIR_SCRIPT = r"""
import asyncio, json, os, tempfile
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.utils.httputil import HTTPClient
from kraken_tpu.utils.metrics import REGISTRY

async def main():
    tmp = tempfile.mkdtemp()
    tracker = TrackerNode(announce_interval_seconds=0.1)
    await tracker.start()
    origin = OriginNode(
        store_root=os.path.join(tmp, "o"), tracker_addr=tracker.addr
    )
    await origin.start()
    ring = Ring(HostList(static=[origin.addr]), max_replica=2)
    cluster = ClusterClient(ring)
    tracker.server.origin_cluster = cluster
    origin.ring = ring
    if origin.server:
        origin.server.ring = ring
    agent = AgentNode(
        store_root=os.path.join(tmp, "a"), tracker_addr=tracker.addr
    )
    await agent.start()
    http = HTTPClient()
    blob = os.urandom(500_000)
    d = Digest.from_bytes(blob)
    oc = BlobClient(origin.addr)
    await oc.upload("library/lint", d, blob, chunk_size=100_000)
    await oc.close()
    got = await http.get(
        f"http://{agent.addr}/namespace/library%2Flint/blobs/{d.hex}"
    )
    assert got == blob
    await http.close()
    await agent.stop()
    await origin.stop()
    await cluster.close()
    await tracker.stop()
    print("NAMES=" + json.dumps(REGISTRY.names()))

asyncio.run(main())
"""


def test_every_live_metric_is_in_the_operations_catalog():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _PAIR_SCRIPT],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, (
        f"pair boot failed:\n{proc.stdout}\n{proc.stderr}"
    )
    names_line = [
        line for line in proc.stdout.splitlines() if line.startswith("NAMES=")
    ]
    assert names_line, f"no NAMES line in output:\n{proc.stdout}"
    names = json.loads(names_line[-1][len("NAMES="):])
    assert len(names) >= 20, f"suspiciously few live metrics: {names}"

    with open(os.path.join(REPO, "docs", "OPERATIONS.md")) as f:
        docs = f.read()
    missing = [n for n in names if not is_cataloged(n, docs)]
    assert not missing, (
        "live metrics missing from the docs/OPERATIONS.md catalog "
        f"(add a row per name): {missing}"
    )

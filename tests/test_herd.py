"""Herd integration tests: the minimum end-to-end slice and beyond.

SURVEY.md SS7 "minimum end-to-end slice": origin + tracker + agent,
push a blob into origin's upload API -> metainfo-gen -> agent GET
/namespace/.../blobs/<digest> -> announce -> P2P download from
origin-as-seeder -> piece verify -> byte-identical blob out.

In-process here (tier 4's process-based herd drives the same assembly via
the CLI). Uses the real HTTP APIs end to end, including the origin upload
protocol and the tracker metainfo proxy.
"""

import asyncio
import os
import time

import pytest

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.backend import Manager as BackendManager
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.utils.httputil import HTTPClient


async def build_herd(tmp_path, n_agents=1, backends=None, n_origins=1):
    tracker = TrackerNode(announce_interval_seconds=0.1, peer_ttl_seconds=5.0)
    await tracker.start()
    origins = []
    for i in range(n_origins):
        o = OriginNode(
            store_root=str(tmp_path / f"origin{i}"),
            tracker_addr=tracker.addr,
            backends=backends,
        )
        await o.start()
        origins.append(o)
    ring = Ring(HostList(static=[o.addr for o in origins]), max_replica=2)
    cluster = ClusterClient(ring)
    tracker.server.origin_cluster = cluster
    for o in origins:
        o.ring = ring
        if o.server:
            o.server.ring = ring
    agents = []
    for i in range(n_agents):
        a = AgentNode(
            store_root=str(tmp_path / f"agent{i}"), tracker_addr=tracker.addr
        )
        await a.start()
        agents.append(a)
    return tracker, origins, agents, cluster


async def teardown(tracker, origins, agents, cluster):
    for a in agents:
        await a.stop()
    for o in origins:
        await o.stop()
    await cluster.close()
    await tracker.stop()


def test_e2e_slice_upload_then_agent_pull(tmp_path):
    """The canonical slice: upload via origin HTTP -> pull via agent HTTP."""

    async def main():
        tracker, origins, agents, cluster = await build_herd(tmp_path)
        http = HTTPClient()
        try:
            blob = os.urandom(500_000)
            d = Digest.from_bytes(blob)

            # Push through the origin's chunked upload API.
            oc = BlobClient(origins[0].addr)
            await oc.upload("library/test", d, blob, chunk_size=100_000)

            # Origin generated metainfo at commit.
            mi = await oc.get_metainfo("library/test", d)
            assert mi.digest == d and mi.length == len(blob)

            # Pull via the agent API: triggers tracker metainfo fetch +
            # announce + P2P download from the seeding origin.
            got = await http.get(
                f"http://{agents[0].addr}/namespace/library%2Ftest/blobs/{d.hex}"
            )
            assert got == blob

            # Agent now reports the blob via stat.
            import json

            stat = json.loads(
                await http.get(
                    f"http://{agents[0].addr}/namespace/library%2Ftest/blobs/{d.hex}/stat"
                )
            )
            assert stat["size"] == len(blob)
            await oc.close()
        finally:
            await http.close()
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_multi_agent_pull_and_peer_exchange(tmp_path):
    async def main():
        tracker, origins, agents, cluster = await build_herd(tmp_path, n_agents=3)
        http = HTTPClient()
        try:
            blob = os.urandom(400_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            results = await asyncio.gather(
                *(
                    http.get(f"http://{a.addr}/namespace/ns/blobs/{d.hex}")
                    for a in agents
                )
            )
            assert all(r == blob for r in results)
            await oc.close()
        finally:
            await http.close()
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_backend_miss_refresh_path(tmp_path):
    """Agent pulls a blob the origin does NOT have cached -- origin fills
    from the remote backend on the tracker's metainfo request
    (SURVEY.md SS3.5)."""

    async def main():
        from kraken_tpu.backend.base import make_backend

        backends = BackendManager(
            [{"namespace": ".*", "backend": "file",
              "config": {"root": str(tmp_path / "remote")}}]
        )
        blob = os.urandom(300_000)
        d = Digest.from_bytes(blob)
        # Blob lives only in the remote backend (logical name; the
        # backend owns physical pathing).
        be = make_backend("file", {"root": str(tmp_path / "remote")})
        await be.upload("ns", d.hex, blob)

        tracker, origins, agents, cluster = await build_herd(
            tmp_path, backends=backends
        )
        http = HTTPClient()
        try:
            got = await http.get(
                f"http://{agents[0].addr}/namespace/ns/blobs/{d.hex}"
            )
            assert got == blob
            # Origin cached it on the way through.
            assert origins[0].store.in_cache(d)
        finally:
            await http.close()
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_writeback_to_backend(tmp_path):
    """Committed blobs flow asynchronously origin -> backend."""

    async def main():
        backends = BackendManager(
            [{"namespace": ".*", "backend": "file",
              "config": {"root": str(tmp_path / "remote")}}]
        )
        tracker, origins, agents, cluster = await build_herd(
            tmp_path, backends=backends, n_agents=0
        )
        try:
            blob = os.urandom(100_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            # Drive the retry queue until the writeback lands.
            for _ in range(50):
                await origins[0].retry.run_once()
                from kraken_tpu.backend.base import make_backend

                be = make_backend("file", {"root": str(tmp_path / "remote")})
                try:
                    got = await be.download("ns", d.hex)
                    assert got == blob
                    break
                except Exception:
                    await asyncio.sleep(0.05)
            else:
                pytest.fail("writeback never landed")
            await oc.close()
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_origin_replication_to_ring_peer(tmp_path):
    """Upload to one origin replicates to the other ring owner."""

    async def main():
        tracker, origins, agents, cluster = await build_herd(
            tmp_path, n_agents=0, n_origins=2
        )
        try:
            # ring + self_addr already set post-start by build_herd; make
            # sure each origin knows itself.
            for o in origins:
                o.server.self_addr = o.addr
            blob = os.urandom(150_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            for _ in range(100):
                await origins[0].retry.run_once()
                if origins[1].store.in_cache(d):
                    break
                await asyncio.sleep(0.05)
            assert origins[1].store.in_cache(d), "replication never landed"
            await oc.close()
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_origin_restart_regenerates_lost_metainfo(tmp_path):
    """A blob whose metainfo sidecar is lost (partial restore, manual
    cleanup) is re-hashed and re-seeded at origin startup -- it must not
    stay invisible to the swarm until explicitly touched."""

    async def main():
        from kraken_tpu.origin.metainfogen import TorrentMetaMetadata

        tracker, origins, agents, cluster = await build_herd(tmp_path)
        blob = os.urandom(300_000)
        d = Digest.from_bytes(blob)
        try:
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            await oc.close()

            # Restart the origin with its sidecar gone. Same port: a
            # production origin has a fixed address, and the herd's ring
            # still lists it.
            store_root = origins[0].store.root
            old_port = origins[0].http_port
            await origins[0].stop()
            reborn = OriginNode(
                store_root=store_root, tracker_addr=tracker.addr,
                http_port=old_port,
            )
            reborn.store.delete_metadata(d, TorrentMetaMetadata)
            await reborn.start()
            origins[0] = reborn

            # The background reseed must hash + seed it BEFORE any agent
            # or tracker traffic could trigger on-demand regeneration
            # (which would mask a broken reseed).
            assert reborn._reseed_task is not None
            await reborn._reseed_task
            assert reborn.generator.get_cached(d) is not None
            http = HTTPClient()
            got = await http.get(
                f"http://{agents[0].addr}/namespace/ns/blobs/{d.hex}"
            )
            assert got == blob
            await http.close()
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_origin_restart_skips_corrupt_blob(tmp_path):
    """Restore corruption: a cached blob whose bytes no longer match its
    digest (and whose sidecar is lost) must NOT be reseeded -- regenerated
    piece hashes would make every agent accept wrong bytes as d."""

    async def main():
        from kraken_tpu.origin.metainfogen import TorrentMetaMetadata

        tracker, origins, agents, cluster = await build_herd(
            tmp_path, n_agents=0
        )
        blob = os.urandom(200_000)
        d = Digest.from_bytes(blob)
        try:
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            await oc.close()

            store_root = origins[0].store.root
            old_port = origins[0].http_port
            await origins[0].stop()
            reborn = OriginNode(
                store_root=store_root, tracker_addr=tracker.addr,
                http_port=old_port,
            )
            reborn.store.delete_metadata(d, TorrentMetaMetadata)
            with await asyncio.to_thread(
                open, reborn.store.cache_path(d), "r+b"
            ) as f:
                f.seek(1000)
                f.write(b"\x00" * 64)  # corrupt in place
            # Model true bit-rot: damage without an mtime bump. (A fresh
            # mtime past the clean-shutdown stamp is the CRASH-WINDOW
            # case, which startup fsck now quarantines before reseed ever
            # sees the blob -- covered in tests/test_recovery.py; here we
            # prove the reseed path's own verify still refuses to serve
            # rot that fsck's stamp heuristic cannot see.)
            old = time.time() - 3600
            os.utime(reborn.store.cache_path(d), (old, old))
            await reborn.start()
            origins[0] = reborn

            assert reborn.fsck_report is not None and not reborn.fsck_report.quarantined
            assert reborn._reseed_task is not None
            await reborn._reseed_task
            # Skipped: no regenerated sidecar, not seeded.
            assert reborn.generator.get_cached(d) is None
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_stat_reads_through_to_backend_after_eviction(tmp_path):
    """HEAD/stat and GET must agree: a blob evicted from the origin cache
    but durable in the backend stats 200 (cheap backend stat, no restore),
    because docker HEADs blobs to decide whether to re-push them."""

    async def main():
        backends = BackendManager(
            [{"namespace": ".*", "backend": "file",
              "config": {"root": str(tmp_path / "remote")}}]
        )
        tracker, origins, agents, cluster = await build_herd(
            tmp_path, n_agents=0, backends=backends
        )
        try:
            blob = os.urandom(120_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload("ns", d, blob)
            # Writeback to the backend, then evict the cache copy.
            for _ in range(50):
                await origins[0].retry.run_once()
                from kraken_tpu.backend.base import make_backend

                be = make_backend("file", {"root": str(tmp_path / "remote")})
                try:
                    await be.download("ns", d.hex)
                    break
                except Exception:
                    await asyncio.sleep(0.05)
            origins[0].store.delete_cache_file(d)
            assert not origins[0].store.in_cache(d)

            info = await oc.stat("ns", d)
            assert info is not None and info.size == len(blob)
            # And the bytes did NOT get restored by the stat.
            assert not origins[0].store.in_cache(d)
            # Repair semantics: local_only means "do YOU cache the bytes",
            # so the evicted copy answers 404 even though it is durable.
            assert await oc.stat("ns", d, local_only=True) is None
            # GET still restores + serves.
            got = await oc.download("ns", d)
            assert got == blob
            await oc.close()
        finally:
            await teardown(tracker, origins, agents, cluster)

    asyncio.run(main())


def test_writeback_legacy_keys_migrate_on_open(tmp_path):
    """Tasks persisted by an earlier build under '{namespace}:{hex}' keys
    must become visible to the digest-first prefix scans the unpin logic
    uses -- otherwise the eviction pin is released while a legacy-keyed
    writeback of the same blob is still queued."""

    async def main():
        from kraken_tpu.origin.writeback import KIND, WritebackExecutor
        from kraken_tpu.persistedretry import Manager as RetryManager, Task
        from kraken_tpu.persistedretry.manager import TaskStore
        from kraken_tpu.store import CAStore

        blob = os.urandom(1000)
        d = Digest.from_bytes(blob)
        ts = TaskStore(str(tmp_path / "retry.db"))
        # Simulate the previous build's key ordering.
        ts.add(Task(kind=KIND, key=f"ns:{d.hex}",
                    payload={"namespace": "ns", "digest": d.hex}))
        # Plus a duplicate already present in canonical form.
        ts.add(Task(kind=KIND, key=f"{d.hex}:other",
                    payload={"namespace": "other", "digest": d.hex}))
        ts.add(Task(kind=KIND, key=f"other:{d.hex}",
                    payload={"namespace": "other", "digest": d.hex}))

        retry = RetryManager(ts)
        backends = BackendManager(
            [{"namespace": ".*", "backend": "file",
              "config": {"root": str(tmp_path / "remote")}}]
        )
        store = CAStore(str(tmp_path / "store"))
        WritebackExecutor(store, backends, retry)
        # Legacy row rewritten; legacy duplicate of a canonical row dropped.
        assert ts.count_pending(KIND, f"{d.hex}:") == 2
        assert {t.key for t in ts.all_pending()} == {
            f"{d.hex}:ns", f"{d.hex}:other"
        }

    asyncio.run(main())

"""CAStore / metadata / cleanup tests. SURVEY.md SS4 tier 1."""

import os
import threading

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.store import CAStore, FileExistsInCacheError, PieceStatusMetadata
from kraken_tpu.store.castore import DigestMismatchError, UploadNotFoundError
from kraken_tpu.store.cleanup import CleanupConfig, CleanupManager
from kraken_tpu.store.metadata import PersistMetadata, TTIMetadata, pin, unpin


@pytest.fixture
def store(tmp_path):
    return CAStore(str(tmp_path / "store"))


def put(store, data: bytes) -> Digest:
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    store.commit_upload(uid, d)
    return d


def test_upload_commit_read(store):
    data = os.urandom(10000)
    d = put(store, data)
    assert store.in_cache(d)
    assert store.read_cache_file(d) == data
    assert store.cache_size(d) == len(data)
    assert b"".join(store.stream_cache_file(d)) == data


def test_chunked_out_of_order_upload(store):
    data = os.urandom(9000)
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 5000, data[5000:])
    store.write_upload_chunk(uid, 0, data[:5000])
    store.commit_upload(uid, d)
    assert store.read_cache_file(d) == data


def test_commit_verifies_digest(store):
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, b"hello")
    wrong = Digest.from_bytes(b"other")
    with pytest.raises(DigestMismatchError):
        store.commit_upload(uid, wrong)
    assert not store.upload_exists(uid)  # poisoned upload removed


def test_duplicate_commit_raises_exists(store):
    data = b"same content"
    d = put(store, data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    with pytest.raises(FileExistsInCacheError):
        store.commit_upload(uid, d)
    assert store.read_cache_file(d) == data


def test_unknown_upload(store):
    with pytest.raises(UploadNotFoundError):
        store.write_upload_chunk("nope", 0, b"x")
    with pytest.raises(UploadNotFoundError):
        store.commit_upload("nope", Digest.from_bytes(b"x"))


def test_missing_cache_file(store):
    with pytest.raises(KeyError):
        store.read_cache_file(Digest.from_bytes(b"missing"))


def test_create_cache_file_stream(store):
    data = os.urandom(100_000)
    d = Digest.from_bytes(data)
    store.create_cache_file(d, iter([data[:40_000], data[40_000:]]))
    assert store.read_cache_file(d) == data
    # idempotent
    store.create_cache_file(d, iter([data]))


def test_allocate_and_metadata_roundtrip(store):
    d = Digest.from_bytes(b"torrent target")
    path = store.allocate_partial_file(d, 1 << 16)
    assert os.path.getsize(path) == 1 << 16
    assert store.has_partial(d) and not store.in_cache(d)

    md = PieceStatusMetadata(10)
    md.set(3)
    md.set(9)
    store.set_metadata(d, md)
    got = store.get_metadata(d, PieceStatusMetadata)
    assert got.has(3) and got.has(9) and not got.has(0)
    assert got.missing() == [0, 1, 2, 4, 5, 6, 7, 8]
    assert not got.complete()
    for i in range(10):
        got.set(i)
    assert got.complete() and got.count() == 10


def test_metadata_absent_returns_none(store):
    d = put(store, b"blob")
    assert store.get_metadata(d, PieceStatusMetadata) is None


def test_delete_removes_data_and_metadata(store):
    d = put(store, b"to delete")
    store.set_metadata(d, TTIMetadata(123.0))
    store.delete_cache_file(d)
    assert not store.in_cache(d)
    assert store.get_metadata(d, TTIMetadata) is None


def test_list_and_disk_usage(store):
    digests = {put(store, os.urandom(1000)) for _ in range(5)}
    assert set(store.list_cache_digests()) == digests
    assert store.disk_usage_bytes() >= 5000


def test_concurrent_same_digest_commit(store):
    """CAS: racing commits of identical content -> one winner, no error
    escapes, content intact."""
    data = os.urandom(5000)
    d = Digest.from_bytes(data)
    errs = []

    def worker():
        uid = store.create_upload()
        store.write_upload_chunk(uid, 0, data)
        try:
            store.commit_upload(uid, d)
        except FileExistsInCacheError:
            pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert store.read_cache_file(d) == data


# -- cleanup ----------------------------------------------------------------


def test_cleanup_tti_eviction(store):
    mgr = CleanupManager(store, CleanupConfig(tti_seconds=100))
    d_old = put(store, b"old blob")
    d_new = put(store, b"new blob")
    store.set_metadata(d_old, TTIMetadata(1000.0))
    store.set_metadata(d_new, TTIMetadata(2000.0))
    evicted = mgr.run_once(now=1500.0)
    assert evicted == [d_old]
    assert not store.in_cache(d_old) and store.in_cache(d_new)


def test_cleanup_watermark_lru(store):
    mgr = CleanupManager(
        store,
        CleanupConfig(tti_seconds=0, high_watermark_bytes=2500, low_watermark_bytes=1500),
    )
    ds = [put(store, os.urandom(1000)) for _ in range(3)]
    for i, d in enumerate(ds):
        store.set_metadata(d, TTIMetadata(float(i)))
    evicted = mgr.run_once(now=10.0)
    # Evicts oldest-accessed until <= low watermark: drops ds[0], ds[1].
    assert evicted == [ds[0], ds[1]]
    assert store.in_cache(ds[2])


def test_cleanup_respects_persist(store):
    mgr = CleanupManager(store, CleanupConfig(tti_seconds=10))
    d = put(store, b"writeback pending")
    store.set_metadata(d, TTIMetadata(0.0))
    store.set_metadata(d, PersistMetadata(True))
    assert mgr.run_once(now=1e9) == []
    assert store.in_cache(d)
    # Unmark -> evictable.
    store.set_metadata(d, PersistMetadata(False))
    assert mgr.run_once(now=1e9) == [d]


def test_persist_pins_are_independent(tmp_path):
    """Two subsystems pin the same blob; one unpin must not release the
    other's (writeback landing while replication still retries)."""

    store = CAStore(str(tmp_path))
    data = b"pinned blob"
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    store.commit_upload(uid, d)

    pin(store, d, "writeback")
    pin(store, d, "replicate")
    assert store.get_metadata(d, PersistMetadata).persist
    unpin(store, d, "writeback")
    assert store.get_metadata(d, PersistMetadata).persist  # replicate holds
    unpin(store, d, "replicate")
    assert not store.get_metadata(d, PersistMetadata).persist

    # Legacy boolean records still deserialize.
    assert PersistMetadata.deserialize(b"1").persist
    assert not PersistMetadata.deserialize(b"0").persist
    back = PersistMetadata.deserialize(
        PersistMetadata({"a", "b"}).serialize()
    )
    assert back.reasons == {"a", "b"}


def test_pending_replication_pins_until_done(tmp_path):
    """Upload with an unreachable ring peer: the blob must be pinned (a
    cleanup sweep cannot evict the cluster's only copy) until replication
    lands."""
    import asyncio

    from kraken_tpu.assembly import OriginNode
    from kraken_tpu.origin.client import BlobClient
    from kraken_tpu.placement import HostList, Ring

    async def main():
        import socket

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        ports = [free_port(), free_port()]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        node = OriginNode(
            store_root=str(tmp_path / "o"),
            http_port=ports[0],
            ring=Ring(HostList(static=addrs), max_replica=2),
            self_addr=addrs[0],
            dedup=False,
            health_interval_seconds=3600,  # keep the dead peer in the ring
        )
        await node.start()
        oc = BlobClient(node.addr)
        try:
            data = b"x" * 50_000
            d = Digest.from_bytes(data)
            await oc.upload("ns", d, data)
            md = node.store.get_metadata(d, PersistMetadata)
            assert md is not None and md.persist, (
                "blob not pinned while replication to the dead peer pends"
            )
            # Aggressive TTI sweep must spare it.
            mgr = CleanupManager(
                node.store, CleanupConfig(tti_seconds=0.000001)
            )
            await asyncio.sleep(0.01)
            assert mgr.run_once() == []
            assert node.store.in_cache(d)
        finally:
            await oc.close()
            await node.stop()

    asyncio.run(main())

"""Continuous profiling plane (utils/profiler.py).

What must hold, per docs/OPERATIONS.md "Continuous profiling":

- the sampler attributes a known busy function correctly (folded-stack
  form, plane tags), starts/stops idempotently, and live-reloads;
- the loop-lag monitor observes a deliberately blocking callback on
  the ``loop_lag_seconds`` histogram AND names the blocking frame in
  its structured WARN (the sampler's concurrent main-thread stack);
- the heap differ reports the allocation site that actually grew;
- worker-shard samples ship home over the shardpool control channel
  through a REAL 2-worker pull, so one /debug/pprof/profile covers
  main loop plus forked shards;
- the flight-recorder triggers (breaker trip et al.) capture a profile
  window beside the trace dump;
- `kraken-tpu flame` folds dumps and exits non-zero on unparseable or
  truncated files (the CI gate);
- the resource sentinel's `loop_lag` budget kind breaches on a bad p99;
- torrent_summary carries the per-pull stage split.
"""

from __future__ import annotations

import asyncio
import glob
import json
import logging
import os
import threading
import time

import pytest

from kraken_tpu.utils import trace
from kraken_tpu.utils.metrics import REGISTRY
from kraken_tpu.utils.profiler import (
    HEAP,
    PROFILER,
    LoopLagMonitor,
    ProfilerConfig,
    SamplingProfiler,
    classify_plane,
    load_profile_dumps,
)

NS = "library/profiler-test"


@pytest.fixture(autouse=True)
def _profiler_isolation():
    """The PROFILER is process-global (like the TRACER): snapshot its
    config/node, reset samples around every test, and restore after so
    rates chosen here never leak into other suites."""
    cfg0, node0 = PROFILER.config, PROFILER.node
    hook0 = trace.TRACER.on_trigger
    PROFILER.reset()
    PROFILER._last_dump.clear()
    yield
    PROFILER.node = node0
    PROFILER.apply(cfg0)
    trace.TRACER.on_trigger = hook0
    PROFILER.reset()
    PROFILER._last_dump.clear()


# -- config -----------------------------------------------------------------

def test_profiler_config_rejects_unknown_keys_and_bad_rates():
    with pytest.raises(ValueError):
        ProfilerConfig.from_dict({"herz": 10})
    with pytest.raises(ValueError):
        ProfilerConfig.from_dict({"hz": 0})
    with pytest.raises(ValueError):
        ProfilerConfig.from_dict({"hz": 1000})
    with pytest.raises(ValueError):
        ProfilerConfig.from_dict({"loop_lag_interval_seconds": 0})
    cfg = ProfilerConfig.from_dict({"hz": 97, "enabled": True})
    assert cfg.hz == 97


# -- plane tagging ----------------------------------------------------------

def test_plane_classification_rules():
    assert classify_plane(["conn.py:_recv_loop", "wire.py:recv_message"]) \
        == "pump"
    assert classify_plane(
        ["dispatch.py:_on_payload", "storage.py:write_piece",
         "storage.py:_write_at"]
    ) == "pwrite"
    assert classify_plane(["hasher.py:hash_batch"]) == "verify"
    assert classify_plane(["shardpool.py:_serve_piece_inner"]) == "serve"
    assert classify_plane(["scheduler.py:_announce_once"]) == "dispatch"
    # The leaf decides idleness even when a plane frame sits above it.
    assert classify_plane(
        ["base_events.py:_run_once", "selectors.py:select"]
    ) == "idle"
    assert classify_plane(["mymodule.py:work"]) == "other"


# -- the sampler ------------------------------------------------------------

def _burn_the_cpu(stop: threading.Event) -> None:
    x = 0
    while not stop.is_set():
        x = (x * 31 + 7) % 1000003


def test_sampler_folds_a_known_busy_function():
    prof = SamplingProfiler(ProfilerConfig(hz=200, window_seconds=5.0))
    stop = threading.Event()
    t = threading.Thread(target=_burn_the_cpu, args=(stop,),
                         name="burner", daemon=True)
    t.start()
    prof.start()
    try:
        time.sleep(0.5)
    finally:
        prof.stop()
        stop.set()
        t.join(1.0)
    folded = prof.folded()
    assert folded, "sampler collected nothing"
    burner = [
        (s, c) for s, c in folded
        if s.startswith("burner;") and "_burn_the_cpu" in s
    ]
    assert burner, f"busy function never sampled: {folded[:5]}"
    # ~100 expected at 200 Hz over 0.5 s; anything >= 20 proves the
    # attribution (shared-core rigs starve the sampler thread).
    assert sum(c for _s, c in burner) >= 20
    # Folded form: thread;root;...;leaf with file:func frames.
    stack = burner[0][0]
    assert ";" in stack and ":" in stack.split(";", 1)[1]


def test_sampler_start_stop_idempotent_and_live_reload():
    prof = SamplingProfiler(ProfilerConfig(hz=50))
    assert not prof.running
    prof.start()
    prof.start()  # idempotent
    assert prof.running
    thread0 = prof._thread
    # Live reload to a new rate restarts the thread; disabling stops it.
    prof.apply(ProfilerConfig(hz=100))
    assert prof.running and prof._thread is not thread0
    prof.apply(ProfilerConfig(enabled=False))
    assert not prof.running
    prof.apply(ProfilerConfig(hz=100))
    assert prof.running
    prof.stop()
    prof.stop()  # idempotent
    assert not prof.running


def test_node_reload_applies_profiling_section(tmp_path):
    """SIGHUP path: AgentNode.reload({'profiling': ...}) swaps the
    process-global sampler's rate and the loop-lag knobs live."""
    from kraken_tpu.assembly import AgentNode

    async def run():
        agent = AgentNode(
            store_root=str(tmp_path / "a"), tracker_addr="",
            profiling={"hz": 31},
        )
        await agent.start()
        try:
            assert PROFILER.running and PROFILER.config.hz == 31
            assert agent.loop_monitor is not None
            agent.reload({"profiling": {
                "hz": 59, "loop_lag_threshold_seconds": 0.9,
            }})
            assert PROFILER.config.hz == 59
            assert agent.loop_monitor.config.loop_lag_threshold_seconds \
                == 0.9
            # dump_dir defaulted beside the trace dumps.
            assert agent.profiling_config.dump_dir.endswith("traces")
            # Disabling live stops BOTH halves (sampler + heartbeat)
            # and unhooks the sentinel's loop_lag probe; re-enabling
            # brings them all back -- the toggle must govern the whole
            # plane, not just the sampler thread.
            agent.reload({"profiling": {"enabled": False}})
            assert not PROFILER.running
            assert agent.loop_monitor is None
            assert agent.sentinel.loop_lag_probe is None
            agent.reload({"profiling": {"hz": 41}})
            assert PROFILER.running and PROFILER.config.hz == 41
            assert agent.loop_monitor is not None
            assert agent.sentinel.loop_lag_probe is not None
        finally:
            await agent.stop()

    asyncio.run(run())


def test_plane_cumulative_survives_window_rotation():
    """Regression: the per-pull plane_split baselines against the
    CUMULATIVE plane counter, not the ring -- the ring rotates windows
    out, so a ring-based delta goes negative/empty on any process up
    longer than the ring span. With a tiny ring, the cumulative count
    must keep every sample the ring already dropped."""
    prof = SamplingProfiler(ProfilerConfig(
        hz=200, window_seconds=0.05, keep_windows=2,
    ))
    stop = threading.Event()
    t = threading.Thread(target=_burn_the_cpu, args=(stop,), daemon=True)
    t.start()
    prof.start()
    try:
        time.sleep(0.6)
        cum_mid = sum(prof.plane_cumulative().values())
        time.sleep(0.2)
    finally:
        prof.stop()
        stop.set()
        t.join(1.0)
    ring = sum(prof.plane_totals().values())
    cum = sum(prof.plane_cumulative().values())
    assert cum >= cum_mid  # monotonic
    # ~0.8 s of samples vs a <=0.1 s ring: rotation dropped most of
    # the ring, the cumulative counter kept everything.
    assert cum > ring, (cum, ring)


# -- loop lag ---------------------------------------------------------------

def _block_the_loop_for(seconds: float) -> None:
    time.sleep(seconds)  # deliberately synchronous: the stall under test


def test_loop_lag_detects_blocking_callback(caplog):
    async def run():
        cfg = ProfilerConfig(
            hz=200,
            loop_lag_interval_seconds=0.05,
            loop_lag_threshold_seconds=0.2,
        )
        PROFILER.apply(cfg)
        mon = LoopLagMonitor("lag-test", cfg)
        mon.start()
        try:
            await asyncio.sleep(0.2)  # a few healthy ticks
            _block_the_loop_for(0.5)
            await asyncio.sleep(0.2)  # let the stalled tick land
        finally:
            mon.stop()
        return mon

    stalls0 = REGISTRY.counter("loop_lag_stalls_total").value(
        component="lag-test"
    )
    with caplog.at_level(logging.WARNING, logger="kraken.profiler"):
        mon = asyncio.run(run())
    snap = mon.snapshot()
    assert snap["stalls"] >= 1, snap
    assert snap["max_s"] >= 0.3, snap
    assert REGISTRY.counter("loop_lag_stalls_total").value(
        component="lag-test"
    ) > stalls0
    assert REGISTRY.histogram("loop_lag_seconds").count(
        component="lag-test"
    ) >= 3
    # The WARN names the blocking frame: the sampler caught the main
    # thread inside the synchronous block.
    warns = [r for r in caplog.records if "event loop stalled" in r.msg]
    assert warns, "no stall WARN logged"
    blame = getattr(warns[-1], "blame", "")
    assert "_block_the_loop_for" in blame, blame
    assert "_block_the_loop_for" in (snap["last_blame"] or "")


def test_loop_lag_p99_feeds_the_sentinel_budget():
    """Satellite: `resources: loop_lag_p99_seconds` is a budget kind --
    a wedged loop breaches as kind="loop_lag" and respects the same
    sustained-breach drain latch as every other budget."""
    from kraken_tpu.utils.resources import ResourceSentinel, ResourcesConfig

    fired: list[list[str]] = []

    async def run():
        sentinel = ResourceSentinel(
            "lagbudget",
            ResourcesConfig(
                loop_lag_p99_seconds=0.05, breach_streak=2,
                drain_on_breach=True,
            ),
            loop_lag_probe=lambda: 0.4,
            on_sustained_breach=fired.append,
        )
        try:
            s1 = await sentinel.sample()
            s2 = await sentinel.sample()
            s3 = await sentinel.sample()
        finally:
            sentinel.stop()
        return s1, s2, s3

    c = REGISTRY.counter("resource_budget_breaches_total")
    before = c.value(kind="loop_lag")
    s1, s2, s3 = asyncio.run(run())
    assert "loop_lag" in s1["breached"]
    assert s1["loop_lag_p99"] == 0.4
    assert c.value(kind="loop_lag") >= before + 3
    # Latched: the sustained hook fired once, not per sample.
    assert fired == [["loop_lag"]]

    async def healthy():
        sentinel = ResourceSentinel(
            "lagbudget2",
            ResourcesConfig(loop_lag_p99_seconds=0.05),
            loop_lag_probe=lambda: 0.001,
        )
        try:
            return await sentinel.sample()
        finally:
            sentinel.stop()

    assert "loop_lag" not in asyncio.run(healthy())["breached"]


# -- heap diff --------------------------------------------------------------

def test_heap_diff_reports_the_growing_site():
    HEAP.stop()
    try:
        assert HEAP.diff()["status"] == "baseline"  # first call baselines
        hoard = [bytes(1024) for _ in range(3000)]  # ~3 MB at THIS line
        doc = HEAP.diff(top_n=5)
        assert doc["status"] == "diff"
        assert doc["traced_current_bytes"] > 0
        top = doc["top"]
        assert top, "no growth sites reported"
        assert any("test_profiler.py" in row["site"] for row in top), top
        assert top[0]["size_diff_bytes"] > 1 << 20
        del hoard
    finally:
        HEAP.stop()
    import tracemalloc

    assert not tracemalloc.is_tracing()


# -- dumps + the flame CLI --------------------------------------------------

def _sampled_profiler(tmp_path) -> None:
    """Point the global profiler at a dump dir and give it samples."""
    PROFILER.apply(ProfilerConfig(hz=200, dump_dir=str(tmp_path)))
    PROFILER.node = "testnode"
    time.sleep(0.15)


def test_dump_and_flame_roundtrip(tmp_path, capsys):
    from kraken_tpu.cli import run_flame_tool

    _sampled_profiler(tmp_path)
    PROFILER.record_foreign(
        "testnode/shard0",
        [["MainThread;shardpool.py:_serve_piece_inner", 7]],
        {"serve": 7},
    )
    path = PROFILER.dump("manual", "roundtrip")
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        header = json.loads(f.readline())
        body = [json.loads(ln) for ln in f]
    assert header["profile"] == "manual"
    assert header["stacks"] == len(body)
    assert any(row["node"] == "testnode/shard0" for row in body)

    assert run_flame_tool([path]) == 0
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    done = json.loads(lines[-1])
    assert done["event"] == "flame_done" and done["errors"] == 0
    assert done["stacks"] == header["stacks"]
    # The collapse carries node-stamped folded stacks, shards included.
    assert any(ln.startswith("testnode/shard0;") for ln in lines[:-1])
    assert "serve" in done["planes"]


def test_flame_gates_on_truncated_and_garbage_files(tmp_path, capsys):
    from kraken_tpu.cli import run_flame_tool

    _sampled_profiler(tmp_path)
    path = PROFILER.dump("manual")
    assert path is not None

    # Truncated: drop the last stack line the header promised.
    truncated = str(tmp_path / "truncated.jsonl")
    with open(path) as f:
        lines = f.readlines()
    with open(truncated, "w") as f:
        f.writelines(lines[:-1])
    assert run_flame_tool([truncated]) == 1
    out = capsys.readouterr().out
    assert "truncated" in out

    # Unparseable line inside an otherwise-valid dump: exit 1, not crash.
    garbled = str(tmp_path / "garbled.jsonl")
    with open(garbled, "w") as f:
        f.write(lines[0])
        f.write("%%% not json %%%\n")
        f.writelines(lines[1:])
    assert run_flame_tool([garbled]) == 1
    capsys.readouterr()

    # Nothing usable at all (no header): usage-grade exit 3.
    garbage = str(tmp_path / "garbage.jsonl")
    with open(garbage, "w") as f:
        f.write("not a dump\n")
    assert run_flame_tool([garbage]) == 3
    assert run_flame_tool([str(tmp_path / "absent.jsonl")]) == 3
    capsys.readouterr()

    # loader surface: errors name the file.
    _stacks, _planes, errors = load_profile_dumps([truncated])
    assert errors and "truncated" in errors[0]


def test_breaker_trip_captures_a_profile_window(tmp_path):
    """The PR-8 flight-recorder triggers now carry STACKS: a breaker
    trip writes profile-breaker_trip-*.jsonl beside the trace dump,
    throttled per trigger kind."""
    from kraken_tpu.placement.healthcheck import PassiveFilter

    dump_dir = str(tmp_path / "traces")
    trace.TRACER.apply(
        trace.TraceConfig(sample_rate=1.0, dump_dir=dump_dir)
    )
    PROFILER.apply(ProfilerConfig(hz=200, dump_dir=dump_dir))
    trace.TRACER.on_trigger = PROFILER.trigger_capture
    time.sleep(0.1)  # give the sampler a window
    with trace.span("rpc.download", addr="origin9:7610"):
        pass
    try:
        pf = PassiveFilter(fail_threshold=1, name="profiler-test")
        pf.failed("origin9:7610")
        files = glob.glob(os.path.join(dump_dir, "profile-breaker_trip-*"))
        assert len(files) == 1, "breaker trip captured no profile"
        with open(files[0]) as f:
            header = json.loads(f.readline())
        assert header["profile"] == "breaker_trip"
        assert header["samples"] > 0
        # Throttled: a second trip inside the floor adds no file.
        pf2 = PassiveFilter(fail_threshold=1, name="profiler-test-2")
        pf2.failed("origin9:7610")
        assert len(
            glob.glob(os.path.join(dump_dir, "profile-breaker_trip-*"))
        ) == 1
    finally:
        trace.TRACER.apply(trace.TraceConfig())
        trace.TRACER.recorder.clear()
        trace.TRACER._last_dump.clear()


# -- worker-shard aggregation (a real 2-worker pull) ------------------------

def test_worker_samples_aggregate_through_2worker_pull(tmp_path):
    """Forked seed-serve shards restart their own sampler and ship
    folded-stack deltas home over the control channel: after a real
    pull with data_plane_workers=2, the parent's profile surface holds
    shard-stamped samples -- one collapse covers the whole node."""
    from tests.test_shardpool import FakeTracker, _metainfo, make_sched

    import numpy as np

    async def run():
        PROFILER.apply(ProfilerConfig(hz=97))
        PROFILER.node = "origin"
        blob = np.random.default_rng(5).integers(
            0, 256, size=4 << 20, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 256 << 10)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        origin, _ostore = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=2
        )
        agent, astore = make_sched(tmp_path, "agent", tracker)
        await origin.start()
        try:
            origin.seed(mi, NS)
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, mi.digest), 60)
            finally:
                await agent.stop()
            with await asyncio.to_thread(
                open, astore.cache_path(mi.digest), "rb"
            ) as f:
                assert await asyncio.to_thread(f.read) == blob
            # Shards ship on the 0.25 s stats tick; wait for samples to
            # come home (their idle loop samples too, so this converges
            # even when the serves themselves were fast).
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if PROFILER.snapshot()["foreign_samples"]:
                    break
                await asyncio.sleep(0.1)
        finally:
            await origin.stop()
        return PROFILER.snapshot()

    snap = asyncio.run(run())
    foreign = snap["foreign_samples"]
    assert foreign, "no worker-shard samples ever shipped home"
    assert all("/shard" in node for node in foreign), foreign
    # The collapse prefixes shard stacks with their node stamp (the
    # shard suffix is the stable part -- the prefix is whatever node
    # name this process's tracer carried when the worker forked).
    assert any(
        "/shard" in stack.split(";", 1)[0]
        for stack, _c in PROFILER.folded()
    )


# -- the mux surfaces -------------------------------------------------------

def test_debug_pprof_surfaces_live_on_agent(tmp_path):
    from kraken_tpu.assembly import AgentNode
    from kraken_tpu.utils.httputil import HTTPClient

    async def run():
        agent = AgentNode(
            store_root=str(tmp_path / "a"), tracker_addr="",
            profiling={"hz": 97},
        )
        await agent.start()
        http = HTTPClient()
        try:
            await asyncio.sleep(0.3)
            # profile: folded text default, JSON on ?format=json.
            folded = (await http.get(
                f"http://{agent.addr}/debug/pprof/profile"
            )).decode()
            assert folded.strip(), "empty profile"
            assert all(
                ln.rsplit(" ", 1)[1].isdigit()
                for ln in folded.strip().splitlines()
            )
            snap = json.loads(await http.get(
                f"http://{agent.addr}/debug/pprof/profile?format=json"
            ))
            assert snap["running"] and snap["hz"] == 97
            assert sum(snap["planes"].values()) > 0
            # heap: baseline then diff, stop releases tracemalloc.
            assert json.loads(await http.get(
                f"http://{agent.addr}/debug/pprof/heap"
            ))["status"] == "baseline"
            assert json.loads(await http.get(
                f"http://{agent.addr}/debug/pprof/heap"
            ))["status"] == "diff"
            assert json.loads(await http.get(
                f"http://{agent.addr}/debug/pprof/heap?stop=1"
            ))["status"] == "stopped"
            # looplag: this node's monitor reports percentiles.
            lag = json.loads(await http.get(
                f"http://{agent.addr}/debug/pprof/looplag"
            ))
            mine = [
                m for m in lag["monitors"].values()
                if m["component"] == "agent"
            ]
            assert mine and mine[0]["ticks"] >= 1
            # stacks: the satellite census section is in the dump.
            stacks = (await http.get(
                f"http://{agent.addr}/debug/stacks"
            )).decode()
            assert "asyncio task census" in stacks
            assert "assembly.py" in stacks or "_cleanup_loop" in stacks \
                or "LoopLagMonitor" in stacks or "_loop" in stacks
        finally:
            await http.close()
            await agent.stop()

    asyncio.run(run())


# -- stage split (satellite) ------------------------------------------------

def test_torrent_summary_carries_stage_split(tmp_path):
    """The per-pull stage-timing split rides torrent_summary: plan
    (metainfo fetch) and dial (handshake) from the scheduler, piece
    wait from request->payload gaps, verify/write walls from the
    torrent's accumulators. Cumulative stage costs, not a timeline."""
    from kraken_tpu.p2p.networkevent import Producer
    from tests.test_shardpool import FakeTracker, _metainfo, make_sched

    async def run():
        blob = os.urandom(2 << 20)
        mi = _metainfo(blob, 256 << 10)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        origin, _ostore = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob]
        )
        agent, astore = make_sched(tmp_path, "agent", tracker)
        events = Producer("leecher")
        agent.events = events
        await origin.start()
        try:
            origin.seed(mi, NS)
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, mi.digest), 30)
            finally:
                await agent.stop()
        finally:
            await origin.stop()
        return events.events

    events = asyncio.run(run())
    summaries = [e for e in events if e["name"] == "torrent_summary"]
    assert len(summaries) == 1
    stages = summaries[0]["stages"]
    assert set(stages) == {
        "plan_s", "dial_s", "piece_wait_s", "verify_s", "write_s"
    }
    # Every piece waited on the wire and went through verify + pwrite.
    assert stages["piece_wait_s"] > 0
    assert stages["verify_s"] > 0
    assert stages["write_s"] >= 0
    assert stages["dial_s"] > 0
    assert stages["plan_s"] >= 0
    assert isinstance(summaries[0]["plane_split"], dict)

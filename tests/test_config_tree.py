"""The shipped config tree must stay loadable and internally consistent.

Config rot is silent: a renamed constructor kwarg or a typo'd YAML key in
`config/` breaks production boots without failing any code-path test.
This loads every shipped file through the SAME loader the CLI uses
(extends-merge included) and cross-checks the keys each component file
carries against what the CLI/assembly actually consume.
"""

import inspect
import os

from kraken_tpu.configutil import load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "config")

# Keys the CLI layer itself consumes (kraken_tpu/cli.py `cfg.get` /
# `pick(...)` sites) rather than forwarding to a constructor kwarg.
CLI_KEYS = {
    "host", "port", "store", "tracker", "p2p_port", "hasher",
    "cluster", "cluster_dns", "self_addr", "max_replica", "backends",
    "cleanup", "tls", "tls_client", "scheduler", "origins",
    "announce_interval_seconds", "peer_ttl_seconds", "peerstore_redis",
    "registry_port", "build_index", "spool", "remotes", "dedup_index",
    "dedup_budget_bytes", "extends", "immutable_tags", "p2p_bandwidth",
    "tag_cache_ttl", "durability", "dedup_low_j_bands", "hash_workers",
    "registry_strict_accept", "failpoints", "scrub", "fsck",
    "task_timeout_seconds", "rpc", "resources", "trace", "delta",
    "profiling", "fleet", "chunkstore", "slo", "canary", "ingest",
    "pex", "quorum",
}


def _component_files():
    for comp in ("agent", "origin", "tracker", "proxy", "build-index"):
        d = os.path.join(CONFIG, comp)
        for f in sorted(os.listdir(d)):
            if f.endswith(".yaml"):
                yield comp, os.path.join(d, f)


def test_every_shipped_config_loads_with_extends():
    seen = 0
    for comp, path in _component_files():
        cfg = load_config(path)
        assert isinstance(cfg, dict) and cfg, path
        # The extends-merge must have pulled the shared base in.
        assert "host" in cfg, f"{path}: base.yaml extends-merge missing"
        seen += 1
    assert seen >= 5


def test_shipped_config_keys_are_consumed():
    """Every top-level key in every shipped file must be one the CLI
    reads -- an unknown key is a typo or a renamed knob, and YAML has no
    other way to tell the operator."""
    for comp, path in _component_files():
        cfg = load_config(path)
        unknown = set(cfg) - CLI_KEYS
        assert not unknown, f"{path}: unconsumed keys {sorted(unknown)}"


def test_cleanup_watermarks_ordered():
    for comp, path in _component_files():
        cfg = load_config(path)
        cl = cfg.get("cleanup")
        if not cl:
            continue
        assert cl["low_watermark_bytes"] < cl["high_watermark_bytes"], path


def test_scrub_sections_construct_scrub_config():
    """Every shipped `scrub:` section must map 1:1 onto ScrubConfig
    kwargs -- the CLI constructs it with ScrubConfig(**section), so a
    typo'd knob is a boot-time TypeError in production."""
    import dataclasses

    from kraken_tpu.store.scrub import ScrubConfig

    fields = {f.name for f in dataclasses.fields(ScrubConfig)}
    seen = 0
    for comp, path in _component_files():
        sc = load_config(path).get("scrub")
        if not sc:
            continue
        assert set(sc) <= fields, f"{path}: unknown scrub keys {set(sc) - fields}"
        cfg = ScrubConfig(**sc)
        assert cfg.bytes_per_second >= 0 and cfg.interval_seconds > 0, path
        seen += 1
    assert seen >= 2  # origin + agent ship scrub enabled


def test_scheduler_sections_construct_scheduler_config():
    """Every shipped `scheduler:` section (wire_send_batch,
    bufpool_budget_mb, pacing knobs...) must map onto SchedulerConfig
    kwargs through the same from_dict the CLI/assembly use -- a typo'd
    wire knob must fail here, not at production boot."""
    from kraken_tpu.p2p.scheduler import SchedulerConfig

    seen = 0
    workers_shipped = 0
    for comp, path in _component_files():
        sc = load_config(path).get("scheduler")
        if not sc:
            continue
        cfg = SchedulerConfig.from_dict(sc)  # raises on unknown keys
        assert cfg.wire_send_batch >= 1, path
        assert cfg.bufpool_budget_mb >= 0, path
        # Multi-core data plane (round 8): the knob must construct, and
        # the SHIPPED default must be 0 -- forking serve shards is an
        # explicit operator decision, never a config-refresh surprise.
        assert cfg.data_plane_workers >= 0, path
        if "data_plane_workers" in sc:
            assert cfg.data_plane_workers == 0, (
                f"{path}: shipped data_plane_workers must default to 0"
            )
            workers_shipped += 1
        # Multi-core LEECH plane (round 19): same contract -- the knob
        # constructs, ships 0 (forking download pumps is an explicit
        # operator decision), and the ring budget stays sane.
        assert cfg.leech_workers >= 0, path
        assert cfg.leech_ring_mb >= 4, path  # must hold >= one 4 MiB slot
        if "leech_workers" in sc:
            assert cfg.leech_workers == 0, (
                f"{path}: shipped leech_workers must default to 0"
            )
        seen += 1
    assert seen >= 2  # origin + agent ship the wire-plane knobs
    assert workers_shipped >= 2  # origin + agent register the knob
    # The agent yaml registers the leech knobs (origins drop them).
    agent_sc = load_config("config/agent/base.yaml").get("scheduler") or {}
    assert "leech_workers" in agent_sc and "leech_ring_mb" in agent_sc


def test_rpc_sections_construct_rpc_config():
    """Every shipped `rpc:` section (deadlines, hedge delay, brown-out
    threshold, drain timeout) must map onto RPCConfig through the same
    from_dict the CLI/assembly use -- a typo'd degradation knob must
    fail here, not at production boot."""
    from kraken_tpu.utils.deadline import RPCConfig

    seen = 0
    for comp, path in _component_files():
        rc = load_config(path).get("rpc")
        if not rc:
            continue
        cfg = RPCConfig.from_dict(rc)  # raises on unknown keys
        assert cfg.announce_timeout_seconds > 0, path
        assert cfg.drain_timeout_seconds > 0, path
        assert cfg.request_deadline_seconds > 0, path
        seen += 1
    assert seen >= 3  # agent + origin + tracker ship the rpc knobs


def test_resources_sections_construct_resources_config():
    """Every shipped `resources:` section (sentinel period + budgets)
    must map onto ResourcesConfig through the same from_dict the
    CLI/assembly use -- a typo'd budget knob must fail here, not at
    production boot (where it would silently disable the sentinel's
    teeth)."""
    from kraken_tpu.utils.resources import ResourcesConfig

    seen = 0
    for comp, path in _component_files():
        rc = load_config(path).get("resources")
        if not rc:
            continue
        cfg = ResourcesConfig.from_dict(rc)  # raises on unknown keys
        assert cfg.interval_seconds > 0, path
        assert cfg.breach_streak >= 1, path
        # Shipped defaults must be observe-only: budgets that drain by
        # default would shed healthy nodes on under-provisioned rigs.
        assert cfg.drain_on_breach is False, path
        seen += 1
    assert seen >= 2  # agent + origin ship the sentinel knobs


def test_trace_sections_construct_trace_config():
    """Every shipped `trace:` section must map onto TraceConfig through
    the same from_dict the CLI/assembly use -- a typo'd tracing knob
    must fail here, not at production boot. The shipped defaults must
    stay SAMPLED-DOWN: a config refresh that ships sample_rate 1.0
    would tax every pull's data plane fleet-wide (the overhead band in
    test_data_plane_band.py is measured at the shipped rate)."""
    from kraken_tpu.utils.trace import TraceConfig

    seen = 0
    for comp, path in _component_files():
        tc = load_config(path).get("trace")
        if not tc:
            continue
        cfg = TraceConfig.from_dict(tc)  # raises on unknown keys
        assert cfg.enabled is True, path
        assert 0.0 < cfg.sample_rate <= 0.05, (
            f"{path}: shipped sample_rate must stay sampled-down"
        )
        assert cfg.slow_threshold_seconds > 0, path
        assert cfg.keep_spans >= 256, path
        assert cfg.dump_min_interval_seconds > 0, path
        # dump_dir ships unset: assembly defaults it under the node's
        # store root, and store-less trackers stay file-dump-free.
        assert cfg.dump_dir == "", path
        seen += 1
    assert seen >= 3  # agent + origin + tracker ship the trace knobs


def test_delta_sections_construct_delta_config():
    """Every shipped `delta:` section must map onto DeltaConfig through
    the same from_dict the CLI/assembly use -- a typo'd knob must fail
    here, not at production boot. The shipped default must stay OFF on
    BOTH sides: delta is a rollout decision (origins serve recipes
    first, agents canary after -- OPERATIONS.md runbook), never a
    config-refresh surprise."""
    from kraken_tpu.p2p.delta import DeltaConfig

    seen = 0
    for comp, path in _component_files():
        dc = load_config(path).get("delta")
        if dc is None:
            continue
        cfg = DeltaConfig.from_dict(dc)  # raises on unknown keys
        assert cfg.enabled is False, (
            f"{path}: shipped delta.enabled must stay false"
        )
        assert cfg.min_blob_bytes >= 0, path
        assert cfg.max_bases >= 1, path
        assert 0.0 <= cfg.min_jaccard <= 1.0, path
        assert 0.0 <= cfg.min_piece_cover <= 1.0, path
        seen += 1
    assert seen >= 2  # agent + origin register the delta knobs


def test_chunkstore_sections_construct_chunkstore_config():
    """Every shipped `chunkstore:` section must map onto
    ChunkStoreConfig through the same from_dict the CLI/assembly use --
    a typo'd knob must fail here, not at production boot. The shipped
    default must stay OFF on BOTH components: converting blobs to
    manifests is a rollout decision (agents first, origins after soak
    -- OPERATIONS.md runbook), never a config-refresh surprise."""
    from kraken_tpu.store.chunkstore import ChunkStoreConfig

    seen = 0
    for comp, path in _component_files():
        cc = load_config(path).get("chunkstore")
        if cc is None:
            continue
        cfg = ChunkStoreConfig.from_dict(cc)  # raises on unknown keys
        assert cfg.enabled is False, (
            f"{path}: shipped chunkstore.enabled must stay false"
        )
        assert cfg.min_blob_bytes >= 0, path
        assert cfg.gc_interval_seconds > 0, path
        assert cfg.gc_bytes_per_second >= 0, path
        seen += 1
    assert seen >= 2  # agent + origin register the chunkstore knobs


def test_profiling_sections_construct_profiler_config():
    """Every shipped `profiling:` section must map onto ProfilerConfig
    through the same from_dict the CLI/assembly use -- a typo'd knob
    must fail here, not at production boot. The shipped sample rate
    must stay LOW: the profiler-on overhead band in
    test_data_plane_band.py is measured at the shipped hz, and a config
    refresh that ships 250 Hz would tax every process fleet-wide."""
    from kraken_tpu.utils.profiler import ProfilerConfig

    seen = 0
    for comp, path in _component_files():
        pc = load_config(path).get("profiling")
        if not pc:
            continue
        cfg = ProfilerConfig.from_dict(pc)  # raises on unknown keys
        assert cfg.enabled is True, path
        assert 0.0 < cfg.hz <= 50.0, (
            f"{path}: shipped profiling.hz must stay sampled-down"
            " (the overhead band is measured at the shipped rate)"
        )
        assert cfg.window_seconds > 0 and cfg.keep_windows >= 2, path
        assert cfg.loop_lag_interval_seconds > 0, path
        assert cfg.loop_lag_threshold_seconds > 0, path
        assert cfg.dump_min_interval_seconds > 0, path
        # dump_dir ships unset: assembly defaults it beside the trace
        # dumps under the node's store root; trackers stay file-free.
        assert cfg.dump_dir == "", path
        seen += 1
    assert seen >= 3  # agent + origin + tracker ship the profiling knobs


def test_slo_sections_construct_slo_config():
    """Every shipped `slo:` section must map onto SLOConfig through the
    same from_dict the CLI/assembly use -- a typo'd objective or window
    knob must fail here, not at production boot (where it would
    silently disable the paging plane)."""
    from kraken_tpu.utils.slo import SLOConfig

    seen = 0
    for comp, path in _component_files():
        sc = load_config(path).get("slo")
        if not sc:
            continue
        cfg = SLOConfig.from_dict(sc)  # raises on unknown keys
        assert cfg.enabled is True, path
        assert cfg.eval_interval_seconds > 0, path
        assert cfg.bucket_seconds > 0, path
        # The shipped window pairs must stay the SRE-workbook shape:
        # page strictly faster + hotter than ticket, AND-conditions
        # well-formed (short <= long).
        assert cfg.fast.short_seconds <= cfg.fast.long_seconds, path
        assert cfg.slow.short_seconds <= cfg.slow.long_seconds, path
        assert cfg.fast.burn_rate > cfg.slow.burn_rate, path
        for sli, obj in cfg.objective_map.items():
            assert 0.0 < obj.target < 1.0, (path, sli)
        seen += 1
    assert seen >= 3  # agent + origin + tracker ship the slo knobs


def test_canary_sections_construct_canary_config():
    """Every shipped `canary:` section must map onto CanaryConfig
    through the same from_dict the CLI/assembly use. The shipped
    default must stay OFF: probing needs `origins` pointed at the
    cluster and is a rollout decision, never a config-refresh
    surprise."""
    from kraken_tpu.utils.canary import CanaryConfig

    seen = 0
    for comp, path in _component_files():
        cc = load_config(path).get("canary")
        if cc is None:
            continue
        cfg = CanaryConfig.from_dict(cc)  # raises on unknown keys
        assert cfg.enabled is False, (
            f"{path}: shipped canary.enabled must stay false"
        )
        assert cfg.interval_seconds >= 10.0, (
            f"{path}: shipped canary cadence must stay modest (the"
            " data-plane bands are measured without canary load)"
        )
        assert 0 < cfg.blob_bytes <= 4 * 1024 * 1024, path
        assert cfg.pull_timeout_seconds > 0, path
        assert cfg.ttl_seconds > cfg.interval_seconds, path
        seen += 1
    assert seen >= 1  # the agent registers the canary knobs


def test_pex_sections_construct_pex_config():
    """Every shipped `pex:` section must map onto PexConfig through the
    same from_dict the CLI/assembly use -- a typo'd knob must fail here,
    not at production boot. The shipped defaults ship the gossip plane
    ON (receive AND send: a fleet that only listens never bootstraps
    through a tracker outage) but with conservative send budgets, and
    the peercache ON so restarts rejoin the swarm tracker-free."""
    from kraken_tpu.p2p.pex import PexConfig

    seen = 0
    for comp, path in _component_files():
        pc = load_config(path).get("pex")
        if pc is None:
            continue
        cfg = PexConfig.from_dict(pc)  # raises on unknown keys
        assert cfg.enabled is True, (
            f"{path}: shipped pex.enabled must stay ON (tracker-outage"
            " survival is the point -- docs/OPERATIONS.md 'Tracker"
            " outage survival')"
        )
        assert cfg.send_enabled is True, (
            f"{path}: shipped pex.send_enabled must stay ON (a"
            " receive-only fleet has nothing to receive)"
        )
        assert cfg.interval_seconds >= 10.0, (
            f"{path}: shipped gossip cadence must stay modest (the"
            " data-plane bands are measured with gossip on)"
        )
        assert 1 <= cfg.max_peers_per_message <= 64, (
            f"{path}: shipped send budget must stay conservative"
        )
        assert cfg.dial_rate > 0 and cfg.dial_burst >= 1, path
        assert cfg.seen_ttl_seconds > 0, path
        assert cfg.max_known_peers >= 64, path
        assert cfg.peercache is True, (
            f"{path}: shipped peercache must stay ON (restart-survival"
            " leg of the outage story)"
        )
        assert cfg.peercache_ttl_seconds > cfg.peercache_flush_seconds, path
        seen += 1
    assert seen >= 1  # the agent registers the pex knobs


def test_ingest_sections_construct_ingest_config():
    """Every shipped `ingest:` section must map onto IngestConfig
    through the same from_dict the CLI/assembly use -- a typo'd knob
    must fail here, not at production boot. The shipped defaults must
    stay SAFE: host pack mode (no feeder cores claimed, mesh-sharded)
    and classic double buffering, so a config refresh never silently
    changes the pack path or balloons staging RAM."""
    from kraken_tpu.core.ingest import IngestConfig

    seen = 0
    for comp, path in _component_files():
        ic = load_config(path).get("ingest")
        if ic is None:
            continue
        cfg = IngestConfig.from_dict(ic)  # raises on unknown keys
        assert cfg.pack_mode == "host", (
            f"{path}: shipped pack_mode must stay 'host' (native/device"
            " are per-rig opt-ins -- PERF.md 'Pipelined ingest plane')"
        )
        assert cfg.windows_in_flight == 2, (
            f"{path}: shipped windows_in_flight must stay 2 (double"
            " buffering; staging RAM scales with it)"
        )
        assert 1 << 20 <= cfg.window_bytes <= 1 << 30, path
        assert cfg.pack_workers >= 0, path
        assert cfg.resume is True, (
            f"{path}: shipped resume must stay ON (pure robustness --"
            " journaled sessions survive origin crashes; flipping it off"
            " is a per-cluster opt-out, not a shipped default)"
        )
        assert cfg.serve_while_ingest is False, (
            f"{path}: shipped serve_while_ingest must stay OFF (serving"
            " from the upload spool pre-commit is a rollout step --"
            " docs/OPERATIONS.md runbook)"
        )
        seen += 1
    assert seen >= 2  # origin AND agent register the ingest knobs


def test_quorum_sections_construct_quorum_config():
    """Every shipped `quorum:` section must map onto QuorumConfig
    through the same from_dict the CLI/assembly use -- a typo'd knob
    must fail here, not at production boot. The shipped default must
    stay `write_quorum: 1` (classic async replication): gating acks on
    replica round-trips is a per-cluster durability/latency trade the
    operator makes deliberately (docs/OPERATIONS.md 'Write
    durability'), never a config-refresh surprise."""
    from kraken_tpu.origin.server import QuorumConfig

    seen = 0
    for comp, path in _component_files():
        qc = load_config(path).get("quorum")
        if qc is None:
            continue
        cfg = QuorumConfig.from_dict(qc)  # raises on unknown keys
        assert cfg.write_quorum == 1, (
            f"{path}: shipped write_quorum must stay 1 (quorum acks are"
            " an explicit operator opt-in)"
        )
        assert cfg.hint_ttl_seconds > 0, path
        assert cfg.push_timeout_seconds > 0, path
        seen += 1
    assert seen >= 1  # the origin registers the quorum knobs


def test_cli_keys_match_cli_source():
    """CLI_KEYS drifts too: every key this test whitelists must actually
    appear in cli.py, so deleting a knob there fails here."""
    src = inspect.getsource(__import__("kraken_tpu.cli", fromlist=["x"]))
    for key in CLI_KEYS - {"extends"}:
        assert (
            f'"{key}"' in src or f"'{key}'" in src or f"args.{key}" in src
        ), f"CLI_KEYS lists {key!r} but cli.py never mentions it"

"""SLO & canary plane (utils/slo.py, utils/canary.py, `kraken-tpu
status`).

What must hold, per docs/OPERATIONS.md "SLO & canary":

- the sliding-window burn-rate math is exact and deterministic: budget
  exhaustion reads negative, a page needs BOTH windows of its pair hot
  (the AND-condition), and recovery clears on the short window alone
  (hysteresis) while the long window is still hot;
- objectives and windows live-reload (SIGHUP) without losing history;
- a firing page ships its own postmortem: the PR-8 flight-recorder
  dump plus the PR-10 profile capture;
- the canary prober drives a real upload + swarm pull under the
  reserved namespace, records canary-labeled SLI samples and the PR-8
  stage split, forces trace sampling (one joined trace per probe), and
  TTL-reaps its blobs from both sides;
- `GET /debug/` indexes the node's debug surfaces; `GET /debug/slo`
  serves the evaluator document; both scrapes count into the lameduck
  drain quiesce (the round-12 /recipe lesson);
- `kraken-tpu status` aggregates a node list and exits 0 healthy /
  1 burning / 2 unreachable;
- THE acceptance chain: zero user traffic + an injected origin
  failpoint -> canary probes fail -> `slo_burn_rate{sli="pull"}` over
  the fast-burn threshold -> /debug/slo reports the firing page ->
  trace dump + profile capture land on disk -> `kraken-tpu status`
  exits non-zero against the herd.
"""

from __future__ import annotations

import asyncio
import glob
import json
import os
import time

import pytest

from kraken_tpu.utils import failpoints
from kraken_tpu.utils.slo import (
    CANARY_NAMESPACE,
    SLO,
    SLIRecorder,
    SLOConfig,
    format_window,
)
from kraken_tpu.utils.trace import TRACER, TraceConfig

NS = "library/slo-test"


@pytest.fixture(autouse=True)
def _slo_isolation():
    """The SLO manager is process-global (like the TRACER): stop its
    evaluator thread, snapshot config/node/clock, and clear recorders +
    alert latches around every test so burn state never leaks between
    suites."""
    SLO.stop()
    cfg0, node0, clock0 = SLO.config, SLO.node, SLO._clock
    canary0 = SLO.canary_status
    SLO._recorders.clear()
    SLO._alerts.clear()
    SLO._last_eval = {}
    SLO.canary_status = None
    yield
    SLO.stop()
    SLO.config, SLO.node, SLO._clock = cfg0, node0, clock0
    SLO.canary_status = canary0
    SLO._recorders.clear()
    SLO._alerts.clear()
    SLO._last_eval = {}


@pytest.fixture(autouse=True)
def _tracer_isolation():
    # The PROFILER's per-trigger capture throttle is process-global
    # too: earlier suites' failed pulls now fire slo_fast_burn pages
    # of their own, and a stamp within 30 s would mute THIS suite's
    # capture assertions (by-design throttling in production, cross-
    # suite leakage here).
    from kraken_tpu.utils.profiler import PROFILER

    cfg0, node0 = TRACER.config, TRACER.node
    TRACER.recorder.clear()
    TRACER._last_dump.clear()
    PROFILER._last_dump.clear()
    yield
    TRACER.config, TRACER.node = cfg0, node0
    TRACER.recorder.clear()
    TRACER._last_dump.clear()
    PROFILER._last_dump.clear()


@pytest.fixture(autouse=True)
def _failpoints_clean():
    failpoints.FAILPOINTS.disarm_all()
    yield
    failpoints.FAILPOINTS.disarm_all()


def _fake_clock(start: float = 1000.0):
    t = [start]
    SLO._clock = lambda: t[0]
    return t


_TEST_CFG = {
    "bucket_seconds": 1.0,
    "eval_interval_seconds": 1.0,
    "objectives": {"pull": {"target": 0.9}},
    # budget 0.1 => max possible burn is 10x; thresholds sit below it.
    "fast": {"short_seconds": 10, "long_seconds": 60, "burn_rate": 6.0},
    "slow": {"short_seconds": 30, "long_seconds": 120, "burn_rate": 2.0},
}


def _set_config(**over) -> SLOConfig:
    cfg = SLOConfig.from_dict({**_TEST_CFG, **over})
    SLO.config = cfg  # direct: unit tests never want the eval thread
    return cfg


# -- burn-rate math ---------------------------------------------------------


def test_window_counts_and_burn_rates_are_exact():
    t = _fake_clock()
    _set_config()
    # 40 good spread over [t, t+50); then 8 good + 2 bad in the last
    # 10 s.  Short window err = 0.2 -> burn 2.0; the long window holds
    # everything: err = 2/50 = 0.04 -> burn 0.4.
    for _ in range(40):
        SLO.record("pull", True)
    t[0] += 50
    for _ in range(8):
        SLO.record("pull", True)
    for _ in range(2):
        SLO.record("pull", False)
    doc = SLO.evaluate()
    w = doc["pull"]["windows"]
    assert w["10s"]["burn_rate"] == pytest.approx(2.0)
    assert w["10s"]["good"] == 8 and w["10s"]["bad"] == 2
    assert w["1m"]["burn_rate"] == pytest.approx(0.4)
    assert doc["pull"]["budget_remaining"] == pytest.approx(
        1 - 0.04 / 0.1, abs=1e-6
    )


def test_budget_exhaustion_reads_negative():
    _fake_clock()
    _set_config()
    for _ in range(10):
        SLO.record("pull", False)
    doc = SLO.evaluate()
    # 100% errors against a 10% budget: 10x overdrawn.
    assert doc["pull"]["budget_remaining"] == pytest.approx(-9.0)
    assert SLO._g_budget.value(sli="pull") == pytest.approx(-9.0)


def test_page_fires_only_when_both_windows_burn():
    t = _fake_clock()
    _set_config()
    # A long healthy history, then a hot 10 s: the short window burns
    # (err 1.0 -> 10x) but the long window is diluted below threshold.
    for _ in range(200):
        SLO.record("pull", True)
    t[0] += 55
    for _ in range(5):
        SLO.record("pull", False)
    doc = SLO.evaluate()
    w = doc["pull"]["windows"]
    assert w["10s"]["burn_rate"] > 6.0
    assert w["1m"]["burn_rate"] < 6.0
    assert doc["pull"]["alerts"]["page"]["firing"] is False, (
        "short-window-only burn must NOT page (the AND-condition)"
    )
    # The healthy history ages out of the long window while the errors
    # persist: now both windows burn and the page fires.
    t[0] += 15
    for _ in range(5):
        SLO.record("pull", False)
    doc = SLO.evaluate()
    w = doc["pull"]["windows"]
    assert w["10s"]["burn_rate"] > 6.0 and w["1m"]["burn_rate"] > 6.0
    assert doc["pull"]["alerts"]["page"]["firing"] is True
    assert SLO.firing()[0]["sli"] == "pull"
    assert SLO._g_firing.value(sli="pull", severity="page") == 1.0


def test_recovery_hysteresis_clears_on_short_window_alone():
    t = _fake_clock()
    _set_config()
    for _ in range(10):
        SLO.record("pull", False)
    doc = SLO.evaluate()
    assert doc["pull"]["alerts"]["page"]["firing"] is True
    # Errors stop; 5 s later the short window still holds them -> the
    # alert must KEEP firing (no flap on the first quiet evaluation).
    t[0] += 5
    doc = SLO.evaluate()
    assert doc["pull"]["alerts"]["page"]["firing"] is True
    # 15 s after the last error the short window is clean -> clears,
    # even though the long window still burns well above threshold
    # (clearing on the AND of both would page for the long window's
    # whole span after recovery).
    t[0] += 10
    doc = SLO.evaluate()
    assert doc["pull"]["windows"]["1m"]["burn_rate"] > 6.0
    assert doc["pull"]["alerts"]["page"]["firing"] is False
    assert SLO._g_firing.value(sli="pull", severity="page") == 0.0


def test_slow_success_counts_against_the_budget():
    _fake_clock()
    _set_config(objectives={
        "pull": {"target": 0.9, "latency_threshold_seconds": 1.0},
    })
    SLO.record("pull", True, latency_s=0.5)
    SLO.record("pull", True, latency_s=5.0)  # success, but too slow
    doc = SLO.evaluate()
    assert doc["pull"]["windows"]["10s"]["good"] == 1
    assert doc["pull"]["windows"]["10s"]["bad"] == 1


def test_canary_samples_are_counted_and_broken_out():
    _fake_clock()
    _set_config()
    c0 = SLO._c_events.value(sli="pull", result="bad", canary="1")
    SLO.record("pull", True)
    SLO.record("pull", False, canary=True)
    doc = SLO.evaluate()
    w = doc["pull"]["windows"]["10s"]
    # Canary is IN the burn math (black-box) and separately visible.
    assert w["good"] == 1 and w["bad"] == 1
    assert w["canary_bad"] == 1 and w["canary_good"] == 0
    assert SLO._c_events.value(
        sli="pull", result="bad", canary="1"
    ) == c0 + 1


def test_live_reload_of_objectives_keeps_history():
    t = _fake_clock()
    _set_config()
    for _ in range(4):
        SLO.record("pull", False)
    assert SLO.evaluate()["pull"]["windows"]["10s"]["bad"] == 4
    # Reload with a looser target: same events, new budget math --
    # history must survive (the window IS the state).
    SLO.apply({**_TEST_CFG, "enabled": False,
               "objectives": {"pull": {"target": 0.5}}})
    doc = SLO.evaluate()
    assert doc["pull"]["windows"]["10s"]["bad"] == 4
    assert doc["pull"]["windows"]["10s"]["burn_rate"] == pytest.approx(2.0)
    # Changing the bucket geometry is the one reload that resets
    # recorders (old buckets are unreadable at the new granularity).
    SLO.apply({**_TEST_CFG, "enabled": False, "bucket_seconds": 2.0})
    assert SLO.evaluate()["pull"]["windows"]["10s"]["bad"] == 0
    del t


def test_apply_follows_the_enabled_flag():
    _set_config()
    SLO.apply({**_TEST_CFG, "enabled": True})
    assert SLO._thread is not None and SLO._thread.is_alive()
    SLO.apply({**_TEST_CFG, "enabled": False})
    assert SLO._thread is None
    # Disabled: record() is a no-op (no recorder growth).
    SLO.record("pull", False)
    assert "pull" not in SLO._recorders


def test_config_validation_rejects_typos_and_bad_values():
    with pytest.raises(ValueError, match="unknown slo config keys"):
        SLOConfig.from_dict({"windowz": {}})
    with pytest.raises(ValueError, match="target must be in"):
        SLOConfig.from_dict({"objectives": {"pull": {"target": 1.5}}})
    with pytest.raises(ValueError, match="unknown keys in slo objective"):
        SLOConfig.from_dict({"objectives": {"pull": {"targt": 0.9}}})
    with pytest.raises(ValueError, match="short <= long"):
        SLOConfig.from_dict(
            {"fast": {"short_seconds": 60, "long_seconds": 5}}
        )
    with pytest.raises(ValueError, match="burn_rate"):
        SLOConfig.from_dict({"slow": {"burn_rate": 0}})
    from kraken_tpu.utils.canary import CanaryConfig

    with pytest.raises(ValueError, match="unknown canary config keys"):
        CanaryConfig.from_dict({"intervall_seconds": 5})
    with pytest.raises(ValueError, match="blob_bytes"):
        CanaryConfig.from_dict({"blob_bytes": 0})


def test_format_window_labels():
    assert format_window(300) == "5m"
    assert format_window(3600) == "1h"
    assert format_window(21600) == "6h"
    assert format_window(90) == "90s"


def test_recorder_prunes_past_horizon():
    t = [0.0]
    rec = SLIRecorder(1.0, 10.0, clock=lambda: t[0])
    for _ in range(5):
        rec.record(False)
    t[0] += 100
    rec.record(True)  # triggers the prune
    assert len(rec._buckets) == 1
    assert rec.counts(10.0)["bad"] == 0


# -- the page ships its own postmortem --------------------------------------


def test_fast_burn_page_writes_flight_recorder_dump(tmp_path):
    _fake_clock()
    _set_config()
    TRACER.apply(TraceConfig(sample_rate=1.0, dump_dir=str(tmp_path)))
    captured: list[tuple[str, str]] = []
    TRACER.on_trigger = lambda trig, detail: captured.append((trig, detail))
    try:
        from kraken_tpu.utils import trace

        with trace.span("slo.test.pull"):
            pass  # the ring must hold something to dump
        for _ in range(10):
            SLO.record("pull", False)
        SLO.evaluate()  # sync context: the dump write is synchronous
        dumps = glob.glob(str(tmp_path / "trace-slo_fast_burn-*.jsonl"))
        assert len(dumps) == 1, "a firing page must persist the ring"
        header = json.loads(open(dumps[0]).read().splitlines()[0])
        assert header["dump"] == "slo_fast_burn"
        assert "pull" in header["detail"]
        # The profiler capture hook (PR 10) fired through on_trigger.
        assert captured and captured[0][0] == "slo_fast_burn"
        # Still firing on the next evaluation: no second dump (the
        # trigger fires on the TRANSITION, not every tick).
        SLO.evaluate()
        assert len(
            glob.glob(str(tmp_path / "trace-slo_fast_burn-*.jsonl"))
        ) == 1
    finally:
        TRACER.on_trigger = None


# -- canary unit ------------------------------------------------------------


def test_canary_blob_deterministic_and_unique():
    from kraken_tpu.utils.canary import canary_blob

    a1 = canary_blob("agent-x", 1, 4096)
    a2 = canary_blob("agent-x", 1, 4096)
    b = canary_blob("agent-x", 2, 4096)
    c = canary_blob("agent-y", 1, 4096)
    assert a1 == a2 and len(a1) == 4096
    assert a1 != b and a1 != c
    # The boot epoch is part of the derivation: a restarted agent must
    # never regenerate its previous run's digests (a warm-cache probe
    # is a no-op probe).
    assert canary_blob("agent-x", 1, 4096, epoch=7) != a1
    assert canary_blob("agent-x", 1, 4096, epoch=7) == canary_blob(
        "agent-x", 1, 4096, epoch=7
    )


# -- surfaces + status tool -------------------------------------------------


def _herd_slo_cfg() -> dict:
    # Tight windows so a herd test fires within seconds: target 0.9
    # (max burn 10x), page on >3x over 6s AND 12s, ticket >1.5x over
    # 10s AND 30s.
    return {
        "eval_interval_seconds": 0.2,
        "bucket_seconds": 1.0,
        "objectives": {"pull": {"target": 0.9}},
        "fast": {"short_seconds": 6, "long_seconds": 12, "burn_rate": 3.0},
        "slow": {"short_seconds": 10, "long_seconds": 30, "burn_rate": 1.5},
    }


def test_debug_index_and_slo_surface_and_drain_inflight(monkeypatch):
    """/debug/ lists what the node serves; /debug/slo answers; both
    scrapes count into inflight_work so a drain cannot quiesce under
    them (the round-12 /recipe lesson applied to the new surfaces)."""
    from kraken_tpu.assembly import TrackerNode
    from kraken_tpu.utils.httputil import HTTPClient

    async def main():
        tracker = TrackerNode(slo={**_herd_slo_cfg(), "enabled": False})
        await tracker.start()
        http = HTTPClient()
        try:
            for path in ("/debug/", "/debug"):
                idx = json.loads(
                    await http.get(f"http://{tracker.addr}{path}")
                )
                assert idx["component"] == "tracker"
                surfaces = idx["surfaces"]
                for expected in (
                    "/metrics", "/health", "/debug/slo", "/debug/trace",
                    "/debug/healthcheck", "/debug/resources",
                    "/debug/failpoints", "/debug/lameduck",
                    "/debug/pprof/profile",
                ):
                    assert expected in surfaces, (expected, surfaces)
                assert "GET" in surfaces["/debug/slo"]
                assert "POST" in surfaces["/debug/lameduck"]

            # The drain-quiesce fix: while the slo handler runs, the
            # server's inflight_work must be > 0 -- observed from
            # INSIDE the scrape by the patched snapshot provider.
            seen: list[int] = []
            real = SLO.debug_snapshot

            def spying_snapshot():
                seen.append(tracker.server.inflight_work)
                return real()

            monkeypatch.setattr(SLO, "debug_snapshot", spying_snapshot)
            doc = json.loads(
                await http.get(f"http://{tracker.addr}/debug/slo")
            )
            assert doc["enabled"] is False
            assert seen == [1], (
                "a /debug/slo scrape must gate the drain quiesce"
            )
            assert tracker.server.inflight_work == 0
        finally:
            await http.close()
            await tracker.stop()

    asyncio.run(main())


def test_status_tool_exit_codes_against_live_node():
    from kraken_tpu.assembly import TrackerNode
    from kraken_tpu.cli import run_status_tool

    async def main():
        tracker = TrackerNode(slo=_herd_slo_cfg())
        await tracker.start()
        try:
            # Healthy: nothing recorded, nothing burns.
            rc = await asyncio.to_thread(run_status_tool, [tracker.addr])
            assert rc == 0
            # Burn the budget (target 0.9, every event bad) and force
            # an evaluation: the node's own /debug/slo now reports the
            # firing page and status gates on it.
            for _ in range(10):
                SLO.record("pull", False)
            SLO.evaluate()
            assert SLO.firing()
            rc = await asyncio.to_thread(run_status_tool, [tracker.addr])
            assert rc == 1
            # An unreachable node dominates: the gate cannot call a
            # fleet it cannot see healthy.
            rc = await asyncio.to_thread(
                run_status_tool, [tracker.addr, "127.0.0.1:1"], 2.0
            )
            assert rc == 2
        finally:
            await tracker.stop()
        assert await asyncio.to_thread(run_status_tool, []) == 3

    asyncio.run(main())


# -- the herd: canary through the real stack --------------------------------


async def _start_herd(tmp_path, canary_overrides: dict | None = None):
    from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
    from kraken_tpu.origin.client import ClusterClient
    from kraken_tpu.placement import HostList, Ring

    # sample_rate 0: whatever the canary traces, IT sampled.
    tcfg = {"sample_rate": 0.0, "keep_spans": 8192}
    tracker = TrackerNode(
        announce_interval_seconds=0.1, peer_ttl_seconds=5.0, trace=tcfg,
    )
    await tracker.start()
    origin = OriginNode(
        store_root=str(tmp_path / "origin"), tracker_addr=tracker.addr,
        trace=tcfg, slo=_herd_slo_cfg(),
    )
    await origin.start()
    ring = Ring(HostList(static=[origin.addr]), max_replica=2)
    cluster = ClusterClient(ring)
    tracker.server.origin_cluster = cluster
    origin.ring = ring
    if origin.server:
        origin.server.ring = ring
    agent = AgentNode(
        store_root=str(tmp_path / "agent"), tracker_addr=tracker.addr,
        trace=tcfg, slo=_herd_slo_cfg(),
        canary={
            "enabled": True, "interval_seconds": 0.3, "blob_bytes": 32768,
            "origins": origin.addr, "pull_timeout_seconds": 1.0,
            "ttl_seconds": 60.0,
            **(canary_overrides or {}),
        },
    )
    await agent.start()
    return tracker, origin, cluster, agent


async def _stop_herd(tracker, origin, cluster, agent):
    await agent.stop()
    await origin.stop()
    await cluster.close()
    await tracker.stop()


def test_canary_ttl_reap_removes_blobs_both_sides(tmp_path):
    from kraken_tpu.core.digest import Digest

    async def main():
        tracker, origin, cluster, agent = await _start_herd(
            tmp_path, {"enabled": False, "ttl_seconds": 0.05}
        )
        try:
            # Canary blobs are EPHEMERAL: the origin's commit pipeline
            # must not ring-replicate them (copies on peer origins the
            # reap's DELETE never reaches) nor write them back to a
            # backend -- spy on the enqueue to prove the gate.
            repl_calls: list[str] = []
            real_enq = origin.server._enqueue_replication
            origin.server._enqueue_replication = (
                lambda ns, d: repl_calls.append(ns)
            )
            try:
                doc = await agent.canary.probe([origin.addr])
            finally:
                origin.server._enqueue_replication = real_enq
            assert doc["result"] == "ok"
            assert repl_calls == [], (
                "canary commits must skip replication/writeback"
            )
            d = Digest.from_hex(doc["digest"])
            assert agent.store.in_cache(d) and origin.store.in_cache(d)
            await asyncio.sleep(0.1)
            await agent.canary._reap()
            assert not agent.store.in_cache(d), "agent copy must reap"
            assert not origin.store.in_cache(d), "origin copy must reap"
            assert agent.canary._live == {}

            # Crash-restart contract: a SECOND probe's blob, then a
            # FRESH prober over the same store (simulating the agent
            # restarting after a crash) must load the persisted reap
            # state and clean the orphan the dead prober left on the
            # origin -- and must derive NEW digests (fresh epoch).
            from kraken_tpu.utils.canary import CanaryProber

            doc2 = await agent.canary.probe([origin.addr])
            d2 = Digest.from_hex(doc2["digest"])
            assert origin.store.in_cache(d2)
            reborn = CanaryProber(
                agent.store, agent.scheduler, agent.canary.config,
                node=agent.canary.node,
            )
            reborn._epoch = agent.canary._epoch + 1  # a later boot
            assert d2.hex in {v[0].hex for v in reborn._live.values()}
            await asyncio.sleep(0.1)
            await reborn._reap()
            assert not origin.store.in_cache(d2), (
                "a restarted prober must reap its predecessor's blobs"
            )
            from kraken_tpu.utils.canary import canary_blob

            assert canary_blob(
                reborn.node, doc2["seq"], 64, reborn._epoch
            ) != canary_blob(
                agent.canary.node, doc2["seq"], 64, agent.canary._epoch
            )
        finally:
            await _stop_herd(tracker, origin, cluster, agent)

    asyncio.run(main())


@pytest.mark.chaos
def test_acceptance_canary_burn_fires_dumps_and_status_gates(tmp_path):
    """THE acceptance chain (ISSUE 14): with ZERO user traffic and an
    injected origin failpoint, the canary prober drives
    `slo_burn_rate{sli="pull"}` over the fast-burn threshold,
    /debug/slo reports the firing page, a trace dump AND a profile
    capture land on disk, and `kraken-tpu status` exits non-zero
    against the herd.  The healthy half first: one probe = one joined
    trace + canary-labeled SLI samples + the PR-8 stage split."""
    from kraken_tpu.cli import run_status_tool
    from kraken_tpu.utils.httputil import HTTPClient

    async def main():
        tracker, origin, cluster, agent = await _start_herd(tmp_path)
        http = HTTPClient()
        try:
            # -- healthy probe: the canary pull works the real stack --
            doc = await agent.canary.probe([origin.addr])
            assert doc["result"] == "ok", doc
            # The PR-8 stage split of the probe's own pull.
            for stage in ("upload_s", "pull_s", "plan_s", "dial_s",
                          "piece_wait_s", "verify_s", "write_s"):
                assert stage in doc["stages"], doc["stages"]
            # One joined trace, forced-sampled by the probe (the herd
            # runs sample_rate 0, so every kept span here is canary's).
            spans = [
                s for s in TRACER.recorder.snapshot()
                if s["trace_id"] == doc["trace_id"]
            ]
            names = {s["name"] for s in spans}
            assert {"canary.probe", "p2p.download", "p2p.announce"} <= names, (
                names
            )
            # Canary-labeled SLI samples are in the recorders.
            SLO.evaluate()
            counts = SLO._recorders["pull"].counts(300)
            assert counts["canary_good"] >= 1 and counts["bad"] == 0
            # No alert burns on a healthy canary.
            assert SLO.firing() == []
            rc = await asyncio.to_thread(
                run_status_tool,
                [agent.addr, origin.addr, tracker.addr],
            )
            assert rc == 0

            # -- inject the origin failpoint: reads stall 3 s, every
            # canary pull (1 s budget) now fails; the background
            # prober (0.3 s cadence) burns the budget on its own. --
            # Clear both postmortem throttles first: a slo_fast_burn
            # dump from ANOTHER suite's page within the last 30 s must
            # not mute the captures this test asserts on.
            from kraken_tpu.utils.profiler import PROFILER

            TRACER._last_dump.clear()
            PROFILER._last_dump.clear()
            failpoints.FAILPOINTS.arm(
                f"rpc.brownout.slow@{origin.addr}", "always+delay:3000"
            )
            deadline = time.monotonic() + 30
            firing: list = []
            while time.monotonic() < deadline:
                slo = json.loads(
                    await http.get(f"http://{agent.addr}/debug/slo")
                )
                firing = slo.get("firing", [])
                if any(
                    f["sli"] == "pull" and f["severity"] == "page"
                    for f in firing
                ):
                    break
                await asyncio.sleep(0.2)
            assert any(
                f["sli"] == "pull" and f["severity"] == "page"
                for f in firing
            ), f"fast-burn page never fired: {firing}"
            # The gauges the alert rules scrape.
            assert SLO._g_burn.value(sli="pull", window="6s") > 3.0
            assert SLO._g_firing.value(sli="pull", severity="page") == 1.0

            # -- the page shipped its own postmortem: trace dump +
            # profile capture beside the agent's store. --
            dump_dir = str(tmp_path / "agent" / "traces")
            deadline = time.monotonic() + 10
            trace_dumps = profile_dumps = []
            while time.monotonic() < deadline:
                trace_dumps = glob.glob(
                    os.path.join(dump_dir, "trace-slo_fast_burn-*.jsonl")
                )
                profile_dumps = glob.glob(
                    os.path.join(dump_dir, "profile-slo_fast_burn-*.jsonl")
                )
                if trace_dumps and profile_dumps:
                    break
                await asyncio.sleep(0.2)
            assert trace_dumps, "firing page must write a trace dump"
            assert profile_dumps, "firing page must capture a profile"

            # -- the operator entry point gates on the herd. --
            rc = await asyncio.to_thread(
                run_status_tool,
                [agent.addr, origin.addr, tracker.addr],
            )
            assert rc == 1
        finally:
            failpoints.FAILPOINTS.disarm_all()
            await http.close()
            await _stop_herd(tracker, origin, cluster, agent)

    asyncio.run(main())

"""Generated Prometheus deployment (utils/promgen.py, `kraken-tpu
promgen`).

Two CI gates:

- the committed ``deploy/prometheus/`` files must match a fresh
  generation byte for byte (edit the generator, not the output);
- every metric the alert rules reference must be a name the
  docs/OPERATIONS.md metric-catalog lint knows -- an alert expression
  over a renamed or never-registered metric silently never fires,
  which is the worst failure mode an alert can have.
"""

from __future__ import annotations

import os
import re

from kraken_tpu.utils.promgen import (
    generate_alert_rules,
    generate_prometheus_config,
    referenced_metric_names,
    write_files,
)
from kraken_tpu.utils.slo import DEFAULT_FAST, DEFAULT_SLOW, format_window

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "deploy", "prometheus")


def test_committed_files_match_regeneration(tmp_path):
    """`python -m kraken_tpu.cli promgen` committed output is current."""
    paths = write_files(str(tmp_path))
    for path in paths:
        name = os.path.basename(path)
        committed = os.path.join(OUT, name)
        assert os.path.exists(committed), (
            f"deploy/prometheus/{name} missing -- run"
            " `python -m kraken_tpu.cli promgen`"
        )
        with open(path) as fresh, open(committed) as repo:
            assert fresh.read() == repo.read(), (
                f"deploy/prometheus/{name} drifted -- run"
                " `python -m kraken_tpu.cli promgen`"
            )


def test_rules_reference_only_cataloged_metrics():
    rules = generate_alert_rules()
    names = referenced_metric_names(rules)
    assert names, "the extractor must find the rule metrics"
    assert "slo_burn_rate" in names  # sanity: the headline rule is seen
    with open(os.path.join(REPO, "docs", "OPERATIONS.md")) as f:
        docs = f.read()
    missing = sorted(n for n in names if f"`{n}" not in docs)
    assert not missing, (
        "alert rules reference metrics the OPERATIONS.md catalog does"
        f" not know (rename drift -- these alerts would never fire):"
        f" {missing}"
    )


def test_burn_rule_windows_match_the_shipped_evaluator():
    """The window labels in the generated expressions must be the exact
    strings the in-process evaluator exports on `slo_burn_rate{window}`
    -- promgen and utils/slo.py share one source of truth."""
    rules = generate_alert_rules()
    for pair in (DEFAULT_FAST, DEFAULT_SLOW):
        for seconds in (pair.short_seconds, pair.long_seconds):
            assert f'window="{format_window(seconds)}"' in rules
        assert f"> {pair.burn_rate}" in rules


def test_scrape_config_covers_every_component():
    cfg = generate_prometheus_config()
    for component in ("agent", "tracker", "origin", "build-index", "proxy"):
        assert f"job_name: kraken-{component}" in cfg
    # The rule file is wired in, and every target is a real port.
    assert "kraken-alerts.yml" in cfg
    assert re.search(r"targets: \['localhost:\d+'\]", cfg)

"""Registry v2 conformance-shaped tests: exact spec error codes.

Real docker/containerd clients branch on the error ENVELOPE -- e.g. the
cross-repo-mount fallback keys off the response to the mount POST, and
push retries key off BLOB_UPLOAD_* -- so every error the pull / push /
mount / resume flows can hit must carry
``{"errors": [{"code", "message", ...}]}`` with the spec's code, plus
``Docker-Distribution-API-Version: registry/2.0`` on every response.
Modeled on the OCI distribution-spec conformance suite's error assertions
(SURVEY.md SS2.4, SS7 hard part #5).
"""

import asyncio
import json
import os

import aiohttp
import pytest
from aiohttp import web

from kraken_tpu.core.digest import Digest
from kraken_tpu.dockerregistry.registry import RegistryServer

GOOD = "sha256:" + "ab" * 32  # valid digest that is nowhere in the registry


class FakeTransferer:
    """In-memory ImageTransferer: conformance tests target the v2 veneer,
    not blob movement."""

    def __init__(self):
        self.blobs: dict[str, bytes] = {}
        self.tags: dict[str, Digest] = {}

    async def download(self, namespace, d):
        return self.blobs[str(d)]

    async def upload(self, namespace, d, data):
        self.blobs[str(d)] = data

    async def stat(self, namespace, d):
        b = self.blobs.get(str(d))
        return None if b is None else len(b)

    async def download_path(self, namespace, d):
        raise KeyError(str(d))

    async def upload_file(self, namespace, d, path):
        with await asyncio.to_thread(open, path, "rb") as f:
            self.blobs[str(d)] = await asyncio.to_thread(f.read)

    async def mount(self, source, target, d):
        return str(d) in self.blobs

    async def get_tag(self, tag):
        return self.tags.get(tag)

    async def put_tag(self, tag, d):
        self.tags[tag] = d

    async def list_repo_tags(self, repo):
        pre = f"{repo}:"
        return [t[len(pre):] for t in self.tags if t.startswith(pre)]

    async def list_all_tags(self):
        return list(self.tags)


class Rig:
    def __init__(self, read_only=False, strict_accept=False):
        self.transferer = FakeTransferer()
        self.server = RegistryServer(
            self.transferer, read_only=read_only, strict_accept=strict_accept
        )

    async def __aenter__(self):
        self.runner = web.AppRunner(self.server.make_app())
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = self.runner.addresses[0][1]
        self.base = f"http://127.0.0.1:{port}"
        self.http = aiohttp.ClientSession()
        return self

    async def __aexit__(self, *exc):
        await self.http.close()
        await self.runner.cleanup()

    async def expect(self, method, path, code, status, **kw):
        """Assert (HTTP status, spec error code, envelope shape, version
        header) for one request."""
        async with self.http.request(method, self.base + path, **kw) as r:
            assert r.status == status, (path, r.status, await r.text())
            assert r.headers["Docker-Distribution-API-Version"] == "registry/2.0"
            body = json.loads(await r.text())
            assert list(body) == ["errors"] and len(body["errors"]) == 1
            err = body["errors"][0]
            assert err["code"] == code, (path, err)
            assert err["message"]  # spec: message is human-readable, non-empty
            return err


def test_api_version_check():
    """GET /v2/ is the client's registry-detection probe: 200, JSON body,
    and the version header present on success AND error responses."""

    async def main():
        async with Rig() as rig:
            async with rig.http.get(rig.base + "/v2/") as r:
                assert r.status == 200
                assert (
                    r.headers["Docker-Distribution-API-Version"]
                    == "registry/2.0"
                )
                assert await r.json() == {}

    asyncio.run(main())


def test_pull_flow_error_codes():
    """Every failure a `docker pull` can hit: manifest by unknown tag /
    unknown digest / malformed digest; blob unknown / malformed digest."""

    async def main():
        async with Rig() as rig:
            e = await rig.expect(
                "GET", "/v2/repo/manifests/nosuchtag", "MANIFEST_UNKNOWN", 404
            )
            assert e["detail"]["tag"] == "nosuchtag"
            await rig.expect(
                "GET", f"/v2/repo/manifests/{GOOD}", "MANIFEST_UNKNOWN", 404
            )
            await rig.expect(
                "GET", "/v2/repo/manifests/sha256:xyz", "DIGEST_INVALID", 400
            )
            await rig.expect(
                "GET", f"/v2/repo/blobs/{GOOD}", "BLOB_UNKNOWN", 404
            )
            await rig.expect(
                "GET", "/v2/repo/blobs/sha256:nothex", "DIGEST_INVALID", 400
            )
            # Blob bytes pulled through the manifest route (legal: both are
            # digest-addressed) must not crash content-type sniffing.
            data = b"[1, 2]"  # valid JSON, not an object
            d = Digest.from_bytes(data)
            rig.transferer.blobs[str(d)] = data
            async with rig.http.get(
                rig.base + f"/v2/repo/manifests/{d}"
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].endswith("json")
            # HEAD errors carry no body (RFC 9110), so check status+header
            # only.
            async with rig.http.head(rig.base + f"/v2/repo/blobs/{GOOD}") as r:
                assert r.status == 404
                assert (
                    r.headers["Docker-Distribution-API-Version"]
                    == "registry/2.0"
                )

    asyncio.run(main())


def test_push_flow_error_codes():
    """Every failure a `docker push` can hit: bogus upload session on
    PATCH/PUT, missing/mismatched digest on finalize, invalid manifest,
    digest-ref mismatch on manifest put."""

    async def main():
        async with Rig() as rig:
            await rig.expect(
                "PATCH", "/v2/repo/blobs/uploads/deadbeef",
                "BLOB_UPLOAD_UNKNOWN", 404, data=b"x",
            )
            await rig.expect(
                "PUT", f"/v2/repo/blobs/uploads/deadbeef?digest={GOOD}",
                "BLOB_UPLOAD_UNKNOWN", 404,
            )

            await rig.expect(
                "GET", "/v2/repo/blobs/uploads/deadbeef",
                "BLOB_UPLOAD_UNKNOWN", 404,
            )

            async def start_upload():
                async with rig.http.post(
                    rig.base + "/v2/repo/blobs/uploads/"
                ) as r:
                    assert r.status == 202
                    assert r.headers["Docker-Upload-UUID"]
                    return r.headers["Location"]

            # Status probe on a live session: 204 + committed Range.
            loc = await start_upload()
            async with rig.http.patch(rig.base + loc, data=b"12345") as r:
                assert r.status == 202
            async with rig.http.get(rig.base + loc) as r:
                assert r.status == 204
                assert r.headers["Range"] == "0-4"

            # Finalize without a digest parameter.
            loc = await start_upload()
            await rig.expect("PUT", loc, "DIGEST_INVALID", 400, data=b"data")
            # Finalize with a digest that doesn't match the content.
            loc = await start_upload()
            e = await rig.expect(
                "PUT", f"{loc}?digest={GOOD}", "DIGEST_INVALID", 400,
                data=b"data",
            )
            assert e["detail"]["computed"] == str(Digest.from_bytes(b"data"))
            # Manifest that isn't JSON.
            await rig.expect(
                "PUT", "/v2/repo/manifests/tag", "MANIFEST_INVALID", 400,
                data=b"\x00not json",
            )
            # Manifest pushed by digest whose URI ref mismatches the payload.
            await rig.expect(
                "PUT", f"/v2/repo/manifests/{GOOD}", "DIGEST_INVALID", 400,
                data=b"{}",
            )

    asyncio.run(main())


def test_mount_flow_falls_back_to_upload_session():
    """A failed cross-repo mount is NOT an error: the spec mandates
    falling back to a normal 202 upload session (docker relies on this
    to retry as a full upload)."""

    async def main():
        async with Rig() as rig:
            async with rig.http.post(
                rig.base + f"/v2/repo/blobs/uploads/?mount={GOOD}&from=other"
            ) as r:
                assert r.status == 202
                assert r.headers["Docker-Upload-UUID"]
                assert "/blobs/uploads/" in r.headers["Location"]
            # And a mountable blob answers 201 with no session.
            data = os.urandom(64)
            d = Digest.from_bytes(data)
            rig.transferer.blobs[str(d)] = data
            async with rig.http.post(
                rig.base + f"/v2/repo/blobs/uploads/?mount={d}&from=other"
            ) as r:
                assert r.status == 201
                assert r.headers["Docker-Content-Digest"] == str(d)

    asyncio.run(main())


def test_resume_flow_expired_session():
    """A purged (TTL-expired) upload session answers BLOB_UPLOAD_UNKNOWN:
    the client's signal to restart the push from POST."""

    async def main():
        async with Rig() as rig:
            async with rig.http.post(
                rig.base + "/v2/repo/blobs/uploads/"
            ) as r:
                uid = r.headers["Docker-Upload-UUID"]
            rig.server._uploads[uid] -= 10_000  # age past the TTL
            rig.server._purge_stale_uploads()
            await rig.expect(
                "PATCH", f"/v2/repo/blobs/uploads/{uid}",
                "BLOB_UPLOAD_UNKNOWN", 404, data=b"more",
            )

    asyncio.run(main())


def test_read_only_and_unsupported_methods():
    """Agent-flavor (read-only) registries reject every mutation with
    UNSUPPORTED; unknown methods on valid routes ditto."""

    async def main():
        async with Rig(read_only=True) as rig:
            await rig.expect(
                "POST", "/v2/repo/blobs/uploads/", "UNSUPPORTED", 405
            )
            await rig.expect(
                "PUT", "/v2/repo/manifests/tag", "UNSUPPORTED", 405,
                data=b"{}",
            )
        async with Rig() as rig:
            await rig.expect(
                "DELETE", "/v2/repo/manifests/tag", "UNSUPPORTED", 405
            )
            await rig.expect(
                "DELETE", f"/v2/repo/blobs/{GOOD}", "UNSUPPORTED", 405
            )

    asyncio.run(main())


def test_name_and_pagination_codes():
    """NAME_INVALID for out-of-grammar repo names, NAME_UNKNOWN for
    unknown repos on tags/list, PAGINATION_NUMBER_INVALID for bad ?n."""

    async def main():
        async with Rig() as rig:
            await rig.expect(
                "GET", f"/v2/UPPER/blobs/{GOOD}", "NAME_INVALID", 400
            )
            await rig.expect(
                "GET", "/v2/bad..name/manifests/tag", "NAME_INVALID", 400
            )
            # %20 decodes to a space: survives the router's `.+` pattern,
            # so OUR grammar check must reject it.
            await rig.expect(
                "GET", f"/v2/repo%20x/blobs/{GOOD}", "NAME_INVALID", 400
            )
            # Trailing newline never even matches the route (aiohttp `.+`
            # stops at \n) -- but the grammar must reject it anyway
            # (fullmatch, not $-anchored match) for any path that reaches
            # it another way.
            from kraken_tpu.dockerregistry.errors import check_repo_name
            from aiohttp import web as _web

            with pytest.raises(_web.HTTPBadRequest):
                check_repo_name("repo\n")
            await rig.expect(
                "GET", "/v2/norepo/tags/list", "NAME_UNKNOWN", 404
            )
            # A failing tag backend is a retryable 500, NOT a 404: docker
            # treats NAME_UNKNOWN as definitive and gives up.
            async def boom(repo):
                raise RuntimeError("backend down")

            rig.transferer.list_repo_tags = boom
            await rig.expect("GET", "/v2/repo/tags/list", "UNKNOWN", 500)
            del rig.transferer.list_repo_tags
            rig.transferer.tags["repo:v1"] = Digest.from_bytes(b"m")
            await rig.expect(
                "GET", "/v2/repo/tags/list?n=0",
                "PAGINATION_NUMBER_INVALID", 400,
            )
            await rig.expect(
                "GET", "/v2/repo/tags/list?n=x",
                "PAGINATION_NUMBER_INVALID", 400,
            )
            # Nested repo paths are valid names.
            async with rig.http.get(
                rig.base + "/v2/repo/tags/list"
            ) as r:
                assert r.status == 200
                assert await r.json() == {"name": "repo", "tags": ["v1"]}

    asyncio.run(main())


def test_transient_dependency_failures_are_retryable_5xx():
    """An unreachable origin/build-index must NOT surface as *_UNKNOWN:
    docker treats the 404 codes as final (pull aborts, mount probe falls
    back to full re-upload), while any 5xx is retried. Only a dependency's
    explicit 404 proves absence."""
    from kraken_tpu.utils.httputil import HTTPError

    async def main():
        async with Rig() as rig:
            transient = HTTPError("GET", "http://origin/blob", 503)

            async def down(*a, **kw):
                raise transient

            # Blob pull paths: HEAD stat + GET download_path. (HEAD has
            # no body to parse -- status + version header only.)
            rig.transferer.stat = down
            rig.transferer.download_path = down
            async with rig.http.head(
                rig.base + f"/v2/repo/blobs/{GOOD}"
            ) as r:
                assert r.status == 502
                assert (
                    r.headers["Docker-Distribution-API-Version"]
                    == "registry/2.0"
                )
            await rig.expect(
                "GET", f"/v2/repo/blobs/{GOOD}", "UNKNOWN", 502
            )
            # Manifest pull: tag resolution down, then manifest body down.
            rig.transferer.get_tag = down
            await rig.expect(
                "GET", "/v2/repo/manifests/v1", "UNKNOWN", 502
            )
            del rig.transferer.get_tag
            rig.transferer.tags["repo:v1"] = Digest.from_bytes(b"m")
            rig.transferer.download = down
            await rig.expect(
                "GET", "/v2/repo/manifests/v1", "UNKNOWN", 502
            )
            # A replica's explicit 404 stays the definitive code.
            async def gone(*a, **kw):
                raise HTTPError("GET", "http://origin/blob", 404)

            rig.transferer.download_path = gone
            await rig.expect(
                "GET", f"/v2/repo/blobs/{GOOD}", "BLOB_UNKNOWN", 404
            )

    asyncio.run(main())


def test_unhandled_exception_still_enveloped():
    """A bug (or unmapped dependency error) escaping a handler must still
    produce the UNKNOWN envelope + API-version header, not aiohttp's bare
    text/plain 500 -- clients parse every error body."""

    async def main():
        async with Rig() as rig:
            async def boom(*a, **kw):
                raise RuntimeError("wire tripped")

            # transferer.upload is called with no handler-level mapping:
            # the middleware catch-all must envelope it.
            rig.transferer.upload = boom
            await rig.expect(
                "PUT", "/v2/repo/manifests/v1", "UNKNOWN", 500,
                data=json.dumps({"mediaType": "x"}).encode(),
            )

    asyncio.run(main())


def test_transferer_get_tag_classifies_dependency_errors():
    """The REAL transferer classes (not the fake) must turn a build-index
    404 into None (proven absent) and let transient failures propagate --
    this is the seam the registry's 404-vs-502 mapping rests on."""
    from kraken_tpu.dockerregistry.transfer import (
        ProxyTransferer, ReadOnlyTransferer,
    )
    from kraken_tpu.utils.httputil import HTTPError

    class Tags:
        def __init__(self, exc):
            self.exc = exc

        async def get(self, tag):
            raise self.exc

    async def main():
        for cls in (ReadOnlyTransferer, ProxyTransferer):
            t = cls.__new__(cls)  # seam test: only the tag path is touched
            from kraken_tpu.utils.dedup import TTLCache

            t._tag_cache = TTLCache(0)
            t.tags = Tags(HTTPError("GET", "http://bi/tags/x", 404))
            assert await t.get_tag("repo:v1") is None
            t.tags = Tags(HTTPError("GET", "http://bi/tags/x", 503))
            with pytest.raises(HTTPError):
                await t.get_tag("repo:v1")

    asyncio.run(main())


def test_error_envelope_on_randomized_garbage():
    """Sweep randomized malformed requests across the whole v2 route
    table: EVERY non-2xx response must carry the JSON error envelope and
    the API-version header (except HEAD, which has no body). Guards
    future handlers against bypassing the envelope contract."""
    import random

    rng = random.Random(7)
    verbs = ["GET", "PUT", "POST", "PATCH", "DELETE", "HEAD"]
    segments = [
        "repo", "UPPER", "re..po", "%2e%2e", "sha256:zz", GOOD,
        "v1", "deadbeef", "", "a" * 300,
    ]
    templates = [
        "/v2/{0}/manifests/{1}",
        "/v2/{0}/blobs/{1}",
        "/v2/{0}/blobs/uploads/",
        "/v2/{0}/blobs/uploads/{1}",
        "/v2/{0}/tags/list?n={1}",
        "/v2/_catalog?last={0}",
    ]

    async def main():
        async with Rig() as rig:
            for _ in range(80):
                t = rng.choice(templates)
                path = t.format(rng.choice(segments), rng.choice(segments))
                method = rng.choice(verbs)
                body = rng.choice([b"", b"x", b"{}", b"\xff" * 64])
                async with rig.http.request(
                    method, rig.base + path, data=body
                ) as r:
                    if r.status >= 400:
                        assert (
                            r.headers.get("Docker-Distribution-API-Version")
                            == "registry/2.0"
                        ), (method, path, r.status)
                        if method != "HEAD":
                            text = await r.text()
                            body_json = json.loads(text)
                            assert "errors" in body_json, (method, path, text)

    asyncio.run(main())


def test_manifest_accept_negotiation():
    """VERDICT r4 #7: manifest GET/HEAD honors Accept. Stored-type
    listed, no header, or a wildcard -> 200 with the stored type; in
    STRICT mode (`registry_strict_accept: true`) a client pinned to
    types we don't hold -> typed 406 (extension code
    MANIFEST_NOT_ACCEPTABLE -- see API.md), never bytes it would choke
    on. Covered for docker-schema2, OCI manifest, and list types."""

    DOCKER2 = "application/vnd.docker.distribution.manifest.v2+json"
    OCI = "application/vnd.oci.image.manifest.v1+json"
    LIST = "application/vnd.docker.distribution.manifest.list.v2+json"
    OCI_INDEX = "application/vnd.oci.image.index.v1+json"

    async def main():
        async with Rig(strict_accept=True) as rig:
            stored = {}
            for tag, media in (
                ("docker2", DOCKER2), ("oci", OCI), ("list", LIST),
            ):
                body = json.dumps({"mediaType": media, "t": tag}).encode()
                d = Digest.from_bytes(body)
                rig.transferer.blobs[str(d)] = body
                rig.transferer.tags[f"repo:{tag}"] = d
                stored[tag] = (d, media, body)

            async def get(tag, accept, expect_status):
                headers = {"Accept": accept} if accept is not None else {}
                async with rig.http.get(
                    f"{rig.base}/v2/repo/manifests/{tag}", headers=headers
                ) as r:
                    assert r.status == expect_status, (
                        tag, accept, r.status, await r.text()
                    )
                    return r

            for tag, (_d, media, body) in stored.items():
                # exact type, wildcard, application/*, and no header serve
                r = await get(tag, media, 200)
                assert r.headers["Content-Type"] == media
                await get(tag, "*/*", 200)
                await get(tag, "application/*", 200)
                await get(tag, None, 200)
                # docker-style multi-type Accept including the stored one
                await get(tag, f"{OCI_INDEX}, {media};q=0.9", 200)

            # Pinned to the WRONG type: enveloped 406.
            err = await rig.expect(
                "GET", "/v2/repo/manifests/docker2", "MANIFEST_NOT_ACCEPTABLE",
                406, headers={"Accept": OCI},
            )
            assert err["detail"]["stored"] == DOCKER2
            await rig.expect(
                "GET", "/v2/repo/manifests/oci", "MANIFEST_NOT_ACCEPTABLE",
                406, headers={"Accept": f"{DOCKER2}, {LIST}"},
            )
            await rig.expect(
                "GET", "/v2/repo/manifests/list", "MANIFEST_NOT_ACCEPTABLE",
                406, headers={"Accept": OCI},
            )
            # HEAD negotiates identically (406, empty-body-safe).
            async with rig.http.head(
                f"{rig.base}/v2/repo/manifests/docker2",
                headers={"Accept": OCI},
            ) as r:
                assert r.status == 406

    asyncio.run(main())


def test_manifest_accept_lenient_by_default():
    """ADVICE r5: strict Accept is opt-in. By DEFAULT a client pinned to
    a type we don't hold still gets the stored bytes with the stored
    Content-Type (the reference's behavior) -- older docker/containerd
    clients send narrow Accept headers yet parse the bytes fine, and a
    406 would fail pulls that used to work."""

    DOCKER2 = "application/vnd.docker.distribution.manifest.v2+json"
    OCI = "application/vnd.oci.image.manifest.v1+json"

    async def main():
        async with Rig() as rig:  # strict_accept defaults to False
            body = json.dumps({"mediaType": DOCKER2, "t": "x"}).encode()
            d = Digest.from_bytes(body)
            rig.transferer.blobs[str(d)] = body
            rig.transferer.tags["repo:docker2"] = d
            async with rig.http.get(
                f"{rig.base}/v2/repo/manifests/docker2",
                headers={"Accept": OCI},  # pinned to a type we don't hold
            ) as r:
                assert r.status == 200, await r.text()
                assert r.headers["Content-Type"] == DOCKER2
                assert await r.read() == body

    asyncio.run(main())


def test_manifest_without_media_type_never_406s():
    """OCI 1.0 manifests may omit mediaType; our docker-typed GUESS must
    not be grounds for refusing a pinned client -- the stored bytes may
    well be what the client wants."""
    OCI = "application/vnd.oci.image.manifest.v1+json"

    async def main():
        async with Rig() as rig:
            body = json.dumps({"schemaVersion": 2, "config": {}}).encode()
            d = Digest.from_bytes(body)
            rig.transferer.blobs[str(d)] = body
            rig.transferer.tags["repo:untyped"] = d
            async with rig.http.get(
                f"{rig.base}/v2/repo/manifests/untyped",
                headers={"Accept": OCI},
            ) as r:
                assert r.status == 200, await r.text()

    asyncio.run(main())

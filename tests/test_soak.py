"""Chaos soak: continuous push/pull traffic WHILE an origin dies and
revives. Every other failure test freezes the world around one injected
fault; real clusters take faults under load. This drives the whole stack
-- chunked uploads, ring replication, P2P pulls through agents, repair --
concurrently with the outage and asserts nothing is lost and nothing is
corrupt at the end.

Kept to ~15 s wall so it stays in the default suite; crank BLOBS /
durations for a longer manual soak.
"""

import asyncio
import os
import socket

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.placement.healthcheck import PassiveFilter
from kraken_tpu.utils.httputil import HTTPClient, HTTPError

BLOBS = 14
BLOB_BYTES = 96_000


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _origin(tmp_path, name, addrs, port):
    node = OriginNode(
        store_root=str(tmp_path / name),
        http_port=port,
        ring=Ring(HostList(static=addrs), max_replica=2),
        self_addr=f"127.0.0.1:{port}",
        dedup=False,
        health_interval_seconds=0.2,
        health_fail_threshold=2,
    )
    return node


def test_soak_push_pull_through_origin_outage(tmp_path):
    asyncio.run(_drive(tmp_path))


async def _drive(tmp_path):
    ports = [_free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in ports]

    tracker = TrackerNode(
        announce_interval_seconds=0.1,
        peer_ttl_seconds=5.0,
        ring_refresh_seconds=0.2,
    )
    await tracker.start()
    origins = {}
    for i in range(3):
        n = _origin(tmp_path, f"o{i}", addrs, ports[i])
        n.tracker_addr = tracker.addr
        await n.start()
        origins[i] = n

    health = PassiveFilter(fail_threshold=1, cooldown_seconds=0.5)
    cluster = ClusterClient(
        Ring(HostList(static=addrs), max_replica=2, health_filter=health.filter),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
        health=health,
    )
    tracker.server.origin_cluster = cluster

    agents = []
    for i in range(2):
        a = AgentNode(
            store_root=str(tmp_path / f"a{i}"), tracker_addr=tracker.addr
        )
        await a.start()
        agents.append(a)

    http = HTTPClient(timeout_seconds=30)
    uploaded: dict[str, bytes] = {}  # digest hex -> bytes, as they land
    errors: list[str] = []

    async def uploader():
        """One blob every ~0.25 s, through the outage. Uploads ride the
        cluster client's replica fan-out; a replica being dead mid-fan
        must not fail the upload (>=1 acceptance wins)."""
        for i in range(BLOBS):
            blob = os.urandom(BLOB_BYTES) + i.to_bytes(4, "big")
            d = Digest.from_bytes(blob)
            try:
                await cluster.upload("ns", d, blob)
                uploaded[d.hex] = blob
            except Exception as e:
                errors.append(f"upload {i}: {e!r}")
            await asyncio.sleep(0.25)

    async def puller(agent, name):
        """Pull everything that exists, repeatedly, verifying bytes.
        Exits once the uploader has finished AND every blob that actually
        landed has been pulled -- gating on BLOBS would spin until the
        outer timeout if an upload failed, and that timeout would mask
        the collected error details."""
        seen: set[str] = set()
        while not (uploading.done() and seen >= uploaded.keys()):
            for hexd, blob in list(uploaded.items()):
                try:
                    got = await http.get(
                        f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
                    )
                except HTTPError as e:
                    if e.status >= 500:
                        continue  # transient during the outage: retry later
                    errors.append(f"{name} pull {hexd[:8]}: {e!r}")
                    seen.add(hexd)
                    continue
                if got != blob:
                    errors.append(f"{name} pull {hexd[:8]}: BYTES DIFFER")
                seen.add(hexd)
            await asyncio.sleep(0.05)

    async def chaos():
        """Kill an origin 1.5 s in, revive it at the same address 2 s
        later, while traffic continues."""
        await asyncio.sleep(1.5)
        victim = 1
        await origins[victim].stop()
        await asyncio.sleep(2.0)
        reborn = _origin(tmp_path / "reborn", f"o{victim}", addrs, ports[victim])
        reborn.tracker_addr = tracker.addr
        await reborn.start()
        origins[victim] = reborn

    uploading = asyncio.create_task(uploader())
    chaos_task = asyncio.create_task(chaos())
    pullers = [
        asyncio.create_task(puller(a, f"agent{i}"))
        for i, a in enumerate(agents)
    ]
    try:
        await asyncio.wait_for(uploading, 30)
        await asyncio.wait_for(chaos_task, 30)
        await asyncio.wait_for(asyncio.gather(*pullers), 60)

        assert not errors, "\n".join(errors)
        assert len(uploaded) == BLOBS, f"only {len(uploaded)} uploads landed"
        # Final sweep: every blob byte-identical via BOTH agents.
        for agent in agents:
            for hexd, blob in uploaded.items():
                got = await http.get(
                    f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
                )
                assert got == blob, f"final pull differs: {hexd[:8]}"
    finally:
        for t in (uploading, chaos_task, *pullers):
            if not t.done():
                t.cancel()
        await http.close()
        await cluster.close()
        for a in agents:
            await a.stop()
        for n in origins.values():
            await n.stop()
        await tracker.stop()

"""Two-tier soak harness: the long-lived-fleet survival tests.

Tier 1 (unmarked, ~20 s): the chaos soak -- continuous push/pull traffic
WHILE an origin dies and revives -- now closed out by a resource audit.
Every other failure test freezes the world around one injected fault;
real clusters take faults under load, and real fleets die of what the
fault tests never measure: the fd that didn't close, the task that was
never reaped, the pooled buffer that never came back, the spool file
nobody swept. The sentinel (kraken_tpu/utils/resources.py) is the
oracle: after the drive, fd delta 0, bufpool fully returned, stores
free of debris -- and the conftest task tripwire asserts zero leaked
asyncio tasks.

Tier 2 (``slow`` + ``soak`` markers, gated on ``KT_SOAK=1``,
5-10 min): the origin soak a production fleet hits weekly but no test
runs -- conn churn, watermark eviction, repeated torrent
create/teardown, seeded failpoints (disconnects, announce errors,
ENOSPC mid-PATCH) -- asserting fd count stable, RSS slope ~ 0 by least
squares over the sentinel's sample history, and a clean store at exit:

    KT_SOAK=1 python -m pytest tests/test_soak.py -q -m slow

``KT_SOAK_SECONDS`` overrides the default 600 s load window (shorter
windows measure the allocator warm-up ramp, not steady state -- see the
``rss_curve_mb`` report field). Measured numbers are recorded in
PERF.md ("Fleet-survival soak").
"""

import asyncio
import gc
import json
import os
import random
import socket
import time

import pytest

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.origin.metainfogen import PieceLengthConfig
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.placement.healthcheck import PassiveFilter
from kraken_tpu.store.cleanup import CleanupConfig
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.httputil import HTTPClient, HTTPError
from kraken_tpu.utils.resources import open_fd_count, scan_store_orphans

BLOBS = 14
BLOB_BYTES = 96_000


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _origin(tmp_path, name, addrs, port, **kw):
    node = OriginNode(
        store_root=str(tmp_path / name),
        http_port=port,
        ring=Ring(HostList(static=addrs), max_replica=2),
        self_addr=f"127.0.0.1:{port}",
        dedup=False,
        health_interval_seconds=0.2,
        health_fail_threshold=2,
        **kw,
    )
    return node


async def _settle_fds(baseline: int, seconds: float = 5.0) -> int:
    """Wait (bounded) for deferred closes -- transports retired via
    call_soon, lingering objects waiting on GC -- then return the fd
    count. The soak asserts against BASELINE, so a leak fails after the
    full grace, never flakily before it."""
    deadline = time.monotonic() + seconds
    while True:
        gc.collect()
        n = open_fd_count()
        if n is not None and n <= baseline:
            return n
        if time.monotonic() >= deadline:
            return n
        await asyncio.sleep(0.1)


def _strict_debris(store) -> dict:
    """Post-teardown debris scan: NOTHING transient is acceptable once a
    node has stopped cleanly, so every class counts at any age."""
    return scan_store_orphans(
        store, upload_ttl_seconds=0.001, min_age_seconds=0.0
    )


def _lsq_slope(points: list[tuple[float, float]]) -> float:
    """Least-squares slope (units/second) over (t, value) samples."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    denom = sum((t - mt) ** 2 for t, _ in points)
    if denom == 0:
        return 0.0
    return sum((t - mt) * (v - mv) for t, v in points) / denom


# -- tier 1: chaos mini-soak with the resource audit -----------------------

def test_soak_push_pull_through_origin_outage(tmp_path):
    asyncio.run(_drive(tmp_path))


async def _drive(tmp_path):
    # The fd baseline is taken INSIDE the loop (the loop's own epoll and
    # self-pipe fds exist on both sides of the measurement) before any
    # node exists; after teardown the process must be back to exactly
    # this number -- the whole-stack fd-hygiene contract.
    gc.collect()
    fd_baseline = open_fd_count()

    ports = [_free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in ports]

    tracker = TrackerNode(
        announce_interval_seconds=0.1,
        peer_ttl_seconds=5.0,
        ring_refresh_seconds=0.2,
    )
    await tracker.start()
    # Spool hygiene is part of the soak contract: the victim origin dies
    # mid-upload, stranding a spool file its client will never commit.
    # The production wall-clock sweep must reclaim it before the final
    # audit -- the same plane that keeps a real origin's upload/ dir
    # bounded (store/cleanup.py).
    cleanup = CleanupConfig(
        tti_seconds=3600.0,
        interval_seconds=0.5,
        upload_ttl_seconds=3.0,
    )
    origins = {}
    all_nodes = []
    for i in range(3):
        n = _origin(tmp_path, f"o{i}", addrs, ports[i], cleanup=cleanup)
        n.tracker_addr = tracker.addr
        await n.start()
        origins[i] = n
        all_nodes.append(n)

    health = PassiveFilter(fail_threshold=1, cooldown_seconds=0.5)
    cluster = ClusterClient(
        Ring(HostList(static=addrs), max_replica=2, health_filter=health.filter),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
        health=health,
    )
    tracker.server.origin_cluster = cluster

    agents = []
    for i in range(2):
        a = AgentNode(
            store_root=str(tmp_path / f"a{i}"), tracker_addr=tracker.addr
        )
        await a.start()
        agents.append(a)
        all_nodes.append(a)

    http = HTTPClient(timeout_seconds=30)
    uploaded: dict[str, bytes] = {}  # digest hex -> bytes, as they land
    errors: list[str] = []
    dead_nodes: list = []  # stopped nodes whose stores no sweep serves

    async def uploader():
        """One blob every ~0.25 s, through the outage. Uploads ride the
        cluster client's replica fan-out; a replica being dead mid-fan
        must not fail the upload (>=1 acceptance wins)."""
        for i in range(BLOBS):
            blob = os.urandom(BLOB_BYTES) + i.to_bytes(4, "big")
            d = Digest.from_bytes(blob)
            try:
                await cluster.upload("ns", d, blob)
                uploaded[d.hex] = blob
            except Exception as e:
                errors.append(f"upload {i}: {e!r}")
            await asyncio.sleep(0.25)

    async def puller(agent, name):
        """Pull everything that exists, repeatedly, verifying bytes.
        Exits once the uploader has finished AND every blob that actually
        landed has been pulled -- gating on BLOBS would spin until the
        outer timeout if an upload failed, and that timeout would mask
        the collected error details."""
        seen: set[str] = set()
        while not (uploading.done() and seen >= uploaded.keys()):
            for hexd, blob in list(uploaded.items()):
                try:
                    got = await http.get(
                        f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
                    )
                except HTTPError as e:
                    if e.status >= 500:
                        continue  # transient during the outage: retry later
                    errors.append(f"{name} pull {hexd[:8]}: {e!r}")
                    seen.add(hexd)
                    continue
                if got != blob:
                    errors.append(f"{name} pull {hexd[:8]}: BYTES DIFFER")
                seen.add(hexd)
            await asyncio.sleep(0.05)

    async def chaos():
        """Kill an origin 1.5 s in, revive it at the same address 2 s
        later, while traffic continues."""
        await asyncio.sleep(1.5)
        victim = 1
        dead_nodes.append(origins[victim])
        await origins[victim].stop()
        await asyncio.sleep(2.0)
        reborn = _origin(
            tmp_path / "reborn", f"o{victim}", addrs, ports[victim],
            cleanup=cleanup,
        )
        reborn.tracker_addr = tracker.addr
        await reborn.start()
        origins[victim] = reborn
        all_nodes.append(reborn)

    uploading = asyncio.create_task(uploader())
    chaos_task = asyncio.create_task(chaos())
    pullers = [
        asyncio.create_task(puller(a, f"agent{i}"))
        for i, a in enumerate(agents)
    ]
    try:
        await asyncio.wait_for(uploading, 30)
        await asyncio.wait_for(chaos_task, 30)
        await asyncio.wait_for(asyncio.gather(*pullers), 60)

        assert not errors, "\n".join(errors)
        assert len(uploaded) == BLOBS, f"only {len(uploaded)} uploads landed"
        # Final sweep: every blob byte-identical via BOTH agents.
        for agent in agents:
            for hexd, blob in uploaded.items():
                got = await http.get(
                    f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
                )
                assert got == blob, f"final pull differs: {hexd[:8]}"

        # Torrent create/teardown churn: evict pulled blobs from an
        # agent and pull them again -- the full unseed -> re-announce ->
        # re-allocate -> re-download cycle, the lifecycle a fleet runs
        # thousands of times a day (each cycle must return every fd,
        # lease, and task it took).
        victim_agent = agents[0]
        for hexd, blob in list(uploaded.items())[:3]:
            await http.delete(f"http://{victim_agent.addr}/blobs/{hexd}")
            got = await http.get(
                f"http://{victim_agent.addr}/namespace/ns/blobs/{hexd}"
            )
            assert got == blob, f"re-pull after delete differs: {hexd[:8]}"

        # The dead victim's store has no node sweeping it anymore --
        # exactly what production handles with the boot-time fsck on
        # that root. Run the same reconciliation offline; anything it
        # cannot reclaim is a real leak and fails the audit below.
        from kraken_tpu.store.recovery import run_fsck

        for n in dead_nodes:
            await asyncio.to_thread(
                run_fsck, n.store,
                upload_ttl_seconds=3.0, expect_namespace=True,
            )
        # Let the live nodes' wall-clock sweeps reclaim any spool an
        # interrupted upload stranded (upload_ttl 3 s + sweep interval).
        deadline = asyncio.get_running_loop().time() + 10.0
        while asyncio.get_running_loop().time() < deadline:
            if all(
                not os.listdir(n.store.upload_dir) for n in all_nodes
            ):
                break
            await asyncio.sleep(0.25)

        # -- the resource audit (tier-1 sentinel contract) ----------------
        # Bufpool fully returned: every piece ever received gave its
        # lease back (the wire plane's no-leak invariant under churn,
        # outage, AND delete/re-pull).
        for n in all_nodes:
            sched = n.scheduler
            if sched is not None:
                assert sched._bufpool.leased == 0, (
                    f"{n.store.root}: {sched._bufpool.leased} leases out"
                )
        # Zero debris in any store: no spool, no .part/.alloc, no orphan
        # or tmp sidecars, nothing quarantined.
        for n in all_nodes:
            debris = _strict_debris(n.store)
            assert not any(debris.values()), (
                f"{n.store.root}: debris after soak: {debris}"
            )
    finally:
        for t in (uploading, chaos_task, *pullers):
            if not t.done():
                t.cancel()
        await http.close()
        await cluster.close()
        for a in agents:
            await a.stop()
        for n in origins.values():
            await n.stop()
        await tracker.stop()

    # fd delta 0: everything the soak opened -- listeners, p2p conns,
    # torrent fds, sqlite retry DBs, aiohttp sessions -- is closed.
    fd_after = await _settle_fds(fd_baseline)
    assert fd_after == fd_baseline, (
        f"fd leak: {fd_baseline} before soak, {fd_after} after"
    )


# -- tier 1: mini-soak with the multi-core data plane ----------------------

def test_mini_soak_with_data_plane_workers(tmp_path):
    """The worker-shard lifecycle under real node churn: pulls served
    through forked shards (sendfile path), delete -> re-pull torrent
    cycles (evict fan-out to workers), then full teardown. The audit is
    the fleet-survival contract extended to the children: fd delta
    exactly 0 in the parent, bufpool fully returned, zero store debris,
    and ZERO orphaned worker processes after stop."""
    asyncio.run(_drive_workers(tmp_path))


async def _drive_workers(tmp_path):
    gc.collect()
    fd_baseline = open_fd_count()

    port = _free_port()
    addr = f"127.0.0.1:{port}"
    tracker = TrackerNode(
        announce_interval_seconds=0.1,
        peer_ttl_seconds=5.0,
        ring_refresh_seconds=0.2,
    )
    await tracker.start()
    origin = _origin(
        tmp_path, "o0", [addr], port,
        scheduler_config_doc={"data_plane_workers": 2},
    )
    origin.tracker_addr = tracker.addr
    await origin.start()
    cluster = ClusterClient(
        Ring(HostList(static=[addr]), max_replica=1),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
    )
    tracker.server.origin_cluster = cluster
    agent = AgentNode(
        store_root=str(tmp_path / "a0"), tracker_addr=tracker.addr
    )
    await agent.start()
    http = HTTPClient(timeout_seconds=30)
    worker_pids: list[int] = []
    try:
        pool = origin.scheduler._shardpool
        assert pool is not None and pool.alive_workers == 2
        worker_pids = [w["pid"] for w in pool.worker_info()]

        from kraken_tpu.utils.metrics import REGISTRY

        def served_bytes() -> float:
            c = REGISTRY.counter("data_plane_worker_bytes_sent_total")
            return sum(
                c.value(shard=f"data_plane_shard{i}") for i in range(2)
            )
        served0 = served_bytes()

        blobs: dict[str, bytes] = {}
        for i in range(4):
            blob = os.urandom(BLOB_BYTES) + i.to_bytes(4, "big")
            d = Digest.from_bytes(blob)
            await cluster.upload("ns", d, blob)
            blobs[d.hex] = blob
        for hexd, blob in blobs.items():
            got = await http.get(
                f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
            )
            assert got == blob, f"worker-served pull differs: {hexd[:8]}"
        # Torrent churn THROUGH the worker plane: delete + re-pull runs
        # the evict fan-out (workers drop fds, close conns) and fresh
        # handoffs, the cycle a fleet runs thousands of times a day.
        for hexd, blob in list(blobs.items())[:2]:
            await http.delete(f"http://{agent.addr}/blobs/{hexd}")
            got = await http.get(
                f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
            )
            assert got == blob, f"re-pull after delete differs: {hexd[:8]}"

        # The bytes genuinely moved through shards (stats pipe lands on
        # a 0.25 s cadence -- poll briefly).
        deadline = time.monotonic() + 5.0
        while served_bytes() <= served0 and time.monotonic() < deadline:
            await asyncio.sleep(0.1)
        assert served_bytes() > served0, "no bytes served via worker shards"

        # Leases fully returned on both schedulers (the agent received
        # through the bufpool; origin serves bypassed it entirely).
        for sched in (origin.scheduler, agent.scheduler):
            for _ in range(100):
                if sched._bufpool.leased == 0:
                    break
                await asyncio.sleep(0.02)
            assert sched._bufpool.leased == 0
        for store in (origin.store, agent.store):
            debris = _strict_debris(store)
            assert not any(debris.values()), f"debris: {debris}"
    finally:
        await http.close()
        await agent.stop()
        await cluster.close()
        await origin.stop()
        await tracker.stop()

    # Zero orphaned worker processes: every shard was reaped at stop.
    assert worker_pids, "no worker shards observed"
    for pid in worker_pids:
        try:
            os.kill(pid, 0)
            raise AssertionError(f"orphaned data-plane worker pid {pid}")
        except ProcessLookupError:
            pass

    fd_after = await _settle_fds(fd_baseline)
    assert fd_after == fd_baseline, (
        f"fd leak with workers: {fd_baseline} before, {fd_after} after"
    )


# -- tier 1: mini-soak with the multi-core LEECH plane ---------------------

def test_mini_soak_with_leech_workers(tmp_path):
    """The fleet-survival contract extended to the DOWNLOAD plane's
    forked shards: pulls pumped through leech workers (shared-ring recv
    + worker pwrite), delete -> re-pull torrent cycles (evict fan-out
    closes the workers' writable fds), full teardown. fd delta exactly
    0 in the parent, every ring slot lease returned, bufpool clean,
    zero store debris, ZERO orphaned worker processes."""
    asyncio.run(_drive_leech_workers(tmp_path))


async def _drive_leech_workers(tmp_path):
    from kraken_tpu.p2p.scheduler import SchedulerConfig

    gc.collect()
    fd_baseline = open_fd_count()

    port = _free_port()
    addr = f"127.0.0.1:{port}"
    tracker = TrackerNode(
        announce_interval_seconds=0.1,
        peer_ttl_seconds=5.0,
        ring_refresh_seconds=0.2,
    )
    await tracker.start()
    # Both planes forked at once: origin serves through seed shards,
    # the agent pumps downloads through leech shards.
    origin = _origin(
        tmp_path, "o0", [addr], port,
        scheduler_config_doc={"data_plane_workers": 1},
    )
    origin.tracker_addr = tracker.addr
    await origin.start()
    cluster = ClusterClient(
        Ring(HostList(static=[addr]), max_replica=1),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
    )
    tracker.server.origin_cluster = cluster
    agent = AgentNode(
        store_root=str(tmp_path / "a0"),
        tracker_addr=tracker.addr,
        scheduler_config=SchedulerConfig.from_dict(
            {"leech_workers": 2, "leech_ring_mb": 8}
        ),
    )
    await agent.start()
    http = HTTPClient(timeout_seconds=30)
    worker_pids: list[int] = []
    try:
        pool = agent.scheduler._leech_pool
        assert pool is not None and pool.alive_workers == 2
        worker_pids = [w["pid"] for w in pool.worker_info()]
        worker_pids += [
            w["pid"] for w in origin.scheduler._shardpool.worker_info()
        ]

        from kraken_tpu.utils.metrics import REGISTRY

        def ring_pieces() -> float:
            c = REGISTRY.counter("data_plane_worker_pieces_total")
            return sum(c.value(shard=f"leech_shard{i}") for i in range(2))
        pieces0 = ring_pieces()

        blobs: dict[str, bytes] = {}
        for i in range(4):
            blob = os.urandom(BLOB_BYTES) + i.to_bytes(4, "big")
            d = Digest.from_bytes(blob)
            await cluster.upload("ns", d, blob)
            blobs[d.hex] = blob
        for hexd, blob in blobs.items():
            got = await http.get(
                f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
            )
            assert got == blob, f"leech-pumped pull differs: {hexd[:8]}"
        # Torrent churn THROUGH the leech plane: delete + re-pull runs
        # the evict fan-out (workers drop their writable .part fds) and
        # fresh handoffs.
        for hexd, blob in list(blobs.items())[:2]:
            await http.delete(f"http://{agent.addr}/blobs/{hexd}")
            got = await http.get(
                f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
            )
            assert got == blob, f"re-pull after delete differs: {hexd[:8]}"

        # Pieces genuinely landed through the shared ring (stats pipe
        # lands on a 0.25 s cadence -- poll briefly).
        deadline = time.monotonic() + 5.0
        while ring_pieces() <= pieces0 and time.monotonic() < deadline:
            await asyncio.sleep(0.1)
        assert ring_pieces() > pieces0, "no pieces via leech shards"

        # Every ring slot lease returned, both bufpools clean.
        for _ in range(100):
            if pool.slot_leases == 0:
                break
            await asyncio.sleep(0.02)
        assert pool.slot_leases == 0, (
            f"{pool.slot_leases} ring slot leases never returned"
        )
        for sched in (origin.scheduler, agent.scheduler):
            for _ in range(100):
                if sched._bufpool.leased == 0:
                    break
                await asyncio.sleep(0.02)
            assert sched._bufpool.leased == 0
        for store in (origin.store, agent.store):
            debris = _strict_debris(store)
            assert not any(debris.values()), f"debris: {debris}"
    finally:
        await http.close()
        await agent.stop()
        await cluster.close()
        await origin.stop()
        await tracker.stop()

    # Zero orphaned worker processes on EITHER plane.
    assert worker_pids, "no worker shards observed"
    for pid in worker_pids:
        try:
            os.kill(pid, 0)
            raise AssertionError(f"orphaned worker pid {pid}")
        except ProcessLookupError:
            pass

    fd_after = await _settle_fds(fd_baseline)
    assert fd_after == fd_baseline, (
        f"fd leak with leech workers: {fd_baseline} before, {fd_after} after"
    )


# -- tier 2: gated origin soak (KT_SOAK=1, -m slow) ------------------------

@pytest.mark.slow
@pytest.mark.soak
def test_origin_soak_fleet_survival(tmp_path):
    """5-10 min of what a production origin lives through in a week:
    continuous ingest, watermark eviction, conn churn, torrent
    create/teardown, seeded faults -- with the sentinel sampling every
    second and the exit asserting the fleet-survival invariants."""
    # 600 s default: the RSS curve's allocator-ratchet knee takes
    # ~300 s to converge on this rig (see rss_curve_mb in the report);
    # the slope audit needs a fully-converged second half. Shorter
    # windows measure the ramp and false-positive.
    seconds = float(os.environ.get("KT_SOAK_SECONDS", "600"))
    report = asyncio.run(_long_soak(tmp_path, seconds))
    print("\nSOAK_REPORT " + json.dumps(report))
    assert not report["errors"], "\n".join(report["errors"])
    assert report["fd_delta_teardown"] == 0, report
    # Steady-state drift bands (measured headroom in PERF.md): an fd
    # leaked per torrent cycle would drift hundreds over the run; RSS
    # creep past ~32 KiB/s compounds to >100 MiB/hour -- the weekly OOM.
    assert abs(report["fd_slope_per_min"]) < 2.0, report
    assert abs(report["rss_slope_kib_per_s"]) < 32.0, report
    assert report["bufpool_leased"] == 0, report
    assert report["debris"] == 0, report


async def _long_soak(tmp_path, seconds: float) -> dict:
    # A 5-min soak emits thousands of INFO records (aiohttp access log
    # per announce/pull, per-torrent completion lines). In production
    # they stream to stdout; under pytest the logging plugin RETAINS
    # every record in memory for the test report -- which reads as a
    # steady ~300 KiB/s RSS "leak" that is pure harness accumulation
    # (confirmed: the same load outside pytest plateaus). Suppress
    # below-WARNING records for the soak window so the sentinel
    # measures the product, not the test runner.
    import logging

    logging.disable(logging.INFO)
    try:
        return await _long_soak_inner(tmp_path, seconds)
    finally:
        logging.disable(logging.NOTSET)


async def _long_soak_inner(tmp_path, seconds: float) -> dict:
    gc.collect()
    fd_baseline = open_fd_count()
    rng = random.Random(1)

    # Seeded faults, the production failpoint plane (utils/failpoints.py):
    # random disconnects mid-transfer, announce errors, ENOSPC mid-PATCH.
    # Deterministic per seed; disarmed (and verified clean) at exit.
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    failpoints.FAILPOINTS.arm("p2p.conn.disconnect", "prob:0.002+seed:17")
    failpoints.FAILPOINTS.arm("tracker.announce.error", "prob:0.02+seed:23")
    failpoints.FAILPOINTS.arm("origin.patch.write", "prob:0.01+seed:29")

    ports = [_free_port() for _ in range(2)]
    addrs = [f"127.0.0.1:{p}" for p in ports]
    tracker = TrackerNode(
        announce_interval_seconds=0.5,
        peer_ttl_seconds=10.0,
        ring_refresh_seconds=1.0,
    )
    await tracker.start()

    # Small pieces + tight watermarks: a 300 s run then covers hundreds
    # of torrent lifecycles and dozens of eviction sweeps.
    # Watermarks sized so the fill phase ends within the first ~quarter
    # of the run at this rig's measured ingest rate: the slope audit
    # needs a long steady-state window (store at watermark, eviction
    # churning), not a ramp.
    origin_cleanup = CleanupConfig(
        tti_seconds=3600.0,
        high_watermark_bytes=12 << 20,
        low_watermark_bytes=8 << 20,
        interval_seconds=1.0,
        upload_ttl_seconds=5.0,
    )
    resources = {"interval_seconds": 1.0, "orphan_min_age_seconds": 30.0}
    # Announce pacing must stay production-SHAPED at test scale: two
    # origins seeding ~125 torrents each against one in-process tracker
    # on a small rig would, at the default 100/s per-scheduler cap, put
    # a 300+ rps announce storm on the shared event loop and starve the
    # data plane (measured: 2 s/upload, announce deadlines firing).
    announce_pacing = {
        "announce_interval_seconds": 1.0,
        "seed_announce_interval_seconds": 5.0,
        "max_announce_rate": 20.0,
    }
    origins = []
    for i in range(2):
        n = _origin(
            tmp_path, f"o{i}", addrs, ports[i],
            cleanup=origin_cleanup,
            piece_lengths=PieceLengthConfig(table=((0, 32 * 1024),)),
            resources=resources,
            scheduler_config_doc=dict(announce_pacing),
        )
        n.tracker_addr = tracker.addr
        await n.start()
        origins.append(n)

    health = PassiveFilter(fail_threshold=2, cooldown_seconds=1.0)
    cluster = ClusterClient(
        Ring(HostList(static=addrs), max_replica=2,
             health_filter=health.filter),
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=1)),
        health=health,
    )
    tracker.server.origin_cluster = cluster

    from kraken_tpu.p2p.scheduler import SchedulerConfig

    agents = []
    for i in range(2):
        a = AgentNode(
            store_root=str(tmp_path / f"a{i}"),
            tracker_addr=tracker.addr,
            cleanup=CleanupConfig(
                tti_seconds=3600.0,
                high_watermark_bytes=8 << 20,
                low_watermark_bytes=6 << 20,
                interval_seconds=1.0,
                upload_ttl_seconds=5.0,
            ),
            scheduler_config=SchedulerConfig(
                conn_churn_idle_seconds=2.0,
                **announce_pacing,
            ),
            resources=resources,
        )
        await a.start()
        agents.append(a)

    all_nodes = [*origins, *agents]
    http = HTTPClient(timeout_seconds=60)
    uploaded: list[tuple[str, bytes]] = []  # recent (hex, bytes)
    counters = {"uploads": 0, "upload_failures": 0, "pulls": 0,
                "pull_misses": 0, "deletes": 0}
    errors: list[str] = []
    stop_load = asyncio.Event()

    async def uploader():
        i = 0
        while not stop_load.is_set():
            blob = os.urandom(192_000) + i.to_bytes(4, "big")
            d = Digest.from_bytes(blob)
            try:
                await cluster.upload("ns", d, blob)
                uploaded.append((d.hex, blob))
                counters["uploads"] += 1
                del uploaded[:-40]  # older blobs may be evicted; drop refs
            except Exception:
                # Injected ENOSPC / replica churn: the pusher's retry is
                # the next cycle, exactly like a real client.
                counters["upload_failures"] += 1
            i += 1
            await asyncio.sleep(0.25)

    async def puller(agent, name):
        while not stop_load.is_set():
            if not uploaded:
                await asyncio.sleep(0.2)
                continue
            hexd, blob = rng.choice(uploaded[-20:])
            try:
                got = await asyncio.wait_for(
                    http.get(
                        f"http://{agent.addr}/namespace/ns/blobs/{hexd}"
                    ),
                    30,
                )
                counters["pulls"] += 1
                if got != blob:
                    errors.append(f"{name} {hexd[:8]}: BYTES DIFFER")
            except (HTTPError, asyncio.TimeoutError):
                counters["pull_misses"] += 1  # eviction/fault churn
                await asyncio.sleep(0.2)
                continue
            if rng.random() < 0.1:
                # Torrent teardown: evict locally, next pull recreates
                # the torrent from scratch through the swarm.
                try:
                    await http.delete(f"http://{agent.addr}/blobs/{hexd}")
                    counters["deletes"] += 1
                except HTTPError:
                    pass
            await asyncio.sleep(rng.uniform(0.05, 0.3))

    load = [
        asyncio.create_task(uploader()),
        *(asyncio.create_task(puller(a, f"agent{i}"))
          for i, a in enumerate(agents)),
    ]

    # KT_SOAK_TRACEMALLOC=1: python-heap diff between mid-run and end,
    # printed with the report -- the "is the RSS slope heap or
    # allocator" diagnostic for when the band ever trips.
    trace = os.environ.get("KT_SOAK_TRACEMALLOC") == "1"
    snap_mid = None
    if trace:
        import tracemalloc

        tracemalloc.start(10)

    t0 = time.monotonic()
    await asyncio.sleep(seconds / 2)
    if trace:
        import tracemalloc

        gc.collect()
        snap_mid = tracemalloc.take_snapshot()
    await asyncio.sleep(seconds / 2)
    stop_load.set()
    await asyncio.gather(*load, return_exceptions=True)
    if trace:
        import tracemalloc

        gc.collect()
        snap_end = tracemalloc.take_snapshot()
        print("\n=== python-heap growth, mid-run -> end ===")
        for s in snap_end.compare_to(snap_mid, "lineno")[:15]:
            print(s)
        cur, peak = tracemalloc.get_traced_memory()
        print(f"traced current={cur/1e6:.1f}MB peak={peak/1e6:.1f}MB")
        tracemalloc.stop()

    # Settle: disarm faults, let in-flight pieces land, conns churn out,
    # and the wall-clock sweeps reclaim every failed upload's spool
    # (upload_ttl 5 s + interval 1 s) before the strict audit.
    failpoints.FAILPOINTS.disarm_all()
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if all(not os.listdir(n.store.upload_dir) for n in all_nodes):
            break
        await asyncio.sleep(0.5)

    # Sentinel sample series (1 Hz, from the origins' own sentinels):
    # fd and RSS slopes over the steady-state window -- the first half
    # is excluded (allocator warm-up, store fill to the watermark, pool
    # warm-up all RATCHET memory by design; the leak question is what
    # happens once eviction churn holds the store at the watermark).
    hist = list(origins[0].sentinel.history)
    cut = max(2, len(hist) // 2)
    fd_series = [(t, fd) for t, fd, _ in hist[cut:] if fd is not None]
    rss_series = [(t, rss) for t, _, rss in hist[cut:] if rss is not None]
    fd_slope = _lsq_slope(fd_series)
    rss_slope = _lsq_slope(rss_series)

    leased = sum(
        n.scheduler._bufpool.leased
        for n in all_nodes if n.scheduler is not None
    )
    retained_mb = sum(
        n.scheduler._bufpool.retained_bytes
        for n in all_nodes if n.scheduler is not None
    ) / (1 << 20)
    controls = {
        f"node{i}": len(n.scheduler._controls)
        for i, n in enumerate(all_nodes) if n.scheduler is not None
    }
    last = origins[0].sentinel.last_sample or {}
    samples = len(hist)

    await http.close()
    await cluster.close()
    for a in agents:
        await a.stop()
    for n in origins:
        await n.stop()
    await tracker.stop()

    debris_by_store = {
        n.store.root: _strict_debris(n.store) for n in all_nodes
    }
    debris_total = sum(
        sum(d.values()) for d in debris_by_store.values()
    )
    for root, d in debris_by_store.items():
        if any(d.values()):
            errors.append(f"debris in {root}: {d}")

    fd_after = await _settle_fds(fd_baseline, seconds=10.0)

    return {
        "seconds": round(time.monotonic() - t0, 1),
        "counters": counters,
        "sentinel_samples": samples,
        "fd_baseline": fd_baseline,
        "fd_after_teardown": fd_after,
        "fd_delta_teardown": fd_after - fd_baseline,
        "fd_slope_per_min": round(fd_slope * 60.0, 3),
        "rss_slope_kib_per_s": round(rss_slope / 1024.0, 3),
        "rss_first_mb": round(rss_series[0][1] / (1 << 20), 1)
        if rss_series else None,
        "rss_last_mb": round(rss_series[-1][1] / (1 << 20), 1)
        if rss_series else None,
        # Decimated full-run curve (MB): the shape is the diagnostic --
        # concave-flattening = allocator ratchet converging (transient
        # peaks, heap flat; see TESTING.md), linear = a real leak.
        "rss_curve_mb": [
            round(rss / (1 << 20), 1)
            for _t, _fd, rss in hist[:: max(1, len(hist) // 20)]
            if rss is not None
        ],
        "bufpool_leased": leased,
        "bufpool_retained_mb": round(retained_mb, 1),
        "torrent_controls": controls,
        "tasks_last_sample": last.get("tasks"),
        "top_task_sites": last.get("top_task_sites"),
        "debris": debris_total,
        "errors": errors,
    }

"""Self-healing storage plane: startup fsck, background scrub, and the
robustness satellites that ride with them (persistedretry timeout/poll
resilience).

The fsck contract is crash-safety BOTH ways: every planted orphan class
is repaired, and a live upload spool or healthy committed blob is NEVER
touched. The scrub contract is bounded IO (token bucket) and quarantine
-- corrupt bytes move aside for post-mortem, never silently vanish.
"""

import asyncio
import os
import sqlite3
import time

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.persistedretry import Manager, Task, TaskStore
from kraken_tpu.store import CAStore
from kraken_tpu.store.metadata import NamespaceMetadata, TTIMetadata
from kraken_tpu.store.recovery import (
    EXIT_CLEAN,
    EXIT_REPAIRED,
    EXIT_UNHEALABLE,
    quarantine_namespace,
    read_clean_shutdown,
    run_fsck,
    write_clean_shutdown,
)
from kraken_tpu.store.scrub import ScrubConfig, Scrubber
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.backoff import Backoff
from kraken_tpu.utils.metrics import REGISTRY

STALE = 8 * 3600  # seconds past any default TTL


def _store(tmp_path, name="store") -> CAStore:
    return CAStore(str(tmp_path / name))


def _put(store: CAStore, data: bytes, ns: str | None = "testns") -> Digest:
    d = Digest.from_bytes(data)
    store.create_cache_file(d, iter([data]))
    if ns is not None:
        store.set_metadata(d, NamespaceMetadata(ns))
    return d


def _backdate(path: str, seconds: float = STALE) -> None:
    t = time.time() - seconds
    os.utime(path, (t, t))


def _plant_orphan_sidecar(s: CAStore, hex_: str) -> str:
    """A sidecar whose data file never existed (its shard directory
    included -- normally the data commit creates it)."""
    d = Digest.from_hex(hex_)
    os.makedirs(os.path.dirname(s.cache_path(d)), exist_ok=True)
    s.set_metadata(d, TTIMetadata(1.0))
    return s.cache_path(d) + "._md_tti"


# -- fsck: orphan classes ----------------------------------------------------


def test_fsck_clean_store_is_a_noop(tmp_path):
    s = _store(tmp_path)
    d = _put(s, os.urandom(10_000))
    report = run_fsck(s, expect_namespace=True, verify="all")
    assert report.clean and report.exit_code == EXIT_CLEAN
    assert report.verified == 1
    assert s.read_cache_file(d) == s.read_cache_file(d)  # still readable


def test_fsck_removes_orphan_sidecar_but_keeps_partial_bitfield(tmp_path):
    s = _store(tmp_path)
    # Orphan: sidecar with neither data nor .part beside it.
    orphan = _plant_orphan_sidecar(s, "a" * 64)
    assert os.path.exists(orphan)
    d_fake = Digest.from_hex("a" * 64)
    # NOT orphan: piece-status sidecar next to a live partial download.
    d_part = Digest.from_hex("b" * 64)
    s.allocate_partial_file(d_part, 4096)
    s.set_metadata(d_part, TTIMetadata(456.0))

    report = run_fsck(s)
    assert report.repairs == {"orphan_sidecar": 1}
    assert report.exit_code == EXIT_REPAIRED
    assert not os.path.exists(s.cache_path(d_fake) + "._md_tti")
    # The resumable download's state survived untouched.
    assert s.has_partial(d_part)
    assert os.path.exists(s.cache_path(d_part) + "._md_tti")


def test_fsck_adopts_orphan_data_on_origins_only(tmp_path):
    s = _store(tmp_path)
    d = _put(s, os.urandom(5_000), ns=None)  # no namespace sidecar

    # Agent semantics: no namespace expected, data left exactly as-is.
    report = run_fsck(s, expect_namespace=False)
    assert report.clean
    assert s.get_metadata(d, NamespaceMetadata) is None

    # Origin semantics: re-adopt under the default namespace so the
    # repair/writeback planes can see the blob again.
    report = run_fsck(s, expect_namespace=True)
    assert report.repairs == {"adopted": 1}
    md = s.get_metadata(d, NamespaceMetadata)
    assert md is not None and md.namespace == "default"
    # Idempotent: a second pass is clean.
    assert run_fsck(s, expect_namespace=True).clean


def test_fsck_sweeps_stale_spool_never_live_uploads(tmp_path):
    s = _store(tmp_path)
    live = s.create_upload()
    s.write_upload_chunk(live, 0, b"in flight")
    stale = s.create_upload()
    _backdate(s.upload_path(stale))

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.repairs == {"stale_spool": 1}
    assert s.upload_exists(live), "fsck must NEVER touch a live upload"
    assert not s.upload_exists(stale)
    # The live upload still commits normally after fsck.
    data = b"in flight"
    d = Digest.from_bytes(data)
    s.commit_upload(live, d)
    assert s.read_cache_file(d) == data


def test_fsck_sweeps_stale_partials_with_their_sidecars(tmp_path):
    s = _store(tmp_path)
    # Stale partial download + its piece-status sidecar: both must go in
    # ONE pass (the sidecar would otherwise survive as a fresh orphan).
    d_stale = Digest.from_hex("c" * 64)
    s.allocate_partial_file(d_stale, 1024)
    s.set_metadata(d_stale, TTIMetadata(1.0))
    _backdate(s.partial_path(d_stale))
    _backdate(s.cache_path(d_stale) + "._md_tti")
    # Torn .alloc staging file from a crashed allocate.
    alloc = s.partial_path(d_stale) + ".alloc"
    with open(alloc, "wb") as f:
        f.truncate(1024)
    _backdate(alloc)
    # Fresh partial: resumable, untouched.
    d_live = Digest.from_hex("d" * 64)
    s.allocate_partial_file(d_live, 1024)

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.repairs == {"stale_partial": 2, "orphan_sidecar": 1}
    assert not s.has_partial(d_stale)
    assert not os.path.exists(alloc)
    assert not os.path.exists(s.cache_path(d_stale) + "._md_tti")
    assert s.has_partial(d_live)


def test_fsck_removes_metadata_tmp_survivors(tmp_path):
    s = _store(tmp_path)
    d = _put(s, os.urandom(1_000))
    # A set_metadata that died between tmp write and rename.
    torn = s.cache_path(d) + "._md_tti.tmp12345.678"
    with open(torn, "wb") as f:
        f.write(b"torn")
    report = run_fsck(s)
    assert report.repairs == {"tmp_sidecar": 1}
    assert not os.path.exists(torn)
    # The real blob and sidecar are untouched.
    assert s.in_cache(d)
    assert s.get_metadata(d, NamespaceMetadata) is not None


# -- fsck: crash-window verify ----------------------------------------------


def test_fsck_crash_window_verify_quarantines_torn_blob(tmp_path):
    s = _store(tmp_path)
    old_blob = os.urandom(8_000)
    d_old = _put(s, old_blob)
    write_clean_shutdown(s)
    # Corrupt a blob "written" after the stamp (torn crash-window write):
    # newer mtime than the stamp, wrong content.
    torn = os.urandom(8_000)
    d_torn = _put(s, torn, ns="crashns")
    with open(s.cache_path(d_torn), "r+b") as f:
        f.seek(100)
        f.write(b"\x00" * 16)
    future = time.time() + 5
    os.utime(s.cache_path(d_torn), (future, future))
    # Also corrupt the OLD blob on disk -- auto mode must NOT look at it
    # (its mtime predates the stamp; the background scrub owns it).
    _backdate(s.cache_path(d_old))

    report = run_fsck(s, verify="auto")
    assert report.verified == 1
    assert report.quarantined == [d_torn.hex]
    assert report.exit_code == EXIT_UNHEALABLE
    assert not s.in_cache(d_torn)
    assert os.path.exists(s.quarantine_path(d_torn))
    # The namespace rode into quarantine with the blob -- the heal plane
    # re-fetches under it.
    assert quarantine_namespace(s, d_torn.hex) == "crashns"
    # Healthy old blob untouched.
    assert s.in_cache(d_old)


def test_fsck_no_stamp_skips_auto_verify_but_starts_the_clock(tmp_path):
    s = _store(tmp_path)
    d = _put(s, os.urandom(2_000))
    with open(s.cache_path(d), "r+b") as f:
        f.write(b"\xff" * 8)
    report = run_fsck(s, verify="auto")  # no stamp: nothing to compare
    assert report.verified == 0 and s.in_cache(d)
    # ...but the pass STAMPS, so a first-boot crash loop is not blind
    # forever: the next crash window has a reference point.
    assert read_clean_shutdown(s) is not None
    torn = os.urandom(2_000)
    d2 = _put(s, torn)
    with open(s.cache_path(d2), "r+b") as f:
        f.write(b"\x00" * 8)
    future = time.time() + 5
    os.utime(s.cache_path(d2), (future, future))
    report = run_fsck(s, verify="auto")
    assert report.quarantined == [d2.hex]
    # verify=all catches the pre-stamp rot regardless.
    report = run_fsck(s, verify="all")
    assert report.quarantined == [d.hex]


def test_fsck_bumps_stamp_so_crash_loops_stay_bounded(tmp_path):
    s = _store(tmp_path)
    _put(s, os.urandom(1_000))
    write_clean_shutdown(s, now=1000.0)  # ancient stamp (weeks-old stop)
    run_fsck(s, verify="auto")
    # The repairing pass moved the stamp to now: the next boot of a
    # crash-looping node re-verifies only blobs written SINCE this one.
    assert read_clean_shutdown(s) > 1000.0
    # Report-only runs examined nothing and must not claim otherwise.
    write_clean_shutdown(s, now=2000.0)
    run_fsck(s, verify="none")
    assert read_clean_shutdown(s) == 2000.0


def test_clean_shutdown_stamp_roundtrip(tmp_path):
    s = _store(tmp_path)
    assert read_clean_shutdown(s) is None
    write_clean_shutdown(s, now=1234.5)
    assert read_clean_shutdown(s) == 1234.5
    write_clean_shutdown(s)  # rewrite moves it forward
    assert read_clean_shutdown(s) > 1234.5


def test_fsck_orphan_failpoint_plants_and_repairs(tmp_path):
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    try:
        s = _store(tmp_path)
        failpoints.FAILPOINTS.arm("store.fsck.orphan", "once")
        report = run_fsck(s)
        # The planted orphan is removed by the same pass -- the chaos
        # tier can prove the repair plane executes in a live node.
        assert report.repairs.get("orphan_sidecar") == 1
    finally:
        failpoints.FAILPOINTS.disarm_all()
        failpoints.allow(False)


# -- offline CLI: kraken-tpu fsck -------------------------------------------


def test_cli_fsck_exit_codes(tmp_path):
    from kraken_tpu import cli

    root = str(tmp_path / "clistore")
    s = CAStore(root)
    d = _put(s, os.urandom(3_000))

    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root])
    assert e.value.code == EXIT_CLEAN

    # Planted orphan -> repaired -> 1.
    _plant_orphan_sidecar(s, "e" * 64)
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root])
    assert e.value.code == EXIT_REPAIRED

    # Corrupt blob + --verify all -> unhealable -> 2.
    with open(s.cache_path(d), "r+b") as f:
        f.write(b"\x00" * 4)
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root, "--verify", "all"])
    assert e.value.code == EXIT_UNHEALABLE

    # Typo'd root is a USAGE error (3), distinct from unhealable (2):
    # deploy tooling must not chase quarantined blobs that don't exist,
    # and the path was never examined so it cannot read as clean.
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", str(tmp_path / "no-such-store")])
    assert e.value.code == 3


# -- scrubber ----------------------------------------------------------------


def test_scrub_detects_quarantines_and_reports(tmp_path):
    s = _store(tmp_path)
    good = [os.urandom(30_000) for _ in range(2)]
    goods = [_put(s, b) for b in good]
    rotted = os.urandom(30_000)
    d_rot = _put(s, rotted, ns="rotns")
    with open(s.cache_path(d_rot), "r+b") as f:
        f.seek(11_000)
        f.write(b"\x5a")  # one flipped byte of bit-rot

    events = []
    corr0 = REGISTRY.counter("scrub_corruptions_total").value(source="scrub")

    async def main():
        sc = Scrubber(
            s,
            ScrubConfig(bytes_per_second=0, chunk_bytes=8192),
            on_corrupt=lambda d, ns: events.append((d.hex, ns)),
        )
        return await sc.run_cycle()

    bad = asyncio.run(main())
    assert [b.hex for b in bad] == [d_rot.hex]
    assert events == [(d_rot.hex, "rotns")]
    assert (
        REGISTRY.counter("scrub_corruptions_total").value(source="scrub")
        == corr0 + 1
    )
    # Quarantined, not deleted: the damaged bytes are the post-mortem.
    assert not s.in_cache(d_rot)
    with open(s.quarantine_path(d_rot), "rb") as f:
        captured = f.read()
    assert captured != rotted and len(captured) == len(rotted)
    assert s.list_quarantined() == [d_rot.hex]
    # Healthy blobs bit-identical and still cached.
    for d, b in zip(goods, good):
        assert s.read_cache_file(d) == b


def test_scrub_bitflip_failpoint_damages_disk_then_detects(tmp_path):
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    try:
        s = _store(tmp_path)
        blob = os.urandom(20_000)
        d = _put(s, blob)
        failpoints.FAILPOINTS.arm("store.scrub.bitflip", "once")

        async def main():
            sc = Scrubber(s, ScrubConfig(bytes_per_second=0))
            return await sc.run_cycle()

        bad = asyncio.run(main())
        assert [b.hex for b in bad] == [d.hex]
        # REAL at-rest damage: the quarantined capture differs from the
        # original bytes (the flip hit the platter, not a read buffer).
        with open(s.quarantine_path(d), "rb") as f:
            assert f.read() != blob
    finally:
        failpoints.FAILPOINTS.disarm_all()
        failpoints.allow(False)


def test_scrub_io_budget_every_byte_through_the_token_bucket(tmp_path):
    """IO-bound proof without wall-clock flakiness: every read chunk
    must acquire exactly its size from the bucket BEFORE the next read,
    so the observed read rate can never exceed what TokenBucket grants
    (TokenBucket's own pacing math is covered in test_utils)."""
    s = _store(tmp_path)
    sizes = [100_000, 65_536, 3]
    for n in sizes:
        _put(s, os.urandom(n))

    acquired = []

    class RecordingBucket:
        async def acquire(self, n):
            acquired.append(n)

    async def main():
        sc = Scrubber(s, ScrubConfig(bytes_per_second=64_000, chunk_bytes=16_384))
        # The real bucket carries the configured budget...
        assert sc._bucket.rate == 64_000
        # ...and at least one chunk of burst so acquire(chunk) is
        # satisfiable without the oversize escape hatch.
        assert sc._bucket.capacity >= 16_384
        sc._bucket = RecordingBucket()
        await sc.run_cycle()

    asyncio.run(main())
    assert sum(acquired) == sum(sizes)
    assert all(n <= 16_384 for n in acquired)


def test_scrub_reuses_hash_pool_for_digest_work(tmp_path):
    from kraken_tpu.core.hasher import CPUPieceHasher

    s = _store(tmp_path)
    blob = os.urandom(50_000)
    d = _put(s, blob)
    hasher = CPUPieceHasher(workers=2)

    async def main():
        sc = Scrubber(s, ScrubConfig(bytes_per_second=0), hasher=hasher)
        assert sc._pool is hasher.pool
        return await sc.run_cycle()

    assert asyncio.run(main()) == []  # clean store: pooled path agrees
    assert s.read_cache_file(d) == blob


# -- node wiring: fsck at start, stamp at stop -------------------------------


def test_origin_node_fscks_on_start_and_stamps_on_stop(tmp_path):
    from kraken_tpu.assembly import OriginNode

    async def main():
        root = str(tmp_path / "origin")
        _plant_orphan_sidecar(CAStore(root), "f" * 64)
        node = OriginNode(store_root=root, dedup=False)
        await node.start()
        try:
            assert node.fsck_report is not None
            assert node.fsck_report.repairs == {"orphan_sidecar": 1}
        finally:
            await node.stop()
        assert read_clean_shutdown(node.store) is not None
        # Second boot: clean tree, and the stamp bounds the verify set.
        node2 = OriginNode(store_root=root, dedup=False)
        await node2.start()
        try:
            assert node2.fsck_report.clean
        finally:
            await node2.stop()

    asyncio.run(main())


# -- persistedretry satellites -----------------------------------------------


def test_retry_task_timeout_reschedules_and_counts():
    async def main():
        m = Manager(
            TaskStore(":memory:"),
            backoff=Backoff(base_seconds=100.0, max_seconds=1000.0, jitter=0),
            task_timeout_seconds=0.05,
        )
        started = asyncio.Event()

        async def hang(task):
            started.set()
            await asyncio.sleep(60)

        done = []

        async def quick(task):
            done.append(task.key)

        m.register("hang", hang)
        m.register("quick", quick)
        m.add(Task(kind="hang", key="h", payload={}))
        m.add(Task(kind="quick", key="q", payload={}))
        t0 = REGISTRY.counter("retry_task_timeouts_total").value(kind="hang")
        ok = await m.run_once()
        # The hung task was cut at the timeout (counted + rescheduled
        # with backoff) and did NOT stall the other kind.
        assert started.is_set()
        assert ok == 1 and done == ["q"]
        assert (
            REGISTRY.counter("retry_task_timeouts_total").value(kind="hang")
            == t0 + 1
        )
        pending = m.store.all_pending()
        assert len(pending) == 1 and pending[0].kind == "hang"
        assert pending[0].attempts == 1
        assert pending[0].not_before > time.time() + 50  # backoff applied

    asyncio.run(main())


def test_retry_poll_survives_store_errors():
    class FlakyStore(TaskStore):
        def __init__(self):
            super().__init__(":memory:")
            self.failures_left = 2

        def ready(self, now, limit=100):
            if self.failures_left > 0:
                self.failures_left -= 1
                raise sqlite3.OperationalError("disk I/O error")
            return super().ready(now, limit)

    async def main():
        m = Manager(FlakyStore(), poll_interval_seconds=0.01)
        done = []

        async def ok(task):
            done.append(task.key)

        m.register("k", ok)
        m.add(Task(kind="k", key="x", payload={}))
        base = REGISTRY.counter("retry_poll_errors_total").value()
        m.start()
        try:
            deadline = asyncio.get_running_loop().time() + 10
            while not done:
                assert asyncio.get_running_loop().time() < deadline, (
                    "poll loop died instead of riding out the store error"
                )
                await asyncio.sleep(0.01)
        finally:
            m.stop()
        assert done == ["x"]
        assert (
            REGISTRY.counter("retry_poll_errors_total").value() == base + 2
        )

    asyncio.run(main())


def test_scrub_treats_unreadable_blob_as_corrupt(tmp_path, monkeypatch):
    """EIO on a dying sector is the scrubber's primary real-world find:
    it must quarantine + report, never silently skip (only a vanished
    file -- evicted mid-scrub -- is benign)."""
    s = _store(tmp_path)
    blob = os.urandom(10_000)
    d = _put(s, blob, ns="eions")
    real_open = s.open_cache_file

    def eio_open(dd):
        if dd.hex == d.hex:
            raise OSError(5, "Input/output error")
        return real_open(dd)

    monkeypatch.setattr(s, "open_cache_file", eio_open)
    events = []

    async def main():
        sc = Scrubber(
            s,
            ScrubConfig(bytes_per_second=0),
            on_corrupt=lambda dd, ns: events.append((dd.hex, ns)),
        )
        return await sc.run_cycle()

    bad = asyncio.run(main())
    assert [b.hex for b in bad] == [d.hex]
    assert events == [(d.hex, "eions")]
    assert not s.in_cache(d) and s.list_quarantined() == [d.hex]


def test_fsck_unreadable_blob_quarantines_not_aborts(tmp_path, monkeypatch):
    s = _store(tmp_path)
    d = _put(s, os.urandom(5_000))
    import builtins

    real_open = builtins.open
    target = s.cache_path(d)

    def eio_open(path, *a, **kw):
        if path == target and a[:1] == ("rb",):
            raise OSError(5, "Input/output error")
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", eio_open)
    report = run_fsck(s, verify="all")
    # The pass completed (no raise) and the unreadable blob is
    # unhealable, not invisible.
    assert report.quarantined == [d.hex]
    assert report.exit_code == EXIT_UNHEALABLE


def test_disk_usage_counts_quarantine(tmp_path):
    """Quarantined bytes are real disk: watermark math must see them or
    the volume fills toward ENOSPC behind the accounting's back."""
    s = _store(tmp_path)
    blob = os.urandom(40_000)
    d = _put(s, blob)
    before = s.disk_usage_bytes()
    assert before >= len(blob)
    s.quarantine_cache_file(d)
    after = s.disk_usage_bytes()
    assert after >= len(blob), "quarantine move must not hide the bytes"
    assert abs(after - before) < 1024  # move, not copy


def test_retry_task_timeout_is_plumbed_from_assembly():
    from kraken_tpu.assembly import BuildIndexNode, OriginNode
    import inspect

    for cls in (OriginNode, BuildIndexNode):
        sig = inspect.signature(cls.__init__)
        assert "task_timeout_seconds" in sig.parameters, cls


def test_heal_never_trusts_an_unverified_cached_copy(tmp_path):
    """A corrupt blob can still sit in cache/ when the heal task runs
    (fsck's quarantine move failed on a dying disk). The heal must
    re-verify before declaring 'cached', move the rot aside, and -- with
    no replica or backend to restore from -- raise so the retry plane
    keeps trying, rather than re-seeding corrupt bytes as healed."""
    from kraken_tpu.backend import BlobNotFoundError
    from kraken_tpu.origin.metainfogen import Generator
    from kraken_tpu.origin.server import OriginServer, _heal_task

    async def main():
        s = _store(tmp_path)
        blob = os.urandom(9_000)
        d = _put(s, blob, ns="healns")
        with await asyncio.to_thread(open, s.cache_path(d), "r+b") as f:
            f.seek(50)
            f.write(b"\x13\x37")
        retry = Manager(TaskStore(":memory:"))
        server = OriginServer(s, Generator(s), retry=retry)
        heals0 = REGISTRY.counter("blob_heals_total").value(source="cached")
        with pytest.raises(BlobNotFoundError):
            await server._execute_heal(_heal_task("healns", d))
        # The corrupt copy was moved aside, never blessed as healed.
        assert not s.in_cache(d)
        assert s.list_quarantined() == [d.hex]
        assert (
            REGISTRY.counter("blob_heals_total").value(source="cached")
            == heals0
        )

        # A genuinely healthy cached copy (racing restore) IS accepted.
        d2 = _put(s, os.urandom(4_000), ns="healns")
        await server._execute_heal(_heal_task("healns", d2))
        assert (
            REGISTRY.counter("blob_heals_total").value(source="cached")
            == heals0 + 1
        )

    asyncio.run(main())


# -- crash-safe resumable sessions: fsck / scrub / cleanup guards ------------


def _journal(s: CAStore, uid: str, digest_hex: str, offset: int = 0) -> None:
    s.write_upload_session(
        uid,
        {
            "version": 1,
            "digest": digest_hex,
            "namespace": "testns",
            "offset": offset,
            "piece_length": 65536,
            "piece_hashes": "",
        },
    )


def test_fsck_preserves_live_journaled_session(tmp_path):
    """A fresh spool + its session journal is a RESUMABLE upload: fsck
    must leave both exactly in place for the restarted origin to adopt."""
    s = _store(tmp_path)
    uid = s.create_upload()
    s.write_upload_chunk(uid, 0, b"still arriving")
    _journal(s, uid, "e" * 64, offset=14)

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.clean, report.repairs
    assert s.upload_exists(uid)
    assert s.read_upload_session(uid) is not None


def test_fsck_sweeps_orphan_journal_and_tmp_debris(tmp_path):
    """A journal whose spool is gone (crash between commit's rename and
    the journal unlink) and a torn .tmp journal write are both debris."""
    s = _store(tmp_path)
    _journal(s, "deadbeef" * 4, "f" * 64)
    torn = os.path.join(
        s.upload_dir, "cafecafe" * 4 + CAStore.SESSION_SUFFIX + ".tmp.1234"
    )
    with open(torn, "wb") as f:
        f.write(b"{torn")

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.repairs == {"upload_session": 2}
    assert s.read_upload_session("deadbeef" * 4) is None
    assert not os.path.exists(torn)


def test_fsck_resume_false_clears_journals_keeps_fresh_spool(tmp_path):
    """resume=False (the rollback knob) drops every journal -- sessions
    degrade to size-based resume -- without touching live spools."""
    s = _store(tmp_path)
    uid = s.create_upload()
    s.write_upload_chunk(uid, 0, b"bytes")
    _journal(s, uid, "a" * 64, offset=5)

    report = run_fsck(s, upload_ttl_seconds=3600, resume=False)
    assert report.repairs == {"upload_session": 1}
    assert s.upload_exists(uid), "the spool itself is still live"
    assert s.read_upload_session(uid) is None


def test_fsck_ttl_stale_spool_takes_its_journal_with_it(tmp_path):
    """Spool + journal age out as ONE unit: a swept spool must not leave
    its journal behind as a next-pass orphan (or worse, a live-digest
    entry shielding sidecars forever)."""
    s = _store(tmp_path)
    uid = s.create_upload()
    s.write_upload_chunk(uid, 0, b"abandoned")
    _journal(s, uid, "b" * 64, offset=9)
    _backdate(s.upload_path(uid))

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.repairs == {"stale_spool": 1}
    assert not s.upload_exists(uid)
    assert s.read_upload_session(uid) is None


def test_fsck_keeps_early_publish_sidecar_for_live_session(tmp_path):
    """serve-while-ingest publishes metainfo sidecars BEFORE the data
    file exists; with a live journaled session for that digest the
    sidecar is NOT an orphan -- the resumed commit delivers its bytes.
    Once the session is gone the same sidecar is debris again."""
    s = _store(tmp_path)
    hex_ = "c" * 64
    sidecar = _plant_orphan_sidecar(s, hex_)
    uid = s.create_upload()
    s.write_upload_chunk(uid, 0, b"tail en route")
    _journal(s, uid, hex_, offset=13)

    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.clean, report.repairs
    assert os.path.exists(sidecar)

    # Session gone (abort clears spool+journal): now it IS an orphan.
    s.abort_upload(uid)
    report = run_fsck(s, upload_ttl_seconds=3600)
    assert report.repairs == {"orphan_sidecar": 1}
    assert not os.path.exists(sidecar)


def test_scrub_skips_blob_with_live_upload_session(tmp_path):
    """Satellite (c): a blob whose tail is still arriving (live session
    journal names its digest) must not be quarantined mid-ingest even if
    the cached bytes don't hash out yet; the next cycle -- session gone
    -- scrubs it for real."""
    s = _store(tmp_path)
    blob = os.urandom(20_000)
    d = _put(s, blob)
    with open(s.cache_path(d), "r+b") as f:
        f.seek(5_000)
        f.write(b"\x5a")  # reads as corrupt until the "tail" lands
    uid = s.create_upload()
    s.write_upload_chunk(uid, 0, b"x")
    _journal(s, uid, d.hex, offset=1)

    async def cycle():
        sc = Scrubber(s, ScrubConfig(bytes_per_second=0))
        return await sc.run_cycle()

    assert asyncio.run(cycle()) == []
    assert s.in_cache(d), "mid-ingest blob must never be quarantined"

    s.abort_upload(uid)
    bad = asyncio.run(cycle())
    assert [b.hex for b in bad] == [d.hex]
    assert not s.in_cache(d)


def test_cleanup_sweeps_spool_and_journal_as_unit(tmp_path):
    """Periodic cleanup mirrors fsck's session semantics: stale spool +
    journal go together, an orphan journal goes alone, a live journal is
    never unlinked out from under its spool."""
    from kraken_tpu.store.cleanup import CleanupConfig, CleanupManager

    s = _store(tmp_path)
    stale = s.create_upload()
    s.write_upload_chunk(stale, 0, b"abandoned")
    _journal(s, stale, "1" * 64)
    _backdate(s.upload_path(stale))
    live = s.create_upload()
    s.write_upload_chunk(live, 0, b"active")
    _journal(s, live, "2" * 64)
    _journal(s, "feedface" * 4, "3" * 64)  # orphan: no spool

    mgr = CleanupManager(s, CleanupConfig(tti_seconds=0, upload_ttl_seconds=3600))
    mgr.run_once()
    assert not s.upload_exists(stale)
    assert s.read_upload_session(stale) is None
    assert s.upload_exists(live)
    assert s.read_upload_session(live) is not None
    assert s.read_upload_session("feedface" * 4) is None


# -- hinted-handoff durability (quorum write plane) --------------------------


def test_hint_task_survives_taskstore_restart(tmp_path):
    """A journaled hint is a DURABILITY promise: it must ride sqlite
    across process death bit-for-bit (addr, namespace, digest, expiry),
    not live in an in-memory queue."""
    from kraken_tpu.origin.server import HINT_KIND, _hint_task

    d = Digest.from_bytes(b"hinted blob")
    expires = time.time() + 3600.0
    db = str(tmp_path / "retry.db")
    store = TaskStore(db)
    assert store.add(_hint_task("10.0.0.7:15003", "models", d, expires))
    store.close()

    reopened = TaskStore(db)
    try:
        assert reopened.count_pending(HINT_KIND, f"{d.hex}:") == 1
        (task,) = reopened.all_pending()
        assert task.kind == HINT_KIND
        assert task.payload == {
            "addr": "10.0.0.7:15003",
            "namespace": "models",
            "digest": d.hex,
            "expires_at": expires,
        }
        # Re-journaling the same hint is idempotent (same kind+key).
        assert not reopened.add(
            _hint_task("10.0.0.7:15003", "models", d, expires + 99)
        )
        assert reopened.count_pending(HINT_KIND, f"{d.hex}:") == 1
    finally:
        reopened.close()


def test_hint_executor_runs_exactly_once_per_journal_entry(tmp_path):
    async def main():
        from kraken_tpu.origin.server import HINT_KIND, _hint_task

        d = Digest.from_bytes(b"one replay")
        m = Manager(TaskStore(str(tmp_path / "retry.db")))
        runs = []
        m.register(HINT_KIND, lambda task: _record(runs, task))

        async def _record(log, task):
            log.append(task.key)

        m.add(_hint_task("127.0.0.1:9", "ns", d, time.time() + 3600.0))
        assert await m.run_once() == 1
        # Retired: further polls never see it again.
        assert await m.run_once() == 0
        assert await m.run_once(now=time.time() + 9999.0) == 0
        assert runs == [f"{d.hex}:ns:127.0.0.1:9"]
        assert m.store.count_pending(HINT_KIND) == 0
        m.close()

    asyncio.run(main())


def test_expired_hint_escalates_to_heal(tmp_path):
    """A hint whose TTL lapsed stops chasing the stale address and hands
    the blob to the heal plane, which repairs against CURRENT ring
    owners. The hint retires (no replay), `expired` is counted, and a
    heal task is journaled for the same blob."""

    async def main():
        from kraken_tpu.assembly import OriginNode
        from kraken_tpu.origin.server import HEAL_KIND, HINT_KIND, _hint_task

        node = OriginNode(store_root=str(tmp_path / "origin"), dedup=False)
        await node.start()
        node.retry.stop()
        try:
            blob = os.urandom(50_000)
            d = Digest.from_bytes(blob)
            from kraken_tpu.origin.client import BlobClient

            oc = BlobClient(node.addr)
            await oc.upload("ns", d, blob)
            await oc.close()

            node.retry.add(
                _hint_task("127.0.0.1:9", "ns", d, time.time() - 1.0)
            )
            expired0 = REGISTRY.counter("origin_hints_total").value(
                state="expired"
            )
            replayed0 = REGISTRY.counter("origin_hints_total").value(
                state="replayed"
            )
            await node.retry.run_once()
            assert (
                REGISTRY.counter("origin_hints_total").value(state="expired")
                == expired0 + 1
            )
            assert (
                REGISTRY.counter("origin_hints_total").value(state="replayed")
                == replayed0
            )
            assert node.retry.store.count_pending(HINT_KIND, f"{d.hex}:") == 0
            assert node.retry.store.count_pending(HEAL_KIND, d.hex) == 1
        finally:
            await node.stop()

    asyncio.run(main())

"""Data-plane streaming: large blobs move with O(piece) request memory.

VERDICT r2 missing #4: every hot endpoint used to buffer whole blobs in
RAM (agent GET, origin GET/replication, registry uploads, cluster upload).
These tests drive a real in-process herd with a blob several times larger
than the asserted allocation peak, so any whole-blob buffer on the path
fails loudly.
"""

import asyncio
import hashlib
import os
import tracemalloc

import numpy as np

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import CPUPieceHasher
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.origin.metainfogen import Generator, PieceLengthConfig
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.store import CAStore

# 96 MiB keeps the suite fast; KT_STREAM_TEST_MB=1024 runs the full
# >=1 GiB validation (verified passing 2026-07-30 post round-5 ingest
# rebuild: peak stays under the same 32 MiB bound -- 32x margin -- in
# ~24 s, was ~57 s before stream-time hashing removed the re-read pass).
BLOB_MB = int(os.environ.get("KT_STREAM_TEST_MB", "96"))
PIECE = 1 << 20  # 1 MiB pieces keep the in-flight bound tight
# The LEGITIMATE in-flight working set is pipeline depth (16) x piece
# (1 MiB) x live conns (up to 2 here) = 32 MiB -- and because this herd
# is single-process, the SEED side's concurrent pread serves and asyncio
# send buffers land in the same tracemalloc peak. Healthy runs measure
# ~28-42 MB depending on how deep the serve/recv pipelines stack under
# CPU contention (a 40 MiB bound flapped under full-suite load; 32 MiB
# flapped even solo). The round-8 tracing plane is NOT a contributor:
# measured 3x each on 2026-08-03, trace-off 30.2-32.1 MB vs shipped
# sampling 27.6-32.1 MB vs sample_rate=1.0 27.6-32.5 MB. 48 MiB keeps
# 2x margin against the whole-blob buffering failure this test exists
# to catch (96 MiB would blow it).
PEAK_BOUND = 48 << 20


def _write_blob(path: str, mb: int) -> Digest:
    """Write an ``mb``-MiB random blob chunk-by-chunk (never in RAM whole)."""
    h = hashlib.sha256()
    with open(path, "wb") as f:
        for _ in range(mb):
            chunk = os.urandom(1 << 20)
            h.update(chunk)
            f.write(chunk)
    return Digest.from_hex(h.hexdigest())


def test_large_blob_pull_memory_bounded(tmp_path):
    asyncio.run(_drive_large_pull(tmp_path))


async def _drive_large_pull(tmp_path):
    from aiohttp import ClientSession

    blob_path = str(tmp_path / "blob.bin")
    d = _write_blob(blob_path, BLOB_MB)

    tracker = TrackerNode(announce_interval_seconds=0.1, peer_ttl_seconds=5.0)
    await tracker.start()
    origin = OriginNode(
        store_root=str(tmp_path / "o"),
        tracker_addr=tracker.addr,
        dedup=False,  # focus the peak on the data plane
        piece_lengths=PieceLengthConfig(table=((0, PIECE),)),
        hash_window_bytes=4 * PIECE,
    )
    await origin.start()
    tracker.server.origin_cluster = ClusterClient(
        Ring(HostList(static=[origin.addr]))
    )
    agent = AgentNode(
        store_root=str(tmp_path / "a"), tracker_addr=tracker.addr
    )
    await agent.start()

    oc = BlobClient(origin.addr)
    try:
        tracemalloc.start(1)
        tracemalloc.reset_peak()

        # Upload: file-streamed chunked PATCHes into the origin.
        await oc.upload_from_file("ns", d, blob_path, chunk_size=4 * PIECE)

        # Pull through the agent (swarm download) and hash the stream.
        h = hashlib.sha256()
        n = 0
        async with ClientSession() as http:
            async with http.get(
                f"http://{agent.addr}/namespace/ns/blobs/{d.hex}"
            ) as r:
                assert r.status == 200
                async for chunk in r.content.iter_chunked(1 << 20):
                    h.update(chunk)
                    n += len(chunk)

        _cur, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()

        assert n == BLOB_MB << 20
        assert h.hexdigest() == d.hex
        assert peak < PEAK_BOUND, (
            f"data-plane allocation peak {peak / 1e6:.1f} MB for a "
            f"{BLOB_MB} MiB blob -- something buffered the blob"
        )
    finally:
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        await oc.close()
        await agent.stop()
        await origin.stop()
        if tracker.server.origin_cluster is not None:
            await tracker.server.origin_cluster.close()
        await tracker.stop()


def test_generator_hashes_in_windows(tmp_path):
    """Windowed metainfo generation matches the single-shot oracle,
    including a ragged tail piece crossing a window boundary."""
    store = CAStore(str(tmp_path))
    data = os.urandom(5 * 256 * 1024 + 12345)  # ragged tail piece
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    store.commit_upload(uid, d)

    pl = PieceLengthConfig(table=((0, 256 * 1024),))
    gen = Generator(store, piece_lengths=pl, window_bytes=512 * 1024)
    mi = gen.generate_sync(d)

    oracle = CPUPieceHasher().hash_pieces(data, 256 * 1024)
    assert mi.piece_hashes == oracle.tobytes()
    assert mi.length == len(data)
    assert np.frombuffer(mi.piece_hashes, dtype=np.uint8).size == oracle.size

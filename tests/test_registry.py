"""Docker-registry flow tests: push an image via the proxy's v2 API, pull
it via the agent's v2 API -- the reference's headline end-to-end scenario
(SURVEY.md SS3.1/SS3.2), plus tag replication between two clusters."""

import asyncio
import hashlib
import json
import os

import pytest

from kraken_tpu.assembly import (
    AgentNode,
    BuildIndexNode,
    OriginNode,
    ProxyNode,
    TrackerNode,
)
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.utils.httputil import HTTPClient, HTTPError


def make_image(nlayers=2, layer_size=50_000):
    """A synthetic docker image: config blob + layers + schema2 manifest."""
    layers = [os.urandom(layer_size) for _ in range(nlayers)]
    config = json.dumps({"architecture": "amd64", "os": "linux"}).encode()
    manifest = json.dumps(
        {
            "schemaVersion": 2,
            "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
            "config": {
                "mediaType": "application/vnd.docker.container.image.v1+json",
                "size": len(config),
                "digest": str(Digest.from_bytes(config)),
            },
            "layers": [
                {
                    "mediaType": "application/vnd.docker.image.rootfs.diff.tar.gzip",
                    "size": len(l),
                    "digest": str(Digest.from_bytes(l)),
                }
                for l in layers
            ],
        }
    ).encode()
    return config, layers, manifest


async def push_image(http: HTTPClient, registry: str, repo: str, tag: str,
                     config: bytes, layers: list[bytes], manifest: bytes):
    """Client-side of `docker push` against the v2 API."""
    for blob in [config, *layers]:
        d = Digest.from_bytes(blob)
        # monolithic upload: POST -> PUT?digest=
        import aiohttp

        session_resp = await http.request(
            "POST", f"http://{registry}/v2/{repo}/blobs/uploads/",
            ok_statuses=(202,),
        )
        # Location header isn't exposed by HTTPClient; re-derive via a raw call
        # -- use aiohttp session directly for header access.
        s = await http._get_session()
        async with s.post(f"http://{registry}/v2/{repo}/blobs/uploads/") as r:
            assert r.status == 202
            loc = r.headers["Location"]
        async with s.put(
            f"http://{registry}{loc}?digest={d}", data=blob
        ) as r:
            assert r.status == 201, await r.text()
    s = await http._get_session()
    async with s.put(
        f"http://{registry}/v2/{repo}/manifests/{tag}",
        data=manifest,
        headers={"Content-Type": "application/vnd.docker.distribution.manifest.v2+json"},
    ) as r:
        assert r.status == 201, await r.text()


async def pull_image(http: HTTPClient, registry: str, repo: str, tag: str):
    """Client-side of `docker pull`: manifest by tag, then every blob."""
    manifest = await http.get(f"http://{registry}/v2/{repo}/manifests/{tag}")
    doc = json.loads(manifest)
    blobs = {}
    for ref in [doc["config"], *doc["layers"]]:
        data = await http.get(f"http://{registry}/v2/{repo}/blobs/{ref['digest']}")
        assert str(Digest.from_bytes(data)) == ref["digest"]
        blobs[ref["digest"]] = data
    return manifest, blobs


async def build_cluster(tmp_path, name: str, remotes=None):
    """tracker + origin + build-index + proxy + agent, fully wired."""
    tracker = TrackerNode(announce_interval_seconds=0.1)
    await tracker.start()
    origin = OriginNode(
        store_root=str(tmp_path / name / "origin"), tracker_addr=tracker.addr
    )
    await origin.start()
    ring = Ring(HostList(static=[origin.addr]), max_replica=1)
    cluster = ClusterClient(ring)
    tracker.server.origin_cluster = cluster
    bindex = BuildIndexNode(
        store_root=str(tmp_path / name / "bindex"),
        remotes=remotes,
        origin_cluster=cluster,
    )
    await bindex.start()
    proxy = ProxyNode(origin_cluster=cluster, build_index_addr=bindex.addr)
    await proxy.start()
    agent = AgentNode(
        store_root=str(tmp_path / name / "agent"),
        tracker_addr=tracker.addr,
        build_index_addr=bindex.addr,
    )
    await agent.start()
    return {
        "tracker": tracker, "origin": origin, "bindex": bindex,
        "proxy": proxy, "agent": agent, "cluster": cluster,
    }


async def stop_cluster(c):
    for key in ("agent", "proxy", "bindex", "origin", "tracker"):
        await c[key].stop()
    await c["cluster"].close()


def test_docker_push_pull_roundtrip(tmp_path):
    async def main():
        c = await build_cluster(tmp_path, "c1")
        http = HTTPClient()
        try:
            config, layers, manifest = make_image()
            await push_image(
                http, c["proxy"].addr, "library/app", "v1", config, layers, manifest
            )
            got_manifest, got_blobs = await pull_image(
                http, f"{c['agent'].host}:{c['agent'].registry_port}",
                "library/app", "v1",
            )
            assert got_manifest == manifest
            assert got_blobs[str(Digest.from_bytes(config))] == config
            for l in layers:
                assert got_blobs[str(Digest.from_bytes(l))] == l

            # tags list + catalog
            tags = json.loads(
                await http.get(
                    f"http://{c['proxy'].addr}/v2/library/app/tags/list"
                )
            )
            assert tags == {"name": "library/app", "tags": ["v1"]}
            catalog = json.loads(
                await http.get(f"http://{c['proxy'].addr}/v2/_catalog")
            )
            assert catalog == {"repositories": ["library/app"]}
        finally:
            await http.close()
            await stop_cluster(c)

    asyncio.run(main())


def test_agent_registry_is_read_only(tmp_path):
    async def main():
        c = await build_cluster(tmp_path, "c1")
        http = HTTPClient()
        try:
            s = await http._get_session()
            url = f"http://{c['agent'].host}:{c['agent'].registry_port}"
            async with s.post(f"{url}/v2/x/blobs/uploads/") as r:
                assert r.status == 405
            async with s.put(f"{url}/v2/x/manifests/latest", data=b"{}") as r:
                assert r.status == 405
        finally:
            await http.close()
            await stop_cluster(c)

    asyncio.run(main())


def test_cross_cluster_tag_replication(tmp_path):
    """Push to cluster-1; its build-index replicates the tag to cluster-2's
    build-index (SURVEY.md SS2.4 tagreplication)."""

    async def main():
        c2 = await build_cluster(tmp_path, "c2")
        c1 = await build_cluster(tmp_path, "c1", remotes=[c2["bindex"].addr])
        http = HTTPClient()
        try:
            config, layers, manifest = make_image(nlayers=1)
            await push_image(
                http, c1["proxy"].addr, "library/app", "v1", config, layers, manifest
            )
            d = Digest.from_bytes(manifest)
            for _ in range(100):
                await c1["bindex"].retry.run_once()
                body = None
                try:
                    body = await http.get(
                        f"http://{c2['bindex'].addr}/tags/library%2Fapp%3Av1"
                    )
                except Exception:
                    await asyncio.sleep(0.05)
                    continue
                assert body.decode() == str(d)
                break
            else:
                pytest.fail("tag never replicated")
        finally:
            await http.close()
            await stop_cluster(c1)
            await stop_cluster(c2)

    asyncio.run(main())


def test_tags_list_pagination(tmp_path):
    """Registry v2 ?n=&last= pagination with the Link header (docker
    clients page through large repos)."""

    async def main():
        c = await build_cluster(tmp_path, "a")
        try:
            http = HTTPClient()
            config, layers, manifest = make_image(nlayers=1)
            for tag in ["v1", "v2", "v3", "v4", "v5"]:
                await push_image(
                    http, c["proxy"].addr, "library/app", tag,
                    config, layers, manifest,
                )
            url = f"http://{c['proxy'].addr}/v2/library/app/tags/list"
            s = await http._get_session()

            async with s.get(url, params={"n": "2"}) as r:
                doc = await r.json()
                assert doc["tags"] == ["v1", "v2"]
                assert 'last=v2' in r.headers["Link"]
            async with s.get(url, params={"n": "2", "last": "v2"}) as r:
                doc = await r.json()
                assert doc["tags"] == ["v3", "v4"]
            async with s.get(url, params={"n": "2", "last": "v4"}) as r:
                doc = await r.json()
                assert doc["tags"] == ["v5"]
                assert "Link" not in r.headers
            async with s.get(url, params={"n": "bogus"}) as r:
                assert r.status == 400
            # n=0 would mean "empty page, no Link" = listing complete:
            # rejected so paging clients can't mis-terminate.
            async with s.get(url, params={"n": "0"}) as r:
                assert r.status == 400
            await http.close()
        finally:
            await stop_cluster(c)

    asyncio.run(main())


def test_blob_get_range_resume(tmp_path):
    """Byte-range blob GETs (docker's pull-resume) on both registry
    flavors: the agent's FileResponse path and the proxy's spooled-temp
    streaming path."""

    async def main():
        c = await build_cluster(tmp_path, "a")
        try:
            http = HTTPClient()
            config, layers, manifest = make_image(nlayers=1, layer_size=300_000)
            await push_image(
                http, c["proxy"].addr, "library/app", "v1",
                config, layers, manifest,
            )
            layer = layers[0]
            d = str(Digest.from_bytes(layer))
            s = await http._get_session()
            for registry in (c["proxy"].addr, c["agent"].registry_addr):
                url = f"http://{registry}/v2/library/app/blobs/{d}"
                async with s.get(url) as r:  # whole blob sanity
                    assert r.status == 200 and await r.read() == layer
                async with s.get(
                    url, headers={"Range": "bytes=100000-"}
                ) as r:
                    assert r.status == 206, await r.text()
                    assert await r.read() == layer[100000:]
                    assert r.headers["Content-Range"].startswith(
                        "bytes 100000-"
                    )
                async with s.get(
                    url, headers={"Range": "bytes=1000-1999"}
                ) as r:
                    assert r.status == 206
                    assert await r.read() == layer[1000:2000]
                # end past EOF is satisfiable (clamped), per RFC 9110
                async with s.get(
                    url, headers={"Range": "bytes=100000-999999999"}
                ) as r:
                    assert r.status == 206
                    assert await r.read() == layer[100000:]
                async with s.get(
                    url, headers={"Range": f"bytes={len(layer)}-"}
                ) as r:
                    assert r.status == 416
            await http.close()
        finally:
            await stop_cluster(c)

    asyncio.run(main())


def test_cross_repo_blob_mount(tmp_path):
    """POST /blobs/uploads/?mount=&from= short-circuits to 201 when the
    cluster already holds the bytes (content-addressed); unknown digests
    fall back to a normal 202 upload session."""

    async def main():
        c = await build_cluster(tmp_path, "a")
        try:
            http = HTTPClient()
            config, layers, manifest = make_image(nlayers=1)
            await push_image(
                http, c["proxy"].addr, "library/app", "v1",
                config, layers, manifest,
            )
            d = str(Digest.from_bytes(layers[0]))
            s = await http._get_session()
            async with s.post(
                f"http://{c['proxy'].addr}/v2/library/other/blobs/uploads/",
                params={"mount": d, "from": "library/app"},
            ) as r:
                assert r.status == 201, await r.text()
                assert r.headers["Docker-Content-Digest"] == d
                assert r.headers["Location"].endswith(f"/blobs/{d}")
            # The 201 must be backed by behavior: the blob serves under
            # the TARGET repo, and the origin adopted it durably into the
            # target namespace (sidecar the repair/writeback paths use).
            got = await http.get(
                f"http://{c['proxy'].addr}/v2/library/other/blobs/{d}"
            )
            assert got == layers[0]
            from kraken_tpu.store.metadata import NamespaceMetadata

            md = c["origin"].store.get_metadata(
                Digest.parse(d), NamespaceMetadata
            )
            assert md is not None and md.namespace == "library/other"
            # Unknown digest -> regular upload session.
            missing = "sha256:" + "0" * 64
            async with s.post(
                f"http://{c['proxy'].addr}/v2/library/other/blobs/uploads/",
                params={"mount": missing, "from": "library/app"},
            ) as r:
                assert r.status == 202
                assert "Docker-Upload-UUID" in r.headers
            await http.close()
        finally:
            await stop_cluster(c)

    asyncio.run(main())


def test_mount_second_writeback_keeps_pin_until_both_land(tmp_path):
    """The writeback pin is a reason-set, not a counter: after a cross-repo
    mount there are TWO pending writebacks for one blob, and the first to
    land must not expose the bytes to eviction while the second is queued."""
    from kraken_tpu.backend import Manager as BackendManager
    from kraken_tpu.store.metadata import PersistMetadata

    async def main():
        backends = BackendManager(
            [{"namespace": ".*", "backend": "file",
              "config": {"root": str(tmp_path / "remote")}}]
        )
        tracker = TrackerNode(announce_interval_seconds=0.1)
        await tracker.start()
        origin = OriginNode(
            store_root=str(tmp_path / "origin"), tracker_addr=tracker.addr,
            backends=backends,
        )
        await origin.start()
        ring = Ring(HostList(static=[origin.addr]), max_replica=1)
        cluster = ClusterClient(ring)
        try:
            blob = os.urandom(100_000)
            d = Digest.from_bytes(blob)
            await cluster.upload("ns-a", d, blob)
            assert await cluster.adopt("ns-b", d, "ns-a")

            # Two writebacks pending for one digest.
            from kraken_tpu.origin.writeback import KIND

            assert origin.retry.store.count_pending(KIND, f"{d.hex}:") == 2

            # Run ONE task: pin must survive (the other writeback still
            # needs the bytes).
            await origin.retry.run_once()
            md = origin.store.get_metadata(d, PersistMetadata)
            remaining = origin.retry.store.count_pending(KIND, f"{d.hex}:")
            if remaining:  # first landed, second queued
                assert md is not None and KIND in md.reasons
                await origin.retry.run_once()
            # Both landed: pin released, both backends have the bytes.
            md = origin.store.get_metadata(d, PersistMetadata)
            assert md is None or KIND not in md.reasons
            from kraken_tpu.backend.base import make_backend

            be = make_backend("file", {"root": str(tmp_path / "remote")})
            assert await be.download("ns-a", d.hex) == blob
            assert await be.download("ns-b", d.hex) == blob
        finally:
            await cluster.close()
            await origin.stop()
            await tracker.stop()

    asyncio.run(main())


def test_immutable_tags(tmp_path):
    """immutable_tags: a tag can never be re-pointed at a different
    digest (409 from the build-index; the proxy's manifest PUT surfaces
    the spec's DENIED envelope), while same-digest re-push stays
    idempotent so docker push retries don't fail."""

    async def main():
        import json as _json

        from kraken_tpu.buildindex.server import TagClient

        origin = OriginNode(store_root=str(tmp_path / "o"), dedup=False)
        await origin.start()
        ring = Ring(HostList(static=[origin.addr]), max_replica=1)
        cluster = ClusterClient(ring)
        bindex = BuildIndexNode(
            store_root=str(tmp_path / "bi"),
            origin_cluster=cluster,
            immutable_tags=True,
        )
        await bindex.start()
        proxy = ProxyNode(origin_cluster=cluster, build_index_addr=bindex.addr)
        await proxy.start()
        http = HTTPClient()
        try:
            tags = TagClient(bindex.addr)
            d1 = Digest.from_bytes(b"manifest-one")
            d2 = Digest.from_bytes(b"manifest-two")
            await tags.put("repo:v1", d1)
            await tags.put("repo:v1", d1)  # idempotent re-put
            with pytest.raises(HTTPError) as e:
                await tags.put("repo:v1", d2)
            assert e.value.status == 409
            assert await tags.get("repo:v1") == d1
            await tags.close()

            # Registry surface: first push of a tag succeeds; re-pointing
            # it is the spec's DENIED (403), which docker reports as a
            # denied push rather than retrying forever.
            m1 = _json.dumps({"mediaType": "x", "n": 1}).encode()
            m2 = _json.dumps({"mediaType": "x", "n": 2}).encode()
            url = f"http://{proxy.addr}/v2/repo/manifests/v2"
            status, _h, _b = await http.request_full(
                "PUT", url, data=m1, ok_statuses=(201,)
            )
            assert status == 201
            status, _h, body = await http.request_full(
                "PUT", url, data=m2, ok_statuses=(403,), retry_5xx=False
            )
            err = _json.loads(body)["errors"][0]
            assert err["code"] == "DENIED", err
            # Same manifest again: idempotent 201.
            status, _h, _b = await http.request_full(
                "PUT", url, data=m1, ok_statuses=(201,)
            )
        finally:
            await http.close()
            await proxy.stop()
            await bindex.stop()
            await origin.stop()
            await cluster.close()

    asyncio.run(main())


def test_immutable_tags_fail_closed_on_backend_outage(tmp_path):
    """ADVICE r4 (medium): the immutability check reads through to the
    backend; a backend OUTAGE must answer a retryable 503, not silently
    accept the put (failing open is the exact re-tag the feature
    prevents). A proven-absent tag (backend 404) still accepts."""
    from aiohttp import web

    from kraken_tpu.backend import BlobNotFoundError, BackendError
    from kraken_tpu.buildindex.server import TagServer
    from kraken_tpu.buildindex.tagstore import TagStore

    class FakeClient:
        def __init__(self):
            self.mode = "outage"

        async def download(self, ns, name):
            if self.mode == "outage":
                raise BackendError("backend down")
            raise BlobNotFoundError(name)

    class FakeBackends:
        def __init__(self):
            self.client = FakeClient()

        def try_get_client(self, ns):
            return self.client

    async def main():
        backends = FakeBackends()
        # Fresh volume: nothing local, so the check MUST consult the
        # backend -- and the backend is down.
        store = TagStore(str(tmp_path / "tags"), backends=backends)
        srv = TagServer(store, immutable=True)
        d = Digest.from_bytes(b"m1")
        with pytest.raises(web.HTTPServiceUnavailable):
            await srv._checked_put("repo:v1", d)
        assert store.get_local("repo:v1") is None  # nothing written

        # Backend answers definitively absent -> the put goes through.
        backends.client.mode = "absent"
        await srv._checked_put("repo:v1", d)
        assert store.get_local("repo:v1") == d

    asyncio.run(main())

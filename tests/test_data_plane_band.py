"""Socket data-plane regression band (VERDICT r5 next #3).

The swarm SIM has a pinned ±5% p99 band; the rebuilt SOCKET path -- the
round-5 headline -- had none, so a 2x regression in storage.py/conn.py/
dispatch.py would ship green. Absolute goodput on this shared-core rig
swings ±30% run to run, so the gate is the PUMP-KNOCKOUT RATIO instead:

    ratio = median wall(full stack) / median wall(verify+write knocked out)

Both walls ride the same rig noise, so the ratio cancels it; what it
keeps is the RELATIVE cost of the endpoint machinery (verify hashing,
data writes, bitfield accounting) over the pure pump -- exactly the
stages whose historical regressions (per-piece sidecar renames, verify
serialization, the 2 ms batch delay) each moved goodput 2.4x or more,
i.e. pushed this ratio well past 3. Measured on this rig: 1.33 with a
healthy second core, up to 2.13 when the shared VM's sha throughput
degrades (the verify term is hash-bound, so the ratio inherits the
rig's 1.25-1.6x thread-envelope drift -- see PERF.md "parallel host
hashing"). Band: a ratio past 3.0 re-introduced per-piece machinery;
below 0.8 the knockout itself broke (it must strictly remove work).
"""

from __future__ import annotations

import asyncio
import pathlib
import statistics
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def _median_wall(n: int, blob_mb: int, piece_kb: int,
                 workers: int = 0, leech_workers: int = 0) -> float:
    from bench_pair import run_pair

    walls = []
    for _ in range(n):
        with tempfile.TemporaryDirectory() as root:
            r = asyncio.run(run_pair(blob_mb, piece_kb, root,
                                     workers=workers,
                                     leech_workers=leech_workers))
            walls.append(r["wall_s"])
    return statistics.median(walls)


def test_pair_pump_knockout_regression_band(monkeypatch):
    from kraken_tpu.p2p import storage as st

    full = _median_wall(3, blob_mb=64, piece_kb=256)

    async def _verified(self, data, expected):
        return True

    monkeypatch.setattr(st.BatchedVerifier, "verify", _verified)
    monkeypatch.setattr(st.Torrent, "_write_at", lambda self, i, data: None)
    knockout = _median_wall(3, blob_mb=64, piece_kb=256)

    ratio = full / knockout
    assert 0.8 <= ratio <= 3.0, (
        f"pump-knockout ratio {ratio:.2f} outside [0.8, 3.0] "
        f"(full {full:.3f}s / knockout {knockout:.3f}s): the endpoint "
        "machinery cost moved -- see this file's docstring before "
        "re-pinning"
    )


def test_pair_pump_knockout_band_with_workers(monkeypatch):
    """The same ratio gate with the seed half sharded onto worker
    processes (round 8, p2p/shardpool.py): the knockout still strictly
    removes agent-side work (verify + data write -- serve-side sendfile
    is untouched by it), so the ratio must hold in the same band. A
    ratio below 0.8 would mean the worker plane broke the knockout; one
    past 3.0 would mean the handoff re-introduced per-piece machinery
    on the main loop. Skipped on single-core rigs, where forking a
    serve shard measures scheduler contention, not the plane."""
    import os

    import pytest

    if (os.cpu_count() or 1) < 2:
        pytest.skip("workers band needs >= 2 cores")

    from kraken_tpu.p2p import storage as st

    full = _median_wall(3, blob_mb=64, piece_kb=256, workers=2)

    async def _verified(self, data, expected):
        return True

    monkeypatch.setattr(st.BatchedVerifier, "verify", _verified)
    monkeypatch.setattr(st.Torrent, "_write_at", lambda self, i, data: None)
    knockout = _median_wall(3, blob_mb=64, piece_kb=256, workers=2)

    ratio = full / knockout
    assert 0.8 <= ratio <= 3.0, (
        f"workers-on pump-knockout ratio {ratio:.2f} outside [0.8, 3.0] "
        f"(full {full:.3f}s / knockout {knockout:.3f}s)"
    )


def test_pair_pump_knockout_band_with_leech_workers(monkeypatch):
    """The ratio gate with the DOWNLOAD half sharded onto leech worker
    processes (round 19, p2p/shardpool.py leech mode): recv + frame
    parse + pwrite run in the forked pump, payloads cross via the
    shared ring, and verify stays batched in the parent -- so the
    verify knockout still strictly removes parent-side work and the
    ratio must hold in the same band. Below 0.8 the leech plane broke
    the knockout; past 3.0 the handoff re-introduced per-piece
    machinery on the main loop (slot bookkeeping, verdict round-trips,
    or ring copies that should not exist). Skipped on single-core
    rigs, where forking a download pump measures scheduler contention,
    not the plane."""
    import os

    import pytest

    if (os.cpu_count() or 1) < 2:
        pytest.skip("leech workers band needs >= 2 cores")

    from kraken_tpu.p2p import storage as st

    full = _median_wall(3, blob_mb=64, piece_kb=256, leech_workers=2)

    async def _verified(self, data, expected):
        return True

    monkeypatch.setattr(st.BatchedVerifier, "verify", _verified)
    monkeypatch.setattr(st.Torrent, "_write_at", lambda self, i, data: None)
    knockout = _median_wall(3, blob_mb=64, piece_kb=256, leech_workers=2)

    ratio = full / knockout
    assert 0.8 <= ratio <= 3.0, (
        f"leech-workers-on pump-knockout ratio {ratio:.2f} outside "
        f"[0.8, 3.0] (full {full:.3f}s / knockout {knockout:.3f}s)"
    )


def test_trace_on_overhead_band():
    """The tracing plane at SHIPPED sampling (base.yaml
    trace.sample_rate = 0.01, pinned by test_config_tree) must cost
    <= 5% pair goodput. The estimator is the MIN OF PAIRWISE RATIOS
    over interleaved off/on rounds: the two legs of one round run
    seconds apart, so they share the same rig phase and the ratio
    cancels it -- unlike min(on)/min(off), which fails spuriously when
    this shared-core VM degrades mid-test (all later legs inflate while
    one early leg of the OTHER side pins its min low; observed in-suite
    with every on-leg >= 2.3 s against a 1.26 s off-leg). A real leak
    of span machinery into the unsampled hot path inflates EVERY round,
    so it survives the min. The residual the gate keeps is the per-pull
    span cost (root/dial/announce spans, the sampled-only gate on the
    per-piece path, the traceparent probe per request batch). A min
    pairwise ratio past 1.05 means span creation or the contextvar
    probes leaked into the unsampled data path -- look at dispatch.py's
    sampled-only gates before re-pinning."""

    from bench_pair import run_pair
    from kraken_tpu.configutil import load_config
    from kraken_tpu.utils.trace import TRACER, TraceConfig

    # The gate's claim is "at the SHIPPED rate": read the actual
    # shipped section (test_config_tree only pins it to a range).
    shipped = TraceConfig.from_dict(
        load_config(str(pathlib.Path(__file__).parent.parent
                        / "config" / "agent" / "base.yaml")).get("trace")
    )

    def wall_once() -> float:
        with tempfile.TemporaryDirectory() as root:
            r = asyncio.run(run_pair(64, 256, root))
            return r["wall_s"]

    ratios: list[float] = []
    try:
        TRACER.apply(TraceConfig(enabled=False))
        wall_once()  # warmup: imports, allocator, page cache
        for _ in range(4):
            TRACER.apply(TraceConfig(enabled=False))
            off = wall_once()
            TRACER.apply(shipped)
            on = wall_once()
            ratios.append(on / off)
    finally:
        TRACER.apply(TraceConfig())
        TRACER.recorder.clear()

    assert min(ratios) <= 1.05, (
        "trace-on/trace-off pairwise wall ratios "
        f"{[f'{r:.3f}' for r in ratios]} all > 1.05: tracing leaked "
        "into the unsampled data path -- see this test's docstring"
    )


def test_profiler_on_overhead_band():
    """The continuous-profiling plane at SHIPPED rate (base.yaml
    profiling.hz, pinned sampled-down by test_config_tree) must cost
    <= 5% pair goodput. Same estimator as the trace band above: MIN OF
    PAIRWISE off/on ratios over interleaved rounds, so the two legs of
    each ratio share a rig phase and the shared-core drift cancels. The
    sampler's entire cost is one ``sys._current_frames()`` walk + a few
    dict increments per tick, OFF the event loop -- a min pairwise
    ratio past 1.05 means per-sample work grew (stack depth, plane
    rules, lock hold) or something leaked onto the data path; look at
    utils/profiler.py _sample_once before re-pinning."""

    from bench_pair import run_pair
    from kraken_tpu.configutil import load_config
    from kraken_tpu.utils.profiler import PROFILER, ProfilerConfig

    shipped = ProfilerConfig.from_dict(
        load_config(str(pathlib.Path(__file__).parent.parent
                        / "config" / "agent" / "base.yaml")).get("profiling")
    )
    cfg0 = PROFILER.config

    def wall_once() -> float:
        with tempfile.TemporaryDirectory() as root:
            r = asyncio.run(run_pair(64, 256, root))
            return r["wall_s"]

    ratios: list[float] = []
    try:
        PROFILER.apply(ProfilerConfig(enabled=False))
        wall_once()  # warmup: imports, allocator, page cache
        for _ in range(4):
            PROFILER.apply(ProfilerConfig(enabled=False))
            off = wall_once()
            PROFILER.apply(shipped)
            on = wall_once()
            ratios.append(on / off)
    finally:
        PROFILER.apply(cfg0)
        PROFILER.reset()

    assert min(ratios) <= 1.05, (
        "profiler-on/off pairwise wall ratios "
        f"{[f'{r:.3f}' for r in ratios]} all > 1.05: the sampler got "
        "expensive -- see this test's docstring"
    )


def test_pipelined_ingest_band():
    """Pipelined vs serial piece pass over identical bytes (VERDICT r16:
    the ingest plane must EARN its machinery). windows_in_flight=2 on a
    healthy second core overlaps two windows' hashlib (GIL-free), so the
    pipelined wall must beat the serial wall by >= 1.3x. Interleaved
    pairwise runs so rig noise hits both configs alike; digests are
    asserted bit-identical every run (the band must never pass on wrong
    bytes). Skipped below 2 cores, where the overlap has nothing to
    overlap with."""
    import os
    import time

    import numpy as np
    import pytest

    if (os.cpu_count() or 1) < 2:
        pytest.skip("pipelined band needs >= 2 cores")

    from kraken_tpu.core.hasher import CPUPieceHasher
    from kraken_tpu.core.ingest import IngestConfig, IngestPipeline

    plen = 256 * 1024
    window = 8 << 20
    blob = np.random.default_rng(21).integers(
        0, 256, size=8 * window, dtype=np.uint8
    ).tobytes()
    hasher = CPUPieceHasher(workers=0)  # serial per window: pure overlap test
    pipe = IngestPipeline(
        hasher, IngestConfig(window_bytes=window, windows_in_flight=2)
    )
    want = hasher.hash_pieces(blob, plen)

    def run_pipelined() -> float:
        ses = pipe.session(plen)
        t0 = time.perf_counter()
        off = 0
        while off < len(blob):
            buf = ses.begin_window()
            n = min(len(buf), len(blob) - off)
            buf[:n] = blob[off : off + n]
            off += n
            ses.submit(n)
        got = ses.finish()
        dt = time.perf_counter() - t0
        assert np.array_equal(got, want)
        return dt

    def run_serial() -> float:
        t0 = time.perf_counter()
        parts = []
        for off in range(0, len(blob), window):
            parts.append(hasher.hash_pieces(blob[off : off + window], plen))
        dt = time.perf_counter() - t0
        assert np.array_equal(np.concatenate(parts), want)
        return dt

    run_pipelined(), run_serial()  # warm pools and page cache
    ratios = []
    for _ in range(5):
        s, p = run_serial(), run_pipelined()
        ratios.append(s / p)
    ratios.sort()
    assert ratios[len(ratios) // 2] >= 1.3, ratios


def test_quorum_commit_overhead_band(tmp_path):
    """Quorum-gated commits (write_quorum=2) on the HEALTHY path must
    cost <= 1.5x the classic async-replication commit. The plane earns
    that band by overlapping, not by skipping work: the replica push
    launches against the upload spool BEFORE the local verify+rename
    (origin/server._begin_quorum_push), streams through a pooled warm
    client, and the hedged fan-out moves the bytes exactly once (the
    spare replica joins only on a failed primary). Estimator: MIN OF
    PAIRWISE off/on ratios over interleaved rounds, same as the trace
    and profiler bands -- both legs of a round share a rig phase, so
    shared-core drift cancels. Skipped below 2 cores, where the push's
    replica-side hashing has no core to overlap the local commit on and
    the wall ratio degenerates to total-CPU ratio (~2x by construction:
    a durability ack IS a second hash+fsync of every byte)."""
    import os
    import socket

    import pytest

    if (os.cpu_count() or 1) < 2:
        pytest.skip("quorum overlap band needs >= 2 cores")

    from kraken_tpu.assembly import OriginNode
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.client import BlobClient
    from kraken_tpu.origin.server import QuorumConfig
    from kraken_tpu.placement import HostList, Ring

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    async def drive() -> list[float]:
        import time

        ports = [free_port() for _ in range(3)]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        nodes = []
        for i in range(3):
            n = OriginNode(
                store_root=str(tmp_path / f"origin{i}"),
                http_port=ports[i],
                ring=Ring(HostList(static=addrs), max_replica=3),
                self_addr=addrs[i],
                dedup=False,
                health_interval_seconds=30.0,
            )
            await n.start()
            n.retry.stop()
            nodes.append(n)
        q_off = QuorumConfig(write_quorum=1)
        q_on = QuorumConfig(write_quorum=2, push_timeout_seconds=30.0)
        client = BlobClient(addrs[0])

        async def commit_wall(q: QuorumConfig) -> float:
            nodes[0].server.quorum = q  # live-swap, as SIGHUP reload does
            blob = os.urandom(2_000_000)
            d = Digest.from_bytes(blob)
            t0 = time.perf_counter()
            await client.upload("band", d, blob)
            return time.perf_counter() - t0

        ratios: list[float] = []
        try:
            await commit_wall(q_off)  # warmup: sessions, page cache
            await commit_wall(q_on)
            for _ in range(5):
                off = await commit_wall(q_off)
                on = await commit_wall(q_on)
                ratios.append(on / off)
        finally:
            await client.close()
            for n in nodes:
                await n.stop()
        return ratios

    ratios = asyncio.run(drive())
    assert min(ratios) <= 1.5, (
        "quorum-on/off pairwise commit-wall ratios "
        f"{[f'{r:.2f}' for r in ratios]} all > 1.5: the healthy-path "
        "push stopped overlapping the local commit (or started moving "
        "bytes twice) -- see origin/server._begin_quorum_push"
    )

"""Utils tests: token bucket, request coalescing, TTL cache, backoff,
HTTP client retry behavior. SURVEY.md SS2.5."""

import asyncio
import time

import pytest
from aiohttp import web

from kraken_tpu.utils.backoff import Backoff
from kraken_tpu.utils.bandwidth import TokenBucket
from kraken_tpu.utils.dedup import RequestCoalescer, TTLCache
from kraken_tpu.utils.httputil import HTTPClient, HTTPError, is_not_found


def run(coro):
    return asyncio.run(coro)


# -- bandwidth --------------------------------------------------------------

def test_token_bucket_unlimited():
    tb = TokenBucket(0)
    assert tb.try_acquire(1e12)
    run(tb.acquire(1e12))  # returns immediately


def test_token_bucket_burst_then_throttle():
    async def main():
        tb = TokenBucket(rate=10_000, capacity=1_000)
        t0 = time.monotonic()
        await tb.acquire(1_000)   # burst
        await tb.acquire(500)     # needs refill: ~0.05s
        assert time.monotonic() - t0 > 0.03

    run(main())


def test_token_bucket_oversized_request_passes():
    async def main():
        tb = TokenBucket(rate=1e6, capacity=100)
        await tb.acquire(1000)  # > capacity: allowed once bucket is full

    run(main())


def test_try_acquire():
    tb = TokenBucket(rate=100, capacity=100)
    assert tb.try_acquire(100)
    assert not tb.try_acquire(100)


# -- dedup ------------------------------------------------------------------

def test_coalescer_single_flight():
    async def main():
        calls = 0

        async def fetch():
            nonlocal calls
            calls += 1
            await asyncio.sleep(0.05)
            return "blob"

        co = RequestCoalescer()
        results = await asyncio.gather(*(co.get("k", fetch) for _ in range(10)))
        assert results == ["blob"] * 10
        assert calls == 1
        # After completion, a new call re-invokes.
        await co.get("k", fetch)
        assert calls == 2

    run(main())


def test_coalescer_propagates_errors():
    async def main():
        async def boom():
            await asyncio.sleep(0.01)
            raise ValueError("x")

        co = RequestCoalescer()
        results = await asyncio.gather(
            *(co.get("k", boom) for _ in range(3)), return_exceptions=True
        )
        assert all(isinstance(r, ValueError) for r in results)

    run(main())


def test_ttl_cache():
    c = TTLCache(ttl_seconds=0.05)
    c.put("k", 1)
    assert c.get("k") == 1
    time.sleep(0.08)
    assert c.get("k") is None
    c.put("k", 2)
    c.invalidate("k")
    assert c.get("k") is None


# -- backoff ----------------------------------------------------------------

def test_backoff_growth_and_cap():
    b = Backoff(base_seconds=1, factor=2, max_seconds=5, jitter=0)
    assert [b.delay(i) for i in range(4)] == [1, 2, 4, 5]


def test_backoff_jitter_bounds():
    b = Backoff(base_seconds=1, factor=1, max_seconds=1, jitter=0.5)
    for _ in range(50):
        assert 0.5 <= b.delay(0) <= 1.5


# -- httputil ---------------------------------------------------------------

def test_http_client_retries_5xx_and_types_errors():
    async def main():
        hits = {"flaky": 0, "missing": 0}

        async def flaky(req):
            hits["flaky"] += 1
            if hits["flaky"] < 3:
                return web.Response(status=503)
            return web.Response(text="ok")

        async def missing(req):
            hits["missing"] += 1
            return web.Response(status=404)

        app = web.Application()
        app.router.add_get("/flaky", flaky)
        app.router.add_get("/missing", missing)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        base = f"http://127.0.0.1:{port}"

        client = HTTPClient(retries=3, backoff=Backoff(base_seconds=0.01, jitter=0))
        try:
            assert await client.get(f"{base}/flaky") == b"ok"
            assert hits["flaky"] == 3
            with pytest.raises(HTTPError) as ei:
                await client.get(f"{base}/missing")
            assert is_not_found(ei.value)
            assert hits["missing"] == 1  # 4xx not retried
        finally:
            await client.close()
            await runner.cleanup()

    run(main())

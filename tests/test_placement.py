"""Placement tests: rendezvous stability, ring re-placement on membership
change, health filtering. SURVEY.md SS2.3/SS5."""

import asyncio

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.placement import HostList, PassiveFilter, Ring, rendezvous_hash
from kraken_tpu.placement.healthcheck import ActiveMonitor


def digests(n):
    return [Digest.from_bytes(str(i).encode()) for i in range(n)]


# -- hrw --------------------------------------------------------------------

def test_hrw_deterministic_and_complete():
    nodes = [f"h{i}:80" for i in range(10)]
    top = rendezvous_hash("key", nodes, k=3)
    assert top == rendezvous_hash("key", nodes, k=3)
    assert len(set(top)) == 3 and all(t in nodes for t in top)


def test_hrw_minimal_disruption():
    """Removing one node must only move keys that lived on it."""
    nodes = [f"h{i}:80" for i in range(10)]
    keys = [f"k{i}" for i in range(200)]
    before = {k: rendezvous_hash(k, nodes, k=1)[0] for k in keys}
    survivors = [n for n in nodes if n != "h3:80"]
    for k in keys:
        after = rendezvous_hash(k, survivors, k=1)[0]
        if before[k] != "h3:80":
            assert after == before[k]


def test_hrw_balance():
    nodes = [f"h{i}:80" for i in range(5)]
    counts = {n: 0 for n in nodes}
    for i in range(2000):
        counts[rendezvous_hash(f"key{i}", nodes, k=1)[0]] += 1
    # Each node gets 400 +- 50% -- loose, just catches gross skew.
    for n, c in counts.items():
        assert 200 < c < 600, counts


# -- ring -------------------------------------------------------------------

def test_ring_locations_replicas():
    ring = Ring(HostList(static=[f"o{i}:80" for i in range(5)]), max_replica=3)
    for d in digests(20):
        locs = ring.locations(d)
        assert len(locs) == 3 and len(set(locs)) == 3


def test_ring_small_cluster():
    ring = Ring(HostList(static=["solo:80"]), max_replica=3)
    assert ring.locations(digests(1)[0]) == ["solo:80"]


def test_ring_membership_change_notifies_and_replaces():
    members = [f"o{i}:80" for i in range(4)]
    ring = Ring(HostList(resolver=lambda: members), max_replica=2)
    events = []
    ring.on_change(events.append)

    d_moved = [d for d in digests(50) if "o0:80" in ring.locations(d)]
    assert d_moved, "setup: no digest placed on o0"
    before = {d.hex: ring.locations(d) for d in digests(50)}

    members = members[1:]  # o0 dies
    assert ring.refresh() is True
    assert events and "o0:80" not in events[0]
    for d in digests(50):
        locs = ring.locations(d)
        assert "o0:80" not in locs
        if "o0:80" not in before[d.hex]:
            assert locs == before[d.hex]  # unaffected blobs stay put

    assert ring.refresh() is False  # no further change


def test_ring_health_filter_integration():
    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=1000)
    ring = Ring(
        HostList(static=["a:1", "b:1", "c:1"]),
        max_replica=2,
        health_filter=pf.filter,
    )
    assert set(ring.members) == {"a:1", "b:1", "c:1"}
    pf.failed("b:1")
    ring.refresh()
    assert "b:1" not in ring.members
    pf.succeeded("b:1")
    ring.refresh()
    assert "b:1" in ring.members


def test_ring_empty_raises():
    ring = Ring(HostList(resolver=lambda: []), max_replica=1)
    with pytest.raises(RuntimeError):
        ring.locations(digests(1)[0])


# -- health -----------------------------------------------------------------

def test_passive_filter_threshold_and_cooldown():
    pf = PassiveFilter(fail_threshold=2, cooldown_seconds=10)
    assert pf.healthy("h", now=0)
    pf.failed("h", now=0)
    assert pf.healthy("h", now=1)  # 1 fail < threshold
    pf.failed("h", now=1)
    assert not pf.healthy("h", now=2)
    assert pf.healthy("h", now=12)  # cooldown expired


def test_passive_filter_never_empties():
    pf = PassiveFilter(fail_threshold=1)
    pf.failed("a", now=0)
    pf.failed("b", now=0)
    assert pf.filter(["a", "b"], now=0) == ["a", "b"]


def test_active_monitor_thresholds():
    health = {"h": True}

    async def probe(host):
        return health[host]

    mon = ActiveMonitor(probe, pass_threshold=1, fail_threshold=2)

    async def main():
        await mon.check_all(["h"])
        assert mon.healthy("h")
        health["h"] = False
        await mon.check_all(["h"])
        assert mon.healthy("h")  # 1 fail < threshold 2
        await mon.check_all(["h"])
        assert not mon.healthy("h")  # 2 consecutive fails
        health["h"] = True
        await mon.check_all(["h"])
        assert mon.healthy("h")  # pass_threshold 1

    asyncio.run(main())


def test_passive_filter_prune_drops_departed_hosts():
    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=1000)
    pf.failed("gone:1")
    pf.failed("stays:1")
    assert not pf.healthy("gone:1") and not pf.healthy("stays:1")
    dropped = pf.prune(["stays:1", "new:1"])
    assert dropped == 1
    # The departed host's verdict is forgotten: if its address is reused
    # by a fresh node, it starts healthy...
    assert pf.healthy("gone:1")
    # ...while hosts still in the list keep their state.
    assert not pf.healthy("stays:1")
    # Bounded under churn: repeated prune against the live set never
    # leaves entries for hosts outside it.
    for i in range(50):
        pf.failed(f"pod-{i}:1")
    pf.prune(["stays:1"])
    assert set(pf._fails) == {"stays:1"}


def test_active_monitor_prune_drops_departed_hosts():
    async def main():
        health = {"a:1": False, "b:1": True}

        async def probe(h):
            return health.get(h, True)

        mon = ActiveMonitor(probe, fail_threshold=1)
        await mon.check_all(["a:1", "b:1"])
        assert not mon.healthy("a:1") and mon.healthy("b:1")
        assert mon.prune(["b:1"]) == 1
        assert set(mon._state) == {"b:1"}
        # A reused address starts at the healthy default.
        assert mon.healthy("a:1")

    asyncio.run(main())

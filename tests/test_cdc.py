"""FastCDC tests: two-phase TPU+host chunker vs the sequential pure-Python
reference (exact boundary equality), plus the properties dedup depends on:
bounds, determinism, and shift-resistance. SURVEY.md SS4 tier 5."""

import numpy as np
import pytest

from kraken_tpu.ops.cdc import (
    CDCParams,
    _WINDOW,
    _gear_candidates,
    chunk,
    chunk_reference,
    chunk_spans,
)

# Small sizes keep the pure-Python reference fast.
P = CDCParams(min_size=64, avg_size=256, max_size=1024)


def rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.mark.parametrize(
    "n", [0, 1, 63, 64, 65, 255, 256, 1000, 4096, 65536, 100001]
)
def test_matches_reference(n):
    data = rand(n, seed=n)
    assert chunk(data, P) == chunk_reference(data, P)


def test_matches_reference_structured():
    # Low-entropy data (long runs) exercises the forced-cut max_size path.
    data = (b"\x00" * 3000) + rand(3000, 1) + (b"ab" * 2000)
    assert chunk(data, P) == chunk_reference(data, P)


def test_chunk_bounds_and_coverage():
    data = rand(200000, 7)
    spans = chunk_spans(data, P)
    assert spans[0][0] == 0 and spans[-1][1] == len(data)
    for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
        assert e0 == s1
    sizes = [e - s for s, e in spans]
    # Every chunk except the last respects (min, max]; last may be short.
    for sz in sizes[:-1]:
        assert P.min_size < sz <= P.max_size
    assert sizes[-1] <= P.max_size
    # Average lands in the right ballpark (loose: x4 either way).
    mean = np.mean(sizes)
    assert P.avg_size / 4 < mean < P.avg_size * 4


def test_deterministic():
    data = rand(50000, 3)
    assert chunk(data, P) == chunk(data, P)


def test_shift_resistance():
    """Inserting bytes at the front must not move most downstream cuts --
    the whole point of content-defined chunking."""
    base = rand(100000, 9)
    shifted = rand(137, 10) + base
    cuts_a = set(chunk(base, P))
    cuts_b = {c - 137 for c in chunk(shifted, P)}
    # After the first few chunks resynchronize, boundaries coincide.
    common = cuts_a & cuts_b
    assert len(common) >= 0.8 * len(cuts_a)


def test_dedup_across_shifted_copies():
    """Two 'layers' sharing shifted content dedup via chunk digests."""
    import hashlib

    shared = rand(120000, 11)
    layer_a = rand(5000, 12) + shared
    layer_b = rand(9000, 13) + shared

    def digests(blob):
        return {
            hashlib.sha256(blob[s:e]).digest() for s, e in chunk_spans(blob, P)
        }

    da, db = digests(layer_a), digests(layer_b)
    assert len(da & db) >= 0.7 * min(len(da), len(db))


def test_param_validation():
    with pytest.raises(ValueError):
        CDCParams(avg_size=1000)  # not a power of two
    with pytest.raises(ValueError):
        CDCParams(min_size=1 << 20, avg_size=1 << 16, max_size=1 << 22)
    with pytest.raises(ValueError):
        CDCParams(min_size=16, avg_size=64, max_size=256)  # < window


def test_segmented_pass_matches_whole_blob(monkeypatch):
    """Blobs larger than the segment produce bit-identical cuts to the
    whole-blob pass AND the sequential reference (the 31-byte overlap
    carries the full gear history across segment boundaries)."""
    import kraken_tpu.ops.cdc as cdc

    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=1_000_000, dtype=np.uint8).tobytes()

    whole = chunk(data, P)  # n < _SEGMENT: single-pass path
    monkeypatch.setattr(cdc, "_SEGMENT", 128 * 1024)
    segmented = chunk(data, P)
    assert segmented == whole
    assert segmented == chunk_reference(data, P)


def test_pallas_candidates_match_xla_path():
    """The Pallas gear kernel (the real-accelerator large-blob path) must
    produce bit-identical candidate positions to the XLA path -- run here
    in interpret mode on a buffer spanning segment boundaries, ragged
    tail included."""
    from kraken_tpu.ops.cdc_pallas import _SEG, candidate_indices_pallas

    import jax.numpy as jnp

    p = CDCParams()
    rng = np.random.default_rng(11)
    n = 2 * _SEG + 12_345  # 2 full segments + ragged tail
    arr = rng.integers(0, 256, size=n, dtype=np.uint8)
    # Plant a prefix whose ZERO-HISTORY hash hits the loose mask inside
    # the first 31 positions -- the window where the kernel's lead-
    # padding handling could diverge from the XLA path's g-domain zero
    # padding (it did, via gear(0) != 0, until round 4 masked the lead).
    for seed in range(10_000):
        prefix = np.random.default_rng(seed).integers(
            0, 256, size=_WINDOW - 1, dtype=np.uint8
        )
        _s, early_loose = _gear_candidates(
            jnp.asarray(prefix), p.mask_strict, p.mask_loose
        )
        if np.asarray(early_loose).any():
            arr[: _WINDOW - 1] = prefix
            break
    else:
        raise AssertionError("no early-candidate prefix found")

    s_idx, l_idx = candidate_indices_pallas(
        arr, n, p.mask_strict, p.mask_loose, interpret=True
    )
    strict, loose = _gear_candidates(
        jnp.asarray(arr), p.mask_strict, p.mask_loose
    )
    want_s = np.flatnonzero(np.asarray(strict))
    want_l = np.flatnonzero(np.asarray(loose))
    np.testing.assert_array_equal(s_idx, want_s)
    np.testing.assert_array_equal(l_idx, want_l)

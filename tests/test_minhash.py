"""MinHash/LSH tests: estimator accuracy vs brute-force Jaccard, LSH recall,
determinism. SURVEY.md SS4 tier 5."""

import numpy as np
import pytest

from kraken_tpu.ops.minhash import (
    _SCORE_DEVICE_MIN,
    BudgetExceeded,
    CompactLSHIndex,
    LSHIndex,
    MinHasher,
    estimate_jaccard,
    fingerprints_from_digests,
)


def make_set(rng, size):
    return np.unique(rng.integers(0, 1 << 32, size=size, dtype=np.uint64).astype(np.uint32))


def true_jaccard(a, b):
    sa, sb = set(a.tolist()), set(b.tolist())
    if not sa and not sb:
        return 1.0
    return len(sa & sb) / len(sa | sb)


def overlapping_pair(rng, n, overlap):
    base = make_set(rng, n)
    shared = base[: int(n * overlap)]
    extra_a = make_set(rng, n - len(shared))
    extra_b = make_set(rng, n - len(shared))
    return np.union1d(shared, extra_a), np.union1d(shared, extra_b)


def test_estimator_tracks_jaccard():
    rng = np.random.default_rng(0)
    mh = MinHasher(num_hashes=256, seed=1)
    for overlap in (0.0, 0.3, 0.6, 0.9):
        a, b = overlapping_pair(rng, 2000, overlap)
        j = true_jaccard(a, b)
        sk = mh.sketch_batch([a, b])
        est = estimate_jaccard(sk[0], sk[1])
        # stderr ~ sqrt(j(1-j)/256) <= 0.031; allow 4 sigma.
        assert abs(est - j) < 0.13, (overlap, j, est)


def test_identical_sets_score_one():
    rng = np.random.default_rng(1)
    mh = MinHasher()
    a = make_set(rng, 500)
    sk1, sk2 = mh.sketch(a), mh.sketch(a.copy())
    assert estimate_jaccard(sk1, sk2) == 1.0


def test_sketch_deterministic_across_instances():
    rng = np.random.default_rng(2)
    a = make_set(rng, 100)
    assert np.array_equal(MinHasher(seed=7).sketch(a), MinHasher(seed=7).sketch(a))
    assert not np.array_equal(MinHasher(seed=7).sketch(a), MinHasher(seed=8).sketch(a))


def test_sketch_batch_padding_invariant():
    """A set's sketch must not depend on what else is in the batch."""
    rng = np.random.default_rng(3)
    a, b = make_set(rng, 10), make_set(rng, 1000)
    mh = MinHasher()
    alone = mh.sketch(a)
    batched = mh.sketch_batch([a, b])[0]
    assert np.array_equal(alone, batched)


def test_lsh_recall_vs_brute_force():
    """LSH candidates must recover the high-similarity neighbors that brute
    force finds (BASELINE.json config #5)."""
    rng = np.random.default_rng(4)
    mh = MinHasher(num_hashes=128, seed=0)
    index = LSHIndex(mh, num_bands=32)

    base = make_set(rng, 1500)
    sets = {}
    # 20 near-dups of base at ~0.75 overlap, 200 unrelated sets.
    for i in range(20):
        extra = make_set(rng, 300)
        sets[f"near{i}"] = np.union1d(base[:1200], extra)
    for i in range(200):
        sets[f"rand{i}"] = make_set(rng, 1500)

    names = list(sets)
    sketches = mh.sketch_batch([sets[n] for n in names])
    for n, sk in zip(names, sketches):
        index.add(n, sk)

    q = mh.sketch(base)
    brute = {k for k, s in index.query_brute(q, k=20) if s > 0.4}
    lsh = {k for k, _ in index.query(q, k=20, min_jaccard=0.4)}
    assert brute, "brute force found no neighbors -- test setup broken"
    recall = len(brute & lsh) / len(brute)
    assert recall >= 0.9, (recall, brute - lsh)
    # And the random sets stay out.
    assert not any(k.startswith("rand") for k in lsh)


def test_fingerprints_from_digests():
    digests = np.arange(64, dtype=np.uint8).reshape(2, 32)
    fp = fingerprints_from_digests(digests)
    assert fp.dtype == np.uint32 and len(fp) == 2
    assert fingerprints_from_digests(np.empty((0, 32), dtype=np.uint8)).size == 0


def test_bands_must_divide():
    with pytest.raises(ValueError):
        LSHIndex(MinHasher(num_hashes=100), num_bands=32)


def test_lsh_remove_and_compaction():
    """Removal drops candidates immediately; churn (add+delete cycles)
    compacts tombstones so memory stays O(live)."""
    rng = np.random.default_rng(9)
    mh = MinHasher(num_hashes=64)
    index = LSHIndex(mh, num_bands=16)

    keep = rng.integers(0, 1 << 32, size=500, dtype=np.uint64).astype(np.uint32)
    index.add("keep", mh.sketch(keep))

    # Churn well past the compaction threshold (64 tombstones).
    for i in range(200):
        s = rng.integers(0, 1 << 32, size=500, dtype=np.uint64).astype(np.uint32)
        index.add(f"tmp{i}", mh.sketch(s))
        assert index.remove(f"tmp{i}")
    assert not index.remove("tmp0")  # already gone
    assert len(index) == 1
    assert len(index._keys) < 100  # tombstones were compacted away

    # The survivor is still found, exactly, by both query paths.
    q = mh.sketch(keep)
    assert index.query(q, k=3)[0][0] == "keep"
    assert index.query_brute(q, k=3)[0][0] == "keep"
    # Removed keys never appear.
    assert all(k == "keep" for k, _ in index.query(q, k=10))


def test_compact_index_matches_dict_index():
    """CompactLSHIndex is a storage change, not a semantics change: same
    candidates and same query results as LSHIndex on identical input,
    before and after flush()."""
    rng = np.random.default_rng(11)
    mh = MinHasher(num_hashes=64)
    a, b = LSHIndex(mh, num_bands=16), CompactLSHIndex(mh, num_bands=16)
    sets = [make_set(rng, 64) for _ in range(800)]
    sk = mh.sketch_batch(sets)
    for i in range(800):
        a.add(i, sk[i])
    b.add_batch(list(range(400)), sk[:400])
    for i in range(400, 800):
        b.add(i, sk[i])
    for qi in rng.integers(0, 800, size=100):
        assert a.candidates(sk[qi]) == b.candidates(sk[qi])
        assert a.query(sk[qi], k=5) == b.query(sk[qi], k=5)
    b.flush()
    for qi in rng.integers(0, 800, size=100):
        assert a.candidates(sk[qi]) == b.candidates(sk[qi])


def test_compact_index_remove_and_readd():
    rng = np.random.default_rng(12)
    mh = MinHasher(num_hashes=64)
    idx = CompactLSHIndex(mh, num_bands=16)
    sets = [make_set(rng, 64) for _ in range(300)]
    sk = mh.sketch_batch(sets)
    idx.add_batch(list(range(300)), sk)
    assert idx.remove(7)
    assert not idx.remove(7)
    assert 7 not in {k for k, _ in idx.query(sk[7], k=5)}
    assert 7 not in {k for k, _ in idx.query_brute(sk[7], k=5)}
    idx.add(7, sk[7])
    assert dict(idx.query(sk[7], k=3))[7] == 1.0
    # Churn compacts: storage stays O(live).
    for i in range(300):
        idx.remove(i) if i != 7 else None
        idx.add(1000 + i, sk[i])
    assert len(idx) in (300, 301)
    assert idx._n - idx._dead == len(idx)


def test_compact_index_budget_evicts_oldest():
    rng = np.random.default_rng(13)
    mh = MinHasher(num_hashes=64)
    sk = mh.sketch_batch([make_set(rng, 64) for _ in range(2000)])
    budget = 3_000_000
    idx = CompactLSHIndex(mh, num_bands=16, budget_bytes=budget)
    for rep in range(4):
        for s in range(0, 2000, 500):
            idx.add_batch(
                [rep * 2000 + s + j for j in range(500)], sk[s : s + 500]
            )
        assert idx.footprint_bytes() <= budget
    assert idx.evictions > 0 and len(idx) > 0
    # Oldest keys evicted first; the newest batch survives.
    assert max(idx._keys) == 4 * 2000 - 1 + 500 - 500
    # A budget below the empty-index floor is a loud error, not a
    # silently empty index.
    tiny = CompactLSHIndex(mh, num_bands=16, budget_bytes=1000)
    with pytest.raises(BudgetExceeded):
        tiny.add(0, sk[0])


def test_query_brute_device_topk_matches_host():
    """Above _SCORE_DEVICE_MIN the brute scan runs on device with an
    on-device top-k (only 2k scalars leave the chip). Results must equal
    the host argsort ordering, tombstones and padded rows excluded."""

    rng = np.random.default_rng(3)
    hasher = MinHasher(num_hashes=16, seed=1)
    idx = LSHIndex(hasher, num_bands=4)
    n = _SCORE_DEVICE_MIN + 700  # force the device path, non-pow2 live set
    sketches = rng.integers(0, 1 << 32, size=(n, 16), dtype=np.uint64).astype(
        np.uint32
    )
    for i in range(n):
        idx.add(f"k{i}", sketches[i])
    # Tombstone a few, including what would be a top hit.
    query = sketches[123].copy()
    idx.remove("k123")
    idx.remove("k5000")

    got = idx.query_brute(query, k=5)
    # Host oracle over the live rows.
    live_keys = [f"k{i}" for i in range(n) if i not in (123, 5000)]
    live_rows = np.stack(
        [sketches[i] for i in range(n) if i not in (123, 5000)]
    )
    scores = np.mean(live_rows == query[None, :], axis=1)
    order = np.argsort(-scores, kind="stable")[:5]
    want_scores = [float(scores[i]) for i in order]
    got_scores = [s for _k, s in got]
    assert got_scores == pytest.approx(want_scores)
    # The top hit's key must match (ties below can legitimately reorder).
    assert got[0][0] == live_keys[order[0]]


def test_low_j_tier_lifts_below_knee_retrieval():
    """VERDICT r4 weak #1: the primary 4-row banding's knee (~J=0.42)
    made J=0.3 planted retrieval ~0.27. The low-J 2-row tier must lift
    below-knee retrieval without hurting above-knee behavior -- verified
    on both index implementations against the same planted corpus."""

    rng = np.random.default_rng(11)
    hasher = MinHasher(num_hashes=128, seed=3)
    n, m = 3000, 128

    def planted_pair(base, j):
        """A set with expected Jaccard ~j vs base (share s of m each)."""
        s = int(round(2 * j * m / (1 + j)))
        keep = rng.choice(m, size=s, replace=False)
        fresh = rng.integers(0, 1 << 32, size=m - s, dtype=np.uint32)
        return np.unique(np.concatenate([base[keep], fresh]))

    bases = [
        np.unique(rng.integers(0, 1 << 32, size=m, dtype=np.uint32))
        for _ in range(n)
    ]
    sketches = hasher.sketch_batch(bases)
    queries = []
    for j in (0.3, 0.7):
        for _ in range(60):
            t = rng.integers(0, n)
            queries.append((j, t, hasher.sketch(planted_pair(bases[t], j))))

    for make in (
        lambda lo: LSHIndex(hasher, low_j_bands=lo),
        lambda lo: CompactLSHIndex(hasher, low_j_bands=lo),
    ):
        hits = {}
        for lo in (0, 32):
            index = make(lo)
            for i, sk in enumerate(sketches):
                index.add(i, sk)
            got = {0.3: 0, 0.7: 0}
            tot = {0.3: 0, 0.7: 0}
            for j, t, qsk in queries:
                tot[j] += 1
                if any(k == t for k, _s in index.query(qsk, k=10)):
                    got[j] += 1
            hits[lo] = {j: got[j] / tot[j] for j in got}
        # Above the knee both shapes retrieve well.
        assert hits[0][0.7] >= 0.9 and hits[32][0.7] >= 0.9, hits
        # Below the knee the tier is the difference between mostly-miss
        # and mostly-hit.
        assert hits[32][0.3] >= 0.8, hits
        assert hits[32][0.3] > hits[0][0.3] + 0.2, hits


def test_negative_low_j_bands_rejected():
    """A negative tier size must fail at construction, not silently drop
    primary bands (dict index) or crash on first ingest (compact)."""

    h = MinHasher(num_hashes=128)
    with pytest.raises(ValueError):
        LSHIndex(h, low_j_bands=-5)
    with pytest.raises(ValueError):
        CompactLSHIndex(h, low_j_bands=-5)

"""Chaos tier: deterministic failure injection through REAL assembled
nodes via the failpoint plane (kraken_tpu/utils/failpoints.py).

Every failure test before this PR hand-monkeypatched one code path; the
reaction paths the system actually sells -- corrupt piece -> peer ban ->
re-pull, ENOSPC mid-PATCH -> clean error + spool reclaim, tracker flap ->
metered announce retry, mid-transfer disconnect -> re-request -- had
never run end-to-end. Here each scenario arms a named failpoint with a
deterministic trigger (seeded RNG, one-shot, every-Nth), drives real
origin/tracker/agent nodes over real TCP, and asserts recovery with
BIT-IDENTITY on every completed pull.

Fast scenarios are unmarked (tier-1 runs them); the probabilistic soak is
``slow``. Everything here carries the ``chaos`` marker.
"""

import asyncio
import json
import os

import pytest

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.origin.metainfogen import PieceLengthConfig
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.backoff import Backoff
from kraken_tpu.utils.httputil import HTTPClient, HTTPError
from kraken_tpu.utils.metrics import REGISTRY

pytestmark = pytest.mark.chaos

NS = "chaos"
# 64 KiB pieces so a ~300 KB blob exercises multi-piece transfer paths.
SMALL_PIECES = PieceLengthConfig(table=((0, 64 * 1024),))


@pytest.fixture(autouse=True)
def chaos_plane():
    """Every test starts disarmed and ACKNOWLEDGED (nodes may assemble
    with failpoints armed), and leaves the process-global plane clean --
    a leaked armed failpoint would inject into unrelated tests."""
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    yield failpoints.FAILPOINTS
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow(False)


async def _wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(interval)


def _fired(name: str) -> float:
    return REGISTRY.counter("failpoints_fired_total").value(name=name)


async def _herd(tmp_path, n_agents=1, scheduler_config=None):
    tracker = TrackerNode(announce_interval_seconds=0.1, peer_ttl_seconds=5.0)
    await tracker.start()
    origin = OriginNode(
        store_root=str(tmp_path / "origin"),
        tracker_addr=tracker.addr,
        piece_lengths=SMALL_PIECES,
        dedup=False,
    )
    await origin.start()
    cluster = ClusterClient(
        Ring(HostList(static=[origin.addr]), max_replica=1)
    )
    tracker.server.origin_cluster = cluster
    agents = []
    for i in range(n_agents):
        a = AgentNode(
            store_root=str(tmp_path / f"agent{i}"),
            tracker_addr=tracker.addr,
            scheduler_config=scheduler_config,
        )
        await a.start()
        agents.append(a)
    return tracker, origin, agents, cluster


async def _teardown(tracker, origin, agents, cluster):
    for a in agents:
        await a.stop()
    await origin.stop()
    await cluster.close()
    await tracker.stop()


async def _pull(agent, d: Digest, timeout: float = 60.0) -> bytes:
    http = HTTPClient(timeout_seconds=timeout, retries=0)
    try:
        return await http.get(
            f"http://{agent.addr}/namespace/{NS}/blobs/{d.hex}"
        )
    finally:
        await http.close()


# -- the failpoint registry itself ------------------------------------------


def test_trigger_grammar_and_deterministic_replay():
    r = failpoints.FailpointRegistry()
    assert r.fire("nothing.armed") is None  # disarmed: no-op

    r.arm("a", "once")
    assert r.fire("a") and r.fire("a") is None

    r.arm("b", "every:3")
    assert [bool(r.fire("b")) for _ in range(6)] == [
        False, False, True, False, False, True,
    ]

    # Seeded probability replays bit-for-bit across arms.
    r.arm("c", "prob:0.5+seed:7")
    seq1 = [bool(r.fire("c")) for _ in range(32)]
    r.arm("c", "prob:0.5+seed:7")
    seq2 = [bool(r.fire("c")) for _ in range(32)]
    assert seq1 == seq2 and any(seq1) and not all(seq1)

    r.arm("d", "always+times:2")
    assert sum(bool(r.fire("d")) for _ in range(5)) == 2

    r.arm("e", "always+delay:250")
    assert abs(r.fire("e").delay_s - 0.25) < 1e-9

    for bad in ("sometimes", "prob:1.5", "every:0", "once+nope:1", "every"):
        with pytest.raises(ValueError):
            r.arm("f", bad)

    r.arm("g", "always")
    r.disarm("g")
    assert r.fire("g") is None


def test_env_arming_is_self_acknowledging():
    n = failpoints.load_from_env(
        {"KRAKEN_FAILPOINTS":
         "castore.write=once, castore.commit = prob:0.25+seed:3"}
    )
    assert n == 2
    assert failpoints.FAILPOINTS.allowed
    snap = failpoints.FAILPOINTS.snapshot()["failpoints"]
    assert snap["castore.write"]["spec"] == "once"
    assert snap["castore.commit"]["spec"] == "prob:0.25+seed:3"
    with pytest.raises(ValueError):
        failpoints.load_from_env({"KRAKEN_FAILPOINTS": "justaname"})
    with pytest.raises(ValueError):
        failpoints.load_from_env(
            {"KRAKEN_FAILPOINTS": "castore.write=bogus:spec"}
        )


def test_env_arming_rejects_undeclared_names():
    # The silent-typo hole: an env entry naming a site that is not in
    # KNOWN_FAILPOINTS would inject nothing and still report the chaos
    # run green. Base names validate; @host variants validate by base.
    with pytest.raises(ValueError, match="KNOWN_FAILPOINTS"):
        failpoints.load_from_env(
            {"KRAKEN_FAILPOINTS": "trcker.announce.error=once"}
        )
    n = failpoints.load_from_env(
        {"KRAKEN_FAILPOINTS": "rpc.brownout.slow@10.0.0.1:7610=once"}
    )
    assert n == 1
    # Programmatic arming (tests, admin endpoint) stays free-form --
    # but boot refuses env/yaml-sourced unknowns via assert_safe.
    reg = failpoints.FailpointRegistry()
    reg.arm("totally.adhoc", "once")
    reg.allowed = True
    reg.assert_safe("test")  # api-sourced: fine
    with pytest.raises(ValueError, match="KNOWN_FAILPOINTS"):
        reg.arm("trcker.announce.error", "once", source="env")
    # Belt-and-braces: an env/yaml-sourced unknown that somehow got
    # armed (older pickle, direct mutation) still fails the boot guard.
    reg.arm("trcker.announce.error", "once")
    reg._armed["trcker.announce.error"].source = "env"
    with pytest.raises(failpoints.FailpointConfigError, match="undeclared"):
        reg.assert_safe("test")


def test_disarmed_by_default_and_boot_guard():
    """Import-time default is a clean, unacknowledged plane, and
    assembly refuses to bind listeners while failpoints are armed
    without the acknowledgement -- a chaos config pasted into prod (or a
    leaked test arm) fails the boot loudly."""
    fresh = failpoints.FailpointRegistry()
    assert fresh.snapshot() == {"allowed": False, "failpoints": {}}

    async def main():
        failpoints.allow(False)
        failpoints.FAILPOINTS.arm("castore.write", "once")
        t = TrackerNode()
        with pytest.raises(failpoints.FailpointConfigError):
            await t.start()
        await t.stop()
        failpoints.allow()  # the deliberate chaos ack: boots fine
        t2 = TrackerNode()
        await t2.start()
        await t2.stop()

    asyncio.run(main())


def test_failpoints_admin_endpoint():
    """The live-node runbook surface: list/arm/disarm with fire counts
    over the metrics mux (docs/OPERATIONS.md)."""

    async def main():
        from aiohttp import web

        from kraken_tpu.utils.metrics import instrument_app

        app = web.Application()
        instrument_app(app, "chaos-admin-test")
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        base = f"http://127.0.0.1:{runner.addresses[0][1]}"
        http = HTTPClient(retries=0)
        try:
            doc = json.loads(await http.get(f"{base}/debug/failpoints"))
            assert doc["failpoints"] == {}
            await http.post(
                f"{base}/debug/failpoints",
                data=json.dumps(
                    {"action": "arm", "name": "chaos.admin.site",
                     "spec": "every:2"}
                ),
            )
            assert failpoints.fire("chaos.admin.site") is None
            assert failpoints.fire("chaos.admin.site")
            doc = json.loads(await http.get(f"{base}/debug/failpoints"))
            entry = doc["failpoints"]["chaos.admin.site"]
            assert entry["hits"] == 2 and entry["fired"] == 1
            # Firing also shows on /metrics.
            text = await http.get(f"{base}/metrics")
            assert b'failpoints_fired_total{name="chaos.admin.site"}' in text
            with pytest.raises(HTTPError) as ei:
                await http.post(
                    f"{base}/debug/failpoints",
                    data=json.dumps({"action": "bogus"}),
                )
            assert ei.value.status == 400
            # Non-string name: rejected (400), never stored -- an int key
            # would TypeError snapshot()'s sorted() and kill this surface.
            with pytest.raises(HTTPError) as ei:
                await http.post(
                    f"{base}/debug/failpoints",
                    data=json.dumps(
                        {"action": "arm", "name": 123, "spec": "once"}
                    ),
                )
            assert ei.value.status == 400
            assert json.loads(await http.get(f"{base}/debug/failpoints"))
            await http.post(
                f"{base}/debug/failpoints",
                data=json.dumps({"action": "disarm_all"}),
            )
            assert failpoints.fire("chaos.admin.site") is None

            # The mux is unauthenticated, so ARMING demands the chaos
            # acknowledgement: without it (and without
            # KRAKEN_FAILPOINTS_ALLOW=1 in the env) the POST is a 403
            # and nothing is armed or allowed. Disarming stays open.
            failpoints.allow(False)
            assert os.environ.get("KRAKEN_FAILPOINTS_ALLOW") != "1"
            with pytest.raises(HTTPError) as ei:
                await http.post(
                    f"{base}/debug/failpoints",
                    data=json.dumps(
                        {"action": "arm", "name": "castore.commit",
                         "spec": "always"}
                    ),
                )
            assert ei.value.status == 403
            assert not failpoints.FAILPOINTS.allowed
            assert failpoints.fire("castore.commit") is None
            await http.post(  # disarm_all needs no ack
                f"{base}/debug/failpoints",
                data=json.dumps({"action": "disarm_all"}),
            )
        finally:
            await http.close()
            await runner.cleanup()

    asyncio.run(main())


# -- httputil failpoints + retry visibility ----------------------------------


def _retries(method: str) -> float:
    return REGISTRY.counter("http_client_retries_total").value(method=method)


def _giveups(method: str) -> float:
    return REGISTRY.counter("http_client_giveups_total").value(method=method)


def test_http_injected_5xx_exhausts_retries_and_is_counted():
    """`httputil.request.error` armed always: every attempt sees a 503,
    the client retries its budget (counted), then gives up (counted +
    one structured WARN). No real server is ever contacted."""

    async def main():
        r0, g0 = _retries("GET"), _giveups("GET")
        failpoints.FAILPOINTS.arm("httputil.request.error", "always")
        http = HTTPClient(
            retries=2, backoff=Backoff(base_seconds=0.001, jitter=0)
        )
        try:
            with pytest.raises(HTTPError) as ei:
                await http.get("http://127.0.0.1:9/failpoint-test")
            assert ei.value.status == 503
        finally:
            await http.close()
        assert _retries("GET") == r0 + 2
        assert _giveups("GET") == g0 + 1

    asyncio.run(main())


def test_http_conn_reset_once_recovers_on_retry():
    async def main():
        from aiohttp import web

        async def ok(request):
            return web.Response(body=b"x" * 64)

        app = web.Application()
        app.router.add_get("/blob", ok)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        base = f"http://127.0.0.1:{runner.addresses[0][1]}"
        http = HTTPClient(
            retries=2, backoff=Backoff(base_seconds=0.001, jitter=0)
        )
        try:
            r0 = _retries("GET")
            failpoints.FAILPOINTS.arm("httputil.request.conn_reset", "once")
            assert await http.get(f"{base}/blob") == b"x" * 64
            assert _retries("GET") == r0 + 1
            # Truncated body: the caller sees the torn prefix (callers
            # must digest/length-check; castore commit would reject it).
            failpoints.FAILPOINTS.arm("httputil.request.truncate_body", "once")
            assert await http.get(f"{base}/blob") == b"x" * 32
        finally:
            await http.close()
            await runner.cleanup()

    asyncio.run(main())


# -- scenario 1: corrupt piece -> peer ban -> pull completes -----------------


def test_corrupt_piece_bans_peer_and_pull_completes(tmp_path):
    """One injected payload corruption: verify fails (PieceError), the
    dispatcher hard-blacklists the corrupting peer, and the pull still
    finishes bit-identical from the remaining healthy peers."""

    async def main():
        tracker, origin, agents, cluster = await _herd(tmp_path, n_agents=2)
        try:
            blob = os.urandom(5 * 64 * 1024 + 1000)  # 6 pieces
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob)
            await oc.close()

            # agent0 pulls clean and stays as a second healthy seeder.
            assert await _pull(agents[0], d) == blob

            fired0 = _fired("p2p.conn.recv.corrupt")
            failpoints.FAILPOINTS.arm("p2p.conn.recv.corrupt", "once")
            got = await _pull(agents[1], d)
            assert got == blob  # bit-identical despite the corruption
            assert _fired("p2p.conn.recv.corrupt") == fired0 + 1
            # The corrupting peer was hard-blacklisted on the leecher.
            assert agents[1].scheduler.conn_state.blacklist._entries
        finally:
            await _teardown(tracker, origin, agents, cluster)

    asyncio.run(main())


# -- scenario 2: ENOSPC mid-PATCH -> clean error, spool reclaimed, retry OK --


def test_enospc_mid_patch_clean_error_spool_reclaimed_retry_succeeds(tmp_path):
    async def main():
        from kraken_tpu.store.cleanup import CleanupConfig, CleanupManager

        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            piece_lengths=SMALL_PIECES,
            dedup=False,
        )
        await origin.start()
        # resume=False pins the LEGACY fail-fast contract (a mid-stream
        # ENOSPC surfaces as a clean 500, never a hang or corrupt blob);
        # test_enospc_mid_patch_resume_heals_transparently covers the
        # resuming client.
        oc = BlobClient(origin.addr, HTTPClient(retries=0), resume=False)
        try:
            blob = os.urandom(3 * 64 * 1024 + 500)
            d = Digest.from_bytes(blob)

            failpoints.FAILPOINTS.arm("origin.patch.write", "once")
            with pytest.raises(HTTPError) as ei:
                await oc.upload(NS, d, blob)
            assert ei.value.status == 500  # clean error, not a hang/corrupt
            assert not origin.store.in_cache(d)

            # The failed upload left its spool file; the wall-clock sweep
            # reclaims it.
            assert os.listdir(origin.store.upload_dir)
            sweeper = CleanupManager(
                origin.store, CleanupConfig(upload_ttl_seconds=0.05)
            )
            await asyncio.sleep(0.11)
            sweeper.run_once()
            assert os.listdir(origin.store.upload_dir) == []

            # Retried upload succeeds and round-trips bit-identical.
            await oc.upload(NS, d, blob)
            assert await oc.download(NS, d) == blob

            # Deferred write error at close (buffered ENOSPC): same
            # contract.
            blob2 = os.urandom(2 * 64 * 1024)
            d2 = Digest.from_bytes(blob2)
            failpoints.FAILPOINTS.arm("origin.patch.close", "once")
            with pytest.raises(HTTPError) as ei2:
                await oc.upload(NS, d2, blob2)
            assert ei2.value.status == 500
            await oc.upload(NS, d2, blob2)
            assert await oc.download(NS, d2) == blob2
        finally:
            await oc.close()
            await origin.stop()

    asyncio.run(main())


# -- scenario 3: tracker flap -> metered announce retry recovers -------------


def test_tracker_flap_metered_announce_retry_recovers(tmp_path):
    async def main():
        tracker, origin, agents, cluster = await _herd(tmp_path, n_agents=1)
        try:
            blob = os.urandom(3 * 64 * 1024)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob)
            await oc.close()

            meter = REGISTRY.counter("announce_failures_total")
            base = meter.value()
            failpoints.FAILPOINTS.arm("tracker.announce.error", "always")
            pull = asyncio.create_task(_pull(agents[0], d))
            # The flap is VISIBLE: announce failures get metered, not
            # swallowed (FailureMeter on the scheduler's announce loop).
            await _wait_for(
                lambda: meter.value() > base,
                timeout=20.0,
                msg="announce failure to be metered",
            )
            assert not pull.done()
            # Tracker recovers: the paced re-announce finds peers and the
            # pull completes bit-identical.
            failpoints.FAILPOINTS.disarm("tracker.announce.error")
            assert await asyncio.wait_for(pull, 40.0) == blob

            # An empty handout (fresh-restarted tracker) is also benign:
            # the leecher just re-announces.
            failpoints.FAILPOINTS.arm("tracker.announce.empty", "always+times:3")
            blob2 = os.urandom(2 * 64 * 1024)
            d2 = Digest.from_bytes(blob2)
            oc2 = BlobClient(origin.addr)
            await oc2.upload(NS, d2, blob2)
            await oc2.close()
            assert await _pull(agents[0], d2) == blob2
        finally:
            await _teardown(tracker, origin, agents, cluster)

    asyncio.run(main())


# -- scenario 4: mid-transfer disconnect -> re-request -> pull finishes ------


def test_mid_transfer_disconnect_rerequests_and_finishes(tmp_path):
    async def main():
        tracker, origin, agents, cluster = await _herd(tmp_path, n_agents=1)
        try:
            blob = os.urandom(6 * 64 * 1024 + 123)  # 7 pieces
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob)
            await oc.close()

            fired0 = _fired("p2p.conn.disconnect")
            # First payload frame kills the conn (and discards the
            # frame): the dispatcher must drop the peer without
            # blacklisting, re-announce, re-dial, and re-request the
            # lost piece.
            failpoints.FAILPOINTS.arm("p2p.conn.disconnect", "once")
            got = await _pull(agents[0], d)
            assert got == blob
            assert _fired("p2p.conn.disconnect") == fired0 + 1
        finally:
            await _teardown(tracker, origin, agents, cluster)

    asyncio.run(main())


# -- scenario 5: at-rest bit flip -> scrub -> quarantine -> ring heal --------


def test_at_rest_bitflip_scrub_quarantine_heal_reconverges(tmp_path):
    """The full self-healing storage loop, end to end over real TCP: an
    injected at-rest bit flip (store.scrub.bitflip writes real damage to
    the platter) is detected by the scrubber, the blob is quarantined
    (file present under quarantine/, scrub_corruptions_total moves),
    restored bit-identical from the healthy ring replica through the
    persistedretry heal plane, and replication is re-enqueued so the
    ring converges back to max_replica."""

    async def main():
        from kraken_tpu.store.scrub import ScrubConfig

        origins = []
        for i in range(2):
            o = OriginNode(
                store_root=str(tmp_path / f"origin{i}"),
                piece_lengths=SMALL_PIECES,
                dedup=False,
                scrub=ScrubConfig(
                    interval_seconds=3600.0, bytes_per_second=0
                ),
            )
            await o.start()
            origins.append(o)
        ring = Ring(HostList(static=[o.addr for o in origins]), max_replica=2)
        for o in origins:
            o.ring = ring
            o.self_addr = o.addr
            o.server.ring = ring
            o.server.self_addr = o.addr
        try:
            blob = os.urandom(4 * 64 * 1024 + 77)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origins[0].addr)
            await oc.upload(NS, d, blob)
            await oc.close()
            # The replication plane fills the second owner, then drains
            # fully: origin1's own replicate-back task must retire BEFORE
            # the corruption, or its push could race (and win against)
            # the heal pull this scenario is proving.
            await _wait_for(
                lambda: origins[1].store.in_cache(d),
                msg="initial replication to the second origin",
            )
            await _wait_for(
                lambda: not any(
                    o.retry.store.all_pending() for o in origins
                ),
                msg="replication plane quiescent",
            )

            corr0 = REGISTRY.counter("scrub_corruptions_total").value(
                source="scrub"
            )
            heal0 = REGISTRY.counter("blob_heals_total").value(source="ring")
            repl0 = REGISTRY.counter("replication_enqueued_total").value()

            failpoints.FAILPOINTS.arm("store.scrub.bitflip", "once")
            bad = await origins[0].scrubber.run_cycle()
            assert [b.hex for b in bad] == [d.hex]
            # Quarantined for post-mortem: damaged bytes present under
            # quarantine/, gone from the cache tree, counted.
            qpath = origins[0].store.quarantine_path(d)
            assert os.path.exists(qpath)
            with await asyncio.to_thread(open, qpath, "rb") as f:
                captured = await asyncio.to_thread(f.read)
            assert captured != blob and len(captured) == len(blob)
            assert not origins[0].store.in_cache(d)
            assert REGISTRY.counter("scrub_corruptions_total").value(
                source="scrub"
            ) == corr0 + 1

            # Heal: the retry plane re-fetches from the healthy replica,
            # bit-identity enforced by the verifying commit.
            await _wait_for(
                lambda: origins[0].store.in_cache(d),
                timeout=30.0,
                msg="heal re-fetch from the ring replica",
            )
            assert origins[0].store.read_cache_file(d) == blob
            # The heal metric and the re-enqueued replication land a
            # beat after the commit (post-commit pipeline): wait, don't
            # assert instantaneously.
            await _wait_for(
                lambda: REGISTRY.counter("blob_heals_total").value(
                    source="ring"
                ) == heal0 + 1,
                msg="heal counted against the ring source",
            )
            await _wait_for(
                lambda: REGISTRY.counter(
                    "replication_enqueued_total"
                ).value() > repl0,
                msg="replication re-enqueued after heal",
            )
            # And the healed blob still serves bit-identical over HTTP.
            oc2 = BlobClient(origins[0].addr)
            assert await oc2.download(NS, d) == blob
            await oc2.close()
        finally:
            for o in origins:
                await o.stop()

    asyncio.run(main())


# -- scenario 6: brown-out origin -> hedged reads keep pull latency bounded --


def test_brownout_origin_hedged_reads_keep_pull_latency_bounded(tmp_path):
    """The tail-tolerance acceptance gate (round 8): a SLOW-BUT-ALIVE
    origin (rpc.brownout.slow@addr stalls its read handlers 2 s, armed
    on one origin of two) must cost tail latency, not availability --
    with hedging on the tracker's metainfo path, p99 pull time stays
    within 2x the healthy baseline instead of eating the full 2 s stall
    on every pull whose primary replica is the browned-out origin."""

    async def main():
        from kraken_tpu.placement.healthcheck import PassiveFilter

        tracker = TrackerNode(
            announce_interval_seconds=0.1, peer_ttl_seconds=5.0
        )
        await tracker.start()
        origins = []
        for i in range(2):
            o = OriginNode(
                store_root=str(tmp_path / f"origin{i}"),
                tracker_addr=tracker.addr,
                piece_lengths=SMALL_PIECES,
                dedup=False,
            )
            await o.start()
            origins.append(o)
        ring = Ring(
            HostList(static=[o.addr for o in origins]), max_replica=2
        )
        cluster = ClusterClient(
            ring,
            health=PassiveFilter(name="chaos-brownout-breaker"),
            hedge_delay_seconds=0.15,
            deadline_seconds=10.0,
            component="tracker",
        )
        tracker.server.origin_cluster = cluster
        agent = AgentNode(
            store_root=str(tmp_path / "agent"), tracker_addr=tracker.addr
        )
        await agent.start()

        def blobs_with_slow_primary(n, salt):
            """Blobs whose ring PRIMARY is origins[0] -- the pulls that
            would eat the brown-out without hedging."""
            out = []
            i = 0
            while len(out) < n:
                blob = os.urandom(3 * 64 * 1024 + 11) + f"{salt}-{i}".encode()
                d = Digest.from_bytes(blob)
                if ring.locations(d)[0] == origins[0].addr:
                    out.append((d, blob))
                i += 1
            return out

        async def seed_everywhere(pairs):
            # Both origins hold + seed every blob, so the hedge target
            # can actually serve the metainfo and the swarm has a
            # healthy seeder either way.
            for o in origins:
                oc = BlobClient(o.addr)
                for d, blob in pairs:
                    await oc.upload(NS, d, blob)
                await oc.close()

        async def timed_pulls(pairs):
            walls = []
            for d, blob in pairs:
                t0 = asyncio.get_running_loop().time()
                assert await _pull(agent, d) == blob
                walls.append(asyncio.get_running_loop().time() - t0)
            return walls

        try:
            healthy_pairs = blobs_with_slow_primary(3, "healthy")
            brown_pairs = blobs_with_slow_primary(3, "brown")
            await seed_everywhere(healthy_pairs + brown_pairs)

            healthy = await timed_pulls(healthy_pairs)
            healthy_p99 = max(healthy)

            wins = REGISTRY.counter("rpc_hedge_wins_total")
            w0 = wins.value(op="get_metainfo")
            site = f"rpc.brownout.slow@{origins[0].addr}"
            failpoints.FAILPOINTS.arm(site, "always+delay:2000")
            brown = await timed_pulls(brown_pairs)
            brown_p99 = max(brown)

            assert _fired(site) >= 1  # the brown-out really stalled reads
            # The acceptance bound: within 2x the healthy baseline (the
            # +0.2 s floor keeps a sub-100ms baseline from turning timer
            # jitter into a false failure; the 2 s stall dwarfs both).
            assert brown_p99 <= 2 * healthy_p99 + 0.2, (
                f"brown-out stalled the pull: {brown} vs healthy {healthy}"
            )
            # The added cost must be hedge_delay-ish, never the 2 s
            # stall itself (relative bound: robust to a slow CI rig).
            assert brown_p99 - healthy_p99 < 1.0, (
                "pull ate the brown-out stall -- hedge never won"
            )
            assert wins.value(op="get_metainfo") > w0
        finally:
            failpoints.FAILPOINTS.disarm_all()
            await agent.stop()
            for o in origins:
                await o.stop()
            await cluster.close()
            await tracker.stop()

    asyncio.run(main())


# -- scenario 7: lameduck drain under an active swarm -> zero failed pulls ---


def test_drain_under_active_swarm_zero_failed_transfers(tmp_path):
    """SIGTERM's drain path, mid-transfer: the origin enters lameduck
    while a bandwidth-throttled pull is in flight. The established conn
    must finish every piece (bit-identity), new work must bounce with
    503+Retry-After, and the drain must quiesce on its own -- zero
    failed piece transfers, zero peer bans."""

    async def main():
        from kraken_tpu.p2p.scheduler import SchedulerConfig

        tracker = TrackerNode(
            announce_interval_seconds=0.1, peer_ttl_seconds=5.0
        )
        await tracker.start()
        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            tracker_addr=tracker.addr,
            piece_lengths=SMALL_PIECES,
            dedup=False,
            # Throttle egress so the pull is reliably still in flight
            # when the drain lands: the bucket's burst covers the first
            # corked batch (~1 MiB), then the remaining ~3 MiB pace out
            # at 1 MiB/s ~= 3 s of mid-drain transfer.
            p2p_bandwidth={"egress_bps": 1024 * 1024},
            # Short churn so the drained conn closes soon after the
            # transfer completes and drain() can quiesce.
            scheduler_config_doc={"conn_churn_idle_seconds": 1.0},
        )
        await origin.start()
        drain_cluster = ClusterClient(
            Ring(HostList(static=[origin.addr]), max_replica=1)
        )
        tracker.server.origin_cluster = drain_cluster
        agent = AgentNode(
            store_root=str(tmp_path / "agent"),
            tracker_addr=tracker.addr,
            scheduler_config=SchedulerConfig(announce_interval_seconds=0.1),
        )
        await agent.start()
        try:
            blob = os.urandom(64 * 64 * 1024 + 99)  # 65 pieces ~= 4 MiB
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob)
            await oc.close()

            pull = asyncio.create_task(_pull(agent, d, timeout=60.0))
            # Wait until the transfer is genuinely in flight.
            await _wait_for(
                lambda: agent.scheduler.num_active_conns > 0
                and not pull.done(),
                msg="pull to open its p2p conn",
            )

            t0 = asyncio.get_running_loop().time()
            drain = asyncio.create_task(origin.drain(timeout=25.0))
            # While draining: health fails, new uploads bounce politely.
            import aiohttp

            async with aiohttp.ClientSession() as sess:
                async with sess.get(
                    f"http://{origin.addr}/health"
                ) as r:
                    assert r.status == 503
                async with sess.post(
                    f"http://{origin.addr}/namespace/{NS}/blobs/"
                    f"{Digest.from_bytes(b'new-upload').hex}/uploads"
                ) as r:
                    assert r.status == 503
                    assert r.headers.get("Retry-After")

            # The in-flight pull finishes bit-identical THROUGH the
            # drain: zero failed piece transfers.
            assert await asyncio.wait_for(pull, 45.0) == blob
            await asyncio.wait_for(drain, 30.0)
            drain_wall = asyncio.get_running_loop().time() - t0
            assert drain_wall < 24.0, "drain only ended at its timeout"
            # Nothing was banned and nothing misbehaved on either side.
            assert not agent.scheduler.conn_state.blacklist._entries
            # Conn teardown lands a callback-beat after the pull
            # resolves (more under KT_SANITIZE's asyncio debug mode):
            # the drain contract is that conns REACH zero, not that
            # they are zero at this exact instant.
            await _wait_for(
                lambda: agent.scheduler.num_active_conns == 0,
                timeout=5.0, msg="agent conns to reap after drain",
            )
        finally:
            await agent.stop()
            await origin.stop()
            await drain_cluster.close()
            await tracker.stop()

    asyncio.run(main())


# -- soak: probabilistic multi-fault swarm (slow) ----------------------------


@pytest.mark.slow
def test_chaos_soak_probabilistic_faults_swarm(tmp_path):
    """Seeded probabilistic corruption + disconnects + tracker errors,
    all at once, over a 3-agent swarm pulling several blobs: every pull
    must complete bit-identical. Fixed seeds make a failure replayable
    with KRAKEN_FAILPOINTS set to the same specs."""

    async def main():
        from kraken_tpu.p2p.connstate import ConnStateConfig
        from kraken_tpu.p2p.scheduler import SchedulerConfig

        # Quick-recovery blacklist: with probabilistic corruption an
        # agent may ban every seeder; the test asserts recovery, not
        # 30 s production cool-offs.
        cfg = SchedulerConfig(
            announce_interval_seconds=0.1,
            conn_state=ConnStateConfig(
                blacklist_backoff=Backoff(
                    base_seconds=0.3, factor=1.5, max_seconds=2.0, jitter=0
                ),
                soft_blacklist_seconds=0.3,
            ),
        )
        tracker, origin, agents, cluster = await _herd(
            tmp_path, n_agents=3, scheduler_config=cfg
        )
        try:
            blobs = []
            oc = BlobClient(origin.addr)
            for i in range(4):
                blob = os.urandom(4 * 64 * 1024 + i * 1111)
                blobs.append((Digest.from_bytes(blob), blob))
                await oc.upload(NS, blobs[-1][0], blob)
            await oc.close()

            failpoints.FAILPOINTS.arm(
                "p2p.conn.recv.corrupt", "prob:0.03+seed:1"
            )
            failpoints.FAILPOINTS.arm(
                "p2p.conn.disconnect", "prob:0.01+seed:2"
            )
            failpoints.FAILPOINTS.arm(
                "tracker.announce.error", "prob:0.2+seed:3"
            )
            failpoints.FAILPOINTS.arm(
                "p2p.conn.send.delay", "prob:0.05+delay:20+seed:4"
            )
            results = await asyncio.gather(
                *(
                    _pull(a, d, timeout=120.0)
                    for a in agents
                    for d, _b in blobs
                )
            )
            expected = [b for _a in agents for _d, b in blobs]
            assert results == expected  # bit-identity on EVERY pull
        finally:
            failpoints.FAILPOINTS.disarm_all()
            await _teardown(tracker, origin, agents, cluster)

    asyncio.run(main())


# -- scenario 8: origin SIGKILL mid-upload -> journaled resume, bit-identical -


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_origin_crash_mid_upload_client_resumes_bit_identical(tmp_path):
    """ACCEPTANCE: an origin hard-killed mid-upload (no clean-shutdown
    stamp, every in-memory tracker lost) restarts, fsck preserves the
    journaled session, HEAD re-adopts it at the durable offset, the
    client re-PATCHes ONLY the tail, and the committed digest + served
    metainfo are bit-identical to the single-shot oracle."""

    async def main():
        import aiohttp

        from kraken_tpu.core.hasher import get_hasher
        from kraken_tpu.origin.metainfogen import TorrentMetaMetadata

        piece = 64 * 1024
        blob = os.urandom(5 * piece + 77)
        d = Digest.from_bytes(blob)
        cut = 3 * piece + 11  # past the flush -> journaled durable offset
        port = _free_port()
        root = str(tmp_path / "origin")

        origin1 = OriginNode(
            store_root=root, http_port=port,
            piece_lengths=SMALL_PIECES, dedup=False,
        )
        await origin1.start()
        base = f"http://{origin1.addr}/namespace/{NS}/blobs/{d}"
        async with aiohttp.ClientSession() as http:
            async with http.post(f"{base}/uploads") as r:
                uid = await r.text()
            async with http.patch(
                f"{base}/uploads/{uid}", data=blob[:cut],
                headers={"X-Upload-Offset": "0"},
            ) as r:
                assert r.status == 204
        # SIGKILL stand-in: stop WITHOUT the clean-shutdown stamp. The
        # process state (upload trackers, pipeline sessions) dies with
        # it; only the spool + session journal survive on disk.
        mp = pytest.MonkeyPatch()
        mp.setattr(
            "kraken_tpu.assembly.write_clean_shutdown", lambda store: None
        )
        try:
            await origin1.stop()
        finally:
            mp.undo()

        origin2 = OriginNode(
            store_root=root, http_port=port,
            piece_lengths=SMALL_PIECES, dedup=False,
        )
        adopted0 = REGISTRY.counter("upload_sessions_adopted_total").value()
        await origin2.start()  # startup fsck preserves the live session
        try:
            async with aiohttp.ClientSession() as http:
                async with http.request(
                    "HEAD", f"{base}/uploads/{uid}"
                ) as r:
                    assert r.status == 200
                    offset = int(r.headers["X-Upload-Offset"])
                # Resume from the journaled durable offset: the client
                # re-sends ONLY the tail, not the whole blob.
                assert offset == cut
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[offset:],
                    headers={"X-Upload-Offset": str(offset)},
                ) as r:
                    assert r.status == 204
                async with http.put(f"{base}/uploads/{uid}/commit") as r:
                    assert r.status == 201
            assert (
                REGISTRY.counter("upload_sessions_adopted_total").value()
                == adopted0 + 1
            )
            assert origin2.store.read_cache_file(d) == blob
            stored = origin2.store.get_metadata(d, TorrentMetaMetadata)
            oracle = get_hasher("cpu").hash_pieces(blob, piece).tobytes()
            assert stored.metainfo.piece_hashes == oracle
            assert stored.metainfo.length == len(blob)
        finally:
            await origin2.stop()

    asyncio.run(main())


# -- scenario 9: device hasher dies mid-stream -> host fallback, identical ---


def test_device_hasher_failpoint_falls_back_host_bit_identical(tmp_path):
    async def main():
        from kraken_tpu.core.hasher import get_hasher
        from kraken_tpu.origin.metainfogen import TorrentMetaMetadata

        piece = 64 * 1024
        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            piece_lengths=SMALL_PIECES, dedup=False,
            ingest={"window_bytes": 1 << 20, "windows_in_flight": 2},
        )
        await origin.start()
        oc = BlobClient(origin.addr, HTTPClient(retries=0))
        try:
            blob = os.urandom(4 * piece + 123)
            d = Digest.from_bytes(blob)
            fell0 = REGISTRY.counter("ingest_fallbacks_total").value(
                reason="failpoint"
            )
            failpoints.FAILPOINTS.arm("origin.ingest.device_fail", "once")
            await oc.upload(NS, d, blob)  # degrades live, never errors
            assert _fired("origin.ingest.device_fail") >= 1
            assert (
                REGISTRY.counter("ingest_fallbacks_total").value(
                    reason="failpoint"
                )
                == fell0 + 1
            )
            stored = origin.store.get_metadata(d, TorrentMetaMetadata)
            oracle = get_hasher("cpu").hash_pieces(blob, piece).tobytes()
            assert stored.metainfo.piece_hashes == oracle
            assert await oc.download(NS, d) == blob
        finally:
            await oc.close()
            await origin.stop()

    asyncio.run(main())


# -- scenario 10: ENOSPC mid-PATCH -> the resuming client heals silently -----


def test_enospc_mid_patch_resume_heals_transparently(tmp_path):
    """The default (resume=True) client turns scenario 2's hard failure
    into a non-event: the failed PATCH is retried from the origin's
    durable offset under backoff and the upload completes with NO
    exception surfacing to the caller."""

    async def main():
        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            piece_lengths=SMALL_PIECES, dedup=False,
        )
        await origin.start()
        oc = BlobClient(origin.addr, HTTPClient(retries=0))
        try:
            blob = os.urandom(3 * 64 * 1024 + 500)
            d = Digest.from_bytes(blob)
            failpoints.FAILPOINTS.arm("origin.patch.write", "once")
            await oc.upload(NS, d, blob)  # no pytest.raises: it heals
            assert _fired("origin.patch.write") >= 1
            assert await oc.download(NS, d) == blob
        finally:
            await oc.close()
            await origin.stop()

    asyncio.run(main())


# -- scenario 11: agent pulls a blob whose commit hasn't finished ------------


def test_pull_of_still_ingesting_blob_serves_before_commit(tmp_path):
    """serve_while_ingest: once every byte is spooled and every piece
    hash known (commit REQUEST time), the metainfo publishes and the
    origin seeds straight from the spool -- an agent pull completes
    while the commit itself is still grinding (origin.commit.slow)."""

    async def main():
        from kraken_tpu.origin.metainfogen import TorrentMetaMetadata

        tracker = TrackerNode(
            announce_interval_seconds=0.1, peer_ttl_seconds=5.0
        )
        await tracker.start()
        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            tracker_addr=tracker.addr,
            piece_lengths=SMALL_PIECES,
            dedup=False,
            ingest={
                "window_bytes": 1 << 20,
                "windows_in_flight": 2,
                "serve_while_ingest": True,
            },
        )
        await origin.start()
        cluster = ClusterClient(
            Ring(HostList(static=[origin.addr]), max_replica=1)
        )
        tracker.server.origin_cluster = cluster
        agent = AgentNode(
            store_root=str(tmp_path / "agent"), tracker_addr=tracker.addr
        )
        await agent.start()
        oc = BlobClient(origin.addr, HTTPClient(retries=0))
        try:
            blob = os.urandom(5 * 64 * 1024 + 99)
            d = Digest.from_bytes(blob)
            # The commit stalls 3s AFTER early publish -- the window in
            # which the swarm must already be serving the spool bytes.
            failpoints.FAILPOINTS.arm("origin.commit.slow", "once+delay:3000")
            upload_task = asyncio.create_task(oc.upload(NS, d, blob))
            # Early publish lands the metainfo sidecar before commit.
            await _wait_for(
                lambda: origin.store.get_metadata(d, TorrentMetaMetadata)
                is not None,
                msg="early-published metainfo",
            )
            got = await _pull(agent, d)
            assert not upload_task.done(), (
                "pull must complete INSIDE the commit window"
            )
            assert got == blob
            await upload_task  # the slow commit still succeeds
            assert origin.store.in_cache(d)
            assert await _pull(agent, d) == blob  # post-promote re-serve
        finally:
            await oc.close()
            await agent.stop()
            await origin.stop()
            await cluster.close()
            await tracker.stop()

    asyncio.run(main())


# -- scenario: link-fault matrix at the HTTP transport -----------------------


def test_link_fault_matrix_partitions_by_destination():
    """`rpc.link.drop@{dst}` severs every HTTP request INTO one host
    while other destinations stay reachable -- the primitive partition
    tests are built from. Global `rpc.link.drop` kills all destinations;
    `rpc.link.delay@{dst}` injects latency without severing."""

    async def main():
        import time

        from aiohttp import web

        async def ok(request):
            return web.Response(body=b"ok")

        runners, bases, dsts = [], [], []
        for _ in range(2):
            app = web.Application()
            app.router.add_get("/x", ok)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = runner.addresses[0][1]
            runners.append(runner)
            bases.append(f"http://127.0.0.1:{port}")
            dsts.append(f"127.0.0.1:{port}")

        http = HTTPClient(retries=0)
        try:
            # Destination-selective: only dsts[0] is partitioned.
            failpoints.FAILPOINTS.arm(f"rpc.link.drop@{dsts[0]}", "always")
            import aiohttp

            with pytest.raises(aiohttp.ClientConnectionError):
                await http.get(f"{bases[0]}/x")
            assert await http.get(f"{bases[1]}/x") == b"ok"
            assert _fired(f"rpc.link.drop@{dsts[0]}") >= 1
            failpoints.FAILPOINTS.disarm_all()

            # Global variant: EVERY destination is dark.
            failpoints.FAILPOINTS.arm("rpc.link.drop", "always")
            for base in bases:
                with pytest.raises(aiohttp.ClientConnectionError):
                    await http.get(f"{base}/x")
            assert _fired("rpc.link.drop") >= 2
            failpoints.FAILPOINTS.disarm_all()

            # Delay variant: slow link, not a severed one.
            failpoints.FAILPOINTS.arm(
                f"rpc.link.delay@{dsts[1]}", "always+delay:80"
            )
            t0 = time.monotonic()
            assert await http.get(f"{bases[1]}/x") == b"ok"
            assert time.monotonic() - t0 >= 0.08
        finally:
            await http.close()
            for runner in runners:
                await runner.cleanup()

    asyncio.run(main())


# -- scenario: crash between hint replay and task retirement -----------------


def test_hint_replay_crash_window_is_effectively_once(tmp_path):
    """`origin.hint.replay.crash` fires AFTER the replay push lands but
    BEFORE the task retires: the hint must stay journaled, and the re-run
    must converge as a cheap stat hit (effectively-once), retiring the
    task and counting exactly one replay."""

    async def main():
        import socket
        import time

        from kraken_tpu.origin.server import HINT_KIND, _hint_task

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        ports = [free_port() for _ in range(2)]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        nodes = []
        for i in range(2):
            node = OriginNode(
                store_root=str(tmp_path / f"origin{i}"),
                http_port=ports[i],
                ring=Ring(HostList(static=addrs), max_replica=2),
                self_addr=addrs[i],
                dedup=False,
            )
            await node.start()
            node.retry.stop()  # tests drive run_once by hand
            nodes.append(node)
        try:
            blob = os.urandom(100_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(addrs[0])
            await oc.upload(NS, d, blob)
            await oc.close()
            assert not nodes[1].store.in_cache(d)

            # Journal a hint for the replica by hand (as a partition at
            # commit would) and crash the first replay attempt.
            nodes[0].retry.add(
                _hint_task(addrs[1], NS, d, time.time() + 3600.0)
            )
            replayed0 = REGISTRY.counter("origin_hints_total").value(
                state="replayed"
            )
            failpoints.FAILPOINTS.arm("origin.hint.replay.crash", "once")
            await nodes[0].retry.run_once()
            assert _fired("origin.hint.replay.crash") >= 1
            # The push landed, but the crash kept the task journaled
            # and the replay uncounted.
            assert nodes[1].store.in_cache(d)
            assert (
                nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:")
                == 1
            )
            assert (
                REGISTRY.counter("origin_hints_total").value(state="replayed")
                == replayed0
            )

            # Re-run past the failure backoff: stat-first replay retires
            # the task; exactly ONE replay is counted for the pair.
            await nodes[0].retry.run_once(now=time.time() + 3600.0)
            assert (
                nodes[0].retry.store.count_pending(HINT_KIND, f"{d.hex}:")
                == 0
            )
            assert (
                REGISTRY.counter("origin_hints_total").value(state="replayed")
                == replayed0 + 1
            )
            c = BlobClient(addrs[1])
            assert await c.download(NS, d) == blob
            await c.close()
        finally:
            for node in nodes:
                await node.stop()

    asyncio.run(main())

"""Dedup plane integration: CDC + SHA + MinHash wired into the origin.

Small CDC params keep runtime down on the CPU suite; the production-size
path is exercised by bench_dedup.py on real hardware.
"""

import asyncio

import numpy as np
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.ops.cdc import CDCParams, chunk_spans
from kraken_tpu.origin.dedup import ChunkSketchMetadata, DedupIndex
from kraken_tpu.store import CAStore

PARAMS = CDCParams(min_size=256, avg_size=1024, max_size=4096)


def _store_blob(store: CAStore, data: bytes) -> Digest:
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    store.commit_upload(uid, d)
    return d


def _near_dup_blobs(rng) -> tuple[bytes, bytes, bytes]:
    """Two blobs sharing most content at SHIFTED offsets + one unrelated."""
    shared = rng.integers(0, 256, size=48 * 1024, dtype=np.uint8).tobytes()
    a = rng.integers(0, 256, size=512, dtype=np.uint8).tobytes() + shared
    b = rng.integers(0, 256, size=2048, dtype=np.uint8).tobytes() + shared
    c = rng.integers(0, 256, size=50 * 1024, dtype=np.uint8).tobytes()
    return a, b, c


@pytest.mark.parametrize("index_kind", ["dict", "compact"])
def test_similar_finds_shifted_duplicate(tmp_path, index_kind):
    rng = np.random.default_rng(0)
    a, b, c = _near_dup_blobs(rng)
    store = CAStore(str(tmp_path))
    index = DedupIndex(store, params=PARAMS, index_kind=index_kind)
    da, db, dc = (_store_blob(store, x) for x in (a, b, c))
    for d in (da, db, dc):
        index.add_blob_sync(d)

    hits = index.similar(da, k=5)
    assert hits, "no near-duplicates found"
    assert hits[0]["digest"] == db.hex
    assert hits[0]["score"] > 0.5
    assert all(h["digest"] != dc.hex or h["score"] < 0.3 for h in hits)

    # Exact byte accounting: b's shared chunks count as duplicate bytes.
    assert index.duplicate_bytes > len(b) // 2
    assert 0.0 < index.dedup_ratio < 1.0


def test_sidecar_persistence_rebuilds_index(tmp_path):
    rng = np.random.default_rng(1)
    a, b, _ = _near_dup_blobs(rng)
    store = CAStore(str(tmp_path))
    index = DedupIndex(store, params=PARAMS)
    da, db = _store_blob(store, a), _store_blob(store, b)
    index.add_blob_sync(da)
    index.add_blob_sync(db)
    stats1 = index.stats()

    # Fresh process: rebuild purely from sidecars (no re-chunking of data).
    index2 = DedupIndex(store, params=PARAMS)
    assert index2.load_existing() == 2
    assert index2.stats() == stats1
    hits = index2.similar(da, k=5)
    assert hits and hits[0]["digest"] == db.hex


def test_sketch_metadata_roundtrip():
    md = ChunkSketchMetadata(
        sketch=np.arange(128, dtype=np.uint32),
        # Ledger fingerprints are 64-bit (first 8 digest bytes): 32-bit
        # truncation hits birthday collisions past ~2^16 unique chunks.
        fps=np.array([1, 2, 1 << 40], dtype=np.uint64),
        sizes=np.array([10, 20, 30], dtype=np.uint32),
    )
    back = ChunkSketchMetadata.deserialize(md.serialize())
    assert back.fps.dtype == np.uint64
    assert np.array_equal(back.sketch, md.sketch)
    assert np.array_equal(back.fps, md.fps)
    assert np.array_equal(back.sizes, md.sizes)


def test_stale_sidecar_version_recomputed(tmp_path):
    """A v1 (32-bit-fps) sidecar is treated as absent and recomputed."""
    import struct

    from kraken_tpu.origin.dedup import _MAGIC

    rng = np.random.default_rng(7)
    a, _, _ = _near_dup_blobs(rng)
    store = CAStore(str(tmp_path))
    da = _store_blob(store, a)
    v1 = struct.pack("<BBHI", _MAGIC, 1, 0, 0)
    with open(store.cache_path(da) + "._md_chunksketch", "wb") as f:
        f.write(v1)

    index = DedupIndex(store, params=PARAMS)
    assert index.load_existing() == 0  # stale sidecar not admitted
    record = index.add_blob_sync(da)  # recomputed, not crashed
    assert record.fps.dtype == np.uint64 and record.fps.size > 0
    assert index.stats()["blobs"] == 1


def test_remove_blob_restores_accounting(tmp_path):
    rng = np.random.default_rng(5)
    a, b, _ = _near_dup_blobs(rng)
    store = CAStore(str(tmp_path))
    index = DedupIndex(store, params=PARAMS)
    da, db = _store_blob(store, a), _store_blob(store, b)
    index.add_blob_sync(da)
    stats_a_only = index.stats()
    index.add_blob_sync(db)
    assert index.duplicate_bytes > 0

    assert index.remove_sync(db)
    assert index.stats() == stats_a_only
    assert all(h["digest"] != db.hex for h in index.similar(da, k=5))
    assert not index.remove_sync(db)  # already gone

    # Re-admission restores the exact pre-removal state.
    index.add_blob_sync(db)
    assert index.stats()["blobs"] == 2
    assert index.duplicate_bytes > 0
    hits = index.similar(da, k=5)
    assert hits and hits[0]["digest"] == db.hex


def test_add_blob_idempotent(tmp_path):
    rng = np.random.default_rng(2)
    a, _, _ = _near_dup_blobs(rng)
    store = CAStore(str(tmp_path))
    index = DedupIndex(store, params=PARAMS)
    da = _store_blob(store, a)
    index.add_blob_sync(da)
    total1 = index.total_bytes
    index.add_blob_sync(da)
    assert index.total_bytes == total1  # no double counting


def test_origin_http_similar_endpoint(tmp_path):
    """Herd-level check: commit two near-dup blobs over HTTP, query
    /similar and /dedup/stats."""
    asyncio.run(_origin_http_similar(tmp_path))


async def _origin_http_similar(tmp_path):
    from aiohttp import ClientSession

    from kraken_tpu.assembly import OriginNode

    rng = np.random.default_rng(3)
    a, b, _ = _near_dup_blobs(rng)

    node = OriginNode(store_root=str(tmp_path / "o"))
    node.dedup.params = PARAMS
    await node.start()
    try:
        async with ClientSession() as http:
            digests = []
            for blob in (a, b):
                d = Digest.from_bytes(blob)
                digests.append(d)
                url = f"http://{node.addr}/namespace/test/blobs/{d}"
                async with http.post(f"{url}/uploads") as r:
                    uid = await r.text()
                async with http.patch(f"{url}/uploads/{uid}", data=blob) as r:
                    assert r.status == 204
                async with http.put(f"{url}/uploads/{uid}/commit") as r:
                    assert r.status == 201
            # Commit-time indexing is off the request path; wait for it.
            for _ in range(100):
                async with http.get(f"http://{node.addr}/dedup/stats") as r:
                    if (await r.json())["blobs"] >= 2:
                        break
                await asyncio.sleep(0.05)
            url = f"http://{node.addr}/namespace/test/blobs/{digests[0]}/similar"
            async with http.get(url) as r:
                assert r.status == 200
                hits = (await r.json())["similar"]
            assert hits and hits[0]["digest"] == digests[1].hex
            async with http.get(f"http://{node.addr}/dedup/stats") as r:
                stats = await r.json()
            assert stats["blobs"] == 2
            assert stats["duplicate_bytes"] > 0

            # Malformed query params are a client error, not a 500.
            async with http.get(url, params={"k": "bogus"}) as r:
                assert r.status == 400
            async with http.get(url, params={"min_jaccard": "nan%"}) as r:
                assert r.status == 400
            async with http.get(url, params={"k": "0"}) as r:
                assert r.status == 400

            # DELETE drops the blob from the index, not just the store.
            del_url = (
                f"http://{node.addr}/namespace/test/blobs/{digests[1]}"
            )
            async with http.delete(del_url) as r:
                assert r.status == 204
            async with http.get(f"http://{node.addr}/dedup/stats") as r:
                stats = await r.json()
            assert stats["blobs"] == 1
            assert stats["duplicate_bytes"] == 0
            async with http.get(url) as r:
                hits = (await r.json())["similar"]
            assert all(h["digest"] != digests[1].hex for h in hits)
    finally:
        await node.stop()


def test_chunk_router_host_and_device_paths_agree(tmp_path):
    """The routing policy (VERDICT r4 #4) must never change RESULTS: host
    and device spans are bit-identical, small blobs skip calibration, and
    on a CPU-only rig the decision is 'host' without touching jax
    transfer paths."""

    from kraken_tpu.origin.dedup import ChunkRouter

    params = CDCParams()
    rng = np.random.default_rng(5)

    small = rng.integers(0, 256, 1 << 20, np.uint8).tobytes()
    big = rng.integers(0, 256, 9 << 20, np.uint8).tobytes()

    r = ChunkRouter(params)
    assert r.spans(small) == chunk_spans(small, params)
    assert r.decision is None  # small blobs never calibrate

    spans = r.spans(big)
    assert spans == chunk_spans(big, params)
    # tests run under JAX_PLATFORMS=cpu: the router must refuse the
    # device path outright (no transfer benchmarking against a fake
    # device) and record the host decision.
    assert r.decision == "host"


def test_low_j_bands_config_reaches_both_indexes(tmp_path):
    """The dedup_low_j_bands knob flows OriginNode -> DedupIndex -> index
    implementation; 0 disables the tier."""

    store = CAStore(str(tmp_path / "s"))
    on = DedupIndex(store)
    off = DedupIndex(store, low_j_bands=0)
    compact_off = DedupIndex(store, index_kind="compact", low_j_bands=0)
    assert on._index.low_j_bands == 32
    assert off._index.low_j_bands == 0
    assert compact_off._index.low_j_bands == 0


def test_eviction_race_raises_typed_and_not_counted_as_failure(tmp_path):
    """Eviction racing an in-flight add_blob raises DedupEvictionRace
    (still a KeyError for the 404 paths) and the origin server counts it
    in origin_dedup_eviction_races_total, NOT in the failure meter the
    races were polluting (round-5 ADVICE)."""
    from kraken_tpu.origin.dedup import DedupEvictionRace
    from kraken_tpu.origin.server import OriginServer
    from kraken_tpu.origin.metainfogen import Generator
    from kraken_tpu.utils.metrics import REGISTRY

    store = CAStore(str(tmp_path / "s"))
    blob = np.random.default_rng(9).integers(
        0, 256, 32 * 1024, np.uint8
    ).tobytes()
    d = _store_blob(store, blob)
    index = DedupIndex(store, params=PARAMS)
    # Simulate the race: the blob "evicts" between compute and admit.
    store.in_cache = lambda _d: False
    with pytest.raises(DedupEvictionRace):
        index.add_blob_sync(d)
    assert isinstance(DedupEvictionRace(d.hex), KeyError)

    # Server-side accounting: races and real failures diverge.
    async def main():
        server = OriginServer(
            store=store, generator=Generator(store), dedup=index,
            stream_piece_hash=False,
        )
        races = REGISTRY.counter("origin_dedup_eviction_races_total")
        failures = REGISTRY.counter("origin_dedup_failures_total")
        r0, f0 = races.value(), failures.value()
        server._schedule_dedup(d)  # hits the monkeypatched race
        await asyncio.gather(*server._dedup_tasks)
        assert races.value() == r0 + 1
        assert failures.value() == f0

        async def boom(_d):
            raise RuntimeError("sidecar corrupt")

        index.add_blob = boom  # a REAL fault still lands in the meter
        server._schedule_dedup(d)
        await asyncio.gather(*server._dedup_tasks)
        assert races.value() == r0 + 1
        assert failures.value() == f0 + 1

    asyncio.run(main())

"""Overload & degradation plane: deadlines, circuit breakers, hedged
reads, and lameduck drain (round 8).

The retry-budget interaction tests are the load-bearing ones: a deadline
of N seconds with a per-attempt timeout of T must yield <= ceil(N/T)
attempts ACROSS HTTPClient retries and ClusterClient replica walks -- the
pre-deadline plane multiplied budgets instead (retries x replicas x
per-attempt timeout). The breaker half-open tests pin the single-probe
property: after a cooldown exactly ONE request is exposed to a
previously-failing host.

This module runs under conftest's no-leaked-asyncio-tasks tripwire:
hedging loses a race on every test here, and a losing hedge that is not
reaped is precisely the regression class this plane can introduce.
"""

import asyncio
import json
import math
import os
import time

import pytest
from aiohttp import web

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.core.peer import PeerIDFactory
from kraken_tpu.origin.client import BlobClient, ClusterClient
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.placement.healthcheck import (
    ActiveMonitor,
    PassiveFilter,
    debug_snapshot,
)
from kraken_tpu.tracker.client import TrackerClient
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.backoff import Backoff, DecorrelatedJitter
from kraken_tpu.utils.deadline import Deadline, DeadlineExceeded, RPCConfig
from kraken_tpu.utils.httputil import HTTPClient, HTTPError
from kraken_tpu.utils.metrics import REGISTRY

NS = "degradation"
FAST = Backoff(base_seconds=0.01, factor=1.0, max_seconds=0.01, jitter=0)


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.FAILPOINTS.disarm_all()
    yield
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow(False)


class _FakeOrigin:
    """A minimal origin read surface: GET blob + stat, with a settable
    per-request delay and a hit counter -- the brown-out stand-in."""

    def __init__(self, body: bytes = b"", delay: float = 0.0):
        self.body = body
        self.delay = delay
        self.hits = 0
        self.runner = None
        self.addr = ""

    async def _blob(self, req):
        self.hits += 1
        if self.delay:
            await asyncio.sleep(self.delay)
        return web.Response(body=self.body)

    async def _stat(self, req):
        self.hits += 1
        if self.delay:
            await asyncio.sleep(self.delay)
        return web.json_response({"size": len(self.body)})

    async def start(self):
        app = web.Application()
        app.router.add_get("/namespace/{ns}/blobs/{d}", self._blob)
        app.router.add_get("/namespace/{ns}/blobs/{d}/stat", self._stat)
        # handler_cancellation + tiny shutdown grace: these fakes hold
        # deliberately-slow handlers, and cleanup() must not serve out
        # aiohttp's default 60 s goodbye per test.
        self.runner = web.AppRunner(
            app, handler_cancellation=True, shutdown_timeout=0.1
        )
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.addr = f"127.0.0.1:{self.runner.addresses[0][1]}"

    async def stop(self):
        await self.runner.cleanup()


# -- Deadline type -----------------------------------------------------------


def test_deadline_remaining_expired_and_min_timeout():
    d = Deadline(10.0, component="t", now=100.0)
    assert d.remaining(now=104.0) == pytest.approx(6.0)
    assert d.timeout(2.0) <= 2.0  # per-attempt wins while budget is big
    spent = Deadline(0.5, now=100.0)
    assert spent.remaining(now=101.0) < 0 and spent.expired
    assert spent.timeout(2.0) == 0.0  # never negative


def test_deadline_exceeded_is_typed_and_counted():
    c = REGISTRY.counter("rpc_deadline_exceeded_total")
    before = c.value(component="unit")
    err = Deadline(0.0, component="unit").exceeded("GET /x")
    assert isinstance(err, DeadlineExceeded)
    assert c.value(component="unit") == before + 1


def test_rpc_config_rejects_unknown_keys():
    with pytest.raises(ValueError):
        RPCConfig.from_dict({"hedge_delay": 1.0})  # typo'd knob
    cfg = RPCConfig.from_dict({"hedge_delay_seconds": 0.1})
    assert cfg.hedge_delay_seconds == 0.1
    assert RPCConfig.from_dict(None).drain_timeout_seconds == 30.0


def test_decorrelated_jitter_bounds():
    import random

    j = DecorrelatedJitter(base_seconds=1.0, max_seconds=10.0)
    assert j.next(0) == 1.0  # first trip: exactly the base cooldown
    rng = random.Random(7)
    prev = 1.0
    for _ in range(50):
        prev = j.next(prev, rng)
        assert 1.0 <= prev <= 10.0


# -- retry-budget interaction (satellite: no budget multiplication) ----------


def _hang_server():
    """An aiohttp server whose handler never answers in time."""

    class S:
        def __init__(self):
            self.hits = 0
            self.runner = None
            self.addr = ""

        async def handler(self, req):
            self.hits += 1
            await asyncio.sleep(30)
            return web.Response(text="late")

        async def start(self):
            app = web.Application()
            app.router.add_route("*", "/{tail:.*}", self.handler)
            self.runner = web.AppRunner(
                app, handler_cancellation=True, shutdown_timeout=0.1
            )
            await self.runner.setup()
            site = web.TCPSite(self.runner, "127.0.0.1", 0)
            await site.start()
            self.addr = f"127.0.0.1:{self.runner.addresses[0][1]}"

        async def stop(self):
            await self.runner.cleanup()

    return S()


def test_http_client_deadline_caps_attempts_at_ceil_n_over_t():
    """retries=10 would normally mean 11 attempts; a 0.4 s deadline over
    a 0.15 s per-attempt timeout must stop at <= ceil(0.4/0.15) = 3,
    raise the TYPED error, and return well before the naive 11x wall."""

    async def main():
        srv = _hang_server()
        await srv.start()
        http = HTTPClient(timeout_seconds=0.15, retries=10, backoff=FAST)
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                await http.get(
                    f"http://{srv.addr}/x",
                    deadline=Deadline(0.4, component="unit-http"),
                )
            wall = time.monotonic() - t0
            assert srv.hits <= math.ceil(0.4 / 0.15) == 3
            assert srv.hits >= 2  # it did retry inside the budget
            assert wall < 2.0  # nowhere near 11 x 0.15 + backoffs
        finally:
            await http.close()
            await srv.stop()

    asyncio.run(main())


def test_http_client_without_deadline_keeps_full_retry_budget():
    async def main():
        srv = _hang_server()
        await srv.start()
        http = HTTPClient(timeout_seconds=0.05, retries=3, backoff=FAST)
        try:
            with pytest.raises(asyncio.TimeoutError):
                await http.get(f"http://{srv.addr}/x")
            assert srv.hits == 4  # legacy behavior intact: retries + 1
        finally:
            await http.close()
            await srv.stop()

    asyncio.run(main())


def test_cluster_walk_respects_one_budget_across_replicas():
    """3 replicas x (retries=2 -> 3 attempts) = 9 attempts un-budgeted;
    one 0.4 s deadline with a 0.15 s per-attempt timeout must cap the
    TOTAL across the whole walk at ceil(N/T) = 3."""

    async def main():
        servers = [_hang_server() for _ in range(3)]
        for s in servers:
            await s.start()
        ring = Ring(
            HostList(static=[s.addr for s in servers]), max_replica=3
        )
        cluster = ClusterClient(
            ring,
            client_factory=lambda a: BlobClient(
                a, HTTPClient(timeout_seconds=0.15, retries=2, backoff=FAST)
            ),
            deadline_seconds=0.4,
            component="unit-walk",
        )
        try:
            d = Digest.from_bytes(b"budget")
            with pytest.raises(DeadlineExceeded):
                await cluster.download(NS, d)
            total = sum(s.hits for s in servers)
            assert total <= 3, f"budget multiplied: {total} attempts"
            assert total >= 1
        finally:
            await cluster.close()
            for s in servers:
                await s.stop()

    asyncio.run(main())


# -- circuit breaker ---------------------------------------------------------


def test_breaker_trips_probes_once_and_reopens_with_backoff():
    pf = PassiveFilter(fail_threshold=3, cooldown_seconds=10.0)
    for t in (0, 1, 2):
        pf.failed("h", now=t)
    assert not pf.healthy("h", now=3)  # OPEN
    # Cooldown passes: membership view turns healthy, and exactly ONE
    # caller gets the probe.
    assert pf.healthy("h", now=13)
    assert pf.try_acquire_probe("h", now=13) == "probe"
    assert pf.try_acquire_probe("h", now=13) is False
    # Probe fails: re-open with a LONGER (decorrelated) cooldown.
    pf.failed("h", now=13)
    s = pf._fails["h"]
    assert s.open_until > 13 + 10.0 - 1e-9  # at least the base again
    first_reopen = s.backoff_prev
    assert first_reopen >= 10.0
    # Next probe failure grows it again (decorrelated draw >= base).
    t2 = 13 + first_reopen + 1
    assert pf.try_acquire_probe("h", now=t2) == "probe"
    pf.failed("h", now=t2)
    assert pf._fails["h"].backoff_prev >= 10.0
    # Probe success closes fully.
    t3 = t2 + pf._fails["h"].backoff_prev + 1
    assert pf.try_acquire_probe("h", now=t3) == "probe"
    pf.succeeded("h")
    assert pf.healthy("h", now=t3) and pf.try_acquire_probe("h", now=t3) is True


def test_breaker_half_open_admits_exactly_one_of_many():
    """The single-probe property: however many concurrent callers race
    the half-open transition, exactly one is admitted."""
    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=5.0)
    pf.failed("h", now=0)
    admitted = [bool(pf.try_acquire_probe("h", now=6.0)) for _ in range(50)]
    assert sum(admitted) == 1 and admitted[0]
    # An abandoned probe (cancelled hedge) returns the token.
    pf.release_probe("h")
    assert pf.try_acquire_probe("h", now=6.0) == "probe"


def test_breaker_stale_failure_streaks_decay():
    """Sporadic failures hours apart on a low-traffic host must not
    accumulate into a trip."""
    pf = PassiveFilter(fail_threshold=2, cooldown_seconds=10.0)
    pf.failed("h", now=0)
    pf.failed("h", now=1000)  # way past the cooldown: streak reset
    assert pf.healthy("h", now=1001)


def test_brownout_sheds_to_back_of_order_without_opening():
    pf = PassiveFilter(brownout_threshold_seconds=0.5)
    pf.observe("slow:1", True, seconds=2.0)
    pf.observe("fast:1", True, seconds=0.05)
    # Slow-but-alive: NOT opened (still healthy for membership)...
    assert pf.healthy("slow:1") and pf.browned_out("slow:1")
    # ...but reads walk it last, and the handout shed-set names it.
    assert pf.order(["slow:1", "fast:1"]) == ["fast:1", "slow:1"]
    assert pf.unhealthy_hosts() == {"slow:1"}
    assert REGISTRY.gauge("host_latency_ewma_seconds").value(
        host="slow:1"
    ) == pytest.approx(2.0)
    # EWMA decays as the host recovers; below threshold it rejoins.
    for _ in range(20):
        pf.observe("slow:1", True, seconds=0.05)
    assert not pf.browned_out("slow:1")
    assert pf.order(["slow:1", "fast:1"]) == ["slow:1", "fast:1"]


def test_breaker_order_tiers_open_hosts_last():
    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=100.0)
    pf.failed("dead:1", now=0)
    # Placement order preserved among healthy; open host shoved last but
    # never dropped.
    assert pf.order(["dead:1", "b:1", "a:1"], now=1) == ["b:1", "a:1", "dead:1"]


def test_healthcheck_gauges_and_debug_snapshot():
    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=100.0,
                       name="deg-pf")
    pf.failed("bad:1")
    assert REGISTRY.gauge("healthcheck_unhealthy_hosts").value(
        source="deg-pf"
    ) == 1
    assert REGISTRY.gauge("breaker_state").value(host="bad:1") == 2  # open
    snap = debug_snapshot()
    assert snap["deg-pf"]["hosts"]["bad:1"]["state"] == "open"

    async def active():
        async def probe(h):
            return False

        mon = ActiveMonitor(probe, fail_threshold=1, name="deg-mon")
        await mon.check_all(["x:1"])
        assert REGISTRY.gauge("healthcheck_unhealthy_hosts").value(
            source="deg-mon"
        ) == 1
        assert debug_snapshot()["deg-mon"]["hosts"]["x:1"]["healthy"] is False

    asyncio.run(active())


def test_debug_healthcheck_on_the_metrics_mux():
    """Operators read breaker verdicts off every component's /debug mux."""

    async def main():
        from kraken_tpu.utils.metrics import instrument_app

        pf = PassiveFilter(fail_threshold=1, cooldown_seconds=50.0,
                           name="deg-mux-pf")
        pf.failed("skipme:1")
        app = web.Application()
        instrument_app(app, "deg-mux-test")
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        http = HTTPClient(retries=0)
        try:
            doc = json.loads(await http.get(
                f"http://127.0.0.1:{runner.addresses[0][1]}/debug/healthcheck"
            ))
            assert doc["deg-mux-pf"]["hosts"]["skipme:1"]["state"] == "open"
        finally:
            await http.close()
            await runner.cleanup()

    asyncio.run(main())


# -- hedged reads ------------------------------------------------------------


async def _hedge_pair(slow_delay=1.0, hedge_delay=0.05):
    """Two fake origins; returns (slow, fast, cluster, digest) with the
    SLOW one first in ring order for the digest."""
    slow = _FakeOrigin(body=b"S" * 64, delay=slow_delay)
    fast = _FakeOrigin(body=b"F" * 64)
    await slow.start()
    await fast.start()
    ring = Ring(HostList(static=[slow.addr, fast.addr]), max_replica=2)
    d = None
    for i in range(200):
        cand = Digest.from_bytes(f"hedge-{i}".encode())
        if ring.locations(cand)[0] == slow.addr:
            d = cand
            break
    assert d is not None
    cluster = ClusterClient(
        ring,
        client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
        hedge_delay_seconds=hedge_delay,
        component="unit-hedge",
    )
    return slow, fast, cluster, d


def test_hedge_wins_against_slow_primary_and_loser_is_reaped():
    async def main():
        slow, fast, cluster, d = await _hedge_pair()
        hedges = REGISTRY.counter("rpc_hedges_total")
        wins = REGISTRY.counter("rpc_hedge_wins_total")
        h0 = hedges.value(op="download")
        w0 = wins.value(op="download")
        try:
            t0 = time.monotonic()
            body = await cluster.download(NS, d)
            wall = time.monotonic() - t0
            assert body == b"F" * 64  # the hedge's answer won
            assert wall < 0.8  # nowhere near the 1.0 s brown-out
            assert hedges.value(op="download") == h0 + 1
            assert wins.value(op="download") == w0 + 1
            assert slow.hits == 1 and fast.hits == 1
            # The loser was cancelled (conftest's task tripwire would
            # fail this test if its transfer task leaked).
        finally:
            await cluster.close()
            await slow.stop()
            await fast.stop()

    asyncio.run(main())


def test_hedge_lose_failpoint_primary_wins_cleanly():
    """rpc.hedge.lose delays the hedge: the primary answers first, the
    hedge is counted but records no win, and its task is reaped."""

    async def main():
        slow, fast, cluster, d = await _hedge_pair(slow_delay=0.3)
        failpoints.FAILPOINTS.arm("rpc.hedge.lose", "always+delay:5000")
        wins = REGISTRY.counter("rpc_hedge_wins_total")
        w0 = wins.value(op="download")
        try:
            body = await cluster.download(NS, d)
            assert body == b"S" * 64  # primary's answer
            assert wins.value(op="download") == w0
        finally:
            failpoints.FAILPOINTS.disarm_all()
            await cluster.close()
            await slow.stop()
            await fast.stop()

    asyncio.run(main())


def test_hedge_disabled_keeps_serial_walk():
    async def main():
        slow = _FakeOrigin(body=b"S" * 8, delay=0.2)
        fast = _FakeOrigin(body=b"F" * 8)
        await slow.start()
        await fast.start()
        ring = Ring(HostList(static=[slow.addr, fast.addr]), max_replica=2)
        d = next(
            c for c in (Digest.from_bytes(f"s-{i}".encode()) for i in range(200))
            if ring.locations(c)[0] == slow.addr
        )
        cluster = ClusterClient(
            ring, client_factory=lambda a: BlobClient(a, HTTPClient(retries=0))
        )
        try:
            assert await cluster.download(NS, d) == b"S" * 8
            assert fast.hits == 0  # no hedge ever launched
        finally:
            await cluster.close()
            await slow.stop()
            await fast.stop()

    asyncio.run(main())


def test_hedged_stat_falls_through_on_failure():
    """A dead primary + hedging: the walk still completes (hedge races
    are an optimization, not a correctness fork)."""

    async def main():
        fast = _FakeOrigin(body=b"F" * 32)
        await fast.start()
        dead_addr = "127.0.0.1:1"  # nothing listens
        ring = Ring(HostList(static=[dead_addr, fast.addr]), max_replica=2)
        d = next(
            c for c in (Digest.from_bytes(f"f-{i}".encode()) for i in range(200))
            if ring.locations(c)[0] == dead_addr
        )
        cluster = ClusterClient(
            ring,
            client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
            hedge_delay_seconds=0.05,
            component="unit-hedge-fail",
        )
        try:
            info = await cluster.stat(NS, d)
            assert info is not None and info.size == 32
        finally:
            await cluster.close()
            await fast.stop()

    asyncio.run(main())


def test_breaker_probe_storm_single_probe_through_cluster():
    """Half-open probe storm, end to end through the cluster client: a
    tripped primary whose cooldown just passed sees EXACTLY ONE request
    from a burst of ten concurrent reads -- the other nine skip to the
    healthy replica while the (slow) probe is in flight."""

    async def main():
        flaky = _FakeOrigin(body=b"X" * 16)
        fast = _FakeOrigin(body=b"X" * 16)
        await flaky.start()
        await fast.start()
        ring = Ring(HostList(static=[flaky.addr, fast.addr]), max_replica=2)
        d = next(
            c for c in (Digest.from_bytes(f"p-{i}".encode()) for i in range(200))
            if ring.locations(c)[0] == flaky.addr
        )
        pf = PassiveFilter(fail_threshold=1, cooldown_seconds=0.2)
        cluster = ClusterClient(
            ring,
            client_factory=lambda a: BlobClient(a, HTTPClient(retries=0)),
            health=pf,
            component="unit-probe-storm",
        )
        try:
            pf.failed(flaky.addr)  # breaker OPEN
            await asyncio.sleep(0.25)  # cooldown passes: probe-eligible
            flaky.delay = 0.3  # the probe is slow; the storm lands NOW
            results = await asyncio.gather(
                *(cluster.stat(NS, d) for _ in range(10))
            )
            assert all(r is not None and r.size == 16 for r in results)
            assert flaky.hits == 1, "probe storm leaked past the gate"
            # The slow-but-successful probe closed the breaker.
            assert pf.healthy(flaky.addr)
            assert pf.try_acquire_probe(flaky.addr) is True
        finally:
            await cluster.close()
            await flaky.stop()
            await fast.stop()

    asyncio.run(main())


# -- tracker: announce deadline + handler metering + handout shedding --------


def test_announce_timeout_bounds_a_hung_tracker_socket():
    async def main():
        srv = _hang_server()
        await srv.start()
        peer_id = PeerIDFactory(PeerIDFactory.RANDOM).create("127.0.0.1", 0)
        tc = TrackerClient(
            srv.addr, peer_id, "127.0.0.1", 1234,
            http=HTTPClient(timeout_seconds=0.2, retries=5, backoff=FAST),
            announce_timeout_seconds=0.3,
        )
        meter = REGISTRY.counter("announce_timeouts_total")
        base = meter.value()
        try:
            blob = b"announce"
            d = Digest.from_bytes(blob)
            from kraken_tpu.core.metainfo import MetaInfo
            from kraken_tpu.core.hasher import get_hasher

            mi = MetaInfo(
                d, len(blob), 64,
                get_hasher("cpu").hash_pieces(blob, 64).tobytes(),
            )
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                await tc.announce(d, mi.info_hash, NS, complete=False)
            assert time.monotonic() - t0 < 2.0  # not 5 x 0.2 + backoffs... and
            assert meter.value() == base + 1  # ...it is VISIBLE
        finally:
            await tc.close()
            await srv.stop()

    asyncio.run(main())


def test_tracker_metainfo_failure_is_metered_not_swallowed(tmp_path):
    async def main():
        tracker = TrackerNode()
        await tracker.start()

        class Exploding:
            async def get_metainfo(self, ns, d):
                raise RuntimeError("origin cluster on fire")

        tracker.server.origin_cluster = Exploding()
        meter = REGISTRY.counter("tracker_handler_errors_total")
        base = meter.value()
        http = HTTPClient(retries=0)
        try:
            d = Digest.from_bytes(b"somemeta")
            with pytest.raises(HTTPError) as ei:
                await http.get(
                    f"http://{tracker.addr}/namespace/ns/blobs/{d.hex}/metainfo"
                )
            assert ei.value.status == 404  # caller contract unchanged
            assert meter.value() == base + 1  # but the failure is VISIBLE
        finally:
            await http.close()
            await tracker.stop()

    asyncio.run(main())


def test_tracker_handout_sheds_unhealthy_origin_peers():
    from kraken_tpu.core.peer import PeerInfo
    from kraken_tpu.tracker.server import TrackerServer

    pf = PassiveFilter(fail_threshold=1, cooldown_seconds=100.0)
    pf.failed("10.0.0.9:7610")  # the origin's HTTP addr trips the breaker

    class FakeCluster:
        health = pf

    srv = TrackerServer(origin_cluster=FakeCluster())
    mk = PeerIDFactory(PeerIDFactory.RANDOM)
    sick_origin = PeerInfo(mk.create("10.0.0.9", 1), "10.0.0.9", 7611,
                           origin=True, complete=True)
    ok_origin = PeerInfo(mk.create("10.0.0.8", 1), "10.0.0.8", 7611,
                         origin=True, complete=True)
    agent = PeerInfo(mk.create("10.0.0.9", 2), "10.0.0.9", 7612,
                     origin=False, complete=True)
    out = srv._shed_unhealthy_origins([sick_origin, agent, ok_origin])
    # The sick ORIGIN goes last; the agent sharing its IP is untouched
    # (the breaker knows nothing about agent hosts).
    assert out[-1] is sick_origin
    assert out[:2] == [agent, ok_origin]


# -- lameduck drain ----------------------------------------------------------


def test_origin_lameduck_refuses_new_work_finishes_old(tmp_path):
    async def main():
        import aiohttp

        origin = OriginNode(store_root=str(tmp_path / "o"), dedup=False)
        await origin.start()
        base = f"http://{origin.addr}"
        async with aiohttp.ClientSession() as sess:
            # An upload session opened BEFORE the drain...
            async with sess.post(
                f"{base}/namespace/{NS}/blobs/"
                f"{Digest.from_bytes(b'x').hex}/uploads"
            ) as r:
                assert r.status == 200
                uid = await r.text()

            async with sess.post(f"{base}/debug/lameduck") as r:
                doc = await r.json()
                assert doc["lameduck"] is True
            assert origin.scheduler.lameduck  # p2p plane drains too

            # /health fails -> ring peers and LBs route away.
            async with sess.get(f"{base}/health") as r:
                assert r.status == 503
            # New upload sessions: refused with the retry hint.
            async with sess.post(
                f"{base}/namespace/{NS}/blobs/"
                f"{Digest.from_bytes(b'y').hex}/uploads"
            ) as r:
                assert r.status == 503
                assert r.headers.get("Retry-After")
            # ...but the in-flight session finishes: PATCH + commit land.
            blob = os.urandom(2048)
            d = Digest.from_bytes(blob)
            async with sess.patch(
                f"{base}/namespace/{NS}/blobs/{d.hex}/uploads/{uid}",
                data=blob, headers={"X-Upload-Offset": "0"},
            ) as r:
                assert r.status == 204
            async with sess.put(
                f"{base}/namespace/{NS}/blobs/{d.hex}/uploads/{uid}/commit"
            ) as r:
                assert r.status == 201
            assert origin.store.in_cache(d)
            # Reads still serve while draining (the ring needs a beat to
            # route away; refusing reads would turn a drain into an
            # availability dip).
            async with sess.get(f"{base}/namespace/{NS}/blobs/{d.hex}") as r:
                assert r.status == 200 and await r.read() == blob
        # Drain with nothing in flight quiesces immediately.
        t0 = time.monotonic()
        await origin.drain(timeout=5.0)
        assert time.monotonic() - t0 < 2.0
        await origin.stop()

    asyncio.run(main())


def test_agent_lameduck_serves_cache_refuses_new_pulls(tmp_path):
    async def main():
        import aiohttp

        tracker = TrackerNode(announce_interval_seconds=0.1)
        await tracker.start()
        agent = AgentNode(
            store_root=str(tmp_path / "a"), tracker_addr=tracker.addr
        )
        await agent.start()
        # Seed the agent cache directly: a cache hit during drain.
        blob = os.urandom(1024)
        d = Digest.from_bytes(blob)
        uid = agent.store.create_upload()
        with await asyncio.to_thread(
            open, agent.store.upload_path(uid), "wb"
        ) as f:
            await asyncio.to_thread(f.write, blob)
        agent.store.commit_upload(uid, d)
        base = f"http://{agent.addr}"
        async with aiohttp.ClientSession() as sess:
            async with sess.post(f"{base}/debug/lameduck") as r:
                assert (await r.json())["lameduck"] is True
            async with sess.get(f"{base}/health") as r:
                assert r.status == 503
            async with sess.get(f"{base}/readiness") as r:
                assert r.status == 503
            # Cache hit: still served (one sendfile, finishes now).
            async with sess.get(f"{base}/namespace/{NS}/blobs/{d.hex}") as r:
                assert r.status == 200 and await r.read() == blob
            # Cache miss would need a NEW swarm pull: refused.
            miss = Digest.from_bytes(b"not cached")
            async with sess.get(
                f"{base}/namespace/{NS}/blobs/{miss.hex}"
            ) as r:
                assert r.status == 503
                assert r.headers.get("Retry-After")
        await agent.drain(timeout=5.0)
        await agent.stop()
        await tracker.stop()

    asyncio.run(main())


def test_rpc_reload_applies_live(tmp_path):
    async def main():
        origin = OriginNode(
            store_root=str(tmp_path / "o"), dedup=False,
            rpc={"announce_timeout_seconds": 5.0},
        )
        await origin.start()
        try:
            assert origin._tracker_client.announce_timeout == 5.0
            origin.reload({"rpc": {
                "announce_timeout_seconds": 1.5,
                "hedge_delay_seconds": 0.123,
            }})
            assert origin._tracker_client.announce_timeout == 1.5
            assert origin.server.rpc.hedge_delay_seconds == 0.123
        finally:
            await origin.stop()

    asyncio.run(main())

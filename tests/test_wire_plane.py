"""Zero-copy wire plane tests (round 7): framing edge cases, the payload
buffer pool's lease lifecycle (leak detector), payload-length enforcement
as peer misbehavior, conn close reasons, and the per-piece allocation
regression pin on the recv path.

The allocation pin is the CI tooth behind the zero-copy claim: a future
refactor that quietly reintroduces a payload copy between the socket and
``os.pwrite`` (the round-5 ``raw[header_len:]`` slice cost a full payload
per piece) fails here, not in a quarterly profile. The leak tests close
the other trap: a pooled buffer is only zero-copy if EVERY path -- happy,
corrupt-piece ban, mid-transfer disconnect -- returns its lease.
"""

import asyncio
import os

import msgpack
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.p2p import wire
from kraken_tpu.p2p.conn import Conn, ConnClosedError
from kraken_tpu.p2p.wire import (
    Message,
    MsgType,
    PayloadOversizeError,
    WireError,
    recv_message,
    send_message,
    send_messages,
)
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.bufpool import MIN_CLASS, BufferPool, _class_for
from tests.test_swarm import (
    FakeTracker, NS, make_metainfo, make_peer, start_all, stop_all,
)


def pid(i: int):
    from kraken_tpu.core.peer import PeerID

    return PeerID((bytes([i]) * 20).hex())


class Sink:
    """StreamWriter-shaped byte sink for offline framing."""

    def __init__(self):
        self.buf = bytearray()

    def write(self, b):
        self.buf += b

    def writelines(self, bufs):
        for b in bufs:
            self.buf += b

    async def drain(self):
        pass


async def frame_bytes(*msgs: Message) -> bytes:
    sink = Sink()
    await send_messages(sink, msgs)
    return bytes(sink.buf)


async def feed(raw: bytes, pool=None, max_payload=wire.MAX_PAYLOAD) -> Message:
    reader = asyncio.StreamReader()
    reader.feed_data(raw)
    reader.feed_eof()
    return await recv_message(reader, pool=pool, max_payload=max_payload)


# -- framing edge cases ------------------------------------------------------


def test_roundtrip_all_types_boundary_payloads():
    """Every message type, with payload sizes at the interesting
    boundaries (empty, 1, one-under/at/one-over a pool size class),
    batched through ONE corked send_messages call and recovered intact --
    the vectored path must preserve framing exactly."""

    async def main():
        pool = BufferPool()
        sizes = [0, 1, MIN_CLASS - 1, MIN_CLASS, MIN_CLASS + 1, 100_000]
        msgs = []
        for i, n in enumerate(sizes):
            msgs.append(Message.piece_payload(i, os.urandom(n)))
        msgs += [
            Message.handshake("ab" * 20, "cd" * 32, "ef" * 32, "ns", b"\x01", 8),
            Message.bitfield(b"\x0f", 4),
            Message.piece_request(7),
            Message.announce_piece(7),
            Message.cancel_piece(3),
            Message.complete(),
            Message.error("busy", "try later"),
        ]
        raw = await frame_bytes(*msgs)
        reader = asyncio.StreamReader()
        reader.feed_data(raw)
        reader.feed_eof()
        for sent in msgs:
            got = await recv_message(reader, pool=pool)
            assert got.type == sent.type
            assert got.header == sent.header
            assert bytes(got.payload) == bytes(sent.payload)
            if sent.type == MsgType.PIECE_PAYLOAD and sent.payload:
                assert isinstance(got.payload, memoryview)
            got.release()
        assert pool.leased == 0

    asyncio.run(main())


def test_max_header_exact_and_off_by_one(monkeypatch):
    """A header of exactly MAX_HEADER parses; one byte more is a
    WireError. Hand-built frames so the boundary is byte-exact."""
    monkeypatch.setattr(wire, "MAX_HEADER", 256)

    def frame_with_header_len(target: int) -> bytes:
        # msgpack str-length encoding widens at size breakpoints; search
        # the pad that lands byte-exact on the target.
        pad = target - len(msgpack.packb({"p": ""}))
        while len(msgpack.packb({"p": "x" * pad})) > target:
            pad -= 1
        header = msgpack.packb({"p": "x" * pad})
        assert len(header) == target
        return (
            bytes([MsgType.PIECE_REQUEST])
            + len(header).to_bytes(4, "big")
            + (0).to_bytes(4, "big")
            + header
        )

    async def main():
        got = await feed(frame_with_header_len(256))
        assert got.type == MsgType.PIECE_REQUEST
        with pytest.raises(WireError):
            await feed(frame_with_header_len(257))

    asyncio.run(main())


def test_max_payload_exact_and_off_by_one(monkeypatch):
    monkeypatch.setattr(wire, "MAX_PAYLOAD", 1 << 16)

    async def main():
        ok = await frame_bytes(Message.piece_payload(0, b"x" * (1 << 16)))
        got = await feed(ok)
        assert len(got.payload) == 1 << 16
        over = await frame_bytes(Message.piece_payload(0, b"x" * ((1 << 16) + 1)))
        with pytest.raises(PayloadOversizeError):
            await feed(over)
        # Non-payload types hit the generic oversize error instead.
        raw = (
            bytes([MsgType.BITFIELD])
            + (0).to_bytes(4, "big")
            + ((1 << 16) + 1).to_bytes(4, "big")
        )
        with pytest.raises(WireError):
            await feed(raw)

    asyncio.run(main())


def test_truncation_at_every_boundary():
    """EOF mid-prefix, mid-header, and mid-payload (every prefix offset,
    the header edge, one-into-payload, one-short-of-complete) must all
    surface as WireError -- and a truncated POOLED payload must return
    its lease (the reader died holding a leased buffer)."""

    async def main():
        payload = os.urandom(100)
        raw = await frame_bytes(Message.piece_payload(3, payload))
        header_len = int.from_bytes(raw[1:5], "big")
        cuts = list(range(1, 9))                       # mid-prefix
        cuts += [9 + header_len // 2, 9 + header_len]  # mid/at header
        cuts += [9 + header_len + 1, len(raw) - 1]     # mid-payload
        pool = BufferPool()
        for cut in cuts:
            with pytest.raises(WireError):
                await feed(raw[:cut], pool=pool)
            assert pool.leased == 0, f"lease leaked at cut {cut}"

    asyncio.run(main())


def test_payload_oversize_rejected_before_buffering():
    """The oversize check runs on the PREFIX: no payload byte is read and
    no buffer is leased, so a hostile length cannot balloon RSS."""

    async def main():
        pool = BufferPool()
        # Prefix claims 1 MiB payload against a 64 KiB piece-length bound;
        # deliver only the prefix+header -- the error must fire anyway.
        header = msgpack.packb({"index": 0})
        raw = (
            bytes([MsgType.PIECE_PAYLOAD])
            + len(header).to_bytes(4, "big")
            + (1 << 20).to_bytes(4, "big")
            + header
        )
        reader = asyncio.StreamReader()
        reader.feed_data(raw)  # no EOF: a read past the prefix would hang
        with pytest.raises(PayloadOversizeError):
            await asyncio.wait_for(
                recv_message(reader, pool=pool, max_payload=64 << 10), 2.0
            )
        assert pool.leased == 0 and pool.hits + pool.misses == 0

    asyncio.run(main())


# -- bufpool -----------------------------------------------------------------


def test_bufpool_size_classes_reuse_and_budget():
    pool = BufferPool(budget_bytes=2 * MIN_CLASS)
    assert _class_for(1) == MIN_CLASS
    assert _class_for(MIN_CLASS + 1) == 2 * MIN_CLASS

    a = pool.lease(100)
    assert len(a.view) == 100 and pool.leased == 1 and pool.misses == 1
    a.release()
    assert pool.leased == 0 and pool.retained_bytes == MIN_CLASS
    b = pool.lease(200)  # same class: reused
    assert pool.hits == 1 and pool.allocated == 1
    # Idempotent release: double release must not double-return.
    b.release()
    b.release()
    assert pool.retained_bytes == MIN_CLASS

    # Budget cap: releases beyond it drop to the allocator.
    c, d, e = pool.lease(10), pool.lease(10), pool.lease(10)
    for lease in (c, d, e):
        lease.release()
    assert pool.retained_bytes <= 2 * MIN_CLASS
    # Live shrink applies on the next release cycle.
    pool.set_budget(0)
    pool.lease(10).release()
    f = pool.lease(10)
    f.release()
    assert pool.retained_bytes == 0


def test_bufpool_use_after_release_is_loud():
    pool = BufferPool()
    lease = pool.lease(50)
    view = lease.view
    view[0] = 7
    lease.release()
    with pytest.raises(ValueError):
        view[0]  # released exporter: loud, not recycled-bytes corruption


# -- conn: close reasons, fast paths, misbehavior ----------------------------


async def _conn_pair(**kw):
    """Real loopback socket pair; returns (conn, remote_reader,
    remote_writer, server)."""
    accepted: asyncio.Future = asyncio.get_running_loop().create_future()

    async def on_accept(r, w):
        accepted.set_result((r, w))

    server = await asyncio.start_server(on_accept, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    rr, rw = await accepted
    from kraken_tpu.core.metainfo import InfoHash

    conn = Conn(
        reader, writer, pid(1), InfoHash(("ab" * 32)), **kw
    )
    conn.start()
    return conn, rr, rw, server


def test_conn_construct_without_running_loop():
    """Conn.__init__ must not touch the event loop (the deprecated
    get_event_loop() crashed here under a non-running loop on 3.12+);
    ``closed`` materializes lazily on the running loop."""
    async def make_reader():
        return asyncio.StreamReader()

    # Built on a loop that is CLOSED by the time Conn constructs below --
    # exactly the post-asyncio.run context where get_event_loop() raises.
    r = asyncio.run(make_reader())

    class W:
        def close(self):
            pass

    conn = Conn(r, W(), pid(1), __import__(
        "kraken_tpu.core.metainfo", fromlist=["InfoHash"]
    ).InfoHash("ab" * 32))
    assert conn._closed_fut is None
    conn.close(reason="test")  # no loop: records reason, skips the future
    assert conn.close_reason == "test"


def test_conn_oversize_payload_is_misbehavior():
    """A PIECE_PAYLOAD longer than the handshaken piece length closes the
    conn with reason=oversize_payload and flags misbehavior -- the
    dispatcher escalates that to the blacklist."""

    async def main():
        from kraken_tpu.utils.metrics import REGISTRY

        counter = REGISTRY.counter("conn_closed_total")
        before = counter.value(reason="oversize_payload")
        conn, rr, rw, server = await _conn_pair(
            pool=BufferPool(), max_payload_length=4096
        )
        try:
            await send_message(rw, Message.piece_payload(0, b"x" * 8192))
            await asyncio.wait_for(conn.wait_closed(), 5.0)
            assert conn.close_reason == "oversize_payload"
            assert conn.misbehavior
            assert counter.value(reason="oversize_payload") == before + 1
            with pytest.raises(ConnClosedError):
                await conn.recv()
        finally:
            conn.close()
            rw.close()
            server.close()
            await server.wait_closed()

    asyncio.run(main())


def test_conn_records_remote_close_reason():
    async def main():
        conn, rr, rw, server = await _conn_pair()
        try:
            rw.close()
            await asyncio.wait_for(conn.wait_closed(), 5.0)
            # Remote FIN surfaces as a wire error ("connection closed").
            assert conn.close_reason in ("wire_error", "connection_error")
            assert conn.close_detail
        finally:
            conn.close()
            server.close()
            await server.wait_closed()

    asyncio.run(main())


def test_conn_fast_paths_roundtrip_and_cork():
    """send() fast path (put_nowait) + the corked send loop must deliver
    a burst of mixed control/payload frames intact through one socket,
    and recv() must take its get_nowait fast path for buffered frames."""

    async def main():
        conn, rr, rw, server = await _conn_pair(send_batch=8)
        try:
            payload = os.urandom(20_000)
            msgs = [Message.piece_request(i) for i in range(5)]
            msgs += [Message.piece_payload(9, payload)]
            msgs += [Message.announce_piece(3), Message.complete()]
            for m in msgs:  # all fast-path enqueues, drained as batches
                await conn.send(m)
            got = []
            for _ in msgs:
                got.append(await recv_message(rr))
            assert [m.type for m in got] == [m.type for m in msgs]
            assert bytes(got[5].payload) == payload
            assert conn.bytes_sent == sum(len(m.payload) for m in msgs)

            # Inbound: push two frames, then recv twice -- the second
            # recv hits the buffered fast path.
            await send_message(rw, Message.announce_piece(1))
            await send_message(rw, Message.announce_piece(2))
            a = await conn.recv()
            b = await conn.recv()
            assert {a.header["index"], b.header["index"]} == {1, 2}
        finally:
            conn.close()
            rw.close()
            server.close()
            await server.wait_closed()

    asyncio.run(main())


def test_dispatcher_blacklists_misbehaving_conn(tmp_path):
    """_fail_peer must convert a misbehavior-flagged conn close into a
    reasoned drop (-> escalating blacklist), and a plain close into a
    reasonless one (-> free redial)."""
    from kraken_tpu.p2p.dispatch import Dispatcher, _Peer
    from tests.test_p2p_units import _seeding_torrent

    async def main():
        failures = []
        t = _seeding_torrent(tmp_path, os.urandom(4096))
        d = Dispatcher(t, on_peer_failure=lambda p, r: failures.append((p, r)))

        class FakeConn:
            def __init__(self, peer_id, misbehavior):
                self.peer_id = peer_id
                self.misbehavior = misbehavior
                self.close_reason = "oversize_payload" if misbehavior else None

            def close(self):
                pass

        bad, good = FakeConn(pid(1), True), FakeConn(pid(2), False)
        now = asyncio.get_running_loop().time()
        d._peers[bad.peer_id] = _Peer(bad, set(), now)
        d._peers[good.peer_id] = _Peer(good, set(), now)
        d._fail_peer(bad.peer_id, ConnClosedError("x"))
        d._fail_peer(good.peer_id, ConnClosedError("x"))
        assert [p for p, _ in failures] == [bad.peer_id]
        assert "oversize_payload" in failures[0][1]
        d.close()

    asyncio.run(main())


def test_payload_flood_bound_sheds_and_releases(tmp_path):
    """Unsolicited PIECE_PAYLOAD flood: admission caps concurrent payload
    tasks per peer (_MAX_RECEIVING_PER_PEER) and sheds over-cap frames by
    RELEASING their pooled buffers -- a hostile pusher gets no unbounded
    lease growth, and the hot-path bypass (which never blocks on the recv
    queue) cannot be used to balloon RSS. Mirrors the serve-side flood
    test: frames arrive back-to-back without yielding to the loop."""
    from kraken_tpu.core.hasher import get_hasher
    from kraken_tpu.core.metainfo import MetaInfo
    from kraken_tpu.p2p.dispatch import Dispatcher, _Peer
    from kraken_tpu.p2p.storage import AgentTorrentArchive, BatchedVerifier
    from kraken_tpu.store import CAStore

    async def main():
        blob = os.urandom(256 * 4096)
        hashes = get_hasher("cpu").hash_pieces(blob, 4096)
        mi = MetaInfo(Digest.from_bytes(blob), len(blob), 4096, hashes.tobytes())
        store = CAStore(str(tmp_path / "s"))
        t = AgentTorrentArchive(store, BatchedVerifier()).create_torrent(mi)
        d = Dispatcher(t)
        hold = asyncio.Event()

        async def parked(self, peer, idx, msg):
            await hold.wait()

        d._on_payload = parked.__get__(d)

        class FakeConn:
            peer_id = pid(1)
            misbehavior = False

            def close(self):
                pass

        peer = _Peer(FakeConn(), set(), asyncio.get_running_loop().time())
        d._peers[peer.conn.peer_id] = peer
        pool = BufferPool()
        n = 200
        for i in range(n):
            lease = pool.lease(4096)
            msg = Message(
                MsgType.PIECE_PAYLOAD, {"index": i}, lease.view, lease=lease
            )
            d._handle_payload_direct(peer, msg)
        cap = Dispatcher._MAX_RECEIVING_PER_PEER
        assert peer.receiving == cap
        assert pool.leased == cap  # over-cap frames shed AND released
        hold.set()
        for _ in range(100):
            if pool.leased == 0:
                break
            await asyncio.sleep(0.01)
        assert pool.leased == 0 and peer.receiving == 0
        d.close()

    asyncio.run(main())


# -- leak detector: every lease returns, even on the failure paths -----------


@pytest.fixture
def chaos_plane():
    failpoints.FAILPOINTS.disarm_all()
    yield failpoints.FAILPOINTS
    failpoints.FAILPOINTS.disarm_all()


async def _drain_leases(scheds, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    def leased():
        return sum(s._bufpool.leased for s in scheds)
    while leased() and asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.05)
    return leased()


def test_bufpool_no_leak_happy_path(tmp_path):
    async def main():
        blob = os.urandom(300_000)
        mi = make_metainfo(blob, piece_length=16 * 1024)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            assert lstore.read_cache_file(mi.digest) == blob
            assert await _drain_leases([seeder, leecher]) == 0
            pool = leecher._bufpool
            assert pool.hits + pool.misses >= mi.num_pieces
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


def test_bufpool_no_leak_corrupt_ban_path(tmp_path, chaos_plane):
    """The corrupt-piece -> PieceError -> peer-ban path must return the
    poisoned buffer too (the failpoint mutates the POOLED buffer in
    place), and the pull still completes bit-identical from the healthy
    seeder."""

    async def main():
        blob = os.urandom(400_000)  # 25 pieces
        mi = make_metainfo(blob, piece_length=16 * 1024)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        s1, _ = make_peer(tmp_path, "seed1", tracker, seed_blob=blob)
        s2, _ = make_peer(tmp_path, "seed2", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(s1, s2, leecher)
        try:
            s1.seed(mi, NS)
            s2.seed(mi, NS)
            chaos_plane.arm("p2p.conn.recv.corrupt", "once")
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            assert lstore.read_cache_file(mi.digest) == blob
            # The corrupting peer got hard-blacklisted...
            assert leecher.conn_state.blacklist._entries
            # ...and no lease leaked, including the banned frame's.
            assert await _drain_leases([s1, s2, leecher]) == 0
        finally:
            await stop_all(s1, s2, leecher)

    asyncio.run(main())


def test_bufpool_no_leak_mid_transfer_disconnect(tmp_path, chaos_plane):
    """A conn dropped mid-transfer (frames parked in queues, io tasks in
    flight) must return every lease; the re-dial completes the pull."""

    async def main():
        blob = os.urandom(400_000)
        mi = make_metainfo(blob, piece_length=16 * 1024)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            chaos_plane.arm("p2p.conn.disconnect", "once")
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            assert lstore.read_cache_file(mi.digest) == blob
            assert await _drain_leases([seeder, leecher]) == 0
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


# -- the allocation regression pin (CI tooth for the zero-copy claim) --------


def test_recv_path_allocation_pin():
    """tracemalloc sample (shared with bench_pair.run_alloc_sample):
    bytes charged to p2p/wire.py per received piece, measured while each
    decoded message is still live. The round-5 slice copy charged a FULL
    payload per piece (fraction ~1.0); the pooled path must stay under a
    generous 0.25 -- anything above means a payload-scale allocation
    crept back in between the socket and os.pwrite."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
    from bench_pair import run_alloc_sample

    r = run_alloc_sample(pieces=8, piece_kb=256)
    assert r["payload_fraction"] < 0.25, r
    # Block count stays O(1) per frame (Message + header + view), never
    # O(payload): a generous 20-block band.
    assert r["wire_blocks_per_piece"] < 20, r
    # And the pool actually recycled: one warm buffer served every frame.
    assert r["pool_allocated"] == 1, r


def test_loopback_pull_reuses_buffers():
    """End-to-end allocation accounting on a real loopback pull: the pool
    must serve most pieces from recycled buffers (allocated << pieces)
    and leak nothing -- the in-flight bound is conns x pipeline depth,
    not O(pieces)."""
    import pathlib
    import sys
    import tempfile

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
    from bench_pair import run_pair

    with tempfile.TemporaryDirectory() as root:
        r = asyncio.run(run_pair(8, 64, root))  # 128 pieces
    assert r["bufpool_leaked"] == 0, r
    assert r["bufpool_leases"] >= r["pieces"], r
    # Generous band: steady-state in-flight is <= pipeline depth (16),
    # but a slow verify ramp can briefly overshoot. Half the pieces is
    # the line between "pooled" and "allocating per piece".
    assert r["bufpool_allocated"] <= r["pieces"] / 2, r
    assert r["bufpool_hit_ratio"] > 0.5, r

"""Origin ingest fast path (round 5, VERDICT r4 #2/#6).

The chunked-upload flow now computes the blob digest AND (CPU-hasher
origins) the per-piece hashes while the bytes stream in, so commit is a
rename -- no re-read, no second hash pass. These tests pin the
correctness edges of that optimization:

- stream-time MetaInfo is bit-identical to the windowed generate() pass;
- out-of-order PATCHes invalidate the tracker and commit falls back to
  the verifying re-read (wrong bytes still rejected);
- a final size that lands in a different piece-length tier than the
  stream-time bet falls back to generate();
- durability="fsync" commits survive and cost only the sync.
"""

import asyncio
import hashlib

import pytest
from aiohttp import ClientSession

from kraken_tpu.assembly import OriginNode
from kraken_tpu.core.digest import SHA256, Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.origin.metainfogen import (
    Generator, PieceLengthConfig, TorrentMetaMetadata,
)

PIECE = 64 * 1024


def _node(tmp_path, **kw):
    kw.setdefault("piece_lengths", PieceLengthConfig(table=((0, PIECE),)))
    return OriginNode(store_root=str(tmp_path / "o"), dedup=False, **kw)


async def _upload(addr, d, chunks, offsets=None):
    """Drive the chunked-upload API; offsets override the sequential
    default to simulate out-of-order clients."""
    base = f"http://{addr}/namespace/ns/blobs/{d}"
    async with ClientSession() as http:
        async with http.post(f"{base}/uploads") as r:
            assert r.status == 200
            uid = await r.text()
        pos = 0
        for i, chunk in enumerate(chunks):
            off = pos if offsets is None else offsets[i]
            async with http.patch(
                f"{base}/uploads/{uid}",
                data=chunk,
                headers={"X-Upload-Offset": str(off)},
            ) as r:
                assert r.status == 204
            pos += len(chunk)
        async with http.put(f"{base}/uploads/{uid}/commit") as r:
            body = await r.text()
            return r.status, body


def test_stream_metainfo_matches_generate(tmp_path):
    """The stream-hashed MetaInfo must be byte-identical to what the
    windowed generate() pass would produce -- agents hash-verify every
    piece against it, so any drift bricks downloads."""

    async def main():
        import os

        blob = os.urandom(5 * PIECE + 1234)  # non-multiple: short last piece
        d = Digest.from_bytes(blob)
        node = _node(tmp_path)
        await node.start()
        try:
            status, _ = await _upload(
                node.addr, d, [blob[: 2 * PIECE], blob[2 * PIECE :]]
            )
            assert status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            # Independent oracle: hash pieces directly.
            want = get_hasher("cpu").hash_pieces(blob, PIECE).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), PIECE, want
            ).serialize()
            # And the generate() path agrees after wiping the sidecar.
            node.store.delete_metadata(d, TorrentMetaMetadata)
            regen = node.generator.generate_sync(d)
            assert regen.serialize() == stored.serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_stream_metainfo_matches_generate_pooled(tmp_path):
    """hash_workers=2: stream-time pieces are hashed on pool workers in
    piece order while the blob digest streams serially -- the MetaInfo
    must still be byte-identical to the serial oracle, including across
    chunk boundaries that straddle pieces and a short trailing piece."""

    async def main():
        import os

        blob = os.urandom(9 * PIECE + 1234)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path, hash_workers=2)
        await node.start()
        try:
            # Deliberately piece-misaligned chunk boundaries.
            cuts = [0, PIECE // 3, 4 * PIECE + 17, 7 * PIECE - 1, len(blob)]
            chunks = [blob[a:b] for a, b in zip(cuts, cuts[1:])]
            status, _ = await _upload(node.addr, d, chunks)
            assert status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            want = get_hasher("cpu").hash_pieces(blob, PIECE).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), PIECE, want
            ).serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_patch_failure_invalidates_tracker(tmp_path):
    """An exception escaping the spool-file close (deferred write error,
    e.g. ENOSPC at flush) must invalidate the upload digest tracker: a
    client that carries on as if the PATCH landed must get the verifying
    re-read at commit, never the fast path over a possible hole
    (round-5 ADVICE, medium)."""

    async def main():
        import os

        from kraken_tpu.core.digest import Digest as D

        blob = os.urandom(2 * PIECE)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path)
        await node.start()

        class FailingClose:
            def __init__(self, f):
                self._f = f

            def __getattr__(self, a):
                return getattr(self._f, a)

            def close(self):
                self._f.close()
                raise OSError("deferred write error at close")

        orig_open = node.store.open_upload_file
        patches = {"n": 0}

        def open_patched(uid):
            patches["n"] += 1
            f = orig_open(uid)
            return FailingClose(f) if patches["n"] == 1 else f

        node.store.open_upload_file = open_patched
        reads = {"n": 0}
        orig_reader = D.from_reader.__func__

        def counting_reader(cls, f):
            reads["n"] += 1
            return orig_reader(cls, f)

        D.from_reader = classmethod(counting_reader)
        try:

            base = f"http://{node.addr}/namespace/ns/blobs/{d}"
            async with ClientSession() as http:
                async with http.post(f"{base}/uploads") as r:
                    uid = await r.text()
                # First PATCH: bytes land, close raises -> 500.
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[:PIECE],
                    headers={"X-Upload-Offset": "0"},
                ) as r:
                    assert r.status == 500
                # Client believes it landed and streams on sequentially.
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[PIECE:],
                    headers={"X-Upload-Offset": str(PIECE)},
                ) as r:
                    assert r.status == 204
                async with http.put(f"{base}/uploads/{uid}/commit") as r:
                    assert r.status == 201, await r.text()
            # Commit must have taken the verifying re-read, not the
            # invalidated tracker's fast path.
            assert reads["n"] >= 1
            assert node.store.read_cache_file(d) == blob
        finally:
            D.from_reader = classmethod(orig_reader)
            await node.stop()

    asyncio.run(main())


def test_invalidated_pooled_tracker_drops_chunk_pins():
    """A pooled tracker buffers memoryview slices of request-body chunks
    until their piece completes; invalidation (PATCH failure, offset
    mismatch) must drop those pins -- an invalidated tracker can sit in
    the map for the 6h TTL, and each view keeps its whole parent chunk
    alive."""
    import io

    from kraken_tpu.core.hasher import HashPool
    from kraken_tpu.origin.server import _UploadDigest

    pool = HashPool(1, name="cpu/test-pins")
    t = _UploadDigest(piece_length=4096, pool=pool)
    t.begin_patch(0)
    t.write_and_update(io.BytesIO(), b"x" * 1000)  # partial piece buffered
    assert t._parts
    t.end_patch()
    t.invalidate()
    assert not t._parts and not t._futs
    # And the offset-mismatch path drops them too.
    t2 = _UploadDigest(piece_length=4096, pool=pool)
    t2.begin_patch(0)
    t2.write_and_update(io.BytesIO(), b"y" * 1000)
    t2.end_patch()
    assert not t2.begin_patch(999)  # wrong offset -> invalidate
    assert not t2._parts


def test_out_of_order_patches_fall_back_and_verify(tmp_path):
    """Reverse-order PATCHes break the running digest; commit must fall
    back to the verifying re-read and still land correctly -- and a
    WRONG body must still be rejected 400 on that path."""

    async def main():
        import os

        blob = os.urandom(3 * PIECE)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path)
        await node.start()
        try:
            # Chunks sent out of order (second half first).
            status, _ = await _upload(
                node.addr, d,
                [blob[2 * PIECE :], blob[: 2 * PIECE]],
                offsets=[2 * PIECE, 0],
            )
            assert status == 201
            assert node.store.read_cache_file(d) == blob

            # Wrong bytes, claimed digest: rejected on the re-read path.
            other = os.urandom(PIECE)
            wrong_d = Digest.from_bytes(os.urandom(32))
            status, body = await _upload(
                node.addr, wrong_d, [other[PIECE // 2 :], other[: PIECE // 2]],
                offsets=[PIECE // 2, 0],
            )
            assert status == 400, body
        finally:
            await node.stop()

    asyncio.run(main())


def test_wrong_digest_rejected_on_stream_path(tmp_path):
    """Sequential upload (stream digest valid) with a lying digest in the
    URL: the precomputed hash must cause the 400, without a re-read."""

    async def main():
        import os

        blob = os.urandom(2 * PIECE)
        lying = Digest.from_bytes(b"not the blob")
        node = _node(tmp_path)
        await node.start()
        # Any re-read would explode: prove the rejection used the
        # streamed digest.
        orig = Digest.from_reader
        Digest.from_reader = classmethod(
            lambda cls, f: (_ for _ in ()).throw(AssertionError("re-read!"))
        )
        try:
            status, body = await _upload(node.addr, lying, [blob])
            assert status == 400, body
        finally:
            Digest.from_reader = orig
            await node.stop()

    asyncio.run(main())


def test_piece_length_tier_mismatch_falls_back(tmp_path):
    """A blob whose final size maps to a BIGGER piece-length tier than
    the stream-time bet: commit must discard the streamed piece hashes
    and run the windowed generate() pass at the right piece length."""

    async def main():
        import os

        table = PieceLengthConfig(table=((0, PIECE), (4 * PIECE, 2 * PIECE)))
        blob = os.urandom(6 * PIECE)  # lands in the 2*PIECE tier
        d = Digest.from_bytes(blob)
        node = _node(tmp_path, piece_lengths=table)
        await node.start()
        try:
            status, _ = await _upload(node.addr, d, [blob])
            assert status == 201
            mi = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            assert mi.piece_length == 2 * PIECE
            want = get_hasher("cpu").hash_pieces(blob, 2 * PIECE).tobytes()
            assert mi.serialize() == type(mi)(
                d, len(blob), 2 * PIECE, want
            ).serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_fsync_durability_mode(tmp_path):
    """durability='fsync' commits blobs + sidecars with fsync on; the
    full upload->metainfo flow works and an invalid mode is rejected."""

    async def main():
        import os

        blob = os.urandom(2 * PIECE + 7)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path, durability="fsync")
        await node.start()
        try:
            status, _ = await _upload(node.addr, d, [blob])
            assert status == 201
            assert node.store.read_cache_file(d) == blob
            assert node.store.get_metadata(d, TorrentMetaMetadata) is not None
        finally:
            await node.stop()

    asyncio.run(main())
    with pytest.raises(ValueError):
        from kraken_tpu.store import CAStore

        CAStore(str(tmp_path / "bad"), durability="paranoid")


def test_agent_pull_with_fsync_durability(tmp_path):
    """durability='fsync' on the AGENT: the whole-blob fsync at torrent
    completion runs off the event loop and the pull completes normally
    (the swarm path, not just the origin upload path)."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_swarm import FakeTracker, make_metainfo, make_peer, NS

    from kraken_tpu.p2p.scheduler import Scheduler

    async def main():
        import os

        from kraken_tpu.core.peer import PeerID
        from kraken_tpu.p2p.storage import (
            AgentTorrentArchive, BatchedVerifier,
        )
        from kraken_tpu.store import CAStore

        blob = os.urandom(300_000)
        mi = make_metainfo(blob, piece_length=16384)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)

        store = CAStore(str(tmp_path / "leech"), durability="fsync")
        ref: dict = {}
        client = tracker.client_for(ref)
        from kraken_tpu.p2p.scheduler import SchedulerConfig

        leecher = Scheduler(
            peer_id=PeerID(os.urandom(20).hex()),
            ip="127.0.0.1", port=0,
            archive=AgentTorrentArchive(store, BatchedVerifier()),
            metainfo_client=client, announce_client=client,
            config=SchedulerConfig(
                announce_interval_seconds=0.1,
                retry_tick_seconds=0.2,
            ),
        )
        ref["s"] = leecher
        await seeder.start()
        await leecher.start()
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(leecher.download(NS, mi.digest), 15)
            assert store.read_cache_file(mi.digest) == blob
        finally:
            await seeder.stop()
            await leecher.stop()

    asyncio.run(main())


# -- pipelined ingest plane (core/ingest.py) -------------------------------


def _pipe_node(tmp_path, **kw):
    """Origin with the pipelined ingest plane on, windows kept small so a
    few hundred KiB of blob spans several windows."""
    kw.setdefault("ingest", {"window_bytes": 1 << 20, "windows_in_flight": 2})
    return _node(tmp_path, **kw)


def test_ingest_config_validation():
    """IngestConfig is the SIGHUP surface: unknown keys and out-of-range
    knobs must fail loudly at parse time, never half-apply."""
    from kraken_tpu.core.ingest import IngestConfig

    cfg = IngestConfig.from_dict(None)
    assert cfg.pack_mode == "host" and cfg.windows_in_flight == 2
    with pytest.raises(ValueError):
        IngestConfig.from_dict({"widow_bytes": 1 << 20})  # typo'd key
    with pytest.raises(ValueError):
        IngestConfig(windows_in_flight=0)
    with pytest.raises(ValueError):
        IngestConfig(pack_mode="avx")
    with pytest.raises(ValueError):
        IngestConfig(window_bytes=4096)


def test_ingest_session_bit_identity():
    """The pipeline reorders WHEN pieces hash, never piece boundaries:
    digests must match the serial oracle for empty, single-window,
    multi-window, and ragged-tail blobs (the full edge square)."""
    import numpy as np

    from kraken_tpu.core.ingest import IngestConfig, IngestPipeline

    pipe = IngestPipeline(
        get_hasher("cpu"),
        IngestConfig(window_bytes=1 << 20, windows_in_flight=2),
    )
    plen = 4096
    rng = __import__("numpy").random.default_rng(7)
    for total in (0, plen, 3 * plen + 1, (1 << 20) * 2 + 5 * plen + 99):
        blob = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
        ses = pipe.session(plen)
        off = 0
        while off < len(blob):
            buf = ses.begin_window()
            n = min(len(buf), len(blob) - off)
            buf[:n] = blob[off : off + n]
            off += n
            ses.submit(n)
        got = ses.finish()
        want = get_hasher("cpu").hash_pieces(blob, plen)
        assert np.array_equal(got, want), f"total={total}"
        if total:
            assert ses.windows >= 1 and ses.wall_seconds > 0


def test_pipelined_stream_matches_generate(tmp_path):
    """Uploads through a pipeline-enabled origin (cpu hasher): the
    stream-time window pass must produce a MetaInfo bit-identical to the
    serial oracle, across piece-misaligned chunk boundaries, multiple
    windows, and a short trailing piece -- and the stage metrics must
    move (the observability contract of the plane)."""

    async def main():
        import os

        from kraken_tpu.utils.metrics import REGISTRY

        blob = os.urandom((1 << 20) * 2 + 5 * PIECE + 1234)
        d = Digest.from_bytes(blob)
        node = _pipe_node(tmp_path)
        assert node.ingest_pipeline is not None
        assert node.generator.pipeline is node.ingest_pipeline
        windows_before = REGISTRY.counter(
            "ingest_windows_total", "x"
        ).value(hasher="cpu")
        await node.start()
        try:
            cuts = [0, PIECE // 3, (1 << 20) + 17, 2 * (1 << 20) - 1, len(blob)]
            chunks = [blob[a:b] for a, b in zip(cuts, cuts[1:])]
            status, _ = await _upload(node.addr, d, chunks)
            assert status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            want = get_hasher("cpu").hash_pieces(blob, PIECE).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), PIECE, want
            ).serialize()
            assert (
                REGISTRY.counter("ingest_windows_total", "x").value(
                    hasher="cpu"
                )
                > windows_before
            )
            assert "ingest_stage_seconds" in REGISTRY.render()
            # The re-generate path rides the pipeline too.
            node.store.delete_metadata(d, TorrentMetaMetadata)
            regen = node.generator.generate_sync(d)
            assert regen.serialize() == stored.serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_pipelined_out_of_order_falls_back(tmp_path):
    """Out-of-order PATCHes on a pipeline origin: the tracker
    invalidates, the session aborts (leases back to the pool), and
    commit falls back to the verifying re-read -- which regenerates the
    same MetaInfo through the pipelined generate path."""

    async def main():
        import os

        blob = os.urandom((1 << 20) + 3 * PIECE + 7)
        d = Digest.from_bytes(blob)
        node = _pipe_node(tmp_path)
        await node.start()
        try:
            half = len(blob) // 2
            status, _ = await _upload(
                node.addr, d,
                [blob[half:], blob[:half]],
                offsets=[half, 0],  # second PATCH rewinds: invalidates
            )
            assert status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            want = get_hasher("cpu").hash_pieces(blob, PIECE).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), PIECE, want
            ).serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_pipelined_tier_mismatch_falls_back(tmp_path):
    """Pipeline origin whose final size lands in a bigger piece-length
    tier than the stream-time bet: the streamed digests are at the wrong
    piece length, the session must be dropped, and the re-generate pass
    (pipelined, right tier) supplies the MetaInfo."""

    async def main():
        import os

        table = PieceLengthConfig(table=((0, PIECE), (4 * PIECE, 2 * PIECE)))
        blob = os.urandom(6 * PIECE)
        d = Digest.from_bytes(blob)
        node = _pipe_node(tmp_path, piece_lengths=table)
        await node.start()
        try:
            status, _ = await _upload(node.addr, d, [blob])
            assert status == 201
            mi = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            assert mi.piece_length == 2 * PIECE
            want = get_hasher("cpu").hash_pieces(blob, 2 * PIECE).tobytes()
            assert mi.serialize() == type(mi)(
                d, len(blob), 2 * PIECE, want
            ).serialize()
        finally:
            await node.stop()

    asyncio.run(main())


def test_pipelined_sharded_hasher_stream(tmp_path):
    """hasher=tpu-sharded + pipeline: stream-time piece hashing rides the
    sharded device plane (the virtual 8-device CPU mesh here) window by
    window; the MetaInfo must be bit-identical to the cpu oracle and the
    sharded hasher's gauges must move."""

    async def main():
        import os

        from kraken_tpu.utils.metrics import REGISTRY

        plen = 4096  # small pieces: short hash chains on the interpret mesh
        table = PieceLengthConfig(table=((0, plen),))
        blob = os.urandom((1 << 20) * 2 + 37 * plen + 123)
        d = Digest.from_bytes(blob)
        node = _pipe_node(tmp_path, hasher="tpu-sharded", piece_lengths=table)
        sharded_before = REGISTRY.counter(
            "hasher_bytes_total", "x"
        ).value(hasher="tpu-sharded")
        await node.start()
        try:
            status, _ = await _upload(node.addr, d, [blob])
            assert status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            want = get_hasher("cpu").hash_pieces(blob, plen).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), plen, want
            ).serialize()
            # The device plane did the stream-time piece pass.
            assert (
                REGISTRY.counter("hasher_bytes_total", "x").value(
                    hasher="tpu-sharded"
                )
                > sharded_before
            )
        finally:
            await node.stop()

    asyncio.run(main())


def test_ingest_reload_applies_and_live_enables(tmp_path):
    """SIGHUP semantics: knob changes live-apply to an existing
    pipeline, and an origin started WITHOUT `ingest:` grows the plane on
    reload (rollout step 1 of the OPERATIONS.md runbook)."""
    node = _pipe_node(tmp_path)
    assert node.ingest_pipeline.config.window_bytes == 1 << 20
    node.reload({"ingest": {"window_bytes": 2 << 20, "windows_in_flight": 3}})
    assert node.ingest_pipeline.config.window_bytes == 2 << 20
    assert node.ingest_pipeline.config.windows_in_flight == 3

    bare = _node(tmp_path / "bare")
    assert bare.ingest_pipeline is None
    bare.reload({"ingest": {"window_bytes": 4 << 20}})
    assert bare.ingest_pipeline is not None
    assert bare.generator.pipeline is bare.ingest_pipeline
    assert bare.ingest_pipeline.config.window_bytes == 4 << 20


# -- crash-safe resumable sessions (PR 17) ---------------------------------


def test_resume_adopts_journal_and_hashes_bit_identical(tmp_path):
    """Tentpole: a PATCH stream interrupted mid-upload resumes from the
    journaled durable offset and hashes BIT-IDENTICAL to an
    uninterrupted stream. The in-memory tracker is dropped between
    chunks (what an origin restart does to every tracker); HEAD must
    re-adopt from the journal+spool and the resumed tail must land on
    the stream fast path -- the committed MetaInfo equals the oracle."""

    async def main():
        import os

        blob = os.urandom(7 * PIECE + 321)
        d = Digest.from_bytes(blob)
        node = _pipe_node(tmp_path)
        await node.start()
        try:
            cut = 3 * PIECE + 100
            base = f"http://{node.addr}/namespace/ns/blobs/{d}"
            async with ClientSession() as http:
                async with http.post(f"{base}/uploads") as r:
                    uid = await r.text()
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[:cut],
                    headers={"X-Upload-Offset": "0"},
                ) as r:
                    assert r.status == 204
                # The journal landed with the flush.
                doc = node.store.read_upload_session(uid)
                assert doc is not None and doc["offset"] == cut
                assert doc["digest"] == d.hex
                # Simulate restart: the tracker (and its pipeline
                # session) evaporates; only spool+journal survive.
                node.server._upload_digests.pop(uid).invalidate()
                async with http.request(
                    "HEAD", f"{base}/uploads/{uid}"
                ) as r:
                    assert r.status == 200
                    assert int(r.headers["X-Upload-Offset"]) == cut
                # Adopted: the tracker is live again and mid-stream.
                assert uid in node.server._upload_digests
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[cut:],
                    headers={"X-Upload-Offset": str(cut)},
                ) as r:
                    assert r.status == 204
                async with http.put(f"{base}/uploads/{uid}/commit") as r:
                    assert r.status == 201
            stored = node.store.get_metadata(d, TorrentMetaMetadata).metainfo
            want = get_hasher("cpu").hash_pieces(blob, PIECE).tobytes()
            assert stored.serialize() == type(stored)(
                d, len(blob), PIECE, want
            ).serialize()
            assert node.store.read_cache_file(d) == blob
            # Commit cleaned the journal up with the spool.
            assert node.store.read_upload_session(uid) is None
        finally:
            await node.stop()

    asyncio.run(main())


def test_resume_patch_past_durable_size_409s(tmp_path):
    """A blind PATCH retry past the journaled durable size would seek
    past EOF and bury a hole under the client's bytes -- the origin must
    409 it (the resume protocol's signal to HEAD for the real offset),
    while rewrites at/below the durable size stay allowed."""

    async def main():
        import os

        blob = os.urandom(4 * PIECE)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path)
        await node.start()
        try:
            base = f"http://{node.addr}/namespace/ns/blobs/{d}"
            async with ClientSession() as http:
                async with http.post(f"{base}/uploads") as r:
                    uid = await r.text()
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[:PIECE],
                    headers={"X-Upload-Offset": "0"},
                ) as r:
                    assert r.status == 204
                # Past-EOF offset (the crash-retry hole): refused.
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[2 * PIECE :],
                    headers={"X-Upload-Offset": str(2 * PIECE)},
                ) as r:
                    assert r.status == 409
                # Recover exactly as a resuming client would.
                async with http.request(
                    "HEAD", f"{base}/uploads/{uid}"
                ) as r:
                    off = int(r.headers["X-Upload-Offset"])
                assert off == PIECE
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[off:],
                    headers={"X-Upload-Offset": str(off)},
                ) as r:
                    assert r.status == 204
                async with http.put(f"{base}/uploads/{uid}/commit") as r:
                    assert r.status == 201
            assert node.store.read_cache_file(d) == blob
        finally:
            await node.stop()

    asyncio.run(main())


def test_unadoptable_session_404s_and_client_restarts(tmp_path):
    """A session whose spool contradicts its journal (here: forced via
    the origin.upload.resume failpoint) must 404 the HEAD -- the
    client's cue to restart the upload from scratch -- and the suspect
    spool+journal must be gone."""

    async def main():
        import os

        from kraken_tpu.utils import failpoints

        blob = os.urandom(3 * PIECE)
        d = Digest.from_bytes(blob)
        node = _node(tmp_path)
        await node.start()
        try:
            base = f"http://{node.addr}/namespace/ns/blobs/{d}"
            async with ClientSession() as http:
                async with http.post(f"{base}/uploads") as r:
                    uid = await r.text()
                async with http.patch(
                    f"{base}/uploads/{uid}", data=blob[:PIECE],
                    headers={"X-Upload-Offset": "0"},
                ) as r:
                    assert r.status == 204
                node.server._upload_digests.pop(uid).invalidate()
                failpoints.allow()
                failpoints.FAILPOINTS.arm("origin.upload.resume", "once")
                try:
                    async with http.request(
                        "HEAD", f"{base}/uploads/{uid}"
                    ) as r:
                        assert r.status == 404
                finally:
                    failpoints.FAILPOINTS.disarm_all()
                    failpoints.allow(False)
                # The whole session is discarded: spool AND journal.
                assert node.store.read_upload_session(uid) is None
                import os as _os

                assert not _os.path.exists(node.store.upload_path(uid))
        finally:
            await node.stop()

    asyncio.run(main())


def test_pipeline_abort_returns_every_lease(tmp_path):
    """abort() mid-stream must provably return every BufferPool lease --
    a leaked staging lease caps all future ingest concurrency."""
    from kraken_tpu.core.ingest import IngestConfig, IngestPipeline

    pipe = IngestPipeline(
        get_hasher("cpu"),
        IngestConfig(window_bytes=1 << 20, windows_in_flight=2),
    )
    ses = pipe.session(4096)
    buf = ses.begin_window()
    buf[: 4096] = b"x" * 4096
    ses.submit(4096)
    ses.begin_window()  # second window leased, never submitted
    ses.abort()
    assert pipe._bufpool.leased == 0


def test_upload_digest_ttl_purge_and_capacity_eviction(tmp_path):
    """Satellite (b): idle trackers purge on the TTL tick (not only past
    a size watermark) and the hard cap evicts the OLDEST idle tracker,
    metered -- never a silent drop."""

    async def main():
        from kraken_tpu.utils.metrics import REGISTRY

        node = _node(tmp_path)
        await node.start()
        try:
            server = node.server
            base = f"http://{node.addr}/namespace/ns/blobs"
            d = Digest.from_bytes(b"ttl-purge")
            async with ClientSession() as http:
                async with http.post(f"{base}/{d}/uploads") as r:
                    uid = await r.text()
            assert uid in server._upload_digests
            # Age the tracker past the TTL and tick the purge.
            server._upload_digests[uid].created -= (
                server.UPLOAD_DIGEST_TTL_SECONDS + 1
            )
            before = REGISTRY.counter(
                "upload_digests_evicted_total"
            ).value(reason="ttl")
            server.purge_upload_digests()
            assert uid not in server._upload_digests
            after = REGISTRY.counter(
                "upload_digests_evicted_total"
            ).value(reason="ttl")
            assert after == before + 1

            # Capacity: with the cap forced to 1, a second start evicts
            # the first (oldest) tracker with reason=capacity.
            server.UPLOAD_DIGEST_CAP = 1
            async with ClientSession() as http:
                async with http.post(f"{base}/{d}/uploads") as r:
                    uid1 = await r.text()
                cap_before = REGISTRY.counter(
                    "upload_digests_evicted_total"
                ).value(reason="capacity")
                async with http.post(f"{base}/{d}/uploads") as r:
                    uid2 = await r.text()
            assert uid1 not in server._upload_digests
            assert uid2 in server._upload_digests
            cap_after = REGISTRY.counter(
                "upload_digests_evicted_total"
            ).value(reason="capacity")
            assert cap_after == cap_before + 1
        finally:
            await node.stop()

    asyncio.run(main())

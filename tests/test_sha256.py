"""Golden tests for the JAX SHA-256 plane vs hashlib (exact equality --
crypto hashes admit no tolerance). SURVEY.md SS4 tier 5."""

import hashlib
import os

import numpy as np
import pytest

from kraken_tpu.core.hasher import get_hasher


def ref_pieces(data: bytes, piece_length: int) -> np.ndarray:
    return get_hasher("cpu").hash_pieces(data, piece_length)


@pytest.fixture(scope="module")
def tpu_hasher():
    return get_hasher("tpu")


# -- hash_batch: single messages of every tricky length ---------------------

@pytest.mark.parametrize(
    "length",
    [0, 1, 3, 55, 56, 57, 63, 64, 65, 119, 120, 127, 128, 1000, 4096, 65537],
)
def test_single_message_lengths(tpu_hasher, length):
    data = os.urandom(length)
    got = tpu_hasher.hash_batch([data])
    assert got.shape == (1, 32)
    assert bytes(got[0]) == hashlib.sha256(data).digest()


def test_known_vectors(tpu_hasher):
    # FIPS 180-2 test vectors.
    cases = {
        b"abc": "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad",
        b"": "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",
        b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq":
            "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1",
    }
    got = tpu_hasher.hash_batch(list(cases))
    for row, expect in zip(got, cases.values()):
        assert bytes(row).hex() == expect


def test_ragged_batch(tpu_hasher):
    rng = np.random.default_rng(0)
    pieces = [rng.integers(0, 256, size=int(n), dtype=np.uint8).tobytes()
              for n in rng.integers(0, 3000, size=40)]
    got = tpu_hasher.hash_batch(pieces)
    for row, p in zip(got, pieces):
        assert bytes(row) == hashlib.sha256(p).digest()


def test_empty_batch(tpu_hasher):
    assert tpu_hasher.hash_batch([]).shape == (0, 32)


# -- hash_pieces: blob splitting, uniform fast path, ragged tail ------------

@pytest.mark.parametrize(
    "blob_len,piece_len",
    [
        (0, 64),            # empty blob -> zero pieces
        (64, 64),           # exactly one piece
        (640, 64),          # uniform, multiple of 64 (fast path)
        (650, 64),          # fast path + short tail
        (1 << 20, 1 << 16), # 1 MiB blob, 64 KiB pieces
        ((1 << 20) + 12345, 1 << 16),
        (1000, 100),        # piece length not a multiple of 64 (ragged path)
        (37, 100),          # single short piece
    ],
)
def test_hash_pieces_matches_cpu(tpu_hasher, blob_len, piece_len):
    data = os.urandom(blob_len)
    got = tpu_hasher.hash_pieces(data, piece_len)
    want = ref_pieces(data, piece_len)
    assert got.shape == want.shape
    assert np.array_equal(got, want)


def test_hash_pieces_streams_sub_batches():
    # Force multiple device dispatches with a tiny sub-batch budget.
    from kraken_tpu.ops.sha256 import JaxPieceHasher

    h = JaxPieceHasher(sub_batch_bytes=256)
    data = os.urandom(64 * 40 + 17)
    got = h.hash_pieces(data, 64)
    assert np.array_equal(got, ref_pieces(data, 64))
    got2 = h.hash_batch([data[i * 100 : (i + 1) * 100] for i in range(20)])
    for row, i in zip(got2, range(20)):
        assert bytes(row) == hashlib.sha256(data[i * 100 : (i + 1) * 100]).digest()


def test_matches_cpu_hasher_interface():
    cpu = get_hasher("cpu")
    tpu = get_hasher("tpu")
    data = os.urandom(300000)
    assert np.array_equal(
        cpu.hash_pieces(data, 1 << 16), tpu.hash_pieces(data, 1 << 16)
    )


def test_hash_batch_mixed_sizes_bounded_memory():
    """One large piece among many tiny ones must not blow up the padded
    allocation (regression: group sizing must respect sub_batch_bytes)."""
    from kraken_tpu.ops.sha256 import JaxPieceHasher

    h = JaxPieceHasher(sub_batch_bytes=1 << 20)
    pieces = [os.urandom(40) for _ in range(300)] + [os.urandom(700_000)]
    got = h.hash_batch(pieces)
    for row, p in zip(got, pieces):
        assert bytes(row) == hashlib.sha256(p).digest()


@pytest.mark.skipif(
    not os.environ.get("RUN_PALLAS_INTERPRET"),
    reason="interpret-mode kernel execution takes minutes on CPU; the "
    "kernel is golden-tested on real TPU (set RUN_PALLAS_INTERPRET=1)",
)
def test_pallas_kernel_interpret_mode():
    """The Pallas kernel (interpret mode on CPU) matches hashlib, including
    block-group padding (chains not a multiple of the kernel's _KB)."""
    import jax.numpy as jnp

    from kraken_tpu.ops.sha256_pallas import hash_pieces_device

    for pl_len, n in ((64, 3), (576, 5), (1024, 2)):
        data = np.frombuffer(os.urandom(n * pl_len), dtype=np.uint8).reshape(n, pl_len)
        out = hash_pieces_device(jnp.asarray(data), pl_len)
        from kraken_tpu.ops.sha256 import _digest_bytes

        got = _digest_bytes(out)
        for i in range(n):
            assert bytes(got[i]) == hashlib.sha256(data[i].tobytes()).digest()

"""End-to-end distributed tracing + flight recorder (utils/trace.py).

What must hold, per docs/OPERATIONS.md "Tracing":

- a W3C-traceparent-style context propagates across await boundaries,
  asyncio tasks, HTTP hops (header), the P2P wire (handshake +
  PIECE_REQUEST frames), and the shardpool fork (handoff descriptor +
  span shipping) -- ONE trace_id per pull, joinable offline;
- head sampling at the root is inherited by children, and the
  error/slow tails are kept even when the head sampler said no;
- every degradation plane (breaker trip, DeadlineExceeded, resource
  breach, lameduck) leaves a flight-recorder JSONL postmortem, throttled
  per trigger kind;
- histograms attach the active SAMPLED trace id as an OpenMetrics
  exemplar, emitted only on OpenMetrics-negotiated scrapes;
- `kraken-tpu trace` reassembles multi-node dumps into span trees with
  the critical path marked, and exits non-zero on orphan spans.

NOTE: the herd tests run in ONE process, so every in-process component
shares the process-global TRACER ring (each /debug/trace returns the
union) -- but the shardpool workers are REAL forked processes, so the
worker-serve half of the propagation test crosses a genuine process
boundary (descriptor in, span shipping out).
"""

from __future__ import annotations

import asyncio
import glob
import json
import logging
import os
import time

import pytest

from kraken_tpu.utils import trace
from kraken_tpu.utils.metrics import REGISTRY, Registry
from kraken_tpu.utils.trace import (
    TRACER,
    TraceConfig,
    assemble_tree,
    critical_path,
    parse_traceparent,
)

NS = "library/trace-test"


@pytest.fixture(autouse=True)
def _tracer_isolation():
    """The TRACER is process-global (like the metric REGISTRY): snapshot
    its config/hooks and clear the ring around every test so sampling
    choices here never leak into other suites."""
    cfg0, node0, hook0 = TRACER.config, TRACER.node, TRACER.on_record
    TRACER.recorder.clear()
    TRACER._last_dump.clear()
    yield
    TRACER.config, TRACER.node, TRACER.on_record = cfg0, node0, hook0
    TRACER.recorder.clear()
    TRACER._last_dump.clear()


def _apply(**kw):
    TRACER.apply(TraceConfig(**kw))


# -- context + sampling unit tests ------------------------------------------


def test_traceparent_parse_and_roundtrip():
    with trace.span("root") as sp:
        assert sp is not None
        parsed = parse_traceparent(sp.traceparent)
        assert parsed is not None
        assert parsed.trace_id == sp.trace_id
        assert parsed.span_id == sp.span_id
        assert parsed.sampled == sp.sampled
    # Malformed values never raise -- a skewed peer's header must not
    # fail the request it rides on.
    for bad in (None, "", "garbage", "00-short-span-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace
                "00-" + "z" * 32 + "-" + "1" * 16 + "-01"):
        assert parse_traceparent(bad) is None
    ok = parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    assert ok is not None and ok.sampled
    assert not parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-00").sampled


def test_contextvar_propagation_and_inheritance():
    """Children join the contextvar's current span -- including across
    asyncio.create_task, which is the mechanism every pump loop and io
    task relies on."""
    _apply(sample_rate=1.0)

    async def main():
        async def child_task():
            with trace.span("child") as c:
                return c.trace_id, c.parent_id

        with trace.span("root") as root:
            tid, pid = await asyncio.create_task(child_task())
            assert tid == root.trace_id
            assert pid == root.span_id
        # Outside the with, the context is restored.
        assert trace.current() is None

    asyncio.run(main())


def test_head_sampling_inherited_and_tails_always_kept():
    # rate=0: fast-ok spans vanish; error and slow spans are KEPT.
    _apply(sample_rate=0.0, slow_threshold_seconds=0.05)
    with trace.span("fast-ok"):
        pass
    assert TRACER.recorder.snapshot() == []

    with pytest.raises(RuntimeError):
        with trace.span("errored"):
            raise RuntimeError("boom")
    snap = TRACER.recorder.snapshot()
    assert [s["name"] for s in snap] == ["errored"]
    assert snap[0]["status"] == "error" and "boom" in snap[0]["error"]

    with trace.span("slow"):
        time.sleep(0.06)
    assert "slow" in [s["name"] for s in TRACER.recorder.snapshot()]

    # rate=1: everything lands, children inherit the root's verdict.
    _apply(sample_rate=1.0)
    with trace.span("r") as r:
        with trace.span("c") as c:
            assert c.sampled and c.trace_id == r.trace_id
    names = [s["name"] for s in TRACER.recorder.snapshot()]
    assert "r" in names and "c" in names

    # An unsampled parent's children stay unsampled (no partial traces).
    _apply(sample_rate=0.0, slow_threshold_seconds=0.0)
    with trace.span("r2"):
        with trace.span("c2") as c2:
            assert not c2.sampled


def test_disabled_creates_no_spans():
    _apply(enabled=False)
    with trace.span("x") as sp:
        assert sp is None
        assert trace.current() is None
        assert trace.current_traceparent() is None
    assert TRACER.recorder.snapshot() == []


def test_flight_recorder_views_and_live_reload():
    _apply(sample_rate=1.0, keep_spans=512)
    with trace.span("a"):
        pass
    with pytest.raises(ValueError):
        with trace.span("b"):
            raise ValueError("x")
    with trace.span("slowest-root"):
        time.sleep(0.03)
    rec = TRACER.recorder
    assert [s["name"] for s in rec.recent(2)] == ["slowest-root", "b"]
    assert [s["name"] for s in rec.errored()] == ["b"]
    slow = rec.slowest(1)
    assert slow[0]["spans"][0]["name"] == "slowest-root"
    tid = rec.recent(1)[0]["trace_id"]
    assert [s["trace_id"] for s in rec.trace(tid)] == [tid]

    # SIGHUP live reload: ring resizes IN PLACE (spans survive a grow),
    # sampling applies to the next root.
    TRACER.apply({"sample_rate": 0.0, "keep_spans": 1024,
                  "slow_threshold_seconds": 0.0})
    assert len(rec.snapshot()) == 3  # survived the resize
    with trace.span("after-reload"):
        pass
    assert "after-reload" not in [s["name"] for s in rec.snapshot()]
    with pytest.raises(ValueError):
        TRACER.apply({"sample_rate": 2.0})
    with pytest.raises(ValueError):
        TRACER.apply({"not_a_knob": 1})


# -- dump triggers (the postmortem plane) -----------------------------------


def _dumps(dump_dir: str, trigger: str) -> list[str]:
    return sorted(glob.glob(os.path.join(dump_dir, f"trace-{trigger}-*.jsonl")))


def test_trigger_dump_writes_throttled_jsonl(tmp_path):
    dump_dir = str(tmp_path / "traces")
    _apply(sample_rate=1.0, dump_dir=dump_dir,
           dump_min_interval_seconds=3600.0)
    with trace.span("the-evidence", digest="abc123"):
        pass
    path = TRACER.trigger_dump("breaker_trip", "origin1:7610")
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert lines[0]["dump"] == "breaker_trip"
    assert lines[0]["detail"] == "origin1:7610"
    assert any(d.get("name") == "the-evidence" for d in lines[1:])
    # Same trigger kind inside the floor: throttled (no second file)...
    assert TRACER.trigger_dump("breaker_trip", "again") is None
    assert len(_dumps(dump_dir, "breaker_trip")) == 1
    # ...but a DIFFERENT trigger kind still dumps.
    assert TRACER.trigger_dump("lameduck", "x") is not None
    # Every ask counts, throttled or not.
    c = REGISTRY.counter("trace_dump_triggers_total")
    assert c.value(trigger="breaker_trip") >= 2
    assert REGISTRY.counter("trace_dumps_total").value(
        trigger="breaker_trip") >= 1


def test_trigger_dump_never_raises_and_skips_empty(tmp_path):
    # Empty ring: nothing to postmortem, no file.
    _apply(sample_rate=1.0, dump_dir=str(tmp_path / "t"))
    assert TRACER.trigger_dump("lameduck") is None
    # No dump dir configured (tracker shape): counted, no file, no error.
    _apply(sample_rate=1.0)
    with trace.span("s"):
        pass
    assert TRACER.trigger_dump("lameduck") is None
    # An unwritable dir must not raise into the degradation plane that
    # is already firing.
    _apply(sample_rate=1.0, dump_dir="/proc/nonexistent/nope")
    assert TRACER.trigger_dump("resource_breach") is None


def test_breaker_trip_leaves_flight_recorder_dump(tmp_path):
    """The PR-5 circuit breaker is a dump trigger: the spans that led to
    the trip are the postmortem, persisted the moment the host opens."""
    from kraken_tpu.placement.healthcheck import PassiveFilter

    dump_dir = str(tmp_path / "traces")
    _apply(sample_rate=1.0, dump_dir=dump_dir)
    with trace.span("rpc.download", addr="origin1:7610"):
        pass
    pf = PassiveFilter(fail_threshold=1, name="trace-test")
    pf.failed("origin1:7610")
    files = _dumps(dump_dir, "breaker_trip")
    assert len(files) == 1, "breaker trip left no flight-recorder dump"
    with open(files[0]) as f:
        header = json.loads(f.readline())
    assert header["dump"] == "breaker_trip"
    assert "origin1:7610" in header["detail"]


def test_deadline_exceeded_leaves_flight_recorder_dump(tmp_path):
    from kraken_tpu.utils.deadline import Deadline, DeadlineExceeded

    dump_dir = str(tmp_path / "traces")
    _apply(sample_rate=1.0, dump_dir=dump_dir)
    with trace.span("http.client GET", url="http://x/slow"):
        pass
    err = Deadline(0.0, component="cluster").exceeded("GET http://x/slow")
    assert isinstance(err, DeadlineExceeded)
    files = _dumps(dump_dir, "deadline_exceeded")
    assert len(files) == 1, "DeadlineExceeded left no flight-recorder dump"
    with open(files[0]) as f:
        header = json.loads(f.readline())
    assert header["dump"] == "deadline_exceeded"
    assert "cluster" in header["detail"]


# -- exemplars ---------------------------------------------------------------


def test_histogram_exemplars_attach_sampled_trace_id():
    _apply(sample_rate=1.0)
    reg = Registry()
    h = reg.histogram("req_seconds", "latency", buckets=(0.1, 1.0))
    with trace.span("the-request") as sp:
        h.observe(0.05, endpoint="/blobs")
        tid = sp.trace_id
    # Un-traced and UNSAMPLED observations leave no exemplar.
    h.observe(0.5, endpoint="/blobs")
    _apply(sample_rate=0.0, slow_threshold_seconds=0.0)
    with trace.span("unsampled"):
        h.observe(0.7, endpoint="/blobs")

    text = reg.render(exemplars=True)
    assert f'# {{trace_id="{tid}"}} 0.05' in text
    assert text.count("# {trace_id=") == 1  # only the sampled bucket
    # The classic exposition stays exemplar-free (classic parsers
    # reject the in-line suffix).
    assert "# {trace_id=" not in reg.render()
    # The exemplar rides the FIRST bucket the value fits (0.1 here).
    ex = h.exemplar(endpoint="/blobs")
    assert list(ex) == [0]
    assert ex[0][1] == tid


def test_metrics_endpoint_negotiates_openmetrics_exemplars(tmp_path):
    """The scrape surface: a plain GET /metrics is classic text (no
    exemplars); an OpenMetrics Accept gets them + the # EOF trailer."""
    from kraken_tpu.assembly import TrackerNode
    from kraken_tpu.utils.httputil import HTTPClient

    async def main():
        node = TrackerNode(trace={"sample_rate": 1.0})
        await node.start()
        http = HTTPClient(retries=0)
        try:
            base = f"http://{node.addr}"
            await http.get(f"{base}/health")  # an observation under a span
            classic = (await http.get(f"{base}/metrics")).decode()
            assert "# {trace_id=" not in classic
            om = (await http.get(
                f"{base}/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )).decode()
            assert "# {trace_id=" in om
            assert om.endswith("# EOF\n")
            # The negotiated body must be VALID OpenMetrics end to end:
            # a counter family declared `# TYPE foo_total counter` (the
            # suffix repeated in the metadata) is a parse error that
            # fails the whole scrape for exactly the exemplar-scraping
            # Prometheus this negotiation targets. Validated against
            # the reference parser when available.
            try:
                from prometheus_client.openmetrics import parser
            except ImportError:
                parser = None
            if parser is not None:
                families = {
                    f.name
                    for f in parser.text_string_to_metric_families(om)
                }
                assert "http_requests" in families  # suffix stripped
                assert "http_request_duration_seconds" in families
            # /debug/trace serves the same spans live.
            doc = json.loads(await http.get(f"{base}/debug/trace"))
            assert doc["sample_rate"] == 1.0
            assert any(
                s["name"].startswith("http.server") for s in doc["spans"]
            )
            assert json.loads(await http.get(
                f"{base}/debug/trace?view=errors"))["spans"] == []
            status, _, _ = await http.request_full(
                "GET", f"{base}/debug/trace?view=bogus",
                ok_statuses=(400,), retry_5xx=False,
            )
            assert status == 400
        finally:
            await http.close()
            await node.stop()

    asyncio.run(main())


def test_hedge_attempt_spans_carry_op_and_hedge_flag():
    """Each replica-walk attempt is its own child span with the hedge
    attribute, so a hedged read shows up in /debug/trace as primary and
    hedge side by side -- which one won is readable off the tree. (The
    walk lives in placement/replicawalk.py since round 12, shared by the
    origin ClusterClient and the tracker fleet client.)"""
    from kraken_tpu.placement.replicawalk import _attempt

    _apply(sample_rate=1.0)

    async def main():
        class _C:
            addr = "h1:1"

        async def op(c, deadline):
            return b"ok"

        with trace.span("caller") as root:
            out = await _attempt(
                None, _C(), op, None, as_hedge=True, op_name="download"
            )
        assert out == b"ok"
        spans = {s["name"]: s for s in TRACER.recorder.snapshot()}
        sp = spans["rpc.download"]
        assert sp["attrs"]["hedge"] is True
        assert sp["attrs"]["addr"] == "h1:1"
        assert sp["parent_id"] == root.span_id

    asyncio.run(main())


# -- satellite stamps --------------------------------------------------------


def test_networkevent_and_structlog_stamp_trace_ids():
    from kraken_tpu.p2p.networkevent import Producer
    from kraken_tpu.utils.structlog import JSONFormatter

    _apply(sample_rate=1.0)
    producer = Producer("peer-1")
    fmt = JSONFormatter(component="agent")
    rec = logging.LogRecord(
        "kraken.p2p", logging.INFO, __file__, 1, "piece done", (), None
    )
    with trace.span("p2p.download") as sp:
        producer.emit("receive_piece", "ih", piece=3)
        line = json.loads(fmt.format(rec))
    assert producer.events[-1]["trace_id"] == sp.trace_id
    assert line["trace_id"] == sp.trace_id
    assert line["span_id"] == sp.span_id
    # Outside a span: no stamp (absent key, not null noise).
    producer.emit("announce", "ih")
    assert "trace_id" not in producer.events[-1]
    assert "trace_id" not in json.loads(fmt.format(rec))


# -- offline reassembly (`kraken-tpu trace`) --------------------------------


def _span(name, tid, sid, parent="", start=0.0, dur=1.0, node="", **extra):
    d = {"trace_id": tid, "span_id": sid, "parent_id": parent, "name": name,
         "start_ts": start, "duration_s": dur, "status": "ok", **extra}
    if node:
        d["node"] = node
    return d


def test_assemble_tree_and_critical_path():
    tid = "t" * 32
    root = _span("pull", tid, "a", start=0.0, dur=10.0)
    fast = _span("dial1", tid, "b", parent="a", start=0.1, dur=1.0)
    slow = _span("dial2", tid, "c", parent="a", start=0.2, dur=9.0)
    leaf = _span("serve", tid, "d", parent="c", start=1.0, dur=8.0)
    roots, orphans = assemble_tree([root, fast, slow, leaf])
    assert [r["span_id"] for r in roots] == ["a"] and not orphans
    # Critical path descends into the latest-ENDING child each level.
    assert critical_path(roots[0]) == {"a", "c", "d"}

    orphan = _span("lost", tid, "e", parent="zz")
    _, orphans = assemble_tree([root, orphan])
    assert [o["span_id"] for o in orphans] == ["e"]


def test_assemble_tree_flags_parent_cycles_as_orphans():
    """A corrupt/crafted dump line with a parent cycle (span_id ==
    parent_id, or a -> b -> a) must surface as orphans and exit-1 the
    CLI -- not vanish from the printed tree or hang critical_path."""
    tid = "t" * 32
    root = _span("pull", tid, "a", start=0.0, dur=1.0)
    selfloop = _span("bad", tid, "x", parent="x")
    roots, orphans = assemble_tree([root, selfloop])
    assert [r["span_id"] for r in roots] == ["a"]
    assert [o["span_id"] for o in orphans] == ["x"]
    assert critical_path(roots[0]) == {"a"}  # terminates

    cyc1 = _span("cyc1", tid, "p", parent="q")
    cyc2 = _span("cyc2", tid, "q", parent="p")
    hanger = _span("child-of-cycle", tid, "r", parent="p")
    roots, orphans = assemble_tree([root, cyc1, cyc2, hanger])
    assert [r["span_id"] for r in roots] == ["a"]
    assert {o["span_id"] for o in orphans} == {"p", "q", "r"}


def test_cancelled_spans_do_not_ride_the_error_tail():
    """Losing hedge attempts and teardown cancel spans by design
    (origin/client.py: cancellation is NOT host evidence); at shipped
    sampling they must not be force-kept as errors and flood the ring /
    ?view=errors. A real exception still is."""
    _apply(sample_rate=0.0, slow_threshold_seconds=0.0)
    with pytest.raises(asyncio.CancelledError):
        with trace.span("rpc.download", addr="o1:7610"):
            raise asyncio.CancelledError()
    assert TRACER.recorder.snapshot() == []

    with pytest.raises(ValueError):
        with trace.span("rpc.download", addr="o1:7610"):
            raise ValueError("boom")
    kept = TRACER.recorder.snapshot()
    assert [s["status"] for s in kept] == ["error"]

    # On a SAMPLED trace the cancelled span is still recorded (the
    # hedge-loser timing is real signal), just not as an error.
    _apply(sample_rate=1.0)
    with pytest.raises(asyncio.CancelledError):
        with trace.span("rpc.download", hedge=True):
            raise asyncio.CancelledError()
    cancelled = [s for s in TRACER.recorder.snapshot()
                 if s["status"] == "cancelled"]
    assert len(cancelled) == 1 and "error" not in cancelled[0]


def test_trace_cli_joins_multi_node_dumps_and_flags_orphans(tmp_path, capsys):
    from kraken_tpu.cli import run_trace_tool

    tid = "f" * 32
    node1 = [
        _span("http.server GET /blobs", tid, "a" * 16, dur=5.0, node="agent"),
        _span("p2p.dial", tid, "b" * 16, parent="a" * 16, start=0.5,
              dur=4.0, node="agent"),
    ]
    node2 = [
        _span("p2p.shard.serve", tid, "c" * 16, parent="b" * 16, start=1.0,
              dur=2.0, node="origin/shard0"),
    ]
    f1, f2 = str(tmp_path / "agent.jsonl"), str(tmp_path / "origin.jsonl")
    for path, spans in ((f1, node1), (f2, node2)):
        with open(path, "w") as f:
            f.write(json.dumps({"dump": "test", "ts": 0}) + "\n")  # header
            for s in spans:
                f.write(json.dumps(s) + "\n")

    # Both dumps together: one joined tree, exit 0, critical path marked.
    assert run_trace_tool([f1, f2]) == 0
    out = capsys.readouterr().out
    assert f"trace {tid}" in out and "nodes=agent,origin/shard0" in out
    assert "p2p.shard.serve" in out
    assert "* " in out  # critical-path gutter
    assert json.loads(out.strip().splitlines()[-1])["orphans"] == 0

    # The origin dump ALONE: the serve span's parent lives in the agent
    # dump -- an orphan, non-zero exit for CI.
    assert run_trace_tool([f2]) == 1
    out = capsys.readouterr().out
    assert "ORPHAN" in out

    # Unknown trace id / unreadable file: distinct failure exits.
    assert run_trace_tool([f1], trace_id="0" * 32) == 1
    capsys.readouterr()
    assert run_trace_tool([str(tmp_path / "missing.jsonl")]) == 3
    capsys.readouterr()


# -- the acceptance test: one trace across the pair + forked workers --------


def test_pair_pull_is_one_trace_across_nodes_and_workers(tmp_path):
    """A single blob pull on a tracker+origin+agent herd with
    data_plane_workers=2 yields ONE trace_id whose spans cover
    announce -> dial -> piece request -> worker sendfile serve -> verify,
    visible on /debug/trace of both nodes and joinable offline by
    `kraken-tpu trace` with zero orphans."""
    from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
    from kraken_tpu.cli import run_trace_tool
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.client import BlobClient, ClusterClient
    from kraken_tpu.placement import HostList, Ring
    from kraken_tpu.utils.httputil import HTTPClient

    tcfg = {"sample_rate": 1.0, "keep_spans": 8192}

    async def main():
        tracker = TrackerNode(
            announce_interval_seconds=0.1, peer_ttl_seconds=5.0, trace=tcfg
        )
        await tracker.start()
        origin = OriginNode(
            store_root=str(tmp_path / "origin"),
            tracker_addr=tracker.addr,
            scheduler_config_doc={"data_plane_workers": 2},
            trace=tcfg,
        )
        await origin.start()
        ring = Ring(HostList(static=[origin.addr]), max_replica=2)
        cluster = ClusterClient(ring)
        tracker.server.origin_cluster = cluster
        origin.ring = ring
        if origin.server:
            origin.server.ring = ring
        agent = AgentNode(
            store_root=str(tmp_path / "agent"), tracker_addr=tracker.addr,
            trace=tcfg,
        )
        await agent.start()
        http = HTTPClient()
        try:
            blob = os.urandom(2_000_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(origin.addr)
            await oc.upload(NS, d, blob, chunk_size=500_000)
            await oc.close()

            got = await http.get(
                f"http://{agent.addr}/namespace/"
                f"{NS.replace('/', '%2F')}/blobs/{d.hex}"
            )
            assert got == blob

            # The pull's trace: rooted at the agent's HTTP server span.
            def pull_spans():
                snap = TRACER.recorder.snapshot()
                tids = {s["trace_id"] for s in snap
                        if s["name"] == "p2p.download"}
                assert len(tids) == 1, f"expected one pull trace, got {tids}"
                tid = tids.pop()
                return tid, [s for s in snap if s["trace_id"] == tid]

            # Worker serve spans ship home on the 0.25 s stats tick --
            # poll until the forked half of the trace has landed.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                tid, spans = pull_spans()
                if any(s["name"] == "p2p.shard.serve" for s in spans):
                    break
                await asyncio.sleep(0.1)
            names = {s["name"] for s in spans}
            for expected in ("p2p.download", "p2p.announce", "p2p.dial",
                             "p2p.piece.request", "p2p.shard.serve",
                             "p2p.piece.receive", "tracker.announce"):
                assert expected in names, f"{expected} missing from {names}"
            # The worker half really crossed the fork: its node stamp
            # carries the shard suffix.
            shard_nodes = {s.get("node") for s in spans
                           if s["name"] == "p2p.shard.serve"}
            assert all(n and "/shard" in n for n in shard_nodes)

            # Both nodes' /debug/trace surfaces hold the trace (one
            # process here, so each returns the shared ring -- the
            # assertion is that the SURFACE works on both).
            for addr in (agent.addr, origin.addr):
                doc = json.loads(await http.get(
                    f"http://{addr}/debug/trace?view=trace&trace_id={tid}"
                ))
                assert {s["name"] for s in doc["spans"]} >= {
                    "p2p.download", "p2p.shard.serve"
                }

            # Offline join: split the ring into per-node dumps the way
            # two real nodes would write them, then reassemble. Zero
            # orphans = no hop dropped the context.
            agent_dump = str(tmp_path / "agent-dump.jsonl")
            origin_dump = str(tmp_path / "origin-dump.jsonl")
            with (
                await asyncio.to_thread(open, agent_dump, "w") as fa,
                await asyncio.to_thread(open, origin_dump, "w") as fo,
            ):
                for s in spans:
                    node = s.get("node", "")
                    f = fo if node.startswith("origin") else fa
                    f.write(json.dumps(s) + "\n")
            assert run_trace_tool(
                [agent_dump, origin_dump], trace_id=tid) == 0
        finally:
            await http.close()
            await agent.stop()
            await origin.stop()
            await cluster.close()
            await tracker.stop()

    asyncio.run(main())

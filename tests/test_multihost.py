"""Multi-host hash plane: REAL multi-process federation, hermetically.

Spawns N python processes that each join a jax.distributed cluster over
localhost (gloo TCP collectives -- the DCN stand-in), hash distinct local
piece batches, and exchange digests with a global-mesh XLA collective.
This is the distributed-backend proof the in-process virtual mesh cannot
give: separate OS processes, separate runtimes, a real wire between them
(SURVEY.md SS2.7/SS5 distributed communication backend).
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(proc: int, n: int, port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The subprocesses form their own cluster; the parent pytest process's
    # virtual-device XLA_FLAGS must not leak in (8 virtual devices per
    # host x 2 hosts would be a different topology than the test asserts).
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [
            sys.executable, "-m", "kraken_tpu.parallel.multihost",
            str(proc), str(n), str(port),
        ],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _drive(n: int, want_digests: int):
    port = _free_port()
    procs = [_spawn(p, n, port) for p in range(n)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "MULTIHOST-OK" in out, out
        # Every host saw the same global digest count.
        assert f"digests={want_digests}" in out, out


def test_two_host_hash_plane_collective():
    _drive(2, 3 + 4)


def test_three_host_hash_plane_collective():
    """Three processes, three distinct ragged batch sizes: the count
    gather and padded digest gather must hold beyond the pairwise case
    (gloo ring with >2 ranks)."""
    _drive(3, 3 + 4 + 5)

"""Multi-device sharding tests for the hash plane.

conftest.py forces an 8-way virtual CPU mesh for the whole session, so
shard_map collectives run for real here (the permanent in-suite multi-chip
signal; the driver's dryrun_multichip covers the same path out-of-suite).
"""

import hashlib
import os

import numpy as np
import pytest

from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.ops.sha256 import _digest_bytes
from kraken_tpu.parallel import (
    ShardedPieceHasher,
    piece_mesh,
    sharded_hash_pieces,
)


def _want(data: np.ndarray) -> list[bytes]:
    return [hashlib.sha256(row.tobytes()).digest() for row in data]


def test_piece_mesh_has_eight_devices():
    mesh = piece_mesh(8)
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("pieces",)
    assert mesh.devices.flat[0].platform == "cpu"


# The Pallas variant is opt-in: XLA:CPU needs >5 min to compile the
# kernel's unrolled body in any CPU mode (see dryrun_multichip docstring);
# the kernel's correctness home is the real chip (entry() + bench.py).
_PALLAS = (
    [False, True] if os.environ.get("RUN_PALLAS_INTERPRET") else [False]
)


@pytest.mark.parametrize("use_pallas", _PALLAS)
def test_sharded_hash_matches_hashlib(use_pallas):
    mesh = piece_mesh(8)
    piece_len = 256
    n = 8 * 3 + 5  # ragged vs the device quantum: exercises row padding
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(n, piece_len), dtype=np.uint8)
    out = sharded_hash_pieces(
        mesh, data, piece_len, use_pallas=use_pallas, replicate=True
    )
    assert out.shape == (n, 8)
    got = _digest_bytes(out)
    want = _want(data)
    for i in range(n):
        assert got[i].tobytes() == want[i], f"piece {i} (pallas={use_pallas})"


def test_sharded_output_replicated():
    mesh = piece_mesh(8)
    data = np.zeros((16, 128), dtype=np.uint8)
    out = sharded_hash_pieces(mesh, data, 128, replicate=True)
    # Replicated: every device holds the full digest matrix.
    assert out.sharding.is_fully_replicated


def test_sharded_hasher_registry_roundtrip():
    hasher = get_hasher("tpu-sharded")
    assert isinstance(hasher, ShardedPieceHasher)
    rng = np.random.default_rng(3)
    # 10 full 256-byte pieces + a 100-byte ragged tail.
    blob = rng.integers(0, 256, size=10 * 256 + 100, dtype=np.uint8).tobytes()
    got = hasher.hash_pieces(blob, 256)
    assert got.shape == (11, 32)
    for i in range(11):
        want = hashlib.sha256(blob[i * 256 : (i + 1) * 256]).digest()
        assert got[i].tobytes() == want, f"piece {i}"


def test_graft_dryrun_is_hermetic():
    """The dryrun must pass with a HOSTILE parent environment.

    Round-2 regression: the driver gate failed because the dryrun depended
    on the driver's XLA_FLAGS for device count and let an eager gather
    index land on the default (real, version-skewed) TPU device. The
    subprocess re-exec must scrub both: bogus JAX_PLATFORMS, no XLA_FLAGS.
    Inside the dryrun, transfer_guard_host_to_device("disallow") turns any
    stray implicit default-device placement into a hard failure.
    """
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"  # bogus here: no TPU in the test env
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__; __graft_entry__.dryrun_multichip(8)",
        ],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"


def test_tpu_sharded_hasher_resolvable_by_name(tmp_path):
    """`hasher: tpu-sharded` in component YAML resolves through the
    registry (deferred hashplane import) and hashes correctly -- the
    production multi-chip path, end to end through a node."""

    from kraken_tpu.origin.metainfogen import Generator
    from kraken_tpu.store import CAStore
    from kraken_tpu.core.digest import Digest

    h = get_hasher("tpu-sharded")
    data = np.random.default_rng(3).integers(
        0, 256, size=300_000, dtype=np.uint8
    ).tobytes()
    got = h.hash_pieces(data, 65536)
    want = [
        hashlib.sha256(data[o : o + 65536]).digest()
        for o in range(0, len(data), 65536)
    ]
    assert [bytes(r) for r in got] == want

    # And through the origin's metainfo generator (the real hot loop).
    store = CAStore(str(tmp_path))
    d = Digest.from_bytes(data)
    uid = store.create_upload()
    store.write_upload_chunk(uid, 0, data)
    store.commit_upload(uid, d)
    gen = Generator(store, hasher=h)
    mi = gen.generate_sync(d)
    assert mi.length == len(data)
    # The generator's chunked read path must produce byte-exact digests
    # (it chooses its own piece length from the blob-size table).
    pl = mi.piece_length
    want_mi = [
        hashlib.sha256(data[o : o + pl]).digest()
        for o in range(0, len(data), pl)
    ]
    assert [mi.piece_hash(i) for i in range(mi.num_pieces)] == want_mi

"""Gossip peer-exchange plane tests (p2p/pex.py + scheduler wiring).

Property tests over the book/dedup/cache primitives, wire framing for
the PEER_EXCHANGE frame, and in-process swarm tests proving the defense
model: gossip discovers peers the tracker never handed out, a
blacklisted peer gossiped back in stays banned, an addr-flooding sender
is banned outright, and the disk peercache redials a swarm across a
restart with the tracker dark.
"""

import asyncio
import json
import os

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.pex import (
    MAX_ENTRIES_PER_MESSAGE,
    KnownPeers,
    PeerCache,
    PexConfig,
    PexManager,
)
from kraken_tpu.p2p.scheduler import Scheduler, SchedulerConfig
from kraken_tpu.p2p.storage import (
    AgentTorrentArchive,
    BatchedVerifier,
    OriginTorrentArchive,
)
from kraken_tpu.p2p.wire import Message, MsgType, recv_message, send_message
from kraken_tpu.store import CAStore
from kraken_tpu.utils import failpoints

from tests.test_swarm import make_metainfo

NS = "pex-ns"


def pid(i: int) -> PeerID:
    return PeerID((bytes([i]) * 20).hex())


def info(i: int, port: int = 7000, origin: bool = False) -> PeerInfo:
    return PeerInfo(pid(i), f"10.0.0.{i}", port, origin=origin)


# -- config ------------------------------------------------------------------


def test_pex_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown pex config keys"):
        PexConfig.from_dict({"interval_secnods": 10.0})
    cfg = PexConfig.from_dict(None)
    assert cfg.enabled and cfg.send_enabled and cfg.peercache


# -- wire framing ------------------------------------------------------------


def test_peer_exchange_frame_roundtrip():
    """The PEX frame survives the real wire: header intact, type routed,
    and the empty-payload shape (it is pure header) holds."""
    async def main():
        got = []

        async def handler(reader, writer):
            got.append(await recv_message(reader))
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        _, writer = await asyncio.open_connection("127.0.0.1", port)
        added = [{"id": pid(1).hex, "ip": "10.0.0.1", "p": 7001, "o": True}]
        await send_message(writer, Message.peer_exchange(added, [pid(2).hex]))
        await asyncio.sleep(0.1)
        writer.close()
        server.close()
        await server.wait_closed()

        (m,) = got
        assert m.type == MsgType.PEER_EXCHANGE
        assert m.header == {"a": added, "d": [pid(2).hex]}
        assert m.payload == b""

    asyncio.run(main())


def test_handshake_carries_listen_port():
    m = Message.handshake("ab" * 20, "cd" * 32, "ef" * 32, "ns", b"\x01", 8,
                          listen_port=7612)
    assert m.header["lp"] == 7612
    # Omitted when zero: older peers' handshakes decode identically.
    m0 = Message.handshake("ab" * 20, "cd" * 32, "ef" * 32, "ns", b"\x01", 8)
    assert "lp" not in m0.header


# -- receive validation + dedup ----------------------------------------------


def test_ingest_flood_is_a_protocol_violation():
    mgr = PexManager(PexConfig())
    added = [
        {"id": (bytes([i % 251 + 1]) * 20).hex()[:40], "ip": "10.0.0.1",
         "p": 7000}
        for i in range(MAX_ENTRIES_PER_MESSAGE + 1)
    ]
    with pytest.raises(ValueError, match="pex flood"):
        mgr.ingest("ab" * 32, pid(9), {"a": added, "d": []}, now=0.0)


@pytest.mark.parametrize("header", [
    {"a": "nope", "d": []},
    {"a": [], "d": "nope"},
    {"a": [42], "d": []},
    {"a": [{"id": "zz" * 20, "ip": "x", "p": 1}], "d": []},  # bad hex
    {"a": [{"id": "ab" * 20, "ip": "", "p": 1}], "d": []},   # empty ip
    {"a": [{"id": "ab" * 20, "ip": "x", "p": 0}], "d": []},  # bad port
    {"a": [{"id": "ab" * 20, "ip": "x", "p": 70000}], "d": []},
    {"a": [{"ip": "x", "p": 1}], "d": []},                   # missing id
    {"a": [], "d": [17]},                                    # non-str drop
    {"a": [], "d": ["zz"]},                                  # bad drop hex
])
def test_ingest_garbage_raises_for_the_ban_path(header):
    mgr = PexManager(PexConfig())
    with pytest.raises(ValueError):
        mgr.ingest("ab" * 32, pid(9), header, now=0.0)


def test_ingest_dedup_ttl():
    """The same addr gossiped twice inside the TTL is absorbed once;
    past the TTL it is fresh again (and per-torrent: the same addr on a
    different swarm is independent)."""
    mgr = PexManager(PexConfig(seen_ttl_seconds=10.0))
    entry = {"id": pid(1).hex, "ip": "10.0.0.1", "p": 7001}
    h1, h2 = "aa" * 32, "bb" * 32
    fresh, _ = mgr.ingest(h1, pid(9), {"a": [entry], "d": []}, now=0.0)
    assert len(fresh) == 1
    fresh, _ = mgr.ingest(h1, pid(8), {"a": [entry], "d": []}, now=5.0)
    assert fresh == []  # different sender, same addr: still deduped
    fresh, _ = mgr.ingest(h2, pid(8), {"a": [entry], "d": []}, now=5.0)
    assert len(fresh) == 1  # other torrent: independent book
    fresh, _ = mgr.ingest(h1, pid(9), {"a": [entry], "d": []}, now=10.5)
    assert len(fresh) == 1  # TTL expired: fresh again


def test_dial_budget_sheds_over_burst():
    mgr = PexManager(PexConfig(dial_rate=1000.0, dial_burst=3.0))
    grants = sum(1 for _ in range(10) if mgr.try_dial_budget())
    assert grants == 3


# -- known-peers book --------------------------------------------------------


def test_known_peers_provenance_scoped_drop():
    """A sender can only retract entries IT gossiped: gossip must not
    evict tracker/handshake knowledge, nor another sender's entries."""
    book = KnownPeers(cap=16)
    book.add(info(1), "tracker")
    book.add(info(2), "gossip:" + pid(8).hex)
    book.add(info(3), "gossip:" + pid(9).hex)
    evil = "gossip:" + pid(9).hex
    book.drop(pid(1), evil)  # tracker entry: untouchable
    book.drop(pid(2), evil)  # another sender's entry: untouchable
    book.drop(pid(3), evil)  # its own entry: retracted
    left = {p.peer_id for p in book.snapshot()}
    assert left == {pid(1), pid(2)}
    # discard (our own dial failed) is unconditional.
    book.discard(pid(1))
    assert {p.peer_id for p in book.snapshot()} == {pid(2)}


def test_known_peers_authoritative_overwrites_gossip_not_vice_versa():
    book = KnownPeers(cap=16)
    book.add(info(1, port=7001), "tracker")
    # Gossip cannot "move" a tracker-recorded addr...
    book.add(info(1, port=9999), "gossip:" + pid(9).hex)
    assert book.snapshot()[0].port == 7001
    # ...but a live handshake can (the peer proved the addr itself).
    book.add(info(1, port=7002), "conn")
    assert book.snapshot()[0].port == 7002


def test_known_peers_cap_gossip_cannot_evict_authoritative():
    book = KnownPeers(cap=2)
    book.add(info(1), "tracker")
    book.add(info(2), "conn")
    assert not book.add(info(3), "gossip:" + pid(9).hex)  # full: refused
    assert len(book) == 2
    # An authoritative add evicts a gossip entry, never the reverse.
    book2 = KnownPeers(cap=2)
    book2.add(info(1), "gossip:" + pid(9).hex)
    book2.add(info(2), "tracker")
    assert book2.add(info(3), "tracker")
    assert {p.peer_id for p in book2.snapshot()} == {pid(2), pid(3)}


# -- send deltas -------------------------------------------------------------


def test_delta_for_budget_recipient_exclusion_and_drops():
    mgr = PexManager(PexConfig(max_peers_per_message=2))
    peers = [info(i) for i in range(1, 6)]
    added, dropped = mgr.delta_for("c1", pid(3), peers)
    assert len(added) == 2  # budget capped
    assert all(e["id"] != pid(3).hex for e in added)  # never echo recipient
    # Next tick says only what is NEW on this conn...
    added2, _ = mgr.delta_for("c1", pid(3), peers)
    assert {e["id"] for e in added2}.isdisjoint({e["id"] for e in added})
    # ...and retracts what left the book.
    sent = {e["id"] for e in added} | {e["id"] for e in added2}
    _, dropped3 = mgr.delta_for("c1", pid(3), [info(1)])
    assert set(dropped3) == sent - {pid(1).hex}
    # A fresh conn key starts from zero; forget_conn resets it.
    added_c2, _ = mgr.delta_for("c2", pid(3), peers)
    assert len(added_c2) == 2
    mgr.forget_conn("c1")
    added_again, _ = mgr.delta_for("c1", pid(3), [info(1)])
    assert [e["id"] for e in added_again] == [pid(1).hex]


# -- peercache ---------------------------------------------------------------


def _cache_doc(mi: MetaInfo, peers):
    return {
        mi.info_hash.hex: {
            "namespace": NS,
            "metainfo": mi.serialize().decode(),
            "peers": peers,
        }
    }


def test_peercache_roundtrip_and_ttl(tmp_path):
    path = str(tmp_path / "sub" / "peercache.json")  # dir is created
    cache = PeerCache(path, ttl_seconds=100.0)
    mi = make_metainfo(b"x" * 10000)
    cache.save(_cache_doc(mi, [info(1), info(2, origin=True)]), now=1000.0)
    loaded = cache.load(now=1050.0)
    rec = loaded[mi.info_hash.hex]
    assert rec["namespace"] == NS
    assert MetaInfo.deserialize(rec["metainfo"].encode()).digest == mi.digest
    assert [p.peer_id for p in rec["peers"]] == [pid(1), pid(2)]
    assert rec["peers"][1].origin is True
    # TTL-aged out entirely past the horizon.
    assert cache.load(now=1101.0) == {}
    # Carried saved_at survives a re-save: merged-forward records keep
    # aging on their ORIGINAL clock instead of living forever.
    cache.save(loaded, now=1090.0)
    assert cache.load(now=1101.0) == {}


def test_peercache_crash_shapes_load_empty(tmp_path):
    path = str(tmp_path / "peercache.json")
    assert PeerCache(path).load() == {}  # missing file
    with open(path, "w") as f:
        f.write('{"v": 1, "torrents"')  # torn mid-write (no tmp+rename)
    assert PeerCache(path).load() == {}
    with open(path, "w") as f:
        f.write(json.dumps({"v": 999, "torrents": {}}))  # future version
    assert PeerCache(path).load() == {}
    # A torn .tmp beside a good file is ignored debris.
    cache = PeerCache(path, ttl_seconds=100.0)
    mi = make_metainfo(b"y" * 5000)
    cache.save(_cache_doc(mi, [info(1)]), now=0.0)
    with open(path + ".tmp", "w") as f:
        f.write('{"v": 1, "torr')
    assert mi.info_hash.hex in cache.load(now=1.0)


def test_peercache_one_torn_record_spares_the_rest(tmp_path):
    path = str(tmp_path / "peercache.json")
    cache = PeerCache(path, ttl_seconds=100.0)
    mi = make_metainfo(b"z" * 5000)
    cache.save(_cache_doc(mi, [info(1)]), now=0.0)
    doc = json.load(open(path))
    doc["torrents"]["ff" * 32] = {"namespace": 1}  # malformed sibling
    doc["torrents"]["ee" * 32] = "not-a-map"
    with open(path, "w") as f:
        json.dump(doc, f)
    loaded = cache.load(now=1.0)
    assert set(loaded) == {mi.info_hash.hex}


# -- swarm integration -------------------------------------------------------


def _fast_pex(**over) -> PexConfig:
    kw = dict(interval_seconds=1.0, jitter=0.0, seen_ttl_seconds=60.0,
              dial_rate=100.0, dial_burst=100.0)
    kw.update(over)
    return PexConfig(**kw)


def _mk_sched(tmp_path, name, client, seed_blob=None, pex=None,
              peercache_path=None):
    store = CAStore(str(tmp_path / name))
    verifier = BatchedVerifier()
    if seed_blob is not None:
        d = Digest.from_bytes(seed_blob)
        store.create_cache_file(d, iter([seed_blob]))
        archive = OriginTorrentArchive(store, verifier)
    else:
        archive = AgentTorrentArchive(store, verifier)
    sched = Scheduler(
        peer_id=PeerID(os.urandom(20).hex()),
        ip="127.0.0.1",
        port=0,
        archive=archive,
        metainfo_client=client,
        announce_client=client,
        config=SchedulerConfig(
            announce_interval_seconds=0.1,
            retry_tick_seconds=0.2,
            dial_timeout_seconds=2.0,
        ),
        pex=pex or _fast_pex(),
        peercache_path=peercache_path,
    )
    return sched, store


class _ScriptedClient:
    """Announce returns a FIXED handout (closures resolve ports after
    bind); metainfo always serves. The tracker never learns -- gossip
    must carry anything beyond the script."""

    def __init__(self, mi: MetaInfo, handout_fn):
        self.mi = mi
        self.handout_fn = handout_fn

    async def get(self, namespace, d):
        return self.mi

    async def announce(self, d, h, namespace, complete):
        return self.handout_fn(), 0.2


def test_gossip_discovers_peers_the_tracker_never_handed_out(tmp_path):
    """Leecher B's tracker handout contains ONLY leecher A -- never the
    seeder. B must still converge bit-identically: A gossips the
    seeder's (listen-port-carrying) record over the B<->A conn and B
    dials it through the normal gates."""
    async def main():
        blob = os.urandom(120_000)
        mi = make_metainfo(blob)
        seeder, _ = _mk_sched(
            tmp_path, "seeder", _ScriptedClient(mi, lambda: []),
            seed_blob=blob,
        )
        refs = {}
        a_client = _ScriptedClient(
            mi, lambda: [PeerInfo(seeder.peer_id, "127.0.0.1", seeder.port,
                                  origin=True)]
        )
        a, _ = _mk_sched(tmp_path, "a", a_client)
        b_client = _ScriptedClient(
            mi, lambda: [PeerInfo(a.peer_id, "127.0.0.1", refs["a_port"])]
        )
        b, bstore = _mk_sched(tmp_path, "b", b_client)
        for s in (seeder, a, b):
            await s.start()
        refs["a_port"] = a.port
        try:
            seeder.seed(mi, NS)
            await asyncio.wait_for(
                asyncio.gather(b.download(NS, mi.digest),
                               a.download(NS, mi.digest)),
                30,
            )
            assert bstore.read_cache_file(mi.digest) == blob
        finally:
            for s in (seeder, a, b):
                await s.stop()

    asyncio.run(main())


def test_blacklisted_peer_gossiped_back_stays_banned(tmp_path):
    """The connstate blacklist outranks gossip: a banned peer's addr
    arriving in a PEX frame must not produce a dial, while a clean addr
    in the same frame does."""
    async def main():
        blob = os.urandom(20_000)
        mi = make_metainfo(blob)
        s, _ = _mk_sched(tmp_path, "s", _ScriptedClient(mi, lambda: []))
        await s.start()
        try:
            task = asyncio.create_task(s.download(NS, mi.digest))
            await asyncio.sleep(0.2)  # control exists, no peers to dial
            h = mi.info_hash
            banned, clean, sender = pid(1), pid(2), pid(9)
            s.conn_state.blacklist.add(banned, h)
            s._on_pex(sender, h, {"a": [
                {"id": banned.hex, "ip": "127.0.0.1", "p": 1},
                {"id": clean.hex, "ip": "127.0.0.1", "p": 1},
            ], "d": []})
            pending = s.conn_state._pending.get(h, set())
            assert clean in pending
            assert banned not in pending
            task.cancel()
        finally:
            await s.stop()

    asyncio.run(main())


def test_pex_flood_gets_the_sender_banned(tmp_path):
    """p2p.pex.flood failpoint: a sender ignoring the send budget ships
    MAX_ENTRIES_PER_MESSAGE+1 entries; the receiver's ingest raises,
    the dispatcher's ban path blacklists the sender and closes the
    conn -- the addr-flood cannot balloon the dial queue."""
    async def main():
        blob = os.urandom(400_000)
        mi = make_metainfo(blob)
        seeder, _ = _mk_sched(
            tmp_path, "seeder", _ScriptedClient(mi, lambda: []),
            seed_blob=blob, pex=_fast_pex(),
        )
        l_client = _ScriptedClient(
            mi, lambda: [PeerInfo(seeder.peer_id, "127.0.0.1", seeder.port,
                                  origin=True)]
        )
        leecher, _ = _mk_sched(tmp_path, "leecher", l_client,
                               pex=_fast_pex())
        await seeder.start()
        await leecher.start()
        try:
            seeder.seed(mi, NS)
            task = asyncio.create_task(leecher.download(NS, mi.digest))
            # Wait for the conn, then arm the flood: the next gossip
            # tick from either side ships the oversized frame.
            deadline = asyncio.get_running_loop().time() + 10
            while not leecher.conn_state.num_active(mi.info_hash):
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            failpoints.FAILPOINTS.disarm_all()
            failpoints.allow()
            failpoints.FAILPOINTS.arm("p2p.pex.flood", "always")
            h = mi.info_hash
            deadline = asyncio.get_running_loop().time() + 15
            while not (
                leecher.conn_state.blacklist.blocked(seeder.peer_id, h)
                or seeder.conn_state.blacklist.blocked(leecher.peer_id, h)
            ):
                assert asyncio.get_running_loop().time() < deadline, (
                    "no side banned its flooding peer"
                )
                await asyncio.sleep(0.1)
            task.cancel()
        finally:
            failpoints.FAILPOINTS.disarm_all()
            failpoints.allow(False)
            await seeder.stop()
            await leecher.stop()

    asyncio.run(main())


def test_peercache_restart_redials_with_tracker_dark(tmp_path):
    """The restart leg of the outage story: an agent mid-pull flushes
    its peercache, restarts, and -- with every tracker RPC failing --
    re-fetches metainfo from the cache, redials the cached seeder, and
    completes bit-identically."""
    async def main():
        blob = os.urandom(150_000)
        mi = make_metainfo(blob)
        cache_path = str(tmp_path / "l" / "peercache.json")
        seeder, _ = _mk_sched(
            tmp_path, "seeder", _ScriptedClient(mi, lambda: []),
            seed_blob=blob,
        )
        await seeder.start()
        seeder.seed(mi, NS)

        class _DarkClient:
            async def get(self, namespace, d):
                raise ConnectionError("tracker outage")

            async def announce(self, d, h, namespace, complete):
                raise ConnectionError("tracker outage")

        try:
            # Incarnation 1: tracker alive, book holds the seeder, then
            # the node "crashes" MID-PULL -- the stop-path flush keeps
            # incomplete torrents (a completed pull would age out of the
            # cache by design; the store serves it after restart).
            l_client = _ScriptedClient(
                mi, lambda: [PeerInfo(seeder.peer_id, "127.0.0.1",
                                      seeder.port, origin=True)]
            )
            l1, _ = _mk_sched(tmp_path, "l1", l_client,
                              peercache_path=cache_path)
            await l1.start()
            dl = asyncio.create_task(l1.download(NS, mi.digest))
            h = mi.info_hash
            deadline = asyncio.get_running_loop().time() + 10
            while not l1._controls.get(h) or not l1._controls[h].known_peers.snapshot():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            dl.cancel()
            with pytest.raises(asyncio.CancelledError):
                await dl
            await l1.stop()
            assert os.path.exists(cache_path)

            # Incarnation 2: fresh store, tracker DARK. The peercache
            # serves metainfo AND the dial set.
            l2, l2store = _mk_sched(tmp_path, "l2", _DarkClient(),
                                    peercache_path=cache_path)
            await l2.start()
            await asyncio.wait_for(l2.download(NS, mi.digest), 20)
            assert l2store.read_cache_file(mi.digest) == blob
            await l2.stop()

            # Without a peercache the same dark-tracker pull fails
            # TYPED at the metainfo fetch (the pre-PEX contract).
            l3, _ = _mk_sched(tmp_path, "l3", _DarkClient())
            await l3.start()
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(l3.download(NS, mi.digest), 10)
            await l3.stop()
        finally:
            await seeder.stop()

    asyncio.run(main())


def test_reload_pex_swaps_knobs_live(tmp_path):
    async def main():
        blob = os.urandom(10_000)
        mi = make_metainfo(blob)
        s, _ = _mk_sched(tmp_path, "s", _ScriptedClient(mi, lambda: []))
        await s.start()
        try:
            s.reload_pex(PexConfig(enabled=False, send_enabled=False))
            assert s.pex_config.enabled is False
            assert s._pex.config.send_enabled is False
            # Receive path now drops gossip without dialing.
            task = asyncio.create_task(s.download(NS, mi.digest))
            await asyncio.sleep(0.2)
            h = mi.info_hash
            s._on_pex(pid(9), h, {"a": [
                {"id": pid(1).hex, "ip": "127.0.0.1", "p": 1},
            ], "d": []})
            assert not s.conn_state._pending.get(h, set())
            task.cancel()
        finally:
            await s.stop()

    asyncio.run(main())

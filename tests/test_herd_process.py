"""Tier-4 black-box test: real processes via the CLI, real HTTP + P2P.

The reference runs its herd in Docker (SURVEY.md SS4 tier 4); here each
component is a subprocess of ``python -m kraken_tpu.cli`` -- same process
isolation, no containers.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn(args: list[str]) -> tuple[subprocess.Popen, dict]:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kraken_tpu.cli", *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        cwd=REPO,
        env=env,
        text=True,
    )
    for line in proc.stdout:
        if line.startswith("READY "):
            return proc, json.loads(line[6:])
    raise RuntimeError(f"component died: {args}")


def test_process_herd_e2e(tmp_path):
    procs = []
    try:
        tracker, tinfo = spawn(["tracker"])
        procs.append(tracker)
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--tracker", tinfo["addr"]]
        )
        procs.append(origin)
        # Tracker needs the origin cluster for metainfo: restart tracker with
        # the origin address (processes are cheap).
        tracker.send_signal(signal.SIGTERM)
        tracker.wait(timeout=10)
        procs.remove(tracker)
        tracker, tinfo2 = spawn(["tracker", "--port", tinfo["addr"].split(":")[1],
                                 "--origins", oinfo["addr"]])
        procs.append(tracker)
        agent, ainfo = spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo2["addr"]]
        )
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.utils.httputil import HTTPClient

            blob = os.urandom(300_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"])
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=60)
            got = await http.get(
                f"http://{ainfo['addr']}/namespace/ns/blobs/{d.hex}"
            )
            await oc.close()
            await http.close()
            assert got == blob

        asyncio.run(drive())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_process_herd_full_five_components(tmp_path):
    """All five reference binaries as CLI processes: push an image via the
    proxy's docker-v2 API, pull it by tag via the agent's registry API."""
    procs = []
    try:
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin")]
        )
        procs.append(origin)
        tracker, tinfo = spawn(["tracker", "--origins", oinfo["addr"]])
        procs.append(tracker)
        # Restart the origin pointed at the tracker (fixed port known now).
        origin.send_signal(signal.SIGTERM)
        origin.wait(timeout=10)
        procs.remove(origin)
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--port", oinfo["addr"].split(":")[1],
             "--tracker", tinfo["addr"]]
        )
        procs.append(origin)
        bi, binfo = spawn(
            ["build-index", "--store", str(tmp_path / "bi"),
             "--origins", oinfo["addr"]]
        )
        procs.append(bi)
        proxy, pinfo = spawn(
            ["proxy", "--origins", oinfo["addr"],
             "--build-index", binfo["addr"]]
        )
        procs.append(proxy)
        agent, ainfo = spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo["addr"],
             "--registry-port", "0", "--build-index", binfo["addr"]]
        )
        procs.append(agent)
        registry_addr = ainfo.get("registry_addr")
        assert registry_addr, "agent did not report a registry endpoint"

        async def drive():
            from kraken_tpu.utils.httputil import HTTPClient
            from test_registry import make_image, push_image, pull_image

            http = HTTPClient(timeout_seconds=60)
            config, layers, manifest = make_image(nlayers=2)
            await push_image(
                http, pinfo["addr"], "library/app", "v1",
                config, layers, manifest,
            )
            got_manifest, got_blobs = await pull_image(
                http, registry_addr, "library/app", "v1"
            )
            assert got_manifest == manifest
            assert set(got_blobs.values()) == {config, *layers}
            await http.close()

        asyncio.run(drive())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

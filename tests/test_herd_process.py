"""Tier-4 black-box test: real processes via the CLI, real HTTP + P2P.

The reference runs its herd in Docker (SURVEY.md SS4 tier 4); here each
component is a subprocess of ``python -m kraken_tpu.cli`` -- same process
isolation, no containers.
"""

import asyncio
import contextlib
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@contextlib.contextmanager
def herd():
    """Owns spawned component processes; SIGTERM + wait (SIGKILL fallback)
    on exit."""
    procs: list[subprocess.Popen] = []
    try:
        yield procs
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def spawn(args: list[str], stderr=subprocess.DEVNULL) -> tuple[subprocess.Popen, dict]:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kraken_tpu.cli", *args],
        stdout=subprocess.PIPE,
        stderr=stderr,
        cwd=REPO,
        env=env,
        text=True,
    )
    for line in proc.stdout:
        if line.startswith("READY "):
            return proc, json.loads(line[6:])
    raise RuntimeError(f"component died: {args}")


def spawn_tracker_and_origin(tmp_path, procs):
    """Tracker + origin with the circular-config dance: the tracker needs
    the origin cluster for metainfo, so it is respawned (same port) once
    the origin's address is known. Returns (tinfo, oinfo)."""
    tracker, tinfo = spawn(["tracker"])
    procs.append(tracker)
    origin, oinfo = spawn(
        ["origin", "--store", str(tmp_path / "origin"),
         "--tracker", tinfo["addr"]]
    )
    procs.append(origin)
    tracker.send_signal(signal.SIGTERM)
    tracker.wait(timeout=10)
    procs.remove(tracker)
    tracker, tinfo = spawn(["tracker", "--port", tinfo["addr"].split(":")[1],
                            "--origins", oinfo["addr"]])
    procs.append(tracker)
    return tinfo, oinfo


def test_process_herd_e2e(tmp_path):
    with herd() as procs:
        tinfo2, oinfo = spawn_tracker_and_origin(tmp_path, procs)
        agent, ainfo = spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo2["addr"]]
        )
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.utils.httputil import HTTPClient

            blob = os.urandom(300_000)
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"])
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=60)
            got = await http.get(
                f"http://{ainfo['addr']}/namespace/ns/blobs/{d.hex}"
            )
            await oc.close()
            await http.close()
            assert got == blob

        asyncio.run(drive())


def test_process_herd_full_five_components(tmp_path):
    """All five reference binaries as CLI processes: push an image via the
    proxy's docker-v2 API, pull it by tag via the agent's registry API."""
    with herd() as procs:
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin")]
        )
        procs.append(origin)
        tracker, tinfo = spawn(["tracker", "--origins", oinfo["addr"]])
        procs.append(tracker)
        # Restart the origin pointed at the tracker (fixed port known now).
        origin.send_signal(signal.SIGTERM)
        origin.wait(timeout=10)
        procs.remove(origin)
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--port", oinfo["addr"].split(":")[1],
             "--tracker", tinfo["addr"]]
        )
        procs.append(origin)
        bi, binfo = spawn(
            ["build-index", "--store", str(tmp_path / "bi"),
             "--origins", oinfo["addr"]]
        )
        procs.append(bi)
        proxy, pinfo = spawn(
            ["proxy", "--origins", oinfo["addr"],
             "--build-index", binfo["addr"]]
        )
        procs.append(proxy)
        agent, ainfo = spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo["addr"],
             "--registry-port", "0", "--build-index", binfo["addr"]]
        )
        procs.append(agent)
        registry_addr = ainfo.get("registry_addr")
        assert registry_addr, "agent did not report a registry endpoint"

        async def drive():
            from kraken_tpu.utils.httputil import HTTPClient
            from test_registry import make_image, push_image, pull_image

            http = HTTPClient(timeout_seconds=60)
            config, layers, manifest = make_image(nlayers=2)
            await push_image(
                http, pinfo["addr"], "library/app", "v1",
                config, layers, manifest,
            )
            got_manifest, got_blobs = await pull_image(
                http, registry_addr, "library/app", "v1"
            )
            assert got_manifest == manifest
            assert set(got_blobs.values()) == {config, *layers}
            await http.close()

        asyncio.run(drive())


def test_shipped_development_configs_boot(tmp_path):
    """The shipped config/ tree loads (extends-layering included) and the
    development overlays boot real processes."""
    from kraken_tpu.configutil import load_config

    # Every shipped file parses and layers.
    for path in sorted(pathlib.Path(REPO, "config").rglob("*.yaml")):
        cfg = load_config(str(path))
        # Layering proof: every file (transitively) extends config/base.yaml,
        # so base-only keys must have merged in.
        assert cfg.get("host"), f"{path}: base.yaml did not merge"
        assert "cleanup" in cfg, f"{path}: base.yaml did not merge"

    dev = load_config(os.path.join(REPO, "config/origin/development.yaml"))
    # Overlay wins where set, base fills the rest (deep merge).
    assert dev["hasher"] == "cpu" and dev["p2p_port"] == 7611
    assert dev["cleanup"]["high_watermark_bytes"] == 1 << 30
    assert dev["cleanup"]["interval_seconds"] == 300  # from config/base.yaml

    with herd() as procs:
        tracker, tinfo = spawn(
            ["tracker", "--config", "config/tracker/development.yaml",
             "--port", "0"]
        )
        procs.append(tracker)
        origin, oinfo = spawn(
            ["origin", "--config", "config/origin/development.yaml",
             "--port", "0", "--p2p-port", "0",
             "--store", str(tmp_path / "o"), "--tracker", tinfo["addr"]]
        )
        procs.append(origin)
        agent, ainfo = spawn(
            ["agent", "--config", "config/agent/development.yaml",
             "--port", "0", "--p2p-port", "0",
             "--store", str(tmp_path / "a"), "--tracker", tinfo["addr"]]
        )
        procs.append(agent)
        assert oinfo["component"] == "origin" and ainfo["component"] == "agent"


def test_sighup_reloads_scheduler_config(tmp_path):
    """SIGHUP re-reads --config and applies the scheduler section live."""
    cfg_path = tmp_path / "agent.yaml"
    cfg_path.write_text("scheduler:\n  max_announce_rate: 50\n")
    err_path = tmp_path / "agent.stderr"
    with herd() as procs, open(err_path, "w") as err:
        agent, _info = spawn(
            ["agent", "--store", str(tmp_path / "a"),
             "--config", str(cfg_path)],
            stderr=err,
        )
        procs.append(agent)
        cfg_path.write_text("scheduler:\n  max_announce_rate: 5\n")
        agent.send_signal(signal.SIGHUP)
        deadline = time.time() + 15
        while time.time() < deadline:
            if "scheduler config reloaded" in err_path.read_text():
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                "reload log line never appeared: " + err_path.read_text()[-2000:]
            )


def test_proxy_crash_resumes_upload_session(tmp_path):
    """Durable proxy spools (--spool): SIGKILL the proxy mid-push,
    restart it on the same port + spool root, and the client resumes the
    SAME upload session (status probe shows the committed offset) and
    finishes the blob. Unknown sessions still answer the spec error."""
    with herd() as procs:
        origin, oinfo = spawn(
            ["origin", "--store", str(tmp_path / "origin")]
        )
        procs.append(origin)
        bi, binfo = spawn(
            ["build-index", "--store", str(tmp_path / "bi"),
             "--origins", oinfo["addr"]]
        )
        procs.append(bi)
        spool = str(tmp_path / "spool")
        proxy, pinfo = spawn(
            ["proxy", "--origins", oinfo["addr"],
             "--build-index", binfo["addr"], "--spool", spool]
        )
        procs.append(proxy)
        pport = pinfo["addr"].split(":")[1]

        async def drive():
            import aiohttp

            from kraken_tpu.core.digest import Digest

            blob = os.urandom(600_000)
            half = len(blob) // 2
            d = Digest.from_bytes(blob)
            base = f"http://{pinfo['addr']}"
            async with aiohttp.ClientSession() as http:
                async with http.post(
                    f"{base}/v2/library/app/blobs/uploads/"
                ) as r:
                    assert r.status == 202
                    loc = r.headers["Location"]
                async with http.patch(
                    f"{base}{loc}", data=blob[:half]
                ) as r:
                    assert r.status == 202

                # Crash the proxy mid-push (no graceful shutdown).
                proxy.kill()
                proxy.wait(timeout=10)
                procs.remove(proxy)
                proxy2, pinfo2 = spawn(
                    ["proxy", "--origins", oinfo["addr"],
                     "--build-index", binfo["addr"], "--spool", spool,
                     "--port", pport]
                )
                procs.append(proxy2)

                # Status probe: the recovered session reports the
                # committed offset.
                async with http.get(f"{base}{loc}") as r:
                    assert r.status == 204, await r.text()
                    assert r.headers["Range"] == f"0-{half - 1}"
                # Resume and finish.
                async with http.patch(
                    f"{base}{loc}", data=blob[half:]
                ) as r:
                    assert r.status == 202
                    assert r.headers["Range"] == f"0-{len(blob) - 1}"
                async with http.put(f"{base}{loc}?digest={d}") as r:
                    assert r.status == 201, await r.text()
                # The blob made it to the origin, byte-identical.
                async with http.get(
                    f"{base}/v2/library/app/blobs/{d}"
                ) as r:
                    assert r.status == 200
                    assert await r.read() == blob
                # A session that never existed answers the spec code.
                async with http.patch(
                    f"{base}/v2/library/app/blobs/uploads/nope", data=b"x"
                ) as r:
                    assert r.status == 404
                    body = json.loads(await r.text())
                    assert body["errors"][0]["code"] == "BLOB_UPLOAD_UNKNOWN"

        asyncio.run(drive())


def test_all_trackers_sigkilled_mid_pull_pex_carries_the_swarm(tmp_path):
    """ISSUE-18 acceptance chaos scenario: a REAL 3-tracker fleet (CLI
    subprocesses) fronting an origin and two agents; every tracker is
    SIGKILLed mid-pull. The in-flight pull must complete bit-identically
    (the data plane + PEX gossip owe the tracker nothing), the outage
    latch must engage, a fresh agent process must re-join the swarm from
    its disk peercache + gossip with every tracker still dark, and when
    the trackers restart announces resume and the latch clears on its
    own."""
    import socket

    import yaml

    def free_ports(n):
        socks = [socket.socket() for _ in range(n)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        return ports

    ns = "pexherd"
    with herd() as procs:

        async def drive():
            from kraken_tpu.assembly import AgentNode, OriginNode
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.origin.metainfogen import PieceLengthConfig
            from kraken_tpu.p2p.scheduler import SchedulerConfig
            from kraken_tpu.placement.healthcheck import PassiveFilter
            from kraken_tpu.utils.httputil import HTTPClient

            ports = free_ports(3)
            fleet = ",".join(f"127.0.0.1:{p}" for p in ports)
            origin = OriginNode(
                store_root=str(tmp_path / "origin"), tracker_addr=fleet,
                piece_lengths=PieceLengthConfig(table=((0, 65536),)),
            )
            await origin.start()

            def spawn_trackers():
                out = []
                for p in ports:
                    t, _ = spawn([
                        "tracker", "--port", str(p),
                        "--origins", origin.addr,
                        "--fleet", fleet, "--self-addr", f"127.0.0.1:{p}",
                    ])
                    procs.append(t)
                    out.append(t)
                return out

            trackers = await asyncio.to_thread(spawn_trackers)

            def fast_breakers(node):
                # Default tracker breakers cool down for 30 s -- fine in
                # production, glacial in CI. The cooldown must still
                # EXCEED the ~1 s announce cadence or the breakers cool
                # off between walks and "all open-and-cooling" (the
                # latch condition) never holds.
                node._tracker_client.health = PassiveFilter(
                    fail_threshold=2, cooldown_seconds=5.0,
                    max_cooldown_seconds=8.0,
                )

            def mk_agent(name):
                return AgentNode(
                    store_root=str(tmp_path / name), tracker_addr=fleet,
                    scheduler_config=SchedulerConfig(
                        announce_interval_seconds=0.4,
                        retry_tick_seconds=0.3,
                        dial_timeout_seconds=2.0,
                    ),
                    pex={"interval_seconds": 1.0, "jitter": 0.0,
                         "dial_rate": 100.0, "dial_burst": 100.0},
                    # Throttled so the tracker massacre lands MID-pull.
                    p2p_bandwidth={"ingress_bps": 250_000, "egress_bps": 0},
                )

            agent1 = mk_agent("agent1")
            await agent1.start()
            fast_breakers(agent1)
            agent2 = mk_agent("agent2")
            await agent2.start()
            fast_breakers(agent2)
            http = HTTPClient(timeout_seconds=120.0)
            try:
                blob = os.urandom(1_200_000)
                d = Digest.from_bytes(blob)
                oc = BlobClient(origin.addr)
                await oc.upload(ns, d, blob, chunk_size=400_000)
                await oc.close()

                async def pull(agent):
                    return await http.get(
                        f"http://{agent.addr}/namespace/{ns}/blobs/{d.hex}"
                    )

                pull1 = asyncio.create_task(pull(agent1))
                pull2 = asyncio.create_task(pull(agent2))
                # Both pulls engaged: metainfo fetched, peers dialing,
                # agent2's peer book non-empty (that book is what the
                # peercache persists).
                deadline = asyncio.get_running_loop().time() + 20
                while True:
                    assert asyncio.get_running_loop().time() < deadline
                    assert not pull1.done() and not pull2.done()
                    ctls = list(agent2.scheduler._controls.values())
                    if ctls and ctls[0].known_peers.snapshot():
                        break
                    await asyncio.sleep(0.05)

                # Agent2 "crashes" mid-pull (its stop-path peercache
                # flush is the same doc the periodic flusher writes).
                pull2.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await pull2
                await agent2.stop()
                assert os.path.exists(
                    str(tmp_path / "agent2" / "peercache.json")
                )

                # THE massacre: every tracker SIGKILLed, no drain.
                for t in trackers:
                    t.kill()
                for t in trackers:
                    t.wait(timeout=10)
                    procs.remove(t)

                # The in-flight pull completes bit-identically.
                got = await asyncio.wait_for(pull1, timeout=90)
                assert got == blob

                # The outage latch engages (all breakers open) on the
                # agent that keeps announcing into the dark.
                deadline = asyncio.get_running_loop().time() + 30
                while not agent1._tracker_client.outage:
                    assert asyncio.get_running_loop().time() < deadline, (
                        "outage latch never engaged"
                    )
                    await asyncio.sleep(0.2)

                # Fresh agent process, same store, every tracker still
                # dark: metainfo + dial set come from the disk peercache,
                # gossip with the live swarm does the rest.
                agent2b = mk_agent("agent2")
                await agent2b.start()
                fast_breakers(agent2b)
                try:
                    got2 = await asyncio.wait_for(pull(agent2b), timeout=90)
                    assert got2 == blob
                finally:
                    await agent2b.stop()

                # Trackers return on the SAME addresses: announces
                # resume (the post-cooldown walk is the probe) and the
                # latch clears without intervention.
                trackers2 = await asyncio.to_thread(spawn_trackers)
                assert len(trackers2) == 3
                deadline = asyncio.get_running_loop().time() + 60
                while agent1._tracker_client.outage:
                    assert asyncio.get_running_loop().time() < deadline, (
                        "outage latch never cleared after tracker restart"
                    )
                    await asyncio.sleep(0.2)
            finally:
                await http.close()
                await agent1.stop()
                await origin.stop()

        asyncio.run(drive())


def test_scrub_and_locate_tools(tmp_path):
    """Operator tools: `scrub` re-hashes every cached blob (exit 1 +
    corrupt-event line on bit rot), `locate` answers ring placement
    offline with the production rendezvous code."""
    import hashlib

    from kraken_tpu.core.digest import Digest
    from kraken_tpu.store import CAStore

    store = CAStore(str(tmp_path / "s"))
    blobs = [os.urandom(10_000) for _ in range(3)]
    for b in blobs:
        store.create_cache_file(Digest.from_bytes(b), iter([b]))

    def run(*cli_args):
        return subprocess.run(
            [sys.executable, "-m", "kraken_tpu.cli", *cli_args],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
        )

    r = run("scrub", "--store", str(tmp_path / "s"))
    assert r.returncode == 0, r.stderr
    done = json.loads(r.stdout.strip().splitlines()[-1])
    assert done == {"event": "scrub_done", "checked": 3, "corrupt": 0}

    # Flip one byte of one cached blob: scrub must name it and exit 1.
    victim = Digest.from_bytes(blobs[0])
    path = store.cache_path(victim)
    raw = bytearray(open(path, "rb").read())
    raw[1234] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    r = run("scrub", "--store", str(tmp_path / "s"))
    assert r.returncode == 1
    events = [json.loads(ln) for ln in r.stdout.strip().splitlines()]
    assert {"event": "corrupt", "digest": victim.hex,
            "actual": Digest.from_bytes(bytes(raw)).hex} in events
    assert events[-1]["corrupt"] == 1

    # locate agrees with an in-process Ring over the same members.
    from kraken_tpu.placement import HostList, Ring

    addrs = ["a:1", "b:2", "c:3", "d:4"]
    r = run("locate", "--cluster", ",".join(addrs),
            "--digest", victim.hex, "--max-replica", "2")
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    ring = Ring(HostList(static=addrs), max_replica=2)
    assert out["replicas"] == ring.locations(victim)
    assert len(out["replicas"]) == 2


def test_testfs_process_serves_origin_backend(tmp_path):
    """tools/bin/testfs parity: the fake backend as a standalone process,
    with an origin's `testfs` backend entry pointed at it -- writeback
    lands there, and a locally-evicted blob restores from it."""
    import asyncio as aio

    from kraken_tpu.backend import Manager as BackendManager
    from kraken_tpu.assembly import OriginNode
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.client import BlobClient

    with herd() as procs:
        tfs, info = spawn(["testfs"])
        procs.append(tfs)

        async def drive():
            backends = BackendManager([{
                "namespace": ".*", "backend": "testfs",
                "config": {"addr": info["addr"]},
            }])
            origin = OriginNode(
                store_root=str(tmp_path / "o"), backends=backends,
                dedup=False,
            )
            await origin.start()
            oc = BlobClient(origin.addr)
            try:
                blob = os.urandom(64_000)
                d = Digest.from_bytes(blob)
                await oc.upload("ns", d, blob)
                for _ in range(50):
                    await origin.retry.run_once()
                    be = backends.get_client("ns")
                    try:
                        if await be.download("ns", d.hex) == blob:
                            break
                    except Exception:  # kt-lint: disable=bare-except  # poll-until-written: not-found / conn errors ARE the waiting state; the loop times out loudly below
                        pass
                    await aio.sleep(0.05)
                else:
                    raise AssertionError("writeback to testfs never landed")
                origin.store.delete_cache_file(d)
                await origin.refresher.refresh("ns", d)
                assert origin.store.read_cache_file(d) == blob
            finally:
                await oc.close()
                await origin.stop()

        asyncio.run(drive())


def test_agent_kill9_resumes_from_persisted_bitfield(tmp_path):
    """Round-5 durability story, end to end with REAL processes: SIGKILL
    an agent mid-download (ingress-capped so the pull is slow enough to
    catch), restart it on the same store, and the pull completes by
    RESUMING from the debounced piece-status sidecar -- proven by the
    reborn process verifying strictly fewer pieces than the blob has."""
    import yaml

    agent_store = tmp_path / "agent"
    cfg_path = tmp_path / "agent.yaml"
    cfg_path.write_text(yaml.safe_dump({
        "p2p_bandwidth": {"ingress_bps": 10_000_000},  # ~10 MB/s pull
    }))

    with herd() as procs:
        tinfo, oinfo = spawn_tracker_and_origin(tmp_path, procs)

        def spawn_agent():
            return spawn(
                ["agent", "--store", str(agent_store),
                 "--tracker", tinfo["addr"], "--config", str(cfg_path)]
            )

        agent, ainfo = spawn_agent()
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.store import CAStore, PieceStatusMetadata
            from kraken_tpu.utils.httputil import HTTPClient

            blob = os.urandom(48 << 20)  # 12 pieces at the 4 MiB default
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"])
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=120)

            async def pull(addr):
                return await http.get(
                    f"http://{addr}/namespace/ns/blobs/{d.hex}"
                )

            first = asyncio.create_task(pull(ainfo["addr"]))
            # Wait until the agent PERSISTED some progress (the debounced
            # sidecar on the shared filesystem), then SIGKILL it.
            store_view = CAStore(str(agent_store))
            persisted = 0
            for _ in range(600):
                await asyncio.sleep(0.05)
                if first.done():
                    # A fast failure must surface ITS exception, not a
                    # misleading no-progress assertion 30s later.
                    raise AssertionError(
                        f"pull ended before the kill: {first.exception()!r}"
                    )
                md = store_view.get_metadata(d, PieceStatusMetadata)
                # >= 2 keeps a margin between the resume bound below
                # (verified <= 12 - persisted) and a full re-download
                # (12), so one racing debounce flush can't blur the two.
                if md is not None and 2 <= md.count() < 10:
                    persisted = md.count()
                    break
            assert persisted >= 2, "never saw persisted partial progress"
            agent.kill()  # SIGKILL: no drain, no final flush
            agent.wait(timeout=10)
            procs.remove(agent)
            with contextlib.suppress(Exception):
                await first

            # Reborn process, same store: the pull must complete...
            agent2, ainfo2 = spawn_agent()
            procs.append(agent2)
            got = await pull(ainfo2["addr"])
            assert got == blob
            # ...by RESUME: the reborn agent verified only the missing
            # pieces (persisted ones never re-crossed the wire).
            metrics = (await http.get(
                f"http://{ainfo2['addr']}/metrics"
            )).decode()
            verified = 0.0
            for line in metrics.splitlines():
                if line.startswith("verify_pieces_total"):
                    verified += float(line.rsplit(" ", 1)[1])
            assert 0 < verified <= 12 - persisted, (
                f"expected resume (<= {12 - persisted} pieces "
                f"re-verified), saw {verified}"
            )
            await oc.close()
            await http.close()

        asyncio.run(drive())

"""The multi-core DOWNLOAD plane (p2p/shardpool.py leech mode): worker
shards pumping active-download conns, the shared-memory piece ring, and
the parent-side verify-then-write verdict loop.

What must hold, per docs/OPERATIONS.md "Leech workers":

- a pull pumped through a leech worker is BIT-IDENTICAL to the blob
  (the bytes travel worker recv -> shared ring -> parent batched verify
  -> worker pwrite, and only verdicts cross the fork boundary);
- every ring slot leased for a piece payload is returned -- happy path,
  corrupt-ban path, and worker-crash path all drain to zero;
- a mid-recv disconnect (failpoint ``p2p.shard.leech.disconnect``) only
  costs a requeue: the piece lands from a healthy peer, no ban;
- a corrupt piece received BY A WORKER (failpoint
  ``p2p.shard.leech.corrupt``) fails the PARENT's batched verify and
  escalates to the parent blacklist exactly like main-loop corruption
  -- and the corrupt bytes never land in the blob;
- SIGKILL of a leech worker respawns the shard, requeues its conns'
  outstanding requests WITHOUT blacklisting anyone (worker death is our
  fault, not the peer's), and leaks no fds or worker processes.
"""

from __future__ import annotations

import asyncio
import os
import signal

import numpy as np
import pytest

from kraken_tpu.utils import failpoints
from kraken_tpu.utils.metrics import REGISTRY

from tests.test_shardpool import (
    NS,
    FakeTracker,
    _metainfo,
    _poll,
    make_sched,
)


def _leech_counter(name: str, shards: int = 8) -> float:
    c = REGISTRY.counter(name)
    return sum(c.value(shard=f"leech_shard{i}") for i in range(shards))


def _make_swarm(tmp_path, tracker, blob, piece_len, *, origins=1,
                leech_workers=1):
    mi = _metainfo(blob, piece_len)
    tracker.metainfos[mi.digest.hex] = mi
    seeds = []
    for i in range(origins):
        o, _ = make_sched(
            tmp_path, f"origin{i}", tracker, seed_blobs=[blob]
        )
        seeds.append(o)
    agent, astore = make_sched(
        tmp_path, "agent", tracker, leech_workers=leech_workers
    )
    return mi, seeds, agent, astore


async def _assert_leases_drained(pool):
    await _poll(
        lambda: pool.slot_leases == 0,
        msg=f"{pool.slot_leases} ring slot leases never returned",
    )


def test_leech_worker_pull_bit_identical_and_leases_returned(tmp_path):
    async def run():
        blob = np.random.default_rng(11).integers(
            0, 256, size=4 << 20, dtype=np.uint8
        ).tobytes()
        tracker = FakeTracker()
        mi, seeds, agent, astore = _make_swarm(
            tmp_path, tracker, blob, 256 << 10
        )
        d = mi.digest
        verify0 = REGISTRY.counter("verify_batches_total").value(path="host")
        pieces0 = _leech_counter("data_plane_worker_pieces_total")
        await seeds[0].start()
        try:
            seeds[0].seed(mi, NS)
            await agent.start()
            try:
                pool = agent._leech_pool
                assert pool is not None and pool.alive_workers == 1
                await asyncio.wait_for(agent.download(NS, d), 60)
                # The conn genuinely went through the worker shard.
                assert pool.num_conns >= 1, "conn never handed to shard"
                await _assert_leases_drained(pool)
                info = pool.worker_info()
                assert len(info) == 1 and info[0]["alive"]
                pids = [w["pid"] for w in info]
                # Verify ran through BatchedVerifier (batch observability
                # rides the same flushes).
                assert (
                    REGISTRY.counter("verify_batches_total").value(path="host")
                    > verify0
                )
                # Worker stats land on a 0.25 s cadence -- poll for the
                # ring-landing counter.
                await _poll(
                    lambda: _leech_counter("data_plane_worker_pieces_total")
                    > pieces0,
                    msg="no pieces counted through the leech shard",
                )
            finally:
                await agent.stop()
            with await asyncio.to_thread(open, astore.cache_path(d), "rb") as f:
                got = await asyncio.to_thread(f.read)
            assert got == blob, "leech-worker pull not bit-identical"
            assert agent._leech_pool is None
        finally:
            await seeds[0].stop()
        # Every shard reaped at stop -- no orphaned pumps.
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)

    asyncio.run(run())


def test_mid_recv_disconnect_requeues_to_healthy_peer(tmp_path):
    """Chaos: the worker's recv pump loses the conn mid-piece. The
    partial slot is freed, the request requeues, and the piece lands
    from a healthy peer -- a connectivity blip, not a ban."""

    async def run():
        blob = np.random.default_rng(12).integers(
            0, 256, size=2 << 20, dtype=np.uint8
        ).tobytes()
        tracker = FakeTracker()
        # Armed BEFORE anything starts: the forked leech shard inherits
        # the registry (the failpoint plane's worker story).
        failpoints.FAILPOINTS.arm("p2p.shard.leech.disconnect", "once")
        mi, seeds, agent, astore = _make_swarm(
            tmp_path, tracker, blob, 128 << 10, origins=2
        )
        d = mi.digest
        for o in seeds:
            await o.start()
            o.seed(mi, NS)
        try:
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, d), 60)
                pool = agent._leech_pool
                await _assert_leases_drained(pool)
                # Connectivity, not misbehavior: neither seeder may
                # carry a HARD offense over the drop (soft cool-off
                # entries keep offense count 0).
                for o in seeds:
                    entry = agent.conn_state.blacklist._entries.get(
                        (o.peer_id, mi.info_hash)
                    )
                    assert entry is None or entry[1] == 0, (
                        "mid-recv disconnect hard-banned a healthy peer"
                    )
            finally:
                await agent.stop()
            with await asyncio.to_thread(open, astore.cache_path(d), "rb") as f:
                assert await asyncio.to_thread(f.read) == blob
        finally:
            for o in seeds:
                await o.stop()
            failpoints.FAILPOINTS.disarm("p2p.shard.leech.disconnect")

    asyncio.run(run())


def test_corrupt_piece_in_worker_escalates_parent_blacklist(tmp_path):
    """A piece that lands corrupt through a worker's ring slot fails
    the PARENT's batched verify; the verdict must travel the same
    misbehavior road as a main-loop corrupt piece: hard blacklist,
    requeue, and -- the crash-resume invariant -- the corrupt bytes
    never reach the blob (verify-then-write)."""

    async def run():
        blob = np.random.default_rng(13).integers(
            0, 256, size=2 << 20, dtype=np.uint8
        ).tobytes()
        tracker = FakeTracker()
        failpoints.FAILPOINTS.arm("p2p.shard.leech.corrupt", "once")
        mi, seeds, agent, astore = _make_swarm(
            tmp_path, tracker, blob, 128 << 10, origins=2
        )
        d = mi.digest
        for o in seeds:
            await o.start()
            o.seed(mi, NS)
        try:
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, d), 60)
                # Exactly the peer that fed us the flipped bit is banned.
                banned = [
                    o for o in seeds
                    if agent.conn_state.blacklist.blocked(
                        o.peer_id, mi.info_hash
                    )
                ]
                assert len(banned) == 1, (
                    f"corrupt verdict banned {len(banned)} peers, want 1"
                )
                await _assert_leases_drained(agent._leech_pool)
            finally:
                await agent.stop()
            # Bit-identical = the corrupt payload was never pwritten.
            with await asyncio.to_thread(open, astore.cache_path(d), "rb") as f:
                assert await asyncio.to_thread(f.read) == blob
        finally:
            for o in seeds:
                await o.stop()
            failpoints.FAILPOINTS.disarm("p2p.shard.leech.corrupt")

    asyncio.run(run())


def test_leech_worker_sigkill_respawns_and_requeues(tmp_path):
    """Crash-shape chaos: SIGKILL the pump mid-life. The supervisor
    respawns the shard, the dead worker's conns close as OUR fault (no
    blacklist -- the peer did nothing), in-flight requests requeue, and
    a subsequent pull runs through the respawned worker. Zero leaked
    slots, zero orphaned processes."""

    async def run():
        rng = np.random.default_rng(14)
        blob1 = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
        blob2 = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
        tracker = FakeTracker()
        mi1 = _metainfo(blob1, 128 << 10)
        tracker.metainfos[mi1.digest.hex] = mi1
        mi2 = _metainfo(blob2, 128 << 10)
        tracker.metainfos[mi2.digest.hex] = mi2
        # Both blobs seeded up front -- the second pull exercises the
        # RESPAWNED shard.
        origin, _ostore = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob1, blob2]
        )
        agent, astore = make_sched(
            tmp_path, "agent", tracker, leech_workers=1
        )
        await origin.start()
        try:
            origin.seed(mi1, NS)
            await agent.start()
            try:
                pool = agent._leech_pool
                crashes0 = _leech_counter("data_plane_worker_crashes_total")
                await asyncio.wait_for(agent.download(NS, mi1.digest), 60)
                # The handed-off conn idles in the shard (churn not yet
                # due) -- kill the pump under it.
                assert pool.num_conns >= 1
                pid0 = pool.worker_info()[0]["pid"]
                os.kill(pid0, signal.SIGKILL)
                await _poll(
                    lambda: pool.alive_workers == 1
                    and pool.worker_info()[0]["pid"] != pid0,
                    msg="killed leech shard never respawned",
                )
                assert (
                    _leech_counter("data_plane_worker_crashes_total")
                    > crashes0
                )
                # Worker death is our fault: nobody got blacklisted.
                assert not agent.conn_state.blacklist.blocked(
                    origin.peer_id, mi1.info_hash
                ), "worker crash blamed on an innocent peer"
                await _assert_leases_drained(pool)
                # The fleet keeps pulling: the second blob runs through
                # the RESPAWNED shard end to end.
                origin.seed(mi2, NS)
                await asyncio.wait_for(agent.download(NS, mi2.digest), 60)
                await _assert_leases_drained(pool)
                pids = [w["pid"] for w in pool.worker_info()]
            finally:
                await agent.stop()
            with await asyncio.to_thread(
                open, astore.cache_path(mi2.digest), "rb"
            ) as f:
                assert await asyncio.to_thread(f.read) == blob2, (
                    "post-respawn pull differs"
                )
        finally:
            await origin.stop()
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)

    asyncio.run(run())


def test_leech_pool_skips_shaped_and_oversize(tmp_path):
    """The handoff classifier's negative gates: ingress-shaped nodes
    and pieces larger than a ring slot stay on the main loop (and the
    pull still completes there)."""

    async def run():
        from kraken_tpu.utils.bandwidth import BandwidthLimiter

        blob = np.random.default_rng(15).integers(
            0, 256, size=1 << 20, dtype=np.uint8
        ).tobytes()
        tracker = FakeTracker()
        mi, seeds, agent, astore = _make_swarm(
            tmp_path, tracker, blob, 128 << 10
        )
        d = mi.digest
        # Shaped agent: leech pool configured AND running, but the token
        # bucket is in-process state -- conns must stay on the loop.
        shaped, sstore = make_sched(
            tmp_path, "shaped", tracker, leech_workers=1,
            bandwidth=BandwidthLimiter(ingress_bps=1 << 30),
        )
        await seeds[0].start()
        try:
            seeds[0].seed(mi, NS)
            await shaped.start()
            try:
                await asyncio.wait_for(shaped.download(NS, d), 60)
                assert shaped._leech_pool.num_conns == 0, (
                    "shaped node handed a conn to the leech plane"
                )
            finally:
                await shaped.stop()
            with await asyncio.to_thread(open, sstore.cache_path(d), "rb") as f:
                assert await asyncio.to_thread(f.read) == blob
        finally:
            await seeds[0].stop()

    asyncio.run(run())

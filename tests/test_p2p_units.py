"""Unit tests for P2P building blocks: wire framing, connstate/blacklist,
piece request policies, batched verifier, torrent storage. SURVEY.md SS4
tier 1."""

import asyncio
import os

import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import InfoHash, MetaInfo
from kraken_tpu.core.peer import PeerID
from kraken_tpu.p2p.connstate import ConnState, ConnStateConfig
from kraken_tpu.p2p.piecerequest import RequestManager
from kraken_tpu.p2p.storage import (
    AgentTorrentArchive,
    BatchedVerifier,
    OriginTorrentArchive,
    PieceError,
)
from kraken_tpu.p2p.wire import Message, MsgType, WireError, recv_message, send_message
from kraken_tpu.store import CAStore, PieceStatusMetadata


def make_metainfo(blob: bytes, piece_length: int = 1024) -> MetaInfo:
    hashes = get_hasher("cpu").hash_pieces(blob, piece_length)
    return MetaInfo(Digest.from_bytes(blob), len(blob), piece_length, hashes.tobytes())


def pid(i: int) -> PeerID:
    return PeerID((bytes([i]) * 20).hex())


def ih(i: int) -> InfoHash:
    return InfoHash((bytes([i]) * 32).hex())


# -- wire -------------------------------------------------------------------

def test_wire_roundtrip_all_types():
    async def main():
        server_got = []

        async def handler(reader, writer):
            try:
                while True:
                    server_got.append(await recv_message(reader))
            except WireError:
                writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)

        msgs = [
            Message.handshake("ab" * 20, "cd" * 32, "ef" * 32, "ns", b"\xff\x01", 10),
            Message.bitfield(b"\x0f", 4),
            Message.piece_request(7),
            Message.piece_payload(7, os.urandom(5000)),
            Message.announce_piece(7),
            Message.cancel_piece(3),
            Message.complete(),
            Message.error("busy", "try later"),
        ]
        for m in msgs:
            await send_message(writer, m)
        await asyncio.sleep(0.1)
        writer.close()
        server.close()
        await server.wait_closed()

        assert [m.type for m in server_got] == [m.type for m in msgs]
        for sent, got in zip(msgs, server_got):
            assert got.header == sent.header
            assert got.payload == sent.payload

    asyncio.run(main())


def test_wire_rejects_unknown_type_and_oversize():
    async def main():
        async def handler(reader, writer):
            writer.write(bytes([99]) + (0).to_bytes(4, "big") + (0).to_bytes(4, "big"))
            await writer.drain()
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        with pytest.raises(WireError):
            await recv_message(reader)
        writer.close()
        server.close()
        await server.wait_closed()

    asyncio.run(main())


# -- connstate --------------------------------------------------------------

def test_connstate_per_torrent_limit():
    cs = ConnState(ConnStateConfig(max_open_conns_per_torrent=2))
    h = ih(1)
    assert cs.add_pending(pid(1), h)
    assert cs.add_pending(pid(2), h)
    assert not cs.add_pending(pid(3), h)  # at limit
    assert cs.promote(pid(1), h)
    cs.remove(pid(2), h)
    assert cs.add_pending(pid(3), h)  # freed a slot


def test_connstate_no_duplicate_dials():
    cs = ConnState()
    h = ih(1)
    assert cs.add_pending(pid(1), h)
    assert not cs.add_pending(pid(1), h)
    cs.promote(pid(1), h)
    assert not cs.add_pending(pid(1), h)


def test_connstate_global_limit():
    cs = ConnState(ConnStateConfig(max_global_conns=2, max_open_conns_per_torrent=5))
    assert cs.add_pending(pid(1), ih(1))
    assert cs.add_pending(pid(2), ih(2))
    assert not cs.add_pending(pid(3), ih(3))


def test_blacklist_backoff_expiry():
    from kraken_tpu.utils.backoff import Backoff

    cfg = ConnStateConfig()
    cfg.blacklist_backoff = Backoff(base_seconds=10, factor=2, max_seconds=100, jitter=0)
    cs = ConnState(cfg)
    h = ih(1)
    cs.blacklist.add(pid(1), h, now=0.0)
    assert cs.blacklist.blocked(pid(1), h, now=5.0)
    assert not cs.blacklist.blocked(pid(1), h, now=11.0)
    cs.blacklist.add(pid(1), h, now=11.0)  # repeat offense: 20s
    assert cs.blacklist.blocked(pid(1), h, now=25.0)
    assert not cs.blacklist.blocked(pid(1), h, now=32.0)
    assert not cs.can_dial(pid(2), h) is False  # unrelated peer unaffected


def test_blacklist_bounded_under_torrent_churn():
    """Fleet-survival regression (found by the soak harness's leak
    audit): blacklist entries must not accumulate forever on a node
    churning torrents -- long-expired verdicts expunge on an amortized
    sweep, and a removed torrent's rows go with it."""
    from kraken_tpu.utils.backoff import Backoff

    cfg = ConnStateConfig()
    cfg.blacklist_backoff = Backoff(
        base_seconds=1, factor=2, max_seconds=10, jitter=0
    )
    cs = ConnState(cfg)
    bl = cs.blacklist

    def ihx(i: int) -> InfoHash:
        return InfoHash(f"{i:064x}")

    # Thousands of distinct (peer, torrent) bans land early, then the
    # node keeps running: once adds continue far past their expiry (and
    # the escalation grace), the amortized sweep must reclaim the old
    # verdicts instead of retaining every (peer, torrent) pair forever.
    for i in range(2000):
        bl.add(pid(i % 50), ihx(i), now=float(i) * 0.001)
    assert len(bl._entries) == 2000  # nothing expired yet: all kept
    for i in range(bl._EXPUNGE_EVERY + 1):  # guarantees one sweep fires
        bl.add(pid(i % 50), ihx(10_000 + i), now=10_000.0)
    assert len(bl._entries) <= 2 * bl._EXPUNGE_EVERY

    # Verdicts SURVIVE clear_torrent: an evicted blob re-pulled later
    # has the same info_hash, and a corrupt peer's escalation must
    # greet the re-pull instead of resetting every eviction cycle.
    h, h2 = ihx(12345), ihx(12346)
    bl.add(pid(1), h, now=10_000.0)
    bl.add(pid(1), h2, now=10_000.0)
    cs.clear_torrent(h)
    assert bl.blocked(pid(1), h, now=10_000.5)
    assert bl.blocked(pid(1), h2, now=10_000.5)

    # Recent (within the escalation grace) entries survive the sweep,
    # so a repeat offender still escalates.
    bl2 = ConnState(cfg).blacklist
    bl2.add(pid(1), ih(1), now=0.0)  # expires at 1.0
    for i in range(bl2._EXPUNGE_EVERY + 1):
        bl2.add(pid(2), ih(2), now=5.0)  # sweep runs at now=5
    assert (pid(1), ih(1)) in bl2._entries  # 4 s past expiry < 20 s grace
    bl2.add(pid(1), ih(1), now=5.0)
    assert bl2._entries[(pid(1), ih(1))][1] == 2  # escalated, not reset


# -- piecerequest -----------------------------------------------------------

def test_request_manager_pipeline_and_dedup():
    rm = RequestManager(policy="rarest_first", pipeline_limit=2)
    missing = [0, 1, 2, 3]
    avail = {0: 3, 1: 1, 2: 2, 3: 1}
    got = rm.select(pid(1), {0, 1, 2, 3}, missing, avail, now=0.0)
    assert len(got) == 2
    assert set(got) == {1, 3}  # the two rarest
    # Same peer at pipeline limit: nothing more.
    assert rm.select(pid(1), {0, 1, 2, 3}, missing, avail, now=0.0) == []
    # Other peer must not duplicate in-flight requests (no endgame yet).
    got2 = rm.select(pid(2), {0, 1, 2, 3}, missing, avail, now=0.0)
    assert set(got2) == {0, 2}


def test_request_manager_timeout_requeues():
    rm = RequestManager(pipeline_limit=4, timeout_seconds=5)
    rm.select(pid(1), {0}, [0], {}, now=0.0)
    # A FRESH in-flight request is not duplicated (deep pipelines make
    # "everything in flight" the normal state, not endgame).
    assert rm.select(pid(2), {0}, [0], {}, now=1.0) == []
    # Once the request goes stale (> timeout/4), a bounded rescue
    # duplicate to another peer is allowed.
    assert rm.select(pid(2), {0}, [0], {}, now=2.0) == [0]
    # after timeout both expire; fresh request allowed again
    assert rm.select(pid(1), {0}, [0], {}, now=20.0) == [0]


def test_request_manager_adaptive_hard_expiry_under_storm():
    """The hard expiry is a FLOOR raised by observed service times: under
    a re-request storm (saturated seeder, honest-but-slow completions)
    in-flight requests must NOT expire at the configured timeout -- that
    feedback loop re-requests live work and collapses goodput -- but the
    adaptive cutoff stays capped at 10x the timeout so a truly dead peer
    cannot park a piece forever."""
    rm = RequestManager(pipeline_limit=4, timeout_seconds=2.0)
    # Load regime: twenty completions each taking ~10 s drive the EWMA
    # to ~10 s (>> the 2 s configured timeout).
    for i in range(20):
        rm.mark_sent(i, pid(1), now=float(i))
        rm.clear_piece(i, now=float(i) + 10.0)
    # cutoff = max(timeout, min(8 * ewma, 10 * timeout)) = 20 s here.
    rm.mark_sent(100, pid(2), now=100.0)
    # Past the base timeout (2 s): still pending -- NOT expired.
    assert rm.pending_for(pid(2), now=104.0) == [100]
    # Just under the 10x-timeout ceiling: still pending.
    assert rm.pending_for(pid(2), now=119.5) == [100]
    # Past the ceiling: expired, the piece is requestable again.
    assert rm.pending_for(pid(2), now=121.0) == []
    assert rm.select(pid(3), {100}, [100], {}, now=121.0) == [100]


def test_request_manager_endgame_duplicates():
    rm = RequestManager(pipeline_limit=4)  # timeout 8 -> stale after 2
    assert rm.select(pid(1), {0, 1}, [0, 1], {}, now=0.0) == [0, 1] or True
    assert rm.select(pid(2), {0, 1}, [0, 1], {}, now=0.0) == []  # fresh
    got = rm.select(pid(2), {0, 1}, [0, 1], {}, now=3.0)
    assert set(got) <= {0, 1} and got  # stale: rescue duplicates allowed
    # Duplication is bounded per piece: a third peer gets nothing.
    assert rm.select(pid(3), {0, 1}, [0, 1], {}, now=3.5) == []

    rm.clear_piece(0)
    assert 0 in rm.select(pid(3), {0}, [0], {}, now=3.5)


# -- batched verifier -------------------------------------------------------

def test_batched_verifier_correct_and_batches():
    async def main():
        import hashlib

        v = BatchedVerifier(max_delay_seconds=0.01)
        pieces = [os.urandom(500) for _ in range(20)]
        oks = await asyncio.gather(
            *(v.verify(p, hashlib.sha256(p).digest()) for p in pieces)
        )
        assert all(oks)
        bad = await v.verify(b"data", hashlib.sha256(b"other").digest())
        assert bad is False

    asyncio.run(main())


# -- torrent storage --------------------------------------------------------

def test_agent_torrent_lifecycle(tmp_path):
    async def main():
        blob = os.urandom(10_000)
        mi = make_metainfo(blob)
        store = CAStore(str(tmp_path / "s"))
        archive = AgentTorrentArchive(store, BatchedVerifier(max_delay_seconds=0.001))
        t = archive.create_torrent(mi)
        assert not t.complete()
        assert t.missing_pieces() == list(range(mi.num_pieces))

        # wrong-length and corrupt pieces rejected
        with pytest.raises(PieceError):
            await t.write_piece(0, b"short")
        with pytest.raises(PieceError):
            await t.write_piece(0, os.urandom(mi.piece_length_of(0)))

        done = False
        for i in range(mi.num_pieces):
            done = await t.write_piece(
                i, blob[i * mi.piece_length : (i + 1) * mi.piece_length]
            )
        assert done and t.complete()
        assert store.read_cache_file(mi.digest) == blob
        # bitfield metadata cleaned up on completion
        assert store.get_metadata(mi.digest, PieceStatusMetadata) is None
        # re-creating yields a complete seeding torrent
        t2 = archive.create_torrent(mi)
        assert t2.complete()
        assert t2.read_piece(0) == blob[: mi.piece_length]

    asyncio.run(main())


def test_origin_archive_requires_blob(tmp_path):
    blob = os.urandom(5000)
    mi = make_metainfo(blob)
    store = CAStore(str(tmp_path / "s"))
    archive = OriginTorrentArchive(store, BatchedVerifier())
    with pytest.raises(KeyError):
        archive.create_torrent(mi)
    store.create_cache_file(mi.digest, iter([blob]))
    t = archive.create_torrent(mi)
    assert t.complete()
    assert t.bitfield() and t.read_piece(mi.num_pieces - 1)


def test_scheduler_config_from_dict_and_reload():
    """YAML `scheduler:` section builds a config (nested conn_state,
    unknown keys rejected); Scheduler.reload applies limits live."""

    from kraken_tpu.p2p.scheduler import SchedulerConfig

    cfg = SchedulerConfig.from_dict({
        "max_announce_rate": 7.0,
        "piece_pipeline_limit": 4,
        "conn_state": {"max_open_conns_per_torrent": 3, "max_global_conns": 9},
    })
    assert cfg.max_announce_rate == 7.0
    assert cfg.conn_state.max_open_conns_per_torrent == 3

    with pytest.raises(ValueError):
        SchedulerConfig.from_dict({"nope": 1})
    with pytest.raises(ValueError):
        SchedulerConfig.from_dict({"conn_state": {"nope": 1}})

    # reload swaps config + conn limits on a live ConnState.
    state = ConnState(SchedulerConfig().conn_state)

    from kraken_tpu.p2p.scheduler import Scheduler

    from kraken_tpu.utils.bufpool import BufferPool

    sched = Scheduler.__new__(Scheduler)  # no IO: just the reload surface
    sched.config = SchedulerConfig()
    sched.conn_state = state
    sched._bufpool = BufferPool()
    sched.reload(cfg)
    assert sched.config.piece_pipeline_limit == 4
    assert state.config.max_global_conns == 9
    assert state.blacklist._config is cfg.conn_state

    # Nested backoff dict coerces at load time, not first use.
    c2 = SchedulerConfig.from_dict(
        {"conn_state": {"blacklist_backoff": {"base_seconds": 10.0}}}
    )
    assert c2.conn_state.blacklist_backoff.delay(0) > 0


def test_wire_fuzz_corrupt_frames_raise_wireerror():
    """Arbitrary bytes on the wire must surface as WireError (the conn
    plane's one failure type), never as msgpack/struct internals escaping
    to the dispatcher."""
    import numpy as np

    rng = np.random.default_rng(11)

    async def feed(raw: bytes):
        reader = asyncio.StreamReader()
        reader.feed_data(raw)
        reader.feed_eof()
        return await recv_message(reader)

    async def main():
        # 1) pure noise, many lengths
        for n in (0, 1, 8, 9, 64, 4096):
            for _ in range(50):
                raw = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
                try:
                    await feed(raw)
                except WireError:
                    pass  # the only acceptable failure
        # 2) bit-flipped valid frames
        valid = []

        class Sink:
            def __init__(self):
                self.buf = bytearray()
            def write(self, b):
                self.buf += b
            def writelines(self, bufs):
                for b in bufs:
                    self.buf += b
            async def drain(self):
                pass

        for msg in (
            Message.handshake("ab" * 20, "cd" * 32, "ef" * 32, "ns", b"\x01", 8),
            Message.piece_payload(3, b"x" * 100),
            Message.error("busy", "full"),
        ):
            sink = Sink()
            await send_message(sink, msg)
            valid.append(bytes(sink.buf))
        for raw in valid:
            got = await feed(raw)  # sanity: clean round trip
            assert isinstance(got, Message)
            for _ in range(200):
                b = bytearray(raw)
                i = int(rng.integers(0, len(b)))
                b[i] ^= int(rng.integers(1, 256))
                try:
                    await feed(bytes(b))
                except WireError:
                    pass

    asyncio.run(main())


# -- dispatcher admission & churn (ADVICE r3 regressions) -------------------

class _FakeConn:
    """Just enough Conn surface for Dispatcher unit tests."""

    def __init__(self, peer_id: PeerID):
        self.peer_id = peer_id
        self.sent = []
        self.closed = False

    async def send(self, msg):
        self.sent.append(msg)

    def close(self):
        self.closed = True


def _seeding_torrent(tmp_path, blob: bytes):
    mi = make_metainfo(blob)
    store = CAStore(str(tmp_path / "s"))
    store.create_cache_file(mi.digest, iter([blob]))
    return OriginTorrentArchive(store, BatchedVerifier()).create_torrent(mi)


def test_serve_flood_bound_holds_for_buffered_bursts(tmp_path):
    """A burst of PIECE_REQUESTs handled back-to-back WITHOUT yielding to
    the event loop (how already-buffered frames arrive off conn.recv())
    must still respect _MAX_SERVING_PER_PEER: admission accounting is
    synchronous, not deferred to when the spawned task first runs."""

    async def main():
        from kraken_tpu.p2p.dispatch import Dispatcher, _Peer

        t = _seeding_torrent(tmp_path, os.urandom(4096))
        d = Dispatcher(t)
        conn = _FakeConn(pid(1))
        peer = _Peer(conn, set(), asyncio.get_running_loop().time())
        d._peers[conn.peer_id] = peer
        for _ in range(200):
            await d._handle(peer, Message.piece_request(0))
        assert peer.serving == Dispatcher._MAX_SERVING_PER_PEER
        for _ in range(100):
            if not peer.serving:
                break
            await asyncio.sleep(0.01)
        assert peer.serving == 0  # done-callbacks released every slot
        assert len(conn.sent) == Dispatcher._MAX_SERVING_PER_PEER
        d.close()

    asyncio.run(main())


def test_idle_churn_exempts_active_transfers(tmp_path):
    """tick() must not drop a conn that is mid-serve (serving > 0) or that
    we have outstanding piece requests to: slow links generate no new
    inbound messages for the whole transfer. But the exemption is bounded
    (10x churn_idle) so a peer that stops reading its socket can't pin a
    conn slot forever."""

    async def main():
        from kraken_tpu.p2p.dispatch import Dispatcher, _Peer

        t = _seeding_torrent(tmp_path, os.urandom(4096))
        d = Dispatcher(t, churn_idle_seconds=2.0)  # cap at 20 s idle
        now = asyncio.get_running_loop().time()
        idle, serving, awaited, stuck = (_FakeConn(pid(i)) for i in (1, 2, 3, 4))
        for conn in (idle, serving, awaited):
            d._peers[conn.peer_id] = _Peer(conn, set(), now - 10.0)
        d._peers[serving.peer_id].serving = 1
        d.requests.mark_sent(0, awaited.peer_id)
        # Mid-serve but idle beyond the cap: a zero-window hostile peer.
        d._peers[stuck.peer_id] = _Peer(stuck, set(), now - 25.0)
        d._peers[stuck.peer_id].serving = 1
        await d.tick()
        assert idle.peer_id not in d._peers  # plain idle: churned
        assert serving.peer_id in d._peers  # mid-serve: kept
        assert awaited.peer_id in d._peers  # awaiting payload: kept
        assert stuck.peer_id not in d._peers  # exemption capped: churned
        d.close()

    asyncio.run(main())


def test_idle_churn_caps_request_pending_exemption(tmp_path):
    """The request-pending exemption has the same 10x churn_idle bound as
    the serving one: a peer we requested from that then goes fully
    silent (no payload, no announce) must lose its conn slot at the cap
    even while its request is still formally in flight."""

    async def main():
        from kraken_tpu.p2p.dispatch import Dispatcher, _Peer

        t = _seeding_torrent(tmp_path, os.urandom(4096))
        # Long request timeout: the pending request must still be live at
        # the churn cap, so the cap (not request expiry) is what drops it.
        d = Dispatcher(
            t, requests=RequestManager(timeout_seconds=60.0),
            churn_idle_seconds=2.0,  # cap at 20 s idle
        )
        now = asyncio.get_running_loop().time()
        slow, dead = _FakeConn(pid(1)), _FakeConn(pid(2))
        d._peers[slow.peer_id] = _Peer(slow, set(), now - 10.0)
        d.requests.mark_sent(0, slow.peer_id, now=now - 10.0)
        d._peers[dead.peer_id] = _Peer(dead, set(), now - 25.0)
        d.requests.mark_sent(1, dead.peer_id, now=now - 25.0)
        await d.tick()
        assert slow.peer_id in d._peers  # within the cap: exempt
        assert dead.peer_id not in d._peers  # past 10x churn_idle: dropped
        # Its in-flight request was released with the peer, so the piece
        # is immediately re-requestable elsewhere.
        assert d.requests.pending_for(dead.peer_id) == []
        d.close()

    asyncio.run(main())


def test_duplicate_final_piece_is_benign(tmp_path):
    """Endgame duplication can deliver the completing piece twice,
    concurrently. The loser must see a duplicate arrival (False), never an
    exception -- an exception hard-blacklists an innocent peer."""

    async def main():
        blob = os.urandom(3000)
        mi = make_metainfo(blob)
        store = CAStore(str(tmp_path / "s"))
        archive = AgentTorrentArchive(
            store, BatchedVerifier(max_delay_seconds=0.001)
        )
        t = archive.create_torrent(mi)
        pl = mi.piece_length
        for i in range(mi.num_pieces - 1):
            await t.write_piece(i, blob[i * pl : (i + 1) * pl])
        last = mi.num_pieces - 1
        data = blob[last * pl :]
        r1, r2 = await asyncio.gather(
            t.write_piece(last, data), t.write_piece(last, data)
        )
        assert sorted([r1, r2]) == [False, True]
        assert t.complete()
        # A third copy landing after completion is also a no-op.
        assert await t.write_piece(last, data) is False

    asyncio.run(main())


def test_verify_burst_does_not_stall_loop():
    """The batched hash runs off the event loop: during a 100-piece verify
    burst (~25 MB of SHA-256, ~100+ ms of CPU) a concurrently-ticking task
    must never observe a loop stall > 50 ms.

    Retried up to 3 attempts: on a loaded single-core box the OS can
    schedule the (correctly off-loop) hashing thread over the loop
    thread for >50 ms -- scheduler noise, not an on-loop hash. The
    discriminating power survives the retries because a genuinely
    ON-loop hash stalls DETERMINISTICALLY on every attempt (the batch's
    ~100+ ms of hashing happens inside one callback)."""

    async def attempt() -> float:
        import hashlib

        v = BatchedVerifier(max_delay_seconds=0.001)
        pieces = [os.urandom(256 * 1024) for _ in range(100)]
        digests = [hashlib.sha256(p).digest() for p in pieces]

        loop = asyncio.get_running_loop()
        stop = loop.create_future()
        max_stall = 0.0

        async def ticker():
            nonlocal max_stall
            last = loop.time()
            while not stop.done():
                await asyncio.sleep(0.005)
                now = loop.time()
                max_stall = max(max_stall, now - last - 0.005)
                last = now

        t = asyncio.create_task(ticker())
        await asyncio.sleep(0)  # let the ticker establish its baseline
        oks = await asyncio.gather(
            *(v.verify(p, d) for p, d in zip(pieces, digests))
        )
        stop.set_result(None)
        await t
        assert all(oks)
        return max_stall

    stalls = []
    for _ in range(3):
        stall = asyncio.run(attempt())
        stalls.append(stall)
        if stall < 0.05:
            return
    raise AssertionError(
        "event loop stalled on every attempt: "
        + ", ".join(f"{s * 1e3:.0f} ms" for s in stalls)
    )


def test_p2p_bandwidth_cap_shapes_transfer(tmp_path):
    """A seeder-side egress cap must bound swarm goodput: 1 MiB through a
    ~1 MiB/s limiter cannot finish in well under a second (uncapped, this
    rig moves it in <100 ms). Wired exactly as the CLI does -- the
    scheduler's shared BandwidthLimiter shaping every conn."""
    from kraken_tpu.utils.bandwidth import BandwidthLimiter
    from tests.test_swarm import (
        FakeTracker, NS, make_metainfo, make_peer, start_all, stop_all,
    )

    async def main():
        blob = os.urandom(1024 * 1024)
        mi = make_metainfo(blob, piece_length=16 * 1024)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        seeder, _ = make_peer(tmp_path, "seeder", tracker, seed_blob=blob)
        # Cap AFTER construction (make_peer has no knob): same object the
        # assembly nodes pass.
        seeder.bandwidth = BandwidthLimiter(
            egress_bps=1_000_000, burst=64 * 1024
        )
        leecher, lstore = make_peer(tmp_path, "leecher", tracker)
        await start_all(seeder, leecher)
        try:
            seeder.seed(mi, NS)
            t0 = asyncio.get_running_loop().time()
            await asyncio.wait_for(leecher.download(NS, mi.digest), 30)
            wall = asyncio.get_running_loop().time() - t0
            assert lstore.read_cache_file(mi.digest) == blob
            assert wall > 0.6, f"cap not applied: 1 MiB in {wall:.3f}s"
        finally:
            await stop_all(seeder, leecher)

    asyncio.run(main())


def test_piece_status_ignores_padding_bits():
    """A corrupt sidecar with stray padding bits in the last byte must not
    make complete() lie: only bits < num_pieces count."""
    # 9 pieces -> 2 bytes; pieces 0-7 set plus a stray padding bit (bit 7
    # of byte 1, piece index 15 which does not exist).
    raw = PieceStatusMetadata(9)
    md = PieceStatusMetadata(9, bytearray([0xFF, 0x80]))
    assert md.count() == 8
    assert not md.complete()
    assert not md.has(8)
    assert raw.count() == 0


def test_torrent_close_refuses_new_io_and_is_idempotent(tmp_path):
    """After close(), piece IO raises PieceError (typed peer failure, not
    EBADF/fd-reuse corruption) and close() can run again safely."""
    import numpy as np


    blob = bytes(np.random.default_rng(0).integers(0, 256, 8192, np.uint8))
    d = Digest.from_bytes(blob)
    store = CAStore(str(tmp_path / "s"))
    store.create_cache_file(d, iter([blob]))
    hashes = get_hasher("cpu").hash_pieces(blob, 4096)
    mi = MetaInfo(d, len(blob), 4096, hashes.tobytes())
    t = OriginTorrentArchive(store, BatchedVerifier()).create_torrent(mi)
    assert t.read_piece(0) == blob[:4096]
    t.close()
    t.close()  # idempotent
    with pytest.raises(PieceError):
        t.read_piece(1)


def test_torrent_close_flushes_bitfield_off_loop(tmp_path):
    """Torrent.close() with a dirty bitfield: the final sidecar flush must
    run OFF the event loop (in fsync mode it pays fsync+dirsync, and a
    sweep tearing down many torrents would stall every conn pump --
    VERDICT r5 weak #3), and still land. Without a loop it flushes
    synchronously."""
    import threading


    blob = os.urandom(8192)
    d = Digest.from_bytes(blob)
    hashes = get_hasher("cpu").hash_pieces(blob, 4096)
    mi = MetaInfo(d, len(blob), 4096, hashes.tobytes())

    async def main():
        store = CAStore(str(tmp_path / "s"))
        t = AgentTorrentArchive(store, BatchedVerifier()).create_torrent(mi)
        await t.write_piece(0, blob[:4096])  # marks bits dirty (debounced)
        loop_thread = threading.get_ident()
        flush_thread: list[int] = []
        orig = store.set_metadata

        def recording(d_, md):
            r = orig(d_, md)
            flush_thread.append(threading.get_ident())  # after the write lands
            return r

        store.set_metadata = recording
        t.close()
        # The flush was handed to the default executor; give it a tick.
        for _ in range(100):
            if flush_thread:
                break
            await asyncio.sleep(0.01)
        assert flush_thread and flush_thread[0] != loop_thread
        md = store.get_metadata(mi.digest, PieceStatusMetadata)
        assert md is not None and md.has(0)

    asyncio.run(main())

    # Sync context (no running loop): close() must flush inline.
    store2 = CAStore(str(tmp_path / "s2"))

    async def setup():
        t = AgentTorrentArchive(store2, BatchedVerifier()).create_torrent(mi)
        await t.write_piece(0, blob[:4096])
        return t

    t2 = asyncio.run(setup())
    t2._bits_dirty = True  # the loop is gone; close() below has no executor
    t2.close()
    md = store2.get_metadata(mi.digest, PieceStatusMetadata)
    assert md is not None and md.has(0)

"""Cleanup is LIVE in the node wiring, not just unit-tested logic.

VERDICT r2 weak #3: CleanupManager existed but nothing scheduled it and
touch() had no callers -- disks filled until crash. Now OriginNode and
AgentNode run periodic sweeps, every blob read feeds the eviction clock,
and eviction spares persist-marked blobs and drops evicted blobs from
the dedup index.
"""

import asyncio
import os

from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.origin.client import BlobClient
from kraken_tpu.store.cleanup import CleanupConfig, CleanupManager
from kraken_tpu.store.metadata import PersistMetadata


async def _wait_for(cond, timeout=10.0, interval=0.05, msg="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not cond():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(interval)


def test_origin_watermark_eviction_spares_pinned_and_recent(tmp_path):
    asyncio.run(_drive_origin_eviction(tmp_path))


async def _drive_origin_eviction(tmp_path):
    from aiohttp import ClientSession

    # Start with pressure OFF (huge watermark): the sweep loop runs from
    # the beginning, but eviction must not race the setup below.
    # tti_seconds=0 genuinely DISABLES idle eviction (a positive TTI
    # would race: the setup backdates every blob's mtime to the epoch,
    # so the 0.1 s sweep could idle-evict `recent` in the window between
    # the utime and the HTTP GET that re-touches it).
    node = OriginNode(
        store_root=str(tmp_path / "o"),
        cleanup=CleanupConfig(
            tti_seconds=0,  # no idle eviction in this test
            high_watermark_bytes=1 << 40,
            low_watermark_bytes=1 << 40,
            interval_seconds=0.1,
        ),
    )
    await node.start()
    oc = BlobClient(node.addr)
    try:
        blobs = [os.urandom(100_000) for _ in range(4)]
        digests = [Digest.from_bytes(b) for b in blobs]
        for b, d in zip(blobs, digests):
            await oc.upload("ns", d, b)
        assert node.cleanup is not None and node.server.cleanup is node.cleanup

        # Pin one blob (as a pending writeback would) and make another
        # recently-read via the HTTP GET path (exercises touch()).
        pinned, recent = digests[0], digests[1]
        node.store.set_metadata(pinned, PersistMetadata(True))
        # Age everything, then read `recent` to bump it.
        for d in digests:
            os.utime(node.store.cache_path(d), (1, 1))
        async with ClientSession() as http:
            async with http.get(
                f"http://{node.addr}/namespace/ns/blobs/{recent.hex}"
            ) as r:
                assert r.status == 200
                await r.read()

        # Now turn disk pressure ON; the scheduled loop must evict the two
        # aged, unpinned blobs (b2, b3) and stop at the low watermark,
        # sparing the pinned and the recently-read blob.
        node.cleanup.config = CleanupConfig(
            tti_seconds=0,
            high_watermark_bytes=350_000,
            low_watermark_bytes=250_000,
            interval_seconds=0.1,
        )
        await _wait_for(
            lambda: node.store.disk_usage_bytes() <= 250_000,
            msg="watermark eviction sweep",
        )
        assert node.store.in_cache(pinned), "persist-marked blob evicted"
        assert node.store.in_cache(recent), "recently-read blob evicted"
        evicted = [d for d in digests[2:] if not node.store.in_cache(d)]
        assert evicted, "LRU blobs were not evicted"

        # Evicted blobs also left the dedup index (on_evict wiring).
        indexed = node.dedup.stats()["blobs"]
        cached = sum(node.store.in_cache(d) for d in digests)
        assert indexed <= cached + 1  # ingest is async; never more than live+1
        for d in evicted:
            assert d.hex not in node.dedup._indexed
    finally:
        await oc.close()
        await node.stop()


def test_agent_schedules_cleanup(tmp_path):
    async def main():
        agent = AgentNode(
            store_root=str(tmp_path / "a"),
            tracker_addr="127.0.0.1:1",  # never contacted in this test
            cleanup=CleanupConfig(interval_seconds=0.05, tti_seconds=0.01),
        )
        await agent.start()
        try:
            assert agent._cleanup_task is not None
            assert agent.server.cleanup is agent.cleanup
            # An idle blob is swept by the TTI policy.
            data = os.urandom(10_000)
            d = Digest.from_bytes(data)
            uid = agent.store.create_upload()
            agent.store.write_upload_chunk(uid, 0, data)
            agent.store.commit_upload(uid, d)
            os.utime(agent.store.cache_path(d), (1, 1))
            await _wait_for(
                lambda: not agent.store.in_cache(d), msg="agent TTI sweep"
            )
        finally:
            await agent.stop()

    asyncio.run(main())


def test_delete_and_eviction_unseed(tmp_path):
    """A deleted or evicted blob leaves the swarm: the scheduler stops
    announcing and drops the torrent control (a seeder must not advertise
    bytes it can no longer serve)."""


    async def main():
        tracker = TrackerNode(announce_interval_seconds=0.1)
        await tracker.start()
        origin = OriginNode(
            store_root=str(tmp_path / "o"), tracker_addr=tracker.addr,
            cleanup=CleanupConfig(
                tti_seconds=0.0, interval_seconds=3600.0,
                high_watermark_bytes=1, low_watermark_bytes=0,
            ),
        )
        await origin.start()
        try:
            oc = BlobClient(origin.addr)
            blob_a, blob_b = os.urandom(60_000), os.urandom(60_000)
            da, db = Digest.from_bytes(blob_a), Digest.from_bytes(blob_b)
            await oc.upload("ns", da, blob_a)
            await oc.upload("ns", db, blob_b)
            assert len(origin.scheduler._controls) == 2

            # Explicit DELETE unseeds immediately.
            await oc.delete("ns", da)
            assert len(origin.scheduler._controls) == 1

            # Eviction sweep unseeds via on_evict (thread -> loop hop).
            evicted = await asyncio.to_thread(origin.cleanup.run_once)
            assert db in evicted
            for _ in range(50):
                if not origin.scheduler._controls:
                    break
                await asyncio.sleep(0.02)
            assert not origin.scheduler._controls
            await oc.close()
        finally:
            await origin.stop()
            await tracker.stop()

    asyncio.run(main())


def test_abandoned_upload_spool_ages_out(tmp_path):
    """An upload whose client died before commit leaves a spool file; the
    sweep removes it after upload_ttl_seconds while sparing fresh (live)
    uploads. Commit/abort files are untouched (already gone)."""
    import time

    from kraken_tpu.store import CAStore

    store = CAStore(str(tmp_path / "s"))
    dead = store.create_upload()
    store.write_upload_chunk(dead, 0, b"abandoned")
    live = store.create_upload()
    store.write_upload_chunk(live, 0, b"active")

    # Age only the dead one.
    old = time.time() - 7200
    os.utime(store.upload_path(dead), (old, old))

    mgr = CleanupManager(
        store, CleanupConfig(tti_seconds=0, upload_ttl_seconds=3600)
    )
    mgr.run_once()
    assert not store.upload_exists(dead)
    assert store.upload_exists(live)


def test_simulated_now_cannot_unlink_live_uploads(tmp_path):
    """run_once(now=...) exists for simulated TTI clocks, but spool ages
    come from REAL filesystem mtimes: the sweep must use wall clock for
    them, or a future-dated simulated now unlinks live uploads mid-stream
    (round-5 ADVICE)."""
    import time

    from kraken_tpu.store import CAStore

    store = CAStore(str(tmp_path / "s"))
    live = store.create_upload()
    store.write_upload_chunk(live, 0, b"mid-stream")

    mgr = CleanupManager(
        store, CleanupConfig(tti_seconds=0, upload_ttl_seconds=3600)
    )
    # Ten TTLs in the future on the injected clock; the spool file's real
    # mtime is NOW, so it must survive.
    mgr.run_once(now=time.time() + 10 * 3600)
    assert store.upload_exists(live)

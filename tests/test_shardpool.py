"""The multi-core seed-serve plane (p2p/shardpool.py): worker shards,
sendfile serves, and the control-plane contracts around them.

What must hold, per docs/OPERATIONS.md "Data-plane workers":

- a pull served through a worker shard is BIT-IDENTICAL to the blob
  (sendfile moves the same bytes the dispatcher path would);
- a mid-serve disconnect (failpoint ``p2p.shard.serve.disconnect``)
  only costs a reconnect -- the pull still finishes, bit-identical;
- evicting a blob mid-serve closes the shard's conns gracefully and the
  leecher requeues onto healthy peers;
- misbehavior observed BY A WORKER (garbage index) reaches the parent's
  blacklist exactly like main-loop misbehavior;
- lameduck drain lets a worker conn finish in-flight serves (SIGTERM
  semantics from the degradation plane survive the handoff);
- SIGHUP resize grows and shrinks the pool live; a killed shard is
  respawned and counted on ``data_plane_worker_crashes_total``.
"""

from __future__ import annotations

import asyncio
import os
import signal
import time

import numpy as np
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.connstate import ConnStateConfig
from kraken_tpu.p2p.scheduler import Scheduler, SchedulerConfig
from kraken_tpu.p2p.storage import (
    AgentTorrentArchive,
    BatchedVerifier,
    OriginTorrentArchive,
)
from kraken_tpu.p2p.wire import Message, MsgType, recv_message, send_message
from kraken_tpu.store import CAStore
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.metrics import REGISTRY

NS = "test-shard"


class FakeTracker:
    """In-process announce + metainfo shared by every scheduler."""

    def __init__(self, interval: float = 0.2):
        self.metainfos: dict[str, MetaInfo] = {}
        self.peers: dict[str, dict[str, PeerInfo]] = {}
        self.interval = interval

    def client_for(self, ref: dict):
        tracker = self

        class _Client:
            async def get(self, namespace, d):
                return tracker.metainfos[d.hex]

            async def announce(self, d, h, namespace, complete):
                sched = ref["s"]
                me = PeerInfo(
                    peer_id=sched.peer_id, ip=sched.ip, port=sched.port,
                    complete=complete,
                )
                swarm = tracker.peers.setdefault(h.hex, {})
                swarm[me.peer_id.hex] = me
                others = [
                    p for pid, p in swarm.items() if pid != me.peer_id.hex
                ]
                return others, tracker.interval

        return _Client()


def _metainfo(blob: bytes, piece_len: int) -> MetaInfo:
    hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
    return MetaInfo(Digest.from_bytes(blob), len(blob), piece_len,
                    hashes.tobytes())


def make_sched(root, name, tracker, *, seed_blobs=None, workers=0,
               leech_workers=0, bandwidth=None, churn_idle=4.0):
    store = CAStore(os.path.join(str(root), name))
    ref: dict = {}
    is_origin = seed_blobs is not None
    if is_origin:
        for blob in seed_blobs:
            d = Digest.from_bytes(blob)
            store.create_cache_file(d, iter([blob]))
        archive = OriginTorrentArchive(store, BatchedVerifier())
    else:
        archive = AgentTorrentArchive(store, BatchedVerifier())
    client = tracker.client_for(ref)
    sched = Scheduler(
        peer_id=PeerID(os.urandom(20).hex()),
        ip="127.0.0.1",
        port=0,
        archive=archive,
        metainfo_client=client,
        announce_client=client,
        is_origin=is_origin,
        bandwidth=bandwidth,
        config=SchedulerConfig(
            announce_interval_seconds=0.2,
            retry_tick_seconds=0.2,
            max_announce_rate=2000.0,
            data_plane_workers=workers,
            leech_workers=leech_workers,
            conn_churn_idle_seconds=churn_idle,
            conn_state=ConnStateConfig(
                max_open_conns_per_torrent=64 if is_origin else 10
            ),
        ),
    )
    ref["s"] = sched
    return sched, store


async def _poll(cond, timeout: float = 10.0, msg: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"condition never held: {msg}")


def _shard_counter(name: str, shards: int = 8) -> float:
    c = REGISTRY.counter(name)
    return sum(
        c.value(shard=f"data_plane_shard{i}") for i in range(shards)
    )


def test_worker_shard_serves_bit_identical_pull(tmp_path):
    async def run():
        blob = np.random.default_rng(1).integers(
            0, 256, size=4 << 20, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 256 << 10)
        d = mi.digest
        tracker = FakeTracker()
        tracker.metainfos[d.hex] = mi
        origin, _ostore = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=2
        )
        agent, astore = make_sched(tmp_path, "agent", tracker)
        handoffs0 = _shard_counter("data_plane_handoffs_total")
        await origin.start()
        try:
            origin.seed(mi, NS)
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, d), 60)
            finally:
                await agent.stop()
            with await asyncio.to_thread(open, astore.cache_path(d), "rb") as f:
                got = await asyncio.to_thread(f.read)
            assert got == blob, "worker-served pull not bit-identical"
            # The serve really went through a shard, not the main loop.
            assert _shard_counter("data_plane_handoffs_total") > handoffs0
            info = origin._shardpool.worker_info()
            assert len(info) == 2 and all(w["alive"] for w in info)
            pids = [w["pid"] for w in info]
        finally:
            await origin.stop()
        # Zero orphaned workers after stop -- every shard reaped.
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)
        assert origin._shardpool is None

    asyncio.run(run())


def test_mid_serve_disconnect_failpoint_recovers(tmp_path):
    """Chaos: a shard drops the conn mid-serve (remote crash shape).
    The leecher redials -- soft cool-off, not a ban -- and the pull
    finishes bit-identically through the respawned conn."""

    async def run():
        blob = np.random.default_rng(2).integers(
            0, 256, size=2 << 20, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 128 << 10)
        d = mi.digest
        tracker = FakeTracker()
        tracker.metainfos[d.hex] = mi
        # Armed BEFORE the origin starts: the forked shard inherits the
        # registry, which is the failpoint plane's worker story.
        failpoints.FAILPOINTS.arm("p2p.shard.serve.disconnect", "once")
        origin, _ = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=1
        )
        agent, astore = make_sched(tmp_path, "agent", tracker)
        await origin.start()
        try:
            origin.seed(mi, NS)
            await agent.start()
            try:
                await asyncio.wait_for(agent.download(NS, d), 60)
            finally:
                await agent.stop()
            with await asyncio.to_thread(open, astore.cache_path(d), "rb") as f:
                assert await asyncio.to_thread(f.read) == blob
        finally:
            await origin.stop()
            failpoints.FAILPOINTS.disarm("p2p.shard.serve.disconnect")

    asyncio.run(run())


def test_eviction_while_serving_requeues_to_healthy_peer(tmp_path):
    """The blob leaves the origin's store mid-pull: its shard conns
    close gracefully, and the leecher finishes from another seeder --
    close-and-requeue, not a wedged transfer."""

    async def run():
        from kraken_tpu.utils.bandwidth import BandwidthLimiter

        blob = np.random.default_rng(3).integers(
            0, 256, size=4 << 20, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 128 << 10)
        d = mi.digest
        tracker = FakeTracker()
        tracker.metainfos[d.hex] = mi
        origin, _ = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=1
        )
        seeder, _ = make_sched(tmp_path, "seeder", tracker)
        # Throttled leecher: the pull outlives the mid-flight eviction.
        leech, lstore = make_sched(
            tmp_path, "leech", tracker,
            bandwidth=BandwidthLimiter(ingress_bps=4 << 20),
        )
        await origin.start()
        try:
            origin.seed(mi, NS)
            await seeder.start()
            await leech.start()
            try:
                # A second full replica first, so eviction never strands
                # the swarm without a complete source.
                await asyncio.wait_for(seeder.download(NS, d), 60)
                pull = asyncio.create_task(leech.download(NS, d))
                # Wait until the origin's shard is actually serving.
                await _poll(
                    lambda: origin._shardpool.num_conns > 0,
                    msg="no shard conn formed",
                )
                assert origin.unseed(d), "origin was not seeding?"
                await asyncio.wait_for(pull, 90)
                # The evicted torrent's shard conns are gone.
                await _poll(
                    lambda: origin._shardpool.num_conns == 0,
                    msg="shard conns survived eviction",
                )
            finally:
                await leech.stop()
                await seeder.stop()
            with await asyncio.to_thread(open, lstore.cache_path(d), "rb") as f:
                assert await asyncio.to_thread(f.read) == blob
        finally:
            await origin.stop()

    asyncio.run(run())


async def _raw_handshake(origin: Scheduler, mi: MetaInfo,
                         peer_hex: str | None = None):
    """Dial the origin's p2p port as a hand-rolled leecher."""
    reader, writer = await asyncio.open_connection("127.0.0.1", origin.port)
    peer_hex = peer_hex or os.urandom(20).hex()
    bits = bytes((mi.num_pieces + 7) // 8)
    await send_message(
        writer,
        Message.handshake(
            peer_hex, mi.info_hash.hex, mi.digest.hex, NS, bits,
            mi.num_pieces,
        ),
    )
    theirs = await asyncio.wait_for(recv_message(reader), 10)
    assert theirs.type == MsgType.HANDSHAKE
    return reader, writer, peer_hex


async def _read_piece_payload(reader, expect_index: int, expect_len: int):
    while True:
        msg = await asyncio.wait_for(recv_message(reader), 15)
        if msg.type == MsgType.PIECE_PAYLOAD:
            assert msg.header["index"] == expect_index
            assert len(msg.payload) == expect_len
            return bytes(msg.payload)


def test_worker_misbehavior_verdict_reaches_parent_blacklist(tmp_path):
    async def run():
        blob = np.random.default_rng(4).integers(
            0, 256, size=512 << 10, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 128 << 10)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        origin, _ = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=1
        )
        await origin.start()
        try:
            origin.seed(mi, NS)
            reader, writer, peer_hex = await _raw_handshake(origin, mi)
            # Sanity: the shard serves an honest request first.
            await send_message(writer, Message.piece_request(0))
            data = await _read_piece_payload(reader, 0, 128 << 10)
            assert data == blob[: 128 << 10]
            # Now the violation: an out-of-range index.
            await send_message(writer, Message.piece_request(10**6))
            peer = PeerID(peer_hex)
            await _poll(
                lambda: origin.conn_state.blacklist.blocked(
                    peer, mi.info_hash
                ),
                msg="worker misbehavior verdict never reached the blacklist",
            )
            writer.close()
        finally:
            await origin.stop()

    asyncio.run(run())


def test_lameduck_drain_lets_worker_conn_finish(tmp_path):
    """PR-5 SIGTERM semantics through the handoff: a draining node
    refuses NEW conns but a shard's in-flight conn keeps serving, and
    the drain quiesce signal counts it until it closes."""

    async def run():
        from kraken_tpu.p2p.conn import PeerBusyError, handshake_outbound

        blob = np.random.default_rng(5).integers(
            0, 256, size=512 << 10, dtype=np.uint8
        ).tobytes()
        mi = _metainfo(blob, 128 << 10)
        tracker = FakeTracker()
        tracker.metainfos[mi.digest.hex] = mi
        origin, _ = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[blob], workers=1,
            churn_idle=1.0,
        )
        await origin.start()
        try:
            origin.seed(mi, NS)
            reader, writer, _ = await _raw_handshake(origin, mi)
            await send_message(writer, Message.piece_request(0))
            await _read_piece_payload(reader, 0, 128 << 10)
            assert origin.num_active_conns == 1  # counts the shard conn
            origin.enter_lameduck()
            # In-flight conn still serves through the drain...
            await send_message(writer, Message.piece_request(1))
            data = await _read_piece_payload(reader, 1, 128 << 10)
            assert data == blob[128 << 10 : 256 << 10]
            # ...while NEW conns get the polite busy frame.
            r2, w2 = await asyncio.open_connection("127.0.0.1", origin.port)
            with pytest.raises(PeerBusyError):
                await handshake_outbound(
                    r2, w2, PeerID(os.urandom(20).hex()), mi.info_hash,
                    mi.digest.hex, NS, bytes((mi.num_pieces + 7) // 8),
                    mi.num_pieces, timeout=5.0,
                )
            w2.close()
            writer.close()
            # The quiesce signal drains to zero once the conn closes.
            await _poll(
                lambda: origin.num_active_conns == 0,
                msg="drain quiesce signal never reached 0",
            )
        finally:
            await origin.stop()

    asyncio.run(run())


def test_reload_resizes_pool_and_crash_respawns(tmp_path):
    async def run():
        tracker = FakeTracker()
        origin, _ = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[b"x" * 1024], workers=1
        )
        await origin.start()
        try:
            pool = origin._shardpool
            assert pool.alive_workers == 1

            def cfg(workers: int) -> SchedulerConfig:
                return SchedulerConfig.from_dict(
                    {"data_plane_workers": workers}
                )

            # SIGHUP grow: a second shard spawns live.
            origin.reload(cfg(3))
            await _poll(lambda: pool.alive_workers == 3, msg="grow to 3")
            # SIGHUP shrink: retired shards drain out and exit.
            origin.reload(cfg(1))
            await _poll(
                lambda: pool.alive_workers == 1 and len(pool.worker_info()) == 1,
                msg="shrink to 1",
            )
            # Crash: SIGKILL the survivor; the supervisor counts it and
            # respawns the shard.
            crashes0 = _shard_counter("data_plane_worker_crashes_total")
            pid = pool.worker_info()[0]["pid"]
            os.kill(pid, signal.SIGKILL)
            await _poll(
                lambda: pool.alive_workers == 1
                and pool.worker_info()[0]["pid"] != pid,
                msg="crashed shard never respawned",
            )
            assert (
                _shard_counter("data_plane_worker_crashes_total") > crashes0
            )
        finally:
            await origin.stop()

    asyncio.run(run())


def test_sentinel_aggregates_workers_and_flags_dead_shard(tmp_path):
    """utils/resources.py with worker processes: child fd/RSS aggregate
    into the sample, and a dead shard is a breach -- never silence."""

    async def run():
        from kraken_tpu.utils.resources import (
            ResourceSentinel,
            ResourcesConfig,
        )

        tracker = FakeTracker()
        origin, ostore = make_sched(
            tmp_path, "origin", tracker, seed_blobs=[b"y" * 2048], workers=2
        )
        await origin.start()
        try:
            sentinel = ResourceSentinel(
                "origin-test",
                ResourcesConfig(interval_seconds=3600.0),
                scheduler=origin,
                store=ostore,
            )
            sample = await sentinel.sample()
            assert sample["workers_expected"] == 2
            assert sample["workers_alive"] == 2
            assert sample["worker_fds"] > 0, "child fds not aggregated"
            assert sample["worker_rss_bytes"] > 0, "child RSS not aggregated"
            # The headline gauges include the children.
            assert sample["open_fds"] > sample["worker_fds"]
            assert not sample["breached"]
            sentinel.stop()

            # Reap-check: a shard that died and was not (yet) respawned
            # must read as a BREACH. Deterministic via a stub pool -- the
            # real supervisor respawns too fast to race reliably.
            class _DeadShardPool:
                expected_workers = 2

                def worker_info(self):
                    return [
                        {"shard": 0, "pid": os.getpid(), "alive": True},
                        {"shard": 1, "pid": None, "alive": False},
                    ]

            class _Sched:
                _shardpool = _DeadShardPool()
                _bufpool = None
                num_active_conns = 0

            breaches = REGISTRY.counter("resource_budget_breaches_total")
            b0 = breaches.value(kind="workers")
            s2 = ResourceSentinel(
                "origin-test-dead", ResourcesConfig(), scheduler=_Sched()
            )
            sample2 = s2._finish_sample({})
            assert "workers" in sample2["breached"]
            assert sample2["workers_alive"] == 1
            assert breaches.value(kind="workers") == b0 + 1
            s2.stop()
        finally:
            await origin.stop()

    asyncio.run(run())

"""kraken-lint: the project-invariant analyzer, and THE tree gate.

Every rule gets a bad/good fixture pair (the bad fixture must produce
exactly the expected finding, the good one zero -- a rule that cannot
tell the two apart guards nothing), pragma enforcement is tested both
ways (reasoned pragma suppresses; reasonless does not and is itself a
finding), the CLI honors the 0/1/3 exit-code contract with a stable
JSON shape, and the final test pins the WHOLE tree -- kraken_tpu/ +
tests/ -- at zero findings. That last test is the point of the PR: the
five defect classes this repo kept re-fixing by hand are now
machine-checked on every run (docs/TESTING.md "Static analysis tier").

Fixture code lives in string literals: the analyzer reads real COMMENT
tokens for pragmas and walks real ASTs, so quoting bad code here cannot
trip the tree gate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kraken_tpu.lint import LintUsageError, lint_paths, run_lint_tool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, source: str, name: str = "mod.py"):
    """Write one fixture module and lint its directory."""
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    findings, _stats = lint_paths([str(tmp_path)])
    return findings


def _rules(findings) -> list[str]:
    return [f.rule for f in findings]


# -- per-rule bad/good pairs -------------------------------------------------


def test_blocking_io_in_async_bad_and_good(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        import asyncio, os, time

        async def handler(path, fd):
            time.sleep(0.1)
            with open(path) as f:
                data = f.read()
            os.fsync(fd)
            return data
    """)
    assert _rules(bad) == ["blocking-io-in-async"] * 3
    # fixture source starts with a newline: flagged lines are 5/6/8
    assert {f.line for f in bad} == {5, 6, 8}

    good = _lint_src(tmp_path / "good", """
        import asyncio, os, time

        def _read(path):
            with open(path) as f:  # sync frame: fine
                return f.read()

        async def handler(path, fd):
            data = await asyncio.to_thread(_read, path)
            await asyncio.to_thread(os.fsync, fd)
            await asyncio.sleep(0.1)
            return data
    """)
    assert good == []


def test_fire_and_forget_task_bad_and_good(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        import asyncio

        async def go(coro, loop):
            asyncio.create_task(coro)
            asyncio.ensure_future(coro)
            loop.create_task(coro)
    """)
    assert _rules(bad) == ["fire-and-forget-task"] * 3

    good = _lint_src(tmp_path / "good", """
        import asyncio

        async def go(coro, tasks, on_done):
            t = asyncio.create_task(coro)
            tasks.add(asyncio.create_task(coro))
            asyncio.create_task(coro).add_done_callback(on_done)
            await t
    """)
    assert good == []


def test_lock_across_await_bad_and_good(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        import asyncio

        class Plane:
            async def update(self):
                with self._lock:
                    snap = dict(self._state)
                    await self._publish(snap)
    """)
    assert _rules(bad) == ["lock-across-await"]

    good = _lint_src(tmp_path / "good", """
        import asyncio

        class Plane:
            async def update(self):
                with self._lock:
                    snap = dict(self._state)
                await self._publish(snap)

            async def aupdate(self):
                async with self._alock:
                    await self._publish(dict(self._state))
    """)
    assert good == []


def test_bare_except_bad_and_good(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        def f(x):
            try:
                return x()
            except:
                return None

        def g(x):
            try:
                return x()
            except Exception:
                pass
    """)
    assert _rules(bad) == ["bare-except"] * 2

    good = _lint_src(tmp_path / "good", """
        import logging

        def f(x):
            try:
                return x()
            except ValueError:
                return None

        def g(x, meter):
            try:
                return x()
            except Exception as e:
                meter.record("g", e)
            try:
                return x()
            except Exception:
                logging.getLogger("t").warning("x failed", exc_info=True)
    """)
    assert good == []


def test_local_import_shadowing_bad_and_good(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        import os

        def f():
            path = os.sep  # UnboundLocalError at runtime...
            import os      # ...because THIS makes os a local
            return os.path.join(path, "x")
    """)
    assert _rules(bad) == ["local-import-shadowing"]

    good = _lint_src(tmp_path / "good", """
        import os

        def f():
            import sys  # not module-level: fine (lazy import)
            return os.path.join(sys.prefix, "x")
    """)
    assert good == []


def test_wall_clock_in_sim_marker_and_sim_path(tmp_path):
    bad = _lint_src(tmp_path / "bad", """
        # kt-lint: sim-clocked
        import time

        def expire(entries, ttl):
            now = time.time()
            return [e for e in entries if e.ts + ttl > now]
    """)
    assert _rules(bad) == ["wall-clock-in-sim"]

    # The real sim module needs no marker: its path opts it in.
    sim = _lint_src(tmp_path / "simtree", """
        import time

        def tick():
            return time.monotonic()
    """, name="p2p/sim.py")
    assert _rules(sim) == ["wall-clock-in-sim"]

    good = _lint_src(tmp_path / "good", """
        # kt-lint: sim-clocked
        def expire(entries, ttl, now):
            return [e for e in entries if e.ts + ttl > now]
    """)
    assert good == []


def _project(tmp_path, *, docs: str, registry: str = "", extra: dict = ()):
    """Lay out a minimal project tree for the cross-file rules."""
    (tmp_path / "docs").mkdir(parents=True, exist_ok=True)
    (tmp_path / "docs" / "OPERATIONS.md").write_text(textwrap.dedent(docs))
    utils = tmp_path / "kraken_tpu" / "utils"
    utils.mkdir(parents=True, exist_ok=True)
    # metrics.py present => the docs->code direction runs.
    (utils / "metrics.py").write_text("REGISTRY = None\n")
    if registry:
        (utils / "failpoints.py").write_text(textwrap.dedent(registry))
    for rel, src in dict(extra).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    findings, _stats = lint_paths([str(tmp_path)])
    return findings


def test_metric_catalog_two_way(tmp_path):
    # Bad both ways: a registered metric missing from the catalog AND a
    # stale catalog row nothing registers.
    bad = _project(tmp_path / "bad", docs="""
        ## Metric catalog

        | Metric | Type | Meaning |
        |---|---|---|
        | `pulls_total` (label `result`) | counter | pulls |
        | `ghosts_total` | counter | stale row |
    """, extra={"kraken_tpu/app.py": """
        def wire(REGISTRY):
            REGISTRY.counter("pulls_total", "pulls")
            REGISTRY.gauge("undocumented_gauge", "nope")
    """})
    assert sorted(_rules(bad)) == ["metric-catalog", "metric-catalog"]
    msgs = " | ".join(f.message for f in bad)
    assert "undocumented_gauge" in msgs and "ghosts_total" in msgs
    # The label annotation must NOT read as a cataloged metric name.
    assert "result" not in {m.split("`")[1] for m in msgs.split(" | ")}

    good = _project(tmp_path / "good", docs="""
        ## Metric catalog

        | Metric | Type | Meaning |
        |---|---|---|
        | `pulls_total` (label `result`) | counter | pulls |
    """, extra={"kraken_tpu/app.py": """
        def wire(REGISTRY):
            REGISTRY.counter("pulls_total", "pulls")
    """})
    assert good == []


_REGISTRY_OK = """
    KNOWN_FAILPOINTS = frozenset({
        "conn.drop",
        "store.write",
    })
"""


def test_failpoint_registry_two_way(tmp_path):
    bad = _project(tmp_path / "bad", docs="## Metric catalog\n",
                   registry="""
        KNOWN_FAILPOINTS = frozenset({
            "conn.drop",
            "conn.drop",
            "store.write",
            "never.fired",
        })
    """, extra={"kraken_tpu/conn.py": """
        from kraken_tpu.utils import failpoints

        def pump():
            if failpoints.fire("conn.drop"):
                raise OSError()
            if failpoints.fire("conn.dorp"):  # the typo class
                raise OSError()
    """, "kraken_tpu/store.py": """
        from kraken_tpu.utils.failpoints import fire

        def write():
            if fire("store.write@origin1"):  # @variant: base validates
                raise OSError()
    """})
    got = sorted((f.rule, f.message.split("`")[1]) for f in bad)
    assert got == [
        ("failpoint-registry", "conn.dorp"),    # undeclared site
        ("failpoint-registry", "conn.drop"),    # duplicate declaration
        ("failpoint-registry", "never.fired"),  # stale registry entry
    ]

    good = _project(tmp_path / "good", docs="## Metric catalog\n",
                    registry=_REGISTRY_OK,
                    extra={"kraken_tpu/conn.py": """
        from kraken_tpu.utils import failpoints

        def pump():
            if failpoints.fire("conn.drop"):
                raise OSError()
            if failpoints.fire("store.write"):
                raise OSError()
    """})
    assert good == []


def test_real_registry_matches_real_sites():
    """The production KNOWN_FAILPOINTS and the production fire sites
    agree exactly (the tree gate below also covers this; this test
    names the contract)."""
    findings, _ = lint_paths([os.path.join(REPO, "kraken_tpu")])
    assert [f for f in findings if f.rule == "failpoint-registry"] == []


# -- pragmas -----------------------------------------------------------------


def test_pragma_with_reason_suppresses(tmp_path):
    src = """
        def f(x):
            try:
                return x()
            except Exception:  # kt-lint: disable=bare-except  # probe: any error means unsupported
                pass
    """
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    findings, stats = lint_paths([str(tmp_path)])
    assert findings == []
    assert stats["suppressed"] == 1


def test_pragma_without_reason_is_a_finding_and_does_not_suppress(tmp_path):
    findings = _lint_src(tmp_path, """
        def f(x):
            try:
                return x()
            except Exception:  # kt-lint: disable=bare-except
                pass
    """)
    assert sorted(_rules(findings)) == ["bare-except", "pragma"]
    pragma = next(f for f in findings if f.rule == "pragma")
    assert "reason" in pragma.message


def test_pragma_unknown_rule_is_a_finding(tmp_path):
    findings = _lint_src(tmp_path, """
        x = 1  # kt-lint: disable=no-such-rule  # some reason
    """)
    assert _rules(findings) == ["pragma"]
    assert "no-such-rule" in findings[0].message


def test_pragma_inside_string_literal_is_inert(tmp_path):
    findings = _lint_src(tmp_path, '''
        FIXTURE = """
        except Exception:  # kt-lint: disable=bare-except
            pass
        """
    ''')
    assert findings == []


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    findings = _lint_src(tmp_path, """
        def broken(:
    """)
    assert _rules(findings) == ["parse-error"]


# -- CLI contract ------------------------------------------------------------


def _cli(args, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "kraken_tpu.cli", "lint", *args],
        capture_output=True, text=True, timeout=300, cwd=cwd, env=env,
    )


def test_cli_exit_codes_and_json_shape(tmp_path):
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "bad.py").write_text(
        "def f(x):\n    try:\n        return x()\n"
        "    except:\n        pass\n"
    )

    proc = _cli([str(clean)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["event"] == "lint_done"
    assert summary["findings"] == 0 and summary["files"] == 1

    proc = _cli([str(dirty)])
    assert proc.returncode == 1
    assert "bare-except" in proc.stdout
    assert "bad.py:4:" in proc.stdout  # path:line:col: rule: message

    proc = _cli([str(dirty), "--json"])
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["event"] == "lint_done" and doc["findings"] == 1
    (finding,) = doc["results"]
    assert finding["rule"] == "bare-except"
    assert finding["path"].endswith("bad.py")
    assert finding["line"] == 4 and isinstance(finding["col"], int)
    assert "message" in finding

    proc = _cli([str(tmp_path / "nope")])
    assert proc.returncode == 3
    assert json.loads(proc.stdout)["event"] == "error"


def test_usage_error_in_process(tmp_path):
    with pytest.raises(LintUsageError):
        lint_paths([])
    assert run_lint_tool([]) == 3
    # An explicitly named non-.py file is usage (3), not "clean" (0):
    # files=0/findings=0 would read as a scan that never happened.
    notpy = tmp_path / "config.yaml"
    notpy.write_text("a: 1\n")
    with pytest.raises(LintUsageError):
        lint_paths([str(notpy)])
    assert run_lint_tool([str(notpy)]) == 3


# -- THE gate ----------------------------------------------------------------


def test_tree_gate_zero_findings():
    """`kraken-tpu lint kraken_tpu/ tests/` is clean. If this fails,
    fix the finding (or, for a deliberate exception, add
    `# kt-lint: disable=<rule>  # <reason>` on the flagged line --
    reasonless pragmas do not count). Every invariant the chaos/soak
    tiers keep rediscovering at runtime is cheaper to hold here."""
    findings, stats = lint_paths([
        os.path.join(REPO, "kraken_tpu"),
        os.path.join(REPO, "tests"),
    ], root=REPO)
    assert findings == [], (
        "the tree gate is dirty:\n"
        + "\n".join(f.render() for f in findings)
    )
    assert stats["files"] > 100  # the scan really covered the tree


def test_retry_without_deadline_bad_and_good(tmp_path):
    """An async frame that loops over RPC awaits with no deadline in
    sight is an unbounded retry sweep -- the exact shape that turns one
    dead peer into a wedged control plane."""
    bad = _lint_src(tmp_path / "bad", """
        async def sweep(clients, ns, d):
            for c in clients:
                if await c.stat(ns, d):
                    return True
            while True:
                await clients[0].download(ns, d)
    """)
    assert _rules(bad) == ["retry-without-deadline"] * 2
    assert {f.line for f in bad} == {3, 6}

    good = _lint_src(tmp_path / "good", """
        from kraken_tpu.utils.deadline import Deadline

        async def sweep(clients, ns, d):
            deadline = Deadline(30.0, component="sweep")
            for c in clients:
                if await c.stat(ns, d, deadline=deadline):
                    return True

        async def local_only(items):
            for x in items:  # no RPC awaits in the body: not a sweep
                await x.refresh_cache()
    """)
    assert good == []

    # Test files are exempt (tests hand-drive tight RPC loops on purpose).
    exempt = _lint_src(tmp_path / "tests", """
        async def hammer(c, ns, d):
            while True:
                await c.stat(ns, d)
    """, name="test_hammer.py")
    assert exempt == []

    # A reasoned pragma on the loop line suppresses (bounded sweeps).
    suppressed = _lint_src(tmp_path / "pragma", """
        async def hops(c, url):
            for _hop in range(5):  # kt-lint: disable=retry-without-deadline  # bounded redirect follow
                await c.request("GET", url)
    """)
    assert suppressed == []

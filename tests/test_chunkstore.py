"""Content-addressed chunk store tier: refcount invariants, crash/rot
tail, chunk-aware reads, and THE tier-1 storage band.

Tiers here:

- property tests: refcount invariants under randomized add/delete
  sequences, checked against a model AND against a crash-replay reload
  (journal) AND against a rebuild-from-manifests (fsck's authority);
- unit tests: multi-base greedy set-cover + union diff tiling, composed
  reads across chunk boundaries, chunk-aware watermark eviction;
- crash/rot tail: fsck orphan-chunk sweep + refcount rebuild + CLI exit
  codes, scrub bitflip-in-chunk -> chunk + blob quarantined (never
  deleted) -> heal-by-recommit restores the shared chunk bit-identically
  for every referencing blob;
- e2e: piece serve and range GET from a chunk-backed origin blob are
  bit-identical to flat storage, and the tier-1 STORAGE band -- on the
  build-over-build corpus the chunk tier stores <= 0.7x the bytes of
  the flat-blob control while the PR 9 bytes-moved band still holds.

Same 16 KiB pieces / 256-1024-4096 CDC params as tests/test_delta.py so
~400 KB blobs exercise multi-piece multi-chunk planning in milliseconds.
"""

import asyncio
import os

import numpy as np
import pytest

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.metainfo import ChunkRecipe, chunk_fp
from kraken_tpu.ops.cdc import CDCParams
from kraken_tpu.p2p.delta import (
    HaveSpan,
    diff_recipes_multi,
    pick_cover_bases,
)
from kraken_tpu.store import CAStore, ChunkManifestMetadata
from kraken_tpu.store.chunkstore import ChunkStore, ChunkStoreConfig
from kraken_tpu.store.recovery import run_fsck
from kraken_tpu.utils import failpoints
from kraken_tpu.utils.metrics import REGISTRY

PARAMS = CDCParams(min_size=256, avg_size=1024, max_size=4096)
NS = "library/chunkstore"
STORED_BAND_MAX = 0.7  # acceptance bar: tier stores <= 0.7x flat control
MOVED_BAND_MAX = 0.6  # the PR 9 wire band must hold with the tier on

_D = Digest.from_bytes(b"chunkstore-test")


@pytest.fixture(autouse=True)
def chaos_plane():
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow()
    yield failpoints.FAILPOINTS
    failpoints.FAILPOINTS.disarm_all()
    failpoints.allow(False)


def _mk_store(tmp_path, enabled=True) -> CAStore:
    store = CAStore(str(tmp_path / "store"))
    store.attach_chunkstore(ChunkStore(
        os.path.join(store.root, "chunks"),
        ChunkStoreConfig(enabled=enabled, min_blob_bytes=1),
        quarantine_dir=store.quarantine_dir,
    ))
    return store


def _table(blob: bytes, n_chunks: int) -> tuple[list[int], list[int]]:
    """A fixed tiling chunk table for unit tests (CDC not needed: the
    tier trusts any table whose chunks tile and hash)."""
    size = max(len(blob) // n_chunks, 1)
    sizes, fps, off = [], [], 0
    while off < len(blob):
        s = min(size, len(blob) - off)
        if len(blob) - (off + s) < size // 2:
            s = len(blob) - off  # fold the tail into the last chunk
        sizes.append(s)
        fps.append(chunk_fp(blob[off : off + s]))
        off += s
    return fps, sizes


def _add(store: CAStore, blob: bytes, n_chunks=8) -> Digest:
    d = Digest.from_bytes(blob)
    store.create_cache_file(d, iter([blob]))
    fps, sizes = _table(blob, n_chunks)
    res = store.convert_to_chunks(d, fps, sizes)
    assert res is not None and store.is_chunked(d)
    return d


# -- refcount invariant property tests ------------------------------------


def test_refcount_invariants_under_add_delete_and_replay(tmp_path):
    """Randomized add/delete over blobs drawn from a shared chunk pool:
    after every step the tier's refcounts match a model, and a reload
    from disk (crash replay of the journal) and a rebuild from the live
    manifests both reproduce the same state."""
    rng = np.random.default_rng(21)
    store = _mk_store(tmp_path)
    cs = store.chunkstore
    pool = [
        rng.integers(0, 256, size=int(rng.integers(512, 4096)),
                     dtype=np.uint8).tobytes()
        for _ in range(12)
    ]
    live: dict[str, tuple[list[int], list[int]]] = {}
    model: dict[tuple[int, int], int] = {}

    def check():
        truth = {k: c for k, c in model.items() if c > 0}
        mine = {k: c for k, c in cs._refs.items() if c > 0}
        assert mine == truth
        # logical = sum size*count; stored >= unique live bytes
        assert cs.logical_bytes() == sum(
            size * c for (_fp, size), c in truth.items()
        )

    for step in range(40):
        if live and rng.random() < 0.4:
            hex_ = list(live)[int(rng.integers(0, len(live)))]
            fps, sizes = live.pop(hex_)
            store.delete_cache_file(Digest.from_hex(hex_))
            for fp, s in zip(fps, sizes):
                model[(fp, s)] -= 1
        else:
            k = int(rng.integers(2, 6))
            idx = rng.integers(0, len(pool), size=k)
            blob = b"".join(pool[i] for i in idx) + bytes([step])
            d = Digest.from_bytes(blob)
            if d.hex in live:
                continue
            store.create_cache_file(d, iter([blob]))
            sizes = [len(pool[i]) for i in idx] + [1]
            fps = [chunk_fp(pool[i]) for i in idx] + [chunk_fp(bytes([step]))]
            assert store.convert_to_chunks(d, fps, sizes) is not None
            live[d.hex] = (fps, sizes)
            for fp, s in zip(fps, sizes):
                model[(fp, s)] = model.get((fp, s), 0) + 1
        check()

    # Crash replay: a fresh ChunkStore over the same dir replays the
    # journal to the same live refcounts.
    cs2 = ChunkStore(cs.root, quarantine_dir=store.quarantine_dir)
    assert {k: c for k, c in cs2._refs.items() if c > 0} == {
        k: c for k, c in model.items() if c > 0
    }
    # Rebuild from manifests (fsck's authority) agrees too -- and so do
    # all reads.
    manifests = [
        (m.fps, m.sizes)
        for m in (store.manifest(d) for d in store.list_cache_digests())
        if m is not None
    ]
    cs.rebuild_refs(manifests)
    check()
    for hex_ in live:
        d = Digest.from_hex(hex_)
        assert store.verify_cache_file(d)


def test_writeback_unpins_flat_and_chunked(tmp_path):
    """Writeback must drop the eviction pin after landing the blob for
    BOTH representations — the flat fast path (regression: an early
    return once skipped the unpin, pinning every written-back blob
    forever) and the chunk-backed export path."""
    from kraken_tpu.origin.writeback import KIND, WritebackExecutor
    from kraken_tpu.persistedretry import Task
    from kraken_tpu.store.metadata import PersistMetadata, pin

    store = _mk_store(tmp_path)
    uploaded = {}

    class _Client:
        async def upload_file(self, ns, hex_, path):
            with await asyncio.to_thread(open, path, "rb") as f:
                uploaded[hex_] = await asyncio.to_thread(f.read)

    class _Backends:
        def get_client(self, ns):
            return _Client()

        def try_get_client(self, ns):
            return _Client()

    class _RetryStore:
        def count_pending(self, kind, prefix):
            return 1

        def canonicalize_keys(self, kind, fn):
            pass

    class _Retry:
        store = _RetryStore()

        def register(self, kind, fn):
            pass

        def add(self, task):
            return True

    wb = WritebackExecutor(store, _Backends(), _Retry())
    flat = os.urandom(9_000)
    d_flat = Digest.from_bytes(flat)
    store.create_cache_file(d_flat, iter([flat]))
    chunked_blob = os.urandom(30_000)
    d_chunked = _add(store, chunked_blob, n_chunks=3)
    for d in (d_flat, d_chunked):
        pin(store, d, KIND)
        task = Task(kind=KIND, key=f"{d.hex}:ns",
                    payload={"namespace": "ns", "digest": d.hex})
        asyncio.run(wb._execute(task))
        md = store.get_metadata(d, PersistMetadata)
        assert md is None or not md.persist, (
            f"writeback left {d.hex[:8]} pinned"
        )
    assert uploaded[d_flat.hex] == flat
    assert uploaded[d_chunked.hex] == chunked_blob


def test_empty_manifest_sidecar_reads_as_unhealthy_not_crash(tmp_path):
    """A power loss under rename durability can leave an EMPTY manifest
    sidecar: every guard must see ValueError (struct.error escaping
    would abort fsck/scrub wholesale). With no flat file the blob is
    quarantined unhealable; WITH a flat file only the bad sidecar is
    dropped (the flat bytes are authoritative)."""

    with pytest.raises(ValueError):
        ChunkManifestMetadata.deserialize(b"")
    store = _mk_store(tmp_path)
    blob = os.urandom(20_000)
    d = _add(store, blob, n_chunks=2)
    with open(store._manifest_path(d), "wb"):
        pass  # torn to empty
    assert store.manifest(d) is None
    rep = run_fsck(store, verify="none")
    assert d.hex in rep.quarantined and not store.in_cache(d)
    # Flat + torn manifest: flat wins, sidecar dropped.
    blob2 = os.urandom(20_000)
    d2 = _add(store, blob2, n_chunks=2)
    store.export_to_file(d2, store.cache_path(d2))
    with open(store._manifest_path(d2), "wb"):
        pass
    rep = run_fsck(store, verify="none")
    assert rep.repairs.get("chunk_dual_state") == 1
    assert store.read_cache_file(d2) == blob2
    assert not os.path.exists(store._manifest_path(d2))


def test_journal_torn_tail_and_compaction(tmp_path):
    """A torn trailing journal line (crash mid-append) is skipped on
    load; compaction snapshots and truncates without changing state."""
    store = _mk_store(tmp_path)
    cs = store.chunkstore
    blob = os.urandom(20_000)
    d = _add(store, blob, n_chunks=4)
    with open(os.path.join(cs.root, "refs.log"), "a") as f:
        f.write("+ deadbeef")  # torn: no newline, no size
    cs2 = ChunkStore(cs.root, quarantine_dir=store.quarantine_dir)
    assert cs2._refs == cs._refs
    with cs._lock:
        cs._compact_locked()
    cs3 = ChunkStore(cs.root, quarantine_dir=store.quarantine_dir)
    assert cs3._refs == cs._refs
    assert store.read_cache_file(d) == blob


# -- multi-base planning ---------------------------------------------------


def _recipe(digest, parts: list[bytes]) -> ChunkRecipe:
    return ChunkRecipe(
        digest, [chunk_fp(p) for p in parts], [len(p) for p in parts]
    )


def test_pick_cover_bases_union_beats_best_single():
    """Greedy set-cover: two bases each holding a DIFFERENT half of the
    target must both be picked, covering more than the best single."""
    rng = np.random.default_rng(3)
    chunks = [
        rng.integers(0, 256, 1024, np.uint8).tobytes() for _ in range(8)
    ]
    target = _recipe(_D, chunks)
    base_a = _recipe(Digest.from_bytes(b"a"), chunks[:5])
    base_b = _recipe(Digest.from_bytes(b"b"), chunks[4:])
    base_c = _recipe(Digest.from_bytes(b"c"), chunks[:2])  # dominated
    picked = pick_cover_bases(
        target,
        [(base_c.digest, base_c), (base_a.digest, base_a),
         (base_b.digest, base_b)],
        max_bases=2,
    )
    assert [d.hex for d, _ in picked] == [
        base_a.digest.hex, base_b.digest.hex
    ]
    haves, needs = diff_recipes_multi(target, [r for _d, r in picked])
    assert needs == []  # union covers everything
    assert sum(h.size for h in haves) == target.length
    # Every span points at the base list index that really holds it.
    for h in haves:
        base = picked[h.base][1]
        keys = {(fp, size) for fp, _o, size in base.chunks()}
        assert (h.fp, h.size) in keys
    # max_bases caps the walk; zero-gain candidates are never picked.
    assert len(
        pick_cover_bases(target, [(base_c.digest, base_c)], 3)
    ) == 1


def test_diff_recipes_multi_tiling_property():
    """have + need spans tile the target exactly for ANY set of bases
    drawn from a shared pool (the multi-base twin of the single-base
    property in tests/test_delta.py)."""
    rng = np.random.default_rng(5)
    pool_fps = rng.integers(0, 1 << 63, size=40, dtype=np.uint64)
    pool_sizes = rng.integers(1, 8192, size=40, dtype=np.uint32)

    def draw(k):
        idx = rng.integers(0, 40, size=k)
        return ChunkRecipe(
            _D,
            [int(pool_fps[i]) for i in idx],
            [int(pool_sizes[i]) for i in idx],
        )

    for _trial in range(25):
        target = draw(int(rng.integers(1, 30)))
        bases = [draw(int(rng.integers(0, 20)))
                 for _ in range(int(rng.integers(0, 4)))]
        haves, needs = diff_recipes_multi(target, bases)
        spans = sorted(
            [(h.target_off, h.size) for h in haves] + list(needs)
        )
        pos = 0
        for off, size in spans:
            assert off == pos, "overlap or gap in the partition"
            pos += size
        assert pos == target.length
        for h in haves:
            assert 0 <= h.base < len(bases)
            assert 0 <= h.base_off <= bases[h.base].length - h.size


# -- chunk-aware eviction ---------------------------------------------------


def test_watermark_eviction_frees_unique_bytes_and_reaps(tmp_path):
    """Evicting a chunk-backed blob frees only its UNIQUE bytes (shared
    chunks stay for the surviving manifest) and pressure-reaps make the
    freed bytes real immediately."""
    from kraken_tpu.store.cleanup import CleanupConfig, CleanupManager

    store = _mk_store(tmp_path)
    shared = os.urandom(40_000)
    a = _add(store, shared + os.urandom(20_000), n_chunks=6)
    b = _add(store, shared + os.urandom(20_000), n_chunks=6)
    # Tile so the shared prefix chunks align: 10k chunks.
    # (re-add with aligned tables)
    for d in (a, b):
        store.delete_cache_file(d)
    store.chunkstore.gc_reap()
    blob_a = shared + os.urandom(20_000)
    blob_b = shared + os.urandom(20_000)
    tables = {}
    for blob in (blob_a, blob_b):
        d = Digest.from_bytes(blob)
        store.create_cache_file(d, iter([blob]))
        sizes = [10_000] * 6
        fps = [chunk_fp(blob[i * 10_000 : (i + 1) * 10_000])
               for i in range(6)]
        assert store.convert_to_chunks(d, fps, sizes) is not None
        tables[d.hex] = (fps, sizes)
    da, db = Digest.from_bytes(blob_a), Digest.from_bytes(blob_b)
    # 40k shared stored ONCE + 2 x 20k unique = 80k (flat would be 120k).
    assert store.chunkstore.stored_bytes() == 80_000
    assert store.evictable_bytes(da) == 20_000
    mgr = CleanupManager(store, CleanupConfig(
        tti_seconds=0, high_watermark_bytes=75_000,
        low_watermark_bytes=70_000,
    ))
    mgr.touch(da, now=100.0)
    mgr.touch(db, now=200.0)  # b more recent: a is the LRU victim
    evicted = mgr.run_once(now=300.0)
    assert evicted == [da]
    # The sweep's pressure-reap made the unique bytes real: only a's
    # 20k unique left; the 40k shared stays for b's manifest.
    assert store.chunkstore.stored_bytes() == 60_000
    assert store.in_cache(db) and store.read_cache_file(db) == blob_b


# -- crash/rot tail ---------------------------------------------------------


def test_fsck_chunk_tier_orphans_rebuild_and_cli_exit_codes(tmp_path):
    """Offline `kraken-tpu fsck` covers the tier: clean store exits 0
    (pending-GC zero-refs are NOT repairs), a planted orphan chunk +
    torn journal exit 1 (repaired: rebuild + reap), a corrupt chunk
    exits 2 (unhealable: chunk AND blob quarantined, never deleted)."""
    from kraken_tpu import cli

    root = str(tmp_path / "clistore")
    store = CAStore(root)
    store.attach_chunkstore(ChunkStore(
        os.path.join(root, "chunks"),
        ChunkStoreConfig(enabled=True, min_blob_bytes=1),
        quarantine_dir=store.quarantine_dir,
    ))
    blob = os.urandom(60_000)
    d = _add(store, blob, n_chunks=6)
    # A deleted-but-not-reaped blob must still fsck CLEAN.
    d2 = _add(store, os.urandom(30_000), n_chunks=3)
    store.delete_cache_file(d2)
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root, "--verify", "all"])
    assert e.value.code == 0

    # Orphan chunk (file the journal never saw) -> repaired, exit 1.
    orphan = os.path.join(store.chunkstore.root, "ab", "ab" * 8 + "-99")
    os.makedirs(os.path.dirname(orphan), exist_ok=True)
    with open(orphan, "wb") as f:
        f.write(b"x" * 99)
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root, "--verify", "none"])
    assert e.value.code == 1
    assert not os.path.exists(orphan)

    # Corrupt chunk -> chunk + blob quarantined, exit 2.
    md = store.manifest(d)
    victim_fp, victim_size = md.fps[2], md.sizes[2]
    path = store.chunkstore.chunk_path(victim_fp, victim_size)
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad")
    with pytest.raises(SystemExit) as e:
        cli.main(["fsck", "--root", root, "--verify", "all"])
    assert e.value.code == 2
    assert os.path.exists(
        store.chunkstore.quarantine_chunk_path(victim_fp, victim_size)
    )
    assert not os.path.exists(path)  # moved, not copied
    assert not store.in_cache(d)  # blob reported unhealable + moved aside


def test_scrub_bitflip_in_shared_chunk_quarantines_and_heals(tmp_path):
    """At-rest rot in a chunk SHARED by two manifests: the scrubber
    quarantines the chunk (never deletes) and both referencing blobs;
    a heal (re-commit + re-convert, what the origin heal plane does
    after its ring re-fetch) rewrites the verified chunk under the same
    name and BOTH blobs read bit-identically again."""
    from kraken_tpu.store.scrub import Scrubber

    store = _mk_store(tmp_path)
    cs = store.chunkstore
    shared = os.urandom(30_000)
    blob_a = shared + os.urandom(10_000)
    blob_b = shared + os.urandom(10_000)
    corrupted = []
    for blob in (blob_a, blob_b):
        d = Digest.from_bytes(blob)
        store.create_cache_file(d, iter([blob]))
        sizes = [10_000] * 4
        fps = [chunk_fp(blob[i * 10_000 : (i + 1) * 10_000])
               for i in range(4)]
        assert store.convert_to_chunks(d, fps, sizes) is not None
    da, db = Digest.from_bytes(blob_a), Digest.from_bytes(blob_b)
    shared_fp = chunk_fp(shared[:10_000])
    assert cs.refcount(shared_fp, 10_000) == 2
    # Flip a bit in the SHARED chunk file, on disk.
    with open(cs.chunk_path(shared_fp, 10_000), "r+b") as f:
        f.seek(5000)
        b0 = f.read(1)
        f.seek(5000)
        f.write(bytes([b0[0] ^ 1]))

    scrubber = Scrubber(
        store, on_corrupt=lambda d, ns: corrupted.append(d.hex)
    )
    quarantined = asyncio.run(scrubber.run_cycle())
    assert {d.hex for d in quarantined} == {da.hex, db.hex}
    assert set(corrupted) == {da.hex, db.hex}
    q = cs.quarantine_chunk_path(shared_fp, 10_000)
    assert os.path.exists(q)  # evidence kept, never deleted
    with open(q, "rb") as f:
        assert chunk_fp(f.read()) != shared_fp  # it really holds the rot
    assert not store.in_cache(da) and not store.in_cache(db)

    # Heal: the origin heal plane re-fetches the blob bit-identically
    # and re-runs the commit pipeline (which re-converts). Simulate its
    # storage half: commit + convert. The shared chunk file is REWRITTEN
    # verified under the same name.
    for blob in (blob_a, blob_b):
        d = Digest.from_bytes(blob)
        uid = store.create_upload()
        store.write_upload_chunk(uid, 0, blob)
        store.commit_upload(uid, d)
        sizes = [10_000] * 4
        fps = [chunk_fp(blob[i * 10_000 : (i + 1) * 10_000])
               for i in range(4)]
        assert store.convert_to_chunks(d, fps, sizes) is not None
    assert cs.verify_chunk(shared_fp, 10_000)
    assert store.read_cache_file(da) == blob_a
    assert store.read_cache_file(db) == blob_b
    # Re-share: both blobs serve through the piece path again.
    assert store.verify_cache_file(da) and store.verify_cache_file(db)


# -- e2e: serve paths + THE storage band -----------------------------------


def _make_build_pair(rng, n_files=24, file_kb=16, reuse=0.8):
    """Two consecutive 'image builds' (same generator as
    tests/test_delta.py): shared content at SHIFTED offsets."""
    files = [
        rng.integers(0, 256, size=file_kb * 1024, dtype=np.uint8).tobytes()
        for _ in range(2 * n_files)
    ]

    def layer(members):
        parts = []
        for fi in members:
            parts.append(
                rng.integers(0, 256, size=64, dtype=np.uint8).tobytes()
            )
            parts.append(files[fi])
        return b"".join(parts)

    m1 = list(range(n_files))
    n_keep = int(n_files * reuse)
    m2 = m1[:n_keep] + list(range(n_files, 2 * n_files - n_keep))
    rng.shuffle(m2)
    return layer(m1), layer(m2)


class _Herd:
    """tracker + origin + agent, delta- and chunk-tier-capable."""

    def __init__(self, tmp_path, agent_delta=None, origin_delta=None,
                 agent_chunkstore=None, origin_chunkstore=None):
        self.tmp = tmp_path
        self.agent_delta = agent_delta
        self.origin_delta = origin_delta
        self.agent_chunkstore = agent_chunkstore
        self.origin_chunkstore = origin_chunkstore

    async def __aenter__(self):
        from kraken_tpu.assembly import AgentNode, OriginNode, TrackerNode
        from kraken_tpu.origin.client import BlobClient, ClusterClient
        from kraken_tpu.origin.dedup import DedupIndex
        from kraken_tpu.origin.metainfogen import PieceLengthConfig
        from kraken_tpu.placement import HostList, Ring
        from kraken_tpu.utils.httputil import HTTPClient

        self.tracker = TrackerNode(announce_interval_seconds=0.1)
        await self.tracker.start()
        self.origin = OriginNode(
            store_root=str(self.tmp / "origin"),
            tracker_addr=self.tracker.addr,
            piece_lengths=PieceLengthConfig(table=((0, 16384),)),
            delta=self.origin_delta,
            chunkstore=self.origin_chunkstore,
        )
        self.origin.dedup = DedupIndex(self.origin.store, params=PARAMS)
        await self.origin.start()
        ring = Ring(HostList(static=[self.origin.addr]), max_replica=2)
        self.cluster = ClusterClient(ring)
        self.tracker.server.origin_cluster = self.cluster
        self.agent = AgentNode(
            store_root=str(self.tmp / "agent"),
            tracker_addr=self.tracker.addr,
            delta=self.agent_delta,
            chunkstore=self.agent_chunkstore,
        )
        await self.agent.start()
        self.http = HTTPClient()
        self.oc = BlobClient(self.origin.addr)
        return self

    async def __aexit__(self, *exc):
        await self.http.close()
        await self.oc.close()
        await self.agent.stop()
        await self.origin.stop()
        await self.cluster.close()
        await self.tracker.stop()

    async def upload(self, blob: bytes) -> Digest:
        d = Digest.from_bytes(blob)
        await self.oc.upload(NS, d, blob)
        return d

    async def pull(self, d: Digest) -> tuple[bytes, int]:
        from urllib.parse import quote

        down = REGISTRY.counter("p2p_piece_bytes_down_total")
        fetched = REGISTRY.counter("delta_bytes_fetched_total")
        d0, f0 = down.value(), fetched.value()
        body = await self.http.get(
            f"http://{self.agent.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}"
        )
        moved = (down.value() - d0) + (fetched.value() - f0)
        return body, int(moved)

    async def wait_origin_chunked(self, d: Digest, timeout=10.0):
        """The origin's dedup + conversion runs as a background task
        after commit; poll until the blob is manifest-backed."""
        await _wait_chunked(self.origin.store, d, timeout)

    async def wait_agent_chunked(self, d: Digest, timeout=10.0):
        """The agent converts as a background task after the pull
        completes (off the download critical path); poll."""
        await _wait_chunked(self.agent.store, d, timeout)


async def _wait_chunked(store, d: Digest, timeout: float):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if store.is_chunked(d):
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"store never chunked {d.hex[:12]}")


DELTA_ON = {"enabled": True, "min_blob_bytes": 1}
TIER_ON = {"enabled": True, "min_blob_bytes": 1}


def test_chunked_origin_serves_pieces_and_ranges_bit_identical(tmp_path):
    """Origin-side tier on: the blob converts to manifest + chunks after
    the dedup pass, and every read path answers bit-identically to flat
    storage -- swarm piece serves (main-loop composed preads), full
    GETs, and the byte-range forms the delta planner sends."""
    asyncio.run(_chunked_origin_serves(tmp_path))


async def _chunked_origin_serves(tmp_path):
    rng = np.random.default_rng(31)
    v1, _ = _make_build_pair(rng, n_files=8)
    async with _Herd(tmp_path, origin_chunkstore=TIER_ON) as herd:
        d = await herd.upload(v1)
        await herd.wait_origin_chunked(d)
        assert herd.origin.store.chunkstore.logical_bytes() == len(v1)
        from urllib.parse import quote

        url = (
            f"http://{herd.origin.addr}/namespace/"
            f"{quote(NS, safe='')}/blobs/{d.hex}"
        )
        # Full GET from the chunk tier.
        assert await herd.http.get(url, retry_5xx=False) == v1
        # Range forms: mid-span crossing chunk boundaries, open-ended,
        # suffix; 206 with correct Content-Range; 416 past the end.
        for rng_hdr, want in [
            (f"bytes=5000-{len(v1) - 4000}", v1[5000 : len(v1) - 3999]),
            ("bytes=0-0", v1[:1]),
            (f"bytes={len(v1) - 7000}-", v1[-7000:]),
            ("bytes=-9000", v1[-9000:]),
        ]:
            status, headers, body = await herd.http.request_full(
                "GET", url, headers={"Range": rng_hdr}, retry_5xx=False,
                ok_statuses=(206,),
            )
            assert status == 206 and body == want, rng_hdr
            assert headers["Content-Range"].endswith(f"/{len(v1)}")
        from kraken_tpu.utils.httputil import HTTPError

        with pytest.raises(HTTPError) as ei:
            await herd.http.get(
                url, headers={"Range": f"bytes={len(v1)}-"},
                retry_5xx=False,
            )
        assert ei.value.status == 416
        # Piece serve: a swarm pull from the chunk-backed seeder.
        got, moved = await herd.pull(d)
        assert got == v1
        assert moved >= len(v1)  # real swarm transfer, not a cache trick


def test_storage_band_build_over_build(tmp_path):
    """THE tier-1 STORAGE band: with the tier on (agent side), the
    build-over-build corpus stores <= 0.7x the bytes of the flat-blob
    control, reads stay bit-identical, the delta base copy serves from
    the chunk-backed base, and the PR 9 bytes-moved band (<= 0.6x of
    control) still holds with the tier enabled."""
    asyncio.run(_storage_band(tmp_path))


async def _storage_band(tmp_path):
    rng = np.random.default_rng(7)
    v1, v2 = _make_build_pair(rng)
    copied = REGISTRY.counter("delta_bytes_copied_local_total")
    converts = REGISTRY.counter("chunkstore_converts_total")
    async with _Herd(
        tmp_path / "on",
        agent_delta=DELTA_ON, origin_delta={"enabled": True},
        agent_chunkstore=TIER_ON,
    ) as herd:
        d1 = await herd.upload(v1)
        k0 = converts.value(outcome="converted")
        got1, _ = await herd.pull(d1)
        assert got1 == v1
        # The completed pull converts in the background (off the pull's
        # critical path): the agent ends up holding v1 as manifest +
        # chunks, no flat file.
        await herd.wait_agent_chunked(d1)
        assert converts.value(outcome="converted") == k0 + 1
        assert herd.agent.store.read_cache_file(d1) == v1
        d2 = await herd.upload(v2)
        c0 = copied.value()
        got2, moved2 = await herd.pull(d2)
        assert got2 == v2, "chunk-tier pull must be bit-identical"
        assert copied.value() > c0, (
            "delta base copy from the chunk-backed base never happened"
        )
        await herd.wait_agent_chunked(d2)
        on_ratio = moved2 / len(v2)
        stored_on = herd.agent.store.disk_usage_bytes()
        # Serving from the tier after conversion stays bit-identical.
        got2b, moved2b = await herd.pull(d2)
        assert got2b == v2 and moved2b == 0  # cache hit, tier-served
    async with _Herd(tmp_path / "off") as herd:  # shipped defaults
        d1 = await herd.upload(v1)
        await herd.pull(d1)
        d2 = await herd.upload(v2)
        got2, moved_off = await herd.pull(d2)
        assert got2 == v2
        off_ratio = moved_off / len(v2)
        stored_off = herd.agent.store.disk_usage_bytes()
    stored_ratio = stored_on / stored_off
    assert stored_ratio <= STORED_BAND_MAX, (
        f"chunk tier stored {stored_on} bytes = {stored_ratio:.3f}x the "
        f"flat control's {stored_off} -- tier regression (band: <= "
        f"{STORED_BAND_MAX}x)"
    )
    assert on_ratio <= MOVED_BAND_MAX * off_ratio, (
        f"bytes-moved band broke with the tier on: {on_ratio:.3f}x vs "
        f"control {off_ratio:.3f}x"
    )


def test_live_reload_attaches_tier_and_default_off(tmp_path):
    """Shipped-off nodes enable the tier via reload() (the SIGHUP
    rollout path); a node restarted with the knob off keeps serving its
    manifest-backed blobs."""
    store = CAStore(str(tmp_path / "s"))
    assert store.chunkstore is None  # default: no tier

    from kraken_tpu.assembly import AgentNode

    agent = AgentNode(
        store_root=str(tmp_path / "a"), tracker_addr="127.0.0.1:1",
    )
    assert agent.store.chunkstore is None
    agent.reload({"chunkstore": {"enabled": True, "min_blob_bytes": 1}})
    assert agent.store.chunkstore is not None
    assert agent.store.chunkstore.config.enabled
    blob = os.urandom(50_000)
    d = _add_via(agent.store, blob)
    # Restart with the knob OFF: tier still attaches (state exists) but
    # conversions stop.
    agent2 = AgentNode(
        store_root=str(tmp_path / "a"), tracker_addr="127.0.0.1:1",
    )
    assert agent2.store.chunkstore is not None
    assert not agent2.store.chunkstore.config.enabled
    assert agent2.store.in_cache(d)
    assert agent2.store.read_cache_file(d) == blob


def _add_via(store, blob):
    d = Digest.from_bytes(blob)
    store.create_cache_file(d, iter([blob]))
    fps, sizes = _table(blob, 5)
    assert store.convert_to_chunks(d, fps, sizes) is not None
    return d

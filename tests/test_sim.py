"""Discrete-event swarm simulator tests (SURVEY.md SS4 tier 3).

The sim drives the production policy objects (RequestManager, ConnState,
AnnounceQueue, default_priority); these tests pin completion, conservation
invariants, and determinism so the 10k-agent bench numbers are trustable
as regression signals.
"""

import pytest

from kraken_tpu.p2p.sim import SimConfig, SwarmSim, run_sim


def test_small_swarm_completes():
    r = run_sim(n_agents=50, num_pieces=16, seed=7)
    assert r["completed"] == 50 and r["incomplete"] == 0
    assert 0 < r["p50_s"] <= r["p99_s"] <= r["max_s"] < 60.0
    # Conservation: every agent got every piece exactly once, plus any
    # endgame duplicates (bounded by the rescue policy).
    assert r["transfers"] == 50 * 16 + r["duplicate_transfers"]
    assert r["duplicate_transfers"] <= 50 * 16 * 0.25
    assert r["announces"] >= 50  # at least the join announces


def test_same_seed_replays_exactly():
    a = run_sim(n_agents=120, num_pieces=16, seed=3)
    b = run_sim(n_agents=120, num_pieces=16, seed=3)
    assert a == b


def test_flash_crowd_exercises_admission_and_churn():
    """A crowd 20x the origin's conn budget must busy-reject (polite
    rejection + soft blacklist) yet still complete: churn is what frees
    seeder slots for waiting leechers."""
    r = run_sim(
        n_agents=200, num_pieces=16, max_conns_per_torrent=10, seed=1
    )
    assert r["busy_rejects"] > 0
    assert r["completed"] == 200


def test_origin_bottleneck_shows_in_latency():
    """Halving the origin's uplink must not halve swarm throughput -- the
    point of the P2P mesh is that agents serve each other. The sim should
    show sublinear sensitivity to origin bandwidth."""
    fast = run_sim(n_agents=100, num_pieces=16, seed=5)
    slow = run_sim(
        n_agents=100, num_pieces=16, seed=5, origin_uplink_bps=1.25e9 / 4
    )
    assert slow["completed"] == fast["completed"] == 100
    assert slow["p99_s"] < fast["p99_s"] * 3.0


def test_incomplete_is_reported_not_hidden():
    """A sim cut off early reports incompletes honestly."""
    r = run_sim(n_agents=100, num_pieces=64, seed=2, max_sim_s=0.5)
    assert r["incomplete"] > 0
    assert r["completed"] + r["incomplete"] == 100


def test_downlink_caps_slow_but_complete():
    """Per-host bandwidth caps (the YAML p2p_bandwidth knob's shape): a
    capped downlink lowers goodput but must not wedge the swarm."""
    free = run_sim(n_agents=100, num_pieces=16, seed=9)
    # Cap low enough that the per-agent downlink is the binding resource:
    # 16 x 4 MiB through 2.5 MB/s has an analytic floor of ~26.8 s.
    capped = run_sim(
        n_agents=100, num_pieces=16, seed=9, downlink_bps=2.5e6,
    )
    floor = 16 * (4 << 20) / 2.5e6
    assert capped["completed"] == free["completed"] == 100
    assert capped["p99_s"] >= floor  # the cap models real bandwidth
    assert capped["p99_s"] < floor * 5  # ...without wedging the swarm
    assert free["p99_s"] < floor  # and the free run proves it was the cap


def test_image_shaped_multi_blob_pull():
    """Multi-blob image pulls: every agent pulls all layers concurrently
    over per-torrent conn budgets; latency is the LAST layer's finish.
    Piece conservation holds per-corpus."""
    layers = (16, 8, 4)
    r = run_sim(n_agents=80, seed=4, blob_pieces=layers)
    assert r["blobs"] == 3
    assert r["completed"] == 80 and r["incomplete"] == 0
    assert r["transfers"] == 80 * sum(layers) + r["duplicate_transfers"]
    # A single-blob pull of the same total pieces for comparison: the
    # image shape must not collapse throughput (layers share the uplink
    # but parallelize the swarm).
    single = run_sim(n_agents=80, seed=4, num_pieces=sum(layers))
    assert r["p99_s"] < single["p99_s"] * 3


def test_restart_wave_recovers():
    """Mid-swarm restart chaos: a third of agents die mid-pull, lose
    their in-flight requests and the debounced-bitfield tail, rejoin,
    and the swarm still completes deterministically."""
    base = run_sim(n_agents=150, num_pieces=32, seed=6)
    r = run_sim(
        n_agents=150, num_pieces=32, seed=6,
        restart_at_s=base["p50_s"] / 2, restart_frac=0.33,
        restart_down_s=1.0, restart_lose_pieces=2,
    )
    assert r["restarts"] == pytest.approx(150 * 0.33, abs=1)
    assert r["completed"] == 150 and r["incomplete"] == 0
    # NOTE: no p99-vs-base assertion -- measured, the wave can IMPROVE
    # the tail (dropping a third of the conns mid-swarm reshuffles
    # endgame topology, the same mechanism that makes churn load-bearing)
    # and the sign of the effect is seed-dependent. Bounded is what
    # matters:
    assert r["p99_s"] < base["p99_s"] * 3
    # Determinism holds with every feature on.
    r2 = run_sim(
        n_agents=150, num_pieces=32, seed=6,
        restart_at_s=base["p50_s"] / 2, restart_frac=0.33,
        restart_down_s=1.0, restart_lose_pieces=2,
    )
    assert r == r2


def test_tracker_fleet_healthy_matches_single_tracker_completion():
    """Fleet mode sanity: with every tracker healthy, sharding announces
    over 3 trackers must not change whether (or how fast, within noise)
    the swarm completes."""
    single = run_sim(n_agents=200, num_pieces=16, seed=8)
    fleet = run_sim(n_agents=200, num_pieces=16, seed=8, n_trackers=3)
    assert fleet["completed"] == single["completed"] == 200
    assert fleet["p99_s"] < single["p99_s"] * 2
    assert fleet["announce_failovers"] == 0
    assert fleet["announce_p99_s"] is not None


def test_tracker_fleet_band_1k_kill_one_of_three():
    """CI band for the tracker HA plane (ISSUE 12 acceptance): 1k
    agents, 3 trackers, the blob's shard owner killed mid-run. The
    fleet must shrug: ZERO failed pulls, and announce p99 <= 3x the
    healthy-fleet control (same seed/config, no kill) -- per-agent
    breakers cap the damage at fail_threshold fast-refused hops before
    everyone routes around the corpse. Deterministic per (seed,
    config), so this is a band, not a flake."""
    kw = dict(n_agents=1000, num_pieces=64, seed=0, n_trackers=3)
    control = run_sim(**kw)
    killed = run_sim(**kw, tracker_kill_at_s=3.0, tracker_kill=1)
    assert control["completed"] == 1000 and control["announce_failovers"] == 0
    # Zero failed pulls through the tracker death.
    assert killed["completed"] == 1000 and killed["incomplete"] == 0
    assert killed["tracker_kills"] == 1
    assert killed["announce_failovers"] > 0  # the death was actually felt
    assert killed["announce_failures"] == 0  # but no announce ever died
    # THE band: announce p99 within 3x of the healthy control.
    assert killed["announce_p99_s"] <= control["announce_p99_s"] * 3.0, (
        killed["announce_p99_s"], control["announce_p99_s"],
    )
    # Swarm-completion time stays in family too (the sim's pull p99 is
    # dominated by bandwidth, not announces; a wedged announce plane
    # would blow this out).
    assert killed["p99_s"] <= control["p99_s"] * 1.5


@pytest.mark.slow
def test_tracker_fleet_band_30k_kill_one_of_three():
    """The bench-scale variant (PERF.md swarm plane): 30k agents
    through the same 1-of-3 tracker death."""
    kw = dict(n_agents=30_000, num_pieces=64, seed=1, n_trackers=3)
    control = run_sim(**kw)
    killed = run_sim(**kw, tracker_kill_at_s=5.0, tracker_kill=1)
    assert killed["completed"] == 30_000
    assert killed["announce_failures"] == 0
    assert killed["announce_p99_s"] <= control["announce_p99_s"] * 3.0


def test_tracker_blackout_band_1k_kill_all_with_pex():
    """CI band for the gossip plane (ISSUE 18 acceptance): 1k agents, 3
    trackers, ALL of them killed mid-run with PEX on. The announce plane
    flatlines (every walk exhausts the fleet) yet >= 99% of in-flight
    pulls must still complete -- gossip over existing conns plus
    book-driven redials are the only discovery left. Banded against the
    same-seed no-kill control; deterministic per (seed, config)."""
    kw = dict(n_agents=1000, num_pieces=64, seed=0, n_trackers=3, pex=True)
    control = run_sim(**kw)
    killed = run_sim(**kw, tracker_kill_at_s=3.0, tracker_kill_all=True)
    assert control["completed"] == 1000
    assert killed["tracker_kills"] == 3
    assert killed["announce_failures"] > 0  # the blackout was total
    assert killed["pex_messages"] > 0
    # THE band: >= 99% of pulls complete through total tracker loss.
    assert killed["completed"] >= 0.99 * control["completed"], (
        killed["completed"], control["completed"],
    )
    # And completion stays in family (gossip discovery is slower than a
    # live tracker's handouts, but must not wedge the tail).
    assert killed["p99_s"] <= control["p99_s"] * 3.0, (
        killed["p99_s"], control["p99_s"],
    )


def test_tracker_blackout_without_pex_strands_the_swarm():
    """The control for the control: the SAME total blackout with gossip
    OFF must strand most of the swarm -- proving the band above measures
    PEX, not some other slack in the model."""
    kw = dict(n_agents=200, num_pieces=32, seed=0, n_trackers=3,
              max_sim_s=120.0)
    stranded = run_sim(**kw, tracker_kill_at_s=1.0, tracker_kill_all=True)
    rescued = run_sim(**kw, tracker_kill_at_s=1.0, tracker_kill_all=True,
                      pex=True, pex_interval_s=2.0)
    assert stranded["completed"] < 0.25 * 200
    assert rescued["completed"] == 200


def test_pex_mode_same_seed_replays_exactly():
    """Determinism holds with gossip + kill-all on (the band above is a
    band, not a flake)."""
    kw = dict(n_agents=150, num_pieces=16, seed=3, n_trackers=3, pex=True,
              tracker_kill_at_s=1.0, tracker_kill_all=True)
    assert run_sim(**kw) == run_sim(**kw)


def test_1k_regression_band():
    """CI regression gate (VERDICT r4 #8): p99 at 1k agents stays within
    +/-5% of the recorded golden (12.43 s, round 5; cross-seed spread
    measured <1%). A policy change that shifts swarm behavior by more
    than the noise floor must update this number CONSCIOUSLY."""
    r = run_sim(n_agents=1000, num_pieces=64, seed=0)
    assert r["completed"] == 1000
    assert r["p99_s"] == pytest.approx(12.433, rel=0.05)

"""Discrete-event swarm simulator tests (SURVEY.md SS4 tier 3).

The sim drives the production policy objects (RequestManager, ConnState,
AnnounceQueue, default_priority); these tests pin completion, conservation
invariants, and determinism so the 10k-agent bench numbers are trustable
as regression signals.
"""

from kraken_tpu.p2p.sim import SimConfig, SwarmSim, run_sim


def test_small_swarm_completes():
    r = run_sim(n_agents=50, num_pieces=16, seed=7)
    assert r["completed"] == 50 and r["incomplete"] == 0
    assert 0 < r["p50_s"] <= r["p99_s"] <= r["max_s"] < 60.0
    # Conservation: every agent got every piece exactly once, plus any
    # endgame duplicates (bounded by the rescue policy).
    assert r["transfers"] == 50 * 16 + r["duplicate_transfers"]
    assert r["duplicate_transfers"] <= 50 * 16 * 0.25
    assert r["announces"] >= 50  # at least the join announces


def test_same_seed_replays_exactly():
    a = run_sim(n_agents=120, num_pieces=16, seed=3)
    b = run_sim(n_agents=120, num_pieces=16, seed=3)
    assert a == b


def test_flash_crowd_exercises_admission_and_churn():
    """A crowd 20x the origin's conn budget must busy-reject (polite
    rejection + soft blacklist) yet still complete: churn is what frees
    seeder slots for waiting leechers."""
    r = run_sim(
        n_agents=200, num_pieces=16, max_conns_per_torrent=10, seed=1
    )
    assert r["busy_rejects"] > 0
    assert r["completed"] == 200


def test_origin_bottleneck_shows_in_latency():
    """Halving the origin's uplink must not halve swarm throughput -- the
    point of the P2P mesh is that agents serve each other. The sim should
    show sublinear sensitivity to origin bandwidth."""
    fast = run_sim(n_agents=100, num_pieces=16, seed=5)
    slow = run_sim(
        n_agents=100, num_pieces=16, seed=5, origin_uplink_bps=1.25e9 / 4
    )
    assert slow["completed"] == fast["completed"] == 100
    assert slow["p99_s"] < fast["p99_s"] * 3.0


def test_incomplete_is_reported_not_hidden():
    """A sim cut off early reports incompletes honestly."""
    r = run_sim(n_agents=100, num_pieces=64, seed=2, max_sim_s=0.5)
    assert r["incomplete"] > 0
    assert r["completed"] + r["incomplete"] == 100

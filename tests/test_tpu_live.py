"""Live-TPU herd test: the PRODUCTION origin wiring on the real chip.

Gated behind ``KT_TPU_E2E=1`` because the default suite pins the whole
pytest process to CPU (tests/conftest.py) and the real chip admits one
client at a time. Run manually / from bench rigs:

    KT_TPU_E2E=1 python -m pytest tests/test_tpu_live.py -q

What it proves that the CPU suite cannot: ``--hasher tpu`` selected via
the production CLI path compiles and runs the Pallas kernel inside a real
origin process (axon PJRT plugin, first compile 20-40 s), its metainfo
feeds a real P2P pull by a CPU agent, and the north-star gauges move on
the origin's /metrics endpoint. The other two production hasher modes
get the same treatment: an agent whose BatchedVerifier batches through
the real chip (``--hasher tpu`` on the RECEIVE side), and an origin
running ``--hasher tpu-sharded`` (shard_map over the local device set,
a 1-device mesh on this rig).
"""

import asyncio
import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    os.environ.get("KT_TPU_E2E") != "1",
    reason="live-TPU herd test: set KT_TPU_E2E=1 (requires the real chip)",
)


def _spawn(args, *, tpu: bool):
    env = dict(os.environ, PYTHONPATH=REPO)
    if tpu:
        # The real chip: the axon platform must win, and the CPU suite's
        # virtual-device flags must not leak in.
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
    else:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "kraken_tpu.cli", *args],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        cwd=REPO,
        env=env,
        text=True,
    )
    for line in proc.stdout:
        if line.startswith("READY "):
            return proc, json.loads(line[6:])
    raise RuntimeError(f"component died: {args}")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _hasher_bytes(metrics: str, hasher: str) -> float:
    """Sum of hasher_bytes_total for one hasher label in a /metrics dump."""
    total = 0.0
    for ln in metrics.splitlines():
        if ln.startswith("hasher_bytes_total") and f'hasher="{hasher}"' in ln:
            total += float(ln.rsplit(" ", 1)[1])
    return total


def test_tpu_hasher_serves_real_pull(tmp_path):
    procs = []
    try:
        origin, oinfo = _spawn(
            ["origin", "--store", str(tmp_path / "origin"), "--hasher", "tpu"],
            tpu=True,
        )
        procs.append(origin)
        tracker, tinfo = _spawn(
            ["tracker", "--origins", oinfo["addr"]], tpu=False
        )
        procs.append(tracker)
        origin.send_signal(signal.SIGTERM)
        origin.wait(timeout=15)
        procs.remove(origin)
        origin, oinfo = _spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--hasher", "tpu",
             "--port", oinfo["addr"].split(":")[1],
             "--tracker", tinfo["addr"]],
            tpu=True,
        )
        procs.append(origin)
        agent, ainfo = _spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo["addr"]],
            tpu=False,
        )
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.utils.httputil import HTTPClient

            # 48 MiB = 12 pieces at the table's 4 MiB: a real multi-piece
            # batch through the TPU plane, small enough to stay minutes-
            # scale through the first Mosaic compile.
            blob = os.urandom(48 * 1024 * 1024)
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"], HTTPClient(timeout_seconds=600))
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=600)
            got = await http.get(
                f"http://{ainfo['addr']}/namespace/ns/blobs/{d.hex}"
            )
            assert got == blob, "pulled bytes differ"
            metrics = (
                await http.get(f"http://{oinfo['addr']}/metrics")
            ).decode()
            await oc.close()
            await http.close()
            tpu_lines = [
                ln for ln in metrics.splitlines()
                if ln.startswith("hasher_bytes_total") and 'hasher="tpu"' in ln
            ]
            assert tpu_lines, f"tpu hasher never ran:\n{metrics[:2000]}"
            assert float(tpu_lines[0].rsplit(" ", 1)[1]) >= len(blob), tpu_lines

        asyncio.run(drive())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_agent_tpu_verifier_verifies_real_pull(tmp_path):
    """The OTHER unexercised hasher mode on the receive side: an agent
    with ``--hasher tpu`` runs its BatchedVerifier batches through the
    real chip. A CPU origin seeds; the agent's P2P pull verifies every
    piece on the device -- proven by bit-identical bytes AND the agent's
    own ``hasher_bytes_total{hasher="tpu"}`` covering the blob."""
    procs = []
    try:
        # Pick the origin's port up front so the tracker can be born
        # knowing it (no kill-and-respawn dance, no second compile).
        oport = _free_port()
        tracker, tinfo = _spawn(
            ["tracker", "--origins", f"127.0.0.1:{oport}"], tpu=False
        )
        procs.append(tracker)
        origin, oinfo = _spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--port", str(oport),
             "--hasher", "cpu", "--tracker", tinfo["addr"]],
            tpu=False,
        )
        procs.append(origin)
        agent, ainfo = _spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--hasher", "tpu", "--tracker", tinfo["addr"]],
            tpu=True,
        )
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.utils.httputil import HTTPClient

            # 48 MiB = a dozen 4 MiB pieces: enough arrivals to form
            # real device verify batches, small enough for the first
            # Mosaic compile to stay minutes-scale.
            blob = os.urandom(48 * 1024 * 1024)
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"], HTTPClient(timeout_seconds=600))
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=600)
            got = await http.get(
                f"http://{ainfo['addr']}/namespace/ns/blobs/{d.hex}"
            )
            assert got == blob, "pulled bytes differ"
            metrics = (
                await http.get(f"http://{ainfo['addr']}/metrics")
            ).decode()
            await oc.close()
            await http.close()
            hashed = _hasher_bytes(metrics, "tpu")
            assert hashed >= len(blob), (
                f"agent verified {hashed} bytes on the tpu hasher, "
                f"expected >= {len(blob)}:\n{metrics[:2000]}"
            )
            assert "verify_pieces_total" in metrics, metrics[:2000]

        asyncio.run(drive())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_tpu_sharded_origin_serves_real_pull(tmp_path):
    """``hasher: tpu-sharded`` THROUGH THE PIPELINED INGEST PLANE,
    assembled via the production CLI on the real chip (a 1-device mesh:
    shard_map over the local device set, however many that is). A real
    upload streams its windows onto the chip at stream time
    (core/ingest.py), the served metainfo's piece hashes are compared
    bit-for-bit against an in-process CPU hashlib oracle, a real agent
    pulls the blob, and the ingest plane's own gauges move on the
    origin's /metrics."""
    procs = []
    try:
        oport = _free_port()
        tracker, tinfo = _spawn(
            ["tracker", "--origins", f"127.0.0.1:{oport}"], tpu=False
        )
        procs.append(tracker)
        # The `ingest:` section only ships via YAML -- exercise the same
        # config path production uses.
        cfg = tmp_path / "origin.yaml"
        cfg.write_text(
            "host: 127.0.0.1\n"
            "ingest:\n"
            "  window_bytes: 16777216\n"
            "  windows_in_flight: 2\n"
            "  pack_mode: host\n"
        )
        origin, oinfo = _spawn(
            ["origin", "--store", str(tmp_path / "origin"),
             "--port", str(oport), "--config", str(cfg),
             "--hasher", "tpu-sharded", "--tracker", tinfo["addr"]],
            tpu=True,
        )
        procs.append(origin)
        agent, ainfo = _spawn(
            ["agent", "--store", str(tmp_path / "agent"),
             "--tracker", tinfo["addr"]],
            tpu=False,
        )
        procs.append(agent)

        async def drive():
            from kraken_tpu.core.digest import Digest
            from kraken_tpu.core.hasher import get_hasher
            from kraken_tpu.core.metainfo import MetaInfo
            from kraken_tpu.origin.client import BlobClient
            from kraken_tpu.utils.httputil import HTTPClient

            blob = os.urandom(48 * 1024 * 1024)
            d = Digest.from_bytes(blob)
            oc = BlobClient(oinfo["addr"], HTTPClient(timeout_seconds=600))
            await oc.upload("ns", d, blob)
            http = HTTPClient(timeout_seconds=600)
            # The metainfo the chip produced at stream time must be
            # bit-identical to the CPU oracle -- the pipeline's whole
            # correctness contract in one assert.
            raw = await http.get(
                f"http://{oinfo['addr']}/namespace/ns/blobs/{d.hex}/metainfo"
            )
            mi = MetaInfo.deserialize(raw)
            want = get_hasher("cpu").hash_pieces(
                blob, mi.piece_length
            ).tobytes()
            assert mi.piece_hashes == want, "sharded digests != CPU oracle"
            got = await http.get(
                f"http://{ainfo['addr']}/namespace/ns/blobs/{d.hex}"
            )
            assert got == blob, "pulled bytes differ"
            metrics = (
                await http.get(f"http://{oinfo['addr']}/metrics")
            ).decode()
            await oc.close()
            await http.close()
            hashed = _hasher_bytes(metrics, "tpu-sharded")
            assert hashed >= len(blob), (
                f"sharded hasher covered {hashed} bytes, expected >= "
                f"{len(blob)}:\n{metrics[:2000]}"
            )
            # The window stream (not the legacy batch path) did the work.
            assert "ingest_windows_total" in metrics, metrics[:2000]
            assert "ingest_stage_seconds" in metrics, metrics[:2000]

        asyncio.run(drive())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

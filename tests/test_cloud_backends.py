"""Cloud-backend tests against in-process fakes (the testfs trick, applied
to S3, WebHDFS, and an upstream Docker registry).

The S3 fake verifies the SigV4 signature byte-for-byte (re-deriving it
server-side with the shared secret), so a signing bug fails loudly instead
of passing against a permissive fake.
"""

import asyncio
import hashlib
import json
import os
import urllib.parse

import pytest
from aiohttp import web

from kraken_tpu.backend import Manager as BackendManager, BlobNotFoundError
from kraken_tpu.backend.base import make_backend
from kraken_tpu.backend.s3backend import sigv4_headers


# -- fakes -------------------------------------------------------------------


class FakeS3:
    """In-memory S3: PUT/GET/HEAD objects + ListObjectsV2, SigV4-checked."""

    __test__ = False

    def __init__(self, access_key="AK", secret_key="SK", region="us-east-1"):
        self.objects: dict[str, bytes] = {}
        self.access_key, self.secret_key, self.region = (
            access_key, secret_key, region,
        )
        self.addr = ""
        self._runner = None
        self.multipart: dict[str, dict[int, bytes]] = {}  # uploadId -> parts
        self.multipart_initiated = 0
        self.multipart_aborted = 0

    def _check_sig(self, req: web.Request, body: bytes) -> None:
        auth = req.headers.get("Authorization", "")
        assert auth.startswith("AWS4-HMAC-SHA256 "), "missing SigV4 header"
        payload_sha = req.headers["x-amz-content-sha256"]
        assert payload_sha == hashlib.sha256(body).hexdigest()
        # Re-derive with the shared secret at the client's stated time.
        import datetime

        amz = req.headers["x-amz-date"]
        now = datetime.datetime.strptime(amz, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=datetime.timezone.utc
        )
        url = f"http://{req.headers['Host']}{req.rel_url}"
        want = sigv4_headers(
            req.method, url, access_key=self.access_key,
            secret_key=self.secret_key, region=self.region,
            payload_sha256=payload_sha, now=now,
        )["Authorization"]
        assert auth == want, f"signature mismatch:\n got {auth}\nwant {want}"

    async def _handle(self, req: web.Request) -> web.Response:
        body = await req.read()
        self._check_sig(req, body)
        path = req.match_info["path"]
        bucket, _, key = path.partition("/")
        if req.method == "GET" and not key:
            prefix = req.query.get("prefix", "")
            keys = sorted(k for k in self.objects if k.startswith(prefix))
            items = "".join(f"<Contents><Key>{k}</Key></Contents>" for k in keys)
            xml = (
                "<?xml version='1.0'?><ListBucketResult>"
                f"<IsTruncated>false</IsTruncated>{items}</ListBucketResult>"
            )
            return web.Response(text=xml, content_type="application/xml")
        # Multipart dance: initiate / upload part / complete / abort.
        if req.method == "POST" and "uploads" in req.query:
            uid = f"mpu-{len(self.multipart)}"
            self.multipart[uid] = {}
            self.multipart_initiated += 1
            return web.Response(
                text=(
                    "<?xml version='1.0'?><InitiateMultipartUploadResult>"
                    f"<UploadId>{uid}</UploadId>"
                    "</InitiateMultipartUploadResult>"
                ),
                content_type="application/xml",
            )
        if req.method == "PUT" and "partNumber" in req.query:
            parts = self.multipart.get(req.query.get("uploadId", ""))
            if parts is None:
                return web.Response(status=404)
            n = int(req.query["partNumber"])
            parts[n] = body
            etag = hashlib.md5(body).hexdigest()
            return web.Response(status=200, headers={"ETag": f'"{etag}"'})
        if req.method == "POST" and "uploadId" in req.query:
            parts = self.multipart.pop(req.query["uploadId"], None)
            if parts is None:
                return web.Response(status=404)
            # Complete must reference every stored part, in order.
            import re as _re

            want_nums = sorted(parts)
            got_nums = [
                int(m) for m in _re.findall(
                    r"<PartNumber>(\d+)</PartNumber>", body.decode()
                )
            ]
            assert got_nums == want_nums, (got_nums, want_nums)
            # Real S3 rejects a complete whose ETags don't match the
            # stored parts (InvalidPart) -- enforce it so an empty or
            # wrong <ETag> fails here like it would in production.
            got_etags = _re.findall(r"<ETag>([^<]*)</ETag>", body.decode())
            want_etags = [
                hashlib.md5(parts[n]).hexdigest() for n in want_nums
            ]
            if got_etags != want_etags:
                return web.Response(status=400, text="InvalidPart")
            self.objects[key] = b"".join(parts[n] for n in want_nums)
            return web.Response(
                text=(
                    "<?xml version='1.0'?><CompleteMultipartUploadResult>"
                    f"<Key>{key}</Key></CompleteMultipartUploadResult>"
                ),
                content_type="application/xml",
            )
        if req.method == "DELETE" and "uploadId" in req.query:
            self.multipart.pop(req.query["uploadId"], None)
            self.multipart_aborted += 1
            return web.Response(status=204)
        if req.method == "PUT":
            self.objects[key] = body
            return web.Response(status=200)
        if key not in self.objects:
            return web.Response(status=404)
        if req.method == "HEAD":
            return web.Response(
                headers={"Content-Length": str(len(self.objects[key]))}
            )
        return web.Response(body=self.objects[key])

    async def __aenter__(self):
        app = web.Application()
        app.router.add_route("*", "/{path:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.addr = f"127.0.0.1:{port}"
        return self

    async def __aexit__(self, *exc):
        await self._runner.cleanup()


class FakeWebHDFS:
    """Namenode + datanode in one app, with the real 307 CREATE dance."""

    __test__ = False

    def __init__(self):
        self.files: dict[str, bytes] = {}
        self.addr = ""
        self._runner = None

    async def _handle(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        op = req.query.get("op", "").upper()
        if op == "CREATE":
            if req.query.get("step") != "2":
                q = dict(req.query)
                q["step"] = "2"
                loc = (
                    f"http://{self.addr}/webhdfs/v1"
                    f"{urllib.parse.quote(path)}?{urllib.parse.urlencode(q)}"
                )
                return web.Response(status=307, headers={"Location": loc})
            self.files[path] = await req.read()
            return web.Response(status=201)
        if op == "GETFILESTATUS":
            if path not in self.files:
                return web.Response(status=404)
            return web.json_response(
                {"FileStatus": {"length": len(self.files[path])}}
            )
        if op == "OPEN":
            if path not in self.files:
                return web.Response(status=404)
            return web.Response(body=self.files[path])
        if op == "LISTSTATUS":
            suffixes = [
                f[len(path) :].lstrip("/")
                for f in self.files
                if f.startswith(path)
            ]
            if not suffixes:
                return web.Response(status=404)
            return web.json_response(
                {"FileStatuses": {"FileStatus": [
                    {"pathSuffix": s} for s in sorted(suffixes)
                ]}}
            )
        return web.Response(status=400)

    async def __aenter__(self):
        app = web.Application()
        app.router.add_route("*", "/webhdfs/v1/{path:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.addr = f"127.0.0.1:{port}"
        return self

    async def __aexit__(self, *exc):
        await self._runner.cleanup()


class FakeUpstreamRegistry:
    """Minimal Docker registry v2: blobs + manifests with content digests.

    With ``token_auth=True`` it enforces the docker token flow (as Docker
    Hub/GHCR do): v2 requests without a valid Bearer token get 401 + a
    ``WWW-Authenticate`` challenge pointing at ``/token``; the token
    endpoint requires basic credentials iff ``username`` is set."""

    __test__ = False

    def __init__(self, token_auth: bool = False, username: str = "", password: str = "", redirect_blobs: bool = False):
        self.blobs: dict[str, bytes] = {}  # "repo/sha256:hex" -> bytes
        self.manifests: dict[str, bytes] = {}  # "repo:tag" -> manifest bytes
        self.addr = ""
        self._runner = None
        self.token_auth = token_auth
        self.username = username
        self.password = password
        self.token_fetches = 0
        self._token = "fake-jwt-0123"
        # Real upstreams 307 authorized blob GETs to a presigned CDN URL
        # that REJECTS an Authorization header (S3 allows only one auth
        # mechanism); redirect_blobs models that.
        self.redirect_blobs = redirect_blobs

    def _challenge(self, req: web.Request) -> web.Response | None:
        if not self.token_auth:
            return None
        if req.headers.get("Authorization") == f"Bearer {self._token}":
            return None
        return web.Response(
            status=401,
            headers={
                "WWW-Authenticate": (
                    f'Bearer realm="http://{self.addr}/token",'
                    f'service="fake-registry",'
                    f'scope="repository:{req.match_info["repo"]}:pull"'
                )
            },
        )

    async def _token_endpoint(self, req: web.Request) -> web.Response:
        if self.username:
            import base64 as b64

            want = "Basic " + b64.b64encode(
                f"{self.username}:{self.password}".encode()
            ).decode()
            if req.headers.get("Authorization") != want:
                return web.Response(status=401)
        assert req.query.get("service") == "fake-registry"
        assert req.query.get("scope", "").startswith("repository:")
        self.token_fetches += 1
        return web.json_response({"token": self._token, "expires_in": 300})

    async def _blob(self, req: web.Request) -> web.Response:
        denied = self._challenge(req)
        if denied is not None:
            return denied
        key = f"{req.match_info['repo']}/{req.match_info['digest']}"
        data = self.blobs.get(key)
        if data is None:
            return web.Response(status=404)
        if self.redirect_blobs and req.method == "GET":
            return web.Response(status=307, headers={
                "Location": (
                    f"http://{self.addr}/cdn/{key}?X-Amz-Signature=fake"
                ),
            })
        headers = {"Content-Length": str(len(data))}
        if req.method == "HEAD":
            return web.Response(headers=headers)
        return web.Response(body=data, headers=headers)

    async def _cdn(self, req: web.Request) -> web.Response:
        if "Authorization" in req.headers:
            # S3's "Only one auth mechanism allowed" on presigned URLs.
            return web.Response(status=400, text="OnlyOneAuthMechanismAllowed")
        data = self.blobs.get(req.match_info["key"])
        if data is None:
            return web.Response(status=404)
        return web.Response(body=data)

    async def _manifest(self, req: web.Request) -> web.Response:
        denied = self._challenge(req)
        if denied is not None:
            return denied
        key = f"{req.match_info['repo']}:{req.match_info['ref']}"
        data = self.manifests.get(key)
        if data is None:
            return web.Response(status=404)
        d = "sha256:" + hashlib.sha256(data).hexdigest()
        return web.Response(body=data, headers={"Docker-Content-Digest": d})

    async def __aenter__(self):
        app = web.Application()
        app.router.add_get("/token", self._token_endpoint)
        app.router.add_get("/cdn/{key:.+}", self._cdn)
        app.router.add_route(
            "*", "/v2/{repo:.+}/blobs/{digest}", self._blob
        )
        app.router.add_route(
            "*", "/v2/{repo:.+}/manifests/{ref}", self._manifest
        )
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.addr = f"127.0.0.1:{port}"
        return self

    async def __aexit__(self, *exc):
        await self._runner.cleanup()


# -- s3 ----------------------------------------------------------------------


def test_sigv4_canonical_uri_is_single_encoded():
    """Keys needing percent-encoding (':', '+', space) must sign with the
    request path as-sent, NOT re-encoded ('%' -> '%25' would yield
    SignatureDoesNotMatch on real AWS/GCS).

    Verified against an INDEPENDENT SigV4 derivation below (canonical
    request built by hand from the AWS spec) -- the FakeS3 re-derives with
    the same sigv4_headers function, so it structurally cannot catch a
    canonicalization bug.
    """
    import datetime
    import hashlib as _hl
    import hmac as _hmac

    key = "repo:tag+v1 latest"  # ':' '+' ' ' all need encoding
    quoted = urllib.parse.quote(key)  # single-encoded, as _url() sends it
    assert "%" in quoted
    url = f"https://bucket.example.com/{quoted}"
    access, secret, region = "AKIDEXAMPLE", "SECRETEXAMPLE", "us-west-2"
    now = datetime.datetime(2026, 7, 29, 12, 0, 0,
                            tzinfo=datetime.timezone.utc)
    payload_sha = _hl.sha256(b"").hexdigest()

    got = sigv4_headers(
        "GET", url, access_key=access, secret_key=secret, region=region,
        payload_sha256=payload_sha, now=now,
    )["Authorization"]

    # Independent derivation, straight from the SigV4 spec: the canonical
    # URI is the absolute path exactly as it appears on the wire.
    creq = "\n".join((
        "GET",
        "/" + quoted,
        "",
        f"host:bucket.example.com\nx-amz-content-sha256:{payload_sha}\n"
        f"x-amz-date:20260729T120000Z\n",
        "host;x-amz-content-sha256;x-amz-date",
        payload_sha,
    ))
    scope = f"20260729/{region}/s3/aws4_request"
    sts = "\n".join((
        "AWS4-HMAC-SHA256", "20260729T120000Z", scope,
        _hl.sha256(creq.encode()).hexdigest(),
    ))
    k = _hmac.new(b"AWS4" + secret.encode(), b"20260729",
                  _hl.sha256).digest()
    for step in (region, "s3", "aws4_request"):
        k = _hmac.new(k, step.encode(), _hl.sha256).digest()
    sig = _hmac.new(k, sts.encode(), _hl.sha256).hexdigest()
    want = (
        f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
        f"SignedHeaders=host;x-amz-content-sha256;x-amz-date, "
        f"Signature={sig}"
    )
    assert got == want


def test_s3_roundtrip_stat_list_and_missing():
    async def main():
        async with FakeS3() as s3:
            client = make_backend("s3", {
                "endpoint": f"http://{s3.addr}", "bucket": "blobs",
                "access_key": "AK", "secret_key": "SK",
            })
            try:
                name = "a" * 64
                await client.upload("ns", name, b"hello s3")
                assert (await client.stat("ns", name)).size == 8
                assert await client.download("ns", name) == b"hello s3"
                keys = await client.list("")
                assert keys == [f"{name[:2]}/{name[2:4]}/{name}"]
                with pytest.raises(BlobNotFoundError):
                    await client.download("ns", "b" * 64)
                with pytest.raises(BlobNotFoundError):
                    await client.stat("ns", "b" * 64)
            finally:
                await client.close()

    asyncio.run(main())


def test_gcs_registration_uses_s3_client():
    client = make_backend("gcs", {"bucket": "b"})
    assert client.endpoint == "https://storage.googleapis.com"


# -- hdfs --------------------------------------------------------------------


def test_hdfs_roundtrip_and_list():
    async def main():
        async with FakeWebHDFS() as nn:
            client = make_backend("hdfs", {
                "namenode": f"http://{nn.addr}", "root": "infra/dockerRegistry",
            })
            try:
                name = "c" * 64
                await client.upload("ns", name, b"hdfs bytes")
                assert (await client.stat("ns", name)).size == 10
                assert await client.download("ns", name) == b"hdfs bytes"
                assert await client.list("") == [
                    f"{name[:2]}/{name[2:4]}/{name}"
                ]
                with pytest.raises(BlobNotFoundError):
                    await client.download("ns", "d" * 64)
            finally:
                await client.close()

    asyncio.run(main())


# -- registry pull-through ---------------------------------------------------


def test_registry_blob_and_tag_backends():
    async def main():
        async with FakeUpstreamRegistry() as up:
            layer = b"layer-bytes" * 100
            d = "sha256:" + hashlib.sha256(layer).hexdigest()
            up.blobs[f"library/nginx/{d}"] = layer
            manifest = json.dumps({"layers": [{"digest": d}]}).encode()
            up.manifests["library/nginx:latest"] = manifest

            blobs = make_backend("registry_blob", {"address": up.addr})
            tags = make_backend("registry_tag", {"address": up.addr})
            try:
                got = await blobs.download("library/nginx", d.split(":")[1])
                assert got == layer
                assert (await blobs.stat("library/nginx", d)).size == len(layer)
                with pytest.raises(BlobNotFoundError):
                    await blobs.download("library/nginx", "0" * 64)
                tag_val = await tags.download("x", "library/nginx:latest")
                want = "sha256:" + hashlib.sha256(manifest).hexdigest()
                assert tag_val.decode() == want
            finally:
                await blobs.close()
                await tags.close()

    asyncio.run(main())


def test_origin_pulls_through_upstream_registry(tmp_path):
    """Herd-level: the blob exists ONLY in the upstream registry; an origin
    with a registry_blob backend serves it via blobrefresh pull-through."""

    async def main():
        from aiohttp import ClientSession

        from kraken_tpu.assembly import OriginNode

        async with FakeUpstreamRegistry() as up:
            layer = b"only-upstream" * 4096
            d = "sha256:" + hashlib.sha256(layer).hexdigest()
            up.blobs[f"library/app/{d}"] = layer

            backends = BackendManager([
                {"namespace": "library/.*", "backend": "registry_blob",
                 "config": {"address": up.addr}},
            ])
            node = OriginNode(
                store_root=str(tmp_path / "o"), backends=backends
            )
            await node.start()
            try:
                async with ClientSession() as http:
                    url = (
                        f"http://{node.addr}/namespace/library%2Fapp/blobs/{d}"
                    )
                    async with http.get(url) as r:
                        assert r.status == 200, await r.text()
                        assert await r.read() == layer
            finally:
                await node.stop()
                await backends.close()

    asyncio.run(main())


def test_registry_backend_token_auth_flow():
    """The docker token flow against a challenging upstream: 401 Bearer
    challenge -> token fetch (with basic creds) -> retried request; the
    token is CACHED per scope (one fetch serves repeated pulls) and bad
    credentials surface as BackendError, not a raw 401."""

    async def main():
        async with FakeUpstreamRegistry(
            token_auth=True, username="puller", password="hunter2"
        ) as up:
            layer = b"private-layer" * 50
            d = "sha256:" + hashlib.sha256(layer).hexdigest()
            up.blobs[f"acme/app/{d}"] = layer
            manifest = json.dumps({"layers": [{"digest": d}]}).encode()
            up.manifests["acme/app:v1"] = manifest

            blobs = make_backend("registry_blob", {
                "address": up.addr, "username": "puller",
                "password": "hunter2",
            })
            tags = make_backend("registry_tag", {
                "address": up.addr, "username": "puller",
                "password": "hunter2",
            })
            try:
                assert await blobs.download("acme/app", d) == layer
                assert (await blobs.stat("acme/app", d)).size == len(layer)
                assert await blobs.download("acme/app", d) == layer
                # One scope, many requests: exactly one token fetch.
                assert up.token_fetches == 1, up.token_fetches
                got = await tags.download("x", "acme/app:v1")
                want = "sha256:" + hashlib.sha256(manifest).hexdigest()
                assert got.decode() == want
                # 404 vs auth stays distinguishable through the flow.
                with pytest.raises(BlobNotFoundError):
                    await blobs.download("acme/app", "0" * 64)
            finally:
                await blobs.close()
                await tags.close()

            from kraken_tpu.backend.base import BackendError

            bad = make_backend("registry_blob", {
                "address": up.addr, "username": "puller",
                "password": "wrong",
            })
            try:
                with pytest.raises(BackendError, match="credentials"):
                    await bad.download("acme/app", d)
            finally:
                await bad.close()

    asyncio.run(main())


def test_registry_backend_anonymous_token_flow():
    """Public upstreams still challenge: the anonymous flow (no creds on
    the token fetch) must work, as docker pulls of public images do."""

    async def main():
        async with FakeUpstreamRegistry(token_auth=True) as up:
            layer = b"public-layer" * 50
            d = "sha256:" + hashlib.sha256(layer).hexdigest()
            up.blobs[f"library/nginx/{d}"] = layer
            blobs = make_backend("registry_blob", {"address": up.addr})
            try:
                assert await blobs.download("library/nginx", d) == layer
                assert up.token_fetches == 1
            finally:
                await blobs.close()

    asyncio.run(main())


def test_s3_multipart_upload_file(tmp_path):
    """Large files take the multipart path (initiate / parts / complete,
    every request SigV4-checked by the fake), small ones the single PUT;
    download_to_file streams back byte-identically; a failed part aborts
    the multipart upload instead of leaking billed orphan parts."""

    async def main():
        async with FakeS3() as s3:
            client = make_backend("s3", {
                "endpoint": f"http://{s3.addr}", "bucket": "bkt",
                "access_key": s3.access_key, "secret_key": s3.secret_key,
                "region": s3.region, "pather": "identity",
                # Tiny thresholds so the test stays KB-scale; the part
                # size floor (5 MiB) is production-only policy, so reach
                # under it for the test.
                "multipart_threshold": 1024,
            })
            client.multipart_part_size = 700
            try:
                big = tmp_path / "big.bin"
                payload = bytes(range(256)) * 10  # 2560 B -> 4 parts of 700
                big.write_bytes(payload)
                await client.upload_file("ns", "bigkey", str(big))
                assert s3.multipart_initiated == 1
                assert s3.objects["bigkey"] == payload

                dest = tmp_path / "restored.bin"
                n = await client.download_to_file("ns", "bigkey", str(dest))
                assert n == len(payload)
                assert dest.read_bytes() == payload

                small = tmp_path / "small.bin"
                small.write_bytes(b"tiny")
                await client.upload_file("ns", "smallkey", str(small))
                assert s3.multipart_initiated == 1  # no new multipart
                assert s3.objects["smallkey"] == b"tiny"

                # Part failure -> abort: break the fake mid-upload by
                # forgetting the uploadId after initiate.
                orig = s3.multipart
                class Vanishing(dict):
                    def __setitem__(self, k, v):
                        super().__setitem__(k, v)
                    def get(self, k, default=None):
                        return None  # every part PUT sees a dead session
                s3.multipart = Vanishing()
                from kraken_tpu.utils.httputil import HTTPError

                with pytest.raises(HTTPError):
                    await client.upload_file("ns", "failkey", str(big))
                assert s3.multipart_aborted >= 1
                s3.multipart = orig
                assert "failkey" not in s3.objects
            finally:
                await client.close()

    asyncio.run(main())


def test_registry_backend_presigned_redirect_drops_auth():
    """Authorized blob GETs that 307 to a presigned CDN URL must follow
    the redirect WITHOUT the Authorization header (S3 rejects mixed auth
    mechanisms); the token cache must also key on the caller's scope so
    repeated pulls don't re-fetch tokens."""

    async def main():
        async with FakeUpstreamRegistry(
            token_auth=True, redirect_blobs=True
        ) as up:
            layer = b"cdn-layer" * 64
            d = "sha256:" + hashlib.sha256(layer).hexdigest()
            up.blobs[f"library/redis/{d}"] = layer
            blobs = make_backend("registry_blob", {"address": up.addr})
            try:
                assert await blobs.download("library/redis", d) == layer
                assert await blobs.download("library/redis", d) == layer
                assert up.token_fetches == 1, up.token_fetches
            finally:
                await blobs.close()

    asyncio.run(main())


def test_origin_writeback_uses_s3_multipart(tmp_path):
    """End-to-end: a committed blob above the multipart threshold rides
    origin writeback -> S3Backend.upload_file -> the real multipart
    dance (SigV4-checked by the fake), landing byte-identically and
    restorable via the streamed download path."""
    from kraken_tpu.assembly import OriginNode
    from kraken_tpu.core.digest import Digest
    from kraken_tpu.origin.client import BlobClient

    async def main():
        async with FakeS3() as s3:
            backends = BackendManager([{
                "namespace": ".*", "backend": "s3",
                "config": {
                    "endpoint": f"http://{s3.addr}", "bucket": "bkt",
                    "access_key": s3.access_key, "secret_key": s3.secret_key,
                    "region": s3.region, "pather": "identity",
                    "multipart_threshold": 64 * 1024,
                },
            }])
            # Force small parts so a 300 KB blob takes several.
            backends.get_client("ns").multipart_part_size = 100 * 1024
            origin = OriginNode(
                store_root=str(tmp_path / "o"), backends=backends,
                dedup=False,
            )
            await origin.start()
            oc = BlobClient(origin.addr)
            try:
                blob = os.urandom(300_000)
                d = Digest.from_bytes(blob)
                await oc.upload("ns", d, blob)
                for _ in range(50):
                    await origin.retry.run_once()
                    if d.hex in s3.objects:
                        break
                    await asyncio.sleep(0.05)
                assert s3.objects.get(d.hex) == blob, "writeback never landed"
                assert s3.multipart_initiated == 1, "single PUT was used"

                # Evict locally, restore via blobrefresh's streamed path.
                origin.store.delete_cache_file(d)
                assert not origin.store.in_cache(d)
                await origin.refresher.refresh("ns", d)
                assert origin.store.read_cache_file(d) == blob
            finally:
                await oc.close()
                await origin.stop()

    asyncio.run(main())

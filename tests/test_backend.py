"""Backend registry / manager / testfs / file backend tests (tier 1-2)."""

import asyncio

import pytest

from kraken_tpu.backend import BlobNotFoundError, Manager
from kraken_tpu.backend.base import make_backend
from kraken_tpu.backend.namepath import get_pather
from kraken_tpu.backend.testfs import TestFSServer


def run(coro):
    return asyncio.run(coro)


# -- namepath ---------------------------------------------------------------

def test_pathers():
    hex64 = "ab" * 32
    assert get_pather("identity")("", "x/y") == "x/y"
    assert get_pather("identity")("root", "x") == "root/x"
    assert (
        get_pather("sharded_docker_blob")("blobs", hex64)
        == f"blobs/ab/ab/{hex64}"
    )
    assert (
        get_pather("docker_tag")("tags", "library/nginx:latest")
        == "tags/library/nginx/_manifests/tags/latest/current/link"
    )
    with pytest.raises(ValueError):
        get_pather("docker_tag")("", "notag")


# -- file backend -----------------------------------------------------------

def test_file_backend_roundtrip(tmp_path):
    async def main():
        c = make_backend("file", {"root": str(tmp_path / "be")})
        await c.upload("ns", "a/b/blob1", b"data1")
        await c.upload("ns", "a/blob2", b"data2")
        assert await c.download("ns", "a/b/blob1") == b"data1"
        assert (await c.stat("ns", "a/blob2")).size == 5
        assert await c.list("a/") == ["a/b/blob1", "a/blob2"]
        with pytest.raises(BlobNotFoundError):
            await c.download("ns", "missing")
        with pytest.raises(BlobNotFoundError):
            await c.stat("ns", "missing")

    run(main())


# -- testfs server + client -------------------------------------------------

def test_testfs_roundtrip():
    async def main():
        async with TestFSServer() as srv:
            c = make_backend("testfs", {"addr": srv.addr})
            await c.upload("ns", "dir/blob", b"hello world")
            assert await c.download("ns", "dir/blob") == b"hello world"
            assert (await c.stat("ns", "dir/blob")).size == 11
            await c.upload("ns", "dir/other", b"x")
            assert await c.list("dir/") == ["dir/blob", "dir/other"]
            with pytest.raises(BlobNotFoundError):
                await c.download("ns", "nope")
            await c.close()

    run(main())


# -- shadow backend ---------------------------------------------------------

def test_shadow_backend(tmp_path):
    async def main():
        c = make_backend(
            "shadow",
            {
                "primary": {"backend": "file", "config": {"root": str(tmp_path / "p")}},
                "shadow": {"backend": "file", "config": {"root": str(tmp_path / "s")}},
            },
        )
        await c.upload("ns", "blob", b"dual")
        p = make_backend("file", {"root": str(tmp_path / "p")})
        s = make_backend("file", {"root": str(tmp_path / "s")})
        assert await p.download("ns", "blob") == b"dual"
        assert await s.download("ns", "blob") == b"dual"
        # primary miss falls through to shadow
        await s.upload("ns", "only-shadow", b"sh")
        assert await c.download("ns", "only-shadow") == b"sh"

    run(main())


# -- manager ----------------------------------------------------------------

def test_manager_namespace_resolution(tmp_path):
    async def main():
        m = Manager(
            [
                {
                    "namespace": r"library/.*",
                    "backend": "file",
                    "config": {"root": str(tmp_path / "lib")},
                },
                {
                    "namespace": r".*",
                    "backend": "file",
                    "config": {"root": str(tmp_path / "default")},
                },
            ]
        )
        lib = m.get_client("library/nginx")
        default = m.get_client("other/repo")
        assert lib is not default
        # first match wins
        assert m.get_client("library/x") is lib
        assert m.try_get_client("anything") is default
        await m.close()

    run(main())


def test_manager_no_match():
    m = Manager([])
    with pytest.raises(KeyError):
        m.get_client("ns")
    assert m.try_get_client("ns") is None


def test_unknown_backend():
    with pytest.raises(KeyError):
        make_backend("s4")


# -- bandwidth-capped client ------------------------------------------------

def test_throttled_backend(tmp_path):
    async def main():
        import time

        m = Manager(
            [
                {
                    "namespace": ".*",
                    "backend": "file",
                    "config": {"root": str(tmp_path / "bw")},
                    "bandwidth": {"ingress_bps": 50_000, "egress_bps": 0},
                }
            ]
        )
        c = m.get_client("ns")
        await c.upload("ns", "blob", bytes(30_000))
        t0 = time.monotonic()
        await c.download("ns", "blob")  # within burst capacity
        await c.download("ns", "blob")  # exceeds burst -> throttled ~0.2s
        elapsed = time.monotonic() - t0
        assert elapsed > 0.1

    run(main())

"""Host->device overlap efficiency (SURVEY.md SS7 hard part #2).

The metainfo-gen staging pipeline relies on JAX async dispatch to
overlap host->device feeding of sub-batch i+1 with hashing of sub-batch
i. This rig's ~25 MB/s relay makes the ABSOLUTE feed rate meaningless
(production PCIe is ~3 orders faster), but the overlap SHAPE is
measurable anywhere:

    ratio = wall(pipelined feed+compute) / max(wall(feed), wall(compute))

ratio ~1.0 = the pipeline hides the smaller cost behind the larger, as
designed; ~2.0 = the runtime serializes transfers against compute. To
make the test non-trivial the per-batch compute is calibrated to match
the per-batch feed time (r chained kernel passes via lax.fori_loop --
the hardest case for overlap; with unbalanced loads the ratio is
trivially ~1).

Prints ONE JSON line. Runs on the TPU by default; OVERLAP_BATCHES /
OVERLAP_MB tune the load.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

K = int(os.environ.get("OVERLAP_BATCHES", 6))
BATCH_MB = float(os.environ.get("OVERLAP_MB", 4))
PIECES = 1024


def main() -> None:
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.sha256 import _digest_bytes
    from kraken_tpu.ops.sha256_pallas import hash_pieces_device

    piece_len = int(BATCH_MB * (1 << 20)) // PIECES // 64 * 64
    batch_bytes = PIECES * piece_len
    rng = np.random.default_rng(0)
    batches = [
        rng.integers(0, 256, size=(PIECES, piece_len), dtype=np.uint8)
        for _ in range(K)
    ]

    # Warmup + correctness gate on the kernel.
    import hashlib

    dev0 = jax.device_put(batches[0])
    dig = _digest_bytes(hash_pieces_device(dev0, piece_len)[:1])
    assert dig[0].tobytes() == hashlib.sha256(
        batches[0][0].tobytes()
    ).digest(), "kernel digest mismatch"

    # Calibrate: single-pass kernel wall (resident) vs single-batch feed.
    t0 = time.perf_counter()
    hash_pieces_device(dev0, piece_len).block_until_ready()
    kernel_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.device_put(batches[1]).block_until_ready()
    feed_s = time.perf_counter() - t0
    r = max(1, min(100_000, round(feed_s / max(kernel_s, 1e-6))))

    def make_hash_r(reps: int):
        @jax.jit
        def hash_r(x):
            # reps chained passes: each iteration's input depends on the
            # last digest, so XLA cannot hoist the loop-invariant hash.
            def body(_i, carry):
                x_i, acc = carry
                d = hash_pieces_device(x_i, piece_len)
                salt = (d[0, 0] & jnp.uint32(0xFF)).astype(jnp.uint8)
                return x_i ^ salt, acc ^ d
            _, acc = jax.lax.fori_loop(
                0, reps, body,
                (x, jnp.zeros((PIECES, 8), dtype=jnp.uint32)),
            )
            return acc

        hash_r(dev0).block_until_ready()  # compile
        return hash_r

    hash_r = make_hash_r(r)

    # Feed-only: issue every transfer, then block all (max transfer
    # pipelining allowed -- a pessimistic baseline would inflate ratio).
    t0 = time.perf_counter()
    devs = [jax.device_put(b) for b in batches]
    for d in devs:
        d.block_until_ready()
    wall_feed = time.perf_counter() - t0
    del devs

    def compute_only() -> float:
        t0 = time.perf_counter()
        outs = [hash_r(dev0) for _ in range(K)]
        for o in outs:
            o.block_until_ready()
        return time.perf_counter() - t0

    wall_comp = compute_only()
    # Rebalance once: single-call calibration under-counts dispatch RTT,
    # and an unbalanced test proves little (the ratio is trivially ~1
    # when one side dominates). Scale r toward wall_feed and re-measure.
    if not 0.67 <= wall_comp / wall_feed <= 1.5:
        r = max(1, min(100_000, round(r * wall_feed / wall_comp)))
        hash_r = make_hash_r(r)
        wall_comp = compute_only()

    def feed_only() -> float:
        t0 = time.perf_counter()
        devs = [jax.device_put(b) for b in batches]
        for d in devs:
            d.block_until_ready()
        return time.perf_counter() - t0

    def pipelined() -> float:
        # Feed batch i+1 while batch i hashes.
        t0 = time.perf_counter()
        outs = [hash_r(jax.device_put(b)) for b in batches]
        for o in outs:
            o.block_until_ready()
        return time.perf_counter() - t0

    # The relay's throughput drifts tens of percent across minutes, so
    # phases measured far apart produce garbage ratios. Each TRIAL runs
    # feed/compute/pipelined back-to-back and yields one ratio; the
    # median across trials is the reported number.
    trials = []
    for _ in range(5):
        f, c, p = feed_only(), compute_only(), pipelined()
        trials.append({
            "feed_s": round(f, 3), "compute_s": round(c, 3),
            "pipelined_s": round(p, 3),
            "ratio": round(p / max(f, c), 3),
        })
    ratios = sorted(t["ratio"] for t in trials)
    ratio = ratios[len(ratios) // 2]
    med_feed = sorted(t["feed_s"] for t in trials)[len(trials) // 2]
    print(json.dumps({
        "metric": "feed_compute_overlap_ratio",
        "value": ratio,
        "unit": "wall(pipelined) / max(wall(feed), wall(compute)), median of 5",
        "vs_baseline": round(ratio / 1.15, 3),  # target <= 1.15
        "batches": K,
        "batch_mb": round(batch_bytes / 1e6, 2),
        "kernel_passes_per_batch": r,
        "trials": trials,
        "feed_mbps": round(K * batch_bytes / med_feed / 1e6, 1),
    }))


if __name__ == "__main__":
    main()

"""MinHash/LSH index benchmark at survey scale.

BASELINE.json config #5: "MinHash/SimHash index, 1M layer chunk-sets,
top-k recall vs brute force -- measure". This drives the production index
(kraken_tpu/ops/minhash.py: MinHasher 128 hashes, LSHIndex 32 bands) on a
corpus of N synthetic layer chunk-fingerprint sets with planted
near-duplicates across the Jaccard range, and reports:

- recall@10 vs the brute-force oracle (restricted to true matches with
  J >= 0.3, i.e. above the LSH S-curve knee at ~0.42 where retrieval is
  the design intent);
- planted-pair retrieval rate per Jaccard bucket (the operative number:
  "if a layer J-similar to a stored one arrives, do we find it?");
- sketch throughput (TPU-batched), index build rate, and query rate.

Prints ONE JSON line. N defaults to 100k sets (~128 chunks each ~= a 8
MiB layer at 64 KiB chunks -- so the default models a ~0.8 TiB corpus);
override with MINHASH_N. Memory is O(N * 128) u32 for sketches.

Run on TPU (default platform) or CPU (JAX_PLATFORMS=cpu).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N = int(os.environ.get("MINHASH_N", 100_000))
CHUNKS_PER_SET = int(os.environ.get("MINHASH_CHUNKS", 128))
N_QUERIES = int(os.environ.get("MINHASH_QUERIES", 500))
J_BUCKETS = (0.3, 0.5, 0.7, 0.9)


def make_corpus(rng: np.random.Generator):
    """N fingerprint sets; the last len(J_BUCKETS)*Q sets are planted
    near-duplicates of base sets at controlled Jaccard levels."""
    sets = [
        rng.integers(1, 1 << 32, size=CHUNKS_PER_SET, dtype=np.uint64)
        .astype(np.uint32)
        for _ in range(N)
    ]
    planted = []  # (query_idx, target_idx, j_expected)
    q_per_bucket = N_QUERIES // len(J_BUCKETS)
    next_idx = N
    for j in J_BUCKETS:
        for _ in range(q_per_bucket):
            base_idx = int(rng.integers(0, N))
            base = sets[base_idx]
            # |A n B| / |A u B| = j with |A| = |B| = m: share s = 2j/(1+j)
            m = len(base)
            shared = int(round(m * 2 * j / (1 + j)))
            q = np.concatenate([
                base[:shared],
                rng.integers(1, 1 << 32, size=m - shared, dtype=np.uint64)
                .astype(np.uint32),
            ])
            sets.append(q)
            planted.append((next_idx, base_idx, j))
            next_idx += 1
    return sets, planted


def main():
    from kraken_tpu.ops.minhash import LSHIndex, MinHasher

    rng = np.random.default_rng(7)
    sets, planted = make_corpus(rng)
    hasher = MinHasher(num_hashes=128)

    # Sketch: TPU-batched in fixed groups.
    t0 = time.perf_counter()
    sketches = []
    B = 2048
    for s in range(0, len(sets), B):
        sketches.append(hasher.sketch_batch(sets[s : s + B]))
    sketches = np.concatenate(sketches)
    sketch_s = time.perf_counter() - t0
    sets_per_s = len(sets) / sketch_s

    # Build the index over the N corpus sets (queries stay out).
    index = LSHIndex(hasher, num_bands=32)
    t0 = time.perf_counter()
    for i in range(N):
        index.add(i, sketches[i])
    build_s = time.perf_counter() - t0

    # Planted-pair retrieval + recall@10 vs brute force.
    hits_by_j = {j: 0 for j in J_BUCKETS}
    count_by_j = {j: 0 for j in J_BUCKETS}
    recall_sum = 0.0
    recall_n = 0
    t0 = time.perf_counter()
    results = [index.query(sketches[qi], k=10) for qi, _t, _j in planted]
    query_s = time.perf_counter() - t0
    for (qi, target, j), got in zip(planted, results):
        count_by_j[j] += 1
        if any(key == target for key, _score in got):
            hits_by_j[j] += 1
        oracle = [
            key
            for key, score in index.query_brute(sketches[qi], k=10)
            if score >= 0.3
        ]
        if oracle:
            found = {key for key, _ in got}
            recall_sum += len(found & set(oracle)) / len(oracle)
            recall_n += 1

    recall10 = recall_sum / max(1, recall_n)
    print(json.dumps({
        "metric": "minhash_lsh_recall_at_10",
        "value": round(recall10, 4),
        "unit": "fraction (vs brute-force oracle, J>=0.3)",
        "vs_baseline": round(recall10, 4),  # baseline target: measure
        "n_sets": len(sets),
        "planted_retrieval_by_jaccard": {
            str(j): round(hits_by_j[j] / max(1, count_by_j[j]), 4)
            for j in J_BUCKETS
        },
        "sketch_sets_per_s": round(sets_per_s),
        "index_adds_per_s": round(N / build_s),
        "queries_per_s": round(len(planted) / query_s),
    }))


if __name__ == "__main__":
    main()

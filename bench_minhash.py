"""MinHash/LSH index benchmark at survey scale.

BASELINE.json config #5: "MinHash/SimHash index, 1M layer chunk-sets,
top-k recall vs brute force -- measure". Drives the production index
(kraken_tpu/ops/minhash.py: MinHasher 128 hashes, 32 bands) on a corpus
of N synthetic layer chunk-fingerprint sets with planted near-duplicates
across the Jaccard range, and reports:

- recall@10 vs the brute-force oracle (restricted to true matches with
  J >= 0.3, i.e. above the LSH S-curve knee at ~0.42 where retrieval is
  the design intent);
- planted-pair retrieval rate per Jaccard bucket (the operative number:
  "if a layer J-similar to a stored one arrives, do we find it?");
- sketch throughput (TPU-batched), index build rate, query rate, peak
  RSS, and the index's accounted bytes/set;
- the 1M-set operating-point proofs (VERDICT r5 weak #4; compact index
  only): FORCED eviction (budget dropped to ``MINHASH_EVICT_FRAC`` of
  the built footprint -> ``forced_evictions > 0``; the long-standing
  ``evictions`` key keeps meaning build-time BUDGET_MB evictions),
  planted retrieval re-run on
  the surviving targets (``recall_after_eviction``), a restart
  index-rebuild wall clock (fresh index re-fed the live sketches, the
  sidecar-driven origin boot path, ``rebuild_s``), and an explicit
  peak-RSS budget (``MINHASH_RSS_BUDGET_MB``, default 6144 ->
  ``rss_within_budget``).

The corpus is generated-and-sketched in streaming batches (raw sets are
never all resident), so N=1,000,000 runs in ~1.2 GB of index memory.
Index implementation: ``CompactLSHIndex`` (array-backed, byte-budgeted)
for N > 200k or MINHASH_INDEX=compact; the dict-based ``LSHIndex`` (the
origin /similar path) otherwise. Prints ONE JSON line.

    MINHASH_N=1000000 python bench_minhash.py        # BASELINE row 5 scale
    MINHASH_BUDGET_MB=1500 MINHASH_N=1000000 ...     # with eviction budget

Run on TPU (default platform) or CPU (JAX_PLATFORMS=cpu).
"""

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N = int(os.environ.get("MINHASH_N", 100_000))
CHUNKS_PER_SET = int(os.environ.get("MINHASH_CHUNKS", 128))
N_QUERIES = int(os.environ.get("MINHASH_QUERIES", 500))
BUDGET_MB = int(os.environ.get("MINHASH_BUDGET_MB", 0))
EVICT_FRAC = float(os.environ.get("MINHASH_EVICT_FRAC", 0.6))
RSS_BUDGET_MB = int(os.environ.get("MINHASH_RSS_BUDGET_MB", 6144))
INDEX_KIND = os.environ.get(
    "MINHASH_INDEX", "compact" if N > 200_000 else "dict"
)
J_BUCKETS = (0.3, 0.5, 0.7, 0.9)
BATCH = 2048


def gen_and_sketch(rng: np.random.Generator, hasher):
    """Stream-generate the corpus and sketch it batch-by-batch; only the
    planted-query base sets are retained as raw fingerprints. Returns
    ([N+Q, K] sketches, planted (query_idx, target_idx, j), seconds)."""
    q_per_bucket = N_QUERIES // len(J_BUCKETS)
    nq = q_per_bucket * len(J_BUCKETS)
    base_idx = rng.integers(0, N, size=nq)
    base_needed = set(base_idx.tolist())
    kept: dict[int, np.ndarray] = {}
    sketches = np.empty((N + nq, hasher.num_hashes), dtype=np.uint32)
    # sketch_s times ONLY the sketch_batch calls (device throughput),
    # not corpus generation -- comparability with the round-3 metric.
    sketch_s = 0.0
    for start in range(0, N, BATCH):
        cnt = min(BATCH, N - start)
        batch = [
            rng.integers(1, 1 << 32, size=CHUNKS_PER_SET, dtype=np.uint64)
            .astype(np.uint32)
            for _ in range(cnt)
        ]
        for k, s in enumerate(batch):
            if start + k in base_needed:
                kept[start + k] = s
        t0 = time.perf_counter()
        sketches[start : start + cnt] = hasher.sketch_batch(batch)
        sketch_s += time.perf_counter() - t0
    planted = []
    qsets = []
    next_idx = N
    qi = 0
    # (query construction below is untimed; their sketching is timed)
    for j in J_BUCKETS:
        for _ in range(q_per_bucket):
            bidx = int(base_idx[qi])
            qi += 1
            base = kept[bidx]
            m = len(base)
            # |A n B| / |A u B| = j with |A| = |B| = m: share 2j/(1+j).
            shared = int(round(m * 2 * j / (1 + j)))
            qsets.append(np.concatenate([
                base[:shared],
                rng.integers(1, 1 << 32, size=m - shared, dtype=np.uint64)
                .astype(np.uint32),
            ]))
            planted.append((next_idx, bidx, j))
            next_idx += 1
    for start in range(0, nq, BATCH):
        cnt = min(BATCH, nq - start)
        t0 = time.perf_counter()
        sketches[N + start : N + start + cnt] = hasher.sketch_batch(
            qsets[start : start + cnt]
        )
        sketch_s += time.perf_counter() - t0
    return sketches, planted, sketch_s


def main():
    from kraken_tpu.ops.minhash import CompactLSHIndex, LSHIndex, MinHasher

    rng = np.random.default_rng(7)
    hasher = MinHasher(num_hashes=128)
    sketches, planted, sketch_s = gen_and_sketch(rng, hasher)
    sets_per_s = (N + len(planted)) / sketch_s

    if INDEX_KIND == "compact":
        index = CompactLSHIndex(
            hasher, num_bands=32,
            budget_bytes=BUDGET_MB << 20 if BUDGET_MB else None,
        )
        t0 = time.perf_counter()
        for s in range(0, N, BATCH):
            index.add_batch(
                list(range(s, min(s + BATCH, N))),
                sketches[s : min(s + BATCH, N)],
            )
        index.flush()  # bulk-load-then-query: queries become pure bisect
        build_s = time.perf_counter() - t0
        bytes_per_set = index.footprint_bytes() // max(1, len(index))
        evictions = index.evictions
    else:
        index = LSHIndex(hasher, num_bands=32)
        t0 = time.perf_counter()
        for i in range(N):
            index.add(i, sketches[i])
        build_s = time.perf_counter() - t0
        bytes_per_set = None  # dict storage: no accounted footprint
        evictions = 0

    hits_by_j = {j: 0 for j in J_BUCKETS}
    count_by_j = {j: 0 for j in J_BUCKETS}
    recall_sum = 0.0
    recall_n = 0
    t0 = time.perf_counter()
    results = [index.query(sketches[qi], k=10) for qi, _t, _j in planted]
    query_s = time.perf_counter() - t0
    for (qi, target, j), got in zip(planted, results):
        count_by_j[j] += 1
        if any(key == target for key, _score in got):
            hits_by_j[j] += 1
        oracle = [
            key
            for key, score in index.query_brute(sketches[qi], k=10)
            if score >= 0.3
        ]
        if oracle:
            found = {key for key, _ in got}
            recall_sum += len(found & set(oracle)) / len(oracle)
            recall_n += 1

    recall10 = recall_sum / max(1, recall_n)

    # -- the 1M operating-point proofs (VERDICT r5 weak #4) ----------------
    # Compact index only: the dict index has no budget/eviction plane and
    # is not the million-set configuration.
    evict = {}
    if INDEX_KIND == "compact":
        built_bytes = index.footprint_bytes()
        # Force the eviction path: shrink the budget to EVICT_FRAC of the
        # BUILT footprint, so ~1-EVICT_FRAC of the oldest live rows must
        # leave (plus compaction savings). set_budget enforces inline.
        t0 = time.perf_counter()
        index.set_budget(int(built_bytes * EVICT_FRAC))
        evict_s = time.perf_counter() - t0
        assert index.evictions > 0, "budget drop failed to force eviction"
        # Recall AFTER eviction, on planted pairs whose target survived:
        # eviction is oldest-first by design, so the check is that the
        # surviving index still retrieves what it claims to hold.
        survivors = [(qi, t, j) for qi, t, j in planted if t in index]
        hits_after = {j: 0 for j in J_BUCKETS}
        count_after = {j: 0 for j in J_BUCKETS}
        for qi, target, j in survivors:
            count_after[j] += 1
            got = index.query(sketches[qi], k=10)
            if any(key == target for key, _score in got):
                hits_after[j] += 1
        total_after = sum(count_after.values())
        recall_after = (
            sum(hits_after.values()) / total_after if total_after else None
        )
        live_keys = [i for i in range(N) if i in index]
        evict_row = {
            # Distinct from the long-standing "evictions" key (build-time
            # BUDGET_MB evictions): this is the proof's forced wave.
            "forced_evictions": index.evictions,
            "evict_s": round(evict_s, 3),
            "evict_budget_bytes": index.budget_bytes,
            "survivors": len(survivors),
            "recall_after": (
                round(recall_after, 4) if recall_after is not None else None
            ),
            "planted_retrieval_after_eviction_by_jaccard": {
                str(j): round(hits_after[j] / max(1, count_after[j]), 4)
                for j in J_BUCKETS
                if count_after[j]
            },
        }
        # Restart rebuild wall: a fresh index re-fed the LIVE sketches --
        # the shape of an origin boot re-admitting persisted sidecars
        # (sidecar disk reads excluded: that is IO, measured elsewhere).
        # The old index is dropped first, as a real restart's would be.
        del index
        t0 = time.perf_counter()
        index = CompactLSHIndex(hasher, num_bands=32)
        for s in range(0, len(live_keys), BATCH):
            keys = live_keys[s : s + BATCH]
            index.add_batch(keys, sketches[keys])
        index.flush()
        rebuild_s = time.perf_counter() - t0
        evict_row["rebuild_s"] = round(rebuild_s, 2)
        evict_row["rebuild_sets_per_s"] = round(
            len(live_keys) / max(rebuild_s, 1e-9)
        )
        evict = evict_row

    peak_rss_mb = (
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    )
    print(json.dumps({
        "metric": "minhash_lsh_recall_at_10",
        "value": round(recall10, 4),
        "unit": "fraction (vs brute-force oracle, J>=0.3)",
        "vs_baseline": round(recall10, 4),  # baseline target: measure
        "n_sets": N + len(planted),
        "index": INDEX_KIND,
        "planted_retrieval_by_jaccard": {
            str(j): round(hits_by_j[j] / max(1, count_by_j[j]), 4)
            for j in J_BUCKETS
        },
        "sketch_sets_per_s": round(sets_per_s),
        "index_adds_per_s": round(N / build_s),
        "queries_per_s": round(len(planted) / query_s),
        "index_bytes_per_set": bytes_per_set,
        # Build-time evictions (the BUDGET_MB cap during ingest), the
        # meaning this key has had since round 4 -- the forced-eviction
        # proof emits its own "forced_evictions" inside `evict`.
        "evictions": evictions,
        **evict,  # forced-eviction / recall-after / rebuild rows
        "peak_rss_mb": peak_rss_mb,
        "rss_budget_mb": RSS_BUDGET_MB,
        "rss_within_budget": peak_rss_mb <= RSS_BUDGET_MB,
    }))


if __name__ == "__main__":
    main()

"""Bound the natural-vs-packed gap with a transpose-only kernel.

VERDICT r4 weak #5: the ~20% gap between the natural path (in-kernel u8
relayout + rounds) and the packed path (pure rounds) was *declared*
irreducible ("bounded below by Mosaic's relayout throughput") but never
isolated. This measures the missing leg: a kernel that performs ONLY the
u8 transpose + byte-plane word recombination (with a 1-xor-per-word fold
so Mosaic cannot dead-code it -- the fold slightly inflates the cost,
making the bound conservative), then checks the serial composition:

    1/R_natural_predicted = 1/R_transpose_only + 1/R_rounds_only

All three rates use the CHAINED method (each dispatch folds the previous
output into its input; PERF.md documents why the plain marginal method is
untrustworthy on this relay). If measured R_natural matches the
prediction, the gap IS the relayout and no scheduling fix inside the
current kernel structure can recover it; a shortfall would mean overlap
headroom. Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PIECE_LEN = int(os.environ.get("BENCH_PIECE_LEN", 256 * 1024))  # = bench.py
REPS = int(os.environ.get("BENCH_REPS", 3))
K_SMALL, K_LARGE = 1, 5


def main() -> None:
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from kraken_tpu.native import pack_tiles
    from kraken_tpu.ops.sha256 import _pad_block_for
    from kraken_tpu.ops.sha256_pallas import (
        _KB, _LANES, _SUB, N_TILE, packed_nb, sha256_packed_tiles,
        sha256_tiles,
    )

    nb = PIECE_LEN // 64
    ngroups = nb // _KB

    def transpose_only_kernel(blk_ref, out_ref):
        b = pl.program_id(1)

        @pl.when(b == 0)
        def _init():
            for i in range(8):
                out_ref[0, i, :, :] = jnp.zeros((_SUB, _LANES), jnp.uint32)

        acc = [out_ref[0, i, :, :] for i in range(8)]
        t8 = jnp.transpose(blk_ref[0], (1, 0)).reshape(
            _KB, 16, 4, _SUB, _LANES
        )
        for kb in range(_KB):
            for j in range(16):
                b0 = t8[kb, j, 0].astype(jnp.uint32)
                b1 = t8[kb, j, 1].astype(jnp.uint32)
                b2 = t8[kb, j, 2].astype(jnp.uint32)
                b3 = t8[kb, j, 3].astype(jnp.uint32)
                w = (
                    (b0 << np.uint32(24)) | (b1 << np.uint32(16))
                    | (b2 << np.uint32(8)) | b3
                )
                acc[j % 8] = acc[j % 8] ^ w
        for i in range(8):
            out_ref[0, i, :, :] = acc[i]

    @functools.partial(jax.jit)
    def transpose_only(data_u8):
        slabs = data_u8.reshape(1, N_TILE, nb * 64)
        return pl.pallas_call(
            transpose_only_kernel,
            grid=(1, ngroups),
            in_specs=[
                pl.BlockSpec(
                    (1, N_TILE, _KB * 64), lambda ti, bi: (ti, 0, bi),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (1, 8, _SUB, _LANES), lambda ti, bi: (ti, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((1, 8, _SUB, _LANES), jnp.uint32),
        )(slabs)

    pad = jnp.asarray(_pad_block_for(PIECE_LEN))

    def chained_rate(step, x0) -> float:
        x, out = step(x0)
        jax.block_until_ready((x, out))

        def timed(k, x):
            t0 = time.perf_counter()
            out = None
            for _ in range(k):
                x, out = step(x)
            np.asarray(out).reshape(-1)[0]
            return time.perf_counter() - t0, x

        rates = []
        x = x0
        for _ in range(REPS):
            t_s, x = timed(K_SMALL, x)
            t_l, x = timed(K_LARGE, x)
            rates.append(
                (K_LARGE - K_SMALL) * N_TILE * PIECE_LEN
                / max(t_l - t_s, 1e-9) / 1e9
            )
        rates.sort()
        return rates[len(rates) // 2]

    x0 = jax.random.bits(
        jax.random.PRNGKey(0), (N_TILE, PIECE_LEN), dtype=jnp.uint8
    )
    x0.block_until_ready()

    @jax.jit
    def step_transpose(x):
        out = transpose_only(x)
        first = jax.lax.bitcast_convert_type(
            out[0, :, 0, 0], jnp.uint8
        ).reshape(-1)
        return jax.lax.dynamic_update_slice(x, first[None, :32], (0, 0)), out

    @jax.jit
    def step_natural(x):
        d = sha256_tiles(x, pad, nb)
        first = jax.lax.bitcast_convert_type(d[0], jnp.uint8).reshape(-1)
        return jax.lax.dynamic_update_slice(x, first[None, :], (0, 0)), d

    r_transpose = chained_rate(step_transpose, x0)
    r_natural = chained_rate(step_natural, x0)

    # Packed path: chain by folding the digest into the packed words.
    nbp = packed_nb(nb)
    packed_np = np.zeros((1, nbp, 16, 1024), dtype=np.uint32)
    pack_tiles(np.asarray(x0), nbp, packed_np)
    packed0 = jnp.asarray(packed_np.reshape(1, nbp, 16, _SUB, _LANES))

    @jax.jit
    def step_packed(p):
        d = sha256_packed_tiles(p, nb)
        fold = d[0].astype(jnp.uint32)  # [8] words
        return p.at[0, 0, :8, 0, 0].set(fold), d

    r_packed = chained_rate(step_packed, packed0)

    predicted = 1.0 / (1.0 / r_transpose + 1.0 / r_packed)
    print(json.dumps({
        "metric": "natural_gap_decomposition",
        "value": round(r_natural / predicted, 3),
        "unit": "measured_natural / serial(transpose+rounds) prediction",
        "vs_baseline": None,
        "transpose_only_gbps": round(r_transpose, 2),
        "rounds_only_packed_gbps": round(r_packed, 2),
        "natural_gbps": round(r_natural, 2),
        "predicted_natural_gbps": round(predicted, 2),
    }))


if __name__ == "__main__":
    main()

"""Swarm-scale benchmark: flash-crowd pull latency over the P2P plane.

BASELINE.md rows 2/6 ("agent piece-verify p99 pull latency", "p99 @ 10k
agents, simulated swarm"): N agent schedulers + 1 origin seeder in one
process, REAL TCP piece traffic (each peer owns a listening socket and
dials over loopback), in-memory tracker (announce/metainfo RPC faked so
the benchmark measures the data plane, not aiohttp routing). All N agents
request the blob at t=0 -- the worst-case flash crowd; completed agents
keep seeding, so late finishers pull mostly from other agents, which is
the swarm effect being measured.

Extrapolation toward 10k agents: p99 growth with N is dominated by swarm
depth (how many hops from the origin the last agent sits), which grows
logarithmically with N once per-peer conn caps bind. Run with --agents at
several N to see the trend.

Usage:
    python bench_swarm.py [--agents 100] [--blob-mb 32] [--piece-kb 256]

Prints one JSON line per metric (driver format:
{"metric", "value", "unit", "vs_baseline"}), p99 last.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time

import numpy as np

from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.p2p.scheduler import Scheduler, SchedulerConfig
from kraken_tpu.p2p.storage import (
    AgentTorrentArchive,
    BatchedVerifier,
    OriginTorrentArchive,
)
from kraken_tpu.store import CAStore

NS = "bench"


class InMemoryTracker:
    """Announce + metainfo, shared by every peer in-process."""

    def __init__(self, interval: float = 0.5):
        self.metainfos: dict[str, MetaInfo] = {}
        self.peers: dict[str, dict[str, PeerInfo]] = {}
        self.interval = interval
        self.announces = 0

    def client_for(self, ref: dict):
        tracker = self

        class _Client:
            async def get(self, namespace, d):
                return tracker.metainfos[d.hex]

            async def announce(self, d, h, namespace, complete):
                tracker.announces += 1
                sched = ref["s"]
                me = PeerInfo(
                    peer_id=sched.peer_id, ip=sched.ip, port=sched.port,
                    complete=complete,
                )
                swarm = tracker.peers.setdefault(h.hex, {})
                swarm[me.peer_id.hex] = me
                others = [
                    p for pid, p in swarm.items() if pid != me.peer_id.hex
                ]
                # Tracker handout policy caps the returned set; mirror that
                # so a 1k swarm does not hand every peer every other peer.
                if len(others) > 20:
                    idx = np.random.default_rng(tracker.announces)
                    others = [others[i] for i in
                              idx.choice(len(others), 20, replace=False)]
                return others, tracker.interval

        return _Client()


def make_peer(root, name, tracker, *, seed_blobs=None, piece_kb=256,
              data_plane_workers=0, leech_workers=0):
    from kraken_tpu.p2p.connstate import ConnStateConfig

    store = CAStore(os.path.join(root, name))
    ref: dict = {}
    is_origin = seed_blobs is not None
    if is_origin:
        for blob in seed_blobs:
            d = Digest.from_bytes(blob)
            store.create_cache_file(d, iter([blob]))
        archive = OriginTorrentArchive(store, BatchedVerifier())
    else:
        archive = AgentTorrentArchive(store, BatchedVerifier())
    client = tracker.client_for(ref)
    sched = Scheduler(
        peer_id=PeerID(os.urandom(20).hex()),
        ip="127.0.0.1",
        port=0,
        archive=archive,
        metainfo_client=client,
        announce_client=client,
        is_origin=is_origin,
        config=SchedulerConfig(
            announce_interval_seconds=0.5,
            retry_tick_seconds=0.5,
            max_announce_rate=2000.0,
            # Multi-core seed-serve plane (p2p/shardpool.py): >0 forks
            # worker processes that serve seed conns via sendfile.
            data_plane_workers=data_plane_workers,
            # Multi-core download plane: >0 forks pump workers that own
            # active-download conns (recv + parse + pwrite off-loop).
            leech_workers=leech_workers,
            # Origins are servers: a 10-conn cap on the only initial seeder
            # strangles the flash crowd's first wave.
            conn_state=ConnStateConfig(
                max_open_conns_per_torrent=64 if is_origin else 10
            ),
        ),
    )
    ref["s"] = sched
    return sched


async def run_bench(n_agents: int, blob_mb: int, piece_kb: int, root: str):
    rng = np.random.default_rng(0)
    blob = rng.integers(0, 256, size=blob_mb << 20, dtype=np.uint8).tobytes()
    d = Digest.from_bytes(blob)
    piece_len = piece_kb << 10
    hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
    metainfo = MetaInfo(d, len(blob), piece_len, hashes.tobytes())

    tracker = InMemoryTracker()
    tracker.metainfos[d.hex] = metainfo

    origin = make_peer(root, "origin", tracker, seed_blobs=[blob])
    agents = [
        make_peer(root, f"agent{i}", tracker) for i in range(n_agents)
    ]
    await origin.start()
    origin.seed(metainfo, NS)
    for a in agents:
        await a.start()

    t0 = time.perf_counter()
    latencies: list[float] = []

    async def pull(a):
        start = time.perf_counter()
        await a.download(NS, d)
        latencies.append(time.perf_counter() - start)

    await asyncio.gather(*(pull(a) for a in agents))
    wall = time.perf_counter() - t0

    for s in (origin, *agents):
        await s.stop()

    lat = np.sort(np.asarray(latencies))
    n_pieces = metainfo.num_pieces
    total_bytes = len(blob) * n_agents
    return {
        "agents": n_agents,
        "blob_mb": blob_mb,
        "pieces_per_blob": n_pieces,
        "p50_s": float(lat[int(0.50 * (len(lat) - 1))]),
        "p99_s": float(lat[int(0.99 * (len(lat) - 1))]),
        "wall_s": wall,
        "swarm_pieces_per_s": n_pieces * n_agents / wall,
        "swarm_gbps": total_bytes / wall / 1e9,
        "announces": tracker.announces,
    }


async def run_image_bench(
    n_agents: int, layers_mb: list[int], piece_kb: int, root: str
):
    """BASELINE row 2 shape: a multi-layer image (sizes modeled on an
    alpine+ubuntu layer set), N agents pull every layer concurrently; an
    agent's pull latency is when its LAST layer lands (what `docker pull`
    wall time means). One origin seeds all layers."""
    rng = np.random.default_rng(1)
    piece_len = piece_kb << 10
    layers = []
    tracker = InMemoryTracker()
    for mb in layers_mb:
        blob = rng.integers(0, 256, size=mb << 20, dtype=np.uint8).tobytes()
        d = Digest.from_bytes(blob)
        hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
        metainfo = MetaInfo(d, len(blob), piece_len, hashes.tobytes())
        tracker.metainfos[d.hex] = metainfo
        layers.append((blob, d, metainfo))

    origin = make_peer(
        root, "origin", tracker, seed_blobs=[b for b, _d, _m in layers]
    )
    agents = [make_peer(root, f"agent{i}", tracker) for i in range(n_agents)]
    await origin.start()
    for _blob, _d, mi in layers:
        origin.seed(mi, NS)
    for a in agents:
        await a.start()

    t0 = time.perf_counter()
    latencies: list[float] = []

    async def pull_image(a):
        start = time.perf_counter()
        await asyncio.gather(*(a.download(NS, d) for _b, d, _m in layers))
        latencies.append(time.perf_counter() - start)

    await asyncio.gather(*(pull_image(a) for a in agents))
    wall = time.perf_counter() - t0
    for sch in (origin, *agents):
        await sch.stop()

    lat = np.sort(np.asarray(latencies))
    image_bytes = sum(len(b) for b, _d, _m in layers)
    return {
        "agents": n_agents,
        "layers_mb": layers_mb,
        "image_mb": image_bytes >> 20,
        "p50_s": float(lat[int(0.50 * (len(lat) - 1))]),
        "p99_s": float(lat[int(0.99 * (len(lat) - 1))]),
        "wall_s": wall,
        "swarm_gbps": image_bytes * n_agents / wall / 1e9,
        "announces": tracker.announces,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=None,
                    help="default: 100 (flash crowd) / 10 (--image)")
    ap.add_argument("--blob-mb", type=int, default=32)
    ap.add_argument("--piece-kb", type=int, default=256)
    ap.add_argument("--image", action="store_true",
                    help="BASELINE row 2: multi-layer alpine+ubuntu-shaped"
                         " image pull (defaults --agents to 10)")
    args = ap.parse_args()

    import tempfile

    with tempfile.TemporaryDirectory(prefix="kt-bench-swarm-") as root:
        if args.image:
            n = args.agents if args.agents is not None else 10
            out = asyncio.run(run_image_bench(
                n, [3, 29, 25, 5, 1], args.piece_kb, root
            ))
            print(json.dumps({
                "metric": "image_pull_p99_latency",
                "value": round(out["p99_s"], 4),
                "unit": "s",
                "vs_baseline": None,
                "detail": out,
            }))
            return
        out = asyncio.run(run_bench(
            args.agents if args.agents is not None else 100,
            args.blob_mb, args.piece_kb, root,
        ))
    for metric, unit in (
        ("p50_s", "s"),
        ("swarm_pieces_per_s", "pieces/s"),
        ("swarm_gbps", "GB/s"),
        ("p99_s", "s"),
    ):
        print(json.dumps({
            "metric": f"swarm_pull_{metric}" if not metric.startswith("swarm")
            else metric,
            "value": round(out[metric], 4),
            "unit": unit,
            "vs_baseline": None,
            "detail": {k: v for k, v in out.items()
                       if k in ("agents", "blob_mb", "pieces_per_blob",
                                "wall_s", "announces")},
        }))


if __name__ == "__main__":
    main()

"""Swarm-scale benchmark: flash-crowd pull latency over the P2P plane.

BASELINE.md rows 2/6 ("agent piece-verify p99 pull latency", "p99 @ 10k
agents, simulated swarm"): N agent schedulers + 1 origin seeder in one
process, REAL TCP piece traffic (each peer owns a listening socket and
dials over loopback), in-memory tracker (announce/metainfo RPC faked so
the benchmark measures the data plane, not aiohttp routing). All N agents
request the blob at t=0 -- the worst-case flash crowd; completed agents
keep seeding, so late finishers pull mostly from other agents, which is
the swarm effect being measured.

Extrapolation toward 10k agents: p99 growth with N is dominated by swarm
depth (how many hops from the origin the last agent sits), which grows
logarithmically with N once per-peer conn caps bind. Run with --agents at
several N to see the trend.

Usage:
    python bench_swarm.py [--agents 100] [--blob-mb 32] [--piece-kb 256]

Prints one JSON line per metric (driver format:
{"metric", "value", "unit", "vs_baseline"}), p99 last.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import time

import numpy as np

from kraken_tpu.assembly import OriginNode
from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import MetaInfo
from kraken_tpu.core.peer import PeerID, PeerInfo
from kraken_tpu.origin.client import BlobClient
from kraken_tpu.origin.server import QuorumConfig
from kraken_tpu.placement import HostList, Ring
from kraken_tpu.p2p.scheduler import Scheduler, SchedulerConfig
from kraken_tpu.utils.deadline import Deadline
from kraken_tpu.p2p.storage import (
    AgentTorrentArchive,
    BatchedVerifier,
    OriginTorrentArchive,
)
from kraken_tpu.store import CAStore

NS = "bench"


class InMemoryTracker:
    """Announce + metainfo, shared by every peer in-process."""

    def __init__(self, interval: float = 0.5):
        self.metainfos: dict[str, MetaInfo] = {}
        self.peers: dict[str, dict[str, PeerInfo]] = {}
        self.interval = interval
        self.announces = 0

    def client_for(self, ref: dict):
        tracker = self

        class _Client:
            async def get(self, namespace, d):
                return tracker.metainfos[d.hex]

            async def announce(self, d, h, namespace, complete):
                tracker.announces += 1
                sched = ref["s"]
                me = PeerInfo(
                    peer_id=sched.peer_id, ip=sched.ip, port=sched.port,
                    complete=complete,
                )
                swarm = tracker.peers.setdefault(h.hex, {})
                swarm[me.peer_id.hex] = me
                others = [
                    p for pid, p in swarm.items() if pid != me.peer_id.hex
                ]
                # Tracker handout policy caps the returned set; mirror that
                # so a 1k swarm does not hand every peer every other peer.
                if len(others) > 20:
                    idx = np.random.default_rng(tracker.announces)
                    others = [others[i] for i in
                              idx.choice(len(others), 20, replace=False)]
                return others, tracker.interval

        return _Client()


def make_peer(root, name, tracker, *, seed_blobs=None, piece_kb=256,
              data_plane_workers=0, leech_workers=0):
    from kraken_tpu.p2p.connstate import ConnStateConfig

    store = CAStore(os.path.join(root, name))
    ref: dict = {}
    is_origin = seed_blobs is not None
    if is_origin:
        for blob in seed_blobs:
            d = Digest.from_bytes(blob)
            store.create_cache_file(d, iter([blob]))
        archive = OriginTorrentArchive(store, BatchedVerifier())
    else:
        archive = AgentTorrentArchive(store, BatchedVerifier())
    client = tracker.client_for(ref)
    sched = Scheduler(
        peer_id=PeerID(os.urandom(20).hex()),
        ip="127.0.0.1",
        port=0,
        archive=archive,
        metainfo_client=client,
        announce_client=client,
        is_origin=is_origin,
        config=SchedulerConfig(
            announce_interval_seconds=0.5,
            retry_tick_seconds=0.5,
            max_announce_rate=2000.0,
            # Multi-core seed-serve plane (p2p/shardpool.py): >0 forks
            # worker processes that serve seed conns via sendfile.
            data_plane_workers=data_plane_workers,
            # Multi-core download plane: >0 forks pump workers that own
            # active-download conns (recv + parse + pwrite off-loop).
            leech_workers=leech_workers,
            # Origins are servers: a 10-conn cap on the only initial seeder
            # strangles the flash crowd's first wave.
            conn_state=ConnStateConfig(
                max_open_conns_per_torrent=64 if is_origin else 10
            ),
        ),
    )
    ref["s"] = sched
    return sched


async def run_bench(n_agents: int, blob_mb: int, piece_kb: int, root: str):
    rng = np.random.default_rng(0)
    blob = rng.integers(0, 256, size=blob_mb << 20, dtype=np.uint8).tobytes()
    d = Digest.from_bytes(blob)
    piece_len = piece_kb << 10
    hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
    metainfo = MetaInfo(d, len(blob), piece_len, hashes.tobytes())

    tracker = InMemoryTracker()
    tracker.metainfos[d.hex] = metainfo

    origin = make_peer(root, "origin", tracker, seed_blobs=[blob])
    agents = [
        make_peer(root, f"agent{i}", tracker) for i in range(n_agents)
    ]
    await origin.start()
    origin.seed(metainfo, NS)
    for a in agents:
        await a.start()

    t0 = time.perf_counter()
    latencies: list[float] = []

    async def pull(a):
        start = time.perf_counter()
        await a.download(NS, d)
        latencies.append(time.perf_counter() - start)

    await asyncio.gather(*(pull(a) for a in agents))
    wall = time.perf_counter() - t0

    for s in (origin, *agents):
        await s.stop()

    lat = np.sort(np.asarray(latencies))
    n_pieces = metainfo.num_pieces
    total_bytes = len(blob) * n_agents
    return {
        "agents": n_agents,
        "blob_mb": blob_mb,
        "pieces_per_blob": n_pieces,
        "p50_s": float(lat[int(0.50 * (len(lat) - 1))]),
        "p99_s": float(lat[int(0.99 * (len(lat) - 1))]),
        "wall_s": wall,
        "swarm_pieces_per_s": n_pieces * n_agents / wall,
        "swarm_gbps": total_bytes / wall / 1e9,
        "announces": tracker.announces,
    }


async def run_image_bench(
    n_agents: int, layers_mb: list[int], piece_kb: int, root: str
):
    """BASELINE row 2 shape: a multi-layer image (sizes modeled on an
    alpine+ubuntu layer set), N agents pull every layer concurrently; an
    agent's pull latency is when its LAST layer lands (what `docker pull`
    wall time means). One origin seeds all layers."""
    rng = np.random.default_rng(1)
    piece_len = piece_kb << 10
    layers = []
    tracker = InMemoryTracker()
    for mb in layers_mb:
        blob = rng.integers(0, 256, size=mb << 20, dtype=np.uint8).tobytes()
        d = Digest.from_bytes(blob)
        hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
        metainfo = MetaInfo(d, len(blob), piece_len, hashes.tobytes())
        tracker.metainfos[d.hex] = metainfo
        layers.append((blob, d, metainfo))

    origin = make_peer(
        root, "origin", tracker, seed_blobs=[b for b, _d, _m in layers]
    )
    agents = [make_peer(root, f"agent{i}", tracker) for i in range(n_agents)]
    await origin.start()
    for _blob, _d, mi in layers:
        origin.seed(mi, NS)
    for a in agents:
        await a.start()

    t0 = time.perf_counter()
    latencies: list[float] = []

    async def pull_image(a):
        start = time.perf_counter()
        await asyncio.gather(*(a.download(NS, d) for _b, d, _m in layers))
        latencies.append(time.perf_counter() - start)

    await asyncio.gather(*(pull_image(a) for a in agents))
    wall = time.perf_counter() - t0
    for sch in (origin, *agents):
        await sch.stop()

    lat = np.sort(np.asarray(latencies))
    image_bytes = sum(len(b) for b, _d, _m in layers)
    return {
        "agents": n_agents,
        "layers_mb": layers_mb,
        "image_mb": image_bytes >> 20,
        "p50_s": float(lat[int(0.50 * (len(lat) - 1))]),
        "p99_s": float(lat[int(0.99 * (len(lat) - 1))]),
        "wall_s": wall,
        "swarm_gbps": image_bytes * n_agents / wall / 1e9,
        "announces": tracker.announces,
    }


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def run_push_availability(
    n_blobs: int, blob_kb: int, write_quorum: int, root: str
):
    """Push-availability wave (ISSUE 20 row): 3 origins over a static
    full-mesh ring, ``n_blobs`` pushed round-robin across them, origin #2
    killed mid-wave. Measures the availability contract of the quorum
    write plane: with ``write_quorum: 2`` an ack means a second origin
    already holds the blob (a dead ring replica gets a hint instead of
    failing the push -- sloppy quorum), so the success rate and commit
    p99 quantify what durability costs while a third of the fleet is
    down. Pushes aimed straight at the dead origin fail under a short
    deadline either way; that shared loss is the client-side routing
    story, not the quorum plane's."""
    rng = np.random.default_rng(2)
    ports = [_free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in ports]
    quorum = (
        QuorumConfig(write_quorum=write_quorum, push_timeout_seconds=5.0)
        if write_quorum > 1 else None
    )
    nodes = []
    for i in range(3):
        node = OriginNode(
            store_root=os.path.join(root, f"q{write_quorum}-origin{i}"),
            http_port=ports[i],
            ring=Ring(HostList(static=addrs), max_replica=3),
            self_addr=addrs[i],
            dedup=False,
            quorum=quorum,
            health_interval_seconds=30.0,
        )
        await node.start()
        nodes.append(node)
    clients = [BlobClient(a) for a in addrs]
    victim = 2
    kill_at = n_blobs // 2
    killed = False
    ok = failed = 0
    commit_s: list[float] = []
    try:
        for i in range(n_blobs):
            if i == kill_at and not killed:
                await nodes[victim].stop()
                killed = True
            blob = rng.integers(
                0, 256, size=blob_kb << 10, dtype=np.uint8
            ).tobytes()
            d = Digest.from_bytes(blob)
            t0 = time.perf_counter()
            try:
                await clients[i % 3].upload(
                    NS, d, blob,
                    deadline=Deadline(8.0, component="bench-push"),
                )
            except Exception:
                failed += 1
            else:
                ok += 1
                commit_s.append(time.perf_counter() - t0)
    finally:
        for c in clients:
            await c.close()
        for i, node in enumerate(nodes):
            if i != victim or not killed:
                await node.stop()
    lat = np.sort(np.asarray(commit_s)) if commit_s else np.asarray([0.0])
    return {
        "write_quorum": write_quorum,
        "blobs": n_blobs,
        "blob_kb": blob_kb,
        "killed_origin_at_blob": kill_at,
        "ok": ok,
        "failed": failed,
        "success_rate": ok / n_blobs if n_blobs else 0.0,
        "commit_p50_s": float(lat[int(0.50 * (len(lat) - 1))]),
        "commit_p99_s": float(lat[int(0.99 * (len(lat) - 1))]),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=None,
                    help="default: 100 (flash crowd) / 10 (--image)")
    ap.add_argument("--blob-mb", type=int, default=32)
    ap.add_argument("--piece-kb", type=int, default=256)
    ap.add_argument("--image", action="store_true",
                    help="BASELINE row 2: multi-layer alpine+ubuntu-shaped"
                         " image pull (defaults --agents to 10)")
    ap.add_argument("--push-availability", action="store_true",
                    help="ISSUE 20 row: push success rate + commit p99"
                         " with 1-of-3 origins killed mid-wave, quorum"
                         " on (write_quorum=2) vs off")
    ap.add_argument("--push-blobs", type=int, default=24,
                    help="wave size for --push-availability")
    ap.add_argument("--push-blob-kb", type=int, default=512,
                    help="blob size for --push-availability")
    args = ap.parse_args()

    import tempfile

    with tempfile.TemporaryDirectory(prefix="kt-bench-swarm-") as root:
        if args.push_availability:
            off = asyncio.run(run_push_availability(
                args.push_blobs, args.push_blob_kb, 1, root
            ))
            on = asyncio.run(run_push_availability(
                args.push_blobs, args.push_blob_kb, 2, root
            ))
            for tag, out, base in (
                ("quorum_off", off, None), ("quorum_on", on, off)
            ):
                print(json.dumps({
                    "metric": f"push_success_rate_{tag}",
                    "value": round(out["success_rate"], 4),
                    "unit": "ratio",
                    "vs_baseline": (
                        round(base["success_rate"], 4) if base else None
                    ),
                    "detail": out,
                }))
                print(json.dumps({
                    "metric": f"push_commit_p99_{tag}",
                    "value": round(out["commit_p99_s"], 4),
                    "unit": "s",
                    "vs_baseline": (
                        round(base["commit_p99_s"], 4) if base else None
                    ),
                    "detail": out,
                }))
            return
        if args.image:
            n = args.agents if args.agents is not None else 10
            out = asyncio.run(run_image_bench(
                n, [3, 29, 25, 5, 1], args.piece_kb, root
            ))
            print(json.dumps({
                "metric": "image_pull_p99_latency",
                "value": round(out["p99_s"], 4),
                "unit": "s",
                "vs_baseline": None,
                "detail": out,
            }))
            return
        out = asyncio.run(run_bench(
            args.agents if args.agents is not None else 100,
            args.blob_mb, args.piece_kb, root,
        ))
    for metric, unit in (
        ("p50_s", "s"),
        ("swarm_pieces_per_s", "pieces/s"),
        ("swarm_gbps", "GB/s"),
        ("p99_s", "s"),
    ):
        print(json.dumps({
            "metric": f"swarm_pull_{metric}" if not metric.startswith("swarm")
            else metric,
            "value": round(out[metric], 4),
            "unit": unit,
            "vs_baseline": None,
            "detail": {k: v for k, v in out.items()
                       if k in ("agents", "blob_mb", "pieces_per_blob",
                                "wall_s", "announces")},
        }))


if __name__ == "__main__":
    main()

"""North-star benchmark: batched SHA-256 piece hashing throughput.

Measures the TPU metainfo-gen hot loop (BASELINE.json config #3: batched
SHA-256 over uniform pieces; target >= 20 GB/s/chip on v5e) against the CPU
hashlib baseline (config #1), printing ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
     "packed_kernel_gbps": ..., "host_pack_gbps_core": ...}

``value`` is the NATURAL-layout device path (what ``hash_pieces`` delivers
from raw piece bytes with no host-side packing) -- the honest end-to-end
chip number. ``packed_kernel_gbps`` is the same kernel fed the word-major
layout the native host packer produces at staging time (the production
origin configuration); ``host_pack_gbps_core`` is that packer's measured
single-core rate here. PERF.md holds the full measured analysis.

``vs_baseline`` is the headline/CPU speedup -- the reference hashes pieces
sequentially on the CPU (uber/kraken lib/metainfogen [UNVERIFIED]), so the
measured CPU rate stands in for the reference baseline (BASELINE.json
``published`` is empty; see BASELINE.md).

Methodology notes:
- On this rig the TPU sits behind a network relay whose host<->device link
  runs at ~25 MB/s with ~200 ms round-trip latency -- both orders of
  magnitude off a production v5e host (PCIe/DMA at tens of GB/s), so
  end-to-end feed throughput here measures the relay, not the system.
- Relay latency is excluded by the marginal-rate method: time K_small and
  K_large back-to-back dispatches (one tiny result fetch each) and divide
  the extra bytes by the extra time; median of REPS runs. Queued
  dispatches execute back-to-back on the chip, so the slope is pure chip
  throughput.
- The warmup doubles as the kernel correctness gate vs hashlib on every
  bench run (CPU-side validation is impractical: XLA:CPU needs >5 min to
  compile the unrolled kernel body -- see PERF.md).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# 256 KiB pieces x 1024-piece tiles = 256 MiB per dispatch: large enough
# that per-dispatch overhead vanishes in the slope, small enough that the
# K_LARGE queued executions' transient buffers fit HBM. SHA-256 work per
# byte is piece-length-invariant, so this measures the 4 MiB-piece rate too.
PIECE_LEN = int(os.environ.get("BENCH_PIECE_LEN", 256 * 1024))
CPU_BYTES = int(os.environ.get("BENCH_CPU_BYTES", 256 * 1024 * 1024))
K_SMALL = 4
K_LARGE = int(os.environ.get("BENCH_K_LARGE", 104))
REPS = int(os.environ.get("BENCH_REPS", 5))


def cpu_baseline_gbps() -> float:
    import hashlib

    data = np.random.default_rng(0).integers(
        0, 256, size=CPU_BYTES, dtype=np.uint8
    ).tobytes()
    t0 = time.perf_counter()
    view = memoryview(data)
    n = (len(view) + PIECE_LEN - 1) // PIECE_LEN
    for i in range(n):
        hashlib.sha256(view[i * PIECE_LEN : (i + 1) * PIECE_LEN]).digest()
    return len(data) / (time.perf_counter() - t0) / 1e9


def _marginal(dispatch, bytes_per_dispatch: int) -> float:
    """Median-of-REPS marginal rate of ``dispatch()`` (async, one fetch)."""

    def timed(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = dispatch()
        _ = np.asarray(out[0, 0])  # forces the whole queued chain
        return time.perf_counter() - t0

    rates = []
    for _ in range(REPS):
        t_small, t_large = timed(K_SMALL), timed(K_LARGE)
        extra = (K_LARGE - K_SMALL) * bytes_per_dispatch
        rates.append(extra / max(t_large - t_small, 1e-9) / 1e9)
    rates.sort()
    return rates[len(rates) // 2]


def tpu_rates() -> tuple[float, float, float]:
    """(natural_gbps, packed_gbps, host_pack_gbps_core)."""
    import hashlib

    import jax
    import jax.numpy as jnp

    from kraken_tpu.native import pack_tiles
    from kraken_tpu.ops.sha256 import _digest_bytes
    from kraken_tpu.ops.sha256_pallas import (
        N_TILE,
        hash_pieces_device,
        packed_nb,
        sha256_packed_tiles,
    )

    key = jax.random.PRNGKey(0)
    d = jax.random.bits(key, (N_TILE, PIECE_LEN), dtype=jnp.uint8)
    d.block_until_ready()
    host = np.asarray(d[:2])
    want = [hashlib.sha256(host[i].tobytes()).digest() for i in range(2)]

    # Natural path: warmup = correctness gate.
    warm = _digest_bytes(hash_pieces_device(d, PIECE_LEN)[:2])
    for i in range(2):
        assert warm[i].tobytes() == want[i], "natural kernel digest mismatch"
    natural = _marginal(
        lambda: hash_pieces_device(d, PIECE_LEN), N_TILE * PIECE_LEN
    )

    # Host packer rate (single core), then packed kernel path.
    host_all = np.asarray(d)
    nb = packed_nb(PIECE_LEN // 64)
    packed_np = np.zeros((1, nb, 16, 1024), dtype=np.uint32)
    t0 = time.perf_counter()
    pack_tiles(host_all, nb, packed_np)
    pack_gbps = host_all.nbytes / (time.perf_counter() - t0) / 1e9
    packed = jnp.asarray(packed_np.reshape(1, nb, 16, 8, 128))
    packed.block_until_ready()
    warm2 = _digest_bytes(sha256_packed_tiles(packed, PIECE_LEN // 64)[:2])
    for i in range(2):
        assert warm2[i].tobytes() == want[i], "packed kernel digest mismatch"
    packed_rate = _marginal(
        lambda: sha256_packed_tiles(packed, PIECE_LEN // 64),
        N_TILE * PIECE_LEN,
    )
    return natural, packed_rate, pack_gbps


def natural_chained_gbps() -> float:
    """Natural path, CHAINED: each dispatch's input folds in the previous
    digest, so every execution is distinct and data-dependent. This
    defeats two relay pathologies the plain marginal method is exposed
    to (observed 2026-07-30: a 41.6 and a physically impossible 132
    GB/s in consecutive runs -- the rounds-only ceiling is ~105):
    queued-replay coalescing of identical executions, and latency jitter
    between the timing fences. Chained runs cluster within ~3%."""
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.sha256 import _pad_block_for
    from kraken_tpu.ops.sha256_pallas import N_TILE, sha256_tiles

    pad = jnp.asarray(_pad_block_for(PIECE_LEN))

    @jax.jit
    def step(x):
        d = sha256_tiles(x, pad, PIECE_LEN // 64)
        first = jax.lax.bitcast_convert_type(d[0], jnp.uint8).reshape(-1)
        return jax.lax.dynamic_update_slice(x, first[None, :], (0, 0)), d

    x = jax.random.bits(
        jax.random.PRNGKey(0), (N_TILE, PIECE_LEN), dtype=jnp.uint8
    )
    x.block_until_ready()
    x, d = step(x)
    jax.block_until_ready((x, d))

    def timed(k: int, x):
        t0 = time.perf_counter()
        d = None
        for _ in range(k):
            x, d = step(x)
        np.asarray(d[0, 0])
        return time.perf_counter() - t0, x

    rates = []
    for _ in range(REPS):
        t_s, x = timed(K_SMALL, x)
        t_l, x = timed(K_LARGE, x)
        rates.append(
            (K_LARGE - K_SMALL) * N_TILE * PIECE_LEN
            / max(t_l - t_s, 1e-9) / 1e9
        )
    rates.sort()
    return rates[len(rates) // 2]


def cdc_gear_rate() -> float:
    """The dedup plane's Pallas gear kernel (ops/cdc_pallas.py), data
    resident, CHAINED (each dispatch folds the previous strict mask into
    its input) -- distinct data-dependent executions, immune to the
    replay-coalescing/jitter pathology natural_chained_gbps documents."""
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.cdc import CDCParams
    from kraken_tpu.ops.cdc_pallas import _ROWS, _T_DISPATCH, _gear_pallas

    p = CDCParams()

    @jax.jit
    def step(x):
        strict, _loose = _gear_pallas(x, p.mask_strict, p.mask_loose)
        # One-row fold: enough to make every execution data-dependent
        # and distinct; a whole-batch fold would add ~2/3 extra HBM
        # traffic and measure the fold, not the kernel.
        x = jax.lax.dynamic_update_slice(x, strict[:, :1, :], (0, 0, 0))
        return x, strict

    x = jax.random.bits(
        jax.random.PRNGKey(0), (_T_DISPATCH, _ROWS, 128), dtype=jnp.uint8
    )
    x.block_until_ready()
    x, s = step(x)
    jax.block_until_ready((x, s))
    n = _T_DISPATCH * (1 << 18)

    def timed(k: int, x):
        t0 = time.perf_counter()
        s = None
        for _ in range(k):
            x, s = step(x)
        np.asarray(s[0, 0])
        return time.perf_counter() - t0, x

    rates = []
    # Chain lengths sized to THIS kernel's 64 MiB dispatch (vs the SHA
    # path's 256 MiB): 200 extra dispatches ≈ 13 GB per trial, enough to
    # dwarf the relay's 100s-of-ms fence jitter. REPS is shared with the
    # other measurements (BENCH_REPS).
    for _ in range(REPS):
        t_s, x = timed(2, x)
        t_l, x = timed(202, x)
        rates.append(200 * n / max(t_l - t_s, 1e-9) / 1e9)
    rates.sort()
    return rates[len(rates) // 2]


def data_plane_extras() -> dict:
    """Round-5 data-plane numbers folded into the headline line,
    best-effort: a failure here must NEVER break the primary metric
    (BENCH_EXTRAS=0 skips). Short configs -- the full sweeps live in
    bench_pair.py / bench_ingest.py."""
    if os.environ.get("BENCH_EXTRAS") == "0":
        return {}
    import asyncio
    import tempfile

    out: dict = {}
    try:
        from bench_pair import run_pair

        rates = []
        for _ in range(2):
            with tempfile.TemporaryDirectory(dir=".") as root:
                rates.append(
                    asyncio.run(run_pair(128, 1024, root))["goodput_mbps"]
                )
        out["pair_goodput_mbps"] = max(rates)
    except Exception as e:  # pragma: no cover - diagnostics only
        out["pair_goodput_error"] = repr(e)[:200]
    try:
        from bench_ingest import make_blob, run_ingest

        blob = make_blob(512)
        rates = []
        for _ in range(2):
            with tempfile.TemporaryDirectory(dir=".") as root:
                rates.append(asyncio.run(
                    run_ingest(blob, root, "cpu", "rename", 0)
                )["ingest_gbps"])
        out["origin_ingest_gbps"] = max(rates)
        rates = []
        for _ in range(2):
            with tempfile.TemporaryDirectory(dir=".") as root:
                rates.append(asyncio.run(run_ingest(
                    blob, root, "cpu", "rename", 0,
                    ingest={"window_bytes": 64 * 1024 * 1024,
                            "windows_in_flight": 2},
                ))["ingest_gbps"])
        out["origin_ingest_pipelined_gbps"] = max(rates)
    except Exception as e:  # pragma: no cover - diagnostics only
        out["origin_ingest_error"] = repr(e)[:200]
    return out


def main() -> None:
    cpu = None
    if os.environ.get("BENCH_SKIP_CPU") != "1":
        cpu = cpu_baseline_gbps()
    # BENCH_PROFILE=<dir>: wrap the TPU section in a jax.profiler trace
    # (XPlane + TensorBoard format) -- the SURVEY SS5 tracing plane for
    # the TPU side, alongside the swarm's networkevent JSONL.
    profile_dir = os.environ.get("BENCH_PROFILE", "")
    if profile_dir:
        import jax

        ctx = jax.profiler.trace(profile_dir)
    else:
        import contextlib

        ctx = contextlib.nullcontext()
    with ctx:
        natural, packed_rate, pack_gbps = tpu_rates()
        chained = natural_chained_gbps()
        cdc_gbps = cdc_gear_rate()
    extras = data_plane_extras()
    # Headline = the CHAINED number: the only method that stays stable
    # (~3% spread) on this relay; the plain marginal is exposed to
    # replay-coalescing / fence jitter (observed 31-132 GB/s swings on
    # unchanged code) and rides along for cross-round comparability.
    headline = chained
    print(
        json.dumps(
            {
                "metric": "batched_sha256_metainfo_gen",
                "value": round(headline, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(headline / cpu, 3) if cpu else None,
                "natural_marginal_gbps": round(natural, 2),
                "natural_chained_gbps": round(chained, 2),
                "packed_kernel_gbps": round(packed_rate, 2),
                "host_pack_gbps_core": round(pack_gbps, 2),
                "cdc_gear_pallas_gbps": round(cdc_gbps, 2),
                **extras,
            }
        )
    )


if __name__ == "__main__":
    main()

"""North-star benchmark: batched SHA-256 piece hashing throughput.

Measures the TPU metainfo-gen hot loop (BASELINE.json config #3: batched
SHA-256 over uniform pieces; target >= 20 GB/s/chip on v5e) against the CPU
hashlib baseline (config #1), printing ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the TPU/CPU speedup -- the reference hashes pieces
sequentially on the CPU (uber/kraken lib/metainfogen [UNVERIFIED]), so the
measured CPU rate stands in for the reference baseline (BASELINE.json
``published`` is empty; see BASELINE.md).

Methodology notes:
- The compute plane is exercised via the Pallas kernel
  (kraken_tpu/ops/sha256_pallas.py) on device-resident data. On this test
  rig the TPU sits behind a network relay whose host<->device link runs at
  ~25 MB/s with ~200 ms round-trip latency -- both orders of magnitude off
  a production v5e host (PCIe/DMA at tens of GB/s), so end-to-end feed
  throughput here measures the relay, not the system.
- Relay latency is excluded by the marginal-rate method: time K_small and
  K_large back-to-back dispatches (one result fetch each) and divide the
  extra bytes by the extra time. Queued dispatches execute back-to-back on
  the chip, so the slope is pure chip throughput.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# 256 KiB pieces x 1024-piece tiles = 256 MiB per dispatch: large enough
# that per-dispatch overhead vanishes in the slope, small enough that the
# K_LARGE queued executions' transient buffers fit HBM. SHA-256 work per
# byte is piece-length-invariant, so this measures the 4 MiB-piece rate too.
PIECE_LEN = int(os.environ.get("BENCH_PIECE_LEN", 256 * 1024))
CPU_BYTES = int(os.environ.get("BENCH_CPU_BYTES", 256 * 1024 * 1024))
K_SMALL = 4
K_LARGE = int(os.environ.get("BENCH_K_LARGE", 24))


def cpu_baseline_gbps() -> float:
    import hashlib

    data = np.random.default_rng(0).integers(
        0, 256, size=CPU_BYTES, dtype=np.uint8
    ).tobytes()
    t0 = time.perf_counter()
    view = memoryview(data)
    n = (len(view) + PIECE_LEN - 1) // PIECE_LEN
    for i in range(n):
        hashlib.sha256(view[i * PIECE_LEN : (i + 1) * PIECE_LEN]).digest()
    return len(data) / (time.perf_counter() - t0) / 1e9


def tpu_marginal_gbps() -> float:
    import jax
    import jax.numpy as jnp

    from kraken_tpu.ops.sha256_pallas import N_TILE, hash_pieces_device

    key = jax.random.PRNGKey(0)
    d = jax.random.bits(key, (N_TILE, PIECE_LEN), dtype=jnp.uint8)
    d.block_until_ready()
    # Warm up: compile + drain the pipeline. The warmup doubles as the
    # kernel's correctness gate on the real chip (CPU-side validation is
    # impractical: XLA:CPU needs >5 min to compile the unrolled body).
    import hashlib

    from kraken_tpu.ops.sha256 import _digest_bytes

    warm = _digest_bytes(hash_pieces_device(d, PIECE_LEN)[:2])
    host = np.asarray(d[:2])
    for i in range(2):
        want = hashlib.sha256(host[i].tobytes()).digest()
        assert warm[i].tobytes() == want, "pallas kernel digest mismatch"

    def timed(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = hash_pieces_device(d, PIECE_LEN)
        _ = np.asarray(out[0, 0])  # forces the whole queued chain
        return time.perf_counter() - t0

    t_small, t_large = timed(K_SMALL), timed(K_LARGE)
    extra_bytes = (K_LARGE - K_SMALL) * N_TILE * PIECE_LEN
    return extra_bytes / max(t_large - t_small, 1e-9) / 1e9


def main() -> None:
    cpu = None
    if os.environ.get("BENCH_SKIP_CPU") != "1":
        cpu = cpu_baseline_gbps()
    tpu = tpu_marginal_gbps()
    print(
        json.dumps(
            {
                "metric": "batched_sha256_metainfo_gen",
                "value": round(tpu, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(tpu / cpu, 3) if cpu else None,
            }
        )
    )


if __name__ == "__main__":
    main()

"""North-star benchmark: batched SHA-256 piece hashing throughput.

Measures the TPU metainfo-gen hot loop (BASELINE.json config #3: batched
SHA-256 over 4 MiB pieces; target >= 20 GB/s/chip on v5e) and the CPU
hashlib baseline (config #1), then prints ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is the TPU/CPU speedup -- the reference hashes pieces
sequentially on the CPU (uber/kraken lib/metainfogen [UNVERIFIED]), so the
measured CPU rate stands in for the reference baseline (BASELINE.json
``published`` is empty; see BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PIECE_LEN = 4 * 1024 * 1024
# Total bytes hashed per timed pass. Big enough to amortize dispatch, small
# enough to run quickly on CPU fallback when no TPU is attached.
TOTAL = int(os.environ.get("BENCH_TOTAL_BYTES", 512 * 1024 * 1024))
REPEATS = int(os.environ.get("BENCH_REPEATS", 3))


def time_hasher(hasher, data: np.ndarray) -> float:
    """Best-of-N GB/s for hashing ``data`` in PIECE_LEN pieces."""
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = hasher.hash_pieces(data, PIECE_LEN)
        assert out.shape == ((len(data) + PIECE_LEN - 1) // PIECE_LEN, 32)
        best = min(best, time.perf_counter() - t0)
    return len(data) / best / 1e9


def main() -> None:
    from kraken_tpu.core.hasher import get_hasher

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=TOTAL, dtype=np.uint8).tobytes()

    cpu_gbps = None
    if os.environ.get("BENCH_SKIP_CPU") != "1":
        # CPU baseline on a smaller slice (hashlib ~2 GB/s; keep it quick).
        cpu_slice = data[: min(TOTAL, 256 * 1024 * 1024)]
        cpu = get_hasher("cpu")
        t0 = time.perf_counter()
        cpu.hash_pieces(cpu_slice, PIECE_LEN)
        cpu_gbps = len(cpu_slice) / (time.perf_counter() - t0) / 1e9

    tpu = get_hasher("tpu")
    # Warm up/compile the exact sub-batch shape the timed passes use.
    per_batch = max(1, tpu._sub_batch_bytes // PIECE_LEN)
    tpu.hash_pieces(data[: per_batch * PIECE_LEN], PIECE_LEN)
    tpu_gbps = time_hasher(tpu, data)

    print(
        json.dumps(
            {
                "metric": "batched_sha256_metainfo_gen",
                "value": round(tpu_gbps, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(tpu_gbps / cpu_gbps, 3) if cpu_gbps else None,
            }
        )
    )


if __name__ == "__main__":
    main()

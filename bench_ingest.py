"""Origin ingest, end to end (BASELINE row 1; VERDICT r4 next-round #2).

Measures the rate the row actually names: bytes enter the origin's
chunked-upload HTTP API -> metainfo is served. One in-process OriginNode
with a REAL aiohttp listener on loopback; the client streams a 1 GiB blob
(PATCH), commits (PUT), then requests metainfo (GET). Decomposed into:

  patch_s     HTTP receive + spool write + running upload digest
  commit_s    digest check (precomputed -> no re-read) + rename [+ fsync]
  metainfo_s  piece-hash pass (windowed, read prefetch overlapped)

Variants: --hasher cpu|tpu (tpu on this rig pushes blob bytes through the
~25 MB/s axon relay -- meaningless absolute rate, see PERF.md; the
production-shaped TPU statement is the service floor below + the
device-resident kernel rate from bench.py), --durability rename|fsync
(the fsync column prices the power-loss-durable mode), --no-hash
(knocks out both hash passes to expose the pure service floor).

Prints one JSON line per run; `origin_ingest_gbps` last.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import tempfile
import time

import numpy as np

from kraken_tpu.core.digest import SHA256, Digest

MB = 1 << 20


def make_blob(size_mb: int) -> bytes:
    # Random-ish but cheap: one 64 MiB random base, tiled, with an 8-byte
    # counter stamped per MiB so no two MiB blocks are identical.
    rng = np.random.default_rng(7)
    base = rng.integers(0, 256, size=min(size_mb, 64) * MB, dtype=np.uint8)
    reps = (size_mb * MB) // len(base)
    blob = bytearray(bytes(base) * reps)
    for i in range(size_mb):
        blob[i * MB : i * MB + 8] = i.to_bytes(8, "big")
    return bytes(blob)


async def run_ingest(
    blob: bytes, root: str, hasher: str, durability: str, chunk_mb: int
) -> dict:
    import aiohttp

    from kraken_tpu.assembly import OriginNode

    node = OriginNode(
        store_root=root, hasher=hasher, dedup=False, durability=durability
    )
    await node.start()
    d = Digest(SHA256, hashlib.sha256(blob).hexdigest())
    base = f"http://{node.addr}/namespace/bench/blobs/{d}"
    timings: dict[str, float] = {}
    try:
        async with aiohttp.ClientSession() as http:
            async with http.post(f"{base}/uploads") as r:
                uid = await r.text()

            # One contiguous body (Content-Length path): the client and
            # server share this rig's single core, so per-chunk client
            # framing would bill the SERVICE for client CPU. chunk_mb > 0
            # switches to chunked transfer encoding for comparison.
            if chunk_mb:
                async def body():
                    for off in range(0, len(blob), chunk_mb * MB):
                        yield blob[off : off + chunk_mb * MB]
                data = body()
            else:
                data = blob

            t0 = time.perf_counter()
            async with http.patch(
                f"{base}/uploads/{uid}", data=data,
                headers={"X-Upload-Offset": "0"},
            ) as r:
                assert r.status == 204, r.status
            timings["patch_s"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            async with http.put(f"{base}/uploads/{uid}/commit") as r:
                assert r.status == 201, (r.status, await r.text())
            timings["commit_s"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            async with http.get(f"{base}/metainfo") as r:
                assert r.status == 200, r.status
                await r.read()
            timings["metainfo_s"] = time.perf_counter() - t0
    finally:
        await node.stop()

    total = sum(timings.values())
    return {
        "hasher": hasher,
        "durability": durability,
        "blob_mb": len(blob) // MB,
        **{k: round(v, 3) for k, v in timings.items()},
        "total_s": round(total, 3),
        "ingest_gbps": round(len(blob) / total / 1e9, 3),
    }


class _NoopHasher:
    """Service-floor probe: pieces 'hash' to zeros instantly."""

    def hash_pieces(self, data: bytes, piece_length: int):
        n = max(1, -(-len(data) // piece_length)) if data else 1
        return np.zeros((n, 32), dtype=np.uint8)

    def hash_batch(self, pieces):
        return np.zeros((len(pieces), 32), dtype=np.uint8)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blob-mb", type=int, default=1024)
    ap.add_argument("--chunk-mb", type=int, default=1)
    ap.add_argument("--hasher", default="cpu")
    ap.add_argument("--durability", default="rename")
    ap.add_argument("--no-hash", action="store_true",
                    help="knock out both hash passes (service floor)")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    blob = make_blob(args.blob_mb)
    if args.no_hash:
        # Knock out the piece hasher AND the running upload digest so the
        # remaining wall is pure service machinery (HTTP, spool, rename,
        # sidecars). Commit verification is forced off via a precomputed
        # digest that always matches.
        from kraken_tpu.core import hasher as hmod
        from kraken_tpu.origin import server as srv

        hmod.register_hasher("noop", _NoopHasher)
        srv._UploadDigest.write_and_update = (
            lambda self, f, chunk: f.write(chunk)
        )
        known = Digest(SHA256, hashlib.sha256(blob).hexdigest())
        srv._UploadDigest.result = lambda self, size: known
        # Zero piece hashes of the right count, so commit takes the SAME
        # adopt path as the real cpu flow (no re-read) minus the hashing.
        srv._UploadDigest.piece_hashes = lambda self, size, plen: (
            b"\0" * 32 * max(1, -(-size // plen)) if size else None
        )
        args.hasher = "noop"

    results = []
    for _ in range(args.repeats):
        with tempfile.TemporaryDirectory(dir=".") as root:
            r = asyncio.run(run_ingest(
                blob, root, args.hasher, args.durability, args.chunk_mb
            ))
            results.append(r)
            print(json.dumps(r))

    best = max(results, key=lambda r: r["ingest_gbps"])
    name = "origin_ingest_gbps" if not args.no_hash else "origin_ingest_service_gbps"
    print(json.dumps({
        "metric": name,
        "value": best["ingest_gbps"],
        "unit": "GB/s",
        "vs_baseline": None,
        "detail": best,
    }))


if __name__ == "__main__":
    main()

"""Origin ingest, end to end (BASELINE row 1; VERDICT r4 next-round #2).

Measures the rate the row actually names: bytes enter the origin's
chunked-upload HTTP API -> metainfo is served. One in-process OriginNode
with a REAL aiohttp listener on loopback; the client streams a 1 GiB blob
(PATCH), commits (PUT), then requests metainfo (GET). Decomposed into:

  patch_s     HTTP receive + spool write + running upload digest
  commit_s    digest check (precomputed -> no re-read) + rename [+ fsync]
  metainfo_s  piece-hash pass (windowed, read prefetch overlapped)

Variants: --hasher cpu|tpu (tpu on this rig pushes blob bytes through the
~25 MB/s axon relay -- meaningless absolute rate, see PERF.md; the
production-shaped TPU statement is the service floor below + the
device-resident kernel rate from bench.py), --durability rename|fsync
(the fsync column prices the power-loss-durable mode), --no-hash
(knocks out both hash passes to expose the pure service floor),
--hash-workers N (host piece-hash pool size; default sweeps 1 and 2
and cross-checks every variant's metainfo against the serial oracle --
parallel hashing must be BIT-IDENTICAL, and emits a direct piece-pass
row per worker count so pool overhead and scaling are visible without
the HTTP client's CPU billed in).

Prints one JSON line per run; `origin_ingest_gbps` last.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import tempfile
import time

import numpy as np

from kraken_tpu.core.digest import SHA256, Digest

MB = 1 << 20


def make_blob(size_mb: int) -> bytes:
    # Random-ish but cheap: one 64 MiB random base, tiled, with an 8-byte
    # counter stamped per MiB so no two MiB blocks are identical.
    rng = np.random.default_rng(7)
    base = rng.integers(0, 256, size=min(size_mb, 64) * MB, dtype=np.uint8)
    reps = (size_mb * MB) // len(base)
    blob = bytearray(bytes(base) * reps)
    for i in range(size_mb):
        blob[i * MB : i * MB + 8] = i.to_bytes(8, "big")
    return bytes(blob)


async def run_ingest(
    blob: bytes, root: str, hasher: str, durability: str, chunk_mb: int,
    hash_workers: int = 1, ingest: dict | None = None,
) -> dict:
    import aiohttp

    from kraken_tpu.assembly import OriginNode

    node = OriginNode(
        store_root=root, hasher=hasher, dedup=False, durability=durability,
        hash_workers=hash_workers, ingest=ingest,
    )
    await node.start()
    d = Digest(SHA256, hashlib.sha256(blob).hexdigest())
    base = f"http://{node.addr}/namespace/bench/blobs/{d}"
    timings: dict[str, float] = {}
    try:
        async with aiohttp.ClientSession() as http:
            async with http.post(f"{base}/uploads") as r:
                uid = await r.text()

            # One contiguous body (Content-Length path): the client and
            # server share this rig's single core, so per-chunk client
            # framing would bill the SERVICE for client CPU. chunk_mb > 0
            # switches to chunked transfer encoding for comparison.
            if chunk_mb:
                async def body():
                    for off in range(0, len(blob), chunk_mb * MB):
                        yield blob[off : off + chunk_mb * MB]
                data = body()
            else:
                data = blob

            t0 = time.perf_counter()
            async with http.patch(
                f"{base}/uploads/{uid}", data=data,
                headers={"X-Upload-Offset": "0"},
            ) as r:
                assert r.status == 204, r.status
            timings["patch_s"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            async with http.put(f"{base}/uploads/{uid}/commit") as r:
                assert r.status == 201, (r.status, await r.text())
            timings["commit_s"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            async with http.get(f"{base}/metainfo") as r:
                assert r.status == 200, r.status
                metainfo_body = await r.read()
            timings["metainfo_s"] = time.perf_counter() - t0

            overlap = None
            if ingest is not None:
                # The pipelined plane publishes its own overlap gauge --
                # scrape it so the e2e row carries the overlap evidence.
                async with http.get(f"http://{node.addr}/metrics") as r:
                    for ln in (await r.text()).splitlines():
                        if ln.startswith("ingest_last_overlap_ratio"):
                            overlap = float(ln.rsplit(" ", 1)[1])
    finally:
        await node.stop()

    total = sum(timings.values())
    row = {
        "hasher": hasher,
        "hash_workers": hash_workers,
        "durability": durability,
        "blob_mb": len(blob) // MB,
        "pipelined": ingest is not None,
        **{k: round(v, 3) for k, v in timings.items()},
        "total_s": round(total, 3),
        "ingest_gbps": round(len(blob) / total / 1e9, 3),
        # Bit-identity probe: parallel piece hashing must serve the SAME
        # metainfo bytes as the serial path (compared in main()).
        "metainfo_sha256": hashlib.sha256(metainfo_body).hexdigest(),
    }
    if overlap is not None:
        row["overlap_ratio"] = round(overlap, 3)
    return row


def measure_piece_pass(blob: bytes, workers_list: list[int],
                       repeats: int) -> tuple[list[dict], bytes]:
    """The piece pass alone -- hash_pieces over the whole blob, no HTTP
    client billing the core, no blob digest competing. workers=0 is the
    strictly serial pre-pool oracle; the workers=1 row prices pure pool
    overhead; workers=2 shows the scaling on this rig.

    Trials INTERLEAVE the worker configs round-robin and report per-
    config medians: this shared rig's throughput drifts tens of percent
    on minute scales (the same pathology the TPU benches chain around,
    PERF.md), and back-to-back sweeps ascribe that drift to whichever
    config ran last."""
    import statistics

    from kraken_tpu.core.hasher import CPUPieceHasher
    from kraken_tpu.origin.metainfogen import PieceLengthConfig

    plen = PieceLengthConfig().piece_length(len(blob))
    workers_list = list(dict.fromkeys(workers_list))  # --hash-workers 0 dedup
    hashers = {w: CPUPieceHasher(workers=w) for w in workers_list}
    digests: dict[int, str] = {}
    hashes_bytes: dict[int, bytes] = {}
    walls: dict[int, list[float]] = {w: [] for w in workers_list}
    for w, h in hashers.items():  # warm: pool thread spawn off the clock
        hashes_bytes[w] = h.hash_pieces(blob, plen).tobytes()
        digests[w] = hashlib.sha256(hashes_bytes[w]).hexdigest()
    for r in range(repeats):
        # Rotate the order each round: slot-in-cycle effects (turbo
        # ramps, hypervisor steal) otherwise bias whichever config
        # always runs in the same position.
        order = workers_list[r % len(workers_list):] + \
            workers_list[:r % len(workers_list)]
        for w in order:
            t0 = time.perf_counter()
            hashes = hashers[w].hash_pieces(blob, plen)
            walls[w].append(time.perf_counter() - t0)
            # Digest-gate EVERY timed run, not just the warm pass: an
            # intermittent sharding bug under timing variation is the
            # exact class this would catch. (The sha of 32 B/piece is
            # off the clock and costs ~nothing.)
            got = hashlib.sha256(hashes.tobytes()).hexdigest()
            assert got == digests[w], f"timed run diverged (workers={w})"
    rows = [
        {
            "piece_pass_workers": w,
            "piece_length": plen,
            "median_s": round(statistics.median(walls[w]), 3),
            "piece_pass_gbps": round(
                len(blob) / statistics.median(walls[w]) / 1e9, 3
            ),
            "median_of": repeats,
            "hashes_sha256": digests[w],
        }
        for w in workers_list
    ]
    # Hand the first config's piece hashes back so the caller's metainfo
    # oracle doesn't pay a second full serial pass over the blob.
    return rows, hashes_bytes[workers_list[0]]


def measure_thread_envelope(blob: bytes, repeats: int = 5) -> dict:
    """What raw 2-thread hashlib delivers on this rig RIGHT NOW -- two
    monolithic half-blob digests, no piece loop, no pool. This is the
    hardware ceiling the pooled piece pass is judged against: on this
    shared VM the second core's yield drifts between ~1.4x and ~1.6x on
    minute scales, so a workers=2 ratio only reads correctly beside the
    envelope measured in the same run."""
    import statistics
    import threading

    view = memoryview(blob)
    half = len(blob) // 2

    def hash_range(lo: int, hi: int) -> None:
        hashlib.sha256(view[lo:hi]).digest()

    serial: list[float] = []
    para: list[float] = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        hash_range(0, len(blob))
        serial.append(time.perf_counter() - t0)
        ts = [
            threading.Thread(target=hash_range, args=(0, half)),
            threading.Thread(target=hash_range, args=(half, len(blob))),
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        para.append(time.perf_counter() - t0)
    s, p = statistics.median(serial), statistics.median(para)
    return {
        "raw_serial_gbps": round(len(blob) / s / 1e9, 3),
        "raw_2thread_gbps": round(len(blob) / p / 1e9, 3),
        "thread_envelope": round(s / p, 2),
    }


def measure_pipelined_session(blob: bytes, wif_list: list[int],
                              window_mb: int, repeats: int) -> list[dict]:
    """The staged ingest session (core/ingest.py) against the serial
    piece pass, SAME hasher object, no HTTP: isolates what the
    read/hash overlap itself buys. Rounds interleave serial with every
    windows-in-flight config (same drift rationale as the piece pass),
    every run is digest-gated against the serial oracle, and each
    pipelined row carries the session's own overlap ratio and per-stage
    walls -- overlap_ratio > 1 is the direct proof that two stages ran
    concurrently."""
    import statistics

    from kraken_tpu.core.hasher import CPUPieceHasher
    from kraken_tpu.core.ingest import IngestConfig, IngestPipeline
    from kraken_tpu.origin.metainfogen import PieceLengthConfig

    plen = PieceLengthConfig().piece_length(len(blob))
    hasher = CPUPieceHasher(workers=0)
    oracle = hashlib.sha256(
        hasher.hash_pieces(blob, plen).tobytes()
    ).hexdigest()
    pipes = {
        wif: IngestPipeline(hasher, IngestConfig(
            window_bytes=window_mb * MB, windows_in_flight=wif,
        ))
        for wif in wif_list
    }

    def run_pipelined(wif: int):
        ses = pipes[wif].session(plen)
        off = 0
        t0 = time.perf_counter()
        while off < len(blob):
            buf = ses.begin_window()
            n = min(len(buf), len(blob) - off)
            buf[:n] = blob[off : off + n]
            ses.submit(n)
            off += n
        digests = ses.finish()
        wall = time.perf_counter() - t0
        got = hashlib.sha256(digests.tobytes()).hexdigest()
        assert got == oracle, f"pipelined session diverged (wif={wif})"
        return wall, ses

    walls: dict = {"serial": [], **{w: [] for w in wif_list}}
    last_ses: dict = {}
    for wif in wif_list:  # warm: executor spawn + bufpool mmap off the clock
        run_pipelined(wif)
    keys = ["serial", *wif_list]
    for r in range(repeats):
        for k in keys[r % len(keys):] + keys[: r % len(keys)]:
            if k == "serial":
                t0 = time.perf_counter()
                hasher.hash_pieces(blob, plen)
                walls["serial"].append(time.perf_counter() - t0)
            else:
                wall, ses = run_pipelined(k)
                walls[k].append(wall)
                last_ses[k] = ses
    s = statistics.median(walls["serial"])
    rows = [{
        "ingest_path": "serial",
        "median_s": round(s, 3),
        "gbps": round(len(blob) / s / 1e9, 3),
        "median_of": repeats,
    }]
    for wif in wif_list:
        m = statistics.median(walls[wif])
        ses = last_ses[wif]
        rows.append({
            "ingest_path": "pipelined",
            "windows_in_flight": wif,
            "window_mb": window_mb,
            "windows": ses.windows,
            "median_s": round(m, 3),
            "gbps": round(len(blob) / m / 1e9, 3),
            "overlap_ratio": round(ses.overlap_ratio(), 3),
            "stage_s": {k: round(v, 3) for k, v in ses.stage_seconds.items()},
            "vs_serial": round(s / m, 2),
            "median_of": repeats,
        })
    return rows


def measure_pack_scaling(size_mb: int, workers_list: list[int],
                         repeats: int) -> list[dict]:
    """Host-pack worker scaling: one window packed to the kernel's
    [G, nb, 16, 8, 128] tile layout through pack_tiles_pooled with 1..N
    pool workers (each worker's stripe runs GIL-free in hostpack.c).
    This is the multi-core lever the device-feed path rides; the pin
    test (test_native.py) asserts the >= 1.3x band, this row prints the
    measured number."""
    import statistics

    from kraken_tpu import native
    from kraken_tpu.core.hasher import HashPool

    if not native.have_native_packer():
        return [{"pack_scaling": "skipped",
                 "reason": "native packer unavailable on this rig"}]
    plen = 4096
    m = max(1024, (size_mb * MB) // plen // 1024 * 1024)
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(m, plen), dtype=np.uint8)
    nb = plen // 64
    ref = native.pack_tiles(data, nb, threads=1)
    pools = {w: HashPool(w, name=f"benchpack{w}") for w in workers_list}
    for w in workers_list:  # warm + bit-identity gate per pool width
        assert np.array_equal(native.pack_tiles_pooled(data, nb, pools[w]),
                              ref), f"pooled pack diverged (workers={w})"
    walls: dict[int, list[float]] = {w: [] for w in workers_list}
    for r in range(repeats):
        order = workers_list[r % len(workers_list):] + \
            workers_list[: r % len(workers_list)]
        for w in order:
            t0 = time.perf_counter()
            native.pack_tiles_pooled(data, nb, pools[w])
            walls[w].append(time.perf_counter() - t0)
    rows = []
    base = statistics.median(walls[workers_list[0]])
    for w in workers_list:
        med = statistics.median(walls[w])
        rows.append({
            "pack_workers": w,
            "window_mb": data.nbytes // MB,
            "median_s": round(med, 4),
            "pack_gbps": round(data.nbytes / med / 1e9, 3),
            "vs_first": round(base / med, 2),
            "median_of": repeats,
        })
    return rows


def run_chained_e2e(blob: bytes, args, ingest_cfg: dict,
                    hash_workers: int, rounds: int) -> dict:
    """Chained e2e: round k's blob embeds round k-1's served-metainfo
    sha256, so no cache tier, spool reuse, or compiler memoization can
    shortcut any round -- each is a full cold ingest whose input depends
    on the previous OUTPUT (the same chaining discipline the TPU kernel
    benches use, PERF.md). Every round's served metainfo is gated
    against a fresh serial oracle for that round's bytes."""
    import statistics

    from kraken_tpu.core.hasher import CPUPieceHasher
    from kraken_tpu.core.metainfo import MetaInfo
    from kraken_tpu.origin.metainfogen import PieceLengthConfig

    oracle = CPUPieceHasher(workers=0)
    plen = PieceLengthConfig().piece_length(len(blob))
    ba = bytearray(blob)
    prev = b"\0" * 32
    vals = []
    for i in range(rounds):
        ba[64:96] = prev
        chained = bytes(ba)
        with tempfile.TemporaryDirectory(dir=".") as root:
            r = asyncio.run(run_ingest(
                chained, root, args.hasher, args.durability, args.chunk_mb,
                hash_workers=hash_workers, ingest=ingest_cfg,
            ))
        d = Digest(SHA256, hashlib.sha256(chained).hexdigest())
        want = hashlib.sha256(MetaInfo(
            d, len(chained), plen,
            oracle.hash_pieces(chained, plen).tobytes(),
        ).serialize()).hexdigest()
        assert r["metainfo_sha256"] == want, (
            f"chained round {i} diverged from its serial oracle"
        )
        prev = bytes.fromhex(r["metainfo_sha256"])
        print(json.dumps({"chained_round": i, **r}))
        vals.append(r["ingest_gbps"])
    return {
        "metric": "origin_ingest_gbps_chained",
        "value": round(statistics.median(vals), 3),
        "unit": "GB/s",
        "rounds": rounds,
        "ingest": ingest_cfg,
    }


class _NoopHasher:
    """Service-floor probe: pieces 'hash' to zeros instantly."""

    def hash_pieces(self, data: bytes, piece_length: int):
        n = max(1, -(-len(data) // piece_length)) if data else 1
        return np.zeros((n, 32), dtype=np.uint8)

    def hash_batch(self, pieces):
        return np.zeros((len(pieces), 32), dtype=np.uint8)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blob-mb", type=int, default=1024)
    ap.add_argument("--chunk-mb", type=int, default=1)
    ap.add_argument("--hasher", default="cpu")
    ap.add_argument("--hash-workers", type=int, default=None,
                    help="host piece-hash pool size; default sweeps 1 and 2")
    ap.add_argument("--durability", default="rename")
    ap.add_argument("--no-hash", action="store_true",
                    help="knock out both hash passes (service floor)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--window-mb", type=int, default=64,
                    help="pipelined ingest staging window size")
    ap.add_argument("--skip-pipelined", action="store_true",
                    help="skip the pipelined-ingest rows (serial bench only)")
    ap.add_argument("--chained-rounds", type=int, default=3,
                    help="chained e2e rounds (0 disables)")
    args = ap.parse_args()

    blob = make_blob(args.blob_mb)
    if args.no_hash:
        # Knock out the piece hasher AND the running upload digest so the
        # remaining wall is pure service machinery (HTTP, spool, rename,
        # sidecars). Commit verification is forced off via a precomputed
        # digest that always matches.
        from kraken_tpu.core import hasher as hmod
        from kraken_tpu.origin import server as srv

        hmod.register_hasher("noop", _NoopHasher)
        srv._UploadDigest.write_and_update = (
            lambda self, f, chunk: f.write(chunk)
        )
        known = Digest(SHA256, hashlib.sha256(blob).hexdigest())
        srv._UploadDigest.result = lambda self, size: known
        # Zero piece hashes of the right count, so commit takes the SAME
        # adopt path as the real cpu flow (no re-read) minus the hashing.
        srv._UploadDigest.piece_hashes = lambda self, size, plen: (
            b"\0" * 32 * max(1, -(-size // plen)) if size else None
        )
        args.hasher = "noop"

    # Direct piece-pass rows (cpu hasher only): serial oracle, then the
    # pooled pool sizes -- pool overhead (workers=1 vs 0) and scaling
    # (workers=2 vs 1) without HTTP noise, digests cross-checked.
    expected_metainfo_sha = None
    if args.hasher == "cpu" and not args.no_hash:
        sweep = (
            [args.hash_workers] if args.hash_workers is not None else [1, 2]
        )
        pp_rows, serial_hashes = measure_piece_pass(
            blob, [0, *sweep], args.repeats
        )
        serial = pp_rows[0]
        for row in pp_rows:
            row["matches_serial"] = (
                row["hashes_sha256"] == serial["hashes_sha256"]
            )
            print(json.dumps(row))
            assert row["matches_serial"], "parallel hashing diverged!"
        print(json.dumps(measure_thread_envelope(blob)))
        from kraken_tpu.core.metainfo import MetaInfo

        d = Digest(SHA256, hashlib.sha256(blob).hexdigest())
        expected_metainfo_sha = hashlib.sha256(MetaInfo(
            d, len(blob), serial["piece_length"], serial_hashes,
        ).serialize()).hexdigest()
    else:
        sweep = [args.hash_workers if args.hash_workers is not None else 1]

    pipelined_on = (
        not args.skip_pipelined and not args.no_hash and args.hasher == "cpu"
    )
    if pipelined_on:
        # Direct session rows: the overlap win in isolation, with the
        # session's own overlap ratio + per-stage walls. Then the host
        # pack-worker scaling row (device-feed lever).
        for row in measure_pipelined_session(
            blob, [1, 2, 4], args.window_mb, args.repeats
        ):
            print(json.dumps(row))
        for row in measure_pack_scaling(64, [1, 2], args.repeats):
            print(json.dumps(row))

    # E2E configs, round-robin interleaved (same drift rationale as the
    # piece pass): the serial hash_workers sweep plus -- unless skipped --
    # the pipelined ingest plane at 1 and 2 windows in flight.
    e2e_cfgs = [
        {"label": f"serial/hw{w}", "hash_workers": w, "ingest": None}
        for w in sweep
    ]
    if pipelined_on:
        for wif in (1, 2):
            e2e_cfgs.append({
                "label": f"pipelined/wif{wif}",
                "hash_workers": sweep[0],
                "ingest": {"window_bytes": args.window_mb * MB,
                           "windows_in_flight": wif},
            })

    results = []
    for rep in range(args.repeats):
        order = e2e_cfgs[rep % len(e2e_cfgs):] + \
            e2e_cfgs[: rep % len(e2e_cfgs)]
        for cfg in order:
            with tempfile.TemporaryDirectory(dir=".") as root:
                r = asyncio.run(run_ingest(
                    blob, root, args.hasher, args.durability, args.chunk_mb,
                    hash_workers=cfg["hash_workers"], ingest=cfg["ingest"],
                ))
                r["config"] = cfg["label"]
                if expected_metainfo_sha is not None:
                    r["metainfo_matches_serial"] = (
                        r["metainfo_sha256"] == expected_metainfo_sha
                    )
                results.append(r)
                print(json.dumps(r))
                assert r.get("metainfo_matches_serial", True), (
                    "served metainfo diverged from the serial oracle!"
                )

    # Median WITHIN each config (cancels run noise -- best-of was the
    # bench_pair cherry-picking this round removes), best config BY
    # median across the sweep (config comparison is the point).
    import statistics

    per_config = []
    for cfg in e2e_cfgs:
        vals = sorted(
            r["ingest_gbps"] for r in results if r["config"] == cfg["label"]
        )
        med = statistics.median(vals)
        per_config.append({
            "config": cfg["label"],
            "hash_workers": cfg["hash_workers"],
            "median_gbps": round(med, 3),
            "median_of": len(vals),
            "min": vals[0],
            "max": vals[-1],
        })
    best = max(per_config, key=lambda c: c["median_gbps"])
    name = "origin_ingest_gbps" if not args.no_hash else "origin_ingest_service_gbps"
    print(json.dumps({
        "metric": name,
        "value": best["median_gbps"],
        "unit": "GB/s",
        "vs_baseline": None,
        "detail": {"per_config": per_config, "best_config": best},
    }))

    if pipelined_on and args.chained_rounds > 0:
        # Chained e2e through the pipelined plane: each round's input
        # depends on the previous round's served metainfo, so every
        # round is a provably cold full ingest.
        print(json.dumps(run_chained_e2e(
            blob, args,
            {"window_bytes": args.window_mb * MB, "windows_in_flight": 2},
            sweep[0], args.chained_rounds,
        )))


if __name__ == "__main__":
    main()

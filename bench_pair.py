"""Single-pair data-plane benchmark: 1 origin seeder -> 1 agent leecher
over loopback TCP, one process.

VERDICT r4 next-round #1: the swarm bench proved the *policies* scale; this
measures (and profiles) what one conn pair can MOVE -- the harness ceiling
every aggregate number divides into. Run with --profile to get a cProfile
table of the combined event loop (both endpoints + both pumps), which is
what localized the round-5 rebuild targets (per-piece file opens, per-piece
bitfield sidecar writes, 64 KiB StreamReader chunking, frame-copy framing).

Usage:
    python bench_pair.py [--blob-mb 256] [--piece-kb 1024] [--profile]
                         [--repeats 3]

Prints one JSON line {"metric": "pair_goodput_mbps", ...} last.
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import io
import json
import pstats
import tempfile
import time

import numpy as np

from bench_swarm import InMemoryTracker, make_peer, NS
from kraken_tpu.core.digest import Digest
from kraken_tpu.core.hasher import get_hasher
from kraken_tpu.core.metainfo import MetaInfo


async def run_pair(blob_mb: int, piece_kb: int, root: str) -> dict:
    rng = np.random.default_rng(0)
    blob = rng.integers(0, 256, size=blob_mb << 20, dtype=np.uint8).tobytes()
    d = Digest.from_bytes(blob)
    piece_len = piece_kb << 10
    hashes = get_hasher("cpu").hash_pieces(blob, piece_len)
    metainfo = MetaInfo(d, len(blob), piece_len, hashes.tobytes())

    tracker = InMemoryTracker()
    tracker.metainfos[d.hex] = metainfo
    origin = make_peer(root, "origin", tracker, seed_blobs=[blob])
    agent = make_peer(root, "agent", tracker)
    await origin.start()
    origin.seed(metainfo, NS)
    await agent.start()

    t0 = time.perf_counter()
    await agent.download(NS, d)
    wall = time.perf_counter() - t0

    await origin.stop()
    await agent.stop()
    return {
        "blob_mb": blob_mb,
        "piece_kb": piece_kb,
        "pieces": metainfo.num_pieces,
        "wall_s": round(wall, 4),
        "goodput_mbps": round(len(blob) / wall / 1e6, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blob-mb", type=int, default=256)
    ap.add_argument("--piece-kb", type=int, default=1024)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--profile", action="store_true")
    args = ap.parse_args()

    results = []
    for _ in range(args.repeats):
        with tempfile.TemporaryDirectory() as root:
            if args.profile:
                prof = cProfile.Profile()
                prof.enable()
            r = asyncio.run(run_pair(args.blob_mb, args.piece_kb, root))
            if args.profile:
                prof.disable()
                s = io.StringIO()
                pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(40)
                print(s.getvalue())
            results.append(r)
            print(json.dumps(r))

    # Median +/- spread of N runs (VERDICT r5 next #3): single best-of
    # runs on this shared core produced BENCH-vs-PERF discrepancies
    # (282.9 recorded vs a "301-371" band); the median is the honest
    # central number and the spread is the honest error bar.
    import statistics

    vals = sorted(r["goodput_mbps"] for r in results)
    med = statistics.median(vals)
    print(json.dumps({
        "metric": "pair_goodput_mbps",
        "value": round(med, 1),
        "unit": "MB/s",
        "median_of": len(vals),
        "min": vals[0],
        "max": vals[-1],
        "spread_pct": round(100 * (vals[-1] - vals[0]) / med, 1) if med else None,
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
